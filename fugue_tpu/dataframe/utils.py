"""DataFrame utilities: test comparator, partition serialization, join schemas.

Parity with the reference (`fugue/dataframe/utils.py:24,97,152`), with a
TPU-first redesign of the serialization wire format: partitions serialize as
**arrow IPC streams** (columnar, zero-copy-friendly) instead of pickled
Python objects.
"""

import os
import uuid as _uuid
from typing import Any, Iterable, List, Optional, Tuple

import pandas as pd
import pyarrow as pa

from .._utils.assertion import assert_or_throw
from ..exceptions import FugueDataFrameOperationError
from ..schema import Schema
from .array_dataframe import ArrayDataFrame
from .arrow_dataframe import ArrowDataFrame
from .dataframe import DataFrame, LocalBoundedDataFrame


def _df_eq(
    df: DataFrame,
    data: Any,
    schema: Any = None,
    check_order: bool = False,
    check_schema: bool = True,
    check_content: bool = True,
    throw: bool = False,
    digits: int = 5,
) -> bool:
    """Compare a DataFrame against expected data (the universal test assert,
    reference ``fugue/dataframe/utils.py:24``)."""
    try:
        if isinstance(data, DataFrame):
            expected = data.as_local()
            exp_schema = data.schema
        else:
            exp_schema = Schema(schema) if schema is not None else df.schema
            expected = ArrayDataFrame(data, exp_schema)
        actual = df.as_local()
        if check_schema:
            assert_or_throw(
                df.schema.is_like(
                    exp_schema,
                    equal_groups=[[pa.types.is_integer], [pa.types.is_floating]],
                ),
                lambda: AssertionError(f"schema mismatch: {df.schema} vs {exp_schema}"),
            )
        if check_content:
            a_rows = [_norm_row(r, digits) for r in actual.as_array(type_safe=True)]
            e_rows = [
                _norm_row(r, digits)
                for r in expected.as_array(
                    columns=df.schema.names if not check_schema else None,
                    type_safe=True,
                )
            ]
            assert_or_throw(
                len(a_rows) == len(e_rows),
                lambda: AssertionError(f"row count {len(a_rows)} != {len(e_rows)}"),
            )
            if not check_order:
                a_rows = sorted(a_rows, key=_row_key)
                e_rows = sorted(e_rows, key=_row_key)
            assert_or_throw(
                a_rows == e_rows,
                lambda: AssertionError(f"content mismatch:\n{a_rows}\nvs\n{e_rows}"),
            )
        return True
    except AssertionError:
        if throw:
            raise
        return False


def _norm_row(row: List[Any], digits: int) -> List[Any]:
    res = []
    for v in row:
        if isinstance(v, float):
            res.append(round(v, digits))
        elif isinstance(v, dict):
            res.append(tuple(sorted((k, _norm_val(x, digits)) for k, x in v.items())))
        elif isinstance(v, (list, tuple)):
            res.append(tuple(_norm_val(x, digits) for x in v))
        else:
            res.append(v)
    return res


def _norm_val(v: Any, digits: int) -> Any:
    if isinstance(v, float):
        return round(v, digits)
    if isinstance(v, (list, tuple)):
        return tuple(_norm_val(x, digits) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _norm_val(x, digits)) for k, x in v.items()))
    return v


def _row_key(row: List[Any]) -> str:
    return repr(row)


# ---------------------------------------------------------------------------
# partition serialization (arrow IPC wire format)
# ---------------------------------------------------------------------------


def serialize_df(
    df: Optional[DataFrame],
    threshold: int = -1,
    file_path: Optional[str] = None,
) -> Optional[bytes]:
    """Serialize a local dataframe into an arrow IPC blob.

    If ``threshold >= 0`` and the blob exceeds it, the blob is written to
    ``file_path`` and a small path-reference blob is returned instead
    (reference behavior: ``fugue/dataframe/utils.py:97``).
    """
    if df is None:
        return None
    tbl = df.as_arrow()
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, tbl.schema) as writer:
        writer.write_table(tbl)
    buf = sink.getvalue().to_pybytes()
    blob = b"\x00" + buf  # 0x00 = inline payload
    if threshold < 0 or len(blob) <= threshold:
        return blob
    assert_or_throw(
        file_path is not None,
        FugueDataFrameOperationError("file_path required beyond threshold"),
    )
    with open(file_path, "wb") as f:  # type: ignore
        f.write(buf)
    return b"\x01" + str(file_path).encode()  # 0x01 = file reference


def deserialize_df(blob: Optional[bytes]) -> Optional[LocalBoundedDataFrame]:
    if blob is None:
        return None
    kind, payload = blob[:1], blob[1:]
    if kind == b"\x01":
        with open(payload.decode(), "rb") as f:
            payload = f.read()
    with pa.ipc.open_stream(pa.BufferReader(payload)) as reader:
        tbl = reader.read_all()
    return ArrowDataFrame(tbl)


def get_temp_df_path(base_path: str) -> str:
    return os.path.join(base_path, str(_uuid.uuid4()) + ".arrow")


# ---------------------------------------------------------------------------
# join schema inference
# ---------------------------------------------------------------------------

_SUPPORTED_JOINS = {
    "inner",
    "cross",
    "left_outer",
    "right_outer",
    "full_outer",
    "left_semi",
    "left_anti",
}


def parse_join_type(how: str) -> str:
    how = how.strip().lower().replace(" ", "_")
    aliases = {
        "full": "full_outer",
        "outer": "full_outer",
        "full_outer": "full_outer",
        "left": "left_outer",
        "right": "right_outer",
        "semi": "left_semi",
        "anti": "left_anti",
        "inner": "inner",
        "cross": "cross",
        "left_outer": "left_outer",
        "right_outer": "right_outer",
        "left_semi": "left_semi",
        "left_anti": "left_anti",
    }
    assert_or_throw(
        how in aliases, lambda: NotImplementedError(f"unsupported join type {how}")
    )
    return aliases[how]


def get_join_schemas(
    df1: DataFrame, df2: DataFrame, how: str, on: Optional[Iterable[str]] = None
) -> Tuple[Schema, Schema]:
    """Infer (key_schema, output_schema) for a join
    (reference ``fugue/dataframe/utils.py:152``)."""
    how = parse_join_type(how)
    on = list(on) if on is not None else []
    if how == "cross":
        assert_or_throw(
            len(on) == 0, FugueDataFrameOperationError("cross join can't have keys")
        )
        overlap = set(df1.schema.names) & set(df2.schema.names)
        assert_or_throw(
            len(overlap) == 0,
            lambda: FugueDataFrameOperationError(
                f"cross join with overlapping columns {overlap}"
            ),
        )
        return Schema(), df1.schema + df2.schema
    if len(on) == 0:
        on = [n for n in df1.schema.names if n in df2.schema]
    assert_or_throw(
        len(on) > 0, FugueDataFrameOperationError("join keys can't be empty")
    )
    missing1 = [k for k in on if k not in df1.schema]
    missing2 = [k for k in on if k not in df2.schema]
    assert_or_throw(
        len(missing1) == 0 and len(missing2) == 0,
        lambda: FugueDataFrameOperationError(
            f"join keys missing: {missing1 + missing2}"
        ),
    )
    # all shared columns must be join keys
    shared = set(df1.schema.names) & set(df2.schema.names)
    assert_or_throw(
        shared == set(on),
        lambda: FugueDataFrameOperationError(
            f"shared columns {shared} must all be join keys {on}"
        ),
    )
    key_schema = df1.schema.extract(on)
    if how in ("left_semi", "left_anti"):
        return key_schema, df1.schema.copy()
    out_schema = df1.schema + (df2.schema - on)
    return key_schema, out_schema
