"""IterableDataFrame — one-pass unbounded local frame.

Parity with the reference (`fugue/dataframe/iterable_dataframe.py:16`): wraps
a row iterator; most operations consume the stream lazily; materializing
converts to :class:`ArrayDataFrame`.
"""

from typing import Any, Dict, Iterable, List, Optional

from .._utils.assertion import assert_or_throw
from .._utils.iter import EmptyAwareIterable, make_empty_aware
from ..exceptions import FugueDataFrameInitError
from ..schema import Schema
from .array_dataframe import ArrayDataFrame
from .dataframe import DataFrame, LocalBoundedDataFrame, LocalUnboundedDataFrame


class IterableDataFrame(LocalUnboundedDataFrame):
    def __init__(self, df: Any = None, schema: Any = None):
        if df is None:
            assert_or_throw(
                schema is not None, FugueDataFrameInitError("schema is required")
            )
            it: Iterable[Any] = []
            s = schema if isinstance(schema, Schema) else Schema(schema)
        elif isinstance(df, IterableDataFrame):
            it = df.native
            s = schema if schema is not None else df.schema
            s = s if isinstance(s, Schema) else Schema(s)
        elif isinstance(df, DataFrame):
            s = schema if schema is not None else df.schema
            s = s if isinstance(s, Schema) else Schema(s)
            it = df.as_array_iterable(columns=s.names if schema is not None else None)
        elif isinstance(df, Iterable):
            assert_or_throw(
                schema is not None, FugueDataFrameInitError("schema is required")
            )
            s = schema if isinstance(schema, Schema) else Schema(schema)
            it = df
        else:
            raise FugueDataFrameInitError(f"can't build IterableDataFrame from {type(df)}")
        self._native: EmptyAwareIterable[List[Any]] = make_empty_aware(it)
        super().__init__(s)

    @property
    def native(self) -> EmptyAwareIterable[List[Any]]:
        return self._native

    @property
    def empty(self) -> bool:
        return self._native.empty

    def peek_array(self) -> List[Any]:
        self.assert_not_empty()
        return list(self._native.peek())

    def as_local_bounded(self) -> LocalBoundedDataFrame:
        return ArrayDataFrame(self.as_array(), self.schema)

    def _drop_cols(self, cols: List[str]) -> DataFrame:
        keep = [n for n in self.schema.names if n not in cols]
        return self._select_cols(keep)

    def _select_cols(self, cols: List[str]) -> DataFrame:
        idx = [self.schema.index_of_key(c) for c in cols]

        def gen() -> Iterable[List[Any]]:
            for row in self._native:
                yield [row[i] for i in idx]

        return IterableDataFrame(gen(), self.schema.extract(cols))

    def rename(self, columns: Dict[str, str]) -> DataFrame:
        return IterableDataFrame(self._native, self.schema.rename(columns))

    def alter_columns(self, columns: Any) -> DataFrame:
        from .arrow_dataframe import ArrowDataFrame

        new_schema = self.schema.alter(columns)
        if new_schema == self.schema:
            return self

        old_schema = self.schema

        def gen() -> Iterable[List[Any]]:
            for chunk in _chunked(self._native, 10000):
                adf = ArrowDataFrame(chunk, old_schema).alter_columns(columns)
                yield from adf.as_array()

        return IterableDataFrame(gen(), new_schema)

    def head(self, n: int, columns: Optional[List[str]] = None) -> LocalBoundedDataFrame:
        src = self if columns is None else self._select_cols(columns)
        rows = []
        for row in src.as_array_iterable():
            if len(rows) >= n:
                break
            rows.append(row)
        return ArrayDataFrame(rows, src.schema)

    def as_array(
        self, columns: Optional[List[str]] = None, type_safe: bool = False
    ) -> List[List[Any]]:
        return list(self.as_array_iterable(columns, type_safe=type_safe))

    def as_array_iterable(
        self, columns: Optional[List[str]] = None, type_safe: bool = False
    ) -> Iterable[List[Any]]:
        src: Iterable[List[Any]]
        if columns is None:
            src = self._native
        else:
            src = self._select_cols(columns).as_array_iterable()  # type: ignore
            yield from src
            return
        if not type_safe:
            yield from src
        else:
            from .arrow_dataframe import ArrowDataFrame

            schema = self.schema
            for chunk in _chunked(src, 10000):
                yield from ArrowDataFrame(chunk, schema).as_array()


def _chunked(it: Iterable[Any], size: int) -> Iterable[List[Any]]:
    buf: List[Any] = []
    for x in it:
        buf.append(x)
        if len(buf) >= size:
            yield buf
            buf = []
    if len(buf) > 0:
        yield buf
