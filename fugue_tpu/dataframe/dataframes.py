"""DataFrames — ordered, named collection of DataFrames.

Parity with the reference (`fugue/dataframe/dataframes.py:9`): the
multi-input container passed to processors/outputters/cotransformers.
"""

from typing import Any, Dict, List

from .._utils.params import IndexedOrderedDict
from ..exceptions import FugueDataFrameInitError
from .dataframe import DataFrame


class DataFrames(IndexedOrderedDict):
    def __init__(self, *args: Any, **kwargs: Any):
        super().__init__()
        self._has_dict_key = False
        for a in args:
            self._append(a)
        for k, v in kwargs.items():
            self[k] = v
        self.set_readonly()

    def _append(self, obj: Any) -> None:
        if obj is None:
            return
        if isinstance(obj, DataFrame):
            self[f"_{len(self)}"] = obj
        elif isinstance(obj, DataFrames) or isinstance(obj, Dict):
            for k, v in obj.items():
                self[k] = v
        elif isinstance(obj, (list, tuple)):
            for x in obj:
                self._append(x)
        else:
            raise FugueDataFrameInitError(f"can't add {type(obj)} to DataFrames")

    def __setitem__(self, key: str, value: Any) -> None:
        if not isinstance(value, DataFrame):
            raise FugueDataFrameInitError(f"{key} value must be a DataFrame")
        if not key.startswith("_"):
            self._has_dict_key = True
        super().__setitem__(key, value)

    @property
    def has_key(self) -> bool:
        return self._has_dict_key

    def __getitem__(self, key: Any) -> DataFrame:  # type: ignore
        if isinstance(key, int):
            return self.get_value_by_index(key)
        return super().__getitem__(key)

    def convert(self, func: Any) -> "DataFrames":
        return DataFrames({k: func(v) for k, v in self.items()})
