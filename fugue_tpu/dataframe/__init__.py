from .array_dataframe import ArrayDataFrame
from .arrow_dataframe import ArrowDataFrame
from .dataframe import (
    AnySchema,
    DataFrame,
    DataFrameDisplay,
    LocalBoundedDataFrame,
    LocalDataFrame,
    LocalUnboundedDataFrame,
    YieldedDataFrame,
)
from .dataframe_iterable_dataframe import (
    IterableArrowDataFrame,
    IterablePandasDataFrame,
    LocalDataFrameIterableDataFrame,
)
from .dataframes import DataFrames
from .function_wrapper import (
    AnnotatedParam,
    DataFrameFunctionWrapper,
    DataFrameParam,
    LocalDataFrameParam,
    fugue_annotated_param,
)
from .iterable_dataframe import IterableDataFrame
from .pandas_dataframe import PandasDataFrame
from .utils import _df_eq, deserialize_df, get_join_schemas, parse_join_type, serialize_df

__all__ = [
    "AnySchema",
    "ArrayDataFrame",
    "ArrowDataFrame",
    "DataFrame",
    "DataFrameDisplay",
    "DataFrames",
    "DataFrameFunctionWrapper",
    "DataFrameParam",
    "LocalDataFrameParam",
    "AnnotatedParam",
    "fugue_annotated_param",
    "IterableDataFrame",
    "IterableArrowDataFrame",
    "IterablePandasDataFrame",
    "LocalBoundedDataFrame",
    "LocalDataFrame",
    "LocalDataFrameIterableDataFrame",
    "LocalUnboundedDataFrame",
    "PandasDataFrame",
    "YieldedDataFrame",
    "_df_eq",
    "serialize_df",
    "deserialize_df",
    "get_join_schemas",
    "parse_join_type",
]
