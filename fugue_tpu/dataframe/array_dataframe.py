"""ArrayDataFrame — local frame over list-of-lists (no type enforcement).

Parity with the reference (`fugue/dataframe/array_dataframe.py:14`): the
cheapest local frame; ``type_safe=True`` conversions go through arrow.
"""

from typing import Any, Dict, Iterable, List, Optional

from .._utils.assertion import assert_or_throw
from ..exceptions import FugueDataFrameInitError
from ..schema import Schema
from .dataframe import DataFrame, LocalBoundedDataFrame


class ArrayDataFrame(LocalBoundedDataFrame):
    def __init__(self, df: Any = None, schema: Any = None):
        if df is None:
            assert_or_throw(
                schema is not None, FugueDataFrameInitError("schema is required")
            )
            data: List[List[Any]] = []
            s = schema if isinstance(schema, Schema) else Schema(schema)
        elif isinstance(df, DataFrame):
            s = schema if schema is not None else df.schema
            s = s if isinstance(s, Schema) else Schema(s)
            data = df.as_array(columns=s.names if schema is not None else None)
        elif isinstance(df, Iterable):
            assert_or_throw(
                schema is not None, FugueDataFrameInitError("schema is required")
            )
            s = schema if isinstance(schema, Schema) else Schema(schema)
            data = [list(row) for row in df]
        else:
            raise FugueDataFrameInitError(f"can't build ArrayDataFrame from {type(df)}")
        self._data = data
        super().__init__(s)

    @property
    def native(self) -> List[List[Any]]:
        return self._data

    @property
    def empty(self) -> bool:
        return len(self._data) == 0

    def count(self) -> int:
        return len(self._data)

    def peek_array(self) -> List[Any]:
        self.assert_not_empty()
        return list(self._data[0])

    def _drop_cols(self, cols: List[str]) -> DataFrame:
        keep = [n for n in self.schema.names if n not in cols]
        return self._select_cols(keep)

    def _select_cols(self, cols: List[str]) -> DataFrame:
        idx = [self.schema.index_of_key(c) for c in cols]
        return ArrayDataFrame(
            [[row[i] for i in idx] for row in self._data], self.schema.extract(cols)
        )

    def rename(self, columns: Dict[str, str]) -> DataFrame:
        return ArrayDataFrame(self._data, self.schema.rename(columns))

    def alter_columns(self, columns: Any) -> DataFrame:
        from .arrow_dataframe import ArrowDataFrame

        new_schema = self.schema.alter(columns)
        if new_schema == self.schema:
            return self
        res = ArrowDataFrame(self._data, self.schema).alter_columns(columns)
        return ArrayDataFrame(res.as_array(), res.schema)

    def head(self, n: int, columns: Optional[List[str]] = None) -> LocalBoundedDataFrame:
        res = self if columns is None else self._select_cols(columns)
        return ArrayDataFrame(res.as_array()[:n], res.schema)  # type: ignore

    def as_array(
        self, columns: Optional[List[str]] = None, type_safe: bool = False
    ) -> List[List[Any]]:
        if type_safe:
            from .arrow_dataframe import ArrowDataFrame

            return ArrowDataFrame(self._data, self.schema).as_array(columns)
        if columns is None:
            return self._data
        idx = [self.schema.index_of_key(c) for c in columns]
        return [[row[i] for i in idx] for row in self._data]

    def as_array_iterable(
        self, columns: Optional[List[str]] = None, type_safe: bool = False
    ) -> Iterable[List[Any]]:
        yield from self.as_array(columns, type_safe=type_safe)
