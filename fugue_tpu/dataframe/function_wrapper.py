"""The interfaceless core: adapt plain Python functions by annotations.

Parity with the reference (`fugue/dataframe/function_wrapper.py:50`): each
function parameter/return annotation maps to an ``AnnotatedParam`` with a
one-char code; the concatenated code string is validated against a regex per
extension type. Codes (matching the reference's conventions):

    e  ExecutionEngine          c  DataFrames (multi-input)
    d  DataFrame (any)          l  LocalDataFrame
    s  no-schema local data (List[List], Iterable[List], List[Dict], ...)
    p  pd.DataFrame (+ Iterable[pd.DataFrame])
    q  pa.Table (+ Iterable[pa.Table])
    f  Callable   F  Optional[Callable]
    x  simple param             z  **kwargs
    n  None / no return annotation

New annotated params register via :func:`fugue_annotated_param` — the same
plugin mechanism backends (including the TPU engine) use to accept
``jax.Array``/device-frame annotations.
"""

import inspect
import re
from typing import (
    Any,
    Callable,
    Dict,
    Iterable,
    Iterator,
    List,
    Optional,
    Type,
    Union,
)

import pandas as pd
import pyarrow as pa

from .._utils.assertion import assert_or_throw
from .._utils.hash import to_uuid
from .._utils.iter import EmptyAwareIterable, make_empty_aware
from .._utils.params import IndexedOrderedDict
from ..exceptions import FugueInterfacelessError
from ..schema import Schema
from .array_dataframe import ArrayDataFrame
from .arrow_dataframe import ArrowDataFrame
from .dataframe import DataFrame, LocalDataFrame
from .dataframe_iterable_dataframe import (
    IterableArrowDataFrame,
    IterablePandasDataFrame,
    LocalDataFrameIterableDataFrame,
)
from .dataframes import DataFrames
from .iterable_dataframe import IterableDataFrame
from .pandas_dataframe import PandasDataFrame

_PARAM_REGISTRY: List[Any] = []  # (matcher, cls) pairs, later registrations win


def fugue_annotated_param(
    annotation: Any = None,
    code: Optional[str] = None,
    matcher: Optional[Callable[[Any], bool]] = None,
):
    """Register an ``AnnotatedParam`` class for an annotation."""

    def deco(cls: Type["AnnotatedParam"]) -> Type["AnnotatedParam"]:
        m = matcher
        if m is None:
            m = lambda a: a == annotation  # noqa: E731
        if code is not None:
            cls.code = code
        _PARAM_REGISTRY.insert(0, (m, cls))
        return cls

    return deco


def _compare_iter(tp: Any) -> Callable[[Any], bool]:
    def m(a: Any) -> bool:
        return a in (
            Iterable[tp],
            Iterator[tp],
        ) or str(a) in (
            f"typing.Generator[{tp}, NoneType, NoneType]",
        )

    return m


class AnnotatedParam:
    code = "x"

    def __init__(self, param: Optional[inspect.Parameter]):
        self.param = param

    @property
    def format_hint(self) -> Optional[str]:
        return None

    def __uuid__(self) -> str:
        return to_uuid(type(self).__name__, self.code)


class _OtherParam(AnnotatedParam):
    code = "x"


class _KeywordParam(AnnotatedParam):
    code = "z"


class _NoneParam(AnnotatedParam):
    code = "n"


class _CallableParam(AnnotatedParam):
    code = "f"


class _OptionalCallableParam(AnnotatedParam):
    code = "F"


def _is_callable_anno(a: Any) -> bool:
    return (
        a == Callable
        or a == callable
        or str(a).startswith("typing.Callable")
        or str(a).startswith("collections.abc.Callable")
    )


def _is_opt_callable_anno(a: Any) -> bool:
    s = str(a)
    return (
        a == Optional[Callable]
        or s.startswith("typing.Optional[typing.Callable")
        or s.startswith("typing.Union[typing.Callable")
        or (s.startswith("typing.Optional[collections.abc.Callable"))
    )


class DataFrameParam(AnnotatedParam):
    """Base for params that carry a dataframe."""

    code = "d"

    def to_input_data(self, df: DataFrame, ctx: Any = None) -> Any:
        return df

    def to_output_df(self, output: Any, schema: Optional[Schema], ctx: Any = None) -> DataFrame:
        assert_or_throw(
            isinstance(output, DataFrame),
            lambda: FugueInterfacelessError(f"output {type(output)} is not a DataFrame"),
        )
        assert_or_throw(
            schema is None or output.schema == schema,
            lambda: FugueInterfacelessError(
                f"output schema {output.schema} != expected {schema}"
            ),
        )
        return output

    def count(self, df: Any) -> int:
        raise NotImplementedError

    @property
    def need_schema(self) -> Optional[bool]:
        return False


class LocalDataFrameParam(DataFrameParam):
    code = "l"

    def to_input_data(self, df: DataFrame, ctx: Any = None) -> LocalDataFrame:
        return df.as_local()

    def count(self, df: LocalDataFrame) -> int:
        return df.count() if df.is_bounded else sum(1 for _ in df.as_array_iterable())


class _NoSchemaParam(LocalDataFrameParam):
    """Local data without an attached schema — output schema is mandatory."""

    code = "s"

    @property
    def need_schema(self) -> Optional[bool]:
        return True


class _ListListParam(_NoSchemaParam):
    def to_input_data(self, df: DataFrame, ctx: Any = None) -> List[List[Any]]:
        return df.as_array(type_safe=True)

    def to_output_df(self, output: Any, schema: Optional[Schema], ctx: Any = None) -> DataFrame:
        return ArrayDataFrame(output, schema)

    def count(self, df: List[List[Any]]) -> int:
        return len(df)


class _IterableListParam(_NoSchemaParam):
    def to_input_data(self, df: DataFrame, ctx: Any = None) -> Iterable[List[Any]]:
        return df.as_array_iterable(type_safe=True)

    def to_output_df(self, output: Any, schema: Optional[Schema], ctx: Any = None) -> DataFrame:
        return IterableDataFrame(output, schema)

    def count(self, df: Any) -> int:
        return sum(1 for _ in df)


class _EmptyAwareIterableListParam(_IterableListParam):
    def to_input_data(self, df: DataFrame, ctx: Any = None) -> EmptyAwareIterable[List[Any]]:
        return make_empty_aware(df.as_array_iterable(type_safe=True))


class _ListDictParam(_NoSchemaParam):
    def to_input_data(self, df: DataFrame, ctx: Any = None) -> List[Dict[str, Any]]:
        return df.as_local().as_dicts()

    def to_output_df(self, output: Any, schema: Optional[Schema], ctx: Any = None) -> DataFrame:
        assert_or_throw(schema is not None, FugueInterfacelessError("schema is required"))
        rows = [[r.get(n, None) for n in schema.names] for r in output]
        return ArrayDataFrame(rows, schema)

    def count(self, df: Any) -> int:
        return len(df)


class _IterableDictParam(_NoSchemaParam):
    def to_input_data(self, df: DataFrame, ctx: Any = None) -> Iterable[Dict[str, Any]]:
        return df.as_dict_iterable()

    def to_output_df(self, output: Any, schema: Optional[Schema], ctx: Any = None) -> DataFrame:
        assert_or_throw(schema is not None, FugueInterfacelessError("schema is required"))
        names = schema.names

        def gen() -> Iterable[List[Any]]:
            for r in output:
                yield [r.get(n, None) for n in names]

        return IterableDataFrame(gen(), schema)

    def count(self, df: Any) -> int:
        return sum(1 for _ in df)


class _EmptyAwareIterableDictParam(_IterableDictParam):
    def to_input_data(self, df: DataFrame, ctx: Any = None) -> EmptyAwareIterable[Dict[str, Any]]:
        return make_empty_aware(df.as_dict_iterable())


class _PandasParam(LocalDataFrameParam):
    code = "p"

    def to_input_data(self, df: DataFrame, ctx: Any = None) -> pd.DataFrame:
        return df.as_pandas()

    def to_output_df(self, output: Any, schema: Optional[Schema], ctx: Any = None) -> DataFrame:
        assert_or_throw(
            isinstance(output, pd.DataFrame),
            lambda: FugueInterfacelessError(f"output {type(output)} is not pd.DataFrame"),
        )
        return PandasDataFrame(output, schema)

    def count(self, df: pd.DataFrame) -> int:
        return len(df)

    @property
    def format_hint(self) -> Optional[str]:
        return "pandas"


class _IterablePandasParam(LocalDataFrameParam):
    code = "p"

    def to_input_data(self, df: DataFrame, ctx: Any = None) -> Iterable[pd.DataFrame]:
        if isinstance(df, LocalDataFrameIterableDataFrame):
            for sub in df.native:
                yield sub.as_pandas()
        else:
            yield df.as_pandas()

    def to_output_df(self, output: Any, schema: Optional[Schema], ctx: Any = None) -> DataFrame:
        def gen() -> Iterable[LocalDataFrame]:
            for pdf in output:
                yield PandasDataFrame(pdf, schema)

        return IterablePandasDataFrame(gen(), schema)

    def count(self, df: Any) -> int:
        return sum(len(x) for x in df)

    @property
    def format_hint(self) -> Optional[str]:
        return "pandas"


class _PyArrowTableParam(LocalDataFrameParam):
    code = "q"

    def to_input_data(self, df: DataFrame, ctx: Any = None) -> pa.Table:
        return df.as_arrow()

    def to_output_df(self, output: Any, schema: Optional[Schema], ctx: Any = None) -> DataFrame:
        assert_or_throw(
            isinstance(output, pa.Table),
            lambda: FugueInterfacelessError(f"output {type(output)} is not pa.Table"),
        )
        res = ArrowDataFrame(output)
        if schema is not None and res.schema != schema:
            res = ArrowDataFrame(output, schema)
        return res

    def count(self, df: pa.Table) -> int:
        return df.num_rows

    @property
    def format_hint(self) -> Optional[str]:
        return "pyarrow"


class _IterableArrowParam(LocalDataFrameParam):
    code = "q"

    def to_input_data(self, df: DataFrame, ctx: Any = None) -> Iterable[pa.Table]:
        if isinstance(df, LocalDataFrameIterableDataFrame):
            for sub in df.native:
                yield sub.as_arrow()
        else:
            yield df.as_arrow()

    def to_output_df(self, output: Any, schema: Optional[Schema], ctx: Any = None) -> DataFrame:
        def gen() -> Iterable[LocalDataFrame]:
            for tbl in output:
                adf = ArrowDataFrame(tbl)
                if schema is not None and adf.schema != schema:
                    adf = ArrowDataFrame(tbl, schema)
                yield adf

        return IterableArrowDataFrame(gen(), schema)

    def count(self, df: Any) -> int:
        return sum(x.num_rows for x in df)

    @property
    def format_hint(self) -> Optional[str]:
        return "pyarrow"


class _DataFramesParam(AnnotatedParam):
    code = "c"


# registration order matters only within equal matchers; each matcher is exact
fugue_annotated_param(DataFrame)(DataFrameParam)
fugue_annotated_param(LocalDataFrame)(LocalDataFrameParam)
fugue_annotated_param(List[List[Any]])(_ListListParam)
fugue_annotated_param(matcher=_compare_iter(List[Any]))(_IterableListParam)
fugue_annotated_param(EmptyAwareIterable[List[Any]])(_EmptyAwareIterableListParam)
fugue_annotated_param(List[Dict[str, Any]])(_ListDictParam)
fugue_annotated_param(matcher=_compare_iter(Dict[str, Any]))(_IterableDictParam)
fugue_annotated_param(EmptyAwareIterable[Dict[str, Any]])(_EmptyAwareIterableDictParam)
fugue_annotated_param(pd.DataFrame)(_PandasParam)
fugue_annotated_param(matcher=_compare_iter(pd.DataFrame))(_IterablePandasParam)
fugue_annotated_param(pa.Table)(_PyArrowTableParam)
fugue_annotated_param(matcher=_compare_iter(pa.Table))(_IterableArrowParam)
fugue_annotated_param(DataFrames)(_DataFramesParam)
fugue_annotated_param(matcher=_is_callable_anno)(_CallableParam)
fugue_annotated_param(matcher=_is_opt_callable_anno)(_OptionalCallableParam)


def parse_annotation(
    annotation: Any,
    param: Optional[inspect.Parameter] = None,
    none_as_other: bool = True,
) -> AnnotatedParam:
    if param is not None and param.kind == param.VAR_KEYWORD:
        return _KeywordParam(param)
    if param is not None and param.kind == param.VAR_POSITIONAL:
        raise FugueInterfacelessError("*args is not supported")
    if annotation is None or annotation == type(None) or annotation is inspect.Parameter.empty:
        return _OtherParam(param) if none_as_other else _NoneParam(param)
    for m, cls in _PARAM_REGISTRY:
        try:
            if m(annotation):
                return cls(param)
        except Exception:
            continue
    return _OtherParam(param)


class DataFrameFunctionWrapper:
    """Wrap a plain function; validate and adapt its dataframe params."""

    def __init__(self, func: Callable, params_re: str = ".*", return_re: str = ".*"):
        from .._utils.convert import annotation_of

        self._func = func
        sig = inspect.signature(func)
        self._params: IndexedOrderedDict = IndexedOrderedDict()
        for name, param in sig.parameters.items():
            anno = annotation_of(func, name)
            if anno is inspect.Parameter.empty:
                anno = param.annotation
            self._params[name] = parse_annotation(anno, param)
        rt_anno = annotation_of(func, None)
        if rt_anno is inspect.Parameter.empty:
            rt_anno = sig.return_annotation
        self._rt = parse_annotation(rt_anno, None, none_as_other=False)
        self._input_code = "".join(p.code for p in self._params.values())
        assert_or_throw(
            re.match(params_re, self._input_code) is not None,
            lambda: FugueInterfacelessError(
                f"input signature {self._input_code!r} of {func} "
                f"doesn't match pattern {params_re!r}"
            ),
        )
        assert_or_throw(
            re.match(return_re, self._rt.code) is not None,
            lambda: FugueInterfacelessError(
                f"return annotation code {self._rt.code!r} of {func} "
                f"doesn't match pattern {return_re!r}"
            ),
        )

    @property
    def input_code(self) -> str:
        return self._input_code

    @property
    def output_code(self) -> str:
        return self._rt.code

    @property
    def params(self) -> IndexedOrderedDict:
        return self._params

    @property
    def rt(self) -> AnnotatedParam:
        return self._rt

    @property
    def need_output_schema(self) -> Optional[bool]:
        return (
            self._rt.need_schema
            if isinstance(self._rt, DataFrameParam)
            else None
        )

    def get_format_hint(self) -> Optional[str]:
        for p in self._params.values():
            if p.format_hint is not None:
                return p.format_hint
        if isinstance(self._rt, AnnotatedParam) and self._rt.format_hint is not None:
            return self._rt.format_hint
        return None

    def __uuid__(self) -> str:
        return to_uuid(self._func, self._input_code, self._rt.code)

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        return self._func(*args, **kwargs)

    def run(
        self,
        args: List[Any],
        kwargs: Dict[str, Any],
        ignore_unknown: bool = False,
        output_schema: Any = None,
        output: bool = True,
        ctx: Any = None,
    ) -> Any:
        """Call the wrapped function, converting dataframe args per annotation."""
        schema = None if output_schema is None else (
            output_schema if isinstance(output_schema, Schema) else Schema(output_schema)
        )
        p: Dict[str, Any] = {}
        remaining = dict(kwargs)
        i = 0
        for name, ap in self._params.items():
            if isinstance(ap, _KeywordParam):
                continue
            if i < len(args):
                p[name] = self._to_input(ap, args[i], ctx)
                i += 1
            elif name in remaining:
                p[name] = self._to_input(ap, remaining.pop(name), ctx)
            elif ap.param is not None and ap.param.default is not inspect.Parameter.empty:
                pass  # use default
            elif isinstance(ap, _OptionalCallableParam):
                p[name] = None
        has_kw = any(isinstance(ap, _KeywordParam) for ap in self._params.values())
        if len(remaining) > 0:
            if has_kw:
                p.update(remaining)
            elif not ignore_unknown:
                raise FugueInterfacelessError(
                    f"{list(remaining.keys())} are not acceptable by {self._func}"
                )
        result = self._func(**p)
        if not output:
            if isinstance(result, (Iterator, Iterable)) and not isinstance(
                result, (str, bytes, list, dict, pd.DataFrame, pa.Table)
            ):
                for _ in result:  # drain generators so side effects happen
                    pass
            return None
        if isinstance(self._rt, DataFrameParam):
            return self._rt.to_output_df(result, schema, ctx)
        return result

    def _to_input(self, ap: AnnotatedParam, value: Any, ctx: Any) -> Any:
        if isinstance(ap, DataFrameParam) and isinstance(value, DataFrame):
            return ap.to_input_data(value, ctx)
        return value
