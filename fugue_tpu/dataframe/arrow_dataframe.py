"""ArrowDataFrame — the columnar workhorse local frame.

Parity with the reference (`fugue/dataframe/arrow_dataframe.py:45`). Arrow is
the interchange format of the whole framework (and the host-side format the
TPU engine converts to/from device arrays), so this frame is the canonical
type-safe local representation.
"""

from typing import Any, Dict, Iterable, List, Optional

import pandas as pd
import pyarrow as pa

from .._utils.assertion import assert_or_throw
from ..exceptions import FugueDataFrameInitError, FugueDataFrameOperationError
from ..schema import Schema, _normalize_type
from .dataframe import DataFrame, LocalBoundedDataFrame


def _normalize_table(tbl: pa.Table) -> pa.Table:
    target = pa.schema([pa.field(f.name, _normalize_type(f.type)) for f in tbl.schema])
    if target != tbl.schema:
        tbl = tbl.cast(target)
    return tbl


def build_arrow_table(df: Any, schema: Optional[Schema]) -> pa.Table:
    """Build a ``pa.Table`` from tables/pandas/arrays/iterables + schema."""
    if df is None:
        assert_or_throw(
            schema is not None, FugueDataFrameInitError("schema is required")
        )
        return schema.create_empty_arrow_table()
    if isinstance(df, pa.Table):
        if schema is not None and Schema(df.schema) != schema:
            return df.cast(schema.pa_schema)
        return _normalize_table(df)
    if isinstance(df, pa.RecordBatch):
        return build_arrow_table(pa.Table.from_batches([df]), schema)
    if isinstance(df, pd.DataFrame):
        if schema is None:
            schema = Schema(df)
        return pa.Table.from_pandas(
            df, schema=schema.pa_schema, preserve_index=False, safe=False
        )
    if isinstance(df, Iterable):
        assert_or_throw(
            schema is not None, FugueDataFrameInitError("schema is required")
        )
        names = schema.names
        rows = [dict(zip(names, row)) for row in df]
        if len(rows) == 0:
            return schema.create_empty_arrow_table()
        try:
            return pa.Table.from_pylist(rows, schema=schema.pa_schema)
        except pa.ArrowInvalid:
            raise
        except pa.lib.ArrowTypeError:
            # string literals for date/timestamp columns (the reference
            # accepts "2020-01-01" in array frames): build loose, then cast
            arrays = []
            for f in schema.pa_schema:
                vals = [r.get(f.name) for r in rows]
                if pa.types.is_date(f.type) or pa.types.is_timestamp(f.type):
                    arr = pa.array(vals)
                    if pa.types.is_string(arr.type):
                        arr = arr.cast(pa.timestamp("us")).cast(f.type)
                    else:
                        arr = arr.cast(f.type)
                else:
                    arr = pa.array(vals, type=f.type)
                arrays.append(arr)
            return pa.Table.from_arrays(arrays, schema=schema.pa_schema)
    raise FugueDataFrameInitError(f"can't build arrow table from {type(df)}")


class ArrowDataFrame(LocalBoundedDataFrame):
    def __init__(self, df: Any = None, schema: Any = None):
        s = None if schema is None else (schema if isinstance(schema, Schema) else Schema(schema))
        if isinstance(df, DataFrame):
            tbl = df.as_arrow()
            if s is not None and Schema(tbl.schema) != s:
                tbl = tbl.cast(s.pa_schema)
        else:
            tbl = build_arrow_table(df, s)
        self._native = tbl
        super().__init__(Schema(tbl.schema))

    @property
    def native(self) -> pa.Table:
        return self._native

    def native_as_df(self) -> pa.Table:
        return self._native

    @property
    def empty(self) -> bool:
        return self._native.num_rows == 0

    def count(self) -> int:
        return self._native.num_rows

    def peek_array(self) -> List[Any]:
        self.assert_not_empty()
        row = self._native.slice(0, 1).to_pylist()[0]
        return [_postprocess(v) for v in row.values()]

    def as_arrow(self, type_safe: bool = False) -> pa.Table:
        return self._native

    def as_pandas(self) -> pd.DataFrame:
        from .._utils.arrow import pa_table_to_pandas

        return pa_table_to_pandas(self._native)

    def _drop_cols(self, cols: List[str]) -> DataFrame:
        keep = [n for n in self.schema.names if n not in cols]
        return ArrowDataFrame(self._native.select(keep))

    def _select_cols(self, cols: List[str]) -> DataFrame:
        return ArrowDataFrame(self._native.select(cols))

    def rename(self, columns: Dict[str, str]) -> DataFrame:
        new_schema = self.schema.rename(columns)  # validates
        return ArrowDataFrame(self._native.rename_columns(new_schema.names))

    def alter_columns(self, columns: Any) -> DataFrame:
        new_schema = self.schema.alter(columns)
        if new_schema == self.schema:
            return self
        try:
            return ArrowDataFrame(self._native.cast(new_schema.pa_schema))
        except pa.ArrowInvalid as e:
            raise FugueDataFrameOperationError(str(e)) from e

    def head(self, n: int, columns: Optional[List[str]] = None) -> LocalBoundedDataFrame:
        tbl = self._native if columns is None else self._native.select(columns)
        return ArrowDataFrame(tbl.slice(0, n))

    def as_array(
        self, columns: Optional[List[str]] = None, type_safe: bool = False
    ) -> List[List[Any]]:
        tbl = self._native if columns is None else self._native.select(columns)
        return [[_postprocess(v) for v in row.values()] for row in tbl.to_pylist()]

    def as_array_iterable(
        self, columns: Optional[List[str]] = None, type_safe: bool = False
    ) -> Iterable[List[Any]]:
        tbl = self._native if columns is None else self._native.select(columns)
        for batch in tbl.to_batches():
            for row in batch.to_pylist():
                yield [_postprocess(v) for v in row.values()]


def _postprocess(v: Any) -> Any:
    # pyarrow returns maps as list-of-tuples; keep as-is (reference behavior)
    return v
