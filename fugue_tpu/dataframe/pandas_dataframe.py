"""PandasDataFrame — local frame over ``pd.DataFrame``.

Parity with the reference (`fugue/dataframe/pandas_dataframe.py:38`),
including the zero-copy wrapper mode (``pandas_df_wrapper=True``) used when
the caller guarantees dtypes already match the schema.
"""

from typing import Any, Dict, Iterable, List, Optional

import numpy as np
import pandas as pd
import pyarrow as pa

from .._utils.assertion import assert_or_throw
from ..exceptions import FugueDataFrameInitError, FugueDataFrameOperationError
from ..schema import Schema
from .dataframe import DataFrame, LocalBoundedDataFrame
from .arrow_dataframe import ArrowDataFrame


def _enforce_type(pdf: pd.DataFrame, schema: Schema) -> pd.DataFrame:
    """Coerce a pandas frame to a schema.

    Fast path: if every column's dtype already equals the schema's expected
    pandas dtype, return as-is (zero copy; the check reads only dtype
    metadata — no per-column Series materialization, it runs once per map
    partition). Otherwise columns coerce individually: plain numeric/bool
    conversions from NaN-free kinds go through ``astype`` (semantics match
    the arrow path's ``safe=False``), and ONLY the columns that need real
    conversion semantics (objects, nullables, datetimes, float→int) pay an
    arrow round trip — one conversion per partition, never a whole-frame
    pandas↔arrow↔pandas bounce per boundary crossing.
    """
    expected = schema.pandas_dtype
    names = schema.names
    if list(pdf.columns) == names and all(
        dt == expected[c] for c, dt in pdf.dtypes.items()
    ):
        return pdf
    idx = pdf.index
    if not (isinstance(idx, pd.RangeIndex) and idx.start == 0 and idx.step == 1):
        # positional semantics (the arrow path's preserve_index=False):
        # all coerced pieces below must share one clean range index
        pdf = pdf.reset_index(drop=True)
    cols: Dict[str, Any] = {}
    arrow_names: List[str] = []
    for c in names:
        s = pdf[c]
        et = expected[c]
        if s.dtype == et:
            cols[c] = s
        elif (
            isinstance(s.dtype, np.dtype)
            and isinstance(et, np.dtype)
            and et.kind in "iufb"
            and (s.dtype.kind in "iub" or (s.dtype.kind == "f" and et.kind == "f"))
        ):
            cols[c] = s.astype(et)
        else:
            arrow_names.append(c)
    if len(arrow_names) > 0:
        from .._utils.arrow import pa_table_to_pandas

        tbl = pa.Table.from_pandas(
            pdf[arrow_names],
            schema=pa.schema([schema.pa_schema.field(c) for c in arrow_names]),
            preserve_index=False,
            safe=False,
        )
        conv = pa_table_to_pandas(tbl)
        for c in arrow_names:
            cols[c] = conv[c]
    # rebuild in schema order — arrow-coerced columns joined the dict last
    return pd.DataFrame({c: cols[c] for c in names})


class PandasDataFrame(LocalBoundedDataFrame):
    def __init__(
        self,
        df: Any = None,
        schema: Any = None,
        pandas_df_wrapper: bool = False,
    ):
        s = None if schema is None else (schema if isinstance(schema, Schema) else Schema(schema))
        if df is None:
            assert_or_throw(s is not None, FugueDataFrameInitError("schema is required"))
            pdf = s.create_empty_pandas_df()
        elif isinstance(df, PandasDataFrame):
            pdf = df.native
            s = s or df.schema
        elif isinstance(df, DataFrame):
            pdf = df.as_pandas()
            s = s or df.schema
        elif isinstance(df, pd.DataFrame):
            idx = df.index
            clean = (
                isinstance(idx, pd.RangeIndex)
                and (idx.start or 0) == 0
                and idx.step == 1
            ) or idx.equals(pd.RangeIndex(len(df)))
            pdf = df if clean else df.reset_index(drop=True)
            if s is None:
                s = Schema(pdf)
        elif isinstance(df, pd.Series):
            pdf = df.to_frame()
            if s is not None:
                assert_or_throw(
                    list(pdf.columns) == s.names,
                    lambda: FugueDataFrameInitError(
                        f"series name {list(pdf.columns)} != schema {s.names}"
                    ),
                )
            else:
                s = Schema(pdf)
        elif isinstance(df, Iterable):
            assert_or_throw(s is not None, FugueDataFrameInitError("schema is required"))
            data = list(df)
            if len(data) == 0:
                pdf = s.create_empty_pandas_df()
            else:
                tbl = pa.Table.from_pylist(
                    [dict(zip(s.names, row)) for row in data], schema=s.pa_schema
                )
                from .._utils.arrow import pa_table_to_pandas

                pdf = pa_table_to_pandas(tbl)
        else:
            raise FugueDataFrameInitError(f"can't build PandasDataFrame from {type(df)}")
        if not pandas_df_wrapper:
            missing = [c for c in s.names if c not in pdf.columns]
            assert_or_throw(
                len(missing) == 0,
                lambda: FugueDataFrameInitError(
                    f"columns {missing} in schema {s} not in data {list(pdf.columns)}"
                ),
            )
            pdf = _enforce_type(pdf, s)
        self._native = pdf
        super().__init__(s)

    @property
    def native(self) -> pd.DataFrame:
        return self._native

    def native_as_df(self) -> pd.DataFrame:
        return self._native

    @property
    def empty(self) -> bool:
        return len(self._native) == 0

    def count(self) -> int:
        return len(self._native)

    def peek_array(self) -> List[Any]:
        self.assert_not_empty()
        head = pa.Table.from_pandas(
            self._native.head(1),
            schema=self.schema.pa_schema,
            preserve_index=False,
            safe=False,
        )
        return list(head.to_pylist()[0].values())

    def as_pandas(self) -> pd.DataFrame:
        return self._native

    def as_arrow(self, type_safe: bool = False) -> pa.Table:
        return pa.Table.from_pandas(
            self._native, schema=self.schema.pa_schema, preserve_index=False, safe=False
        )

    def _drop_cols(self, cols: List[str]) -> DataFrame:
        keep = [n for n in self.schema.names if n not in cols]
        return PandasDataFrame(
            self._native[keep], self.schema.extract(keep), pandas_df_wrapper=True
        )

    def _select_cols(self, cols: List[str]) -> DataFrame:
        return PandasDataFrame(
            self._native[cols], self.schema.extract(cols), pandas_df_wrapper=True
        )

    def rename(self, columns: Dict[str, str]) -> DataFrame:
        new_schema = self.schema.rename(columns)
        pdf = self._native.rename(columns=columns)
        return PandasDataFrame(pdf, new_schema, pandas_df_wrapper=True)

    def alter_columns(self, columns: Any) -> DataFrame:
        new_schema = self.schema.alter(columns)
        if new_schema == self.schema:
            return self
        return ArrowDataFrame(self.as_arrow()).alter_columns(columns)

    def head(self, n: int, columns: Optional[List[str]] = None) -> LocalBoundedDataFrame:
        pdf = self._native if columns is None else self._native[columns]
        schema = self.schema if columns is None else self.schema.extract(columns)
        return PandasDataFrame(pdf.head(n), schema, pandas_df_wrapper=True)

    def as_array(
        self, columns: Optional[List[str]] = None, type_safe: bool = False
    ) -> List[List[Any]]:
        # always go through arrow: nulls become None, values match schema types
        return ArrowDataFrame(self.as_arrow()).as_array(columns)

    def as_array_iterable(
        self, columns: Optional[List[str]] = None, type_safe: bool = False
    ) -> Iterable[List[Any]]:
        yield from ArrowDataFrame(self.as_arrow()).as_array_iterable(columns)
