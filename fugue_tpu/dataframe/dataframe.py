"""DataFrame ABC: schema-carrying datasets with columnar conversions.

Parity with the reference (`fugue/dataframe/dataframe.py:29-299`):
lazy schema, conversions (pandas/arrow/arrays/dicts), column ops
(rename/drop/alter/head), local/bounded variants, and the ``YieldedDataFrame``
handle used by workflow yields. Redesigned TPU-first: conversions are
columnar (arrow is the interchange format); per-row paths exist only for the
user-facing ``as_array*`` APIs.
"""

from abc import abstractmethod
from typing import Any, Callable, Dict, Iterable, List, Optional, Union

import pandas as pd
import pyarrow as pa

from .._utils.assertion import assert_or_throw
from ..collections.yielded import Yielded
from ..dataset.dataset import Dataset, DatasetDisplay, get_dataset_display
from ..exceptions import (
    FugueDataFrameEmptyError,
    FugueDataFrameOperationError,
    FugueInvalidOperation,
)
from ..schema import Schema

AnySchema = Union[Schema, str, pa.Schema, List[Any], Dict[str, Any], None]


class DataFrame(Dataset):
    """Abstract schema-carrying dataframe."""

    def __init__(self, schema: Any = None):
        super().__init__()
        if callable(schema):
            self._schema: Union[Schema, Callable[[], Any]] = schema
            self._schema_discovered = False
        else:
            s = schema if isinstance(schema, Schema) else Schema(schema)
            s.assert_not_empty().set_readonly()
            self._schema = s
            self._schema_discovered = True

    @property
    def schema(self) -> Schema:
        if not self._schema_discovered:
            raw = self._schema()  # type: ignore
            s = raw if isinstance(raw, Schema) else Schema(raw)
            s.assert_not_empty().set_readonly()
            self._schema = s
            self._schema_discovered = True
        return self._schema  # type: ignore

    @property
    def schema_discovered(self) -> bool:
        return self._schema_discovered

    @property
    def columns(self) -> List[str]:
        return self.schema.names

    # ---- abstract surface -------------------------------------------------
    @abstractmethod
    def peek_array(self) -> List[Any]:
        """First row as a list; raises when empty."""
        raise NotImplementedError

    @abstractmethod
    def as_local_bounded(self) -> "LocalBoundedDataFrame":
        raise NotImplementedError

    @abstractmethod
    def as_array(
        self, columns: Optional[List[str]] = None, type_safe: bool = False
    ) -> List[List[Any]]:
        raise NotImplementedError

    @abstractmethod
    def as_array_iterable(
        self, columns: Optional[List[str]] = None, type_safe: bool = False
    ) -> Iterable[List[Any]]:
        raise NotImplementedError

    @abstractmethod
    def _drop_cols(self, cols: List[str]) -> "DataFrame":
        raise NotImplementedError

    @abstractmethod
    def _select_cols(self, cols: List[str]) -> "DataFrame":
        raise NotImplementedError

    @abstractmethod
    def rename(self, columns: Dict[str, str]) -> "DataFrame":
        raise NotImplementedError

    @abstractmethod
    def alter_columns(self, columns: Any) -> "DataFrame":
        """Cast a subset of columns to new types (``columns`` is schema-like)."""
        raise NotImplementedError

    @abstractmethod
    def head(
        self, n: int, columns: Optional[List[str]] = None
    ) -> "LocalBoundedDataFrame":
        raise NotImplementedError

    # ---- provided ---------------------------------------------------------
    def as_local(self) -> "LocalDataFrame":
        return self.as_local_bounded()

    def peek_dict(self) -> Dict[str, Any]:
        arr = self.peek_array()
        return dict(zip(self.schema.names, arr))

    def as_dicts(self, columns: Optional[List[str]] = None) -> List[Dict[str, Any]]:
        names = columns or self.schema.names
        return [dict(zip(names, row)) for row in self.as_array(columns, type_safe=True)]

    def as_dict_iterable(
        self, columns: Optional[List[str]] = None
    ) -> Iterable[Dict[str, Any]]:
        names = columns or self.schema.names
        for row in self.as_array_iterable(columns, type_safe=True):
            yield dict(zip(names, row))

    def as_pandas(self) -> pd.DataFrame:
        return self.as_arrow().to_pandas(use_threads=False)

    def as_arrow(self, type_safe: bool = False) -> pa.Table:
        return pa.Table.from_pylist(
            [dict(zip(self.schema.names, row)) for row in self.as_array(type_safe=True)],
            schema=self.schema.pa_schema,
        )

    def drop(self, columns: List[str]) -> "DataFrame":
        assert_or_throw(
            len(columns) > 0, FugueDataFrameOperationError("columns can't be empty")
        )
        missing = [c for c in columns if c not in self.schema]
        assert_or_throw(
            len(missing) == 0,
            lambda: FugueDataFrameOperationError(f"columns {missing} not in {self.schema}"),
        )
        assert_or_throw(
            len(columns) < len(self.schema),
            FugueDataFrameOperationError("can't drop all columns"),
        )
        return self._drop_cols(columns)

    def __getitem__(self, columns: List[Any]) -> "DataFrame":
        assert_or_throw(
            isinstance(columns, list) and len(columns) > 0,
            FugueDataFrameOperationError("columns must be a non-empty list"),
        )
        missing = [c for c in columns if c not in self.schema]
        assert_or_throw(
            len(missing) == 0,
            lambda: FugueDataFrameOperationError(f"columns {missing} not in {self.schema}"),
        )
        return self._select_cols(columns)

    def get_info_str(self) -> str:
        return f"{type(self).__name__}({self.schema})"

    def __repr__(self) -> str:
        return self.get_info_str()

    def _repr_html_(self) -> str:
        try:
            return get_dataset_display(self).repr_html()
        except NotImplementedError:
            return "<pre>" + self.get_info_str() + "</pre>"

    def assert_not_empty(self) -> None:
        if self.empty:
            raise FugueDataFrameEmptyError("dataframe is empty")


class LocalDataFrame(DataFrame):
    """A dataframe fully resident in the driver process."""

    @property
    def is_local(self) -> bool:
        return True

    @property
    def num_partitions(self) -> int:
        return 1


class LocalBoundedDataFrame(LocalDataFrame):
    @property
    def is_bounded(self) -> bool:
        return True

    def as_local_bounded(self) -> "LocalBoundedDataFrame":
        return self


class LocalUnboundedDataFrame(LocalDataFrame):
    @property
    def is_bounded(self) -> bool:
        return False

    def count(self) -> int:
        raise FugueInvalidOperation("can't count an unbounded dataframe")


class YieldedDataFrame(Yielded):
    """A dataframe-valued workflow yield (reference
    ``fugue/dataframe/dataframe.py:384``)."""

    def __init__(self, yid: str):
        super().__init__(yid)
        self._df: Optional[DataFrame] = None

    @property
    def is_set(self) -> bool:
        return self._df is not None

    def set_value(self, df: DataFrame) -> None:
        self._df = df

    @property
    def result(self) -> DataFrame:
        assert_or_throw(self.is_set, FugueInvalidOperation("value is not set"))
        return self._df  # type: ignore


class DataFrameDisplay(DatasetDisplay):
    """Plain-text tabular display for any DataFrame."""

    @property
    def df(self) -> DataFrame:
        return self._ds  # type: ignore

    def show(
        self, n: int = 10, with_count: bool = False, title: Optional[str] = None
    ) -> None:
        head = self.df.head(n)
        rows = head.as_array(type_safe=True)
        print(self.render(rows, with_count=with_count, title=title, n=n))

    def render(
        self,
        rows: List[List[Any]],
        with_count: bool = False,
        title: Optional[str] = None,
        n: int = 10,
    ) -> str:
        lines: List[str] = []
        if title is not None:
            lines.append(title)
        schema = self.df.schema
        headers = [f"{f.name}:{_short_type(f)}" for f in schema.fields]
        widths = [
            max(len(h), *(len(_cell(r[i])) for r in rows)) if len(rows) > 0 else len(h)
            for i, h in enumerate(headers)
        ]
        lines.append("|".join(h.ljust(w) for h, w in zip(headers, widths)))
        lines.append("+".join("-" * w for w in widths))
        for r in rows:
            lines.append("|".join(_cell(v).ljust(w) for v, w in zip(r, widths)))
        if with_count:
            lines.append(f"Total count: {self.df.count()}")
        return "\n".join(lines)


@get_dataset_display.candidate(lambda ds: isinstance(ds, DataFrame), priority=0.1)
def _default_dataframe_display(ds: Dataset) -> DatasetDisplay:
    return DataFrameDisplay(ds)


def _short_type(f: pa.Field) -> str:
    from ..schema import type_to_expression

    return type_to_expression(f.type)


def _cell(v: Any) -> str:
    if v is None:
        return "NULL"
    s = str(v)
    return s if len(s) <= 40 else s[:37] + "..."
