"""LocalDataFrameIterableDataFrame — a stream of local frames.

Parity with the reference (`fugue/dataframe/dataframe_iterable_dataframe.py:21`):
the chunked output format of map operations, letting a partition be processed
as a sequence of small columnar frames without full materialization.
"""

from typing import Any, Dict, Iterable, List, Optional

import pandas as pd
import pyarrow as pa

from .._utils.assertion import assert_or_throw
from .._utils.iter import EmptyAwareIterable, make_empty_aware
from ..exceptions import FugueDataFrameInitError
from ..schema import Schema
from .array_dataframe import ArrayDataFrame
from .arrow_dataframe import ArrowDataFrame
from .dataframe import DataFrame, LocalBoundedDataFrame, LocalDataFrame, LocalUnboundedDataFrame
from .pandas_dataframe import PandasDataFrame


class LocalDataFrameIterableDataFrame(LocalUnboundedDataFrame):
    def __init__(self, df: Any = None, schema: Any = None):
        if df is None:
            it: Iterable[LocalDataFrame] = []
        elif isinstance(df, LocalDataFrameIterableDataFrame):
            it = df.native
            schema = schema or (df.schema if df.schema_discovered else None)
        elif isinstance(df, DataFrame):
            it = [df.as_local()]  # type: ignore
            schema = schema or df.schema
        elif isinstance(df, Iterable):
            it = df
        else:
            raise FugueDataFrameInitError(
                f"can't build LocalDataFrameIterableDataFrame from {type(df)}"
            )
        self._native: EmptyAwareIterable[LocalDataFrame] = make_empty_aware(
            self._wrap(it)
        )
        if schema is not None:
            super().__init__(schema)
        else:
            assert_or_throw(
                not self._native.empty,
                FugueDataFrameInitError(
                    "schema is required when the iterable can be empty"
                ),
            )
            super().__init__(lambda: self._native.peek().schema)

    def _wrap(self, it: Iterable[Any]) -> Iterable[LocalDataFrame]:
        for x in it:
            if isinstance(x, LocalDataFrame):
                yield x
            elif isinstance(x, pd.DataFrame):
                yield PandasDataFrame(x)
            elif isinstance(x, pa.Table):
                yield ArrowDataFrame(x)
            else:
                raise FugueDataFrameInitError(f"invalid chunk type {type(x)}")

    @property
    def native(self) -> EmptyAwareIterable[LocalDataFrame]:
        return self._native

    @property
    def empty(self) -> bool:
        # like the reference, only the head chunk is inspected (one-pass)
        return self._native.empty or self._native.peek().empty

    def peek_array(self) -> List[Any]:
        self.assert_not_empty()
        return self._native.peek().peek_array()

    def as_local_bounded(self) -> LocalBoundedDataFrame:
        chunks = [f for f in self._native if f.count() > 0]
        if len(chunks) == 0:
            return ArrowDataFrame(None, self.schema)
        # all-pandas chunks with identical schemas concat natively — the
        # per-chunk pandas→arrow conversion is the map loop's single
        # largest assembly cost
        if all(
            isinstance(f, PandasDataFrame) and f.schema == self.schema
            for f in chunks
        ):
            import pandas as pd

            return PandasDataFrame(
                pd.concat([f.native for f in chunks], ignore_index=True),
                self.schema,
                pandas_df_wrapper=True,
            )
        tables = [f.as_arrow() for f in chunks]
        target = self.schema.pa_schema
        tables = [t if t.schema == target else t.cast(target) for t in tables]
        return ArrowDataFrame(pa.concat_tables(tables))

    def _drop_cols(self, cols: List[str]) -> DataFrame:
        schema = self.schema - cols

        def gen() -> Iterable[LocalDataFrame]:
            for f in self._native:
                yield f.drop(cols)  # type: ignore

        return LocalDataFrameIterableDataFrame(gen(), schema)

    def _select_cols(self, cols: List[str]) -> DataFrame:
        schema = self.schema.extract(cols)

        def gen() -> Iterable[LocalDataFrame]:
            for f in self._native:
                yield f[cols]  # type: ignore

        return LocalDataFrameIterableDataFrame(gen(), schema)

    def rename(self, columns: Dict[str, str]) -> DataFrame:
        schema = self.schema.rename(columns)

        def gen() -> Iterable[LocalDataFrame]:
            for f in self._native:
                yield f.rename(columns)  # type: ignore

        return LocalDataFrameIterableDataFrame(gen(), schema)

    def alter_columns(self, columns: Any) -> DataFrame:
        schema = self.schema.alter(columns)
        if schema == self.schema:
            return self

        def gen() -> Iterable[LocalDataFrame]:
            for f in self._native:
                yield f.alter_columns(columns)  # type: ignore

        return LocalDataFrameIterableDataFrame(gen(), schema)

    def head(self, n: int, columns: Optional[List[str]] = None) -> LocalBoundedDataFrame:
        rows: List[List[Any]] = []
        src = self if columns is None else self._select_cols(columns)
        for f in src.native:  # type: ignore
            if len(rows) >= n:
                break
            rows.extend(f.head(n - len(rows)).as_array())
        return ArrayDataFrame(rows, src.schema)

    def as_array(
        self, columns: Optional[List[str]] = None, type_safe: bool = False
    ) -> List[List[Any]]:
        return list(self.as_array_iterable(columns, type_safe=type_safe))

    def as_array_iterable(
        self, columns: Optional[List[str]] = None, type_safe: bool = False
    ) -> Iterable[List[Any]]:
        for f in self._native:
            yield from f.as_array_iterable(columns, type_safe=type_safe)

    def as_pandas(self) -> pd.DataFrame:
        return self.as_local_bounded().as_pandas()

    def as_arrow(self, type_safe: bool = False) -> pa.Table:
        return self.as_local_bounded().as_arrow()


class IterablePandasDataFrame(LocalDataFrameIterableDataFrame):
    """Stream of pandas chunks (reference ``:202``)."""


class IterableArrowDataFrame(LocalDataFrameIterableDataFrame):
    """Stream of arrow chunks (reference ``:207``)."""
