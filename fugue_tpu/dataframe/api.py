"""Functional DataFrame API — plugin-dispatched over *any* frame type.

Parity with the reference (`fugue/dataframe/api.py`): each verb works on
fugue frames, pandas frames, arrow tables, and anything a backend registers
a candidate for (the TPU engine registers its device frames).
"""

from typing import Any, Dict, Iterable, List, Optional

import pandas as pd
import pyarrow as pa

from .._utils.registry import fugue_plugin
from ..schema import Schema
from .arrow_dataframe import ArrowDataFrame
from .dataframe import DataFrame, LocalBoundedDataFrame
from .pandas_dataframe import PandasDataFrame

AnyDataFrame = Any


@fugue_plugin
def as_fugue_df(df: AnyDataFrame, **kwargs: Any) -> DataFrame:
    """Convert any supported object to a fugue DataFrame (plugin hook)."""
    if isinstance(df, DataFrame):
        return df
    if isinstance(df, pd.DataFrame):
        return PandasDataFrame(df, **kwargs)
    if isinstance(df, (pa.Table, pa.RecordBatch)):
        return ArrowDataFrame(df, **kwargs)
    raise NotImplementedError(f"can't convert {type(df)} to a fugue DataFrame")


def is_df(df: Any) -> bool:
    try:
        return isinstance(df, DataFrame) or as_fugue_df(df) is not None
    except NotImplementedError:
        return False


@fugue_plugin
def get_native_as_df(df: AnyDataFrame) -> AnyDataFrame:
    """Return the most natural native object of a dataframe."""
    if isinstance(df, DataFrame):
        return df.native
    return df


def get_schema(df: AnyDataFrame) -> Schema:
    return as_fugue_df(df).schema


def get_column_names(df: AnyDataFrame) -> List[Any]:
    return get_schema(df).names


def rename(df: AnyDataFrame, columns: Dict[str, Any], as_fugue: bool = False) -> AnyDataFrame:
    if len(columns) == 0:
        return as_fugue_df(df) if as_fugue else df
    return _adjust(df, as_fugue_df(df).rename(columns), as_fugue)


def drop_columns(df: AnyDataFrame, columns: List[str], as_fugue: bool = False) -> AnyDataFrame:
    return _adjust(df, as_fugue_df(df).drop(columns), as_fugue)


def select_columns(df: AnyDataFrame, columns: List[Any], as_fugue: bool = False) -> AnyDataFrame:
    return _adjust(df, as_fugue_df(df)[columns], as_fugue)


def alter_columns(df: AnyDataFrame, columns: Any, as_fugue: bool = False) -> AnyDataFrame:
    return _adjust(df, as_fugue_df(df).alter_columns(columns), as_fugue)


def head(
    df: AnyDataFrame, n: int, columns: Optional[List[str]] = None, as_fugue: bool = False
) -> AnyDataFrame:
    return _adjust(df, as_fugue_df(df).head(n, columns=columns), as_fugue)


def peek_array(df: AnyDataFrame) -> List[Any]:
    return as_fugue_df(df).peek_array()


def peek_dict(df: AnyDataFrame) -> Dict[str, Any]:
    return as_fugue_df(df).peek_dict()


def as_array(
    df: AnyDataFrame, columns: Optional[List[str]] = None, type_safe: bool = False
) -> List[List[Any]]:
    return as_fugue_df(df).as_array(columns=columns, type_safe=type_safe)


def as_array_iterable(
    df: AnyDataFrame, columns: Optional[List[str]] = None, type_safe: bool = False
) -> Iterable[List[Any]]:
    return as_fugue_df(df).as_array_iterable(columns=columns, type_safe=type_safe)


def as_dicts(df: AnyDataFrame, columns: Optional[List[str]] = None) -> List[Dict[str, Any]]:
    return as_fugue_df(df).as_dicts(columns=columns)


def as_dict_iterable(
    df: AnyDataFrame, columns: Optional[List[str]] = None
) -> Iterable[Dict[str, Any]]:
    return as_fugue_df(df).as_dict_iterable(columns=columns)


def as_pandas(df: AnyDataFrame) -> pd.DataFrame:
    return as_fugue_df(df).as_pandas()


def as_arrow(df: AnyDataFrame) -> pa.Table:
    return as_fugue_df(df).as_arrow()


def as_local(df: AnyDataFrame, as_fugue: bool = False) -> AnyDataFrame:
    res = as_fugue_df(df).as_local()
    return res if as_fugue else get_native_as_df(res)


def as_local_bounded(df: AnyDataFrame, as_fugue: bool = False) -> AnyDataFrame:
    res = as_fugue_df(df).as_local_bounded()
    return res if as_fugue else get_native_as_df(res)


def normalize_column_names(df: AnyDataFrame) -> Any:
    """Rename columns not expressible in schema syntax to ``_N`` and return
    (renamed_df, inverse_rename_map) — reference ``fugue/dataframe/api.py``."""
    fdf = as_fugue_df(df)
    rename_map: Dict[str, str] = {}
    inverse: Dict[str, str] = {}
    for i, name in enumerate(fdf.schema.names):
        if not name.isidentifier():
            new = f"_{i}"
            rename_map[name] = new
            inverse[new] = name
    if len(rename_map) == 0:
        return df, {}
    return fdf.rename(rename_map), inverse


def _adjust(original: Any, result: DataFrame, as_fugue: bool) -> AnyDataFrame:
    if as_fugue or isinstance(original, DataFrame):
        return result
    return get_native_as_df(result)
