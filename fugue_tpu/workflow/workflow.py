"""FugueWorkflow — the lazy workflow DAG and its dataframe handles.

Parity with the reference (`fugue/workflow/workflow.py:88,1499`): every
operation *describes* a task; ``run(engine)`` executes the graph on any
engine. ``WorkflowDataFrame`` mirrors the DataFrame API lazily and adds
partitioning hints, checkpoints, yields, persist/broadcast and joins.
"""

from contextlib import nullcontext
from typing import Any, Callable, Dict, Iterable, List, Optional, Union

from .._utils.assertion import assert_or_throw
from .._utils.convert import get_caller_global_local_vars
from .._utils.params import IndexedOrderedDict, ParamDict
from ..collections.partition import PartitionSpec
from ..collections.sql import StructuredRawSQL
from ..collections.yielded import PhysicalYielded, Yielded
from ..column import ColumnExpr
from ..column import SelectColumns as ColSelectColumns
from ..constants import (
    FUGUE_CONF_WORKFLOW_AUTO_PERSIST,
    FUGUE_CONF_WORKFLOW_AUTO_PERSIST_VALUE,
)
from ..dataframe import DataFrame, YieldedDataFrame
from ..exceptions import FugueWorkflowCompileError, FugueWorkflowError
from ..execution.factory import make_execution_engine
from ..extensions._builtins import creators as bc
from ..extensions._builtins import outputters as bo
from ..extensions._builtins import processors as bp
from ..extensions.creator.convert import _to_creator
from ..extensions.outputter.convert import _to_outputter
from ..extensions.outputter.outputter import Outputter as _OutputterBase
from ..extensions.processor.convert import _to_processor
from ..extensions.transformer.convert import _to_output_transformer, _to_transformer
from ._checkpoint import Checkpoint, StrongCheckpoint, WeakCheckpoint
from ._tasks import CreateTask, FugueTask, OutputTask, ProcessTask
from ._workflow_context import FugueWorkflowContext



class WorkflowDataFrames(IndexedOrderedDict):
    """Ordered dictionary of :class:`WorkflowDataFrame` (reference
    ``fugue/workflow/workflow.py:1413``): the lazy-handle counterpart of
    :class:`~fugue_tpu.dataframe.DataFrames` — keyed or positional
    (``_<n>`` keys), immutable once built, and every member must belong
    to the SAME workflow."""

    def __init__(self, *args: Any, **kwargs: Any):
        super().__init__()
        self._has_dict_key = False
        for a in args:
            self._append(a)
        for k, v in kwargs.items():
            self[k] = v
        self.set_readonly()

    @property
    def has_key(self) -> bool:
        return self._has_dict_key

    @property
    def workflow(self) -> "FugueWorkflow":
        assert_or_throw(
            len(self) > 0, FugueWorkflowCompileError("empty WorkflowDataFrames")
        )
        return next(iter(self.values())).workflow

    def _append(self, obj: Any) -> None:
        if obj is None:
            return
        if isinstance(obj, WorkflowDataFrame):
            self[f"_{len(self)}"] = obj
        elif isinstance(obj, (WorkflowDataFrames, dict)):
            for k, v in obj.items():
                if isinstance(k, str) and k.startswith("_"):
                    # positional members RE-KEY on merge, or the second
                    # container's "_0" would silently overwrite the first's
                    self._append(v)
                else:
                    self[k] = v
        elif isinstance(obj, (list, tuple)):
            for x in obj:
                self._append(x)
        else:
            raise FugueWorkflowCompileError(
                f"can't add {type(obj)} to WorkflowDataFrames"
            )

    def __getitem__(self, key: Any) -> "WorkflowDataFrame":  # type: ignore
        if isinstance(key, int):
            return self.get_value_by_index(key)
        return super().__getitem__(key)

    def __setitem__(self, key: Any, value: Any) -> None:
        assert_or_throw(
            isinstance(key, str),
            FugueWorkflowCompileError(f"key {key!r} must be a string"),
        )
        assert_or_throw(
            isinstance(value, WorkflowDataFrame),
            FugueWorkflowCompileError(f"{key} value must be a WorkflowDataFrame"),
        )
        if len(self) > 0 and value.workflow is not next(iter(self.values())).workflow:
            raise FugueWorkflowCompileError(
                "all members must come from the same workflow"
            )
        super().__setitem__(key, value)  # readonly check runs FIRST
        if not key.startswith("_"):
            self._has_dict_key = True


class FugueWorkflowResult:
    """The outcome of ``FugueWorkflow.run`` — holds the yields."""

    def __init__(self, yields: Dict[str, Yielded]):
        self._yields = dict(yields)

    @property
    def yields(self) -> Dict[str, Any]:
        return self._yields

    def __getitem__(self, name: str) -> Any:
        return self._yields[name]


class WorkflowDataFrame:
    """Lazy handle to a dataframe inside the DAG (reference ``workflow.py:88``)."""

    def __init__(
        self,
        workflow: "FugueWorkflow",
        task: FugueTask,
        pre_partition: Optional[PartitionSpec] = None,
    ):
        self._workflow = workflow
        self._task = task
        self._pre_partition = pre_partition

    @property
    def workflow(self) -> "FugueWorkflow":
        return self._workflow

    @property
    def partition_spec(self) -> PartitionSpec:
        return self._pre_partition or PartitionSpec()

    def spec_uuid(self) -> str:
        return self._task.__uuid__()

    @property
    def name(self) -> str:
        return self._task.name

    # -- partition hints ----------------------------------------------------
    def partition(self, *args: Any, **kwargs: Any) -> "WorkflowDataFrame":
        return WorkflowDataFrame(self._workflow, self._task, PartitionSpec(*args, **kwargs))

    def partition_by(self, *keys: str, **kwargs: Any) -> "WorkflowDataFrame":
        return self.partition(by=list(keys), **kwargs)

    def per_partition_by(self, *keys: str) -> "WorkflowDataFrame":
        return self.partition(by=list(keys), algo="even")

    def per_row(self) -> "WorkflowDataFrame":
        return self.partition("per_row")

    # -- transforms ---------------------------------------------------------
    def transform(
        self,
        using: Any,
        schema: Any = None,
        params: Any = None,
        pre_partition: Any = None,
        ignore_errors: Optional[List[Any]] = None,
        callback: Any = None,
    ) -> "WorkflowDataFrame":
        _g, _l = get_caller_global_local_vars()
        return self._workflow.transform(
            self,
            using=using,
            schema=schema,
            params=params,
            pre_partition=pre_partition or self._pre_partition,
            ignore_errors=ignore_errors or [],
            callback=callback,
            global_vars=_g,
            local_vars=_l,
        )

    def out_transform(
        self,
        using: Any,
        params: Any = None,
        pre_partition: Any = None,
        ignore_errors: Optional[List[Any]] = None,
        callback: Any = None,
    ) -> None:
        _g, _l = get_caller_global_local_vars()
        self._workflow.out_transform(
            self,
            using=using,
            params=params,
            pre_partition=pre_partition or self._pre_partition,
            ignore_errors=ignore_errors or [],
            callback=callback,
            global_vars=_g,
            local_vars=_l,
        )

    def process(
        self,
        using: Any,
        schema: Any = None,
        params: Any = None,
        pre_partition: Any = None,
    ) -> "WorkflowDataFrame":
        _g, _l = get_caller_global_local_vars()
        return self._workflow.process(
            self,
            using=using,
            schema=schema,
            params=params,
            pre_partition=pre_partition or self._pre_partition,
            global_vars=_g,
            local_vars=_l,
        )

    def output(self, using: Any, params: Any = None, pre_partition: Any = None) -> None:
        _g, _l = get_caller_global_local_vars()
        self._workflow.output(
            self,
            using=using,
            params=params,
            pre_partition=pre_partition or self._pre_partition,
            global_vars=_g,
            local_vars=_l,
        )

    # -- column/relational ops ---------------------------------------------
    def _simple_process(self, processor: Any, params: Any = None, pre_partition: Any = None) -> "WorkflowDataFrame":
        return self._workflow.add_process_task(
            processor, [self], params=params, pre_partition=pre_partition
        )

    def rename(self, *args: Any, **kwargs: Any) -> "WorkflowDataFrame":
        columns: Dict[str, str] = {}
        for a in args:
            columns.update(a)
        columns.update(kwargs)
        return self._simple_process(bp.Rename(), params=dict(columns=columns))

    def alter_columns(self, columns: Any) -> "WorkflowDataFrame":
        return self._simple_process(bp.AlterColumns(), params=dict(columns=str(columns)))

    def drop(self, columns: List[str], if_exists: bool = False) -> "WorkflowDataFrame":
        return self._simple_process(
            bp.DropColumns(), params=dict(columns=columns, if_exists=if_exists)
        )

    def __getitem__(self, columns: List[Any]) -> "WorkflowDataFrame":
        return self._simple_process(bp.SelectColumns(), params=dict(columns=columns))

    def distinct(self) -> "WorkflowDataFrame":
        return self._simple_process(bp.Distinct())

    def dropna(
        self, how: str = "any", thresh: Optional[int] = None, subset: Optional[List[str]] = None
    ) -> "WorkflowDataFrame":
        params: Dict[str, Any] = dict(how=how)
        if thresh is not None:
            params["thresh"] = thresh
        if subset is not None:
            params["subset"] = subset
        return self._simple_process(bp.Dropna(), params=params)

    def fillna(self, value: Any, subset: Optional[List[str]] = None) -> "WorkflowDataFrame":
        params: Dict[str, Any] = dict(value=value)
        if subset is not None:
            params["subset"] = subset
        return self._simple_process(bp.Fillna(), params=params)

    def sample(
        self,
        n: Optional[int] = None,
        frac: Optional[float] = None,
        replace: bool = False,
        seed: Optional[int] = None,
    ) -> "WorkflowDataFrame":
        params: Dict[str, Any] = dict(replace=replace)
        if n is not None:
            params["n"] = n
        if frac is not None:
            params["frac"] = frac
        if seed is not None:
            params["seed"] = seed
        return self._simple_process(bp.Sample(), params=params)

    def take(self, n: int, presort: str = "", na_position: str = "last") -> "WorkflowDataFrame":
        return self._workflow.add_process_task(
            bp.Take(),
            [self],
            params=dict(n=n, presort=presort, na_position=na_position),
            pre_partition=self._pre_partition,
        )

    def select(self, *columns: Any, where: Any = None, having: Any = None, distinct: bool = False) -> "WorkflowDataFrame":
        from ..column import col as _col

        cols = ColSelectColumns(
            *[(_col(c) if isinstance(c, str) else c) for c in columns],
            arg_distinct=distinct,
        )
        params: Dict[str, Any] = dict(columns=cols)
        if where is not None:
            params["where"] = where
        if having is not None:
            params["having"] = having
        return self._simple_process(bp.Select(), params=params)

    def filter(self, condition: ColumnExpr) -> "WorkflowDataFrame":
        return self._simple_process(bp.Filter(), params=dict(condition=condition))

    def assign(self, *args: ColumnExpr, **kwargs: Any) -> "WorkflowDataFrame":
        from ..column import lit

        cols = list(args) + [
            (v if isinstance(v, ColumnExpr) else lit(v)).alias(k)
            for k, v in kwargs.items()
        ]
        return self._simple_process(bp.Assign(), params=dict(columns=cols))

    def aggregate(self, *agg_cols: ColumnExpr, **kw_agg_cols: ColumnExpr) -> "WorkflowDataFrame":
        cols = list(agg_cols) + [v.alias(k) for k, v in kw_agg_cols.items()]
        return self._workflow.add_process_task(
            bp.Aggregate(),
            [self],
            params=dict(columns=cols),
            pre_partition=self._pre_partition,
        )

    # -- joins & set ops ----------------------------------------------------
    def join(self, *dfs: "WorkflowDataFrame", how: str, on: Optional[List[str]] = None) -> "WorkflowDataFrame":
        return self._workflow.join(self, *dfs, how=how, on=on)

    def inner_join(self, *dfs: "WorkflowDataFrame", on: Optional[List[str]] = None) -> "WorkflowDataFrame":
        return self.join(*dfs, how="inner", on=on)

    def semi_join(self, *dfs: "WorkflowDataFrame", on: Optional[List[str]] = None) -> "WorkflowDataFrame":
        return self.join(*dfs, how="semi", on=on)

    def left_semi_join(self, *dfs: "WorkflowDataFrame", on: Optional[List[str]] = None) -> "WorkflowDataFrame":
        return self.join(*dfs, how="left_semi", on=on)

    def anti_join(self, *dfs: "WorkflowDataFrame", on: Optional[List[str]] = None) -> "WorkflowDataFrame":
        return self.join(*dfs, how="anti", on=on)

    def left_anti_join(self, *dfs: "WorkflowDataFrame", on: Optional[List[str]] = None) -> "WorkflowDataFrame":
        return self.join(*dfs, how="left_anti", on=on)

    def left_outer_join(self, *dfs: "WorkflowDataFrame", on: Optional[List[str]] = None) -> "WorkflowDataFrame":
        return self.join(*dfs, how="left_outer", on=on)

    def right_outer_join(self, *dfs: "WorkflowDataFrame", on: Optional[List[str]] = None) -> "WorkflowDataFrame":
        return self.join(*dfs, how="right_outer", on=on)

    def full_outer_join(self, *dfs: "WorkflowDataFrame", on: Optional[List[str]] = None) -> "WorkflowDataFrame":
        return self.join(*dfs, how="full_outer", on=on)

    def cross_join(self, *dfs: "WorkflowDataFrame") -> "WorkflowDataFrame":
        return self.join(*dfs, how="cross")

    def union(self, *dfs: "WorkflowDataFrame", distinct: bool = True) -> "WorkflowDataFrame":
        return self._workflow.set_op("union", self, *dfs, distinct=distinct)

    def subtract(self, *dfs: "WorkflowDataFrame", distinct: bool = True) -> "WorkflowDataFrame":
        return self._workflow.set_op("subtract", self, *dfs, distinct=distinct)

    def intersect(self, *dfs: "WorkflowDataFrame", distinct: bool = True) -> "WorkflowDataFrame":
        return self._workflow.set_op("intersect", self, *dfs, distinct=distinct)

    # -- zip ----------------------------------------------------------------
    def zip(
        self,
        *dfs: "WorkflowDataFrame",
        how: str = "inner",
        partition: Any = None,
        temp_path: Optional[str] = None,
        to_file_threshold: int = -1,
    ) -> "WorkflowDataFrame":
        return self._workflow.zip(
            self,
            *dfs,
            how=how,
            partition=partition or self._pre_partition,
            temp_path=temp_path,
            to_file_threshold=to_file_threshold,
        )

    # -- checkpoints, persist, broadcast, yields ----------------------------
    def checkpoint(self, storage_type: str = "file") -> "WorkflowDataFrame":
        self._task.set_checkpoint(StrongCheckpoint(storage_type=storage_type))
        return self

    def weak_checkpoint(self, lazy: bool = False, **kwargs: Any) -> "WorkflowDataFrame":
        self._task.set_checkpoint(WeakCheckpoint(lazy=lazy, **kwargs))
        return self

    def strong_checkpoint(
        self,
        storage_type: str = "file",
        lazy: bool = False,
        partition: Any = None,
        single: bool = False,
        **kwargs: Any,
    ) -> "WorkflowDataFrame":
        self._task.set_checkpoint(
            StrongCheckpoint(
                storage_type=storage_type,
                deterministic=False,
                lazy=lazy,
                partition=partition,
                single=single,
                **kwargs,
            )
        )
        return self

    def deterministic_checkpoint(
        self,
        storage_type: str = "file",
        lazy: bool = False,
        partition: Any = None,
        single: bool = False,
        namespace: Any = None,
        **kwargs: Any,
    ) -> "WorkflowDataFrame":
        self._task.set_checkpoint(
            StrongCheckpoint(
                storage_type=storage_type,
                deterministic=True,
                lazy=lazy,
                partition=partition,
                single=single,
                namespace=namespace,
                **kwargs,
            )
        )
        return self

    def persist(self) -> "WorkflowDataFrame":
        return self.weak_checkpoint(lazy=False)

    def broadcast(self) -> "WorkflowDataFrame":
        self._task.broadcast_flag = True
        return self

    def yield_file_as(self, name: str) -> None:
        cp = StrongCheckpoint(storage_type="file", deterministic=True, permanent=True)
        cp.yielded = PhysicalYielded(self._task.__uuid__(), "file")
        self._task.set_checkpoint(cp)
        self._workflow._register_yield(name, cp.yielded)

    def yield_table_as(self, name: str) -> None:
        cp = StrongCheckpoint(storage_type="table", deterministic=True, permanent=True)
        cp.yielded = PhysicalYielded(self._task.__uuid__(), "table")
        self._task.set_checkpoint(cp)
        self._workflow._register_yield(name, cp.yielded)

    def yield_dataframe_as(self, name: str, as_local: bool = False) -> None:
        yielded = YieldedDataFrame(self._task.__uuid__())
        self._workflow._register_yield(name, yielded)
        # weakref: a strong workflow ref here would close the cycle
        # workflow → tasks → handler → workflow, deferring the release of
        # every result frame (device memory!) to cyclic GC instead of
        # refcounting. The handler only fires during run(), when the
        # workflow is necessarily alive.
        import weakref

        wf_ref = weakref.ref(self._workflow)

        def handler(df: DataFrame) -> None:
            wf = wf_ref()
            e = wf._last_engine if wf is not None else None
            out = e.convert_yield_dataframe(df, as_local) if e is not None else df
            yielded.set_value(out)

        self._task.set_yield_dataframe_handler(handler)

    # -- io & sinks ----------------------------------------------------------
    def save(
        self,
        path: str,
        fmt: str = "",
        mode: str = "overwrite",
        partition: Any = None,
        single: bool = False,
        **kwargs: Any,
    ) -> None:
        self._workflow.add_output_task(
            bo.Save(),
            [self],
            params=dict(path=path, fmt=fmt, mode=mode, single=single, params=kwargs),
            pre_partition=partition or self._pre_partition,
        )

    def save_and_use(
        self,
        path: str,
        fmt: str = "",
        mode: str = "overwrite",
        partition: Any = None,
        single: bool = False,
        **kwargs: Any,
    ) -> "WorkflowDataFrame":
        return self._workflow.add_process_task(
            bp.SaveAndUse(),
            [self],
            params=dict(path=path, fmt=fmt, mode=mode, single=single, params=kwargs),
            pre_partition=partition or self._pre_partition,
        )

    def show(
        self,
        n: int = 10,
        with_count: bool = False,
        title: Optional[str] = None,
    ) -> None:
        self._workflow.show(self, n=n, with_count=with_count, title=title)

    def assert_eq(self, *dfs: Any, **params: Any) -> None:
        self._workflow.assert_eq(self, *dfs, **params)

    def assert_not_eq(self, *dfs: Any, **params: Any) -> None:
        self._workflow.assert_not_eq(self, *dfs, **params)

    # -- run-time access -----------------------------------------------------
    @property
    def result(self) -> DataFrame:
        return self._workflow.get_result(self)

    def compute(self, *args: Any, **kwargs: Any) -> DataFrame:
        self._workflow.run(*args, **kwargs)
        return self.result


class FugueWorkflow:
    """The lazy DAG builder (reference ``workflow.py:1499``)."""

    def __init__(self, compile_conf: Any = None):
        self._tasks: List[FugueTask] = []
        self._conf = ParamDict(compile_conf)
        self._yields: Dict[str, Yielded] = {}
        self._last_context: Optional[FugueWorkflowContext] = None
        self._last_engine = None
        self._graph_uuid: Optional[str] = None

    @property
    def conf(self) -> ParamDict:
        return self._conf

    @property
    def yields(self) -> Dict[str, Yielded]:
        return self._yields

    def __enter__(self) -> "FugueWorkflow":
        return self

    def __exit__(self, exc_type: Any, exc_val: Any, exc_tb: Any) -> None:
        pass

    def _register_yield(self, name: str, yielded: Yielded) -> None:
        assert_or_throw(
            name not in self._yields,
            FugueWorkflowCompileError(f"yield name {name} already exists"),
        )
        self._yields[name] = yielded

    # -- task plumbing -------------------------------------------------------
    def _add(self, task: FugueTask) -> WorkflowDataFrame:
        self._tasks.append(task)
        self._graph_uuid = None
        return WorkflowDataFrame(self, task)

    def add_process_task(
        self,
        processor: Any,
        inputs: List[WorkflowDataFrame],
        params: Any = None,
        pre_partition: Any = None,
        input_names: Optional[List[str]] = None,
    ) -> WorkflowDataFrame:
        task = ProcessTask(
            processor,
            [d._task for d in inputs],
            params=params,
            partition_spec=None if pre_partition is None else PartitionSpec(pre_partition),
            input_names=input_names,
        )
        return self._add(task)

    def add_output_task(
        self,
        outputter: Any,
        inputs: List[WorkflowDataFrame],
        params: Any = None,
        pre_partition: Any = None,
        input_names: Optional[List[str]] = None,
    ) -> None:
        task = OutputTask(
            outputter,
            [d._task for d in inputs],
            params=params,
            partition_spec=None if pre_partition is None else PartitionSpec(pre_partition),
            input_names=input_names,
        )
        self._add(task)

    # -- creation ------------------------------------------------------------
    def create(
        self, using: Any, schema: Any = None, params: Any = None
    ) -> WorkflowDataFrame:
        import pandas as _pd
        import pyarrow as _pa

        if isinstance(
            using, (DataFrame, WorkflowDataFrame, _pd.DataFrame, _pa.Table)
        ):
            # a dataframe: identical task spec to ``df()`` so the two
            # spellings share one deterministic uuid (reference
            # test_create_df_equivalence — checkpoint identity depends on
            # it). Anything else — Creator instances/classes, callables,
            # registered names — goes through the creator conversion
            assert_or_throw(
                params is None,
                FugueWorkflowCompileError("params must be None for dataframes"),
            )
            return self.create_data(using, schema)
        _g, _l = get_caller_global_local_vars()
        creator = _to_creator(using, schema, global_vars=_g, local_vars=_l)
        return self._add(CreateTask(creator, params=ParamDict(params)))

    def df(self, data: Any, schema: Any = None) -> WorkflowDataFrame:
        return self.create_data(data, schema)

    def create_data(self, data: Any, schema: Any = None) -> WorkflowDataFrame:
        if isinstance(data, WorkflowDataFrame):
            assert_or_throw(
                data.workflow is self,
                FugueWorkflowCompileError("dataframe belongs to another workflow"),
            )
            assert_or_throw(
                schema is None,
                FugueWorkflowCompileError("schema must be None for WorkflowDataFrame"),
            )
            return data
        task = CreateTask(
            bc.CreateData(),
            params=dict(data=data, schema=None if schema is None else str(schema)),
        )
        return self._add(task)

    def load(
        self, path: str, fmt: str = "", columns: Any = None, **kwargs: Any
    ) -> WorkflowDataFrame:
        return self._add(
            CreateTask(
                bc.Load(),
                params=dict(path=path, fmt=fmt, columns=columns, params=kwargs),
            )
        )

    # -- generic extensions ---------------------------------------------------
    def process(
        self,
        *dfs: Any,
        using: Any,
        schema: Any = None,
        params: Any = None,
        pre_partition: Any = None,
        global_vars: Any = None,
        local_vars: Any = None,
    ) -> WorkflowDataFrame:
        global_vars, local_vars = get_caller_global_local_vars(global_vars, local_vars)
        processor = _to_processor(using, schema, global_vars=global_vars, local_vars=local_vars)
        inputs, names = self._to_dfs(dfs)
        return self.add_process_task(
            processor,
            inputs,
            params=ParamDict(params),
            pre_partition=pre_partition,
            input_names=names,
        )

    def output(
        self,
        *dfs: Any,
        using: Any,
        params: Any = None,
        pre_partition: Any = None,
        global_vars: Any = None,
        local_vars: Any = None,
    ) -> None:
        global_vars, local_vars = get_caller_global_local_vars(global_vars, local_vars)
        outputter = _to_outputter(using, global_vars=global_vars, local_vars=local_vars)
        inputs, names = self._to_dfs(dfs)
        self.add_output_task(
            outputter,
            inputs,
            params=ParamDict(params),
            pre_partition=pre_partition,
            input_names=names,
        )

    def transform(
        self,
        *dfs: Any,
        using: Any,
        schema: Any = None,
        params: Any = None,
        pre_partition: Any = None,
        ignore_errors: Optional[List[Any]] = None,
        callback: Any = None,
        global_vars: Any = None,
        local_vars: Any = None,
    ) -> WorkflowDataFrame:
        global_vars, local_vars = get_caller_global_local_vars(global_vars, local_vars)
        inputs, _ = self._to_dfs(dfs)
        assert_or_throw(
            len(inputs) == 1,
            NotImplementedError("transform supports only one dataframe; use zip+transform for multiple"),
        )
        tf = _to_transformer(using, schema, global_vars=global_vars, local_vars=local_vars)
        from ..extensions._utils import validate_partition_spec

        validate_partition_spec(
            PartitionSpec(pre_partition) if pre_partition is not None else PartitionSpec(),
            tf.validation_rules,
        )
        return self.add_process_task(
            bp.RunTransformer(),
            inputs,
            params=dict(
                transformer=tf,
                ignore_errors=ignore_errors or [],
                params=ParamDict(params),
                callback=callback,
            ),
            pre_partition=pre_partition,
        )

    def out_transform(
        self,
        *dfs: Any,
        using: Any,
        params: Any = None,
        pre_partition: Any = None,
        ignore_errors: Optional[List[Any]] = None,
        callback: Any = None,
        global_vars: Any = None,
        local_vars: Any = None,
    ) -> None:
        global_vars, local_vars = get_caller_global_local_vars(global_vars, local_vars)
        inputs, _ = self._to_dfs(dfs)
        assert_or_throw(
            len(inputs) == 1,
            NotImplementedError("out_transform supports only one dataframe"),
        )
        tf = _to_output_transformer(using, global_vars=global_vars, local_vars=local_vars)
        res = self.add_process_task(
            bp.RunTransformer(),
            inputs,
            params=dict(
                transformer=tf,
                ignore_errors=ignore_errors or [],
                params=ParamDict(params),
                callback=callback,
            ),
            pre_partition=pre_partition,
        )
        # force materialization: consume as a sink
        self.add_output_task(_NoOpOutputter(), [res])

    # -- joins/set ops/zip -----------------------------------------------------
    def join(
        self, *dfs: Any, how: str, on: Optional[List[str]] = None
    ) -> WorkflowDataFrame:
        inputs, _ = self._to_dfs(dfs)
        return self.add_process_task(
            bp.RunJoin(), inputs, params=dict(how=how, on=on or [])
        )

    def set_op(self, how: str, *dfs: Any, distinct: bool = True) -> WorkflowDataFrame:
        inputs, _ = self._to_dfs(dfs)
        return self.add_process_task(
            bp.RunSetOperation(), inputs, params=dict(how=how, distinct=distinct)
        )

    def union(self, *dfs: Any, distinct: bool = True) -> WorkflowDataFrame:
        return self.set_op("union", *dfs, distinct=distinct)

    def subtract(self, *dfs: Any, distinct: bool = True) -> WorkflowDataFrame:
        return self.set_op("subtract", *dfs, distinct=distinct)

    def intersect(self, *dfs: Any, distinct: bool = True) -> WorkflowDataFrame:
        return self.set_op("intersect", *dfs, distinct=distinct)

    def zip(
        self,
        *dfs: Any,
        how: str = "inner",
        partition: Any = None,
        temp_path: Optional[str] = None,
        to_file_threshold: int = -1,
    ) -> WorkflowDataFrame:
        inputs, names = self._to_dfs(dfs)
        return self.add_process_task(
            bp.Zip(),
            inputs,
            params=dict(how=how, temp_path=temp_path, to_file_threshold=to_file_threshold),
            pre_partition=partition,
            input_names=names,
        )

    def select(
        self,
        *statements: Any,
        sql_engine: Any = None,
        sql_engine_params: Any = None,
        dialect: str = "spark",
    ) -> WorkflowDataFrame:
        """Raw SQL select over workflow frames; pieces may be strings or
        WorkflowDataFrames (reference ``workflow.py`` raw-sql path).
        ``sql_engine`` runs this one select on a specific SQL engine (name,
        class, or an execution-engine name whose SQL facet is used)."""
        parts: List[Any] = []
        inputs: List[WorkflowDataFrame] = []
        names: List[str] = []
        seen: Dict[int, str] = {}
        for s in statements:
            if isinstance(s, str):
                parts.append((False, s))
            elif isinstance(s, WorkflowDataFrame):
                # the SAME frame referenced multiple times (e.g. a
                # correlated subquery's qualifier) must keep ONE table
                # name, or correlation analysis sees unrelated aliases
                if id(s) in seen:
                    parts.append((True, seen[id(s)]))
                    continue
                name = f"_{len(inputs)}"
                seen[id(s)] = name
                parts.append((True, name))
                inputs.append(s)
                names.append(name)
            else:
                raise FugueWorkflowCompileError(f"invalid select statement piece {s}")
        statement = StructuredRawSQL(parts, dialect=dialect)
        params: Dict[str, Any] = dict(statement=statement)
        if sql_engine is not None:
            params["sql_engine"] = sql_engine
            params["sql_engine_params"] = dict(sql_engine_params or {})
        return self.add_process_task(
            bp.RunSQLSelect(),
            inputs,
            params=params,
            input_names=names if len(names) > 0 else None,
        )

    # -- sinks -----------------------------------------------------------------
    def show(
        self,
        *dfs: Any,
        n: int = 10,
        with_count: bool = False,
        title: Optional[str] = None,
    ) -> None:
        inputs, _ = self._to_dfs(dfs)
        self.add_output_task(
            bo.Show(), inputs, params=dict(n=n, with_count=with_count, title=title)
        )

    def assert_eq(self, *dfs: Any, **params: Any) -> None:
        inputs, _ = self._to_dfs(dfs)
        self.add_output_task(bo.AssertEqual(), inputs, params=params)

    def assert_not_eq(self, *dfs: Any, **params: Any) -> None:
        inputs, _ = self._to_dfs(dfs)
        self.add_output_task(bo.AssertNotEqual(), inputs, params=params)

    # -- run -------------------------------------------------------------------
    def run(self, engine: Any = None, conf: Any = None, **kwargs: Any) -> FugueWorkflowResult:
        infer_by = kwargs.pop("infer_by", None) or self._collect_raw_inputs()
        e = make_execution_engine(engine, conf, infer_by=infer_by, **kwargs)
        # the optimizer gate sees engine conf overlaid with this
        # workflow's compile conf (same precedence explain() uses). The
        # workflow conf is RUN-SCOPED: instead of being written into a
        # possibly shared engine's conf — where it leaked into later runs
        # of OTHER workflows on the same engine — the execution below
        # enters e.run_conf_scope(self._conf), a context-local overlay
        # every engine.conf read inside this run (and the threads/workers
        # it forks) resolves through. Per-tenant serve overlays depend on
        # this: any fugue.tpu.* key is now safely per-run.
        plan_conf = ParamDict(e.conf)
        for k, v in self._conf.items():
            plan_conf[k] = v
        self._last_engine = e
        ctx = FugueWorkflowContext(e, conf=plan_conf)
        self._last_context = ctx
        self._apply_auto_persist(e, plan_conf)
        from ..obs import get_tracer
        from ..plan import optimize_tasks

        tracer = get_tracer()
        with tracer.span("plan.optimize", cat="plan", tasks=len(self._tasks)) as psp:
            run_tasks, aliases, removed, report = optimize_tasks(
                self._tasks,
                plan_conf,
                stats=e.plan_stats,
                analysis_stats=e.analysis_stats,
            )
            psp.set(**report.span_attrs())
        self._last_plan_report = report
        # run attribution (ISSUE 6): while tracing is on, every span-metric
        # sample this run produces carries workflow/run labels — the
        # per-tenant attribution scheme the serving layer will reuse. The
        # workflow label is a stable hash of the task uuids (same dag =>
        # same label across runs) unless conf names one explicitly.
        run_attrs: Dict[str, Any] = {}
        run_ctx: Any = nullcontext()
        trace_ctx: Any = nullcontext()
        if tracer.enabled:
            import hashlib
            import uuid as _uuid

            from ..constants import FUGUE_TPU_CONF_TELEMETRY_WORKFLOW
            from ..obs import run_labels as _run_labels
            from ..obs import trace_scope as _trace_scope

            wf_label = str(
                plan_conf.get(FUGUE_TPU_CONF_TELEMETRY_WORKFLOW, "")
            ) or "wf-" + hashlib.sha1(
                "|".join(t.__uuid__() for t in self._tasks).encode()
            ).hexdigest()[:8]
            run_attrs = {"workflow": wf_label, "run": _uuid.uuid4().hex[:8]}
            run_ctx = _run_labels(**run_attrs)
            # cluster trace context (ISSUE 18): mint ONE trace id for this
            # run — every hop below (fork workers, board tasks, HTTP, fleet
            # claims) carries it, so remote spans attach under this run.
            # Inside an already-traced scope (a serve replica running a
            # submitted dag) ADOPT that trace instead of minting: the
            # whole execution stays one trace end to end.
            from ..obs import current_trace_id as _current_trace_id

            self._last_trace_id = _current_trace_id() or _uuid.uuid4().hex[:16]
            run_attrs["trace"] = self._last_trace_id
            trace_ctx = _trace_scope(self._last_trace_id)
        # adaptive execution (docs/tuning.md): key this run's telemetry by
        # the POST-optimization plan fingerprint so the tuner's learned
        # settings apply to — and learn from — exactly this plan; the
        # scope respects a per-workflow/per-tenant tuning kill-switch via
        # plan_conf without touching the shared engine
        from ..tuning import plan_fingerprint as _plan_fp, run_scope as _tuning_scope

        self._last_plan_fingerprint = _plan_fp(run_tasks)
        try:
            with e.run_conf_scope(self._conf), e._as_borrowed_context():
                with trace_ctx, run_ctx, tracer.span(
                    "workflow.run", cat="workflow", tasks=len(run_tasks), **run_attrs
                ), _tuning_scope(e, self._last_plan_fingerprint, plan_conf):
                    ctx.run(
                        run_tasks,
                        result_aliases=aliases,
                        removed_results=removed,
                    )
        except Exception as ex:
            from .._utils.exception import modify_traceback

            # plan_conf, not e.conf: the run scope has already exited
            # here, and the exception conf keys may be workflow-scoped
            raise modify_traceback(ex, plan_conf)
        finally:
            self._maybe_export_trace(e, tracer, plan_conf)
        return FugueWorkflowResult(self._yields)

    def _maybe_export_trace(
        self, engine: Any, tracer: Any, conf: Any = None
    ) -> None:
        """Auto-export a Chrome trace after the run when the (run-scoped)
        conf sets ``fugue.tpu.trace.dir`` (one file per run, Perfetto)."""
        from ..constants import FUGUE_TPU_CONF_TRACE_DIR

        if not tracer.enabled:
            return
        trace_dir = (conf if conf is not None else engine.conf).get(
            FUGUE_TPU_CONF_TRACE_DIR, ""
        )
        if trace_dir == "":
            return
        import os
        import uuid as _uuid

        from ..obs import write_chrome_trace

        try:
            path = os.path.join(
                trace_dir, f"fugue_trace_{_uuid.uuid4().hex[:8]}.json"
            )
            write_chrome_trace(path, tracer.records())
            engine.log.info("workflow trace exported to %s", path)
        except Exception as ex:  # export must never fail the run
            engine.log.warning("trace export failed: %s", ex)

    def timeline(
        self, events_dir: Optional[str] = None, conf: Any = None
    ) -> str:
        """Human-readable post-mortem of the cluster recovery events the
        last :meth:`run` produced (ISSUE 18 flight recorder): lease
        steals, heartbeat expiries, re-dispatches, orphan invalidations,
        speculative twins — merged from every process's event file and
        filtered to this run's trace id. ``events_dir`` defaults to the
        run conf's ``fugue.tpu.events.dir`` (env
        ``FUGUE_TPU_EVENTS_DIR`` overrides)."""
        import os as _os

        from ..constants import FUGUE_TPU_CONF_EVENTS_DIR
        from ..obs import read_events, render_timeline

        if events_dir is None:
            events_dir = _os.environ.get("FUGUE_TPU_EVENTS_DIR", "")
            if not events_dir:
                merged = self._merged_plan_conf(conf, engine=self._last_engine)
                events_dir = str(merged.get(FUGUE_TPU_CONF_EVENTS_DIR, ""))
        if not events_dir:
            return "(no events dir configured — set fugue.tpu.events.dir)"
        return render_timeline(
            read_events(events_dir),
            trace=getattr(self, "_last_trace_id", None),
        )

    def _merged_plan_conf(self, conf: Any = None, engine: Any = None) -> ParamDict:
        from ..constants import _FUGUE_GLOBAL_CONF

        merged = ParamDict(_FUGUE_GLOBAL_CONF)
        if engine is not None:
            merged.update(ParamDict(engine.conf))
        merged.update(self._conf)
        if conf is not None:
            merged.update(ParamDict(conf))
        return merged

    def explain(
        self, conf: Any = None, engine: Any = None, lint: bool = False
    ) -> str:
        """Render what the plan optimizer (``fugue_tpu/plan``) would do to
        this workflow's DAG — the logical plan, the optimized plan with
        per-pass counters (cols_pruned / filters_pushed / verbs_fused /
        udfs_translated / bytes_skipped estimate), and any refusal notes
        (including every UDF's analyzer verdict) — followed by the
        result cache's would-be cut over the optimized plan: which tasks
        hit, which are uncacheable (and why), and which upstream producers
        a warm run would skip entirely. Dry-run only — nothing executes.
        Pass ``engine`` to consult that engine's live cache tiers (memory
        + disk); without it only a conf-derived disk store is probed.
        ``lint=True`` appends the structured static-check section (see
        :meth:`lint`). After a ``run()``, the report of the plan that
        actually executed is also available via ``last_plan_report``."""
        from ..cache import describe_cache
        from ..plan import optimize_tasks
        from ..plan.ir import build_graph
        from ..plan.optimizer import _render_nodes

        merged = self._merged_plan_conf(conf, engine)
        run_tasks, _, _, report = optimize_tasks(self._tasks, merged)
        if not report.before:
            report.before = _render_nodes(build_graph(self._tasks))
        lines = [report.render()]
        lines.extend(
            describe_cache(
                run_tasks,
                merged,
                cache=None if engine is None else engine.result_cache,
                engine_kind="any" if engine is None else type(engine).__name__,
            )
        )
        # distributed workflows (docs/distributed.md): which fragments
        # would route through the board tier and why the rest refuse
        from ..plan import describe_distribution

        lines.extend(describe_distribution(run_tasks, merged))
        # adaptive tuning (docs/tuning.md): what the tuner would use for
        # this plan right now — every learned knob with its evidence and
        # confidence, or why each stays static
        from ..tuning import describe_tuning, plan_fingerprint

        lines.extend(
            describe_tuning(merged, plan_fingerprint(run_tasks), engine=engine)
        )
        if lint:
            lines.append(self.lint(conf=conf, engine=engine).render())
        return "\n".join(lines)

    def lint(self, conf: Any = None, engine: Any = None) -> Any:
        """No-execution static check pass (docs/analysis.md): runs the
        UDF analyzer plus the plan machinery over this workflow and
        returns a :class:`~fugue_tpu.analysis.LintReport` of structured
        diagnostics — per-UDF verdict and refusal reason, predicted join
        strategies, predicted lowered segments, and every optimizer note.
        Nothing executes and the compiled tasks are never mutated."""
        from ..analysis import lint_tasks

        return lint_tasks(self._tasks, self._merged_plan_conf(conf, engine))

    @property
    def last_plan_report(self) -> Any:
        """The :class:`~fugue_tpu.plan.PlanReport` of the last ``run()``
        (None before the first run)."""
        return getattr(self, "_last_plan_report", None)

    @property
    def last_plan_fingerprint(self) -> Optional[str]:
        """The plan fingerprint of the last ``run()`` — the key the
        adaptive tuner stores learned settings under (None before the
        first run or for unfingerprintable plans)."""
        return getattr(self, "_last_plan_fingerprint", None)

    @property
    def last_cache_plan(self) -> Any:
        """The :class:`~fugue_tpu.cache.CachePlan` of the last ``run()``:
        fingerprints, frontier hits and the skipped-upstream set (None
        before the first run or with the cache disabled)."""
        if self._last_context is None:
            return None
        return getattr(self._last_context, "_cache_plan", None)

    def release_task_results(self) -> None:
        """Drop the per-task result frames held by the last run's context.

        The workflow graph contains inherent reference cycles
        (WorkflowDataFrame ↔ workflow), so a dropped workflow frees its
        (possibly device-resident) intermediates only at the next cyclic
        GC pass — measurably late for multi-GB frames. Single-shot API
        wrappers (transform/raw_sql/fugue_sql) extract their yields and
        then call this so intermediates free by refcount immediately.
        After calling, ``get_result``/``WorkflowDataFrame.result`` raise
        KeyError — yields are unaffected (they hold their own refs)."""
        if self._last_context is not None:
            self._last_context._results.clear()

    def get_result(self, df: WorkflowDataFrame) -> DataFrame:
        assert_or_throw(
            self._last_context is not None,
            FugueWorkflowError("workflow has not been run"),
        )
        return self._last_context.get_result(df._task)  # type: ignore

    def spec_uuid(self) -> str:
        from .._utils.hash import to_uuid

        if self._graph_uuid is None:
            self._graph_uuid = to_uuid([t.__uuid__() for t in self._tasks])
        return self._graph_uuid

    # -- helpers ---------------------------------------------------------------
    def _to_dfs(self, dfs: Any) -> Any:
        inputs: List[WorkflowDataFrame] = []
        names: Optional[List[str]] = None
        flat: List[Any] = []
        for d in dfs:
            if isinstance(d, dict):
                names = names or []
                for k, v in d.items():
                    flat.append((k, v))
            else:
                flat.append((None, d))
        for k, d in flat:
            wdf = d if isinstance(d, WorkflowDataFrame) else self.create_data(d)
            inputs.append(wdf)
            if k is not None:
                assert names is not None
                names.append(k)
        if names is not None and len(names) != len(inputs):
            raise FugueWorkflowCompileError("can't mix named and unnamed inputs")
        return inputs, names

    def _collect_raw_inputs(self) -> List[Any]:
        res = []
        for t in self._tasks:
            if isinstance(t, CreateTask):
                p = t.params.get("params", {})
                if isinstance(p, dict) and "data" in p:
                    res.append(p["data"])
        return res

    def _apply_auto_persist(self, engine: Any, conf: Any = None) -> None:
        # conf is the run-scoped merge (engine conf + workflow conf) —
        # workflow conf is no longer written into the engine, so reading
        # engine.conf here would miss a workflow-level auto_persist
        conf = conf if conf is not None else engine.conf
        if not conf.get(FUGUE_CONF_WORKFLOW_AUTO_PERSIST, False):
            return
        consumers: Dict[int, int] = {}
        for t in self._tasks:
            for d in t.inputs:
                consumers[id(d)] = consumers.get(id(d), 0) + 1
        value = conf.get(FUGUE_CONF_WORKFLOW_AUTO_PERSIST_VALUE, "")
        for t in self._tasks:
            if consumers.get(id(t), 0) > 1 and t.checkpoint.is_null and t.has_output:
                t.set_checkpoint(
                    WeakCheckpoint() if value == "" else WeakCheckpoint(value=value)
                )


class _NoOpOutputter(_OutputterBase):
    def process(self, dfs: Any) -> None:
        for df in dfs.values():
            # touch the frame so lazy engines materialize it
            df.count() if df.is_bounded else df.as_local_bounded()
