"""The in-tree DAG runner (replaces adagio).

Parity with the reference (`fugue/workflow/_workflow_context.py:19-58`): binds
the execution engine + RPC server + checkpoint path, and runs the task graph
with configurable parallelism (``fugue.workflow.concurrency``). Adds
checkpoint-aware pruning: tasks whose deterministic checkpoint already exists
load from storage and their exclusive ancestors are skipped (true resume).
"""

import contextvars
import time
import uuid as _uuid
from concurrent.futures import FIRST_COMPLETED, Future, ThreadPoolExecutor, wait
from typing import Any, Dict, List, Optional, Set

from ..constants import FUGUE_CONF_WORKFLOW_CONCURRENCY
from ..dataframe import DataFrame
from ..exceptions import FugueWorkflowError, FugueWorkflowRuntimeError
from ..execution.execution_engine import ExecutionEngine
from ..resilience import (
    SITE_TASK_EXECUTE,
    FaultInjector,
    RetryPolicy,
    classify_failure,
)
from ._checkpoint import CheckpointPath, StrongCheckpoint
from ._tasks import FugueTask


class FugueWorkflowContext:
    def __init__(self, execution_engine: ExecutionEngine, conf: Any = None):
        # conf is the RUN-scoped merge (engine conf + workflow conf) when
        # workflow.run builds the context; workflow conf no longer writes
        # through to the engine, so reading engine.conf alone would miss
        # workflow-level fault plans / retry policies
        conf = conf if conf is not None else execution_engine.conf
        self._engine = execution_engine
        self._conf = conf
        self._checkpoint_path = CheckpointPath(execution_engine, conf=conf)
        self._results: Dict[str, DataFrame] = {}
        self._aliases: Dict[int, FugueTask] = {}
        self._removed: Set[int] = set()
        self._cache_plan: Any = None
        self._dist_plan: Any = None
        # fault budgets span the whole run (an injected `error@1` fails one
        # task once, not once per retry attempt)
        self._injector = FaultInjector.from_conf(conf)
        # default 1 attempt = fail fast, the reference behavior; retried
        # attempts re-consult StrongCheckpoint.exists so work that already
        # reached storage replays from disk instead of recomputing
        self._task_policy = RetryPolicy.from_conf(
            conf,
            prefix="fugue.tpu.retry.task",
            default_attempts=1,
        )

    @property
    def execution_engine(self) -> ExecutionEngine:
        return self._engine

    @property
    def checkpoint_path(self) -> CheckpointPath:
        return self._checkpoint_path

    def get_result(self, task: FugueTask) -> DataFrame:
        t = self._aliases.get(id(task), task)
        if id(t) not in self._results and id(task) in self._removed:
            raise FugueWorkflowError(
                "this task's intermediate result was optimized away by the "
                "plan optimizer (fused into a neighbor or repositioned by "
                "filter pushdown); pin it with persist()/checkpoint()/"
                "yield_dataframe_as(), or disable the optimizer with "
                "fugue.tpu.plan.optimize=false"
            )
        plan = getattr(self, "_cache_plan", None)
        if (
            id(t) not in self._results
            and plan is not None
            and id(t) in plan.skipped
        ):
            raise FugueWorkflowError(
                "this task was never executed: a downstream result-cache "
                "hit cut the plan above it (fugue_tpu/cache, docs/cache.md);"
                " pin it with persist()/checkpoint()/yield_dataframe_as() to"
                " keep it addressable, or disable the cache with "
                "fugue.tpu.cache.enabled=false"
            )
        dp = getattr(self, "_dist_plan", None)
        if (
            id(t) not in self._results
            and dp is not None
            and id(t) in dp.interior_ids
        ):
            raise FugueWorkflowError(
                "this task executed REMOTELY as a leased board task inside a "
                "distributed workflow fragment (fugue_tpu/plan/distribute.py,"
                " docs/distributed.md); its intermediate frame never "
                "materialized in this process. Pin it with persist()/"
                "checkpoint()/yield_dataframe_as() to keep it local, or set "
                "fugue.tpu.dist.enabled=false"
            )
        return self._results[id(t)]

    def has_result(self, task: FugueTask) -> bool:
        t = self._aliases.get(id(task), task)
        return id(t) in self._results

    def run(
        self,
        tasks: List[FugueTask],
        result_aliases: Optional[Dict[int, FugueTask]] = None,
        removed_results: Optional[Set[int]] = None,
    ) -> None:
        execution_id = str(_uuid.uuid4())
        # plan-optimizer aliasing: the optimizer may execute CLONES of the
        # compiled tasks (pruned creates, rewired filters, fused chains);
        # get_result resolves an original task to its executed stand-in,
        # and raises a descriptive error for results the rewrites removed
        self._aliases: Dict[int, FugueTask] = result_aliases or {}
        self._removed = removed_results or set()
        self._checkpoint_path.init_temp_path(execution_id)
        # result cache (fugue_tpu/cache): fingerprint the post-optimization
        # DAG, cut it at the deepest cached frontier, and eagerly load the
        # frontier frames; tasks upstream of the cut never run. Disabled
        # (fugue.tpu.cache.enabled=false) this whole block is one boolean
        # check and the run path is byte-for-byte the pre-cache one.
        self._cache_plan = None
        cache = self._engine.result_cache
        if cache.enabled:
            from ..cache import plan_cache

            self._cache_plan = plan_cache(
                tasks, self._engine, cache, self._checkpoint_path
            )
        # distributed-workflow pass (fugue_tpu/plan/distribute.py): with
        # fugue.tpu.dist.board set, distributable fragments route through
        # DistSupervisor.run_workflow_job and their interior tasks never
        # run locally. Planner bugs must never fail a run: any planning
        # error degrades to fully-local execution with a warning.
        self._dist_plan = None
        try:
            from ..plan import plan_distribution

            dp = plan_distribution(tasks, self._conf, self._cache_plan)
            if dp.active and dp.fragments:
                self._dist_plan = dp
        except Exception as ex:  # pragma: no cover - defensive degrade
            self._engine.log.warning(
                "distributed-workflow planning failed (%s: %s); "
                "running fully local",
                type(ex).__name__,
                ex,
            )
        # fan-out map: a ONE-PASS (local unbounded) result consumed by more
        # than one downstream task must be materialized once, or the second
        # consumer would silently read an exhausted stream
        self._consumers: Dict[int, int] = {}
        for t in tasks:
            for d in t.inputs:
                self._consumers[id(d)] = self._consumers.get(id(d), 0) + 1
        from ..obs import get_tracer

        # capture the workflow.run span id on THIS thread: with concurrency
        # > 1 tasks run on pool threads whose span stacks are empty, so the
        # task spans parent onto it explicitly instead of detaching
        self._trace_root = get_tracer().current_span_id()
        # the rpc server's start/stop is REF-COUNTED (RPCHandler._running),
        # so N concurrent runs on one engine share one live server and the
        # last finisher tears it down; the engine-level active-run counter
        # is the occupancy gauge the serving layer's /readyz reports
        rpc_server = self._engine.rpc_server
        rpc_server.start()
        self._engine._run_started()
        try:
            self._run_graph(tasks)
        finally:
            self._engine._run_finished()
            rpc_server.stop()
            self._checkpoint_path.remove_temp_path()

    # ------------------------------------------------------------------
    def _run_graph(self, tasks: List[FugueTask]) -> None:
        """Run every task (insertion order is topological by construction);
        a deterministic-checkpoint hit loads from storage instead of
        executing (reference semantics: set_result replaces the computed
        frame with the stored one — here we shortcut the execute too when
        the task's own inputs are checkpoint hits or absent)."""
        concurrency = self._engine.conf.get(FUGUE_CONF_WORKFLOW_CONCURRENCY, 1)
        plan = getattr(self, "_cache_plan", None)
        cut = set(plan.skipped) if plan is not None else set()
        dp = getattr(self, "_dist_plan", None)
        if dp is not None:
            # fragment interiors execute remotely as leased board map/reduce
            # tasks; locally they are part of the cut (their consumers — the
            # fragment result tasks — are intercepted in _run_task_once)
            cut |= dp.interior_ids
        if concurrency <= 1:
            for t in tasks:
                if id(t) not in cut:
                    self._run_task(t)
            return
        remaining = {id(t): t for t in tasks if id(t) not in cut}
        # skipped tasks count as done so their consumers' readiness checks
        # pass (a consumer that needed them would not have been cut)
        done: Set[int] = set(cut)
        running: Dict[Future, int] = {}
        first_error: List[BaseException] = []
        with ThreadPoolExecutor(max_workers=concurrency) as pool:
            while (remaining or running) and not first_error:
                ready = [
                    t
                    for t in list(remaining.values())
                    if all(id(d) in done for d in t.inputs)
                ]
                for t in ready:
                    del remaining[id(t)]
                    # pool threads have no context of their own: submit
                    # through a context copy so task spans (and anything
                    # they fork) keep the run-attribution labels
                    running[
                        pool.submit(
                            contextvars.copy_context().run, self._run_task, t
                        )
                    ] = id(t)
                if not running:
                    if remaining:
                        raise FugueWorkflowRuntimeError("workflow graph has a cycle")
                    break
                finished, _ = wait(list(running.keys()), return_when=FIRST_COMPLETED)
                for f in finished:
                    tid = running.pop(f)
                    exc = f.exception()
                    if exc is not None:
                        first_error.append(exc)
                    else:
                        done.add(tid)
        if first_error:
            raise first_error[0]

    def _run_task(self, task: FugueTask) -> None:
        """One task under the per-task retry policy (``fugue.tpu.retry.task.*``).

        Each attempt starts by re-consulting the task's deterministic
        StrongCheckpoint: work that reached storage on a previous attempt
        (or a previous RUN — checkpoint files are uuid-keyed and permanent)
        replays from disk instead of recomputing. Deterministic (POISON)
        failures are never retried — the same inputs would fail the same
        way."""
        from ..obs import get_tracer

        policy = self._task_policy
        attempts = 0
        with get_tracer().span(
            "workflow.task",
            cat="workflow",
            parent=getattr(self, "_trace_root", None),
            task=task.name or type(task).__name__,
        ) as sp:
            while True:
                try:
                    self._run_task_once(task)
                    sp.set(attempts=attempts + 1)
                    return
                except Exception as ex:
                    cat = classify_failure(ex)
                    attempts += 1
                    if not policy.should_retry(cat, attempts):
                        sp.set(attempts=attempts)
                        if task.defined_at and hasattr(ex, "add_note"):
                            ex.add_note(
                                f"[fugue-tpu] failing task defined at {task.defined_at}"
                            )
                        raise
                    self._engine.resilience_stats.inc("workflow.task_retries")
                    self._engine.log.warning(
                        "task %s failed with %s [%s]; retry %d/%d",
                        task.name or type(task).__name__,
                        type(ex).__name__,
                        cat.value,
                        attempts,
                        policy.max_attempts - 1,
                    )
                    time.sleep(policy.delay(attempts, seed=task.__uuid__()))

    def _run_task_once(self, task: FugueTask) -> None:
        from ..obs import get_tracer

        tid = task.__uuid__()
        plan = getattr(self, "_cache_plan", None)
        cp = task.checkpoint
        if isinstance(cp, StrongCheckpoint):
            cp.set_id(tid)
            if cp.exists(self._checkpoint_path, tid):
                self._engine.resilience_stats.inc("workflow.checkpoint_replays")
                with get_tracer().span(
                    "task.checkpoint_replay", cat="workflow", task_uuid=tid
                ):
                    df = cp.load(self._checkpoint_path)
                    if task.broadcast_flag:
                        df = self._engine.broadcast(df)
                    if task.yield_dataframe_handler is not None:
                        task.yield_dataframe_handler(df)
                    self._results[id(task)] = df
                # one artifact, two indexes: the replayed checkpoint file
                # gets a cache ref so future runs also cut ABOVE this task
                self._maybe_cache_publish(task, df)
                return
        if plan is not None and id(task) in plan.hits:
            # served from the result cache: the frame is already loaded
            # (plan time); checkpoint/broadcast/yield contracts still run
            with get_tracer().span(
                "task.cache_hit",
                cat="cache",
                task_uuid=tid,
                tier=plan.hit_tier.get(id(task), ""),
            ):
                result = task.set_result(self, plan.hits[id(task)])
                self._results[id(task)] = result
            return
        if plan is not None and id(task) in plan.delta_hits:
            # partition-level delta recompute (fugue_tpu/cache/delta.py):
            # cached partitions were eager-loaded at plan time; only the
            # NEW partitions stream through the chain here, then merge
            from ..cache.delta import execute_delta

            hit = plan.delta_hits[id(task)]
            with get_tracer().span(
                "task.delta_recompute",
                cat="cache",
                task_uuid=tid,
                partitions=f"{hit.matched_parts}/{hit.total_parts}",
                bytes_skipped=hit.bytes_matched,
            ):
                df = execute_delta(self, task, hit)
                result = task.set_result(self, df)
                self._results[id(task)] = result
            # publishes the MERGED result under the new full fingerprint
            # (a later exact-match run takes the whole-task fast path) and
            # appends the fresh segment / partial to the manifest
            self._maybe_cache_publish(task, result, delta_hit=hit)
            return
        dp = getattr(self, "_dist_plan", None)
        if dp is not None and id(task) in dp.results:
            # fragment result: the whole covered subgraph (loads, row-local
            # chains, shuffle, terminal, tail) ran as leased board tasks
            # under the dist recovery ladder; only the combined frame lands
            # here. The result still flows through set_result so the
            # checkpoint/broadcast/yield contracts — and the cache publish
            # below — behave exactly as a locally-computed frame would.
            from ..plan import execute_fragment

            frag = dp.results[id(task)]
            with get_tracer().span(
                "dist.workflow_fragment",
                cat="dist",
                task=task.name or type(task.extension).__name__,
                keys=",".join(frag.keys),
                buckets=frag.buckets,
            ):
                pdf = execute_fragment(frag, self._engine, self._conf)
                df = self._engine.to_df(pdf)
                result = task.set_result(self, df)
                self._results[id(task)] = result
            self._maybe_cache_publish(task, result)
            return
        inputs = [self._results[id(d)] for d in task.inputs]
        self._injector.fire(SITE_TASK_EXECUTE)
        result = task.execute(self, inputs)
        if result is not None:
            result = task.set_result(self, result)
            if (
                getattr(self, "_consumers", {}).get(id(task), 0) > 1
                and result.is_local
                and not result.is_bounded
            ):
                # stream results stay lazy for single consumers (the
                # out-of-core contract); a fan-out forces one host-side
                # materialization so every consumer sees all rows
                result = result.as_local_bounded()
            self._results[id(task)] = result
            self._maybe_cache_publish(task, result, inputs=inputs)

    def _maybe_cache_publish(
        self,
        task: FugueTask,
        result: DataFrame,
        inputs: Optional[List[DataFrame]] = None,
        delta_hit: Any = None,
    ) -> None:
        """Publish a finished (bounded) result under its plan fingerprint.
        A permanent StrongCheckpoint file is indexed by reference instead
        of re-written — the cache never holds a second copy of an artifact
        the checkpoint publisher already owns. Delta-eligible tasks
        (``fugue_tpu/cache/delta.py``) additionally maintain their source
        partition manifest so the NEXT grown-source run recomputes only
        its delta."""
        plan = getattr(self, "_cache_plan", None)
        if plan is None:
            return
        fp = plan.fp(task)
        if fp is None:
            return
        if result.is_local and not result.is_bounded:
            return  # publishing would consume a one-pass stream
        from ..obs import get_tracer

        ref = None
        cp = task.checkpoint
        if (
            isinstance(cp, StrongCheckpoint)
            and cp.storage_type == "file"
            and cp.permanent
        ):
            try:
                ref = cp._file_path(self._checkpoint_path)
            except Exception:
                ref = None
        with get_tracer().span(
            "cache.publish",
            cat="cache",
            task=task.name or type(task.extension).__name__,
            fp=fp[:12],
        ) as sp:
            info = self._engine.result_cache.publish(
                fp, result, self._engine, str(result.schema), ref_path=ref
            )
            sp.set(**info)
        from ..cache.delta import publish_manifest_after

        publish_manifest_after(self, task, result, inputs=inputs, hit=delta_hit)
