"""Single-op workflow wrappers: transform / out_transform / raw_sql.

Parity with the reference (`fugue/workflow/api.py:34,187,253`) — the
flagship entrypoints: wrap one operation into a one-task DAG, run it, return
native data.
"""

from typing import Any, Callable, List, Optional

from .._utils.convert import get_caller_global_local_vars
from ..collections.yielded import Yielded
from ..dataframe import DataFrame
from ..dataframe.api import get_native_as_df
from ..exceptions import FugueWorkflowError
from .workflow import FugueWorkflow, FugueWorkflowResult


def transform(
    df: Any,
    using: Any,
    schema: Any = None,
    params: Any = None,
    partition: Any = None,
    callback: Any = None,
    ignore_errors: Optional[List[Any]] = None,
    engine: Any = None,
    engine_conf: Any = None,
    as_fugue: bool = False,
    as_local: bool = False,
) -> Any:
    """Transform a dataframe with any engine (reference ``workflow/api.py:34``)."""
    global_vars, local_vars = get_caller_global_local_vars()
    dag = FugueWorkflow()
    src = dag.create_data(df) if not isinstance(df, str) else dag.load(df)
    tdf = dag.transform(
        src,
        using=using,
        schema=schema,
        params=params,
        pre_partition=partition,
        ignore_errors=ignore_errors or [],
        callback=callback,
        global_vars=global_vars,
        local_vars=local_vars,
    )
    tdf.yield_dataframe_as("result", as_local=as_local)
    dag.run(engine, engine_conf, infer_by=[df])
    result = dag.yields["result"].result  # type: ignore
    dag.release_task_results()  # free intermediates now, not at cyclic GC
    return _adjust_result(result, df, as_fugue)


def _adjust_result(result: DataFrame, original: Any, as_fugue: bool) -> Any:
    """Return the result in the same family as the input (reference
    ``workflow/api.py:182-184``)."""
    import pandas as pd
    import pyarrow as pa

    if as_fugue or isinstance(original, (DataFrame, Yielded)):
        return result
    if isinstance(original, pd.DataFrame):
        return result.as_pandas()
    if isinstance(original, pa.Table):
        return result.as_arrow()
    return get_native_as_df(result)


def out_transform(
    df: Any,
    using: Any,
    params: Any = None,
    partition: Any = None,
    callback: Any = None,
    ignore_errors: Optional[List[Any]] = None,
    engine: Any = None,
    engine_conf: Any = None,
) -> None:
    """Transform with no output (side effects), reference ``:187``."""
    global_vars, local_vars = get_caller_global_local_vars()
    dag = FugueWorkflow()
    src = dag.create_data(df) if not isinstance(df, str) else dag.load(df)
    dag.out_transform(
        src,
        using=using,
        params=params,
        pre_partition=partition,
        ignore_errors=ignore_errors or [],
        callback=callback,
        global_vars=global_vars,
        local_vars=local_vars,
    )
    dag.run(engine, engine_conf, infer_by=[df])
    dag.release_task_results()


def raw_sql(
    *statements: Any,
    engine: Any = None,
    engine_conf: Any = None,
    as_fugue: bool = False,
    as_local: bool = False,
) -> Any:
    """Run a SQL statement mixing strings and dataframes (reference ``:253``)."""
    dag = FugueWorkflow()
    parts: List[Any] = []
    raw_inputs: List[Any] = []
    for s in statements:
        if isinstance(s, str):
            parts.append(s)
        else:
            parts.append(dag.create_data(s))
            raw_inputs.append(s)
    res = dag.select(*parts)
    res.yield_dataframe_as("result", as_local=as_local)
    dag.run(engine, engine_conf, infer_by=raw_inputs)
    result = dag.yields["result"].result  # type: ignore
    dag.release_task_results()  # free intermediates now, not at cyclic GC
    if as_fugue or any(isinstance(s, (DataFrame, Yielded)) for s in raw_inputs):
        return result
    return get_native_as_df(result)
