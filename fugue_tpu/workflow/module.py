"""``@module`` — reusable sub-DAG functions.

Parity with the reference (`fugue/workflow/module.py:20`): a module is a
plain function whose first dataframe/workflow argument binds it into an
existing DAG; calling it composes its tasks into the caller's workflow.
"""

import inspect
from typing import Any, Callable, Optional

from .._utils.assertion import assert_or_throw
from ..exceptions import FugueWorkflowCompileError
from .workflow import FugueWorkflow, WorkflowDataFrame


def module(func: Optional[Callable] = None, as_method: bool = False, name: Optional[str] = None) -> Any:
    """Mark a function as a workflow module.

    The function must take a ``FugueWorkflow`` (or one or more
    ``WorkflowDataFrame``) and may return a ``WorkflowDataFrame``::

        @module
        def create(wf: FugueWorkflow, n: int = 1) -> WorkflowDataFrame:
            return wf.df([[n]], "a:long")

        @module
        def doubled(df: WorkflowDataFrame) -> WorkflowDataFrame:
            return df.transform(double_fn, schema="*")
    """

    def deco(fn: Callable) -> Callable:
        sig = inspect.signature(fn)
        params = list(sig.parameters.values())
        assert_or_throw(
            len(params) > 0,
            FugueWorkflowCompileError("a module needs at least one parameter"),
        )

        def wrapper(*args: Any, **kwargs: Any) -> Any:
            assert_or_throw(
                len(args) > 0
                and isinstance(args[0], (FugueWorkflow, WorkflowDataFrame)),
                FugueWorkflowCompileError(
                    "first argument of a module call must be a FugueWorkflow "
                    "or WorkflowDataFrame"
                ),
            )
            result = fn(*args, **kwargs)
            assert_or_throw(
                result is None or isinstance(result, WorkflowDataFrame),
                FugueWorkflowCompileError(
                    "a module must return a WorkflowDataFrame or None"
                ),
            )
            return result

        wrapper.__name__ = name or fn.__name__
        wrapper.__doc__ = fn.__doc__
        wrapper.__wrapped__ = fn  # type: ignore
        return wrapper

    if func is not None:
        return deco(func)
    return deco
