"""Workflow tasks with deterministic uuids.

Parity with the reference (`fugue/workflow/_tasks.py`): ``Create``/``Process``/
``Output`` task specs, uuid-based determinism (``:85-98``), and the
checkpoint/broadcast/yield handling in ``set_result`` (``:143-152``). The
execution substrate is the in-tree DAG runner in ``_workflow_context.py``
(replacing adagio).
"""

from typing import Any, Callable, Dict, List, Optional

from .._utils.assertion import assert_or_throw
from .._utils.hash import to_uuid
from .._utils.params import ParamDict
from ..collections.partition import PartitionSpec
from ..collections.yielded import PhysicalYielded, Yielded
from ..dataframe import DataFrame, DataFrames, YieldedDataFrame
from ..exceptions import FugueWorkflowCompileError, FugueWorkflowError
from ..extensions._utils import validate_partition_spec
from ..extensions.creator.creator import Creator
from ..extensions.outputter.outputter import Outputter
from ..extensions.processor.processor import Processor
from ._checkpoint import Checkpoint, StrongCheckpoint
from ..rpc.base import to_rpc_handler


def _caller_site() -> str:
    """The user-code location where this task was defined (first frame
    outside the framework) — injected into runtime errors so they point at
    the DAG construction site. Gated by the
    ``fugue.workflow.exception.inject`` conf (0 disables); uses raw frame
    walking (no source-line fetching) to stay cheap per task."""
    import sys

    from ..constants import (
        FUGUE_CONF_WORKFLOW_EXCEPTION_INJECT,
        _FUGUE_GLOBAL_CONF,
    )

    if _FUGUE_GLOBAL_CONF.get(FUGUE_CONF_WORKFLOW_EXCEPTION_INJECT, 3) <= 0:
        return ""
    f = sys._getframe(2)
    while f is not None:
        fn = f.f_code.co_filename
        if "/fugue_tpu/" not in fn and "fugue_tpu_test" not in fn:
            return f"{fn}:{f.f_lineno} in {f.f_code.co_name}"
        f = f.f_back
    return ""


class FugueTask:
    """One node of the workflow DAG."""

    def __init__(
        self,
        extension: Any,
        params: Any = None,
        partition_spec: Optional[PartitionSpec] = None,
        input_tasks: Optional[List["FugueTask"]] = None,
        input_names: Optional[List[str]] = None,
    ):
        self.extension = extension
        self.params = ParamDict(params)
        self.partition_spec = partition_spec or PartitionSpec()
        self.inputs: List["FugueTask"] = list(input_tasks or [])
        self.input_names = input_names
        self.checkpoint: Checkpoint = Checkpoint()
        self.broadcast_flag = False
        self.yield_dataframe_handler: Optional[Callable[[DataFrame], None]] = None
        self.name = ""
        self._uuid: Optional[str] = None
        self.defined_at = _caller_site()
        # compile-time validation of the partition spec against extension rules
        rules = getattr(extension, "validation_rules", {})
        if rules:
            validate_partition_spec(self.partition_spec, rules)

    @property
    def has_output(self) -> bool:
        return True

    def __uuid__(self) -> str:
        if self._uuid is None:
            self._uuid = to_uuid(
                type(self).__name__,
                getattr(self.extension, "__uuid__", lambda: to_uuid(type(self.extension).__name__))(),
                self._params_uuid(),
                self.partition_spec,
                [t.__uuid__() for t in self.inputs],
            )
        return self._uuid

    def _params_uuid(self) -> str:
        import pandas as pd
        import pyarrow as pa

        safe: Dict[str, Any] = {}
        for k, v in self.params.items():
            if isinstance(v, (pd.DataFrame, pa.Table)):
                # raw frames hash by identity: never cross-run deterministic,
                # so a deterministic checkpoint can't false-hit on different
                # data that shares column names
                safe[k] = to_uuid(repr(type(v)), id(v))
            else:
                try:
                    safe[k] = to_uuid(v)
                except Exception:
                    safe[k] = repr(v)
        return to_uuid(safe)

    def clone_with(
        self,
        extension: Any = None,
        params: Any = None,
        input_tasks: Optional[List["FugueTask"]] = None,
    ) -> "FugueTask":
        """Shallow clone for the plan optimizer: same checkpoint/yield/
        broadcast/name, optionally different extension/params/inputs, and
        a fresh uuid (computed over the NEW params and inputs). The
        original task is never mutated — its uuid, checkpoint identity
        and handlers stay exactly as compiled."""
        import copy

        c = copy.copy(self)
        if extension is not None:
            c.extension = extension
        if params is not None:
            c.params = ParamDict(params)
        if input_tasks is not None:
            c.inputs = list(input_tasks)
        c._uuid = None
        return c

    def set_checkpoint(self, checkpoint: Checkpoint) -> None:
        assert_or_throw(
            checkpoint.is_null or self.has_output,
            FugueWorkflowCompileError("output tasks can't have checkpoints"),
        )
        self.checkpoint = checkpoint
        self._uuid = None

    def set_yield_dataframe_handler(self, handler: Callable[[DataFrame], None]) -> None:
        self.yield_dataframe_handler = handler

    def _setup_extension(self, ctx: Any) -> None:
        ext = self.extension
        ext._params = self.params
        ext._workflow_conf = ctx.execution_engine.conf
        ext._execution_engine = ctx.execution_engine
        ext._partition_spec = self.partition_spec
        ext._rpc_server = ctx.execution_engine.rpc_server

    def execute(self, ctx: Any, inputs: List[DataFrame]) -> Optional[DataFrame]:
        raise NotImplementedError

    def set_result(self, ctx: Any, df: DataFrame) -> DataFrame:
        """checkpoint → broadcast → yield (reference ``:143-152``)."""
        df = self.checkpoint.run(df, ctx.checkpoint_path)
        if self.broadcast_flag:
            df = ctx.execution_engine.broadcast(df)
        if self.yield_dataframe_handler is not None:
            self.yield_dataframe_handler(df)
        return df


class CreateTask(FugueTask):
    """0-input creation (reference ``Create:214``)."""

    def __init__(self, creator: Creator, params: Any = None):
        super().__init__(creator, params=params)

    def execute(self, ctx: Any, inputs: List[DataFrame]) -> Optional[DataFrame]:
        self._setup_extension(ctx)
        return self.extension.create()


class ProcessTask(FugueTask):
    """n-input → 1-output (reference ``Process:243``)."""

    def __init__(
        self,
        processor: Processor,
        input_tasks: List[FugueTask],
        params: Any = None,
        partition_spec: Optional[PartitionSpec] = None,
        input_names: Optional[List[str]] = None,
    ):
        super().__init__(
            processor,
            params=params,
            partition_spec=partition_spec,
            input_tasks=input_tasks,
            input_names=input_names,
        )

    def execute(self, ctx: Any, inputs: List[DataFrame]) -> Optional[DataFrame]:
        self._setup_extension(ctx)
        if self.input_names is not None:
            dfs = DataFrames(dict(zip(self.input_names, inputs)))
        else:
            dfs = DataFrames(inputs)
        return self.extension.process(dfs)


class OutputTask(FugueTask):
    """n-input → 0-output sink (reference ``Output:297``)."""

    def __init__(
        self,
        outputter: Outputter,
        input_tasks: List[FugueTask],
        params: Any = None,
        partition_spec: Optional[PartitionSpec] = None,
        input_names: Optional[List[str]] = None,
    ):
        super().__init__(
            outputter,
            params=params,
            partition_spec=partition_spec,
            input_tasks=input_tasks,
            input_names=input_names,
        )

    @property
    def has_output(self) -> bool:
        return False

    def execute(self, ctx: Any, inputs: List[DataFrame]) -> Optional[DataFrame]:
        self._setup_extension(ctx)
        if self.input_names is not None:
            dfs = DataFrames(dict(zip(self.input_names, inputs)))
        else:
            dfs = DataFrames(inputs)
        self.extension.process(dfs)
        return None
