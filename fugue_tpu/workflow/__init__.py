from .workflow import FugueWorkflow, FugueWorkflowResult, WorkflowDataFrame
from .api import out_transform, raw_sql, transform
from ._checkpoint import Checkpoint, StrongCheckpoint, WeakCheckpoint
from .factory import build_workflow, is_workflow_factory, validate_view_factory
from .module import module

__all__ = [
    "FugueWorkflow",
    "FugueWorkflowResult",
    "WorkflowDataFrame",
    "transform",
    "out_transform",
    "raw_sql",
    "Checkpoint",
    "StrongCheckpoint",
    "WeakCheckpoint",
    "module",
    "is_workflow_factory",
    "build_workflow",
    "validate_view_factory",
]
