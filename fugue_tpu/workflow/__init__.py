from .workflow import FugueWorkflow, FugueWorkflowResult, WorkflowDataFrame
from .api import out_transform, raw_sql, transform
from ._checkpoint import Checkpoint, StrongCheckpoint, WeakCheckpoint
from .module import module

__all__ = [
    "FugueWorkflow",
    "FugueWorkflowResult",
    "WorkflowDataFrame",
    "transform",
    "out_transform",
    "raw_sql",
    "Checkpoint",
    "StrongCheckpoint",
    "WeakCheckpoint",
    "module",
]
