"""Checkpoints: weak (persist), strong (save+reload), deterministic (resume).

Parity with the reference (`fugue/workflow/_checkpoint.py:15,38,111,131`):
deterministic checkpoints are uuid-keyed permanent files reused across runs —
true resume.
"""

import os
import shutil
import uuid as _uuid
from typing import Any, Optional

from .._utils.assertion import assert_or_throw
from .._utils.params import ParamDict
from ..collections.partition import PartitionSpec
from ..collections.yielded import PhysicalYielded
from ..constants import FUGUE_CONF_WORKFLOW_CHECKPOINT_PATH
from ..dataframe import DataFrame
from ..exceptions import FugueWorkflowCompileError, FugueWorkflowRuntimeError
from ..execution.execution_engine import ExecutionEngine


def _atomic_publish(tmp: str, final: str) -> None:
    """Atomically move a finished write into place. ``tmp`` may be a single
    parquet file or a partitioned directory; same-directory rename is atomic
    on POSIX for both. Also the publish discipline of the result cache's
    artifact store (``fugue_tpu/cache/store.py``), so every durable frame
    in the system is either absent or complete — never torn."""
    if os.path.isdir(tmp):
        if os.path.isdir(final):
            shutil.rmtree(final)
        elif os.path.exists(final):
            os.remove(final)
        os.rename(tmp, final)
    else:
        os.replace(tmp, final)


def _best_effort_remove(p: str) -> None:
    try:
        if os.path.isdir(p):
            shutil.rmtree(p)
        else:
            os.remove(p)
    except OSError:  # pragma: no cover - cleanup only
        pass


class Checkpoint:
    """No-op checkpoint base."""

    def __init__(
        self,
        to_file: bool = False,
        deterministic: bool = False,
        permanent: bool = False,
        lazy: bool = False,
        **kwargs: Any,
    ):
        self.to_file = to_file
        self.deterministic = deterministic
        self.permanent = permanent
        self.lazy = lazy
        self.kwargs = dict(kwargs)
        self.yielded: Optional[PhysicalYielded] = None

    @property
    def is_null(self) -> bool:
        return True

    def run(self, df: DataFrame, path: "CheckpointPath") -> DataFrame:
        return df

    def exists(self, path: "CheckpointPath", tid: str) -> bool:
        return False


class WeakCheckpoint(Checkpoint):
    """Engine persist/cache (reference ``:38``)."""

    def __init__(self, lazy: bool = False, **kwargs: Any):
        super().__init__(to_file=False, deterministic=False, permanent=False, lazy=lazy, **kwargs)

    @property
    def is_null(self) -> bool:
        return False

    def run(self, df: DataFrame, path: "CheckpointPath") -> DataFrame:
        return path.execution_engine.persist(df, lazy=self.lazy, **self.kwargs)


class StrongCheckpoint(Checkpoint):
    """Save to storage and reload (reference ``:111``); with
    ``deterministic=True`` + permanent path this is cross-run resume."""

    def __init__(
        self,
        storage_type: str = "file",
        deterministic: bool = False,
        permanent: bool = False,
        lazy: bool = False,
        partition: Any = None,
        single: bool = False,
        namespace: Any = None,
        **kwargs: Any,
    ):
        super().__init__(
            to_file=True,
            deterministic=deterministic,
            permanent=permanent or deterministic,
            lazy=lazy,
            **kwargs,
        )
        assert_or_throw(
            storage_type in ("file", "table"),
            FugueWorkflowCompileError(f"invalid storage type {storage_type}"),
        )
        self.storage_type = storage_type
        self.partition = None if partition is None else PartitionSpec(partition)
        self.single = single
        self.namespace = namespace
        self._tid = ""

    @property
    def is_null(self) -> bool:
        return False

    def set_id(self, tid: str) -> None:
        from .._utils.hash import to_uuid

        self._tid = to_uuid(tid, self.namespace) if self.namespace is not None else tid

    def _file_path(self, path: "CheckpointPath") -> str:
        base = path.permanent_path if self.permanent else path.temp_path
        return os.path.join(base, self._tid + ".parquet")

    def _table_name(self) -> str:
        return "tbl_" + self._tid.replace("-", "")

    def exists(self, path: "CheckpointPath", tid: str) -> bool:
        if not self.deterministic:
            return False
        self.set_id(tid)
        if self.storage_type == "file":
            return os.path.exists(self._file_path(path))
        try:
            return path.execution_engine.sql_engine.table_exists(self._table_name())
        except Exception:  # engines without table support can't resume
            return False

    def run(self, df: DataFrame, path: "CheckpointPath") -> DataFrame:
        engine = path.execution_engine
        fp = self._file_path(path)
        if self.storage_type == "file":
            if not (self.deterministic and os.path.exists(fp)):
                # write to a temp name and atomically publish: an
                # interrupted write must never leave a torn file at the
                # final path, or a later run's exists() would resume from
                # corrupt data
                tmp = f"{fp}.__tmp_{_uuid.uuid4().hex}"
                try:
                    engine.save_df(
                        df,
                        tmp,
                        format_hint="parquet",
                        mode="overwrite",
                        partition_spec=self.partition,
                        force_single=self.single,
                        **self.kwargs,
                    )
                    from ..resilience import SITE_CHECKPOINT_SAVE, FaultInjector

                    # injection point between write and publish: a fault
                    # here proves torn checkpoints are invisible
                    FaultInjector.from_conf(engine.conf).fire(SITE_CHECKPOINT_SAVE)
                    _atomic_publish(tmp, fp)
                finally:
                    if os.path.exists(tmp):  # failed before publish
                        _best_effort_remove(tmp)
            res = engine.load_df(fp, format_hint="parquet")
        else:
            table = self._table_name()
            if not (self.deterministic and engine.sql_engine.table_exists(table)):
                engine.sql_engine.save_table(df, table, **self.kwargs)
            res = engine.sql_engine.load_table(table)
        if self.yielded is not None:
            self.yielded.set_value(fp if self.storage_type == "file" else table)
        return res

    def load(self, path: "CheckpointPath") -> DataFrame:
        if self.storage_type == "table":
            table = self._table_name()
            res = path.execution_engine.sql_engine.load_table(table)
            if self.yielded is not None:
                self.yielded.set_value(table)
            return res
        fp = self._file_path(path)
        res = path.execution_engine.load_df(fp, format_hint="parquet")
        if self.yielded is not None:
            self.yielded.set_value(fp)
        return res


class CheckpointPath:
    """Temp/permanent checkpoint directory lifecycle (reference ``:131``)."""

    def __init__(self, engine: ExecutionEngine, conf: Any = None):
        # conf: the run-scoped merge when built by a workflow run — a
        # workflow-level checkpoint path must keep working now that
        # workflow conf no longer writes through to the engine
        self._engine = engine
        self._conf_path = (conf if conf is not None else engine.conf).get(
            FUGUE_CONF_WORKFLOW_CHECKPOINT_PATH, ""
        )
        self._temp_path = ""
        self._execution_id = ""

    @property
    def execution_engine(self) -> ExecutionEngine:
        return self._engine

    @property
    def permanent_path(self) -> str:
        assert_or_throw(
            self._conf_path != "",
            FugueWorkflowRuntimeError(
                f"{FUGUE_CONF_WORKFLOW_CHECKPOINT_PATH} is not set"
            ),
        )
        os.makedirs(self._conf_path, exist_ok=True)
        return self._conf_path

    @property
    def temp_path(self) -> str:
        assert_or_throw(
            self._temp_path != "",
            FugueWorkflowRuntimeError("temp checkpoint path is not initialized"),
        )
        return self._temp_path

    def init_temp_path(self, execution_id: str) -> str:
        # like the reference, file checkpoints REQUIRE the conf path; the
        # error surfaces when a checkpoint accesses temp_path during run
        if self._conf_path == "":
            self._temp_path = ""
            return ""
        self._execution_id = execution_id
        self._temp_path = os.path.join(self._conf_path, execution_id)
        os.makedirs(self._temp_path, exist_ok=True)
        return self._temp_path

    def remove_temp_path(self) -> None:
        if self._temp_path != "":
            try:
                shutil.rmtree(self._temp_path)
            except Exception:  # pragma: no cover - best effort cleanup
                pass
