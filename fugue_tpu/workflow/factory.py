"""Registration-safe workflow factories.

A *workflow factory* is a zero-arg callable that builds and returns a
fresh :class:`~fugue_tpu.workflow.workflow.FugueWorkflow` each call. It
is the form that crosses process boundaries cleanly — a BUILT dag may
close over live in-process frames, but a factory cloudpickles as code
and rebuilds against whatever engine runs it. The serving tier has
always accepted both forms on submit; continuous views (ISSUE 20,
``docs/views.md``) make the factory form load-bearing: a registered
view's factory is journaled in the WAL, re-hydrated after replica
death, and re-invoked once per generation for the lifetime of the view,
so it must be durable, rebuildable, and must actually yield something
to publish. :func:`validate_view_factory` checks exactly that at
registration time, turning "my view silently never refreshes" into an
immediate 400.
"""

from typing import Any, Callable

__all__ = [
    "is_workflow_factory",
    "build_workflow",
    "validate_view_factory",
]


def is_workflow_factory(obj: Any) -> bool:
    """True when ``obj`` is the factory form: callable and not a built
    dag (a built :class:`FugueWorkflow` carries ``_tasks``)."""
    return callable(obj) and not hasattr(obj, "_tasks")


def build_workflow(obj: Any) -> Any:
    """Return a runnable dag: invoke the factory form, pass a built dag
    through unchanged."""
    return obj() if is_workflow_factory(obj) else obj


def validate_view_factory(factory: Callable[[], Any]) -> None:
    """Registration gate for a standing view's factory: it must be a
    zero-arg factory (not a built dag), must cloudpickle (it outlives
    this process via the WAL), must build without error, and the built
    workflow must yield at least one dataframe (a view with nothing to
    publish is a misregistration, not a quiet no-op). Raises
    ``ValueError`` with the specific reason."""
    if not callable(factory):
        raise ValueError("view factory is not callable")
    if not is_workflow_factory(factory):
        raise ValueError(
            "view factory is a built workflow; register the zero-arg "
            "factory so each generation rebuilds against the live source"
        )
    try:
        import cloudpickle

        cloudpickle.loads(cloudpickle.dumps(factory))
    except Exception as ex:
        raise ValueError(
            f"view factory does not survive cloudpickle "
            f"({type(ex).__name__}: {ex}); a standing view's factory is "
            f"journaled and replayed across replica restarts"
        ) from ex
    try:
        dag = factory()
    except Exception as ex:
        raise ValueError(
            f"view factory raised while building its workflow "
            f"({type(ex).__name__}: {ex})"
        ) from ex
    if not hasattr(dag, "_tasks"):
        raise ValueError(
            f"view factory returned {type(dag).__name__}, not a "
            f"FugueWorkflow"
        )
    if not getattr(dag, "yields", None):
        raise ValueError(
            "view factory's workflow yields nothing — a view must "
            "yield_dataframe_as(...) the frames it publishes"
        )
