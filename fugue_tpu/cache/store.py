"""The tiered result store: in-process LRU over live frames, backed by a
content-addressed on-disk Arrow/parquet artifact store.

Memory tier (:class:`MemoryLRU`): byte-budgeted
(``fugue.tpu.cache.mem_bytes``) references to the exact DataFrame objects
a run produced — a hit re-serves the live (possibly device-resident)
frame with zero decode/H2D. Per-engine, because device frames are laid
out for one mesh.

Disk tier (:class:`ArtifactStore`): ``objs/<fp>.parquet`` artifacts plus
a ``<fp>.meta.json`` sidecar (schema + bytes), published through the same
temp-write + atomic-rename discipline as the PR 1 checkpoint publisher —
two processes racing to publish the same fingerprint both succeed and the
survivor is a complete file. A fingerprint can instead be a *ref*
(``<fp>.ref.json``) pointing at an artifact some other subsystem already
owns (a permanent StrongCheckpoint file): one artifact, two indexes,
double-publishing impossible. Size-capped (``fugue.tpu.cache.disk_bytes``)
with LRU eviction on artifact mtime (hits re-touch). A corrupt or torn
artifact is a MISS: the reader deletes it and the caller recomputes.

:class:`ResultCache` composes both tiers behind ``lookup``/``publish``
and owns the :class:`CacheStats` counters surfaced as
``engine.stats()["cache"]``. ``stats.reset()`` follows the ``JitCache``
contract: counters zero, live entries stay.
"""

import json
import os
import shutil
import socket
import threading
import time
import uuid as _uuid
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

from ..workflow._checkpoint import _atomic_publish, _best_effort_remove

__all__ = [
    "CacheStats",
    "MemoryLRU",
    "ArtifactStore",
    "ResultCache",
    "estimate_df_bytes",
    "clean_cache_dir",
    "try_claim_file",
    "read_claim_file",
    "release_claim_file",
]


# ---------------------------------------------------------------------------
# the shared file-claim primitive (docs/serving.md "Fleet",
# docs/distributed.md "Leases")
# ---------------------------------------------------------------------------
# One small json file created with O_CREAT|O_EXCL — the same kernel-atomic
# primitive the temp-write+rename publishes lean on — so exactly one
# creator wins a cold race. A held claim is STEALABLE when the caller's
# ``stealable(holder)`` predicate says so (lease expiry, dead pid, stale
# heartbeat); steal races settle by re-reading the file after the atomic
# rewrite: whichever payload survived the rename owns it. The fleet's
# fingerprint-ownership claims and the dist tier's task leases are both
# THIS protocol with different payloads and different stealable rules.


def _claim_write_json(final: str, payload: Dict[str, Any]) -> None:
    tmp = f"{final}.__tmp_{_uuid.uuid4().hex}"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, final)


def read_claim_file(path: str) -> Optional[Dict[str, Any]]:
    """The current claim payload, or None. A torn/corrupt claim file is
    deleted and reads as absent (stealable, never a wedge)."""
    try:
        with open(path) as f:
            return json.load(f)
    except FileNotFoundError:
        return None
    except Exception:
        _best_effort_remove(path)
        return None


def try_claim_file(
    path: str,
    payload: Dict[str, Any],
    stealable: Any,
) -> Tuple[bool, Optional[Dict[str, Any]]]:
    """Atomically claim ``path`` with ``payload`` (must carry ``owner``).

    Returns ``(owned, holder)``: ``owned`` means the payload's owner
    holds the claim now (fresh, re-entered, or stolen); otherwise
    ``holder`` is the live holder to wait on. ``stealable(holder)``
    decides whether a foreign holder may be overwritten."""
    owner = payload.get("owner")
    try:
        fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        try:
            data = json.dumps(payload).encode()
            os.write(fd, data)
            os.fsync(fd)
        finally:
            os.close(fd)
        return True, payload
    except FileExistsError:
        pass
    except OSError:
        return False, None  # store trouble: behave as not-owned
    holder = read_claim_file(path)
    if holder is not None:
        if holder.get("owner") == owner:
            # re-entrant: the owner restarting meets its own prior claim
            return True, holder
        if not stealable(holder):
            return False, holder
    # expired/dead/torn: steal via atomic rewrite; the last rename wins,
    # so re-read to learn who actually owns it now
    try:
        _claim_write_json(path, payload)
    except OSError:
        return False, holder
    cur = read_claim_file(path)
    return (cur is not None and cur.get("owner") == owner), cur


def release_claim_file(path: str, owner: str) -> bool:
    """Remove the claim if ``owner`` still holds it (a steal victim's
    late release must not drop the thief's claim)."""
    cur = read_claim_file(path)
    if cur is not None and cur.get("owner") != owner:
        return False
    _best_effort_remove(path)
    return True

_COUNTERS = (
    "lookups",
    "hits_mem",
    "hits_disk",
    "misses",
    "refusals",
    "publishes",
    "links",
    "evictions_mem",
    "evictions_disk",
    "bytes_served",
    "bytes_published",
    "bytes_skipped",
    "tasks_skipped",
    # partition-level delta recompute (docs/cache.md "Incremental
    # recompute"): a partial hit serves the cached part of a grown source
    # and recomputes only the delta partitions
    "partial_hits",
    "delta_partitions",
    "delta_partitions_fresh",
    "bytes_skipped_delta",
    "delta_refusals",
    "manifest_publishes",
)


class CacheStats:
    """Thread-safe cache counters (a ``MetricsRegistry`` source).

    ``reset()`` zeroes the counters WITHOUT evicting live entries —
    mirroring ``JitCache.reset``: a stats reset must never become a perf
    event. Entry/byte gauges are re-read from the tiers on every
    ``as_dict`` so they survive resets."""

    def __init__(self, cache: Optional["ResultCache"] = None) -> None:
        self._lock = threading.Lock()
        self._cache = cache
        self.reset()

    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._c[name] = self._c.get(name, 0) + n

    def as_dict(self) -> Dict[str, int]:
        with self._lock:
            out = {k: self._c.get(k, 0) for k in _COUNTERS}
        if self._cache is not None:
            out["mem_entries"] = self._cache.mem.entries
            out["mem_bytes"] = self._cache.mem.bytes
            out["disk_enabled"] = self._cache.disk is not None
        return out

    def reset(self) -> None:
        with self._lock:
            self._c: Dict[str, int] = {}


class MemoryLRU:
    """Byte-budgeted LRU of live DataFrames keyed by fingerprint."""

    def __init__(self, budget_bytes: int):
        self.budget = int(budget_bytes)
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, Tuple[Any, int]]" = OrderedDict()
        self._bytes = 0

    @property
    def entries(self) -> int:
        with self._lock:
            return len(self._entries)

    @property
    def bytes(self) -> int:
        with self._lock:
            return self._bytes

    def contains(self, fp: str) -> bool:
        with self._lock:
            return fp in self._entries

    def get(self, fp: str) -> Optional[Tuple[Any, int]]:
        with self._lock:
            hit = self._entries.get(fp)
            if hit is None:
                return None
            self._entries.move_to_end(fp)
            return hit

    def put(self, fp: str, df: Any, nbytes: int) -> int:
        """Insert (or refresh) an entry; returns how many were evicted.
        A frame larger than the whole budget is refused outright."""
        nbytes = max(0, int(nbytes))
        if self.budget <= 0 or nbytes > self.budget:
            return 0
        evicted = 0
        with self._lock:
            old = self._entries.pop(fp, None)
            if old is not None:
                self._bytes -= old[1]
            self._entries[fp] = (df, nbytes)
            self._bytes += nbytes
            while self._bytes > self.budget and len(self._entries) > 1:
                _, (_odf, ob) = self._entries.popitem(last=False)
                self._bytes -= ob
                evicted += 1
        return evicted

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0


class ArtifactStore:
    """Content-addressed parquet artifacts under ``<dir>/objs``."""

    def __init__(
        self,
        path: str,
        cap_bytes: int,
        log: Any = None,
        cap_entries: int = 0,
        hb_dir: Optional[str] = None,
        hb_stale_s: float = 3.0,
    ):
        self.root = path
        self.objs = os.path.join(path, "objs")
        self.manifests = os.path.join(path, "manifests")
        self.claims = os.path.join(path, "claims")
        self.cap = int(cap_bytes)
        self.cap_entries = int(cap_entries)
        # cross-host claim-steal liveness (docs/distributed.md): when a
        # heartbeat dir is configured, a claim owner's staleness there is
        # the death proof; the same-host pid probe stays as the fallback
        # for owners that never wrote a beat
        self.hb_dir = hb_dir or None
        self.hb_stale_s = float(hb_stale_s)
        self._log = log
        os.makedirs(self.objs, exist_ok=True)
        os.makedirs(self.manifests, exist_ok=True)
        os.makedirs(self.claims, exist_ok=True)

    # -- paths ---------------------------------------------------------------
    def _obj(self, fp: str) -> str:
        return os.path.join(self.objs, fp + ".parquet")

    def _meta(self, fp: str) -> str:
        return os.path.join(self.objs, fp + ".meta.json")

    def _ref(self, fp: str) -> str:
        return os.path.join(self.objs, fp + ".ref.json")

    def _manifest(self, key: str) -> str:
        return os.path.join(self.manifests, key + ".manifest.json")

    def _claim(self, key: str) -> str:
        return os.path.join(self.claims, key + ".claim.json")

    # -- fingerprint-ownership claims (docs/serving.md "Fleet") --------------
    # Replicas sharing this store collapse identical work ACROSS processes
    # by claiming a key before executing it: the winner executes and
    # publishes, everyone else waits on the published artifact. The claim
    # is a small json file created with O_CREAT|O_EXCL (the same
    # kernel-atomic primitive the temp-write+rename publishes lean on), so
    # exactly one creator wins a cold race. A claim is STEALABLE when its
    # owner is provably dead (same-host pid gone) or its lease expired —
    # steal races settle by re-reading the file after the atomic rewrite:
    # whichever payload survived the rename owns it.
    def try_claim(
        self, key: str, owner: str, lease_s: float
    ) -> Tuple[bool, Optional[Dict[str, Any]]]:
        """(owned, holder_payload). ``owned`` means THIS ``owner`` holds
        the claim now (fresh, re-entered after a restart, or stolen);
        otherwise ``holder_payload`` is the live holder to wait on."""
        payload = {
            "owner": owner,
            "pid": os.getpid(),
            "host": socket.gethostname(),
            "ts": time.time(),
            "lease_s": float(lease_s),
        }
        return try_claim_file(self._claim(key), payload, self._claim_stealable)

    def _claim_stealable(self, holder: Dict[str, Any]) -> bool:
        ts = float(holder.get("ts", 0.0))
        lease = float(holder.get("lease_s", 0.0))
        if ts + lease <= time.time():
            return True
        # cross-host liveness first (ISSUE 14): a stale heartbeat is proof
        # of death regardless of host; a FRESH one pins the claim for the
        # rest of its lease even when the pid probe can't see the owner
        if self.hb_dir:
            from ..dist.heartbeat import holder_alive

            alive = holder_alive(
                str(holder.get("owner") or ""), self.hb_dir, self.hb_stale_s
            )
            if alive is not None:
                return not alive
        # fallback (no heartbeat dir configured, or an owner that never
        # beat): a SIGKILLed same-host owner shouldn't pin its claim for
        # the whole lease — a dead pid is stealable immediately
        pid = holder.get("pid")
        if pid and holder.get("host") == socket.gethostname():
            try:
                os.kill(int(pid), 0)
            except ProcessLookupError:
                return True
            except OSError:
                pass
        return False

    def read_claim(self, key: str) -> Optional[Dict[str, Any]]:
        """The current claim payload, or None. A torn/corrupt claim file
        is deleted and reads as absent (stealable, never a wedge)."""
        return read_claim_file(self._claim(key))

    def release_claim(self, key: str, owner: str) -> bool:
        """Remove the claim if ``owner`` still holds it (a steal victim's
        late release must not drop the thief's claim)."""
        return release_claim_file(self._claim(key), owner)

    # -- delta manifests -----------------------------------------------------
    def load_manifest(self, key: str) -> Optional[Dict[str, Any]]:
        """The partition manifest published under a delta key, or None. A
        torn/corrupt manifest is deleted and reads as absent (a delta miss
        degrades to whole-task recompute, never a wrong hit)."""
        path = self._manifest(key)
        try:
            with open(path) as f:
                return json.load(f)
        except FileNotFoundError:
            return None
        except Exception:
            _best_effort_remove(path)
            return None

    def publish_manifest(self, key: str, payload: Dict[str, Any]) -> None:
        """Atomic last-writer-wins: two processes publishing the manifest
        of the same grown source write identical content by construction
        (segment artifacts are content-addressed), so either winner is
        complete and correct."""
        self._write_json(self._manifest(key), payload)

    def remove_manifest(self, key: str) -> None:
        _best_effort_remove(self._manifest(key))

    # -- read side -----------------------------------------------------------
    def exists(self, fp: str) -> bool:
        if os.path.exists(self._obj(fp)) and os.path.exists(self._meta(fp)):
            return True
        return os.path.exists(self._ref(fp))

    def load(self, fp: str, engine: Any) -> Optional[Tuple[Any, int]]:
        """(frame, artifact_bytes) or None. The sidecar's schema is
        re-applied on load so the parquet round trip can't drift dtypes.
        A torn/corrupt owned artifact is deleted and reads as a miss."""
        path, meta_path, owned = self._obj(fp), self._meta(fp), True
        if not os.path.exists(path):
            ref = self._ref(fp)
            if not os.path.exists(ref):
                return None
            try:
                with open(ref) as f:
                    target = json.load(f)
                path, meta_path, owned = target["path"], ref, False
            except Exception:
                _best_effort_remove(ref)
                return None
        try:
            with open(meta_path) as f:
                meta = json.load(f)
            df = engine.load_df(path, format_hint="parquet")
            schema = meta.get("schema")
            if schema:
                df = engine.to_df(df, schema=schema)
            nbytes = int(meta.get("bytes", 0)) or _path_bytes(path)
            os.utime(self._meta(fp) if owned else meta_path, None)
            if owned:
                os.utime(path, None)
            return df, nbytes
        except Exception as ex:
            if self._log is not None:
                self._log.warning(
                    "result-cache artifact %s unreadable (%s); recomputing",
                    fp[:12],
                    type(ex).__name__,
                )
            if owned:
                _best_effort_remove(path)
                _best_effort_remove(meta_path)
            else:
                _best_effort_remove(self._ref(fp))
            return None

    # -- write side ----------------------------------------------------------
    def publish(self, fp: str, df: Any, engine: Any, schema: str) -> int:
        """Write the artifact + sidecar atomically; a concurrent publisher
        of the same fingerprint harmlessly wins or loses the final rename
        (the content is the same by construction). Returns bytes written
        (0 when the artifact already existed)."""
        if self.exists(fp):
            return 0
        final = self._obj(fp)
        tmp = f"{final}.__tmp_{_uuid.uuid4().hex}"
        try:
            engine.save_df(
                df, tmp, format_hint="parquet", mode="overwrite", force_single=True
            )
            nbytes = _path_bytes(tmp)
            _atomic_publish(tmp, final)
        finally:
            if os.path.exists(tmp):
                _best_effort_remove(tmp)
        self._write_json(self._meta(fp), {"schema": schema, "bytes": nbytes})
        return nbytes

    def link(self, fp: str, path: str, schema: str) -> bool:
        """Index an artifact another subsystem owns (one artifact, two
        indexes): the memoization path never writes a second copy of a
        frame a permanent StrongCheckpoint already published."""
        if self.exists(fp):
            return False
        self._write_json(
            self._ref(fp), {"path": path, "schema": schema, "bytes": _path_bytes(path)}
        )
        return True

    def _write_json(self, final: str, payload: Dict[str, Any]) -> None:
        tmp = f"{final}.__tmp_{_uuid.uuid4().hex}"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, final)

    # -- eviction ------------------------------------------------------------
    def evict_to_cap(self) -> int:
        """Drop least-recently-used artifacts until under BOTH the size
        cap and the entry-count cap (per-partition delta artifacts
        multiply small files, so bytes alone don't bound inode pressure).
        Raced deletions are fine: the loser's remove is a no-op. Manifests
        referencing an evicted artifact are invalidated LAZILY — the next
        delta match sees the missing artifact, deletes the stale manifest
        and degrades that one chain to whole-task recompute."""
        if self.cap <= 0 and self.cap_entries <= 0:
            return 0
        entries: List[Tuple[float, int, str]] = []
        total = 0
        try:
            names = os.listdir(self.objs)
        except OSError:
            return 0
        for n in names:
            if not n.endswith(".parquet"):
                continue
            p = os.path.join(self.objs, n)
            try:
                st = os.stat(p)
            except OSError:
                continue
            entries.append((st.st_mtime, int(st.st_size), p[: -len(".parquet")]))
            total += int(st.st_size)
        evicted = 0
        count = len(entries)
        for _mt, size, base in sorted(entries):
            over_bytes = self.cap > 0 and total > self.cap
            over_count = self.cap_entries > 0 and count > self.cap_entries
            if not (over_bytes or over_count):
                break
            _best_effort_remove(base + ".parquet")
            _best_effort_remove(base + ".meta.json")
            total -= size
            count -= 1
            evicted += 1
        return evicted

    def clear(self) -> None:
        shutil.rmtree(self.objs, ignore_errors=True)
        shutil.rmtree(self.manifests, ignore_errors=True)
        os.makedirs(self.objs, exist_ok=True)
        os.makedirs(self.manifests, exist_ok=True)


class ResultCache:
    """The engine-facing cache: conf-driven tiers + counters."""

    def __init__(self, conf: Any, log: Any = None):
        from ..constants import (
            FUGUE_TPU_CONF_CACHE_DELTA_ENABLED,
            FUGUE_TPU_CONF_CACHE_DIR,
            FUGUE_TPU_CONF_CACHE_DISK_BYTES,
            FUGUE_TPU_CONF_CACHE_DISK_MAX_ENTRIES,
            FUGUE_TPU_CONF_CACHE_ENABLED,
            FUGUE_TPU_CONF_CACHE_MAX_ARTIFACT_BYTES,
            FUGUE_TPU_CONF_CACHE_MEM_BYTES,
        )

        def _get(key: str, default: Any) -> Any:
            try:
                return conf.get(key, default)
            except Exception:
                return default

        self._log = log
        self.enabled = bool(_get(FUGUE_TPU_CONF_CACHE_ENABLED, True))
        self.delta_enabled = bool(_get(FUGUE_TPU_CONF_CACHE_DELTA_ENABLED, True))
        self.max_artifact_bytes = int(
            _get(FUGUE_TPU_CONF_CACHE_MAX_ARTIFACT_BYTES, 256 * 1024 * 1024)
        )
        self.mem = MemoryLRU(int(_get(FUGUE_TPU_CONF_CACHE_MEM_BYTES, 256 * 1024 * 1024)))
        self.stats = CacheStats(self)
        self.disk: Optional[ArtifactStore] = None
        # in-process manifest tier: delta recompute works memory-only too
        # (same-engine warm runs); the disk copy is the cross-process one
        self._manifest_lock = threading.Lock()
        self._mem_manifests: Dict[str, Dict[str, Any]] = {}
        cache_dir = str(
            _get(FUGUE_TPU_CONF_CACHE_DIR, "") or os.environ.get("FUGUE_TPU_CACHE_DIR", "")
        )
        if self.enabled and cache_dir:
            from ..constants import (
                FUGUE_TPU_CONF_DIST_HB_DIR,
                FUGUE_TPU_CONF_DIST_HB_STALE_S,
            )

            cap = int(_get(FUGUE_TPU_CONF_CACHE_DISK_BYTES, 4 * 1024 * 1024 * 1024))
            cap_entries = int(_get(FUGUE_TPU_CONF_CACHE_DISK_MAX_ENTRIES, 65536))
            try:
                store = ArtifactStore(
                    cache_dir,
                    cap,
                    log=log,
                    cap_entries=cap_entries,
                    hb_dir=str(_get(FUGUE_TPU_CONF_DIST_HB_DIR, "")) or None,
                    hb_stale_s=float(_get(FUGUE_TPU_CONF_DIST_HB_STALE_S, 3.0)),
                )
                probe = os.path.join(store.objs, f".probe_{_uuid.uuid4().hex}")
                with open(probe, "w") as f:
                    f.write("ok")
                os.remove(probe)
                self.disk = store
            except OSError as ex:
                # degrade to memory-only: ONE warning, never a crash
                if log is not None:
                    log.warning(
                        "fugue.tpu.cache.dir %r is not writable (%s); result "
                        "cache degrades to memory-only",
                        cache_dir,
                        ex,
                    )

    # -- read side -----------------------------------------------------------
    def contains(self, fp: str) -> Optional[str]:
        """Which tier could serve ``fp`` right now (no counters touched —
        the planner probes many times while computing the cut)."""
        if not self.enabled:
            return None
        if self.mem.contains(fp):
            return "mem"
        if self.disk is not None and self.disk.exists(fp):
            return "disk"
        return None

    def lookup(self, fp: str, engine: Any) -> Optional[Tuple[Any, str, int]]:
        """(frame, tier, bytes) or None. Disk hits are promoted into the
        memory tier so a hot fingerprint is served live next time."""
        self.stats.inc("lookups")
        if not self.enabled:
            self.stats.inc("misses")
            return None
        hit = self.mem.get(fp)
        if hit is not None:
            self.stats.inc("hits_mem")
            self.stats.inc("bytes_served", hit[1])
            return hit[0], "mem", hit[1]
        if self.disk is not None:
            loaded = self.disk.load(fp, engine)
            if loaded is not None:
                df, nbytes = loaded
                self.stats.inc("hits_disk")
                self.stats.inc("bytes_served", nbytes)
                self.stats.inc("evictions_mem", self.mem.put(fp, df, nbytes))
                return df, "disk", nbytes
        self.stats.inc("misses")
        return None

    # -- write side ----------------------------------------------------------
    def publish(
        self,
        fp: str,
        df: Any,
        engine: Any,
        schema: str,
        ref_path: Optional[str] = None,
    ) -> Dict[str, Any]:
        """Memory-insert always; disk-publish when a store is mounted and
        the frame fits the artifact cap. ``ref_path`` indexes an existing
        file (a permanent checkpoint) instead of writing a copy."""
        out: Dict[str, Any] = {"tier": "mem"}
        if not self.enabled:
            return out
        nbytes = estimate_df_bytes(df)
        self.stats.inc("evictions_mem", self.mem.put(fp, df, nbytes))
        if self.disk is None:
            return out
        try:
            if ref_path is not None and os.path.exists(ref_path):
                if self.disk.link(fp, ref_path, schema):
                    self.stats.inc("links")
                out["tier"] = "ref"
            elif nbytes <= self.max_artifact_bytes:
                written = self.disk.publish(fp, df, engine, schema)
                if written > 0:
                    self.stats.inc("publishes")
                    self.stats.inc("bytes_published", written)
                    self.stats.inc("evictions_disk", self.disk.evict_to_cap())
                out["tier"] = "disk"
                out["bytes"] = written
        except Exception as ex:  # publishing must never fail the run
            if self._log is not None:
                self._log.warning(
                    "result-cache publish of %s failed: %s", fp[:12], ex
                )
        return out

    # -- delta manifests -----------------------------------------------------
    def get_manifest(self, key: str) -> Optional[Dict[str, Any]]:
        """Freshest manifest for a delta key: the in-process copy when this
        engine published it, else the shared disk copy."""
        if not self.enabled or not self.delta_enabled:
            return None
        with self._manifest_lock:
            m = self._mem_manifests.get(key)
        if m is not None:
            return m
        if self.disk is not None:
            return self.disk.load_manifest(key)
        return None

    def put_manifest(self, key: str, payload: Dict[str, Any]) -> None:
        if not self.enabled:
            return
        with self._manifest_lock:
            self._mem_manifests[key] = payload
        if self.disk is not None:
            try:
                self.disk.publish_manifest(key, payload)
            except Exception as ex:  # publishing must never fail the run
                if self._log is not None:
                    self._log.warning(
                        "delta manifest publish of %s failed: %s", key[:12], ex
                    )
        self.stats.inc("manifest_publishes")

    def drop_manifest(self, key: str) -> None:
        """A stale manifest (evicted/changed artifacts) invalidates ONLY
        itself — the rest of the cache stays serviceable."""
        with self._manifest_lock:
            self._mem_manifests.pop(key, None)
        if self.disk is not None:
            self.disk.remove_manifest(key)

    def clear(self) -> None:
        self.mem.clear()
        with self._manifest_lock:
            self._mem_manifests.clear()
        if self.disk is not None:
            self.disk.clear()


def estimate_df_bytes(df: Any) -> int:
    """Byte size of a live frame for LRU accounting (best effort)."""
    try:
        from ..jax.dataframe import JaxDataFrame

        if isinstance(df, JaxDataFrame):
            return df.device_nbytes
    except Exception:
        pass
    try:
        import pandas as pd
        import pyarrow as pa

        native = getattr(df, "native", None)
        if isinstance(native, pa.Table):
            return int(native.nbytes)
        if isinstance(native, pd.DataFrame):
            return int(native.memory_usage(index=False, deep=False).sum())
        if isinstance(native, list):
            return len(native) * max(1, len(df.schema)) * 16
    except Exception:
        pass
    try:
        return int(df.count()) * max(1, len(df.schema)) * 16
    except Exception:
        return 0


def _path_bytes(path: str) -> int:
    try:
        if os.path.isdir(path):
            total = 0
            for root, _d, names in os.walk(path):
                for n in names:
                    total += os.path.getsize(os.path.join(root, n))
            return total
        return os.path.getsize(path)
    except OSError:
        return 0


def clean_cache_dir(path: str) -> str:
    """``make cache-clean``: wipe a result-cache directory's artifacts."""
    if not path:
        return (
            "no cache dir given (set FUGUE_TPU_CACHE_DIR or pass a path); "
            "nothing cleaned"
        )
    objs = os.path.join(path, "objs")
    if not os.path.isdir(objs):
        return f"{path} holds no result-cache artifacts; nothing cleaned"
    n = len([f for f in os.listdir(objs) if not f.startswith(".")])
    shutil.rmtree(objs, ignore_errors=True)
    manifests = os.path.join(path, "manifests")
    if os.path.isdir(manifests):
        n += len([f for f in os.listdir(manifests) if not f.startswith(".")])
        shutil.rmtree(manifests, ignore_errors=True)
    return f"removed {n} artifact file(s) from {objs}"
