"""Canonical plan fingerprints — the content-address of a task's output.

A fingerprint is a recursive md5 over the POST-optimization task DAG:
verb kind + normalized params + UDF source/bytecode/closure hash +
(inferred) output schema + the fingerprints of every input. Two runs —
in different processes, days apart — that would compute the same bytes
produce the same fingerprint, which is what lets the result cache
(``fugue_tpu/cache/store.py``) serve one run's output to another.

Soundness over coverage: anything whose identity can't be captured
statically REFUSES to fingerprint (``None``) and poisons its whole
consumer subtree — a refused node is a cache miss, never a wrong hit.
The refusal rules (also in ``docs/cache.md``):

- **Load** sources fingerprint as (path, size, mtime_ns) per matched
  file; a missing path refuses.
- **CreateData** fingerprints small re-readable tables by CONTENT
  (pandas / arrow / fugue bounded local frames up to
  ``fugue.tpu.cache.fingerprint_max_bytes``); device frames, one-pass
  streams, yielded handles and oversized tables refuse — identity of
  the object is never used as a stand-in for identity of the data.
- **UDFs** hash their source (fallback: bytecode), default args and
  closure cells; a UDF marked with :func:`non_deterministic`, or one
  using an RPC ``callback``, refuses.
- **Extensions** outside ``fugue_tpu.*`` hash their class source; a
  param whose only representation is an ``at 0x…`` repr refuses.
- **Sample** without an explicit seed refuses; **SaveAndUse** (a raw
  side effect) refuses; output sinks are never fingerprinted.
- **Custom creators** (anything that is not Load/CreateData) refuse:
  they read the outside world — files, services, RNGs — and nothing in
  the plan captures that input's content.
"""

import glob as _glob
import inspect
import os
import textwrap
from hashlib import md5
from typing import Any, Dict, List, Optional

from .._utils.hash import to_uuid
from ..workflow._tasks import CreateTask, FugueTask, OutputTask

__all__ = [
    "FingerprintReport",
    "fingerprint_tasks",
    "non_deterministic",
    "FP_VERSION",
]

# bump to invalidate every existing cache entry on a semantic change to
# the engine or the fingerprint algorithm itself
# v2: fused/segment tasks classify under their own kinds (K_FUSED /
# K_SEGMENT instead of opaque) so delta keys can see chain structure
FP_VERSION = "fugue-tpu-cache-v2"

_NON_DETERMINISTIC_ATTR = "__fugue_non_deterministic__"


def non_deterministic(func: Any) -> Any:
    """Mark a UDF (or extension class) as non-deterministic: the result
    cache will never memoize any task that uses it, nor anything
    downstream of such a task."""
    setattr(func, _NON_DETERMINISTIC_ATTR, True)
    return func


class _Refused(Exception):
    """Internal control flow: this node can't be fingerprinted."""

    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


class FingerprintReport:
    """Per-task fingerprints of one (post-optimization) task list.

    ``fps[id(task)]`` is the fingerprint string or ``None`` (refused /
    poisoned); ``reasons`` explains every ``None``; ``source_bytes``
    records the producer-side bytes behind Load/CreateData tasks so the
    planner can report how much a cache cut skipped."""

    def __init__(self) -> None:
        self.fps: Dict[int, Optional[str]] = {}
        self.reasons: Dict[int, str] = {}
        self.source_bytes: Dict[int, int] = {}
        # delta keys: the same recursive hash with every Load's per-file
        # list replaced by its PATH — the identity of "this chain over
        # this source, whatever files it currently holds". Keys the
        # partition manifests of fugue_tpu/cache/delta.py; None wherever
        # the full fingerprint refused.
        self.delta_fps: Dict[int, Optional[str]] = {}

    def fp(self, task: FugueTask) -> Optional[str]:
        return self.fps.get(id(task))

    def delta_fp(self, task: FugueTask) -> Optional[str]:
        return self.delta_fps.get(id(task))


def fingerprint_tasks(
    tasks: List[FugueTask], conf: Any, engine_kind: str
) -> FingerprintReport:
    """Fingerprint every task of a (post-optimization) DAG in one topo
    pass. ``engine_kind`` partitions the cache per engine class — two
    engines may produce dtype-different results for the same plan, so
    they never share entries. Never raises: refusal is a value."""
    from ..plan.ir import build_graph, infer_schemas

    salt = ""
    max_bytes = 64 * 1024 * 1024
    try:
        from ..constants import (
            FUGUE_CONF_DEFAULT_PARTITIONS,
            FUGUE_TPU_CONF_CACHE_FINGERPRINT_MAX_BYTES,
            FUGUE_TPU_CONF_CACHE_SALT,
        )

        # fugue.default.partitions changes the physical chunking an
        # UN-keyed transformer sees (per-partition UDF semantics), so it
        # is part of every fingerprint
        salt = to_uuid(
            str(conf.get(FUGUE_TPU_CONF_CACHE_SALT, "")),
            str(conf.get(FUGUE_CONF_DEFAULT_PARTITIONS, -1)),
        )
        max_bytes = int(
            conf.get(FUGUE_TPU_CONF_CACHE_FINGERPRINT_MAX_BYTES, max_bytes)
        )
    except Exception:
        pass
    rep = FingerprintReport()
    nodes = build_graph(tasks)
    schemas = infer_schemas(nodes)
    for node in nodes:
        task = node.task
        if task is None:  # synthesized nodes never appear post-emit
            continue
        if isinstance(task, OutputTask):
            rep.fps[id(task)] = None
            rep.reasons[id(task)] = "output sink (side effects run every time)"
            continue
        in_fps = [rep.fps.get(id(d)) for d in task.inputs]
        if any(f is None for f in in_fps):
            rep.fps[id(task)] = None
            rep.reasons[id(task)] = "poisoned by unfingerprintable input"
            continue
        in_delta = [rep.delta_fps.get(id(d)) for d in task.inputs]
        try:
            fp, dfp = _task_fp(
                task,
                node.kind,
                in_fps,  # type: ignore[arg-type]
                None if any(f is None for f in in_delta) else in_delta,
                schemas.get(id(node)),
                salt,
                engine_kind,
                max_bytes,
                rep,
            )
            rep.fps[id(task)] = fp
            rep.delta_fps[id(task)] = dfp
        except _Refused as r:
            rep.fps[id(task)] = None
            rep.reasons[id(task)] = r.reason
        except Exception as ex:  # fingerprinting must never fail a run
            rep.fps[id(task)] = None
            rep.reasons[id(task)] = f"fingerprint error: {type(ex).__name__}"
    return rep


# ---------------------------------------------------------------------------
# per-task fingerprint
# ---------------------------------------------------------------------------


def _task_fp(
    task: FugueTask,
    kind: str,
    in_fps: List[str],
    in_delta_fps: Optional[List[str]],
    schema_names: Optional[List[str]],
    salt: str,
    engine_kind: str,
    max_bytes: int,
    rep: FingerprintReport,
) -> "Any":
    """(full fingerprint, delta key or None). The delta key differs from
    the full fingerprint in exactly one way: every Load source hashes by
    its PATH instead of its per-file (path, size, mtime) list, and inputs
    chain delta keys instead of full fingerprints — so a grown directory
    keeps its delta key while its full fingerprint changes."""
    from ..extensions._builtins import creators as bc
    from ..plan.ir import K_SAMPLE
    from ..plan.passes import _PrunedCreator

    ext = task.extension
    wrapper_cols: Optional[List[str]] = None
    if isinstance(ext, _PrunedCreator):
        # the column-pruning pass wraps the creator; the pruned column
        # list is part of the output's identity (a [k,v] projection and a
        # [s,v] projection of the same table are different results)
        wrapper_cols = list(ext.pruned_columns)
        ext = ext._inner
    if getattr(ext, _NON_DETERMINISTIC_ATTR, False) or getattr(
        type(ext), _NON_DETERMINISTIC_ATTR, False
    ):
        raise _Refused("extension marked non-deterministic")
    common: List[Any] = [
        FP_VERSION,
        engine_kind,
        salt,
        type(task).__name__,
        kind,
        wrapper_cols,
        task.partition_spec,
        schema_names,
        _extension_fp(ext),
    ]
    parts: List[Any] = list(common) + [in_fps]
    delta_parts: Optional[List[Any]] = (
        None if in_delta_fps is None else list(common) + [in_delta_fps]
    )

    def both(token: Any) -> None:
        parts.append(token)
        if delta_parts is not None:
            delta_parts.append(token)

    if isinstance(task, CreateTask):
        if isinstance(ext, bc.Load):
            parts.append(_load_fp(task, rep))
            if delta_parts is not None:
                delta_parts.append(
                    ("delta-source", task.params.get_or_none("path", object))
                )
            both(_params_fp(task, max_bytes, skip=("path",)))
        elif isinstance(ext, bc.CreateData):
            data = task.params.get_or_none("data", object)
            digest, nbytes = _data_fp(data, max_bytes)
            rep.source_bytes[id(task)] = nbytes
            both(digest)
            both(_params_fp(task, max_bytes, skip=("data",)))
        else:
            # arbitrary creators read the OUTSIDE WORLD (files, services,
            # RNGs) — Load and CreateData are the content-addressable
            # creation paths; everything else refuses by design
            raise _Refused(
                f"opaque creator {type(ext).__name__} (external input is "
                "not content-addressable)"
            )
    elif kind == K_SAMPLE:
        if task.params.get_or_none("seed", int) is None:
            raise _Refused("sample without an explicit seed")
        both(_params_fp(task, max_bytes))
    else:
        from ..extensions._builtins import processors as bp

        if isinstance(ext, bp.SaveAndUse):
            raise _Refused("save_and_use writes storage (raw side effect)")
        if isinstance(ext, bp.RunTransformer):
            if task.params.get_or_none("callback", object) is not None:
                raise _Refused("transformer uses an RPC callback")
            both(_udf_fp(task.params.get_or_throw("transformer", object)))
            both(_params_fp(task, max_bytes, skip=("transformer", "callback")))
        else:
            both(_params_fp(task, max_bytes))
    h = md5()
    _feed_safe(h, parts, max_bytes)
    dfp: Optional[str] = None
    if delta_parts is not None:
        dh = md5()
        _feed_safe(dh, ["delta"] + delta_parts, max_bytes)
        dfp = dh.hexdigest()
    return h.hexdigest(), dfp


def _extension_fp(ext: Any) -> str:
    """Identity of the extension CODE plus its instance state (via
    ``__uuid__`` where defined). In-tree extensions are versioned by
    FP_VERSION + class path; anything else hashes its class source so an
    edited user extension invalidates its entries."""
    cls = type(ext)
    base = f"{cls.__module__}.{cls.__qualname__}"
    inst = ""
    if hasattr(cls, "__uuid__"):
        try:
            inst = ext.__uuid__()
        except Exception:
            inst = ""
    if cls.__module__.split(".")[0] in ("fugue_tpu",):
        return to_uuid(base, inst)
    return to_uuid(base, inst, _source_hash_of(cls))


# ---------------------------------------------------------------------------
# sources: Load files and CreateData content
# ---------------------------------------------------------------------------


def _load_fp(task: FugueTask, rep: FingerprintReport) -> List[Any]:
    path = task.params.get_or_none("path", object)
    if not isinstance(path, str) or path == "":
        raise _Refused("load path is not a plain string")
    files: List[str] = []
    if _glob.has_magic(path):
        files = sorted(_glob.glob(path))
    elif os.path.isdir(path):
        for root, _dirs, names in os.walk(path):
            files.extend(os.path.join(root, n) for n in sorted(names))
        files.sort()
    elif os.path.exists(path):
        files = [path]
    if len(files) == 0:
        raise _Refused(f"load source {path} does not exist (yet)")
    out: List[Any] = []
    total = 0
    for f in files:
        st = os.stat(f)
        total += int(st.st_size)
        out.append((f, int(st.st_size), int(st.st_mtime_ns)))
    rep.source_bytes[id(task)] = total
    return out


def _data_fp(data: Any, max_bytes: int) -> Any:
    """Content digest of a CreateData payload, or refusal. Only types
    that can be re-read without consuming them are hashed — identity of
    a one-pass stream or a device frame is NOT identity of its data."""
    import pandas as pd
    import pyarrow as pa

    from ..collections.yielded import Yielded
    from ..dataframe import DataFrame
    from ..dataframe.array_dataframe import ArrayDataFrame
    from ..dataframe.arrow_dataframe import ArrowDataFrame
    from ..dataframe.pandas_dataframe import PandasDataFrame

    if data is None:
        return ("none", 0)
    if isinstance(data, Yielded):
        raise _Refused("yielded handle (depends on another run)")
    if isinstance(data, pa.Table):
        return _arrow_fp(data, max_bytes)
    if isinstance(data, pd.DataFrame):
        return _pandas_fp(data, max_bytes)
    if isinstance(data, DataFrame):
        if data.is_local and not data.is_bounded:
            raise _Refused("one-pass stream input (hashing would consume it)")
        if isinstance(data, ArrowDataFrame):
            return _arrow_fp(data.native, max_bytes)
        if isinstance(data, PandasDataFrame):
            return _pandas_fp(data.native, max_bytes)
        if isinstance(data, ArrayDataFrame):
            return (
                to_uuid(str(data.schema), data.native),
                len(data.native) * max(1, len(data.schema)) * 16,
            )
        raise _Refused(
            f"{type(data).__name__} input (no content digest; identity-of-"
            "object is refused)"
        )
    if isinstance(data, (list, tuple)):
        return (to_uuid(data), len(data) * 16)
    raise _Refused(f"{type(data).__name__} create input")


def _arrow_fp(tbl: "Any", max_bytes: int) -> Any:
    nbytes = int(tbl.nbytes)
    if nbytes > max_bytes:
        raise _Refused(
            f"table of {nbytes} bytes exceeds fingerprint_max_bytes={max_bytes}"
        )
    h = md5()
    h.update(str(tbl.schema).encode())
    h.update(str(tbl.num_rows).encode())
    for col in tbl.columns:
        for chunk in col.chunks:
            # a sliced chunk shares its parent's buffers: offset+length
            # make the digest position-aware (worst case a spurious miss,
            # never a false hit)
            h.update(f"|{chunk.offset}:{len(chunk)}".encode())
            for buf in chunk.buffers():
                if buf is not None:
                    h.update(buf)
    return ("arrow", h.hexdigest()), nbytes


def _pandas_fp(pdf: "Any", max_bytes: int) -> Any:
    import pandas as pd

    nbytes = int(pdf.memory_usage(index=False, deep=False).sum())
    if nbytes > max_bytes:
        raise _Refused(
            f"frame of {nbytes} bytes exceeds fingerprint_max_bytes={max_bytes}"
        )
    h = md5()
    h.update(("|".join(str(c) for c in pdf.columns)).encode())
    h.update(("|".join(str(t) for t in pdf.dtypes)).encode())
    try:
        hashed = pd.util.hash_pandas_object(pdf, index=False)
        h.update(hashed.values.tobytes())
    except Exception:
        raise _Refused("pandas content not hashable")
    return ("pandas", h.hexdigest()), nbytes


# ---------------------------------------------------------------------------
# UDFs and generic params
# ---------------------------------------------------------------------------

_SOURCE_HASH_CACHE: Dict[Any, str] = {}


def _source_hash_of(obj: Any) -> str:
    """Hash of an object's SOURCE (dedented, so moving a function doesn't
    invalidate), falling back to bytecode + consts for callables defined
    in a REPL/exec. The task-uuid layer hashes module+qualname only —
    stable across edits — so this is what makes an EDITED udf miss."""
    key = obj if isinstance(obj, type) else getattr(obj, "__code__", obj)
    try:
        cached = _SOURCE_HASH_CACHE.get(key)
        if cached is not None:
            return cached
    except TypeError:  # unhashable key
        key = None
    try:
        src = textwrap.dedent(inspect.getsource(obj))
        out = md5(src.encode()).hexdigest()
    except Exception:
        code = getattr(obj, "__code__", None)
        if code is None:
            raise _Refused(f"no source or bytecode for {obj!r}")
        out = md5(
            code.co_code + repr(code.co_consts).encode() + repr(code.co_names).encode()
        ).hexdigest()
    if key is not None:
        _SOURCE_HASH_CACHE[key] = out
    return out


def _callable_fp(func: Any) -> str:
    """Source + defaults + closure-cell contents: two factory-made UDFs
    sharing source but closing over different values must differ."""
    if getattr(func, _NON_DETERMINISTIC_ATTR, False):
        raise _Refused(f"{getattr(func, '__name__', func)!r} marked non-deterministic")
    parts: List[Any] = [_source_hash_of(func)]
    defaults = getattr(func, "__defaults__", None)
    if defaults:
        parts.append([_value_token(v, 0) for v in defaults])
    closure = getattr(func, "__closure__", None)
    if closure:
        parts.append([_value_token(c.cell_contents, 0) for c in closure])
    return to_uuid(parts)


def _udf_fp(tf: Any) -> str:
    """Transformer identity: its declared uuid (schema arg + wiring) AND
    the actual code behind it."""
    func = getattr(getattr(tf, "_wrapper", None), "_func", None)
    if func is not None and getattr(func, _NON_DETERMINISTIC_ATTR, False):
        raise _Refused("transformer function marked non-deterministic")
    parts: List[Any] = []
    try:
        parts.append(tf.__uuid__())
    except Exception:
        parts.append(f"{type(tf).__module__}.{type(tf).__qualname__}")
    if func is not None:
        parts.append(_callable_fp(func))
    else:
        parts.append(_source_hash_of(type(tf)))
    return to_uuid(parts)


def _value_token(v: Any, depth: int) -> Any:
    """A deterministic token for one param value, or a refusal. The
    default-object ``… at 0x…`` repr is the tell that a value has no
    stable representation."""
    import pandas as pd
    import pyarrow as pa

    if depth > 6:
        raise _Refused("param nesting too deep")
    if v is None or isinstance(v, (bool, int, float, str, bytes)):
        return v
    if isinstance(v, (pd.DataFrame, pa.Table)):
        return _data_fp(v, 64 * 1024 * 1024)
    if isinstance(v, dict):
        return {str(k): _value_token(x, depth + 1) for k, x in v.items()}
    if isinstance(v, (list, tuple, set, frozenset)):
        items = list(v)
        if isinstance(v, (set, frozenset)):
            items = sorted(items, key=repr)
        return [_value_token(x, depth + 1) for x in items]
    if hasattr(v, "__uuid__"):
        return v.__uuid__()
    if inspect.isclass(v):
        return f"{v.__module__}.{v.__qualname__}"
    if callable(v):
        return _callable_fp(v)
    r = repr(v)
    if " at 0x" in r:
        raise _Refused(f"param {type(v).__name__} has no stable identity")
    return r


def _params_fp(task: FugueTask, max_bytes: int, skip: Any = ()) -> Any:
    out: Dict[str, Any] = {}
    for k, v in task.params.items():
        if k in skip:
            continue
        out[str(k)] = _value_token(v, 0)
    return out


def _feed_safe(h: Any, obj: Any, max_bytes: int) -> None:
    """Feed the (already-tokenized) component list into the digest via
    the deterministic ``to_uuid`` encoding."""
    h.update(to_uuid(obj).encode())
