"""Content-addressed result cache: cross-run memoization keyed on
canonical plan fingerprints (docs/cache.md).

- :mod:`~fugue_tpu.cache.fingerprint` — canonical recursive hash per
  post-optimization plan node; refusal (poisoning) over guessing.
- :mod:`~fugue_tpu.cache.store` — in-process byte-budgeted LRU over live
  frames backed by an on-disk parquet artifact store.
- :mod:`~fugue_tpu.cache.planner` — cuts the DAG at the deepest cached
  frontier so upstream producers are never executed.
- :mod:`~fugue_tpu.cache.delta` — partition-level incremental recompute:
  a warm run over a GROWN Load source recomputes only the new partitions
  and merges with the cached result / partial accumulator.
"""

from .delta import (
    DeltaHit,
    DeltaTemplate,
    build_delta_templates,
    execute_delta,
    match_manifest,
)
from .fingerprint import (
    FP_VERSION,
    FingerprintReport,
    fingerprint_tasks,
    non_deterministic,
)
from .planner import CachePlan, describe_cache, plan_cache
from .store import (
    ArtifactStore,
    CacheStats,
    MemoryLRU,
    ResultCache,
    clean_cache_dir,
    estimate_df_bytes,
)

__all__ = [
    "FP_VERSION",
    "FingerprintReport",
    "fingerprint_tasks",
    "non_deterministic",
    "CachePlan",
    "plan_cache",
    "describe_cache",
    "ArtifactStore",
    "CacheStats",
    "MemoryLRU",
    "ResultCache",
    "clean_cache_dir",
    "estimate_df_bytes",
    "DeltaHit",
    "DeltaTemplate",
    "build_delta_templates",
    "match_manifest",
    "execute_delta",
]
