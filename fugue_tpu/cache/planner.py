"""Cache-aware plan cut: decide, per run, which tasks are served from the
result cache and which upstream tasks are therefore never executed.

Reverse-topological walk over the POST-optimization task list:

- roots (output sinks, pinned tasks — checkpoints/yields/broadcasts —
  and dangling results) are always *needed*;
- a needed task that the cache (or an existing deterministic
  StrongCheckpoint) can resolve becomes a **frontier hit**: its result
  is loaded, its inputs are NOT marked needed;
- a needed task with no hit executes and marks its inputs needed;
- everything never marked needed is **skipped entirely** — not decoded,
  not transferred, no ``workflow.task`` span.

Frontier loads happen eagerly at plan time: a torn artifact or an
eviction race turns that task back into a miss and the cut is recomputed
(the load failure propagates need upstream, which may itself hit). So by
the time the graph runs, every hit already holds its frame.
"""

from typing import Any, Dict, List, Optional, Set

from ..workflow._checkpoint import StrongCheckpoint
from ..workflow._tasks import FugueTask, OutputTask

__all__ = ["CachePlan", "plan_cache", "describe_cache"]


class CachePlan:
    """One run's cut: what hits, what executes, what is skipped."""

    def __init__(self, fpr: Any) -> None:
        self.fpr = fpr  # FingerprintReport
        self.hits: Dict[int, Any] = {}  # id(task) -> loaded DataFrame
        self.hit_tier: Dict[int, str] = {}
        self.checkpoint_hits: Set[int] = set()
        self.skipped: Set[int] = set()
        self.executes: Set[int] = set()
        self.bytes_skipped = 0
        # partition-level delta recompute (fugue_tpu/cache/delta.py):
        # tasks served as cached-partitions + fresh-partitions merges
        self.delta_hits: Dict[int, Any] = {}  # id(task) -> DeltaHit
        self.delta_templates: Dict[int, Any] = {}
        self.delta_reasons: Dict[int, str] = {}

    def fp(self, task: FugueTask) -> Optional[str]:
        return self.fpr.fp(task)

    def summary(self) -> Dict[str, int]:
        return {
            "hits": len(self.hits),
            "checkpoint_hits": len(self.checkpoint_hits),
            "skipped": len(self.skipped),
            "executes": len(self.executes),
            "bytes_skipped": self.bytes_skipped,
            "delta_hits": len(self.delta_hits),
            "delta_partitions": sum(
                h.matched_parts for h in self.delta_hits.values()
            ),
            "bytes_skipped_delta": sum(
                h.bytes_matched for h in self.delta_hits.values()
            ),
        }


def _checkpoint_available(task: FugueTask, checkpoint_path: Any) -> bool:
    """Whether the task's own deterministic StrongCheckpoint can replay it
    without inputs (the existing ``_run_task_once`` branch serves it; the
    planner only uses this to skip its ancestors)."""
    cp = task.checkpoint
    if not isinstance(cp, StrongCheckpoint) or not cp.deterministic:
        return False
    try:
        return cp.exists(checkpoint_path, task.__uuid__())
    except Exception:
        return False


def _compute_cut(
    tasks: List[FugueTask],
    available: Any,
    checkpoint_path: Any,
) -> Dict[str, Any]:
    """One reverse-topo pass; ``available(task) -> Optional[str]`` says
    which cache tier could currently resolve the task."""
    from ..plan.ir import task_pinned

    consumers: Dict[int, int] = {}
    for t in tasks:
        for d in t.inputs:
            consumers[id(d)] = consumers.get(id(d), 0) + 1
    needed: Set[int] = set()
    hits: Dict[int, str] = {}
    cp_hits: Set[int] = set()
    executes: Set[int] = set()
    skipped: List[FugueTask] = []
    for t in reversed(tasks):
        is_root = (
            isinstance(t, OutputTask)
            or task_pinned(t)
            or consumers.get(id(t), 0) == 0
        )
        if not (is_root or id(t) in needed):
            skipped.append(t)
            continue
        if not isinstance(t, OutputTask):
            if _checkpoint_available(t, checkpoint_path):
                cp_hits.add(id(t))
                continue  # replay branch needs no inputs
            tier = available(t)
            if tier is not None:
                hits[id(t)] = tier
                continue  # the cache needs no inputs either
        executes.add(id(t))
        for d in t.inputs:
            needed.add(id(d))
    return {
        "hits": hits,
        "cp_hits": cp_hits,
        "executes": executes,
        "skipped": skipped,
    }


def plan_cache(
    tasks: List[FugueTask],
    engine: Any,
    cache: Any,
    checkpoint_path: Any,
) -> CachePlan:
    """Fingerprint, cut, and eagerly load the frontier. Emits one
    ``cache.lookup`` span per frontier decision (hit or miss) so a warm
    run's trace shows exactly where the plan was cut."""
    from ..obs import get_tracer
    from .delta import _DeltaRefused, build_delta_templates, match_manifest
    from .fingerprint import fingerprint_tasks

    fpr = fingerprint_tasks(tasks, engine.conf, type(engine).__name__)
    plan = CachePlan(fpr)
    tracer = get_tracer()
    blacklist: Set[str] = set()
    looked_up: Set[int] = set()
    delta_on = cache.enabled and cache.delta_enabled
    if delta_on:
        plan.delta_templates, plan.delta_reasons = build_delta_templates(
            tasks, fpr
        )
    delta_offers: Dict[int, Any] = {}
    delta_blacklist: Set[int] = set()

    def available(task: FugueTask) -> Optional[str]:
        fp = fpr.fp(task)
        if fp is None or fp in blacklist:
            return None
        tier = cache.contains(fp)
        if tier is not None:
            return tier
        if id(task) in delta_offers:
            return "delta"
        if delta_on and id(task) not in delta_blacklist:
            tpl = plan.delta_templates.get(id(task))
            if tpl is not None:
                try:
                    delta_offers[id(task)] = match_manifest(tpl, cache)
                    return "delta"
                except _DeltaRefused as r:
                    plan.delta_reasons[id(task)] = r.reason
                    delta_blacklist.add(id(task))
                    if r.had_manifest:
                        cache.stats.inc("delta_refusals")
        return None

    # the eager-load loop: a frontier load that fails (eviction race,
    # torn artifact) blacklists that fingerprint and recomputes the cut
    for _ in range(len(tasks) + 1):
        cut = _compute_cut(tasks, available, checkpoint_path)
        retry = False
        for t in tasks:
            if id(t) not in cut["hits"]:
                continue
            if cut["hits"][id(t)] == "delta":
                if id(t) in plan.delta_hits:
                    continue
                hit = delta_offers[id(t)]
                looked_up.add(id(t))
                with tracer.span(
                    "cache.lookup",
                    cat="cache",
                    task=t.name or type(t.extension).__name__,
                    fp=(fpr.fp(t) or "")[:12],
                ) as sp:
                    frames = []
                    for afp in hit.artifact_fps:
                        loaded = cache.lookup(afp, engine)
                        if loaded is None:
                            break
                        frames.append(loaded[0])
                    if len(frames) != len(hit.artifact_fps):
                        # an artifact evaporated under us: this manifest is
                        # stale — invalidate it alone and recut without it
                        cache.drop_manifest(hit.template.delta_key)
                        delta_offers.pop(id(t), None)
                        delta_blacklist.add(id(t))
                        plan.delta_reasons[id(t)] = (
                            "cached partition artifact evicted (manifest "
                            "entry invalidated)"
                        )
                        sp.set(outcome="delta_miss")
                        retry = True
                        break
                    hit.cached_frames = frames
                    plan.delta_hits[id(t)] = hit
                    sp.set(
                        outcome="delta",
                        partitions=f"{hit.matched_parts}/{hit.total_parts}",
                        bytes_skipped=hit.bytes_matched,
                    )
                continue
            if id(t) in plan.hits:
                continue
            fp = fpr.fp(t)
            looked_up.add(id(t))
            with tracer.span(
                "cache.lookup",
                cat="cache",
                task=t.name or type(t.extension).__name__,
                fp=(fp or "")[:12],
            ) as sp:
                loaded = cache.lookup(fp, engine)
                if loaded is None:
                    blacklist.add(fp)  # type: ignore[arg-type]
                    sp.set(outcome="miss")
                    retry = True
                    break
                df, tier, nbytes = loaded
                plan.hits[id(t)] = df
                plan.hit_tier[id(t)] = tier
                sp.set(outcome="hit", tier=tier, bytes=nbytes)
        if not retry:
            break
    # drop hits that a later recut decided not to use after all (their
    # consumer's load failed and the consumer now executes: the hit frame
    # is still valid and stays — it feeds the consumer directly)
    plan.checkpoint_hits = cut["cp_hits"]
    plan.executes = cut["executes"]
    # a delta hit that a recut no longer uses must not keep its frames
    plan.delta_hits = {
        i: h for i, h in plan.delta_hits.items() if cut["hits"].get(i) == "delta"
    }
    for i in plan.delta_hits:
        plan.hit_tier[i] = "delta"
    for t in cut["skipped"]:
        plan.skipped.add(id(t))
        plan.bytes_skipped += fpr.source_bytes.get(id(t), 0)
    # the Load under a delta hit is "skipped" but its NEW partitions are
    # re-read — count only the bytes the delta actually avoids
    for h in plan.delta_hits.values():
        if id(h.template.load_task) in plan.skipped:
            plan.bytes_skipped = max(0, plan.bytes_skipped - h.bytes_fresh)
    # misses among tasks that will execute but were fingerprintable:
    # count them so hit-rate math works without a lookup side effect
    for t in tasks:
        if (
            id(t) in plan.executes
            and id(t) not in looked_up
            and fpr.fp(t) is not None
        ):
            cache.stats.inc("misses")
            cache.stats.inc("lookups")
        if fpr.fp(t) is None and not isinstance(t, OutputTask):
            cache.stats.inc("refusals")
    for h in plan.delta_hits.values():
        cache.stats.inc("partial_hits")
        cache.stats.inc("delta_partitions", h.matched_parts)
        cache.stats.inc(
            "delta_partitions_fresh", max(1, len(h.new_files))
        )
        cache.stats.inc("bytes_skipped_delta", h.bytes_matched)
    cache.stats.inc("tasks_skipped", len(plan.skipped))
    cache.stats.inc("bytes_skipped", plan.bytes_skipped)
    return plan


def describe_cache(
    tasks: List[FugueTask],
    conf: Any,
    cache: Any = None,
    checkpoint_path: Any = None,
    engine_kind: str = "any",
) -> List[str]:
    """Render the would-be cut for ``workflow.explain()`` (dry run: probes
    ``contains`` only, loads nothing, counts nothing). Fingerprints are
    engine-partitioned, so hit/miss is only accurate when ``engine_kind``
    names the engine class the run will use."""
    from ..constants import FUGUE_TPU_CONF_CACHE_ENABLED
    from .fingerprint import fingerprint_tasks
    from .store import ResultCache

    try:
        enabled = bool(conf.get(FUGUE_TPU_CONF_CACHE_ENABLED, True))
    except Exception:
        enabled = True
    if not enabled:
        return ["== result cache disabled (fugue.tpu.cache.enabled=false) =="]
    if cache is None:
        cache = ResultCache(conf)
    fpr = fingerprint_tasks(tasks, conf, engine_kind)
    from .delta import _DeltaRefused, build_delta_templates, match_manifest

    delta_on = cache.enabled and cache.delta_enabled
    templates: Dict[int, Any] = {}
    delta_reasons: Dict[int, str] = {}
    if delta_on:
        templates, delta_reasons = build_delta_templates(tasks, fpr)
    delta_offers: Dict[int, Any] = {}

    def available(task: FugueTask) -> Optional[str]:
        fp = fpr.fp(task)
        if fp is None:
            return None
        tier = cache.contains(fp)
        if tier is not None:
            return tier
        if id(task) in delta_offers:
            return "delta"
        tpl = templates.get(id(task))
        if tpl is not None and id(task) not in delta_reasons:
            try:
                # dry run: probe only, never repair/delete stale manifests
                delta_offers[id(task)] = match_manifest(tpl, cache, repair=False)
                return "delta"
            except _DeltaRefused as r:
                delta_reasons[id(task)] = r.reason
        return None

    cut = _compute_cut(tasks, available, checkpoint_path)
    skipped_ids = {id(t) for t in cut["skipped"]}
    bytes_skipped = sum(fpr.source_bytes.get(i, 0) for i in skipped_ids)
    scope = "" if engine_kind == "any" else f" for {engine_kind}"
    lines = [
        "== result cache%s (cut: %d hit, %d checkpoint, %d skipped upstream, "
        "~%d source bytes never read) =="
        % (scope, len(cut["hits"]), len(cut["cp_hits"]), len(skipped_ids), bytes_skipped)
    ]
    for i, t in enumerate(tasks):
        fp = fpr.fp(t)
        if id(t) in cut["hits"]:
            if cut["hits"][id(t)] == "delta":
                h = delta_offers[id(t)]
                status = (
                    f"DELTA[{h.matched_parts}/{h.total_parts} partitions] "
                    f"{h.template.delta_key[:12]} (~{h.bytes_matched} source "
                    "bytes served from cache; only new partitions recompute)"
                )
            else:
                status = f"HIT[{cut['hits'][id(t)]}] {fp[:12]}"
        elif id(t) in cut["cp_hits"]:
            status = "checkpoint replay"
        elif id(t) in skipped_ids:
            status = "skipped (downstream hit cuts the plan here)"
        elif fp is None:
            status = "uncacheable: " + fpr.reasons.get(id(t), "?")
        else:
            status = f"miss {fp[:12]}"
            why = delta_reasons.get(id(t))
            if why is not None and delta_on:
                status += f" (delta: {why})"
        lines.append(f"  t{i}: {type(t.extension).__name__} -- {status}")
    return lines
