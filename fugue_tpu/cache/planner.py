"""Cache-aware plan cut: decide, per run, which tasks are served from the
result cache and which upstream tasks are therefore never executed.

Reverse-topological walk over the POST-optimization task list:

- roots (output sinks, pinned tasks — checkpoints/yields/broadcasts —
  and dangling results) are always *needed*;
- a needed task that the cache (or an existing deterministic
  StrongCheckpoint) can resolve becomes a **frontier hit**: its result
  is loaded, its inputs are NOT marked needed;
- a needed task with no hit executes and marks its inputs needed;
- everything never marked needed is **skipped entirely** — not decoded,
  not transferred, no ``workflow.task`` span.

Frontier loads happen eagerly at plan time: a torn artifact or an
eviction race turns that task back into a miss and the cut is recomputed
(the load failure propagates need upstream, which may itself hit). So by
the time the graph runs, every hit already holds its frame.
"""

from typing import Any, Dict, List, Optional, Set

from ..workflow._checkpoint import StrongCheckpoint
from ..workflow._tasks import FugueTask, OutputTask

__all__ = ["CachePlan", "plan_cache", "describe_cache"]


class CachePlan:
    """One run's cut: what hits, what executes, what is skipped."""

    def __init__(self, fpr: Any) -> None:
        self.fpr = fpr  # FingerprintReport
        self.hits: Dict[int, Any] = {}  # id(task) -> loaded DataFrame
        self.hit_tier: Dict[int, str] = {}
        self.checkpoint_hits: Set[int] = set()
        self.skipped: Set[int] = set()
        self.executes: Set[int] = set()
        self.bytes_skipped = 0

    def fp(self, task: FugueTask) -> Optional[str]:
        return self.fpr.fp(task)

    def summary(self) -> Dict[str, int]:
        return {
            "hits": len(self.hits),
            "checkpoint_hits": len(self.checkpoint_hits),
            "skipped": len(self.skipped),
            "executes": len(self.executes),
            "bytes_skipped": self.bytes_skipped,
        }


def _checkpoint_available(task: FugueTask, checkpoint_path: Any) -> bool:
    """Whether the task's own deterministic StrongCheckpoint can replay it
    without inputs (the existing ``_run_task_once`` branch serves it; the
    planner only uses this to skip its ancestors)."""
    cp = task.checkpoint
    if not isinstance(cp, StrongCheckpoint) or not cp.deterministic:
        return False
    try:
        return cp.exists(checkpoint_path, task.__uuid__())
    except Exception:
        return False


def _compute_cut(
    tasks: List[FugueTask],
    available: Any,
    checkpoint_path: Any,
) -> Dict[str, Any]:
    """One reverse-topo pass; ``available(task) -> Optional[str]`` says
    which cache tier could currently resolve the task."""
    from ..plan.ir import task_pinned

    consumers: Dict[int, int] = {}
    for t in tasks:
        for d in t.inputs:
            consumers[id(d)] = consumers.get(id(d), 0) + 1
    needed: Set[int] = set()
    hits: Dict[int, str] = {}
    cp_hits: Set[int] = set()
    executes: Set[int] = set()
    skipped: List[FugueTask] = []
    for t in reversed(tasks):
        is_root = (
            isinstance(t, OutputTask)
            or task_pinned(t)
            or consumers.get(id(t), 0) == 0
        )
        if not (is_root or id(t) in needed):
            skipped.append(t)
            continue
        if not isinstance(t, OutputTask):
            if _checkpoint_available(t, checkpoint_path):
                cp_hits.add(id(t))
                continue  # replay branch needs no inputs
            tier = available(t)
            if tier is not None:
                hits[id(t)] = tier
                continue  # the cache needs no inputs either
        executes.add(id(t))
        for d in t.inputs:
            needed.add(id(d))
    return {
        "hits": hits,
        "cp_hits": cp_hits,
        "executes": executes,
        "skipped": skipped,
    }


def plan_cache(
    tasks: List[FugueTask],
    engine: Any,
    cache: Any,
    checkpoint_path: Any,
) -> CachePlan:
    """Fingerprint, cut, and eagerly load the frontier. Emits one
    ``cache.lookup`` span per frontier decision (hit or miss) so a warm
    run's trace shows exactly where the plan was cut."""
    from ..obs import get_tracer
    from .fingerprint import fingerprint_tasks

    fpr = fingerprint_tasks(tasks, engine.conf, type(engine).__name__)
    plan = CachePlan(fpr)
    tracer = get_tracer()
    blacklist: Set[str] = set()
    looked_up: Set[int] = set()

    def available(task: FugueTask) -> Optional[str]:
        fp = fpr.fp(task)
        if fp is None or fp in blacklist:
            return None
        return cache.contains(fp)

    # the eager-load loop: a frontier load that fails (eviction race,
    # torn artifact) blacklists that fingerprint and recomputes the cut
    for _ in range(len(tasks) + 1):
        cut = _compute_cut(tasks, available, checkpoint_path)
        retry = False
        for t in tasks:
            if id(t) not in cut["hits"] or id(t) in plan.hits:
                continue
            fp = fpr.fp(t)
            looked_up.add(id(t))
            with tracer.span(
                "cache.lookup",
                cat="cache",
                task=t.name or type(t.extension).__name__,
                fp=(fp or "")[:12],
            ) as sp:
                loaded = cache.lookup(fp, engine)
                if loaded is None:
                    blacklist.add(fp)  # type: ignore[arg-type]
                    sp.set(outcome="miss")
                    retry = True
                    break
                df, tier, nbytes = loaded
                plan.hits[id(t)] = df
                plan.hit_tier[id(t)] = tier
                sp.set(outcome="hit", tier=tier, bytes=nbytes)
        if not retry:
            break
    # drop hits that a later recut decided not to use after all (their
    # consumer's load failed and the consumer now executes: the hit frame
    # is still valid and stays — it feeds the consumer directly)
    plan.checkpoint_hits = cut["cp_hits"]
    plan.executes = cut["executes"]
    for t in cut["skipped"]:
        plan.skipped.add(id(t))
        plan.bytes_skipped += fpr.source_bytes.get(id(t), 0)
    # misses among tasks that will execute but were fingerprintable:
    # count them so hit-rate math works without a lookup side effect
    for t in tasks:
        if (
            id(t) in plan.executes
            and id(t) not in looked_up
            and fpr.fp(t) is not None
        ):
            cache.stats.inc("misses")
            cache.stats.inc("lookups")
        if fpr.fp(t) is None and not isinstance(t, OutputTask):
            cache.stats.inc("refusals")
    cache.stats.inc("tasks_skipped", len(plan.skipped))
    cache.stats.inc("bytes_skipped", plan.bytes_skipped)
    return plan


def describe_cache(
    tasks: List[FugueTask],
    conf: Any,
    cache: Any = None,
    checkpoint_path: Any = None,
    engine_kind: str = "any",
) -> List[str]:
    """Render the would-be cut for ``workflow.explain()`` (dry run: probes
    ``contains`` only, loads nothing, counts nothing). Fingerprints are
    engine-partitioned, so hit/miss is only accurate when ``engine_kind``
    names the engine class the run will use."""
    from ..constants import FUGUE_TPU_CONF_CACHE_ENABLED
    from .fingerprint import fingerprint_tasks
    from .store import ResultCache

    try:
        enabled = bool(conf.get(FUGUE_TPU_CONF_CACHE_ENABLED, True))
    except Exception:
        enabled = True
    if not enabled:
        return ["== result cache disabled (fugue.tpu.cache.enabled=false) =="]
    if cache is None:
        cache = ResultCache(conf)
    fpr = fingerprint_tasks(tasks, conf, engine_kind)

    def available(task: FugueTask) -> Optional[str]:
        fp = fpr.fp(task)
        return None if fp is None else cache.contains(fp)

    cut = _compute_cut(tasks, available, checkpoint_path)
    skipped_ids = {id(t) for t in cut["skipped"]}
    bytes_skipped = sum(fpr.source_bytes.get(i, 0) for i in skipped_ids)
    scope = "" if engine_kind == "any" else f" for {engine_kind}"
    lines = [
        "== result cache%s (cut: %d hit, %d checkpoint, %d skipped upstream, "
        "~%d source bytes never read) =="
        % (scope, len(cut["hits"]), len(cut["cp_hits"]), len(skipped_ids), bytes_skipped)
    ]
    for i, t in enumerate(tasks):
        fp = fpr.fp(t)
        if id(t) in cut["hits"]:
            status = f"HIT[{cut['hits'][id(t)]}] {fp[:12]}"
        elif id(t) in cut["cp_hits"]:
            status = "checkpoint replay"
        elif id(t) in skipped_ids:
            status = "skipped (downstream hit cuts the plan here)"
        elif fp is None:
            status = "uncacheable: " + fpr.reasons.get(id(t), "?")
        else:
            status = f"miss {fp[:12]}"
        lines.append(f"  t{i}: {type(t.extension).__name__} -- {status}")
    return lines
