"""Partition-level incremental recompute (ISSUE 9 / ROADMAP item 2).

The PR 5 result cache is all-or-nothing per task: appending one file to a
loaded directory changes the Load fingerprint and invalidates the whole
downstream subtree — a 1% delta pays a 100% recompute. This module
refines the cut to the *delta* frontier:

- every Load-rooted chain of provably row-local verbs (filter / project /
  rename / assign / fused chains / dropna / fillna) — or such a chain
  terminating in a sum/count/avg/min/max aggregate (bounded, segment, or
  plain) — gets a **delta key**: the chain fingerprint with the Load's
  per-file list replaced by its path (``fingerprint.py``);
- a run that publishes such a task's result also publishes a **partition
  manifest** under the delta key: the exact source partitions (per-file
  ``(path, size, mtime_ns)``; per-file content digest + row count for
  appendable single-file csv/json sources) the artifact covers;
- a warm run whose full fingerprint MISSES consults the manifest: if the
  cached partitions are an order-preserving prefix of the current source
  (pure append — new files sorting after the cached ones, or a grown
  csv/json file whose stored digest matches its old prefix), only the new
  partitions are loaded and pushed through the chain:

  * **row-local chains**: every output row depends on exactly one input
    row, so ``chain(old ++ new) == chain(old) ++ chain(new)`` — the fresh
    rows concatenate after the cached artifact(s);
  * **aggregates**: the cached *partial accumulator* (the finished
    per-group tables with ``avg`` decomposed into sum+count — the host
    image of the donated-accumulator fold state in
    ``jax/streaming.py``) combines with the fresh partitions' partial via
    the merge semantics of ``_fold_dense_acc`` (sum/count add, min/max
    meet, NULL is the identity), then ``avg`` is re-finished as
    sum/count — incremental view maintenance.

Soundness over coverage, exactly like the fingerprint layer: anything
this module cannot prove REFUSES (``_DeltaRefused``) and the task falls
back to the PR 5 whole-task behavior — a delta miss is never a wrong
hit. The refusal ladder is rendered by ``workflow.explain()`` and
documented in ``docs/cache.md`` ("Incremental recompute").
"""

import glob as _glob
import os
from hashlib import md5
from typing import Any, Dict, List, Optional, Tuple

from .._utils.hash import to_uuid
from ..workflow._tasks import FugueTask

__all__ = [
    "DeltaTemplate",
    "DeltaHit",
    "build_delta_templates",
    "match_manifest",
    "execute_delta",
    "publish_manifest_after",
    "MANIFEST_VERSION",
]

MANIFEST_VERSION = 1

# formats where appending rows appends bytes (the stored-digest grown-file
# path); parquet's footer lives at the end, so a "grown" parquet file is a
# rewrite, never an append
_APPENDABLE_FORMATS = ("csv", "json")


class _DeltaRefused(Exception):
    """This task cannot be delta-served; degrade to whole-task semantics.
    ``had_manifest`` distinguishes a real refusal (a manifest existed but
    could not be applied) from the ordinary first-run state."""

    def __init__(self, reason: str, had_manifest: bool = False):
        super().__init__(reason)
        self.reason = reason
        self.had_manifest = had_manifest


# ---------------------------------------------------------------------------
# source partition discovery
# ---------------------------------------------------------------------------


def _token(path: str) -> Dict[str, Any]:
    st = os.stat(path)
    return {"path": path, "size": int(st.st_size), "mtime_ns": int(st.st_mtime_ns)}


def _digest_prefix(path: str, nbytes: int) -> str:
    h = md5()
    left = int(nbytes)
    with open(path, "rb") as f:
        while left > 0:
            chunk = f.read(min(left, 4 * 1024 * 1024))
            if not chunk:
                break
            h.update(chunk)
            left -= len(chunk)
    return h.hexdigest()


def list_source_partitions(path: Any, fmt: str) -> Tuple[List[Dict[str, Any]], str, bool]:
    """(partition tokens in LOAD order, resolved format, is_single_file).

    The list mirrors what the loader (``_utils/io.py``) will actually
    read, in the order it reads it — refusing every layout where
    per-file loading is not provably equivalent to the whole-source load
    (hive/nested datasets, schema sidecars)."""
    from .._utils.io import FileParser

    if not isinstance(path, str) or path == "":
        raise _DeltaRefused("load path is not a plain string")
    try:
        parser = FileParser(path, fmt or None)
    except Exception as ex:
        raise _DeltaRefused(f"unparseable load path ({type(ex).__name__})")
    file_format = parser.file_format
    if file_format == "avro":
        raise _DeltaRefused("avro sources are not delta-eligible")
    if parser.has_glob:
        files = sorted(_glob.glob(path))
        if any(os.path.isdir(f) for f in files):
            raise _DeltaRefused("glob matches a directory (dataset layout)")
    elif os.path.isdir(path):
        names = sorted(os.listdir(path))
        files = []
        for n in names:
            full = os.path.join(path, n)
            if os.path.isdir(full):
                raise _DeltaRefused(
                    "nested directory (hive/partitioned dataset layout)"
                )
            if n.startswith((".", "_")):
                # the loaders skip hidden files, but a schema sidecar
                # changes the whole-directory load's column order/types
                # in a way per-file delta loads cannot reproduce
                if n == "_fugue_schema":
                    raise _DeltaRefused(
                        "directory carries a _fugue_schema sidecar "
                        "(dataset load semantics)"
                    )
                continue
            files.append(full)
    elif os.path.exists(path):
        files = [path]
    else:
        raise _DeltaRefused(f"load source {path} does not exist")
    if len(files) == 0:
        raise _DeltaRefused(f"load source {path} holds no files")
    return [_token(f) for f in files], file_format, (
        len(files) == 1 and os.path.isfile(path)
    )


# ---------------------------------------------------------------------------
# aggregate delta spec: partial / combine / finish
# ---------------------------------------------------------------------------


class AggSpec:
    """How a sum/count/avg/min/max aggregate decomposes into a partial
    frame (the accumulator image), a combine pass and a finish.

    ``partial_cols`` is ``[(name, combine_op)]`` — the partial frame's
    non-key columns in order with the operation that merges two partials
    (count combines by SUM; NULL is the identity throughout, mirroring
    ``_fold_dense_acc``). ``finish`` is ``[(out_name, kind)]`` where kind
    is ``pass`` or ``avg`` (out = ``<out>__dsum / <out>__dcnt``)."""

    def __init__(self) -> None:
        self.keys: List[str] = []
        self.partial_exprs: List[Any] = []
        self.partial_cols: List[Tuple[str, str]] = []
        self.finish: List[Tuple[str, str]] = []
        self.has_avg = False


def parse_agg_spec(keys: List[str], agg_cols: List[Any]) -> AggSpec:
    from ..column import col as _col
    from ..column import functions as ff
    from ..column.expressions import (
        _FuncExpr,
        _LitColumnExpr,
        _NamedColumnExpr,
    )

    spec = AggSpec()
    spec.keys = list(keys)
    seen: set = set(keys)
    builders = {"SUM": ff.sum, "MIN": ff.min, "MAX": ff.max, "COUNT": ff.count}
    combine_of = {"SUM": "sum", "MIN": "min", "MAX": "max", "COUNT": "sum"}
    for c in agg_cols:
        if not isinstance(c, _FuncExpr) or not c.is_agg or c.is_distinct:
            raise _DeltaRefused(
                f"aggregate column {c!r} has no accumulator form"
            )
        func = c.func.upper()
        if func not in ("SUM", "COUNT", "AVG", "MIN", "MAX") or len(c.args) != 1:
            raise _DeltaRefused(
                f"aggregate {func} is not incrementally maintainable"
            )
        name = c.output_name
        if name == "" or name in seen:
            raise _DeltaRefused("unnamed or duplicate aggregate output")
        seen.add(name)
        arg = c.args[0]
        count_star = func == "COUNT" and (
            (isinstance(arg, _LitColumnExpr) and arg.value is not None)
            or (isinstance(arg, _NamedColumnExpr) and arg.wildcard)
        )
        if not count_star and not (
            isinstance(arg, _NamedColumnExpr) and not arg.wildcard
        ):
            raise _DeltaRefused(
                f"aggregate {func} over a computed expression is not "
                "delta-eligible"
            )
        if func == "AVG":
            spec.has_avg = True
            spec.partial_exprs.append(ff.sum(_col(arg.name)).alias(f"{name}__dsum"))
            spec.partial_exprs.append(ff.count(_col(arg.name)).alias(f"{name}__dcnt"))
            spec.partial_cols.append((f"{name}__dsum", "sum"))
            spec.partial_cols.append((f"{name}__dcnt", "sum"))
            spec.finish.append((name, "avg"))
        else:
            if count_star:
                # reuse the original COUNT(*)/COUNT(lit) expression shape,
                # re-aliased; its cast (if any) is re-applied at finish
                expr = ff.count(_col("*")).alias(name)
            else:
                expr = builders[func](_col(arg.name)).alias(name)
            spec.partial_exprs.append(expr)
            spec.partial_cols.append((name, combine_of[func]))
            spec.finish.append((name, "pass"))
    if len(spec.partial_cols) == 0:
        raise _DeltaRefused("aggregate has no aggregation columns")
    return spec


def _combine_exprs(spec: AggSpec) -> List[Any]:
    """The merge pass over a (cached ++ fresh) partial union — the
    frame-level image of ``_fold_dense_acc`` (jax/streaming.py): sums and
    counts ADD, min/max MEET, NULL is the merge identity."""
    from ..column import col as _col
    from ..column import functions as ff

    ops = {"sum": ff.sum, "min": ff.min, "max": ff.max}
    return [ops[op](_col(n)).alias(n) for n, op in spec.partial_cols]


def _combine_partials(
    engine: Any, cached: Any, fresh: Any, spec: AggSpec, partial_schema: str
) -> Any:
    """Combine two partial frames through the ENGINE's own aggregate, so
    the merged frame comes back in exactly the group order that engine's
    whole-input aggregate would produce (key-sorted on the dense device
    path, first-appearance on host paths — and cached rows precede fresh
    rows in the union, which is the appearance order of old-then-new
    data). The union is normalized to one host frame with the manifest's
    partial schema first: every delta generation then presents the
    combine with an identical layout, so the compiled combine program is
    reused instead of re-traced."""
    import pandas as pd

    from ..dataframe.pandas_dataframe import PandasDataFrame
    from ..schema import Schema

    schema = Schema(partial_schema)
    uni = pd.concat(
        [cached.as_pandas(), fresh.as_pandas()], ignore_index=True
    )[schema.names]
    from ..collections.partition import PartitionSpec

    uni_df = engine.to_df(PandasDataFrame(uni, schema))
    combined = engine.aggregate(
        uni_df, PartitionSpec(by=list(spec.keys)), _combine_exprs(spec)
    )
    return engine.to_df(combined, schema=partial_schema)


def _finish_partial(engine: Any, combined: Any, spec: AggSpec, out_schema: str) -> Any:
    """avg = sum/count, declared dtypes and column order — the frame-level
    image of ``_finish_dense_host`` (jax/streaming.py)."""
    from ..dataframe.pandas_dataframe import PandasDataFrame
    from ..schema import Schema

    schema = Schema(out_schema)
    if not spec.has_avg:
        return engine.to_df(combined, schema=str(schema))
    import pandas as pd

    pdf = combined.as_pandas()
    out = pd.DataFrame()
    for k in spec.keys:
        out[k] = pdf[k]
    for name, kind in spec.finish:
        if kind == "pass":
            out[name] = pdf[name]
        else:
            cnt = pdf[f"{name}__dcnt"].astype("float64")
            out[name] = pdf[f"{name}__dsum"].astype("float64") / cnt.where(cnt > 0)
    return engine.to_df(PandasDataFrame(out[schema.names], schema))


# ---------------------------------------------------------------------------
# delta templates: static eligibility over the post-optimization DAG
# ---------------------------------------------------------------------------


class DeltaTemplate:
    """One task's delta shape: its single Load root, the row-local tasks
    between them (in execution order; for ``frame`` mode the task itself
    is the last entry), and — for ``acc`` mode — the segment steps plus
    the parsed aggregate spec."""

    def __init__(self) -> None:
        self.task: Optional[FugueTask] = None
        self.mode = "frame"  # or "acc"
        self.load_task: Optional[FugueTask] = None
        self.apply_tasks: List[FugueTask] = []
        self.steps: List[Any] = []  # segment chain (acc-from-segment only)
        self.is_segment = False
        self.agg: Optional[AggSpec] = None
        self.delta_key = ""
        self.path = ""
        self.fmt = ""
        self.partitions: List[Dict[str, Any]] = []
        self.file_format = ""
        self.single_file = False


def _load_params(load_task: FugueTask) -> Tuple[str, str, Any, Dict[str, Any]]:
    p = load_task.params
    return (
        p.get_or_throw("path", str),
        p.get("fmt", ""),
        p.get_or_none("columns", object),
        dict(p.get("params", dict())),
    )


def build_delta_templates(
    tasks: List[FugueTask], fpr: Any
) -> Tuple[Dict[int, DeltaTemplate], Dict[int, str]]:
    """Classify every fingerprintable task as delta-eligible (template) or
    not (reason). Never raises — eligibility is a value, like refusal in
    the fingerprint layer."""
    from ..plan.ir import (
        K_AGGREGATE,
        K_LOAD,
        K_SEGMENT,
        build_graph,
        node_delta_row_local,
    )

    templates: Dict[int, DeltaTemplate] = {}
    reasons: Dict[int, str] = {}
    nodes = build_graph(tasks)
    by_id = {id(n.task): n for n in nodes if n.task is not None}
    part_memo: Dict[int, Any] = {}

    def partitions_of(load_task: FugueTask) -> Tuple[List[Dict[str, Any]], str, bool]:
        if id(load_task) not in part_memo:
            path, fmt, _cols, _kw = _load_params(load_task)
            try:
                part_memo[id(load_task)] = list_source_partitions(path, fmt)
            except _DeltaRefused as r:
                part_memo[id(load_task)] = r
        memo = part_memo[id(load_task)]
        if isinstance(memo, _DeltaRefused):
            raise memo
        return memo

    for node in nodes:
        task = node.task
        if task is None:
            continue
        dfp = fpr.delta_fp(task)
        if dfp is None:
            continue  # the fingerprint layer already carries the reason
        try:
            tpl = DeltaTemplate()
            tpl.task = task
            tpl.delta_key = dfp
            if node.kind == K_LOAD:
                tpl.mode = "frame"
                load_node = node
            elif node_delta_row_local(node):
                tpl.mode = "frame"
                load_node = None
            elif node.kind == K_AGGREGATE:
                tpl.mode = "acc"
                tpl.agg = parse_agg_spec(
                    list(task.partition_spec.partition_by),
                    list(task.params.get("columns", [])),
                )
                load_node = None
            elif node.kind == K_SEGMENT:
                terminal = node.info.get("terminal") or ("?",)
                if terminal[0] != "aggregate":
                    raise _DeltaRefused(
                        f"segment terminal {terminal[0]!r} is not "
                        "incrementally maintainable"
                    )
                tpl.mode = "acc"
                tpl.steps = list(node.info.get("steps", []))
                tpl.is_segment = True
                tpl.agg = parse_agg_spec(
                    list(task.partition_spec.partition_by), list(terminal[1])
                )
                load_node = None
            else:
                raise _DeltaRefused(
                    f"verb {node.kind!r} is not row-local and has no "
                    "accumulator form"
                )
            # walk down the producer chain to the single Load root
            chain: List[FugueTask] = []
            cur = node
            while load_node is None:
                if len(cur.inputs) != 1:
                    raise _DeltaRefused(
                        "producer chain is not single-source (join/zip "
                        "upstream)"
                    )
                parent = cur.inputs[0]
                if parent.kind == K_LOAD:
                    load_node = parent
                    break
                if not node_delta_row_local(parent):
                    raise _DeltaRefused(
                        f"producer {parent.kind!r} is not row-local"
                    )
                chain.append(parent.task)
                cur = parent
            chain.reverse()
            tpl.load_task = load_node.task
            tpl.apply_tasks = list(chain)
            if tpl.mode == "frame" and task is not tpl.load_task:
                tpl.apply_tasks.append(task)
            tpl.partitions, tpl.file_format, tpl.single_file = partitions_of(
                tpl.load_task
            )
            tpl.path, tpl.fmt, _c, _k = _load_params(tpl.load_task)
            templates[id(task)] = tpl
        except _DeltaRefused as r:
            reasons[id(task)] = r.reason
        except Exception as ex:  # eligibility must never fail a run
            reasons[id(task)] = f"delta analysis error: {type(ex).__name__}"
    return templates, reasons


# ---------------------------------------------------------------------------
# manifest match
# ---------------------------------------------------------------------------


class DeltaHit:
    """A matched manifest: which partitions are served from cache, which
    are fresh, and (after the planner's eager load) the cached frames."""

    def __init__(self, template: DeltaTemplate, manifest: Dict[str, Any]):
        self.template = template
        self.manifest = manifest
        self.new_files: List[str] = []
        self.grown_rows: Optional[int] = None  # reload + slice [rows:]
        self.matched_parts = 0
        self.total_parts = 0
        self.bytes_matched = 0
        self.bytes_fresh = 0
        self.out_schema: str = manifest.get("out_schema", "")
        self.artifact_fps: List[str] = []  # to eager-load, in merge order
        self.cached_frames: List[Any] = []
        self.fresh_input_rows = 0
        self.fresh_result: Any = None  # frame mode: fresh chain output
        self.combined_partial: Any = None  # acc mode
        self.failed = False  # runtime fallback taken; skip manifest upkeep


def _tokens_equal(a: Dict[str, Any], b: Dict[str, Any]) -> bool:
    return (
        a.get("path") == b.get("path")
        and int(a.get("size", -1)) == int(b.get("size", -2))
        and int(a.get("mtime_ns", -1)) == int(b.get("mtime_ns", -2))
    )


def match_manifest(
    template: DeltaTemplate, cache: Any, repair: bool = True
) -> DeltaHit:
    """Match the published manifest against the CURRENT source partitions;
    returns a (not yet loaded) DeltaHit or raises ``_DeltaRefused``. With
    ``repair`` (the run path, not explain), a manifest referencing evicted
    artifacts is deleted so only that chain degrades."""
    m = cache.get_manifest(template.delta_key)
    if m is None:
        raise _DeltaRefused("no partition manifest published yet")
    refuse = lambda msg: _DeltaRefused(msg, had_manifest=True)  # noqa: E731
    if int(m.get("version", -1)) != MANIFEST_VERSION or m.get("mode") not in (
        "frame",
        "acc",
    ):
        raise refuse("unreadable manifest version")
    if m.get("mode") != template.mode:
        raise refuse("manifest mode mismatch (plan shape changed)")
    hit = DeltaHit(template, m)
    current = template.partitions
    old_parts = list(m.get("partitions", []))
    hit.total_parts = len(current)
    if m.get("by") == "rows":
        # single appendable file: the stored digest proves the old bytes
        # are an unchanged prefix of the grown file
        if not (template.single_file and len(current) == 1 and len(old_parts) == 1):
            raise refuse("source is no longer a single file")
        cur, old = current[0], old_parts[0]
        if cur["path"] != old.get("path"):
            raise refuse("source path changed")
        if _tokens_equal(cur, old):
            raise refuse("source unchanged (whole-task fingerprint serves it)")
        if template.file_format not in _APPENDABLE_FORMATS:
            raise refuse("format cannot grow by append")
        if int(cur["size"]) <= int(old.get("size", 0)):
            raise refuse("partition contents changed (not an append)")
        digest = old.get("digest")
        rows = old.get("rows")
        if not digest or rows is None:
            raise refuse("manifest lacks prefix digest/rows for append check")
        if _digest_prefix(cur["path"], int(old["size"])) != digest:
            raise refuse("partition contents changed (prefix digest mismatch)")
        hit.grown_rows = int(rows)
        hit.matched_parts = 1
        hit.bytes_matched = int(old["size"])
        hit.bytes_fresh = int(cur["size"]) - int(old["size"])
        hit.total_parts = 1
    else:
        if len(old_parts) > len(current):
            raise refuse("cached partitions missing from source (shrunk or rewritten)")
        for i, old in enumerate(old_parts):
            cur = current[i]
            if cur["path"] != old.get("path"):
                raise refuse(
                    "partition order changed (a new file sorts before cached "
                    "ones — not an append)"
                )
            if not _tokens_equal(cur, old):
                raise refuse("partition contents changed (not an append)")
        if len(old_parts) == len(current):
            raise refuse(
                "no new partitions (whole-task fingerprint serves exact matches)"
            )
        hit.new_files = [t["path"] for t in current[len(old_parts):]]
        hit.matched_parts = len(old_parts)
        hit.bytes_matched = sum(int(t.get("size", 0)) for t in old_parts)
        hit.bytes_fresh = sum(
            int(t.get("size", 0)) for t in current[len(old_parts):]
        )
    # every referenced artifact must still exist; a stale manifest
    # invalidates ITSELF, never the rest of the cache
    if template.mode == "frame":
        segs = list(m.get("segments", []))
        if len(segs) == 0:
            raise refuse("manifest holds no segments")
        hit.artifact_fps = [s["artifact"] for s in segs]
    else:
        partial = m.get("partial") or {}
        if not partial.get("artifact"):
            raise refuse("manifest holds no partial accumulator")
        hit.artifact_fps = [partial["artifact"]]
    missing = [fp for fp in hit.artifact_fps if cache.contains(fp) is None]
    if missing:
        if repair:
            cache.drop_manifest(template.delta_key)
        raise refuse(
            "cached partition artifact evicted (manifest entry invalidated)"
        )
    if not hit.out_schema:
        raise refuse("manifest lacks the output schema")
    return hit


# ---------------------------------------------------------------------------
# execution
# ---------------------------------------------------------------------------


def _load_fresh(engine: Any, hit: DeltaHit) -> Any:
    """The delta input frame: only the new partitions (or the grown
    file's appended rows) go through decode/transfer."""
    tpl = hit.template
    _path, fmt, columns, kwargs = _load_params(tpl.load_task)
    if hit.grown_rows is not None:
        full = engine.load_df(
            tpl.path, format_hint=fmt or None, columns=columns, **kwargs
        )
        hit.fresh_input_rows = max(0, full.count() - hit.grown_rows)
        try:
            tbl = full.as_arrow()
            from ..dataframe.arrow_dataframe import ArrowDataFrame

            sliced: Any = ArrowDataFrame(tbl.slice(hit.grown_rows))
        except Exception:
            import pandas as pd  # noqa: F401

            from ..dataframe.pandas_dataframe import PandasDataFrame

            pdf = full.as_pandas().iloc[hit.grown_rows:].reset_index(drop=True)
            sliced = PandasDataFrame(pdf, full.schema)
        return engine.to_df(sliced, schema=str(full.schema))
    fresh = engine.load_df(
        list(hit.new_files), format_hint=fmt or None, columns=columns, **kwargs
    )
    hit.fresh_input_rows = fresh.count()
    return fresh


def _apply_chain(ctx: Any, df: Any, tasks: List[FugueTask]) -> Any:
    for t in tasks:
        df = t.execute(ctx, [df])
    return df


def _concat_frames(engine: Any, frames: List[Any], out_schema: str) -> Any:
    """Order-preserving concatenation on the HOST, then ONE engine
    ingestion. ``engine.union`` is deliberately not used: on a sharded
    mesh it concatenates per shard, interleaving the global row order,
    while a full recompute would produce cached-rows-then-fresh-rows."""
    import pyarrow as pa

    from ..dataframe.arrow_dataframe import ArrowDataFrame
    from ..schema import Schema

    pa_schema = Schema(out_schema).pa_schema
    tables = []
    for f in frames:
        t = f.as_arrow()
        if t.schema != pa_schema:
            t = t.select(pa_schema.names).cast(pa_schema)
        tables.append(t)
    merged = pa.concat_tables(tables) if len(tables) > 1 else tables[0]
    return engine.to_df(ArrowDataFrame(merged))


def execute_delta(ctx: Any, task: FugueTask, hit: DeltaHit) -> Any:
    """Compute the task's FULL result from cached partitions + fresh
    partitions. Any failure degrades in place to a full recompute from
    the source (the chain is single-source, so no DAG inputs are needed)
    — never a wrong result."""
    engine = ctx.execution_engine
    tpl = hit.template
    try:
        fresh_in = _load_fresh(engine, hit)
        if tpl.mode == "frame":
            fresh_out = _apply_chain(ctx, fresh_in, tpl.apply_tasks)
            hit.fresh_result = engine.to_df(fresh_out)
            return _concat_frames(
                engine,
                list(hit.cached_frames) + [hit.fresh_result],
                hit.out_schema,
            )
        spec = tpl.agg
        if tpl.is_segment:
            # stream the new partitions through the EXISTING lowered path:
            # one compiled program runs chain + partial aggregate, keyed by
            # the (steps, partial terminal) fingerprint — equal-sized
            # appends reuse the compiled program across delta generations
            from ..plan.lowering import segment_fingerprint

            terminal = ("aggregate", tuple(spec.partial_exprs))
            fresh_partial = engine.lowered_segment(
                [_apply_chain(ctx, fresh_in, tpl.apply_tasks)],
                list(tpl.steps),
                terminal,
                task.partition_spec,
                fingerprint=segment_fingerprint(list(tpl.steps), terminal),
            )
        else:
            chain_out = _apply_chain(ctx, fresh_in, tpl.apply_tasks)
            fresh_partial = engine.aggregate(
                chain_out, task.partition_spec, list(spec.partial_exprs)
            )
        partial_schema = hit.manifest["partial"]["schema"]
        fresh_partial = engine.to_df(fresh_partial, schema=partial_schema)
        combined = _combine_partials(
            engine, hit.cached_frames[0], fresh_partial, spec, partial_schema
        )
        hit.combined_partial = combined
        return _finish_partial(engine, combined, spec, hit.out_schema)
    except Exception as ex:
        engine.log.warning(
            "delta recompute of %s failed (%s: %s); falling back to full "
            "recompute from source",
            task.name or type(task.extension).__name__,
            type(ex).__name__,
            ex,
        )
        hit.fresh_result = None
        hit.combined_partial = None
        hit.failed = True
        # the chain is single-source: rebuild the task's input from the
        # original Load task and run the ORIGINAL tasks — exactly the
        # plain whole-task computation
        df = tpl.load_task.execute(ctx, [])
        df = _apply_chain(ctx, df, tpl.apply_tasks)
        if tpl.mode == "acc":
            df = task.execute(ctx, [df])
        return df


# ---------------------------------------------------------------------------
# manifest publishing (cold runs AND after a delta-served run)
# ---------------------------------------------------------------------------


def _partial_fp(delta_key: str, partitions: List[Dict[str, Any]]) -> str:
    return "d" + to_uuid(
        "partial", delta_key, [(t["path"], t["size"], t["mtime_ns"]) for t in partitions]
    ).replace("-", "")[:30]


def _segment_fp(delta_key: str, partitions: List[Dict[str, Any]]) -> str:
    return "d" + to_uuid(
        "segment", delta_key, [(t["path"], t["size"], t["mtime_ns"]) for t in partitions]
    ).replace("-", "")[:30]


def _enrich_single_file(
    ctx: Any, tpl: DeltaTemplate, tokens: List[Dict[str, Any]], rows: Optional[int]
) -> None:
    """Record the content digest + row count that make a single csv/json
    source append-detectable later. Skipped when the file changed since
    plan time (the artifact would not cover the new bytes)."""
    if not (
        tpl.single_file
        and len(tokens) == 1
        and tpl.file_format in _APPENDABLE_FORMATS
        and rows is not None
    ):
        return
    t = tokens[0]
    try:
        if not _tokens_equal(_token(t["path"]), t):
            return
        t["digest"] = _digest_prefix(t["path"], int(t["size"]))
        t["rows"] = int(rows)
    except OSError:
        return


def _load_rows(ctx: Any, tpl: DeltaTemplate) -> Optional[int]:
    try:
        if ctx.has_result(tpl.load_task):
            return int(ctx.get_result(tpl.load_task).count())
    except Exception:
        return None
    return None


def publish_manifest_after(
    ctx: Any,
    task: FugueTask,
    result: Any,
    inputs: Optional[List[Any]] = None,
    hit: Optional[DeltaHit] = None,
) -> None:
    """Maintain the partition manifest after a task publishes its result.

    Cold runs write the first manifest (frame mode references the task's
    own artifact; acc mode with ``avg`` additionally publishes the
    decomposed partial, computed from the task's still-live input frame).
    Delta-served runs append the fresh segment / replace the partial so
    the NEXT append only pays for its own delta. Never raises."""
    plan = getattr(ctx, "_cache_plan", None)
    if plan is None:
        return
    engine = ctx.execution_engine
    cache = engine.result_cache
    if not (cache.enabled and cache.delta_enabled):
        return
    tpl = getattr(plan, "delta_templates", {}).get(id(task))
    if tpl is None or getattr(hit, "failed", False):
        return
    fp = plan.fp(task)
    if fp is None or (result.is_local and not result.is_bounded):
        return
    try:
        if hit is None:
            _publish_cold(ctx, cache, engine, task, tpl, fp, result, inputs)
        else:
            _publish_warm(ctx, cache, engine, task, tpl, fp, result, hit)
    except Exception as ex:  # manifest upkeep must never fail the run
        engine.log.warning(
            "delta manifest publish for %s failed: %s", tpl.delta_key[:12], ex
        )


def _base_manifest(tpl: DeltaTemplate, out_schema: str, by: str) -> Dict[str, Any]:
    return {
        "version": MANIFEST_VERSION,
        "delta_key": tpl.delta_key,
        "mode": tpl.mode,
        "by": by,
        "fmt": tpl.file_format,
        "path": tpl.path,
        "out_schema": out_schema,
        "partitions": [dict(t) for t in tpl.partitions],
    }


def _publish_cold(
    ctx: Any,
    cache: Any,
    engine: Any,
    task: FugueTask,
    tpl: DeltaTemplate,
    fp: str,
    result: Any,
    inputs: Optional[List[Any]],
) -> None:
    if cache.contains(fp) is None:
        return  # the result artifact itself was not cacheable
    if tpl.single_file:
        if tpl.file_format not in _APPENDABLE_FORMATS:
            return  # a single parquet file can never grow by append
        by = "rows"
    else:
        by = "files"
    m = _base_manifest(tpl, str(result.schema), by)
    if by == "rows":
        _enrich_single_file(ctx, tpl, m["partitions"], _load_rows(ctx, tpl))
        if "rows" not in m["partitions"][0]:
            return  # source changed mid-run; no manifest
    if tpl.mode == "frame":
        if by == "rows":
            m["segments"] = [
                {"rows": [0, int(m["partitions"][0]["rows"])], "artifact": fp}
            ]
        else:
            m["segments"] = [{"upto": len(m["partitions"]), "artifact": fp}]
    else:
        spec = tpl.agg
        if not spec.has_avg:
            # the finished frame IS the accumulator (sum-of-sums == sum);
            # one artifact, two roles
            m["partial"] = {"artifact": fp, "schema": str(result.schema)}
        else:
            if not inputs or len(inputs) != 1:
                return
            src = inputs[0]
            if src.is_local and not src.is_bounded:
                return  # stream input already consumed
            chain_out = src
            if tpl.steps:
                chain_out = engine.fused_apply(chain_out, list(tpl.steps))
            partial = engine.aggregate(
                chain_out, task.partition_spec, list(spec.partial_exprs)
            )
            pfp = _partial_fp(tpl.delta_key, tpl.partitions)
            cache.publish(pfp, partial, engine, str(partial.schema))
            if cache.contains(pfp) is None:
                return
            m["partial"] = {"artifact": pfp, "schema": str(partial.schema)}
    cache.put_manifest(tpl.delta_key, m)


def _publish_warm(
    ctx: Any,
    cache: Any,
    engine: Any,
    task: FugueTask,
    tpl: DeltaTemplate,
    fp: str,
    result: Any,
    hit: DeltaHit,
) -> None:
    m = _base_manifest(tpl, hit.out_schema, hit.manifest.get("by", "files"))
    if m["by"] == "rows":
        old = hit.manifest["partitions"][0]
        _enrich_single_file(
            ctx,
            tpl,
            m["partitions"],
            int(old.get("rows", 0)) + hit.fresh_input_rows,
        )
        if "rows" not in m["partitions"][0]:
            return
    if tpl.mode == "frame":
        if hit.fresh_result is None:
            return
        seg_fp = _segment_fp(tpl.delta_key, tpl.partitions)
        cache.publish(seg_fp, hit.fresh_result, engine, str(hit.fresh_result.schema))
        if cache.contains(seg_fp) is None:
            return
        segs = list(hit.manifest.get("segments", []))
        if m["by"] == "rows":
            start = int(hit.manifest["partitions"][0].get("rows", 0))
            segs.append(
                {"rows": [start, int(m["partitions"][0]["rows"])], "artifact": seg_fp}
            )
        else:
            segs.append({"upto": len(m["partitions"]), "artifact": seg_fp})
        m["segments"] = segs
    else:
        if hit.combined_partial is None:
            return
        spec = tpl.agg
        if not spec.has_avg and cache.contains(fp) is not None:
            # the merged result was just published under the new full
            # fingerprint — reuse it as the accumulator
            m["partial"] = {"artifact": fp, "schema": str(result.schema)}
        else:
            pfp = _partial_fp(tpl.delta_key, tpl.partitions)
            cache.publish(
                pfp, hit.combined_partial, engine, str(hit.combined_partial.schema)
            )
            if cache.contains(pfp) is None:
                return
            m["partial"] = {
                "artifact": pfp,
                "schema": str(hit.combined_partial.schema),
            }
    cache.put_manifest(tpl.delta_key, m)
