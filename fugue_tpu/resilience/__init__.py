"""Resilience layer: retry/backoff policy, deadlines, failure taxonomy,
fault injection, and structured recovery counters.

Graceful-degradation order everywhere in the codebase:
**parallel → retry → serial → raise** (see ``docs/resilience.md``).
"""

from .counters import ResilienceStats
from .fault import (
    NULL_INJECTOR,
    SITE_CHECKPOINT_SAVE,
    SITE_DIST_BOARD,
    SITE_DIST_HEARTBEAT,
    SITE_DIST_LEASE,
    SITE_MAP_CHUNK,
    SITE_MAP_DISPATCH,
    SITE_RPC_REQUEST,
    SITE_SERVE_CLAIM,
    SITE_SERVE_JOURNAL,
    SITE_SHUFFLE_SPILL,
    SITE_STREAM_CHUNK,
    SITE_TASK_EXECUTE,
    SITE_VIEW_REGISTER,
    FaultInjector,
)
from .policy import (
    ChunkTimeoutError,
    Deadline,
    FailureCategory,
    InjectedFaultError,
    ParallelMapError,
    RetryPolicy,
    WorkerLostError,
    classify_failure,
)

__all__ = [
    "ResilienceStats",
    "FaultInjector",
    "NULL_INJECTOR",
    "SITE_MAP_DISPATCH",
    "SITE_MAP_CHUNK",
    "SITE_TASK_EXECUTE",
    "SITE_RPC_REQUEST",
    "SITE_CHECKPOINT_SAVE",
    "SITE_SERVE_JOURNAL",
    "SITE_SERVE_CLAIM",
    "SITE_SHUFFLE_SPILL",
    "SITE_STREAM_CHUNK",
    "SITE_DIST_LEASE",
    "SITE_DIST_HEARTBEAT",
    "SITE_DIST_BOARD",
    "SITE_VIEW_REGISTER",
    "RetryPolicy",
    "Deadline",
    "FailureCategory",
    "classify_failure",
    "WorkerLostError",
    "ChunkTimeoutError",
    "InjectedFaultError",
    "ParallelMapError",
]
