"""Deterministic fault injection at named sites.

The resilience layer is only trustworthy if its failure paths are
exercised, so the injector is a first-class, conf/env-driven part of the
subsystem rather than test-local monkeypatching: production code calls
``injector.fire(SITE)`` at each named site and the call is a no-op unless
a fault plan is configured.

Plan grammar (``fugue.tpu.fault.plan`` conf key or ``FUGUE_TPU_FAULT_PLAN``
env var) — semicolon-separated rules::

    <site>=<kind>[:<arg>][@<count>]

    map.chunk=kill                 # SIGKILL the worker running the 1st chunk
    map.chunk=delay:3@2            # sleep 3s inside the first 2 chunks
    rpc.request=error:TimeoutError # raise TimeoutError on the 1st request
    task.execute=error@2           # raise InjectedFaultError on 2 tasks

``count`` (default 1) is the rule's budget: the fault triggers on the
first ``count`` arrivals at the site and never again. Budgets live in
fork-shared memory, so a budget consumed inside a forked pool worker is
visible to every later worker and to the driver — "kill exactly one
worker" means exactly one across the whole map, not one per child.

Named sites wired through the codebase:

- ``map.dispatch`` — driver side, before a chunk is handed to the pool
- ``map.chunk``    — inside the forked worker, before a chunk's first
  partition runs (``kill`` here exercises worker-crash recovery)
- ``task.execute`` — driver side, before a workflow task body runs
- ``rpc.request``  — inside the HTTP RPC client, before the request
- ``checkpoint.save`` — between a checkpoint's data write and its atomic
  publish rename (exercises torn-write recovery)

``kill`` is only honoured in a process other than the injector's creator
(a forked worker); in the driver it degrades to a raised
``InjectedFaultError`` so a mis-scoped plan cannot take down the session.
"""

import multiprocessing as mp
import os
import signal
import threading
import time
from typing import Any, Dict, List, Optional

from .policy import InjectedFaultError

__all__ = [
    "FaultInjector",
    "NULL_INJECTOR",
    "SITE_MAP_DISPATCH",
    "SITE_MAP_CHUNK",
    "SITE_TASK_EXECUTE",
    "SITE_RPC_REQUEST",
    "SITE_CHECKPOINT_SAVE",
    "SITE_STREAM_CHUNK",
    "SITE_SHUFFLE_SPILL",
    "SITE_SERVE_JOURNAL",
    "SITE_SERVE_CLAIM",
    "SITE_DIST_LEASE",
    "SITE_DIST_HEARTBEAT",
    "SITE_DIST_BOARD",
    "SITE_VIEW_REGISTER",
]

SITE_MAP_DISPATCH = "map.dispatch"
SITE_MAP_CHUNK = "map.chunk"
SITE_TASK_EXECUTE = "task.execute"
SITE_RPC_REQUEST = "rpc.request"
SITE_CHECKPOINT_SAVE = "checkpoint.save"
# inside the streaming ingest pipeline's producer thread, after a chunk is
# decoded and before it is enqueued (fugue_tpu/jax/pipeline.py) — `error`
# here is the poison-chunk scenario: it must propagate to the consumer
# with its traceback and must never deadlock the bounded queue
SITE_STREAM_CHUNK = "stream.chunk"
# inside the shuffle spill partitioner, between a bucket file's write and
# its atomic publish rename (fugue_tpu/shuffle/partitioner.py) — `error`
# here leaves that one bucket unpublished; the reader recovers it by
# repartitioning ONLY that bucket from a replayable source
SITE_SHUFFLE_SPILL = "shuffle.spill"
# inside EngineServer.submit, between the WAL append and the submission
# entering the queue (fugue_tpu/serve/server.py) — `kill` here leaves a
# journaled-but-never-queued admission: the crash window a restart's
# journal replay (and a FleetClient failover) must cover exactly once
SITE_SERVE_JOURNAL = "serve.journal"
# inside FleetCoordinator.acquire, between the cross-replica claim write
# and execution start (fugue_tpu/serve/fleet.py) — `kill` here leaves a
# dead owner's claim for waiters to steal (lease expiry / dead-pid
# detection); `delay` widens the window so a chaos test can SIGKILL the
# owner deterministically mid-claim
SITE_SERVE_CLAIM = "serve.claim"
# inside DistWorker.run_task, between the task-lease acquisition and the
# task body (fugue_tpu/dist/worker.py) — `error` here leaves an acquired
# lease to unwind-release (the fail record is TRANSIENT, the task is
# re-dispatched); `kill` leaves an orphaned lease for a live worker to
# steal once the dead owner's heartbeat goes stale
SITE_DIST_LEASE = "dist.lease"
# inside HeartbeatWriter's beat loop, before each atomic heartbeat write
# (fugue_tpu/dist/heartbeat.py) — `error` SKIPS that beat (a simulated
# network partition: enough skipped beats and the worker reads as dead
# to lease/claim stealers); `delay` widens the gap the same way
SITE_DIST_HEARTBEAT = "dist.heartbeat"
# inside DistWorker.run_task, between a done record's construction (all
# task outputs durably written) and its exclusive-create publish to the
# board (fugue_tpu/dist/worker.py) — `error` here records a TRANSIENT
# failure with outputs orphaned on disk (the re-dispatch must republish or
# dedup them); `kill` is the torn-publish crash window the lease-steal +
# orphaned-fragment-invalidation ladder must cover without losing or
# double-counting a row
SITE_DIST_BOARD = "dist.board"
# inside ViewRegistry.register, between the WAL append and the spec's
# atomic publish to the shared registry (fugue_tpu/views/registry.py) —
# `error`/`kill` here leave a journaled-but-invisible registration: the
# crash window a restarted replica's view replay must close by
# re-publishing the spec from its own WAL
SITE_VIEW_REGISTER = "view.register"

FUGUE_TPU_FAULT_PLAN_ENV = "FUGUE_TPU_FAULT_PLAN"

# exceptions nameable in `error:<Name>` rules; limited to types whose
# classification is meaningful to the retry machinery
_NAMED_ERRORS: Dict[str, type] = {
    "InjectedFaultError": InjectedFaultError,
    "TimeoutError": TimeoutError,
    "ConnectionError": ConnectionError,
    "ConnectionRefusedError": ConnectionRefusedError,
    "OSError": OSError,
    "ValueError": ValueError,
    "RuntimeError": RuntimeError,
}


class _Budget:
    """A decrement-once counter shared across fork children when possible."""

    def __init__(self, count: int):
        self._count = count
        try:
            self._shared: Any = mp.get_context("fork").Value("i", count)
        except (ValueError, OSError):  # no fork on this platform
            self._shared = None
            self._local = count
            self._lock = threading.Lock()

    def acquire(self) -> bool:
        if self._shared is not None:
            with self._shared.get_lock():
                if self._shared.value > 0:
                    self._shared.value -= 1
                    return True
                return False
        with self._lock:
            if self._local > 0:
                self._local -= 1
                return True
            return False

    @property
    def remaining(self) -> int:
        if self._shared is not None:
            return int(self._shared.value)
        return self._local


class _Rule:
    def __init__(self, site: str, kind: str, arg: str, count: int, creator_pid: int):
        if kind not in ("kill", "delay", "error"):
            raise ValueError(f"unknown fault kind {kind!r}")
        self.site = site
        self.kind = kind
        self.arg = arg
        self.budget = _Budget(count)
        self._creator_pid = creator_pid

    def perform(self, site: str) -> None:
        if self.kind == "kill":
            if os.getpid() != self._creator_pid:
                os.kill(os.getpid(), signal.SIGKILL)
            raise InjectedFaultError(
                f"injected kill at {site} (driver process — degraded to raise)"
            )
        if self.kind == "delay":
            time.sleep(float(self.arg or "1"))
            return
        exc_type = _NAMED_ERRORS.get(self.arg or "InjectedFaultError")
        if exc_type is None:
            raise ValueError(f"unknown injected error type {self.arg!r}")
        raise exc_type(f"injected fault at {site}")


def _parse_plan(plan: str, creator_pid: int) -> Dict[str, List[_Rule]]:
    rules: Dict[str, List[_Rule]] = {}
    for raw in plan.split(";"):
        raw = raw.strip()
        if not raw:
            continue
        site, _, action = raw.partition("=")
        site = site.strip()
        action = action.strip()
        if not site or not action:
            raise ValueError(f"malformed fault rule {raw!r}")
        count = 1
        if "@" in action:
            action, _, c = action.rpartition("@")
            count = int(c)
        kind, _, arg = action.partition(":")
        rules.setdefault(site, []).append(
            _Rule(site, kind.strip(), arg.strip(), count, creator_pid)
        )
    return rules


class FaultInjector:
    """Fires configured faults at named sites; inert without a plan.

    Budgets are scoped to the injector instance — the engine creates one
    injector per map call / workflow run, so ``@1`` means "once per map",
    matching the acceptance scenario "SIGKILL one fork worker per map".
    """

    def __init__(self, plan: Optional[str] = None):
        self._plan = plan or ""
        self._rules = _parse_plan(self._plan, os.getpid()) if plan else {}

    @classmethod
    def from_conf(cls, conf: Any) -> "FaultInjector":
        from ..constants import FUGUE_TPU_CONF_FAULT_PLAN

        plan = str(conf.get(FUGUE_TPU_CONF_FAULT_PLAN, "")) or os.environ.get(
            FUGUE_TPU_FAULT_PLAN_ENV, ""
        )
        if not plan:
            return NULL_INJECTOR
        return cls(plan)

    @property
    def enabled(self) -> bool:
        return bool(self._rules)

    @property
    def plan(self) -> str:
        return self._plan

    def fire(self, site: str) -> None:
        """Trigger any armed rule for ``site``; no-op when the plan has no
        rule there or every matching budget is spent."""
        for rule in self._rules.get(site, ()):
            if rule.budget.acquire():
                rule.perform(site)


NULL_INJECTOR = FaultInjector(None)
