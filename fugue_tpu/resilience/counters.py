"""Structured resilience counters — retries and fallbacks are never silent.

Every recovery action in the resilience layer increments a named counter
on the owning engine's ``resilience_stats`` so operators can distinguish
"healthy" from "healthy because it retried 400 times". Counter names are
dotted, grouped by layer:

- ``map.chunks_ok``            chunks that completed in the fork pool
- ``map.chunk_retries``        chunks re-dispatched after a retryable failure
- ``map.worker_lost``          pool workers observed dead (OOM/SIGKILL/segfault)
- ``map.deadline_expiries``    chunks that blew their per-chunk deadline
- ``map.quarantined_chunks``   chunks demoted to serial in-driver execution
- ``map.quarantined_partitions`` partitions inside quarantined chunks
- ``map.serial_fallbacks``     quarantined chunks that then succeeded serially
- ``map.pool_rebuilds``        fresh pools forked after a wave was lost
- ``map.worker_chunks``        chunk bodies completed INSIDE fork workers
  (shipped home as counter deltas with each chunk result)
- ``map.worker_partitions``    partitions executed inside fork workers
- ``map.worker_rows_out``      rows produced inside fork workers
- ``workflow.task_retries``    task bodies re-run under the task retry policy
- ``workflow.checkpoint_replays`` tasks served from a StrongCheckpoint
  instead of recomputing
- ``rpc.retries``              HTTP RPC requests re-sent after backoff
"""

import threading
from typing import Dict

__all__ = ["ResilienceStats"]


class ResilienceStats:
    """Thread-safe monotonic counters (fork children mutate their own copy;
    only driver-side increments are observable, which is where every
    recovery decision is made)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {}

    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def get(self, name: str) -> int:
        with self._lock:
            return self._counters.get(name, 0)

    def merge(self, delta: Dict[str, int]) -> None:
        """Fold a counter delta (e.g. one shipped home from a forked map
        worker with its chunk result) into this registry."""
        with self._lock:
            for name, n in delta.items():
                self._counters[name] = self._counters.get(name, 0) + int(n)

    def as_dict(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counters)

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()

    def __repr__(self) -> str:
        return f"ResilienceStats({self.as_dict()})"
