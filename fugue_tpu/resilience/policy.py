"""Retry/deadline policy and the failure-category taxonomy.

Distributed dataframe systems treat partition-level fault recovery as
table stakes (arXiv:2209.06146 §5, arXiv:2301.07896 §4): a transient
worker loss must not abort an hour of upstream work. This module is the
policy half of the resilience layer — *what* to do when something fails.
The mechanisms (supervised fork pools, task replay, RPC retry) live at
the call sites in ``execution/parallel_map.py``, ``workflow/`` and
``rpc/http.py``.

Failure taxonomy — every exception maps to exactly one category:

- ``TRANSIENT``  — connection resets/refusals, injected synthetic faults;
  safe to retry anywhere.
- ``TIMEOUT``    — a deadline expired (chunk deadline, socket timeout);
  retryable, the work may simply have been slow.
- ``WORKER_LOST`` — a pool worker died (OOM-kill, SIGKILL, segfault); the
  work unit is intact, only the executor is gone — retry on a fresh pool.
- ``POISON``     — deterministic user-code failure (the same inputs will
  fail the same way); retrying wastes time — degrade to the serial
  in-driver path for a clean traceback, then raise.
- ``FATAL``      — interrupts/exits; never retried, never quarantined.
"""

import enum
import hashlib
import time
from typing import Any, FrozenSet, Optional

__all__ = [
    "FailureCategory",
    "classify_failure",
    "RetryPolicy",
    "Deadline",
    "WorkerLostError",
    "ChunkTimeoutError",
    "InjectedFaultError",
    "ParallelMapError",
]


class FailureCategory(enum.Enum):
    TRANSIENT = "transient"
    TIMEOUT = "timeout"
    WORKER_LOST = "worker_lost"
    POISON = "poison"
    FATAL = "fatal"


class WorkerLostError(RuntimeError):
    """A pool worker process died (OOM-killed, segfaulted, SIGKILLed)
    while its chunk was in flight."""


class ChunkTimeoutError(TimeoutError):
    """A chunk exceeded its per-chunk deadline (``fugue.tpu.map.chunk_timeout``)."""


class InjectedFaultError(RuntimeError):
    """Synthetic error raised by the FaultInjector (always TRANSIENT)."""


class ParallelMapError(RuntimeError):
    """Terminal failure of a parallel map after retries AND the serial
    quarantine path failed. Carries a per-partition failure report."""

    def __init__(self, report: dict):
        self.report = dict(report)
        lines = [
            f"  partition {no}: {err}" for no, err in sorted(self.report.items())
        ]
        super().__init__(
            "parallel map failed after retry and serial fallback on "
            f"{len(self.report)} partition(s):\n" + "\n".join(lines)
        )


_TRANSIENT_TYPES = (
    ConnectionError,  # covers ConnectionRefused/Reset/Aborted, BrokenPipe
    InterruptedError,
)
_FATAL_TYPES = (KeyboardInterrupt, SystemExit, GeneratorExit)


def classify_failure(exc: BaseException) -> FailureCategory:
    """Map an exception to its failure category (see module docstring)."""
    if isinstance(exc, _FATAL_TYPES):
        return FailureCategory.FATAL
    if isinstance(exc, WorkerLostError):
        return FailureCategory.WORKER_LOST
    if isinstance(exc, (ChunkTimeoutError, TimeoutError)):
        return FailureCategory.TIMEOUT
    if isinstance(exc, (InjectedFaultError,) + _TRANSIENT_TYPES):
        return FailureCategory.TRANSIENT
    if isinstance(exc, OSError):
        # EAGAIN/EINTR-style host pressure; pandas/pyarrow raise subclasses
        # for real file errors but those carry filename context and are rare
        # on the in-memory map path — treat the bucket as retry-worthy
        return FailureCategory.TRANSIENT
    return FailureCategory.POISON


_RETRYABLE: FrozenSet[FailureCategory] = frozenset(
    {
        FailureCategory.TRANSIENT,
        FailureCategory.TIMEOUT,
        FailureCategory.WORKER_LOST,
    }
)


class RetryPolicy:
    """Bounded retry with exponential backoff and deterministic jitter.

    ``delay(attempt)`` grows as ``base * multiplier**(attempt-1)`` capped
    at ``max_delay``, plus up to ``jitter`` fraction of that value. The
    jitter is a hash of ``(seed, attempt)`` — deterministic, so tests and
    cross-run debugging see identical schedules, while distinct seeds
    (e.g. chunk ids) de-synchronize retry storms.
    """

    def __init__(
        self,
        max_attempts: int = 3,
        base_delay: float = 0.1,
        multiplier: float = 2.0,
        max_delay: float = 30.0,
        jitter: float = 0.1,
        retry_on: FrozenSet[FailureCategory] = _RETRYABLE,
    ):
        self.max_attempts = max(1, int(max_attempts))
        self.base_delay = max(0.0, float(base_delay))
        self.multiplier = max(1.0, float(multiplier))
        self.max_delay = max(0.0, float(max_delay))
        self.jitter = max(0.0, float(jitter))
        self.retry_on = frozenset(retry_on)

    @classmethod
    def from_conf(
        cls,
        conf: Any,
        prefix: str = "fugue.tpu.retry",
        default_attempts: int = 3,
    ) -> "RetryPolicy":
        """Build from conf keys ``<prefix>.attempts/base/multiplier/
        max_backoff/jitter``; absent keys use the constructor defaults."""
        return cls(
            max_attempts=int(conf.get(f"{prefix}.attempts", default_attempts)),
            base_delay=float(conf.get(f"{prefix}.base", 0.1)),
            multiplier=float(conf.get(f"{prefix}.multiplier", 2.0)),
            max_delay=float(conf.get(f"{prefix}.max_backoff", 30.0)),
            jitter=float(conf.get(f"{prefix}.jitter", 0.1)),
        )

    def should_retry(self, category: FailureCategory, attempts_done: int) -> bool:
        """True when a unit that has already failed ``attempts_done`` times
        deserves another attempt."""
        return attempts_done < self.max_attempts and category in self.retry_on

    def delay(self, attempt: int, seed: Any = None) -> float:
        """Backoff before attempt ``attempt`` (1-based count of failures)."""
        if self.base_delay <= 0:
            return 0.0
        raw = self.base_delay * (self.multiplier ** max(0, attempt - 1))
        raw = min(raw, self.max_delay)
        if self.jitter > 0:
            h = hashlib.blake2b(
                f"{seed}:{attempt}".encode(), digest_size=8
            ).digest()
            frac = int.from_bytes(h, "big") / float(1 << 64)
            raw += raw * self.jitter * frac
        return min(raw, self.max_delay * (1.0 + self.jitter))


class Deadline:
    """A wall-clock budget; ``Deadline.after(None | 0)`` never expires."""

    def __init__(self, seconds: Optional[float]):
        self._seconds = seconds
        self._t0 = time.monotonic()

    @classmethod
    def after(cls, seconds: Optional[float]) -> "Deadline":
        if seconds is not None and seconds <= 0:
            seconds = None
        return cls(seconds)

    @property
    def unbounded(self) -> bool:
        return self._seconds is None

    @property
    def expired(self) -> bool:
        return (
            self._seconds is not None
            and time.monotonic() - self._t0 > self._seconds
        )

    def remaining(self) -> Optional[float]:
        if self._seconds is None:
            return None
        return max(0.0, self._seconds - (time.monotonic() - self._t0))

    def raise_if_expired(self, what: str = "operation") -> None:
        if self.expired:
            raise ChunkTimeoutError(
                f"{what} exceeded its {self._seconds}s deadline"
            )
