"""Static output-schema inference over parsed SQL plans.

The warehouse engine's raw-SQL path normally reads result schemas from
driver introspection + value sampling; an EMPTY result set with computed
columns has nothing to sample and used to degrade to string columns
(round-3/4 advice item). The reference never hits this because ibis
expressions carry types end-to-end
(`/root/reference/fugue_ibis/execution_engine.py:41-58`). This module is
the equivalent for the in-tree stack: parse the statement with
``sql.parser`` and fold ``ColumnExpr.infer_type`` over the plan, deriving
the output schema from the INPUT frames' schemas alone.

Best-effort by design: returns None the moment anything is unknown
(unresolvable name, untyped expression, correlated subquery), and callers
fall back to sampling. Used only where sampling is strictly worse (empty
results), so a conservative None can never regress an answer.
"""

from typing import Dict, List, Optional

import pyarrow as pa

from ..schema import Schema
from .parser import (
    JoinNode,
    LimitNode,
    PlanNode,
    Scan,
    SelectNode,
    SetOpNode,
    SortNode,
    SQLParser,
    Subquery,
)


def infer_output_schema(
    sql: str, schemas: Dict[str, Schema]
) -> Optional[Schema]:
    """Output schema of ``sql`` over input tables ``schemas``, or None."""
    try:
        plan = SQLParser(sql).parse_full()
    except Exception:
        return None
    try:
        return _infer(plan, schemas)
    except Exception:
        return None


def _infer(plan: Optional[PlanNode], schemas: Dict[str, Schema]) -> Optional[Schema]:
    if plan is None:
        return None
    if isinstance(plan, Scan):
        s = schemas.get(plan.name)
        return s
    if isinstance(plan, Subquery):
        return _infer(plan.child, schemas)
    if isinstance(plan, (SortNode, LimitNode)):
        return _infer(plan.child, schemas)
    if isinstance(plan, SetOpNode):
        return _infer(plan.left, schemas)
    if isinstance(plan, JoinNode):
        left = _infer(plan.left, schemas)
        right = _infer(plan.right, schemas)
        if left is None or right is None:
            return None
        on = set(plan.on)
        fields = list(left.fields) + [
            f for f in right.fields if f.name not in on
        ]
        if plan.how in ("semi", "anti", "left_semi", "left_anti"):
            fields = list(left.fields)
        return Schema(fields)
    if isinstance(plan, SelectNode):
        child = (
            _infer(plan.child, schemas)
            if plan.child is not None
            else Schema([])
        )
        if child is None:
            return None
        fields: List[pa.Field] = []
        for c in plan.projections:
            name = getattr(c, "name", None)
            if name == "*":
                fields.extend(child.fields)
                continue
            out = c.output_name
            if out == "":
                return None
            tp = c.infer_type(child)
            if tp is None:
                return None
            fields.append(pa.field(out, tp))
        return Schema(fields)
    return None
