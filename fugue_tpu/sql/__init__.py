from .fsql import FugueSQLWorkflow, fugue_sql, fugue_sql_flow, fill_sql_template
from . import dialect  # registers the transpile_sql implementation
from .dialect import DialectProfile, register_dialect, transpile
from .local_sql import LocalSQLEngine
from .parser import SQLParser
from .executor import SQLExecutor

fsql = fugue_sql_flow  # reference-compatible alias

__all__ = [
    "FugueSQLWorkflow",
    "fugue_sql",
    "fugue_sql_flow",
    "fsql",
    "fill_sql_template",
    "LocalSQLEngine",
    "SQLParser",
    "SQLExecutor",
    "DialectProfile",
    "register_dialect",
    "transpile",
]

from ..execution.factory import register_sql_engine

register_sql_engine("local", lambda engine, **kw: LocalSQLEngine(engine))
register_sql_engine("sql", lambda engine, **kw: LocalSQLEngine(engine))
