"""SQL dialect profiles + a token-level transpiler (the sqlglot role).

The reference transpiles FugueSQL SELECT text between backend dialects via
sqlglot behind the ``transpile_sql`` plugin
(`/root/reference/fugue/collections/sql.py:25-45`), so one query can run on
a Spark-dialect engine and a DuckDB-dialect engine alike. sqlglot is not in
this environment; this module implements the load-bearing subset natively:

- **quoting**: string vs identifier quote conventions (spark/fugue treat
  ``"x"`` as a string and `` `x` `` as an identifier; postgres/sqlite treat
  ``"x"`` as an identifier; mysql uses backticks; mssql uses ``[x]``);
- **LIMIT/TOP**: ``LIMIT n`` ↔ ``SELECT TOP n`` (mssql);
- **type names** in ``CAST(x AS t)``: fugue's canonical names map per
  dialect (``double`` → ``DOUBLE PRECISION`` on postgres, ``REAL`` on
  sqlite, …), both directions;
- **function renames**: ``SUBSTRING``/``SUBSTR``, ``STRING_AGG``/
  ``GROUP_CONCAT``, ``RANDOM``/``RAND``, ``NVL``/``IFNULL`` → ``COALESCE``,
  ``CEILING``/``CEIL``, both directions via a canonical name;
- **boolean literals**: ``TRUE``/``FALSE`` → ``1``/``0`` where the dialect
  has no boolean type (sqlite, mssql).

The pipeline is: tokenize with the SOURCE profile's quote conventions →
canonicalize names → emit with the TARGET profile's conventions. Everything
unrecognized passes through verbatim, so the transpiler never rejects a
query — it only rewrites the constructs it knows.

Registered as the ``transpile_sql`` plugin (see ``collections/sql.py``);
the warehouse engine routes its generated SQL through it
(`fugue_tpu/warehouse/execution_engine.py`).
"""

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..exceptions import FugueSQLSyntaxError

# ---------------------------------------------------------------------------
# profiles
# ---------------------------------------------------------------------------

# canonical function names (the in-tree/fugue spelling) per dialect
_FUNCS_SQLITE = {
    "SUBSTRING": "SUBSTR",
    "STRING_AGG": "GROUP_CONCAT",
    "CEILING": "CEIL",
}
_FUNCS_MYSQL = {"RANDOM": "RAND", "STRING_AGG": "GROUP_CONCAT"}
# canonical spellings are already SUBSTRING/CEILING (the T-SQL ones)
_FUNCS_MSSQL = {"RANDOM": "RAND"}

# read-side aliases accepted from ANY dialect and normalized to the
# canonical spelling (same arg shapes) — SUBSTR/CEIL normalize here so a
# fugue/spark query using the short forms still emits valid SQL on
# dialects that only have the long spellings (sqlite targets re-shorten
# via their own func_map)
_READ_ALIASES = {
    "NVL": "COALESCE",
    "IFNULL": "COALESCE",
    "SUBSTR": "SUBSTRING",
    "CEIL": "CEILING",
}

# canonical type names are fugue schema-expression names (lower) plus the
# standard SQL spellings normalized onto them
_CANON_TYPES = {
    "INT": "int",
    "INTEGER": "int",
    "BIGINT": "long",
    "LONG": "long",
    "SMALLINT": "short",
    "SHORT": "short",
    "TINYINT": "byte",
    "BYTE": "byte",
    "FLOAT": "float",
    "REAL": "float",
    "DOUBLE": "double",
    "DOUBLE PRECISION": "double",
    "STR": "str",
    "STRING": "str",
    "TEXT": "str",
    "VARCHAR": "str",
    "BOOL": "bool",
    "BOOLEAN": "bool",
    "DATETIME": "datetime",
    "TIMESTAMP": "datetime",
    "DATE": "date",
    "BYTES": "bytes",
    "BLOB": "bytes",
    "BINARY": "bytes",
    "BYTEA": "bytes",
    "VARBINARY": "bytes",
}


@dataclass(frozen=True)
class DialectProfile:
    """Everything the transpiler needs to read/write one dialect."""

    name: str
    # how identifiers are quoted on OUTPUT: ('"', '"'), ('`', '`'), ('[', ']')
    ident_quote: Tuple[str, str] = ('"', '"')
    # whether a double-quoted token is a STRING (spark-style) or an identifier
    dquote_is_string: bool = False
    # whether backticks quote identifiers when READING
    backtick_idents: bool = False
    # whether [brackets] quote identifiers when READING
    bracket_idents: bool = False
    # "limit" or "top"
    limit_style: str = "limit"
    # TRUE/FALSE rendering; None = keep the keywords
    bool_literals: Optional[Tuple[str, str]] = None
    # canonical fugue type name -> dialect type name (CAST targets)
    type_map: Dict[str, str] = field(default_factory=dict)
    # canonical function name -> dialect function name
    func_map: Dict[str, str] = field(default_factory=dict)
    # dialect type name -> canonical name OVERRIDES for reading (the
    # shared _CANON_TYPES table assumes standard-SQL meanings; e.g.
    # sqlite's REAL is 8-byte and mssql's FLOAT is double precision)
    type_read_map: Dict[str, str] = field(default_factory=dict)

    def func_to_canonical(self) -> Dict[str, str]:
        return {v.upper(): k for k, v in self.func_map.items()}


DIALECTS: Dict[str, DialectProfile] = {}


def register_dialect(profile: DialectProfile) -> None:
    DIALECTS[profile.name] = profile


def get_dialect(name: Optional[str]) -> DialectProfile:
    if name is None or name == "":
        name = "fugue"
    key = name.lower()
    if key not in DIALECTS:
        raise FugueSQLSyntaxError(
            f"unknown SQL dialect {name!r}; known: {sorted(DIALECTS)}"
        )
    return DIALECTS[key]


register_dialect(
    DialectProfile(
        name="fugue",  # the in-tree dialect: spark conventions
        ident_quote=("`", "`"),
        dquote_is_string=True,
        backtick_idents=True,
    )
)
register_dialect(
    DialectProfile(
        name="spark",
        ident_quote=("`", "`"),
        dquote_is_string=True,
        backtick_idents=True,
        type_map={"str": "STRING", "datetime": "TIMESTAMP", "bytes": "BINARY"},
    )
)
register_dialect(
    DialectProfile(
        name="sqlite",
        ident_quote=('"', '"'),
        bool_literals=("1", "0"),
        # sqlite REAL is ALWAYS 8-byte; INTEGER is up to 8-byte
        type_read_map={"REAL": "double", "INTEGER": "long", "INT": "long"},
        type_map={
            "int": "INTEGER",
            "long": "INTEGER",
            "short": "INTEGER",
            "byte": "INTEGER",
            "float": "REAL",
            "double": "REAL",
            "str": "TEXT",
            "bool": "INTEGER",
            "datetime": "TEXT",
            "date": "TEXT",
            "bytes": "BLOB",
        },
        func_map=_FUNCS_SQLITE,
    )
)
register_dialect(
    DialectProfile(
        name="postgres",
        ident_quote=('"', '"'),
        type_map={
            "int": "INTEGER",
            "long": "BIGINT",
            "short": "SMALLINT",
            "byte": "SMALLINT",
            "float": "REAL",
            "double": "DOUBLE PRECISION",
            "str": "TEXT",
            "bool": "BOOLEAN",
            "datetime": "TIMESTAMP",
            "date": "DATE",
            "bytes": "BYTEA",
        },
    )
)
register_dialect(
    DialectProfile(
        name="mysql",
        ident_quote=("`", "`"),
        backtick_idents=True,
        type_map={
            "long": "BIGINT",
            "double": "DOUBLE",
            "str": "TEXT",
            "bool": "BOOLEAN",
            "datetime": "DATETIME",
            "bytes": "BLOB",
        },
        func_map=_FUNCS_MYSQL,
    )
)
register_dialect(
    DialectProfile(
        name="mssql",
        ident_quote=("[", "]"),
        bracket_idents=True,
        limit_style="top",
        bool_literals=("1", "0"),
        # T-SQL: FLOAT defaults to FLOAT(53) = double; REAL is float32
        type_read_map={
            "FLOAT": "double",
            "REAL": "float",
            "NVARCHAR": "str",
            "BIT": "bool",
            "DATETIME2": "datetime",
        },
        type_map={
            "long": "BIGINT",
            "float": "REAL",  # T-SQL: bare FLOAT means FLOAT(53) = double
            "double": "FLOAT",
            "str": "NVARCHAR(MAX)",
            "bool": "BIT",
            "datetime": "DATETIME2",
            "bytes": "VARBINARY(MAX)",
        },
        func_map=_FUNCS_MSSQL,
    )
)


# ---------------------------------------------------------------------------
# dialect-aware tokenizer (quote conventions differ per dialect, so the
# parser's spark-flavored tokenizer can't read postgres text)
# ---------------------------------------------------------------------------


@dataclass
class _Tok:
    kind: str  # IDENT QIDENT STRING NUMBER OP PUNCT
    value: str


def _tokenize(sql: str, p: DialectProfile) -> List[_Tok]:
    toks: List[_Tok] = []
    i, n = 0, len(sql)
    while i < n:
        c = sql[i]
        if c.isspace():
            i += 1
            continue
        if c == "-" and sql.startswith("--", i):
            j = sql.find("\n", i)
            i = n if j < 0 else j + 1
            continue
        if sql.startswith("/*", i):
            j = sql.find("*/", i + 2)
            i = n if j < 0 else j + 2
            continue
        if c == "'" or (c == '"' and p.dquote_is_string):
            val, i = _read_quoted(sql, i, c, c)
            toks.append(_Tok("STRING", val))
            continue
        if c == '"' and not p.dquote_is_string:
            val, i = _read_quoted(sql, i, '"', '"')
            toks.append(_Tok("QIDENT", val))
            continue
        if c == "`" and p.backtick_idents:
            val, i = _read_quoted(sql, i, "`", "`")
            toks.append(_Tok("QIDENT", val))
            continue
        if c == "[" and p.bracket_idents:
            j = sql.find("]", i + 1)
            if j < 0:
                raise FugueSQLSyntaxError(f"unterminated identifier at {i}")
            toks.append(_Tok("QIDENT", sql[i + 1 : j]))
            i = j + 1
            continue
        if c.isdigit() or (c == "." and i + 1 < n and sql[i + 1].isdigit()):
            j = i
            seen_dot = False
            while j < n and (sql[j].isdigit() or (sql[j] == "." and not seen_dot)):
                if sql[j] == ".":
                    seen_dot = True
                j += 1
            if j < n and sql[j] in "eE":
                k = j + 1
                if k < n and sql[k] in "+-":
                    k += 1
                if k < n and sql[k].isdigit():
                    while k < n and sql[k].isdigit():
                        k += 1
                    j = k
            toks.append(_Tok("NUMBER", sql[i:j]))
            i = j
            continue
        if c.isalpha() or c == "_":
            j = i
            while j < n and (sql[j].isalnum() or sql[j] == "_"):
                j += 1
            toks.append(_Tok("IDENT", sql[i:j]))
            i = j
            continue
        matched = False
        for op in ("<>", "<=", ">=", "!=", "==", "||", "<<", ">>"):
            if sql.startswith(op, i):
                toks.append(_Tok("OP", op))
                i += len(op)
                matched = True
                break
        if matched:
            continue
        if c in "+-*/%<>=&|^~!":
            toks.append(_Tok("OP", c))
        elif c in "(),.;[]{}:?@#$":
            toks.append(_Tok("PUNCT", c))
        else:
            raise FugueSQLSyntaxError(f"unexpected character {c!r} at {i}")
        i += 1
    return toks


def _read_quoted(sql: str, i: int, open_c: str, close_c: str) -> Tuple[str, int]:
    j = i + 1
    buf: List[str] = []
    n = len(sql)
    while j < n:
        if sql[j] == close_c:
            if j + 1 < n and sql[j + 1] == close_c:  # doubled-quote escape
                buf.append(close_c)
                j += 2
                continue
            return "".join(buf), j + 1
        buf.append(sql[j])
        j += 1
    raise FugueSQLSyntaxError(f"unterminated quote at {i}")


# ---------------------------------------------------------------------------
# transpiler
# ---------------------------------------------------------------------------


def transpile(
    raw: str, from_dialect: Optional[str], to_dialect: Optional[str]
) -> str:
    """Transpile ``raw`` between two registered dialects. Identity when the
    profiles are the same object. Constructs it does not understand pass
    through verbatim."""
    src = get_dialect(from_dialect)
    dst = get_dialect(to_dialect)
    if src is dst:
        return raw
    toks = _tokenize(raw, src)
    toks = _canonicalize(toks, src)
    if src.limit_style != dst.limit_style:
        toks = _convert_limit(toks, dst.limit_style)
    return _emit(toks, dst)


def _canonicalize(toks: List[_Tok], src_profile: DialectProfile) -> List[_Tok]:
    """Rename dialect functions/types to canonical names in place."""
    to_canon = src_profile.func_to_canonical()
    out: List[_Tok] = []
    i = 0
    cast_depth: List[int] = []  # paren depths of open CAST(
    depth = 0
    while i < len(toks):
        t = toks[i]
        if t.kind == "PUNCT" and t.value == "(":
            depth += 1
        elif t.kind == "PUNCT" and t.value == ")":
            if cast_depth and cast_depth[-1] == depth:
                cast_depth.pop()
            depth -= 1
        if t.kind == "IDENT":
            up = t.value.upper()
            nxt = toks[i + 1] if i + 1 < len(toks) else None
            if up == "CAST" and nxt is not None and nxt.value == "(":
                cast_depth.append(depth + 1)
                out.append(t)
                i += 1
                continue
            if (
                cast_depth
                and cast_depth[-1] == depth
                and out
                and out[-1].kind == "IDENT"
                and out[-1].value.upper() == "AS"
            ):
                # the CAST target type: may span two words (DOUBLE PRECISION)
                words = [up]
                if (
                    nxt is not None
                    and nxt.kind == "IDENT"
                    and f"{up} {nxt.value.upper()}" in _CANON_TYPES
                ):
                    words.append(nxt.value.upper())
                    i += 1
                tname = " ".join(words)
                canon = src_profile.type_read_map.get(tname) or _CANON_TYPES.get(
                    tname
                )
                out.append(_Tok("TYPE", canon if canon is not None else t.value))
                i += 1
                # drop a parenthesized size suffix of a RECOGNIZED type —
                # VARCHAR(10) → str; the canonical types carry no modifier
                if (
                    canon is not None
                    and i < len(toks)
                    and toks[i].value == "("
                ):
                    d = 0
                    while i < len(toks):
                        if toks[i].value == "(":
                            d += 1
                        elif toks[i].value == ")":
                            d -= 1
                            if d == 0:
                                i += 1
                                break
                        i += 1
                continue
            if (
                nxt is not None
                and nxt.value == "("
                and (up in to_canon or up in _READ_ALIASES)
            ):
                out.append(
                    _Tok("IDENT", to_canon.get(up, _READ_ALIASES.get(up, up)))
                )
                i += 1
                continue
        out.append(t)
        i += 1
    return out


def _convert_limit(toks: List[_Tok], target_style: str) -> List[_Tok]:
    """LIMIT n ↔ SELECT TOP n at paren depth 0."""
    out = list(toks)
    if target_style == "top":
        # a top-level set operation makes TOP non-equivalent (it would bind
        # to the first branch, not the combined result) — leave LIMIT alone
        depth = 0
        for t in out:
            if t.value == "(":
                depth += 1
            elif t.value == ")":
                depth -= 1
            elif (
                depth == 0
                and t.kind == "IDENT"
                and t.value.upper() in ("UNION", "EXCEPT", "INTERSECT")
            ):
                return out
        # find top-level LIMIT n; move as TOP n after the first SELECT
        depth = 0
        for i, t in enumerate(out):
            if t.value == "(":
                depth += 1
            elif t.value == ")":
                depth -= 1
            elif (
                depth == 0
                and t.kind == "IDENT"
                and t.value.upper() == "LIMIT"
                and i + 1 < len(out)
                and out[i + 1].kind == "NUMBER"
            ):
                num = out[i + 1]
                del out[i : i + 2]
                for j, s in enumerate(out):
                    if s.kind == "IDENT" and s.value.upper() == "SELECT":
                        out[j + 1 : j + 1] = [_Tok("IDENT", "TOP"), num]
                        break
                break
    else:
        # SELECT TOP n ... -> ... LIMIT n
        for i, t in enumerate(out):
            if (
                t.kind == "IDENT"
                and t.value.upper() == "TOP"
                and i > 0
                and out[i - 1].value.upper() == "SELECT"
                and i + 1 < len(out)
                and out[i + 1].kind == "NUMBER"
            ):
                num = out[i + 1]
                del out[i : i + 2]
                out.extend([_Tok("IDENT", "LIMIT"), num])
                break
    return out


_NO_SPACE_BEFORE = {",", ")", ".", ";"}
_NO_SPACE_AFTER = {"(", "."}


def _emit(toks: List[_Tok], dst: DialectProfile) -> str:
    parts: List[str] = []
    prev: Optional[_Tok] = None
    for i, t in enumerate(toks):
        nxt = toks[i + 1] if i + 1 < len(toks) else None
        if t.kind == "STRING":
            text = "'" + t.value.replace("'", "''") + "'"
        elif t.kind == "QIDENT":
            o, c = dst.ident_quote
            text = o + t.value.replace(c, c + c) + c
        elif t.kind == "TYPE":
            text = dst.type_map.get(t.value, t.value)
        elif t.kind == "IDENT":
            up = t.value.upper()
            if dst.bool_literals is not None and up in ("TRUE", "FALSE"):
                text = dst.bool_literals[0 if up == "TRUE" else 1]
            elif (
                up in dst.func_map
                and nxt is not None
                and nxt.value == "("
            ):
                # only CALLS rename — a column named like a function stays
                text = dst.func_map[up]
            else:
                text = t.value
        else:
            text = t.value
        sep = " "
        if prev is None:
            sep = ""
        elif text in _NO_SPACE_BEFORE:
            sep = ""
        elif prev.value in _NO_SPACE_AFTER and prev.kind == "PUNCT":
            sep = ""
        elif prev.kind in ("IDENT", "QIDENT") and text == "(":
            # function call / CAST parens hug the name; this also joins
            # `name (` in FROM clauses, which SQL treats identically
            sep = ""
        parts.append(sep + text)
        prev = _Tok(t.kind if t.kind != "TYPE" else "IDENT", text)
    return "".join(parts)


# ---------------------------------------------------------------------------
# plugin registration: this IS the transpile_sql implementation
# ---------------------------------------------------------------------------

from ..collections.sql import transpile_sql  # noqa: E402


@transpile_sql.candidate(
    lambda raw, from_dialect, to_dialect: (
        from_dialect is not None
        and to_dialect is not None
        and from_dialect != to_dialect
        and from_dialect.lower() in DIALECTS
        and to_dialect.lower() in DIALECTS
    )
)
def _transpile_registered(
    raw: str, from_dialect: Optional[str], to_dialect: Optional[str]
) -> str:
    return transpile(raw, from_dialect, to_dialect)
