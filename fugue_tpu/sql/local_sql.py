"""The in-tree SQL engine facet.

Replaces the reference's qpd (pandas SQL) and DuckDB SQL engines
(`fugue/execution/native_execution_engine.py:42-66`,
`fugue_duckdb/execution_engine.py:36`) — neither dependency exists here.
SQL parses to a logical plan and executes through the PARENT execution
engine's verbs, so the same SQL distributes on the TPU engine.

Tables (for deterministic-checkpoint ``storage_type="table"`` and
``yield_table_as``) are parquet files in a managed directory — the host-side
"warehouse" equivalent.
"""

import os
from typing import Any, Dict, Optional

from ..collections.sql import StructuredRawSQL
from ..dataframe import DataFrame, DataFrames
from ..execution.execution_engine import SQLEngine
from .executor import SQLExecutor
from .parser import SQLParser


class LocalSQLEngine(SQLEngine):
    """Dialect: spark-ish subset, parsed in-tree."""

    @property
    def is_distributed(self) -> bool:
        return self.execution_engine.is_distributed

    @property
    def dialect(self) -> Optional[str]:
        return "spark"

    def select(self, dfs: DataFrames, statement: StructuredRawSQL) -> DataFrame:
        sql = statement.construct(dialect=self.dialect, log=self.log)
        plan = SQLParser(sql).parse_full()
        return SQLExecutor(self.execution_engine, dict(dfs)).run(plan)

    # -- table storage ------------------------------------------------------
    def _table_dir(self) -> str:
        from ..constants import FUGUE_CONF_WORKFLOW_CHECKPOINT_PATH

        base = self.conf.get(FUGUE_CONF_WORKFLOW_CHECKPOINT_PATH, "")
        if base == "":
            import tempfile

            base = os.path.join(tempfile.gettempdir(), "fugue_tpu_tables")
        path = os.path.join(base, "_tables")
        os.makedirs(path, exist_ok=True)
        return path

    def _table_path(self, table: str) -> str:
        return os.path.join(self._table_dir(), table + ".parquet")

    def table_exists(self, table: str) -> bool:
        return os.path.exists(self._table_path(table))

    def save_table(
        self,
        df: DataFrame,
        table: str,
        mode: str = "overwrite",
        partition_spec: Any = None,
        **kwargs: Any,
    ) -> None:
        self.execution_engine.save_df(
            df, self._table_path(table), format_hint="parquet", mode=mode, **kwargs
        )

    def load_table(self, table: str, **kwargs: Any) -> DataFrame:
        return self.execution_engine.load_df(
            self._table_path(table), format_hint="parquet"
        )
