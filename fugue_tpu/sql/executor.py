"""Logical-plan executor over ExecutionEngine verbs.

Each plan node lowers to engine operations (join/union/select/take/...), so
SQL inherits every engine's execution strategy — on the TPU engine,
aggregations take the device segment-reduction path and projections compile
with the jnp evaluator.
"""

from typing import Any, Dict, List, Optional

import pandas as pd

from ..column import SelectColumns, col as _col
from ..column.expressions import (
    ColumnExpr,
    _LitColumnExpr,
    _NamedColumnExpr,
    _WindowExpr,
)


def _referenced_names(expr: "ColumnExpr") -> List[str]:
    """All column names referenced anywhere in the expression tree."""
    names: List[str] = []

    def walk(e: "ColumnExpr") -> None:
        if isinstance(e, _NamedColumnExpr):
            names.append(e.name)
        for c in e.children:
            walk(c)

    walk(expr)
    return names
from ..column.functions import is_agg
from ..dataframe import ArrayDataFrame, DataFrame, PandasDataFrame
from ..exceptions import FugueSQLRuntimeError, FugueSQLSyntaxError
from ..execution.execution_engine import ExecutionEngine
from .parser import (
    JoinNode,
    LimitNode,
    PlanNode,
    Scan,
    SelectNode,
    SetOpNode,
    SortNode,
    Subquery,
)


def _contains_window(expr: Any) -> bool:
    if isinstance(expr, _WindowExpr):
        return True
    return any(_contains_window(c) for c in getattr(expr, "children", []))


class SQLExecutor:
    def __init__(self, engine: ExecutionEngine, dfs: Dict[str, DataFrame]):
        self._engine = engine
        self._dfs = dict(dfs)

    def run(self, plan: PlanNode) -> DataFrame:
        return self._exec(plan)

    def _exec(self, node: PlanNode) -> DataFrame:
        e = self._engine
        if isinstance(node, Scan):
            if node.name not in self._dfs:
                raise FugueSQLRuntimeError(
                    f"table {node.name!r} not found; available: {sorted(self._dfs)}"
                )
            return self._dfs[node.name]
        if isinstance(node, Subquery):
            return self._exec(node.child)
        if isinstance(node, JoinNode):
            left = self._exec(node.left)
            right = self._exec(node.right)
            if node.condition is None:
                return e.join(left, right, how=node.how, on=node.on or None)
            # non-equi ON: equi-join (or cross product when no equi keys)
            # then filter the residual predicate over the joined output
            if node.how not in ("inner", "cross"):
                raise NotImplementedError(
                    "non-equi join conditions are supported for INNER joins only"
                )
            if len(node.on) > 0:
                res = e.join(left, right, how="inner", on=node.on)
            else:
                res = e.join(left, right, how="cross")
            return e.filter(res, node.condition)
        if isinstance(node, SetOpNode):
            left = self._exec(node.left)
            right = self._exec(node.right)
            if node.op == "union":
                return e.union(left, right, distinct=node.distinct)
            if node.op == "except":
                return e.subtract(left, right, distinct=True)
            return e.intersect(left, right, distinct=True)
        if isinstance(node, SortNode):
            child = node.child
            sort_names = [n for n, _ in node.by]
            extras: List[str] = []
            # standard SQL: ORDER BY may reference source columns that the
            # projection drops — augment the projection, sort, then drop.
            # Expression sorts whose inputs the projection drops compute
            # INSIDE the select scope the same way
            if isinstance(child, SelectNode) and child.child is not None:
                out_names = {
                    c.output_name
                    for c in child.projections
                    if c.output_name not in ("", "*")
                }
                has_wildcard = any(
                    isinstance(c, _NamedColumnExpr) and c.name == "*"
                    for c in child.projections
                )
                missing = [
                    n
                    for n in sort_names
                    if n not in node.exprs
                    and n not in out_names
                    and not has_wildcard
                ]
                alias_names = {
                    c.output_name
                    for c in child.projections
                    if c.output_name not in ("", "*")
                    and not (
                        isinstance(c, _NamedColumnExpr)
                        and c.name == c.output_name
                    )
                }
                missing_exprs = []
                for n in sort_names:
                    if n not in node.exprs or has_wildcard:
                        continue
                    refs = _referenced_names(node.exprs[n])
                    if all(r in out_names for r in refs):
                        continue  # evaluates over the select output later
                    used_aliases = [r for r in refs if r in alias_names]
                    if len(used_aliases) > 0:
                        # pre-projection scope has no aliases; the select
                        # output lacks the dropped source columns — no
                        # scope can evaluate this expression
                        raise FugueSQLSyntaxError(
                            f"ORDER BY expression {n!r} mixes projection "
                            f"aliases {used_aliases} with source columns "
                            "the projection drops"
                        )
                    missing_exprs.append(n)
                if (
                    len(missing) + len(missing_exprs) > 0
                    and len(child.group_by) == 0
                    and not child.distinct
                    and not any(is_agg(c) for c in child.projections)
                ):
                    child = SelectNode(
                        child.child,
                        list(child.projections)
                        + [_col(n) for n in missing]
                        + [node.exprs[n].alias(n) for n in missing_exprs],
                        child.where,
                        child.group_by,
                        child.having,
                        child.distinct,
                    )
                    extras = missing + missing_exprs
            df = self._exec(child)
            local = e.to_df(df).as_local_bounded()
            # ORDER BY <ordinal>: a bare int literal is SQL positional
            # ordering — resolve it against the USER-VISIBLE columns (the
            # augmented frame also carries hidden sort helpers)
            visible = [n for n in local.schema.names if n not in extras]
            for j, (n, asc) in enumerate(list(node.by)):
                ex = node.exprs.get(n)
                if isinstance(ex, _LitColumnExpr):
                    if not isinstance(ex.value, int) or isinstance(ex.value, bool):
                        raise FugueSQLSyntaxError(
                            f"can't ORDER BY the constant {ex.value!r}"
                        )
                    if not (1 <= ex.value <= len(visible)):
                        raise FugueSQLSyntaxError(
                            f"ORDER BY position {ex.value} is out of range "
                            f"(select has {len(visible)} columns)"
                        )
                    sort_names[j] = visible[ex.value - 1]
            # expression sorts not yet materialized evaluate over the
            # RESULT frame (its columns are the select outputs)
            still = [
                n
                for n in sort_names
                if n in node.exprs and n not in local.schema
            ]
            for n in still:
                bad = [
                    r
                    for r in _referenced_names(node.exprs[n])
                    if r not in local.schema
                ]
                if len(bad) > 0:
                    raise FugueSQLSyntaxError(
                        f"ORDER BY expression {n!r} references column(s) "
                        f"{bad} not in the select output "
                        f"{local.schema.names} (aggregated selects can "
                        "only order by projected columns)"
                    )
            if len(still) > 0:
                local = e.to_df(
                    e.assign(local, [node.exprs[n].alias(n) for n in still])
                ).as_local_bounded()
                extras = extras + still
            absent = [n for n in sort_names if n not in local.schema]
            if len(absent) > 0:
                raise FugueSQLSyntaxError(
                    f"ORDER BY column(s) {absent} are not in the select output "
                    f"{local.schema.names} (aggregated selects can only order "
                    "by projected columns)"
                )
            pdf = local.as_pandas().sort_values(
                sort_names,
                ascending=[a for _, a in node.by],
                na_position="first",
            )
            if len(extras) > 0:
                pdf = pdf.drop(columns=extras)
            schema = local.schema - extras if len(extras) > 0 else local.schema
            return e.to_df(
                PandasDataFrame(pdf.reset_index(drop=True), schema)
            )
        if isinstance(node, LimitNode):
            df = self._exec(node.child)
            return e.take(df, node.n, presort="")
        if isinstance(node, SelectNode):
            return self._exec_select(node)
        raise FugueSQLSyntaxError(f"unknown plan node {type(node)}")

    # -- correlated subqueries (decorrelation to joins) ---------------------

    @staticmethod
    def _conjuncts(expr: Optional[ColumnExpr]) -> List[ColumnExpr]:
        from ..column.expressions import _BinaryOpExpr

        if expr is None:
            return []
        if isinstance(expr, _BinaryOpExpr) and expr.op == "&":
            return SQLExecutor._conjuncts(expr.left) + SQLExecutor._conjuncts(
                expr.right
            )
        return [expr]

    @staticmethod
    def _rebuild_and(cs: List[ColumnExpr]) -> Optional[ColumnExpr]:
        from ..column.expressions import _BinaryOpExpr

        cur: Optional[ColumnExpr] = None
        for c in cs:
            cur = c if cur is None else _BinaryOpExpr("&", cur, c)
        return cur

    def _scan_names(self, plan: Optional[PlanNode]) -> set:
        """Table names AND aliases visible in a plan's FROM tree."""
        names: set = set()

        def walk(p: Any) -> None:
            if isinstance(p, Scan):
                names.add(p.name)
                if p.alias:
                    names.add(p.alias)
                return
            if isinstance(p, Subquery):
                # a derived table HIDES its inner tables; only the alias is
                # visible to the enclosing scope
                if p.alias:
                    names.add(p.alias)
                return
            for f in getattr(p, "__dataclass_fields__", {}):
                v = getattr(p, f)
                if isinstance(v, PlanNode):
                    walk(v)

        if plan is not None:
            walk(plan)
        return names

    def _assert_no_foreign_refs(self, plan: PlanNode) -> None:
        """Refuse to run a subplan that references tables outside its own
        FROM tree (a correlated subquery in an unsupported position):
        qualifiers are stripped from column names at parse time, so running
        such a plan would silently bind outer refs to same-named inner
        columns."""
        own = self._scan_names(plan)

        def walk_expr(e: Any) -> None:
            if isinstance(e, _NamedColumnExpr):
                q = getattr(e, "_sql_qualifier", "")
                if q and q not in own:
                    raise NotImplementedError(
                        f"correlated subquery reference {q}.{e.name} is "
                        "only supported as an equality conjunct of a top-"
                        "level WHERE EXISTS / scalar subquery"
                    )
            for c in getattr(e, "children", []):
                walk_expr(c)

        def walk(p: Any) -> None:
            if isinstance(p, SelectNode):
                for c in p.projections:
                    walk_expr(c)
                if p.where is not None:
                    walk_expr(p.where)
                if p.having is not None:
                    walk_expr(p.having)
            if isinstance(p, JoinNode) and p.condition is not None:
                walk_expr(p.condition)
            for f in getattr(p, "__dataclass_fields__", {}):
                v = getattr(p, f)
                if isinstance(v, PlanNode):
                    walk(v)

        walk(plan)

    def _exec_memo(self, plan: PlanNode) -> DataFrame:
        """Execute a subquery's FROM tree once per analysis pass."""
        memo = getattr(self, "_plan_memo", None)
        if memo is None:
            memo = self._plan_memo = {}
        key = id(plan)
        if key not in memo:
            memo[key] = self._exec(plan)
        return memo[key]

    def _refs_outer(
        self, expr: ColumnExpr, ischema: Any, outer_names: set, oschema: Any
    ) -> bool:
        def walk(c: Any) -> bool:
            if isinstance(c, _NamedColumnExpr):
                q = getattr(c, "_sql_qualifier", "")
                if q and q in outer_names:
                    return True
                if not q and c.name not in ischema and c.name in oschema:
                    return True
            return any(walk(x) for x in getattr(c, "children", []))

        return walk(expr)

    def _corr_split(self, plan: PlanNode, outer_names: set, oschema: Any):
        """Analyze a subquery plan for equality correlation against the
        outer select. Returns (inner_df, pairs[(outer,inner)], residual,
        plan) for a correlated shape, "uncorrelated", or None (shape this
        decorrelator doesn't handle → let the generic path error)."""
        from ..column.expressions import _BinaryOpExpr

        if (
            not isinstance(plan, SelectNode)
            or plan.child is None
            or len(plan.group_by) > 0
            or plan.having is not None
            or plan.grouping_sets is not None
        ):
            return None
        inner_names = self._scan_names(plan.child)
        try:
            inner_df = self._exec_memo(plan.child)
        except Exception:
            return None
        ischema = inner_df.schema
        pairs: List[Any] = []
        residual: List[ColumnExpr] = []
        for c in self._conjuncts(plan.where):
            if (
                isinstance(c, _BinaryOpExpr)
                and c.op == "=="
                and isinstance(c.left, _NamedColumnExpr)
                and isinstance(c.right, _NamedColumnExpr)
            ):
                sides = []
                for cc in (c.left, c.right):
                    q = getattr(cc, "_sql_qualifier", "")
                    if q and q in inner_names:
                        sides.append("i")
                    elif q and q in outer_names:
                        sides.append("o")
                    elif cc.name in ischema:
                        sides.append("i")
                    elif cc.name in oschema:
                        sides.append("o")
                    else:
                        sides.append("?")
                if sides == ["i", "o"]:
                    pairs.append((c.right.name, c.left.name))
                    continue
                if sides == ["o", "i"]:
                    pairs.append((c.left.name, c.right.name))
                    continue
            residual.append(c)
        for c in residual:
            if self._refs_outer(c, ischema, outer_names, oschema):
                return None  # non-equality correlation — unsupported
        if len(pairs) == 0:
            return "uncorrelated"
        return inner_df, pairs, self._rebuild_and(residual), plan

    def _decorrelate(self, node: SelectNode, child: DataFrame):
        """Rewrite correlated EXISTS / scalar subqueries into joins against
        ``child``. Returns (node, child), possibly unchanged. Matches the
        capability the reference gets free from its SQL backends
        (``fugue_duckdb/execution_engine.py:95-105``)."""
        import dataclasses

        from ..collections.partition import PartitionSpec
        from ..column.expressions import _UnaryOpExpr
        from .parser import _SubqueryExistsExpr, _SubqueryScalarExpr

        e = self._engine
        outer_names = self._scan_names(node.child)
        oschema = child.schema

        # --- [NOT] EXISTS as top-level WHERE conjuncts → semi/anti join ----
        kept: List[ColumnExpr] = []
        changed = False
        for c in self._conjuncts(node.where):
            positive, core = True, c
            if (
                isinstance(c, _UnaryOpExpr)
                and c.op == "~"
                and isinstance(c.col, _SubqueryExistsExpr)
            ):
                positive, core = False, c.col
            if isinstance(core, _SubqueryExistsExpr):
                cplan = core.plan
                # ORDER BY / LIMIT>=1 can't change EXISTS truth per key
                while isinstance(cplan, SortNode) or (
                    isinstance(cplan, LimitNode) and cplan.n >= 1
                ):
                    cplan = cplan.child
                info = self._corr_split(cplan, outer_names, oschema)
                if info is not None and info != "uncorrelated":
                    inner_df, pairs, residual, _ = info
                    sub = (
                        e.filter(inner_df, residual)
                        if residual is not None
                        else inner_df
                    )
                    sub = e.select(
                        sub,
                        SelectColumns(
                            *[_col(ik).alias(on) for on, ik in pairs],
                            arg_distinct=True,
                        ),
                    )
                    child = e.join(
                        child,
                        sub,
                        how="left_semi" if positive else "left_anti",
                        on=[on for on, _ in pairs],
                    )
                    changed = True
                    continue
                if info is None and self._plan_refs_outer(
                    core.plan, outer_names, oschema
                ):
                    raise NotImplementedError(
                        "only equality-correlated EXISTS subqueries are "
                        "supported"
                    )
            kept.append(c)
        if changed:
            node = dataclasses.replace(node, where=self._rebuild_and(kept))

        # --- correlated scalar subqueries → left join on grouped aggregate -
        replacements: Dict[int, ColumnExpr] = {}
        counter = [0]

        def scan_scalar(expr: Any) -> None:
            nonlocal child
            if isinstance(expr, _SubqueryScalarExpr) and id(expr) not in replacements:
                info = self._corr_split(expr.plan, outer_names, oschema)
                if info is None or info == "uncorrelated":
                    return  # generic substitution (or its error) handles it
                inner_df, pairs, residual, plan = info
                if len(plan.projections) != 1 or not is_agg(plan.projections[0]):
                    raise NotImplementedError(
                        "correlated scalar subqueries must select exactly "
                        "one aggregate"
                    )
                tmp = f"__sq{counter[0]}__"
                counter[0] += 1
                while tmp in oschema:
                    tmp = "_" + tmp
                sub = (
                    e.filter(inner_df, residual)
                    if residual is not None
                    else inner_df
                )
                agg = plan.projections[0].infer_alias().alias(tmp)
                grouped = e.aggregate(
                    sub, PartitionSpec(by=[ik for _, ik in pairs]), [agg]
                )
                renamed = e.select(
                    grouped,
                    SelectColumns(
                        *[_col(ik).alias(on) for on, ik in pairs], _col(tmp)
                    ),
                )
                child = e.join(
                    child, renamed, how="left_outer", on=[on for on, _ in pairs]
                )
                repl: ColumnExpr = _col(tmp)
                inner_agg = plan.projections[0]
                if (
                    getattr(inner_agg, "func", "").upper() == "COUNT"
                ):
                    # COUNT over zero matching rows is 0, not NULL — the
                    # left join produces NULL for unmatched outer rows
                    from ..column import lit as _lit
                    from ..column.functions import coalesce as _coalesce

                    repl = _coalesce(repl, _lit(0))
                replacements[id(expr)] = repl
            for ch in getattr(expr, "children", []):
                scan_scalar(ch)

        for p in node.projections:
            scan_scalar(p)
        if node.where is not None:
            scan_scalar(node.where)
        if replacements:
            if any(
                type(p).__name__ == "_AllColumnsExpr" or p.output_name == "*"
                for p in node.projections
            ):
                raise NotImplementedError(
                    "correlated scalar subqueries with '*' projections are "
                    "not supported"
                )
            node = dataclasses.replace(
                node,
                projections=[
                    self._apply_replacements(p, replacements)
                    for p in node.projections
                ],
                where=(
                    self._apply_replacements(node.where, replacements)
                    if node.where is not None
                    else None
                ),
            )
        return node, child

    def _plan_refs_outer(
        self, plan: Any, outer_names: set, oschema: Any
    ) -> bool:
        """Best-effort: does the subquery reference outer columns at all?"""
        if not isinstance(plan, SelectNode) or plan.child is None:
            return False
        inner_names = self._scan_names(plan.child)
        try:
            ischema = self._exec_memo(plan.child).schema
        except Exception:
            return False
        for c in self._conjuncts(plan.where):
            if self._refs_outer(c, ischema, outer_names - inner_names, oschema):
                return True
        return False

    def _apply_replacements(
        self, expr: ColumnExpr, repl: Dict[int, ColumnExpr]
    ) -> ColumnExpr:
        from .parser import _SubqueryScalarExpr

        if isinstance(expr, _SubqueryScalarExpr) and id(expr) in repl:
            out = repl[id(expr)]
            if expr.as_name:
                out = out.alias(expr.as_name)
            if expr.as_type is not None:
                out = out.cast(expr.as_type)
            return out
        from ..column.expressions import (
            _BinaryOpExpr,
            _CaseWhenExpr,
            _FuncExpr,
            _InExpr,
            _LikeExpr,
            _UnaryOpExpr,
        )

        if isinstance(expr, _BinaryOpExpr):
            l = self._apply_replacements(expr.left, repl)
            r = self._apply_replacements(expr.right, repl)
            if l is expr.left and r is expr.right:
                return expr
            out = _BinaryOpExpr(expr.op, l, r)
        elif isinstance(expr, _InExpr):
            c = self._apply_replacements(expr.col, repl)
            if c is expr.col:
                return expr
            out = _InExpr(c, expr.values, expr.positive)
        elif isinstance(expr, _LikeExpr):
            c = self._apply_replacements(expr.col, repl)
            if c is expr.col:
                return expr
            out = _LikeExpr(c, expr.pattern, expr.positive)
        elif isinstance(expr, _UnaryOpExpr):
            c = self._apply_replacements(expr.col, repl)
            if c is expr.col:
                return expr
            out = _UnaryOpExpr(expr.op, c)
        elif isinstance(expr, _FuncExpr):
            args = [self._apply_replacements(a, repl) for a in expr.args]
            if all(a is b for a, b in zip(args, expr.args)):
                return expr
            out = _FuncExpr(
                expr.func, *args, arg_distinct=expr.is_distinct, is_agg=expr.is_agg
            )
        elif isinstance(expr, _CaseWhenExpr):
            cases = [
                (
                    self._apply_replacements(c, repl),
                    self._apply_replacements(v, repl),
                )
                for c, v in expr.cases
            ]
            default = (
                self._apply_replacements(expr.default, repl)
                if expr.default is not None
                else None
            )
            if default is expr.default and all(
                c is c0 and v is v0
                for (c, v), (c0, v0) in zip(cases, expr.cases)
            ):
                return expr
            out = _CaseWhenExpr(cases, default)
        else:
            return expr
        if expr.as_name:
            out = out.alias(expr.as_name)
        if expr.as_type is not None:
            out = out.cast(expr.as_type)
        return out

    def _decorrelate_safe(self, node: SelectNode, child: DataFrame):
        """Run decorrelation only when subquery expressions are present."""
        from .parser import _SubqueryExistsExpr, _SubqueryScalarExpr

        def has_sub(expr: Any) -> bool:
            if isinstance(expr, (_SubqueryExistsExpr, _SubqueryScalarExpr)):
                return True
            return any(has_sub(c) for c in getattr(expr, "children", []))

        exprs = list(node.projections)
        if node.where is not None:
            exprs.append(node.where)
        if not any(has_sub(x) for x in exprs):
            return node, child
        return self._decorrelate(node, child)

    def _exec_grouping_sets(self, node: SelectNode, child: DataFrame) -> DataFrame:
        """ROLLUP/CUBE/GROUPING SETS = union of per-set grouped aggregates,
        grouped-out key columns NULL (the reference gets these free from
        its SQL backends)."""
        import dataclasses

        from ..column import lit as _lit

        e = self._engine
        all_keys = [
            g.name for g in node.group_by if isinstance(g, _NamedColumnExpr)
        ]
        # WHERE applies identically to every set — filter ONCE, not per set
        if node.where is not None:
            child = e.filter(child, node.where)
            node = dataclasses.replace(node, where=None)
        parts: List[DataFrame] = []
        for s in node.grouping_sets or []:
            proj: List[ColumnExpr] = []
            for c in node.projections:
                base = c
                if (
                    isinstance(base, _NamedColumnExpr)
                    and not is_agg(base)
                    and base.name in all_keys
                    and base.name not in s
                ):
                    tp = child.schema[base.name].type
                    proj.append(
                        _lit(None).cast(tp).alias(base.output_name or base.name)
                    )
                    continue
                if not is_agg(base) and any(
                    n in all_keys and n not in s
                    for n in _referenced_names(base)
                ):
                    raise NotImplementedError(
                        "expressions over grouped-out keys are not supported "
                        "in GROUPING SETS projections"
                    )
                proj.append(base)
            sub_node = dataclasses.replace(
                node,
                projections=proj,
                group_by=[_col(k) for k in s],
                grouping_sets=None,
            )
            if len(s) == 0:
                # global aggregate: no grouping keys — project aggregates
                # (and NULL key stand-ins) over the whole frame
                parts.append(
                    e.select(
                        child,
                        SelectColumns(*[p.infer_alias() for p in proj]),
                        having=sub_node.having,
                    )
                )
                continue
            parts.append(self._exec_select_on(sub_node, child))
        res = parts[0]
        for p in parts[1:]:
            res = e.union(res, p, distinct=False)
        return res

    def _exec_select_on(self, node: SelectNode, child: DataFrame) -> DataFrame:
        """Execute a SelectNode against an ALREADY-materialized child."""
        import uuid

        tmp = f"__gs_{uuid.uuid4().hex[:8]}__"
        self._dfs[tmp] = child
        try:
            import dataclasses

            return self._exec_select(
                dataclasses.replace(node, child=Scan(tmp))
            )
        finally:
            self._dfs.pop(tmp, None)

    def _exec_select(self, node: SelectNode) -> DataFrame:
        e = self._engine
        if node.child is not None:
            # memoized: correlation analysis may already have run this tree
            pre_child = self._exec_memo(node.child)
            node, pre_child = self._decorrelate_safe(node, pre_child)
        else:
            pre_child = None
        node = self._substitute_subqueries(node)
        if node.child is None:
            # SELECT <literals> with no FROM → one constant row
            row: List[Any] = []
            fields = []
            import pyarrow as pa

            for i, c in enumerate(node.projections):
                if not isinstance(c, _LitColumnExpr):
                    raise FugueSQLSyntaxError(
                        "SELECT without FROM supports only literals"
                    )
                name = c.output_name or f"_{i}"
                row.append(c.value)
                tp = c.infer_type(None) or pa.string()
                fields.append(pa.field(name, tp))
            from ..schema import Schema

            return ArrayDataFrame([row], Schema(fields))
        child = pre_child
        # window functions: computed on host after WHERE, before projection
        has_window = any(_contains_window(c) for c in node.projections)
        if has_window:
            return self._exec_windowed_select(node, child)
        if node.grouping_sets is not None:
            return self._exec_grouping_sets(node, child)
        cols = SelectColumns(
            *[c.infer_alias() for c in node.projections], arg_distinct=node.distinct
        )
        if len(node.group_by) > 0 and any(
            not isinstance(g, _NamedColumnExpr) for g in node.group_by
        ):
            # GROUP BY <expression>: materialize each computed key as a
            # helper column, group by its name, and rewrite matching
            # projection/having subexpressions to reference it
            node, child = self._materialize_groupby_exprs(node, child)
            cols = SelectColumns(
                *[c.infer_alias() for c in node.projections],
                arg_distinct=node.distinct,
            )
        if len(node.group_by) > 0:
            gb_names: List[str] = []
            for g in node.group_by:
                if not isinstance(g, _NamedColumnExpr):
                    raise NotImplementedError(
                        "GROUP BY supports plain column references or "
                        "expressions that also appear in the SELECT list"
                    )
                gb_names.append(g.name)
            expanded = cols.replace_wildcard(child.schema).all_cols
            keys_in_proj_source = {
                c.name
                for c in expanded
                if isinstance(c, _NamedColumnExpr) and not is_agg(c)
            }
            proj_keys = {c.output_name for c in expanded if not is_agg(c)}
            having_needs_agg = node.having is not None and not any(
                is_agg(c) for c in expanded
            )
            if having_needs_agg or not (
                set(gb_names) == proj_keys
                or set(gb_names) == keys_in_proj_source
            ):
                # GROUP BY decoupled from the projection: aggregate by the
                # GROUP BY keys, then project/filter over the O(groups)
                # result — also the path for aggregate HAVING over a
                # key-only projection (eval_select can't see those aggs)
                return self._exec_decoupled_groupby(node, child, gb_names)
        return e.select(child, cols, where=node.where, having=node.having)

    def _materialize_groupby_exprs(
        self, node: SelectNode, child: DataFrame
    ) -> Any:
        """GROUP BY over computed expressions (the reference gets this free
        from backend SQL): each non-named key materializes as an assigned
        helper column on the child; identical TOP-LEVEL projections (by
        structural uuid, alias/cast ignored) rewrite to the helper name so
        the grouped evaluator sees plain keys. A grouped expression only
        appearing NESTED inside a projection still raises downstream."""
        import dataclasses

        from ..column.expressions import col as _named_col

        e = self._engine
        from ..column.eval import substitute_exprs
        from ..column.expressions import derived_name as _derived_name
        from ..column.expressions import structural_key as _structural_key

        # the wildcard must expand against the ORIGINAL schema, or the
        # helper columns would leak into SELECT *
        projections = list(
            SelectColumns(
                *[c.infer_alias() for c in node.projections]
            ).replace_wildcard(child.schema).all_cols
        )
        assigns: List[ColumnExpr] = []
        repl: Dict[str, str] = {}
        new_gb: List[ColumnExpr] = []
        for i, g in enumerate(node.group_by):
            if isinstance(g, _NamedColumnExpr):
                new_gb.append(g)
                continue
            # a readable derived name (what SQL backends show for an
            # unaliased grouped expression), not an internal token
            name = _derived_name(g)
            repl[_structural_key(g)] = name
            assigns.append(g.alias(name))
            new_gb.append(_named_col(name))
        child2 = e.assign(child, assigns)
        new_proj = [substitute_exprs(c, repl) for c in projections]
        new_having = None
        if node.having is not None:
            # HAVING evaluates over the AGGREGATED frame, whose columns are
            # the projection OUTPUT names — a grouped expr that is also
            # projected must rewrite to its output alias, not the helper
            having_map = dict(repl)
            for c in projections:
                key = _structural_key(c)
                if key in repl and c.output_name != "":
                    having_map[key] = c.output_name
            new_having = substitute_exprs(node.having, having_map)
        new_node = dataclasses.replace(
            node, projections=new_proj, group_by=new_gb, having=new_having
        )
        return new_node, child2

    def _substitute_subqueries(self, node: SelectNode) -> SelectNode:
        """Evaluate uncorrelated subqueries and substitute their results:
        scalar subqueries become literals, ``IN (SELECT ...)`` becomes a
        plain IN over the subquery's first column. Correlated references
        surface as unknown-table/column errors."""
        import dataclasses

        from ..column.expressions import (
            _BinaryOpExpr,
            _CaseWhenExpr,
            _FuncExpr,
            _InExpr,
            _LikeExpr,
            _LitColumnExpr,
            _UnaryOpExpr,
        )
        from .parser import (
            _SubqueryExistsExpr,
            _SubqueryInExpr,
            _SubqueryScalarExpr,
        )

        found = [False]

        def _run(plan: PlanNode) -> pd.DataFrame:
            # a subplan referencing tables outside its own FROM is a
            # correlated subquery in a position the decorrelator doesn't
            # cover — running it would silently bind outer refs to inner
            # columns, so refuse loudly instead
            self._assert_no_foreign_refs(plan)
            ex = SQLExecutor(self._engine, self._dfs)
            # share FROM-tree materializations with the correlation
            # analysis (it may already have executed this subquery's child)
            ex._plan_memo = getattr(self, "_plan_memo", {})
            return ex.run(plan).as_pandas()

        def sub(e: Any) -> Any:
            if e is None:
                return None
            if isinstance(e, _SubqueryScalarExpr):
                found[0] = True
                res = _run(e.plan)
                if len(res.columns) != 1 or len(res) > 1:
                    raise FugueSQLRuntimeError(
                        "scalar subquery must return one column and at most "
                        f"one row; got {res.shape}"
                    )
                v = None if len(res) == 0 else res.iloc[0, 0]
                v = None if pd.isna(v) else (v.item() if hasattr(v, "item") else v)
                out: Any = _LitColumnExpr(v)
            elif isinstance(e, _SubqueryExistsExpr):
                found[0] = True
                plan = e.plan
                # ORDER BY never matters to EXISTS; LIMIT n>=1 doesn't
                # either (LIMIT 0 makes it constant-false)
                limit0 = False
                while isinstance(plan, (SortNode, LimitNode)):
                    if isinstance(plan, LimitNode) and plan.n <= 0:
                        limit0 = True
                    plan = plan.child
                if (
                    isinstance(plan, SelectNode)
                    and plan.child is not None
                    and len(plan.group_by) == 0
                    and plan.grouping_sets is None
                ):
                    # the projection is irrelevant to EXISTS (often a bare
                    # unnamed literal) — count rows, don't shape them.
                    # Grouped / FROM-less subqueries keep their projections
                    # (a '*' would be invalid there).
                    import dataclasses as _dc

                    plan = _dc.replace(
                        plan, projections=[_col("*")], distinct=False
                    )
                elif isinstance(plan, SelectNode) and plan.child is None:
                    import dataclasses as _dc

                    plan = _dc.replace(
                        plan,
                        projections=[
                            p if p.output_name else p.alias(f"_e{i}")
                            for i, p in enumerate(plan.projections)
                        ],
                    )
                exists = (not limit0) and len(_run(plan)) > 0
                out = _LitColumnExpr(exists == e.positive)
            elif isinstance(e, _SubqueryInExpr):
                found[0] = True
                res = _run(e.plan)
                if len(res.columns) != 1:
                    raise FugueSQLRuntimeError(
                        "IN subquery must return exactly one column"
                    )
                col_res = res.iloc[:, 0]
                has_null = bool(col_res.isna().any())
                vals = [
                    x.item() if hasattr(x, "item") else x
                    for x in col_res.dropna().tolist()
                ]
                if has_null:
                    # SQL three-valued logic: a NULL in the IN-set means a
                    # non-matching row compares NULL, never TRUE/FALSE —
                    #   x IN (..., NULL)     → TRUE on match, else NULL
                    #   x NOT IN (..., NULL) → FALSE on match, else NULL
                    match = _InExpr(sub(e.col), vals, True)
                    out = _CaseWhenExpr(
                        [(match, _LitColumnExpr(e.positive))],
                        _LitColumnExpr(None),
                    )
                else:
                    out = _InExpr(sub(e.col), vals, e.positive)
            elif isinstance(e, _BinaryOpExpr):
                l, r = sub(e.left), sub(e.right)
                if l is e.left and r is e.right:
                    return e  # unchanged — keep subclass identity/aliases
                out = _BinaryOpExpr(e.op, l, r)
            elif isinstance(e, _UnaryOpExpr):
                c = sub(e.col)
                if c is e.col:
                    return e
                out = _UnaryOpExpr(e.op, c)
            elif isinstance(e, _FuncExpr):
                args = [sub(a) for a in e.args]
                if all(a is b for a, b in zip(args, e.args)):
                    return e
                out = _FuncExpr(
                    e.func, *args, arg_distinct=e.is_distinct, is_agg=e.is_agg
                )
            elif isinstance(e, _InExpr):
                c = sub(e.col)
                if c is e.col:
                    return e
                out = _InExpr(c, e.values, e.positive)
            elif isinstance(e, _LikeExpr):
                c = sub(e.col)
                if c is e.col:
                    return e
                out = _LikeExpr(c, e.pattern, e.positive)
            elif isinstance(e, _CaseWhenExpr):
                cases = [(sub(c), sub(v)) for c, v in e.cases]
                default = sub(e.default)
                if default is e.default and all(
                    c is c0 and v is v0
                    for (c, v), (c0, v0) in zip(cases, e.cases)
                ):
                    return e
                out = _CaseWhenExpr(cases, default)
            else:
                return e
            if e.as_name != "":
                out = out.alias(e.as_name)
            if e.as_type is not None:
                out = out.cast(e.as_type)
            return out

        new_projections = [sub(c) for c in node.projections]
        new_where = sub(node.where)
        new_having = sub(node.having)
        if not found[0]:
            return node
        return dataclasses.replace(
            node,
            projections=new_projections,
            where=new_where,
            having=new_having,
        )

    def _exec_decoupled_groupby(
        self, node: SelectNode, child: DataFrame, gb_names: List[str]
    ) -> DataFrame:
        """``SELECT <exprs over keys + aggs> ... GROUP BY k1,...`` where the
        key set differs from the plain projection columns (keys may be
        dropped, transformed, or a superset). Two phases: an engine
        aggregate by the GROUP BY keys, then a host-side projection over
        the aggregated frame with aggregate subtrees reading their
        computed columns."""
        from ..collections.partition import PartitionSpec
        from ..column.expressions import (
            _BinaryOpExpr,
            _FuncExpr,
            _UnaryOpExpr,
        )

        e = self._engine
        if node.where is not None:
            child = e.filter(child, node.where)
        agg_map: Dict[str, str] = {}
        agg_list: List[ColumnExpr] = []

        def extract(expr: ColumnExpr) -> ColumnExpr:
            if isinstance(expr, _FuncExpr) and expr.is_agg:
                bare = expr.alias("").cast(None)
                key = bare.__uuid__()
                if key not in agg_map:
                    name = f"__agg_{len(agg_map)}__"
                    agg_map[key] = name
                    agg_list.append(bare.alias(name))
                ref: ColumnExpr = _col(agg_map[key])
                if expr.as_type is not None:
                    ref = ref.cast(expr.as_type)
                if expr.as_name != "":
                    ref = ref.alias(expr.as_name)
                return ref
            if isinstance(expr, _BinaryOpExpr):
                res: ColumnExpr = _BinaryOpExpr(
                    expr.op, extract(expr.left), extract(expr.right)
                )
            elif isinstance(expr, _UnaryOpExpr):
                res = _UnaryOpExpr(expr.op, extract(expr.col))
            elif isinstance(expr, _FuncExpr) and not expr.is_agg:
                res = _FuncExpr(
                    expr.func,
                    *[extract(a) for a in expr.args],
                    arg_distinct=expr.is_distinct,
                )
            else:
                names = _referenced_names(expr)
                bad = [n for n in names if n not in gb_names]
                if len(bad) > 0:
                    raise FugueSQLSyntaxError(
                        f"column(s) {bad} must appear in GROUP BY or inside "
                        "an aggregate function"
                    )
                return expr
            if expr.as_name != "":
                res = res.alias(expr.as_name)
            if expr.as_type is not None:
                res = res.cast(expr.as_type)
            return res

        finals = [extract(c.infer_alias()) for c in node.projections]
        having = extract(node.having) if node.having is not None else None
        if len(agg_list) > 0:
            grouped = e.aggregate(child, PartitionSpec(by=gb_names), agg_list)
        else:  # pure grouping (key superset, no aggregates) = distinct keys
            grouped = e.select(
                child,
                SelectColumns(*[_col(k) for k in gb_names], arg_distinct=True),
            )
        if having is not None:
            grouped = e.filter(grouped, having)
        return e.select(
            grouped, SelectColumns(*finals, arg_distinct=node.distinct)
        )

    def _try_device_windowed_select(
        self, node: "SelectNode", child: DataFrame
    ) -> Optional[DataFrame]:
        """Device plan for windowed SELECTs: WHERE as a device filter, all
        OVER columns in one shard_map (jax/window.py), projection via the
        engine's column IR — the frame never materializes on the host.
        Returns None (host fallback) for ineligible engines/shapes."""
        e = self._engine
        try:
            from ..jax.execution_engine import JaxExecutionEngine
            from ..jax.window import plan_device_windows, run_device_windows
        except ImportError:  # pragma: no cover
            return None
        if not isinstance(e, JaxExecutionEngine):
            return None
        items: List[Any] = []
        projections: List[Any] = []
        for i, c in enumerate(node.projections):
            if isinstance(c, _WindowExpr):
                items.append((f"__w{i}__", c))
                sub = _col(f"__w{i}__").alias(c.output_name or f"_w{i}")
                if c.as_type is not None:
                    sub = sub.cast(c.as_type)
                projections.append(sub)
            elif _contains_window(c):
                return None  # nested windows keep the host error path
            else:
                projections.append(c)
        jdf = e.to_df(child)
        # gate BEFORE the WHERE filter — an ineligible query shouldn't pay
        # for device work the host path will redo
        plan = plan_device_windows(jdf, items)
        if plan is None:
            return None
        if node.where is not None:
            jdf = e.filter(jdf, node.where)
        work = run_device_windows(e, jdf, plan)
        if work is None:
            return None
        cols = SelectColumns(
            *[c.infer_alias() for c in projections], arg_distinct=node.distinct
        )
        return e.select(work, cols)

    def _exec_windowed_select(self, node: SelectNode, child: DataFrame) -> DataFrame:
        """SQL evaluation order: WHERE → window → projection → DISTINCT."""
        import pyarrow as pa

        from ..column.eval import eval_filter
        from ..column.window import eval_window
        from ..schema import Schema

        e = self._engine
        if len(node.group_by) > 0 or node.having is not None:
            raise NotImplementedError(
                "window functions can't be combined with GROUP BY/HAVING yet"
            )
        device = self._try_device_windowed_select(node, child)
        if device is not None:
            return device
        local = e.to_df(child).as_local_bounded()
        pdf = local.as_pandas()
        if node.where is not None:
            pdf = eval_filter(pdf, node.where)
        schema = local.schema
        projections: List[Any] = []
        extra_fields: List[Any] = []
        for i, c in enumerate(node.projections):
            w = c
            # unwrap nothing: only top-level windows supported
            if isinstance(w, _WindowExpr):
                name = w.output_name or f"_w{i}"
                series = eval_window(pdf, w)
                pdf = pdf.assign(**{f"__w{i}__": series})
                tp = w.infer_type(schema)
                extra_fields.append(
                    pa.field(f"__w{i}__", tp if tp is not None else pa.float64())
                )
                sub = _col(f"__w{i}__").alias(name)
                if w.as_type is not None:
                    sub = sub.cast(w.as_type)
                projections.append(sub)
            elif _contains_window(c):
                raise NotImplementedError(
                    "window functions nested inside expressions are not supported"
                )
            else:
                projections.append(c)
        work_schema = Schema(list(schema.fields) + extra_fields)
        work = PandasDataFrame(pdf, work_schema)
        cols = SelectColumns(
            *[c.infer_alias() for c in projections], arg_distinct=node.distinct
        )
        return e.select(work, cols)
