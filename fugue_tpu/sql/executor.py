"""Logical-plan executor over ExecutionEngine verbs.

Each plan node lowers to engine operations (join/union/select/take/...), so
SQL inherits every engine's execution strategy — on the TPU engine,
aggregations take the device segment-reduction path and projections compile
with the jnp evaluator.
"""

from typing import Any, Dict, List, Optional

import pandas as pd

from ..column import SelectColumns, col as _col
from ..column.expressions import (
    ColumnExpr,
    _LitColumnExpr,
    _NamedColumnExpr,
    _WindowExpr,
)


def _referenced_names(expr: "ColumnExpr") -> List[str]:
    """All column names referenced anywhere in the expression tree."""
    names: List[str] = []

    def walk(e: "ColumnExpr") -> None:
        if isinstance(e, _NamedColumnExpr):
            names.append(e.name)
        for c in e.children:
            walk(c)

    walk(expr)
    return names
from ..column.functions import is_agg
from ..dataframe import ArrayDataFrame, DataFrame, PandasDataFrame
from ..exceptions import FugueSQLRuntimeError, FugueSQLSyntaxError
from ..execution.execution_engine import ExecutionEngine
from .parser import (
    JoinNode,
    LimitNode,
    PlanNode,
    Scan,
    SelectNode,
    SetOpNode,
    SortNode,
    Subquery,
)


def _contains_window(expr: Any) -> bool:
    if isinstance(expr, _WindowExpr):
        return True
    return any(_contains_window(c) for c in getattr(expr, "children", []))


class SQLExecutor:
    def __init__(self, engine: ExecutionEngine, dfs: Dict[str, DataFrame]):
        self._engine = engine
        self._dfs = dict(dfs)

    def run(self, plan: PlanNode) -> DataFrame:
        return self._exec(plan)

    def _exec(self, node: PlanNode) -> DataFrame:
        e = self._engine
        if isinstance(node, Scan):
            if node.name not in self._dfs:
                raise FugueSQLRuntimeError(
                    f"table {node.name!r} not found; available: {sorted(self._dfs)}"
                )
            return self._dfs[node.name]
        if isinstance(node, Subquery):
            return self._exec(node.child)
        if isinstance(node, JoinNode):
            left = self._exec(node.left)
            right = self._exec(node.right)
            if node.condition is None:
                return e.join(left, right, how=node.how, on=node.on or None)
            # non-equi ON: equi-join (or cross product when no equi keys)
            # then filter the residual predicate over the joined output
            if node.how not in ("inner", "cross"):
                raise NotImplementedError(
                    "non-equi join conditions are supported for INNER joins only"
                )
            if len(node.on) > 0:
                res = e.join(left, right, how="inner", on=node.on)
            else:
                res = e.join(left, right, how="cross")
            return e.filter(res, node.condition)
        if isinstance(node, SetOpNode):
            left = self._exec(node.left)
            right = self._exec(node.right)
            if node.op == "union":
                return e.union(left, right, distinct=node.distinct)
            if node.op == "except":
                return e.subtract(left, right, distinct=True)
            return e.intersect(left, right, distinct=True)
        if isinstance(node, SortNode):
            child = node.child
            sort_names = [n for n, _ in node.by]
            extras: List[str] = []
            # standard SQL: ORDER BY may reference source columns that the
            # projection drops — augment the projection, sort, then drop
            if isinstance(child, SelectNode) and child.child is not None:
                out_names = {
                    c.output_name
                    for c in child.projections
                    if c.output_name not in ("", "*")
                }
                has_wildcard = any(
                    isinstance(c, _NamedColumnExpr) and c.name == "*"
                    for c in child.projections
                )
                missing = [
                    n for n in sort_names if n not in out_names and not has_wildcard
                ]
                if (
                    len(missing) > 0
                    and len(child.group_by) == 0
                    and not child.distinct
                    and not any(is_agg(c) for c in child.projections)
                ):
                    child = SelectNode(
                        child.child,
                        list(child.projections) + [_col(n) for n in missing],
                        child.where,
                        child.group_by,
                        child.having,
                        child.distinct,
                    )
                    extras = missing
            df = self._exec(child)
            local = e.to_df(df).as_local_bounded()
            absent = [n for n in sort_names if n not in local.schema]
            if len(absent) > 0:
                raise FugueSQLSyntaxError(
                    f"ORDER BY column(s) {absent} are not in the select output "
                    f"{local.schema.names} (aggregated selects can only order "
                    "by projected columns)"
                )
            pdf = local.as_pandas().sort_values(
                sort_names,
                ascending=[a for _, a in node.by],
                na_position="first",
            )
            if len(extras) > 0:
                pdf = pdf.drop(columns=extras)
            schema = local.schema - extras if len(extras) > 0 else local.schema
            return e.to_df(
                PandasDataFrame(pdf.reset_index(drop=True), schema)
            )
        if isinstance(node, LimitNode):
            df = self._exec(node.child)
            return e.take(df, node.n, presort="")
        if isinstance(node, SelectNode):
            return self._exec_select(node)
        raise FugueSQLSyntaxError(f"unknown plan node {type(node)}")

    def _exec_select(self, node: SelectNode) -> DataFrame:
        e = self._engine
        node = self._substitute_subqueries(node)
        if node.child is None:
            # SELECT <literals> with no FROM → one constant row
            row: List[Any] = []
            fields = []
            import pyarrow as pa

            for i, c in enumerate(node.projections):
                if not isinstance(c, _LitColumnExpr):
                    raise FugueSQLSyntaxError(
                        "SELECT without FROM supports only literals"
                    )
                name = c.output_name or f"_{i}"
                row.append(c.value)
                tp = c.infer_type(None) or pa.string()
                fields.append(pa.field(name, tp))
            from ..schema import Schema

            return ArrayDataFrame([row], Schema(fields))
        child = self._exec(node.child)
        # window functions: computed on host after WHERE, before projection
        has_window = any(_contains_window(c) for c in node.projections)
        if has_window:
            return self._exec_windowed_select(node, child)
        cols = SelectColumns(
            *[c.infer_alias() for c in node.projections], arg_distinct=node.distinct
        )
        if len(node.group_by) > 0:
            gb_names: List[str] = []
            for g in node.group_by:
                if not isinstance(g, _NamedColumnExpr):
                    raise NotImplementedError(
                        "GROUP BY supports plain column references only"
                    )
                gb_names.append(g.name)
            expanded = cols.replace_wildcard(child.schema).all_cols
            keys_in_proj_source = {
                c.name
                for c in expanded
                if isinstance(c, _NamedColumnExpr) and not is_agg(c)
            }
            proj_keys = {c.output_name for c in expanded if not is_agg(c)}
            if not (
                set(gb_names) == proj_keys
                or set(gb_names) == keys_in_proj_source
            ):
                # GROUP BY decoupled from the projection: aggregate by the
                # GROUP BY keys, then project/filter over the O(groups) result
                return self._exec_decoupled_groupby(node, child, gb_names)
        return e.select(child, cols, where=node.where, having=node.having)

    def _substitute_subqueries(self, node: SelectNode) -> SelectNode:
        """Evaluate uncorrelated subqueries and substitute their results:
        scalar subqueries become literals, ``IN (SELECT ...)`` becomes a
        plain IN over the subquery's first column. Correlated references
        surface as unknown-table/column errors."""
        import dataclasses

        from ..column.expressions import (
            _BinaryOpExpr,
            _CaseWhenExpr,
            _FuncExpr,
            _InExpr,
            _LikeExpr,
            _LitColumnExpr,
            _UnaryOpExpr,
        )
        from .parser import _SubqueryInExpr, _SubqueryScalarExpr

        found = [False]

        def _run(plan: PlanNode) -> pd.DataFrame:
            return (
                SQLExecutor(self._engine, self._dfs)
                .run(plan)
                .as_pandas()
            )

        def sub(e: Any) -> Any:
            if e is None:
                return None
            if isinstance(e, _SubqueryScalarExpr):
                found[0] = True
                res = _run(e.plan)
                if len(res.columns) != 1 or len(res) > 1:
                    raise FugueSQLRuntimeError(
                        "scalar subquery must return one column and at most "
                        f"one row; got {res.shape}"
                    )
                v = None if len(res) == 0 else res.iloc[0, 0]
                v = None if pd.isna(v) else (v.item() if hasattr(v, "item") else v)
                out: Any = _LitColumnExpr(v)
            elif isinstance(e, _SubqueryInExpr):
                found[0] = True
                res = _run(e.plan)
                if len(res.columns) != 1:
                    raise FugueSQLRuntimeError(
                        "IN subquery must return exactly one column"
                    )
                col_res = res.iloc[:, 0]
                has_null = bool(col_res.isna().any())
                vals = [
                    x.item() if hasattr(x, "item") else x
                    for x in col_res.dropna().tolist()
                ]
                if has_null:
                    # SQL three-valued logic: a NULL in the IN-set means a
                    # non-matching row compares NULL, never TRUE/FALSE —
                    #   x IN (..., NULL)     → TRUE on match, else NULL
                    #   x NOT IN (..., NULL) → FALSE on match, else NULL
                    match = _InExpr(sub(e.col), vals, True)
                    out = _CaseWhenExpr(
                        [(match, _LitColumnExpr(e.positive))],
                        _LitColumnExpr(None),
                    )
                else:
                    out = _InExpr(sub(e.col), vals, e.positive)
            elif isinstance(e, _BinaryOpExpr):
                l, r = sub(e.left), sub(e.right)
                if l is e.left and r is e.right:
                    return e  # unchanged — keep subclass identity/aliases
                out = _BinaryOpExpr(e.op, l, r)
            elif isinstance(e, _UnaryOpExpr):
                c = sub(e.col)
                if c is e.col:
                    return e
                out = _UnaryOpExpr(e.op, c)
            elif isinstance(e, _FuncExpr):
                args = [sub(a) for a in e.args]
                if all(a is b for a, b in zip(args, e.args)):
                    return e
                out = _FuncExpr(
                    e.func, *args, arg_distinct=e.is_distinct, is_agg=e.is_agg
                )
            elif isinstance(e, _InExpr):
                c = sub(e.col)
                if c is e.col:
                    return e
                out = _InExpr(c, e.values, e.positive)
            elif isinstance(e, _LikeExpr):
                c = sub(e.col)
                if c is e.col:
                    return e
                out = _LikeExpr(c, e.pattern, e.positive)
            elif isinstance(e, _CaseWhenExpr):
                cases = [(sub(c), sub(v)) for c, v in e.cases]
                default = sub(e.default)
                if default is e.default and all(
                    c is c0 and v is v0
                    for (c, v), (c0, v0) in zip(cases, e.cases)
                ):
                    return e
                out = _CaseWhenExpr(cases, default)
            else:
                return e
            if e.as_name != "":
                out = out.alias(e.as_name)
            if e.as_type is not None:
                out = out.cast(e.as_type)
            return out

        new_projections = [sub(c) for c in node.projections]
        new_where = sub(node.where)
        new_having = sub(node.having)
        if not found[0]:
            return node
        return dataclasses.replace(
            node,
            projections=new_projections,
            where=new_where,
            having=new_having,
        )

    def _exec_decoupled_groupby(
        self, node: SelectNode, child: DataFrame, gb_names: List[str]
    ) -> DataFrame:
        """``SELECT <exprs over keys + aggs> ... GROUP BY k1,...`` where the
        key set differs from the plain projection columns (keys may be
        dropped, transformed, or a superset). Two phases: an engine
        aggregate by the GROUP BY keys, then a host-side projection over
        the aggregated frame with aggregate subtrees reading their
        computed columns."""
        from ..collections.partition import PartitionSpec
        from ..column.expressions import (
            _BinaryOpExpr,
            _FuncExpr,
            _UnaryOpExpr,
        )

        e = self._engine
        if node.where is not None:
            child = e.filter(child, node.where)
        agg_map: Dict[str, str] = {}
        agg_list: List[ColumnExpr] = []

        def extract(expr: ColumnExpr) -> ColumnExpr:
            if isinstance(expr, _FuncExpr) and expr.is_agg:
                bare = expr.alias("").cast(None)
                key = bare.__uuid__()
                if key not in agg_map:
                    name = f"__agg_{len(agg_map)}__"
                    agg_map[key] = name
                    agg_list.append(bare.alias(name))
                ref: ColumnExpr = _col(agg_map[key])
                if expr.as_type is not None:
                    ref = ref.cast(expr.as_type)
                if expr.as_name != "":
                    ref = ref.alias(expr.as_name)
                return ref
            if isinstance(expr, _BinaryOpExpr):
                res: ColumnExpr = _BinaryOpExpr(
                    expr.op, extract(expr.left), extract(expr.right)
                )
            elif isinstance(expr, _UnaryOpExpr):
                res = _UnaryOpExpr(expr.op, extract(expr.col))
            elif isinstance(expr, _FuncExpr) and not expr.is_agg:
                res = _FuncExpr(
                    expr.func,
                    *[extract(a) for a in expr.args],
                    arg_distinct=expr.is_distinct,
                )
            else:
                names = _referenced_names(expr)
                bad = [n for n in names if n not in gb_names]
                if len(bad) > 0:
                    raise FugueSQLSyntaxError(
                        f"column(s) {bad} must appear in GROUP BY or inside "
                        "an aggregate function"
                    )
                return expr
            if expr.as_name != "":
                res = res.alias(expr.as_name)
            if expr.as_type is not None:
                res = res.cast(expr.as_type)
            return res

        finals = [extract(c.infer_alias()) for c in node.projections]
        having = extract(node.having) if node.having is not None else None
        if len(agg_list) > 0:
            grouped = e.aggregate(child, PartitionSpec(by=gb_names), agg_list)
        else:  # pure grouping (key superset, no aggregates) = distinct keys
            grouped = e.select(
                child,
                SelectColumns(*[_col(k) for k in gb_names], arg_distinct=True),
            )
        if having is not None:
            grouped = e.filter(grouped, having)
        return e.select(
            grouped, SelectColumns(*finals, arg_distinct=node.distinct)
        )

    def _try_device_windowed_select(
        self, node: "SelectNode", child: DataFrame
    ) -> Optional[DataFrame]:
        """Device plan for windowed SELECTs: WHERE as a device filter, all
        OVER columns in one shard_map (jax/window.py), projection via the
        engine's column IR — the frame never materializes on the host.
        Returns None (host fallback) for ineligible engines/shapes."""
        e = self._engine
        try:
            from ..jax.execution_engine import JaxExecutionEngine
            from ..jax.window import plan_device_windows, run_device_windows
        except ImportError:  # pragma: no cover
            return None
        if not isinstance(e, JaxExecutionEngine):
            return None
        items: List[Any] = []
        projections: List[Any] = []
        for i, c in enumerate(node.projections):
            if isinstance(c, _WindowExpr):
                items.append((f"__w{i}__", c))
                sub = _col(f"__w{i}__").alias(c.output_name or f"_w{i}")
                if c.as_type is not None:
                    sub = sub.cast(c.as_type)
                projections.append(sub)
            elif _contains_window(c):
                return None  # nested windows keep the host error path
            else:
                projections.append(c)
        jdf = e.to_df(child)
        # gate BEFORE the WHERE filter — an ineligible query shouldn't pay
        # for device work the host path will redo
        plan = plan_device_windows(jdf, items)
        if plan is None:
            return None
        if node.where is not None:
            jdf = e.filter(jdf, node.where)
        work = run_device_windows(e, jdf, plan)
        if work is None:
            return None
        cols = SelectColumns(
            *[c.infer_alias() for c in projections], arg_distinct=node.distinct
        )
        return e.select(work, cols)

    def _exec_windowed_select(self, node: SelectNode, child: DataFrame) -> DataFrame:
        """SQL evaluation order: WHERE → window → projection → DISTINCT."""
        import pyarrow as pa

        from ..column.eval import eval_filter
        from ..column.window import eval_window
        from ..schema import Schema

        e = self._engine
        if len(node.group_by) > 0 or node.having is not None:
            raise NotImplementedError(
                "window functions can't be combined with GROUP BY/HAVING yet"
            )
        device = self._try_device_windowed_select(node, child)
        if device is not None:
            return device
        local = e.to_df(child).as_local_bounded()
        pdf = local.as_pandas()
        if node.where is not None:
            pdf = eval_filter(pdf, node.where)
        schema = local.schema
        projections: List[Any] = []
        extra_fields: List[Any] = []
        for i, c in enumerate(node.projections):
            w = c
            # unwrap nothing: only top-level windows supported
            if isinstance(w, _WindowExpr):
                name = w.output_name or f"_w{i}"
                series = eval_window(pdf, w)
                pdf = pdf.assign(**{f"__w{i}__": series})
                tp = w.infer_type(schema)
                extra_fields.append(
                    pa.field(f"__w{i}__", tp if tp is not None else pa.float64())
                )
                sub = _col(f"__w{i}__").alias(name)
                if w.as_type is not None:
                    sub = sub.cast(w.as_type)
                projections.append(sub)
            elif _contains_window(c):
                raise NotImplementedError(
                    "window functions nested inside expressions are not supported"
                )
            else:
                projections.append(c)
        work_schema = Schema(list(schema.fields) + extra_fields)
        work = PandasDataFrame(pdf, work_schema)
        cols = SelectColumns(
            *[c.infer_alias() for c in projections], arg_distinct=node.distinct
        )
        return e.select(work, cols)
