"""Hand-rolled SQL tokenizer + SELECT parser.

In-tree replacement for the reference's delegated SQL stack (qpd/DuckDB —
neither exists in this environment, SURVEY §0). SQL parses into a logical
plan over the column-expression IR (``fugue_tpu/column``), executed through
ExecutionEngine verbs (``executor.py``) — so the same SQL statement runs on
the pandas oracle AND distributed on the TPU engine.

Grammar (spark-ish subset)::

    query     := select (UNION [ALL] | EXCEPT | INTERSECT) select ...
    select    := SELECT [DISTINCT] proj (, proj)*
                 [FROM source (join)*] [WHERE expr]
                 [GROUP BY expr (, expr)*] [HAVING expr]
                 [ORDER BY name [ASC|DESC] (, ...)*] [LIMIT n]
    source    := ident [AS alias] | ( query ) [AS alias]
    join      := [INNER|LEFT|RIGHT|FULL|CROSS|SEMI|ANTI] JOIN source
                 [ON eq (AND eq)*]
    proj      := expr [AS name] | * | ident.*
    expr      := standard precedence with CASE WHEN, CAST, IN, LIKE,
                 BETWEEN, IS [NOT] NULL, functions, literals
"""

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple, Union

from ..column import ColumnExpr, col, function, lit
from ..column.expressions import (
    derived_name,
    _BinaryOpExpr,
    _CaseWhenExpr,
    _InExpr,
    _LikeExpr,
    _NamedColumnExpr,
    _UnaryOpExpr,
)
from ..exceptions import FugueSQLSyntaxError
from ..schema import to_pa_datatype

_AGG_FUNCS = {"SUM", "COUNT", "AVG", "MEAN", "MIN", "MAX", "FIRST", "LAST"}

_KEYWORD_STOP = {
    "FROM", "WHERE", "GROUP", "HAVING", "ORDER", "LIMIT", "UNION", "EXCEPT",
    "INTERSECT", "JOIN", "INNER", "LEFT", "RIGHT", "FULL", "CROSS", "ON",
    "AS", "ASC", "DESC", "BY", "AND", "OR", "NOT", "IN", "IS", "NULL",
    "BETWEEN", "LIKE", "CASE", "WHEN", "THEN", "ELSE", "END", "DISTINCT",
    "ALL", "SEMI", "ANTI", "OUTER", "USING",
}


# ---------------------------------------------------------------------------
# tokenizer
# ---------------------------------------------------------------------------


class Token:
    """One SQL token. kinds: IDENT QIDENT STRING NUMBER OP PUNCT EOF."""

    __slots__ = ("kind", "value", "pos", "_upper")

    def __init__(self, kind: str, value: str, pos: int):
        self.kind = kind
        self.value = value
        self.pos = pos
        self._upper: Optional[str] = None

    @property
    def upper(self) -> str:
        if self._upper is None:
            self._upper = self.value.upper()
        return self._upper

    def __repr__(self) -> str:
        return f"Token({self.kind},{self.value!r},{self.pos})"


def tokenize(sql: str) -> List[Token]:
    """Tokenize SQL; uses the C++ tokenizer when available
    (``fugue_tpu/native``), falling back to pure Python."""
    import os

    if os.environ.get("FUGUE_TPU_DISABLE_NATIVE", "") != "1":
        try:
            from ..native import tokenize_native

            res = tokenize_native(sql)
            if res is not None:
                return res
        except ImportError:  # pragma: no cover
            pass
    return _tokenize_py(sql)


def _tokenize_py(sql: str) -> List[Token]:
    tokens: List[Token] = []
    i, n = 0, len(sql)
    while i < n:
        c = sql[i]
        if c.isspace():
            i += 1
            continue
        if c == "-" and i + 1 < n and sql[i + 1] == "-":  # line comment
            while i < n and sql[i] != "\n":
                i += 1
            continue
        if c == "/" and i + 1 < n and sql[i + 1] == "*":  # block comment
            j = sql.find("*/", i + 2)
            i = n if j < 0 else j + 2
            continue
        if c == "'" or c == '"':
            quote = c
            j = i + 1
            buf = []
            while j < n:
                if sql[j] == quote:
                    if j + 1 < n and sql[j + 1] == quote:  # escaped quote
                        buf.append(quote)
                        j += 2
                        continue
                    break
                buf.append(sql[j])
                j += 1
            if j >= n:
                raise FugueSQLSyntaxError(f"unterminated string at {i}")
            tokens.append(Token("STRING", "".join(buf), i))
            i = j + 1
            continue
        if c == "`":
            j = sql.find("`", i + 1)
            if j < 0:
                raise FugueSQLSyntaxError(f"unterminated identifier at {i}")
            tokens.append(Token("QIDENT", sql[i + 1 : j], i))
            i = j + 1
            continue
        if c.isdigit() or (c == "." and i + 1 < n and sql[i + 1].isdigit()):
            j = i
            seen_dot = False
            while j < n and (sql[j].isdigit() or (sql[j] == "." and not seen_dot)):
                if sql[j] == ".":
                    seen_dot = True
                j += 1
            if j < n and sql[j] in "eE":
                # only consume the exponent when a digit follows the optional
                # sign — '1e' / '2e+' must tokenize as NUMBER+IDENT, matching
                # the native tokenizer's backtracking
                k = j + 1
                if k < n and sql[k] in "+-":
                    k += 1
                if k < n and sql[k].isdigit():
                    while k < n and sql[k].isdigit():
                        k += 1
                    j = k
                    seen_dot = True
            tokens.append(Token("NUMBER", sql[i:j], i))
            i = j
            continue
        if c.isalpha() or c == "_":
            j = i
            while j < n and (sql[j].isalnum() or sql[j] == "_"):
                j += 1
            tokens.append(Token("IDENT", sql[i:j], i))
            i = j
            continue
        for op in ("<>", "<=", ">=", "!=", "=="):
            if sql.startswith(op, i):
                tokens.append(Token("OP", op, i))
                i += len(op)
                break
        else:
            if c in "+-*/%<>=":
                tokens.append(Token("OP", c, i))
                i += 1
            elif c in "(),.;[]{}:?":
                tokens.append(Token("PUNCT", c, i))
                i += 1
            elif c == "<":
                tokens.append(Token("OP", c, i))
                i += 1
            else:
                raise FugueSQLSyntaxError(f"unexpected character {c!r} at {i}")
    tokens.append(Token("EOF", "", n))
    return tokens


# ---------------------------------------------------------------------------
# logical plan
# ---------------------------------------------------------------------------


@dataclass
class PlanNode:
    pass


@dataclass
class Scan(PlanNode):
    name: str
    alias: str = ""


def _source_names(p: "PlanNode") -> set:
    """Visible table names/aliases of a FROM source (derived tables hide
    their inner scans — only the alias shows)."""
    out: set = set()
    if isinstance(p, Scan):
        out.add(p.name)
        if p.alias:
            out.add(p.alias)
    elif isinstance(p, Subquery):
        if p.alias:
            out.add(p.alias)
    elif isinstance(p, JoinNode):
        out |= _source_names(p.left)
        out |= _source_names(p.right)
    return out


@dataclass
class Subquery(PlanNode):
    child: PlanNode
    alias: str = ""


@dataclass
class JoinNode(PlanNode):
    left: PlanNode
    right: PlanNode
    how: str
    on: List[str] = field(default_factory=list)
    # residual (non-equi) ON predicate, applied over the joined output
    condition: Optional[ColumnExpr] = None


class _SubqueryScalarExpr(ColumnExpr):
    """``(SELECT ...)`` used as a scalar value inside an expression.

    The executor evaluates the (uncorrelated) subplan and substitutes the
    single-cell result as a literal before the outer select runs.
    """

    def __init__(self, plan: "PlanNode"):
        super().__init__()
        self.plan = plan

    def _uuid_keys(self) -> List[Any]:
        return ["subquery_scalar", repr(self.plan)]

    def __repr__(self) -> str:
        return f"(SELECT ...{type(self.plan).__name__})"


class _SubqueryExistsExpr(ColumnExpr):
    """``[NOT] EXISTS (SELECT ...)``.

    Uncorrelated: substituted as a boolean literal. Correlated by equality
    (``inner.k = outer.k`` conjuncts): decorrelated into a device semi/anti
    join when the EXISTS is a top-level WHERE conjunct.
    """

    def __init__(self, plan: "PlanNode", positive: bool = True):
        super().__init__()
        self.plan = plan
        self.positive = positive

    def _uuid_keys(self) -> List[Any]:
        return ["subquery_exists", self.positive, repr(self.plan)]

    def __repr__(self) -> str:
        return f"EXISTS (SELECT ...{type(self.plan).__name__})"


class _SubqueryInExpr(ColumnExpr):
    """``expr [NOT] IN (SELECT ...)`` — the executor evaluates the subplan
    and substitutes a plain IN over its first column's values."""

    def __init__(self, expr: Any, plan: "PlanNode", positive: bool = True):
        super().__init__()
        self.col = expr
        self.plan = plan
        self.positive = positive

    @property
    def children(self) -> List[ColumnExpr]:
        return [self.col]

    def _uuid_keys(self) -> List[Any]:
        return ["subquery_in", self.positive, repr(self.plan)]

    def __repr__(self) -> str:
        return f"{self.col!r} IN (SELECT ...)"


@dataclass
class SelectNode(PlanNode):
    child: Optional[PlanNode]
    projections: List[ColumnExpr]
    where: Optional[ColumnExpr] = None
    group_by: List[ColumnExpr] = field(default_factory=list)
    having: Optional[ColumnExpr] = None
    distinct: bool = False
    # GROUP BY ROLLUP/CUBE/GROUPING SETS: each entry is one key subset;
    # group_by holds the union of all keys
    grouping_sets: Optional[List[List[str]]] = None


@dataclass
class SetOpNode(PlanNode):
    op: str  # union | except | intersect
    left: PlanNode
    right: PlanNode
    distinct: bool = True


@dataclass
class SortNode(PlanNode):
    child: PlanNode
    by: List[Tuple[str, bool]]
    # ORDER BY <expression>: generated sort names -> their expressions
    # (materialized as helper columns at execution, dropped after the sort)
    exprs: Dict[str, ColumnExpr] = field(default_factory=dict)


@dataclass
class LimitNode(PlanNode):
    child: PlanNode
    n: int


# ---------------------------------------------------------------------------
# parser
# ---------------------------------------------------------------------------


class SQLParser:
    def __init__(self, sql: str):
        self._tokens = tokenize(sql)
        self._i = 0

    # -- token helpers -----------------------------------------------------
    def peek(self, offset: int = 0) -> Token:
        return self._tokens[min(self._i + offset, len(self._tokens) - 1)]

    def next(self) -> Token:
        t = self.peek()
        self._i += 1
        return t

    def at_kw(self, *kws: str) -> bool:
        t = self.peek()
        return t.kind == "IDENT" and t.upper in kws

    def eat_kw(self, *kws: str) -> bool:
        if self.at_kw(*kws):
            self.next()
            return True
        return False

    def expect_kw(self, kw: str) -> None:
        if not self.eat_kw(kw):
            t = self.peek()
            raise FugueSQLSyntaxError(f"expected {kw}, got {t.value!r} at {t.pos}")

    def at_punct(self, p: str) -> bool:
        t = self.peek()
        return t.kind == "PUNCT" and t.value == p

    def eat_punct(self, p: str) -> bool:
        if self.at_punct(p):
            self.next()
            return True
        return False

    def expect_punct(self, p: str) -> None:
        if not self.eat_punct(p):
            t = self.peek()
            raise FugueSQLSyntaxError(f"expected {p!r}, got {t.value!r} at {t.pos}")

    # -- entry -------------------------------------------------------------
    def parse_query(self) -> PlanNode:
        plan = self._parse_query_body()
        # trailing ORDER BY / LIMIT apply to the whole set expression
        plan = self._maybe_order_limit(plan)
        return plan

    def parse_full(self) -> PlanNode:
        plan = self.parse_query()
        self.eat_punct(";")
        if self.peek().kind != "EOF":
            t = self.peek()
            raise FugueSQLSyntaxError(f"unexpected {t.value!r} at {t.pos}")
        return plan

    def _parse_query_body(self) -> PlanNode:
        left = self._parse_select()
        while True:
            if self.at_kw("UNION"):
                self.next()
                distinct = not self.eat_kw("ALL")
                self.eat_kw("DISTINCT")
                right = self._parse_select()
                left = SetOpNode("union", left, right, distinct)
            elif self.at_kw("EXCEPT"):
                self.next()
                self.eat_kw("DISTINCT")
                right = self._parse_select()
                left = SetOpNode("except", left, right, True)
            elif self.at_kw("INTERSECT"):
                self.next()
                self.eat_kw("DISTINCT")
                right = self._parse_select()
                left = SetOpNode("intersect", left, right, True)
            else:
                return left

    def _maybe_order_limit(self, plan: PlanNode) -> PlanNode:
        if self.at_kw("ORDER"):
            self.next()
            self.expect_kw("BY")
            by: List[Tuple[str, bool]] = []
            exprs: Dict[str, ColumnExpr] = {}
            while True:
                item = self._parse_expr()
                if (
                    isinstance(item, _NamedColumnExpr)
                    and item.as_name == ""
                    and item.as_type is None
                    and item.name != "*"
                ):
                    name = item.name
                else:
                    # ORDER BY <expression>: name it by its readable
                    # derived form (cast KEPT — CAST(x AS t) must not
                    # collide with plain x); bare int literals resolve as
                    # SQL positional ordering in the executor
                    name = derived_name(item)
                    exprs[name] = item
                asc = True
                if self.eat_kw("DESC"):
                    asc = False
                else:
                    self.eat_kw("ASC")
                by.append((name, asc))
                if not self.eat_punct(","):
                    break
            plan = SortNode(plan, by, exprs)
        if self.at_kw("LIMIT"):
            self.next()
            t = self.next()
            if t.kind != "NUMBER":
                raise FugueSQLSyntaxError(f"expected number after LIMIT at {t.pos}")
            plan = LimitNode(plan, int(t.value))
        return plan

    def _parse_select(self) -> PlanNode:
        if self.eat_punct("("):
            inner = self._parse_query_body()
            inner = self._maybe_order_limit(inner)
            self.expect_punct(")")
            return inner
        self.expect_kw("SELECT")
        distinct = self.eat_kw("DISTINCT")
        projections: List[ColumnExpr] = []
        while True:
            projections.append(self._parse_projection())
            if not self.eat_punct(","):
                break
        child: Optional[PlanNode] = None
        if self.eat_kw("FROM"):
            child = self._parse_source()
            while True:
                how = self._peek_join_type()
                if how is None:
                    break
                right = self._parse_source()
                on: List[str] = []
                residual: Optional[ColumnExpr] = None
                if self.eat_kw("ON"):
                    on, residual = self._parse_on_condition(
                        _source_names(child) | _source_names(right)
                    )
                elif self.eat_kw("USING"):
                    self.expect_punct("(")
                    while True:
                        on.append(self._parse_name())
                        if not self.eat_punct(","):
                            break
                    self.expect_punct(")")
                child = JoinNode(child, right, how, on, residual)
        where = None
        if self.eat_kw("WHERE"):
            where = self._parse_expr()
        group_by: List[ColumnExpr] = []
        grouping_sets: Optional[List[List[str]]] = None
        if self.at_kw("GROUP"):
            self.next()
            self.expect_kw("BY")
            if self.at_kw("ROLLUP") or self.at_kw("CUBE"):
                kind = self.next().upper
                keys = self._parse_name_list_parens()
                if kind == "ROLLUP":
                    grouping_sets = [keys[:i] for i in range(len(keys), -1, -1)]
                else:  # CUBE: every subset, preserving key order
                    grouping_sets = [
                        [k for j, k in enumerate(keys) if mask & (1 << j)]
                        for mask in range((1 << len(keys)) - 1, -1, -1)
                    ]
                group_by = [col(k) for k in keys]
            elif self.at_kw("GROUPING") and self.peek(1).upper == "SETS":
                self.next()
                self.next()
                self.expect_punct("(")
                grouping_sets = []
                while True:
                    grouping_sets.append(self._parse_name_list_parens())
                    if not self.eat_punct(","):
                        break
                self.expect_punct(")")
                seen: List[str] = []
                for s in grouping_sets:
                    for k in s:
                        if k not in seen:
                            seen.append(k)
                group_by = [col(k) for k in seen]
            else:
                while True:
                    group_by.append(self._parse_expr())
                    if not self.eat_punct(","):
                        break
        having = None
        if self.eat_kw("HAVING"):
            having = self._parse_expr()
        node: PlanNode = SelectNode(
            child, projections, where, group_by, having, distinct,
            grouping_sets=grouping_sets,
        )
        return self._maybe_order_limit(node)

    def _parse_name_list_parens(self) -> List[str]:
        """``( name, name, ... )`` — also accepts the empty ``()`` set."""
        self.expect_punct("(")
        names: List[str] = []
        if not self.at_punct(")"):
            while True:
                names.append(self._parse_qualified_name())
                if not self.eat_punct(","):
                    break
        self.expect_punct(")")
        return names

    def _peek_join_type(self) -> Optional[str]:
        if self.at_kw("JOIN"):
            self.next()
            return "inner"
        for kw, how in (
            ("INNER", "inner"),
            ("CROSS", "cross"),
            ("SEMI", "semi"),
            ("ANTI", "anti"),
        ):
            if self.at_kw(kw) and self.peek(1).upper == "JOIN":
                self.next()
                self.next()
                return how
        for kw, how in (
            ("LEFT", "left_outer"),
            ("RIGHT", "right_outer"),
            ("FULL", "full_outer"),
        ):
            if self.at_kw(kw):
                nxt = self.peek(1).upper
                if nxt == "JOIN":
                    self.next(); self.next()
                    return how
                if nxt == "OUTER" and self.peek(2).upper == "JOIN":
                    self.next(); self.next(); self.next()
                    return how
                if nxt in ("SEMI", "ANTI") and self.peek(2).upper == "JOIN":
                    how2 = "semi" if nxt == "SEMI" else "anti"
                    self.next(); self.next(); self.next()
                    return how2
        return None

    def _parse_source(self) -> PlanNode:
        if self.eat_punct("("):
            inner = self._parse_query_body()
            inner = self._maybe_order_limit(inner)
            self.expect_punct(")")
            alias = ""
            if self.eat_kw("AS"):
                alias = self._parse_name()
            elif self.peek().kind in ("IDENT", "QIDENT") and not self._at_clause_kw():
                alias = self._parse_name()
            return Subquery(inner, alias)
        name = self._parse_name()
        alias = ""
        if self.eat_kw("AS"):
            alias = self._parse_name()
        elif self.peek().kind in ("IDENT", "QIDENT") and not self._at_clause_kw():
            alias = self._parse_name()
        return Scan(name, alias)

    def _at_clause_kw(self) -> bool:
        t = self.peek()
        return t.kind == "IDENT" and t.upper in _KEYWORD_STOP

    def _parse_on_condition(self, local_names: Any = None) -> Any:
        """Parse a general ON predicate and split it into equi-join keys
        (``a.k = b.k`` on a shared name) and a residual (non-equi)
        condition evaluated over the joined output.

        ``local_names``: table names/aliases of the two joined sources —
        a qualifier outside this set is a correlated outer reference the
        join can't bind, and silently treating it as an equi key would
        join the wrong columns; refuse loudly instead.
        """
        from ..column.expressions import _BinaryOpExpr, _NamedColumnExpr

        cond = self._parse_expr()
        conjuncts: List[ColumnExpr] = []

        def split(e: ColumnExpr) -> None:
            if isinstance(e, _BinaryOpExpr) and e.op == "&":
                split(e.left)
                split(e.right)
            else:
                conjuncts.append(e)

        split(cond)

        def _foreign(c: _NamedColumnExpr) -> bool:
            q = getattr(c, "_sql_qualifier", "")
            return bool(q) and local_names is not None and q not in local_names

        keys: List[str] = []
        residual: Optional[ColumnExpr] = None
        for c in conjuncts:
            if (
                isinstance(c, _BinaryOpExpr)
                and c.op == "=="
                and isinstance(c.left, _NamedColumnExpr)
                and isinstance(c.right, _NamedColumnExpr)
            ):
                if _foreign(c.left) or _foreign(c.right):
                    raise FugueSQLSyntaxError(
                        "JOIN ON references a table outside the join "
                        "(correlated ON conditions are not supported)"
                    )
                if c.left.name == c.right.name:  # qualifiers stripped
                    keys.append(c.left.name)
                    continue
            residual = c if residual is None else (residual & c)
        return keys, residual

    def _parse_name(self) -> str:
        t = self.next()
        if t.kind not in ("IDENT", "QIDENT"):
            raise FugueSQLSyntaxError(f"expected name, got {t.value!r} at {t.pos}")
        return t.value

    def _parse_qualified_name(self) -> str:
        name = self._parse_name()
        while self.at_punct("."):
            self.next()
            name = self._parse_name()  # keep last segment (unqualified)
        return name

    def _parse_projection(self) -> ColumnExpr:
        t = self.peek()
        if t.kind == "OP" and t.value == "*":
            self.next()
            return col("*")
        if (
            t.kind in ("IDENT", "QIDENT")
            and self.peek(1).value == "."
            and self.peek(2).value == "*"
        ):
            self.next(); self.next(); self.next()
            return col("*")
        e = self._parse_expr()
        if self.eat_kw("AS"):
            e = e.alias(self._parse_name())
        elif self.peek().kind in ("IDENT", "QIDENT") and not self._at_clause_kw():
            e = e.alias(self._parse_name())
        return e

    # -- expressions --------------------------------------------------------
    def _parse_expr(self) -> ColumnExpr:
        return self._parse_or()

    def _parse_or(self) -> ColumnExpr:
        left = self._parse_and()
        while self.eat_kw("OR"):
            left = _BinaryOpExpr("|", left, self._parse_and())
        return left

    def _parse_and(self) -> ColumnExpr:
        left = self._parse_not()
        while self.eat_kw("AND"):
            left = _BinaryOpExpr("&", left, self._parse_not())
        return left

    def _parse_not(self) -> ColumnExpr:
        if self.eat_kw("NOT"):
            return _UnaryOpExpr("~", self._parse_not())
        return self._parse_predicate()

    def _parse_predicate(self) -> ColumnExpr:
        left = self._parse_additive()
        while True:
            t = self.peek()
            if t.kind == "OP" and t.value in ("=", "==", "!=", "<>", "<", "<=", ">", ">="):
                self.next()
                op = {"=": "==", "<>": "!="}.get(t.value, t.value)
                left = _BinaryOpExpr(op, left, self._parse_additive())
                continue
            if self.at_kw("IS"):
                self.next()
                negate = self.eat_kw("NOT")
                self.expect_kw("NULL")
                left = _UnaryOpExpr("NOT_NULL" if negate else "IS_NULL", left)
                continue
            if self.at_kw("IN") or (self.at_kw("NOT") and self.peek(1).upper == "IN"):
                positive = not self.eat_kw("NOT")
                self.expect_kw("IN")
                self.expect_punct("(")
                if self.at_kw("SELECT"):
                    plan = self._parse_query_body()
                    plan = self._maybe_order_limit(plan)
                    self.expect_punct(")")
                    left = _SubqueryInExpr(left, plan, positive)
                    continue
                values: List[Any] = []
                while True:
                    values.append(self._parse_literal_value())
                    if not self.eat_punct(","):
                        break
                self.expect_punct(")")
                left = _InExpr(left, values, positive)
                continue
            if self.at_kw("BETWEEN") or (
                self.at_kw("NOT") and self.peek(1).upper == "BETWEEN"
            ):
                positive = not self.eat_kw("NOT")
                self.expect_kw("BETWEEN")
                lo = self._parse_additive()
                self.expect_kw("AND")
                hi = self._parse_additive()
                rng = _BinaryOpExpr("&", left >= lo, left <= hi)
                left = rng if positive else _UnaryOpExpr("~", rng)
                continue
            if self.at_kw("LIKE") or (self.at_kw("NOT") and self.peek(1).upper == "LIKE"):
                positive = not self.eat_kw("NOT")
                self.expect_kw("LIKE")
                p = self.next()
                if p.kind != "STRING":
                    raise FugueSQLSyntaxError(f"LIKE pattern must be a string at {p.pos}")
                left = _LikeExpr(left, p.value, positive)
                continue
            return left

    def _parse_additive(self) -> ColumnExpr:
        left = self._parse_mult()
        while True:
            t = self.peek()
            if t.kind == "OP" and t.value in ("+", "-"):
                self.next()
                left = _BinaryOpExpr(t.value, left, self._parse_mult())
            else:
                return left

    def _parse_mult(self) -> ColumnExpr:
        left = self._parse_unary()
        while True:
            t = self.peek()
            if t.kind == "OP" and t.value in ("*", "/", "%"):
                if t.value == "*" and self._looks_like_projection_star():
                    return left
                self.next()
                if t.value == "%":
                    left = function("MOD", left, self._parse_unary())
                else:
                    left = _BinaryOpExpr(t.value, left, self._parse_unary())
            else:
                return left

    def _looks_like_projection_star(self) -> bool:
        nxt = self.peek(1)
        return nxt.kind == "PUNCT" and nxt.value in (",",) or (
            nxt.kind == "IDENT" and nxt.upper == "FROM"
        )

    def _parse_unary(self) -> ColumnExpr:
        t = self.peek()
        if t.kind == "OP" and t.value == "-":
            self.next()
            return _UnaryOpExpr("-", self._parse_unary())
        if t.kind == "OP" and t.value == "+":
            self.next()
            return self._parse_unary()
        return self._parse_primary()

    def _parse_literal_value(self) -> Any:
        t = self.next()
        if t.kind == "STRING":
            return t.value
        if t.kind == "NUMBER":
            return float(t.value) if "." in t.value or "e" in t.value.lower() else int(t.value)
        if t.kind == "IDENT" and t.upper == "NULL":
            return None
        if t.kind == "IDENT" and t.upper in ("TRUE", "FALSE"):
            return t.upper == "TRUE"
        if t.kind == "OP" and t.value == "-":
            v = self._parse_literal_value()
            return -v
        raise FugueSQLSyntaxError(f"expected literal, got {t.value!r} at {t.pos}")

    def _parse_primary(self) -> ColumnExpr:
        t = self.peek()
        if t.kind == "STRING":
            self.next()
            return lit(t.value)
        if t.kind == "NUMBER":
            self.next()
            v = float(t.value) if "." in t.value or "e" in t.value.lower() else int(t.value)
            return lit(v)
        if t.kind == "PUNCT" and t.value == "(":
            self.next()
            if self.at_kw("SELECT"):  # scalar subquery
                plan = self._parse_query_body()
                plan = self._maybe_order_limit(plan)
                self.expect_punct(")")
                return _SubqueryScalarExpr(plan)
            e = self._parse_expr()
            self.expect_punct(")")
            return e
        if t.kind == "QIDENT":
            self.next()
            return col(t.value)
        if t.kind == "IDENT":
            up = t.upper
            if up == "NULL":
                self.next()
                return lit(None)
            if up in ("TRUE", "FALSE"):
                self.next()
                return lit(up == "TRUE")
            if up == "CASE":
                return self._parse_case()
            if up == "EXISTS" and self.peek(1).value == "(":
                self.next()
                self.expect_punct("(")
                plan = self._parse_query_body()
                plan = self._maybe_order_limit(plan)
                self.expect_punct(")")
                return _SubqueryExistsExpr(plan, True)
            if up == "CAST":
                self.next()
                self.expect_punct("(")
                e = self._parse_expr()
                self.expect_kw("AS")
                tp = self._parse_type_name()
                self.expect_punct(")")
                return e.cast(tp)
            if self.peek(1).value == "(":  # function call
                self.next()
                self.next()
                distinct = self.eat_kw("DISTINCT")
                args: List[ColumnExpr] = []
                if not self.at_punct(")"):
                    while True:
                        a = self.peek()
                        if a.kind == "OP" and a.value == "*":
                            self.next()
                            args.append(lit(1))  # COUNT(*)
                        else:
                            args.append(self._parse_expr())
                        if not self.eat_punct(","):
                            break
                self.expect_punct(")")
                if self.at_kw("OVER"):
                    if distinct:
                        raise FugueSQLSyntaxError(
                            "DISTINCT is not supported in window functions"
                        )
                    return self._parse_over(up, args)
                return self._make_func(up, args, distinct)
            # plain or qualified column ref — the qualifier is kept as
            # side-band metadata (correlated-subquery analysis needs it;
            # everything else sees the bare name)
            self.next()
            name = t.value
            qual = ""
            while self.at_punct(".") and self.peek(1).kind in ("IDENT", "QIDENT"):
                self.next()
                qual = name
                name = self._parse_name()
            c = col(name)
            if qual:
                c._sql_qualifier = qual  # type: ignore[attr-defined]
            return c
        raise FugueSQLSyntaxError(f"unexpected token {t.value!r} at {t.pos}")

    def _parse_type_name(self) -> Any:
        name = self._parse_name().lower()
        # SQL type names → schema expression types
        mapping = {
            "integer": "int",
            "bigint": "long",
            "smallint": "short",
            "tinyint": "byte",
            "varchar": "str",
            "text": "str",
            "string": "str",
            "real": "float",
            "boolean": "bool",
            "timestamp": "datetime",
        }
        base = mapping.get(name, name)
        if self.eat_punct("("):  # e.g. VARCHAR(10), DECIMAL(10,2)
            args = []
            while not self.at_punct(")"):
                args.append(self.next().value)
                self.eat_punct(",")
            self.expect_punct(")")
            if base == "decimal":
                return f"decimal({','.join(args)})"
        return to_pa_datatype(base)

    def _parse_case(self) -> ColumnExpr:
        self.expect_kw("CASE")
        cases: List[Tuple[ColumnExpr, ColumnExpr]] = []
        base: Optional[ColumnExpr] = None
        if not self.at_kw("WHEN"):
            base = self._parse_expr()
        while self.eat_kw("WHEN"):
            cond = self._parse_expr()
            if base is not None:
                cond = _BinaryOpExpr("==", base, cond)
            self.expect_kw("THEN")
            val = self._parse_expr()
            cases.append((cond, val))
        default = None
        if self.eat_kw("ELSE"):
            default = self._parse_expr()
        self.expect_kw("END")
        return _CaseWhenExpr(cases, default)

    def _parse_over(self, func: str, args: List[ColumnExpr]) -> ColumnExpr:
        from ..column.expressions import _WindowExpr

        self.expect_kw("OVER")
        self.expect_punct("(")
        partition_by: List[str] = []
        order_by: List[Any] = []
        if self.at_kw("PARTITION"):
            self.next()
            self.expect_kw("BY")
            while True:
                partition_by.append(self._parse_qualified_name())
                if not self.eat_punct(","):
                    break
        if self.at_kw("ORDER"):
            self.next()
            self.expect_kw("BY")
            while True:
                name = self._parse_qualified_name()
                asc = True
                if self.eat_kw("DESC"):
                    asc = False
                else:
                    self.eat_kw("ASC")
                order_by.append((name, asc))
                if not self.eat_punct(","):
                    break
        frame = None
        if self.at_kw("ROWS") or self.at_kw("RANGE"):
            kind = self.next().value.lower()
            if self.eat_kw("BETWEEN"):
                start = self._parse_frame_bound()
                self.expect_kw("AND")
                end = self._parse_frame_bound()
            else:
                start = self._parse_frame_bound()
                end = "current"
            if start == "unb_foll" or end == "unb_prec":
                raise FugueSQLSyntaxError(
                    "invalid window frame: the start bound cannot be "
                    "UNBOUNDED FOLLOWING and the end bound cannot be "
                    "UNBOUNDED PRECEDING"
                )
            if kind == "rows" and any(
                isinstance(b, tuple) and not isinstance(b[1], int)
                for b in (start, end)
            ):
                raise FugueSQLSyntaxError(
                    "ROWS frame offsets must be integers"
                )
            frame = (kind, start, end)
        self.expect_punct(")")
        return _WindowExpr(func, args, partition_by, order_by, frame=frame)

    def _parse_frame_bound(self) -> Any:
        if self.eat_kw("UNBOUNDED"):
            if self.eat_kw("PRECEDING"):
                return "unb_prec"
            self.expect_kw("FOLLOWING")
            return "unb_foll"
        if self.eat_kw("CURRENT"):
            self.expect_kw("ROW")
            return "current"
        t = self.next()
        if t.kind != "NUMBER":
            raise FugueSQLSyntaxError(f"invalid frame bound {t.value!r}")
        # RANGE offsets are value distances and may be fractional; keep the
        # exact number (ROWS validates integrality where the frame is built)
        v = float(t.value)
        n: Any = int(v) if v.is_integer() else v
        if self.eat_kw("PRECEDING"):
            return ("prec", n)
        self.expect_kw("FOLLOWING")
        return ("foll", n)

    def _make_func(self, name: str, args: List[ColumnExpr], distinct: bool) -> ColumnExpr:
        if name in _AGG_FUNCS:
            from ..column.functions import _SameTypeUnaryAggFuncExpr, _UnaryAggFuncExpr

            a = args[0] if len(args) > 0 else lit(1)
            fn = {"MEAN": "AVG"}.get(name, name)
            if fn in ("SUM", "COUNT", "AVG"):
                return _UnaryAggFuncExpr(fn, a, arg_distinct=distinct)
            return _SameTypeUnaryAggFuncExpr(fn, a, arg_distinct=distinct)
        return function(name, *args, arg_distinct=distinct)


def parse_select(sql: str) -> PlanNode:
    return SQLParser(sql).parse_full()
