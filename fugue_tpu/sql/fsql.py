"""FugueSQL — the extended SQL dialect compiled into FugueWorkflow.

In-tree replacement for the reference's ANTLR-based FugueSQL stack
(`fugue/sql/_visitors.py`, external ``fugue-sql-antlr`` — SURVEY §2.6):
a statement-oriented parser over the same tokenizer as ``parser.py``.

Supported statements (each optionally prefixed ``name =`` / ``name ?=``):

    CREATE [[...]] SCHEMA a:int,b:str
    CREATE USING ext [(params)] [SCHEMA s]
    df = LOAD [PARQUET|CSV|JSON] "path" [(params)] [COLUMNS schema_or_cols]
    SAVE [df] [PREPARTITION ...] OVERWRITE|APPEND|TO [SINGLE] "path" [(params)]
    TRANSFORM [df] [PREPARTITION BY k [PRESORT s]] USING ext [(params)] [SCHEMA s]
    OUTTRANSFORM [df] [PREPARTITION ...] USING ext [(params)]
    PROCESS [dfs] [PREPARTITION ...] USING ext [(params)] [SCHEMA s]
    OUTPUT [dfs] USING ext [(params)]
    PRINT [n ROWS] [FROM dfs] [ROWCOUNT] [TITLE "t"]
    SELECT ...                      (standard SQL; frames are table names;
                                     no FROM → previous statement's output)
    TAKE n ROW[S] [FROM df] [PREPARTITION BY ...] [PRESORT ...]
    SAMPLE [REPLACE] n ROWS|x PERCENT [SEED n] [FROM df]
    DROP ROWS IF ANY|ALL NULL[S] [ON cols] [FROM df]
    DROP COLUMNS a,b [IF EXISTS] [FROM df]
    FILL NULLS PARAMS k:v,... [FROM df]
    RENAME COLUMNS a:b,... [FROM df]
    ALTER COLUMNS a:type,... [FROM df]
    YIELD [LOCAL] DATAFRAME|FILE|TABLE AS name
    PERSIST | BROADCAST | CHECKPOINT | WEAK CHECKPOINT |
    STRONG CHECKPOINT | DETERMINISTIC CHECKPOINT

Statements separate on ``;`` or on a newline that begins a new statement
keyword / assignment. Jinja templating (``{{var}}``) fills from passed
variables and captured caller locals (reference ``fugue/sql/workflow.py:52``).
"""

import json
from typing import Any, Dict, List, Optional, Tuple

from .._utils.convert import get_caller_global_local_vars
from ..collections.partition import PartitionSpec
from ..dataframe import DataFrame
from ..exceptions import FugueSQLSyntaxError
from ..workflow.workflow import FugueWorkflow, WorkflowDataFrame
from .parser import Token, tokenize

_STATEMENT_KEYWORDS = {
    "CREATE", "LOAD", "SAVE", "TRANSFORM", "OUTTRANSFORM", "PROCESS",
    "OUTPUT", "PRINT", "SELECT", "TAKE", "SAMPLE", "DROP", "FILL",
    "RENAME", "ALTER", "YIELD", "PERSIST", "BROADCAST", "CHECKPOINT",
    "DETERMINISTIC", "WEAK", "STRONG", "OUT",
}

_CLAUSE_KEYWORDS = {
    "USING", "SCHEMA", "PARAMS", "PREPARTITION", "PRESORT", "FROM",
    "OVERWRITE", "APPEND", "TO", "SINGLE", "COLUMNS", "CALLBACK",
    "ROWCOUNT", "TITLE", "ROWS", "ROW",
}


class _StatementSplitter:
    """Split a token stream into statements at depth-0 boundaries."""

    def __init__(self, sql: str):
        self._sql = sql
        self._tokens = tokenize(sql)

    def split(self) -> List[Any]:
        """Returns a list of (tokens, end_pos) per statement."""
        import bisect

        newlines = [i for i, ch in enumerate(self._sql) if ch == "\n"]
        statements: List[Any] = []
        cur: List[Token] = []
        depth = 0
        last_line = -1
        for idx, t in enumerate(self._tokens):
            if t.kind == "EOF":
                break
            if t.kind == "PUNCT" and t.value == "(":
                depth += 1
            elif t.kind == "PUNCT" and t.value == ")":
                depth -= 1
            if t.kind == "PUNCT" and t.value == ";" and depth == 0:
                if cur:
                    statements.append((cur, t.pos))
                    cur = []
                continue
            line = bisect.bisect_left(newlines, t.pos)
            if (
                depth == 0
                and cur
                and line > last_line
                and self._starts_statement(t, idx)
            ):
                statements.append((cur, t.pos))
                cur = []
            cur.append(t)
            last_line = line
        if cur:
            statements.append((cur, len(self._sql)))
        return statements

    def _starts_statement(self, t: Token, idx: int) -> bool:
        if t.kind != "IDENT" and t.kind != "QIDENT":
            return False
        if t.kind == "IDENT" and t.upper in _STATEMENT_KEYWORDS:
            return True
        # assignment: IDENT [?]= ...
        nxt = self._tokens[idx + 1] if idx + 1 < len(self._tokens) else None
        if nxt is not None and nxt.kind == "OP" and nxt.value in ("=",):
            return True
        if (
            nxt is not None
            and nxt.value == "?"
            and idx + 2 < len(self._tokens)
            and self._tokens[idx + 2].value == "="
        ):
            return True
        return False


class _StatementParser:
    """Cursor over one statement's tokens."""

    def __init__(self, tokens: List[Token], sql: str, end_pos: Optional[int] = None):
        self._tokens = tokens + [Token("EOF", "", -1)]
        self._sql = sql
        self._end_pos = len(sql) if end_pos is None else end_pos
        self._i = 0

    def peek(self, offset: int = 0) -> Token:
        return self._tokens[min(self._i + offset, len(self._tokens) - 1)]

    def next(self) -> Token:
        t = self.peek()
        self._i += 1
        return t

    def done(self) -> bool:
        return self.peek().kind == "EOF"

    def at_kw(self, *kws: str) -> bool:
        t = self.peek()
        return t.kind == "IDENT" and t.upper in kws

    def eat_kw(self, *kws: str) -> bool:
        if self.at_kw(*kws):
            self.next()
            return True
        return False

    def expect_kw(self, kw: str) -> None:
        if not self.eat_kw(kw):
            t = self.peek()
            raise FugueSQLSyntaxError(f"expected {kw}, got {t.value!r}")

    def text_until(self, *stop_kws: str) -> str:
        """Raw source text until a stop keyword at depth 0 (or end)."""
        start_tok = self.peek()
        if start_tok.kind == "EOF":
            return ""
        start = start_tok.pos
        depth = 0
        end = len(self._sql)
        while not self.done():
            t = self.peek()
            if t.kind == "PUNCT" and t.value == "(":
                depth += 1
            elif t.kind == "PUNCT" and t.value == ")":
                depth -= 1
            if depth == 0 and t.kind == "IDENT" and t.upper in stop_kws:
                end = t.pos
                break
            self.next()
            if self.done():
                end = self._end_pos
        return self._sql[start:end].strip()

    def parse_params(self) -> Dict[str, Any]:
        """(a=1, b="x") or PARAMS a:1,b:"x" or a JSON object."""
        params: Dict[str, Any] = {}
        if self.peek().kind == "PUNCT" and self.peek().value == "(":
            self.next()
            while not (self.peek().kind == "PUNCT" and self.peek().value == ")"):
                key = self.next().value
                t = self.next()
                if not (t.value in ("=", ":")):
                    raise FugueSQLSyntaxError(f"expected = or : after {key}")
                params[key] = self._parse_value()
                if self.peek().value == ",":
                    self.next()
            self.next()
        else:
            while True:
                key = self.next().value
                t = self.next()
                if t.value not in ("=", ":"):
                    raise FugueSQLSyntaxError(f"expected = or : after {key}")
                params[key] = self._parse_value()
                if self.peek().value == ",":
                    self.next()
                    continue
                break
        return params

    def _parse_value(self) -> Any:
        t = self.next()
        if t.kind == "STRING":
            return t.value
        if t.kind == "NUMBER":
            return float(t.value) if "." in t.value else int(t.value)
        if t.kind == "IDENT":
            if t.upper == "TRUE":
                return True
            if t.upper == "FALSE":
                return False
            if t.upper == "NULL":
                return None
            return t.value
        if t.kind == "PUNCT" and t.value == "(":  # nested tuple-ish → list
            vals = []
            while not (self.peek().kind == "PUNCT" and self.peek().value == ")"):
                vals.append(self._parse_value())
                if self.peek().value == ",":
                    self.next()
            self.next()
            return vals
        raise FugueSQLSyntaxError(f"invalid value {t.value!r}")


class FugueSQLCompiler:
    """Compile a FugueSQL script into workflow tasks."""

    def __init__(
        self,
        workflow: FugueWorkflow,
        scope_dfs: Dict[str, Any],
        global_vars: Dict[str, Any],
        local_vars: Dict[str, Any],
    ):
        self._wf = workflow
        self._scope: Dict[str, WorkflowDataFrame] = {}
        self._raw_scope = dict(scope_dfs)
        self._gv = global_vars
        self._lv = local_vars
        self._last: Optional[WorkflowDataFrame] = None

    @property
    def last(self) -> Optional[WorkflowDataFrame]:
        return self._last

    def compile(self, sql: str) -> None:
        for tokens, end_pos in _StatementSplitter(sql).split():
            self._compile_statement(_StatementParser(tokens, sql, end_pos), sql)

    # ------------------------------------------------------------------
    def _resolve_df(self, name: str) -> WorkflowDataFrame:
        if name in self._scope:
            return self._scope[name]
        if name in self._raw_scope:
            wdf = self._wf.create_data(self._raw_scope[name])
            self._scope[name] = wdf
            return wdf
        for vars_ in (self._lv, self._gv):
            if name in vars_ and _is_df_like(vars_[name]):
                wdf = self._wf.create_data(vars_[name])
                self._scope[name] = wdf
                return wdf
        raise FugueSQLSyntaxError(f"dataframe {name!r} is not defined")

    def _resolve_ext(self, name: str) -> Any:
        for vars_ in (self._lv, self._gv):
            if name in vars_:
                return vars_[name]
        return name  # registered-name / import-path resolution happens later

    def _compile_statement(self, p: _StatementParser, sql: str) -> None:
        assign: Optional[str] = None
        t0, t1 = p.peek(0), p.peek(1)
        if t0.kind in ("IDENT", "QIDENT") and (
            t0.kind == "QIDENT" or t0.upper not in _STATEMENT_KEYWORDS
        ):
            if t1.kind == "OP" and t1.value == "=":
                assign = t0.value
                p.next()
                p.next()
            elif t1.value == "?" and p.peek(2).value == "=":
                assign = t0.value  # `?=` treated as plain assignment
                p.next()
                p.next()
                p.next()
        result = self._statement_body(p, sql)
        # postfix modifiers on the produced frame
        while result is not None and not p.done():
            if p.eat_kw("PERSIST"):
                result.persist()
            elif p.eat_kw("BROADCAST"):
                result.broadcast()
            elif p.at_kw("WEAK") and p.peek(1).upper == "CHECKPOINT":
                p.next(); p.next()
                result.weak_checkpoint()
            elif p.at_kw("STRONG") and p.peek(1).upper == "CHECKPOINT":
                p.next(); p.next()
                result.strong_checkpoint()
            elif p.at_kw("DETERMINISTIC") and p.peek(1).upper == "CHECKPOINT":
                p.next(); p.next()
                result.deterministic_checkpoint()
            elif p.eat_kw("CHECKPOINT"):
                result.checkpoint()
            elif p.eat_kw("YIELD"):
                self._yield_clause(p, result)
            else:
                t = p.peek()
                raise FugueSQLSyntaxError(f"unexpected {t.value!r} in statement")
        if result is not None:
            if assign is not None:
                self._scope[assign] = result
            self._last = result

    def _yield_clause(self, p: _StatementParser, df: WorkflowDataFrame) -> None:
        local = p.eat_kw("LOCAL")
        if p.eat_kw("DATAFRAME"):
            p.expect_kw("AS")
            df.yield_dataframe_as(p.next().value, as_local=local)
        elif p.eat_kw("FILE"):
            p.expect_kw("AS")
            df.yield_file_as(p.next().value)
        elif p.eat_kw("TABLE"):
            p.expect_kw("AS")
            df.yield_table_as(p.next().value)
        else:
            raise FugueSQLSyntaxError("YIELD must be DATAFRAME, FILE or TABLE")

    # ------------------------------------------------------------------
    def _statement_body(self, p: _StatementParser, sql: str) -> Optional[WorkflowDataFrame]:
        if p.at_kw("CREATE"):
            return self._stmt_create(p)
        if p.at_kw("LOAD"):
            return self._stmt_load(p)
        if p.at_kw("SAVE"):
            self._stmt_save(p)
            return None
        if p.at_kw("TRANSFORM"):
            return self._stmt_transform(p, output=False)
        if p.at_kw("OUTTRANSFORM") or (p.at_kw("OUT") and p.peek(1).upper == "TRANSFORM"):
            if p.eat_kw("OUT"):
                pass
            return self._stmt_transform(p, output=True)
        if p.at_kw("PROCESS"):
            return self._stmt_process(p, output=False)
        if p.at_kw("OUTPUT"):
            self._stmt_process(p, output=True)
            return None
        if p.at_kw("PRINT"):
            self._stmt_print(p)
            return None
        if p.at_kw("SELECT"):
            return self._stmt_select(p, sql)
        if p.at_kw("CONNECT"):
            return self._stmt_connect(p, sql)
        if p.at_kw("TAKE"):
            return self._stmt_take(p)
        if p.at_kw("SAMPLE"):
            return self._stmt_sample(p)
        if p.at_kw("DROP"):
            return self._stmt_drop(p)
        if p.at_kw("FILL"):
            return self._stmt_fill(p)
        if p.at_kw("RENAME"):
            return self._stmt_rename(p)
        if p.at_kw("ALTER"):
            return self._stmt_alter(p)
        if p.at_kw(
            "YIELD", "PERSIST", "BROADCAST", "CHECKPOINT", "DETERMINISTIC",
            "WEAK", "STRONG",
        ):
            # modifier-only statement applies to the previous frame
            df = self._need_last()
            while not p.done():
                if p.eat_kw("YIELD"):
                    self._yield_clause(p, df)
                elif p.eat_kw("PERSIST"):
                    df.persist()
                elif p.eat_kw("BROADCAST"):
                    df.broadcast()
                elif p.at_kw("WEAK") and p.peek(1).upper == "CHECKPOINT":
                    p.next(); p.next(); df.weak_checkpoint()
                elif p.at_kw("STRONG") and p.peek(1).upper == "CHECKPOINT":
                    p.next(); p.next(); df.strong_checkpoint()
                elif p.at_kw("DETERMINISTIC") and p.peek(1).upper == "CHECKPOINT":
                    p.next(); p.next(); df.deterministic_checkpoint()
                elif p.eat_kw("CHECKPOINT"):
                    df.checkpoint()
                else:
                    raise FugueSQLSyntaxError(f"unexpected {p.peek().value!r}")
            return df
        t = p.peek()
        raise FugueSQLSyntaxError(f"unknown statement start {t.value!r}")

    def _need_last(self) -> WorkflowDataFrame:
        if self._last is None:
            raise FugueSQLSyntaxError("no previous dataframe in scope")
        return self._last

    def _opt_from_df(self, p: _StatementParser) -> WorkflowDataFrame:
        if p.eat_kw("FROM"):
            return self._resolve_df(p.next().value)
        t = p.peek()
        if t.kind in ("IDENT", "QIDENT") and t.upper not in _CLAUSE_KEYWORDS and t.upper not in _STATEMENT_KEYWORDS:
            p.next()
            return self._resolve_df(t.value)
        return self._need_last()

    def _opt_df_list(self, p: _StatementParser) -> List[WorkflowDataFrame]:
        dfs: List[WorkflowDataFrame] = []
        while True:
            t = p.peek()
            if t.kind in ("IDENT", "QIDENT") and t.upper not in _CLAUSE_KEYWORDS:
                p.next()
                dfs.append(self._resolve_df(t.value))
                if p.peek().value == ",":
                    p.next()
                    continue
            break
        if len(dfs) == 0 and self._last is not None:
            dfs.append(self._last)
        return dfs

    def _prepartition(self, p: _StatementParser) -> Optional[PartitionSpec]:
        if not p.eat_kw("PREPARTITION"):
            return None
        kwargs: Dict[str, Any] = {}
        if p.peek().kind == "NUMBER":
            kwargs["num"] = int(p.next().value)
        if p.eat_kw("BY"):
            cols = []
            while True:
                cols.append(p.next().value)
                if p.peek().value == ",":
                    p.next()
                    continue
                break
            kwargs["by"] = cols
        if p.eat_kw("PRESORT"):
            parts = []
            while True:
                name = p.next().value
                direction = ""
                if p.at_kw("ASC", "DESC"):
                    direction = " " + p.next().value
                parts.append(name + direction)
                if p.peek().value == ",":
                    p.next()
                    continue
                break
            kwargs["presort"] = ",".join(parts)
        return PartitionSpec(**kwargs)

    # -- statements ------------------------------------------------------
    def _stmt_create(self, p: _StatementParser) -> WorkflowDataFrame:
        p.expect_kw("CREATE")
        if p.eat_kw("USING"):
            ext = self._resolve_ext(p.next().value)
            params = {}
            if p.peek().value == "(":
                params = p.parse_params()
            schema = None
            if p.eat_kw("SCHEMA"):
                schema = p.text_until("PARAMS", "YIELD", "PERSIST", "BROADCAST", "CHECKPOINT")
            if p.eat_kw("PARAMS"):
                params.update(p.parse_params())
            return self._wf.create(ext, schema=schema, params=params)
        # inline data: [[...],[...]] SCHEMA s
        data_text = p.text_until("SCHEMA")
        p.expect_kw("SCHEMA")
        schema = p.text_until(
            "YIELD", "PERSIST", "BROADCAST", "CHECKPOINT", "DETERMINISTIC",
            "WEAK", "STRONG",
        )
        try:
            data = json.loads(data_text)
        except json.JSONDecodeError as e:
            raise FugueSQLSyntaxError(f"invalid inline data {data_text!r}") from e
        return self._wf.df(data, schema)

    def _stmt_load(self, p: _StatementParser) -> WorkflowDataFrame:
        p.expect_kw("LOAD")
        fmt = ""
        if p.at_kw("PARQUET", "CSV", "JSON"):
            fmt = p.next().value.lower()
        t = p.next()
        if t.kind != "STRING":
            raise FugueSQLSyntaxError("LOAD path must be a quoted string")
        params: Dict[str, Any] = {}
        if p.peek().value == "(":
            params = p.parse_params()
        columns = None
        if p.eat_kw("COLUMNS"):
            columns = p.text_until(
                "YIELD", "PERSIST", "BROADCAST", "CHECKPOINT",
            )
            if ":" not in columns:
                columns = [c.strip() for c in columns.split(",")]
        return self._wf.load(t.value, fmt=fmt, columns=columns, **params)

    def _stmt_save(self, p: _StatementParser) -> None:
        p.expect_kw("SAVE")
        df = self._opt_from_df(p)
        spec = self._prepartition(p)
        mode = "overwrite"
        if p.eat_kw("OVERWRITE"):
            mode = "overwrite"
        elif p.eat_kw("APPEND"):
            mode = "append"
        elif p.eat_kw("TO"):
            mode = "error"
        single = p.eat_kw("SINGLE")
        fmt = ""
        if p.at_kw("PARQUET", "CSV", "JSON"):
            fmt = p.next().value.lower()
        t = p.next()
        if t.kind != "STRING":
            raise FugueSQLSyntaxError("SAVE path must be a quoted string")
        params: Dict[str, Any] = {}
        if p.peek().value == "(":
            params = p.parse_params()
        df.save(t.value, fmt=fmt, mode=mode, partition=spec, single=single, **params)

    def _stmt_transform(self, p: _StatementParser, output: bool) -> Optional[WorkflowDataFrame]:
        p.next()  # TRANSFORM / OUTTRANSFORM
        dfs = self._opt_df_list(p)
        spec = self._prepartition(p)
        p.expect_kw("USING")
        ext = self._resolve_ext(p.next().value)
        params: Dict[str, Any] = {}
        if p.peek().value == "(":
            params = p.parse_params()
        schema = None
        if p.eat_kw("SCHEMA"):
            schema = p.text_until(
                "PARAMS", "CALLBACK", "YIELD", "PERSIST", "BROADCAST",
                "CHECKPOINT", "DETERMINISTIC", "WEAK", "STRONG",
            )
        if p.eat_kw("PARAMS"):
            params.update(p.parse_params())
        callback = None
        if p.eat_kw("CALLBACK"):
            callback = self._resolve_ext(p.next().value)
        src = dfs[0] if len(dfs) == 1 else self._wf.zip(*dfs, partition=spec)
        if output:
            self._wf.out_transform(
                src, using=ext, params=params,
                pre_partition=spec, callback=callback,
                global_vars=self._gv, local_vars=self._lv,
            )
            return None
        return self._wf.transform(
            src, using=ext, schema=schema, params=params,
            pre_partition=spec, callback=callback,
            global_vars=self._gv, local_vars=self._lv,
        )

    def _stmt_process(self, p: _StatementParser, output: bool) -> Optional[WorkflowDataFrame]:
        p.next()  # PROCESS / OUTPUT
        dfs = self._opt_df_list(p)
        spec = self._prepartition(p)
        p.expect_kw("USING")
        ext = self._resolve_ext(p.next().value)
        params: Dict[str, Any] = {}
        if p.peek().value == "(":
            params = p.parse_params()
        schema = None
        if p.eat_kw("SCHEMA"):
            schema = p.text_until("PARAMS", "YIELD", "PERSIST", "BROADCAST", "CHECKPOINT")
        if p.eat_kw("PARAMS"):
            params.update(p.parse_params())
        if output:
            self._wf.output(
                *dfs, using=ext, params=params, pre_partition=spec,
                global_vars=self._gv, local_vars=self._lv,
            )
            return None
        return self._wf.process(
            *dfs, using=ext, schema=schema, params=params, pre_partition=spec,
            global_vars=self._gv, local_vars=self._lv,
        )

    def _stmt_print(self, p: _StatementParser) -> None:
        p.expect_kw("PRINT")
        n = 10
        if p.peek().kind == "NUMBER":
            n = int(p.next().value)
            p.eat_kw("ROWS") or p.eat_kw("ROW")
        dfs = []
        if p.eat_kw("FROM"):
            while True:
                dfs.append(self._resolve_df(p.next().value))
                if p.peek().value == ",":
                    p.next()
                    continue
                break
        else:
            t = p.peek()
            if t.kind in ("IDENT", "QIDENT") and t.upper not in ("ROWCOUNT", "TITLE"):
                dfs.append(self._resolve_df(p.next().value))
        if len(dfs) == 0:
            dfs.append(self._need_last())
        with_count = p.eat_kw("ROWCOUNT")
        title = None
        if p.eat_kw("TITLE"):
            t = p.next()
            title = t.value
        self._wf.show(*dfs, n=n, with_count=with_count, title=title)

    def _stmt_connect(self, p: _StatementParser, sql: str) -> WorkflowDataFrame:
        """``CONNECT <engine> [PARAMS k=v,...] SELECT ...`` — run ONE select
        on a specific SQL engine (the reference's engine-specific query,
        ``fugue/sql/_visitors.py:728-760``)."""
        p.expect_kw("CONNECT")
        parts: List[str] = [p.next().value]
        while p.peek().value == "." or (
            p.peek().kind == "IDENT"
            and not p.at_kw("SELECT")
            and not p.at_kw("PARAMS")
        ):
            parts.append(p.next().value)
        engine = "".join(parts)
        params: Dict[str, Any] = {}
        if p.eat_kw("PARAMS"):
            params = p.parse_params()
        if not p.at_kw("SELECT"):
            raise FugueSQLSyntaxError("CONNECT must be followed by SELECT")
        return self._stmt_select(p, sql, sql_engine=engine, sql_engine_params=params)

    def _stmt_select(
        self,
        p: _StatementParser,
        sql: str,
        sql_engine: Any = None,
        sql_engine_params: Optional[Dict[str, Any]] = None,
    ) -> WorkflowDataFrame:
        text = p.text_until(
            "PERSIST", "BROADCAST", "CHECKPOINT", "DETERMINISTIC", "WEAK",
            "STRONG", "YIELD",
        )
        # compile-dialect support: FugueSQL SELECTs written in a foreign
        # dialect (conf ``fugue.sql.compile.dialect``, e.g. "postgres")
        # transpile to the in-tree dialect BEFORE parsing — table-name
        # discovery and execution then see native text (the reference
        # routes this through sqlglot, fugue/constants.py:9 +
        # collections/sql.py:25-45)
        from ..constants import _FUGUE_GLOBAL_CONF, FUGUE_CONF_SQL_DIALECT

        compile_dialect = str(
            self._wf.conf.get(
                FUGUE_CONF_SQL_DIALECT,
                _FUGUE_GLOBAL_CONF.get(FUGUE_CONF_SQL_DIALECT, "spark"),
            )
        ).lower()
        if compile_dialect not in ("spark", "fugue"):
            from ..collections.sql import transpile_sql
            from .dialect import get_dialect

            get_dialect(compile_dialect)  # unknown dialects raise HERE —
            # a silent passthrough would parse foreign quoting as strings
            text = transpile_sql(text, compile_dialect, "fugue")
        # find referenced table names: parse and collect Scan nodes
        from .parser import SQLParser, Scan as ScanNode, PlanNode, JoinNode, Subquery, SelectNode, SetOpNode, SortNode, LimitNode

        plan = SQLParser(text).parse_full()
        names: List[str] = []

        def walk_expr(e: Any) -> None:
            # subquery expressions reference tables of their own
            from .parser import (
                _SubqueryExistsExpr,
                _SubqueryInExpr,
                _SubqueryScalarExpr,
            )

            if isinstance(
                e, (_SubqueryScalarExpr, _SubqueryInExpr, _SubqueryExistsExpr)
            ):
                walk(e.plan)
            for c in getattr(e, "children", []):
                walk_expr(c)

        def walk(n: PlanNode) -> None:
            if isinstance(n, ScanNode):
                if n.name not in names:
                    names.append(n.name)
            elif isinstance(n, Subquery):
                walk(n.child)
            elif isinstance(n, JoinNode):
                walk(n.left)
                walk(n.right)
            elif isinstance(n, SetOpNode):
                walk(n.left)
                walk(n.right)
            elif isinstance(n, (SortNode, LimitNode)):
                walk(n.child)
            elif isinstance(n, SelectNode):
                if n.child is not None:
                    walk(n.child)
                for c in n.projections:
                    walk_expr(c)
                if n.where is not None:
                    walk_expr(n.where)
                if n.having is not None:
                    walk_expr(n.having)

        walk(plan)
        if len(names) == 0:
            # no FROM → operate on the previous frame as table "_0"
            prev = self._need_last()
            text2 = _inject_from(text)
            return self._wf.select(
                *_interleave(text2, {"_0": prev}),
                sql_engine=sql_engine,
                sql_engine_params=sql_engine_params,
            )
        mapping = {n: self._resolve_df(n) for n in names}
        return self._wf.select(
            *_interleave(text, mapping),
            sql_engine=sql_engine,
            sql_engine_params=sql_engine_params,
        )

    def _stmt_take(self, p: _StatementParser) -> WorkflowDataFrame:
        p.expect_kw("TAKE")
        n = int(p.next().value)
        p.eat_kw("ROWS") or p.eat_kw("ROW")
        df = self._opt_from_df(p)
        spec = self._prepartition(p)
        presort = ""
        if p.eat_kw("PRESORT"):
            presort = p.text_until("YIELD", "PERSIST", "BROADCAST", "CHECKPOINT")
        if spec is not None:
            df = df.partition(spec)
        return df.take(n, presort=presort)

    def _stmt_sample(self, p: _StatementParser) -> WorkflowDataFrame:
        p.expect_kw("SAMPLE")
        replace = p.eat_kw("REPLACE")
        num = p.next()
        n: Optional[int] = None
        frac: Optional[float] = None
        if p.eat_kw("ROWS") or p.eat_kw("ROW"):
            n = int(num.value)
        elif p.eat_kw("PERCENT"):
            frac = float(num.value) / 100.0
        else:
            raise FugueSQLSyntaxError("SAMPLE needs ROWS or PERCENT")
        seed = None
        if p.eat_kw("SEED"):
            seed = int(p.next().value)
        df = self._opt_from_df(p)
        return df.sample(n=n, frac=frac, replace=replace, seed=seed)

    def _stmt_drop(self, p: _StatementParser) -> WorkflowDataFrame:
        p.expect_kw("DROP")
        if p.eat_kw("ROWS"):
            p.expect_kw("IF")
            how = "any"
            if p.eat_kw("ALL"):
                how = "all"
            else:
                p.eat_kw("ANY")
            p.eat_kw("NULLS") or p.eat_kw("NULL")
            subset = None
            if p.eat_kw("ON"):
                subset = []
                while True:
                    subset.append(p.next().value)
                    if p.peek().value == ",":
                        p.next()
                        continue
                    break
            df = self._opt_from_df(p)
            return df.dropna(how=how, subset=subset)
        p.expect_kw("COLUMNS")
        cols = []
        while True:
            cols.append(p.next().value)
            if p.peek().value == ",":
                p.next()
                continue
            break
        if_exists = False
        if p.eat_kw("IF"):
            p.expect_kw("EXISTS")
            if_exists = True
        df = self._opt_from_df(p)
        return df.drop(cols, if_exists=if_exists)

    def _stmt_fill(self, p: _StatementParser) -> WorkflowDataFrame:
        p.expect_kw("FILL")
        p.eat_kw("NULLS") or p.eat_kw("NULL")
        p.eat_kw("PARAMS")
        params = p.parse_params()
        df = self._opt_from_df(p)
        return df.fillna(dict(params))

    def _stmt_rename(self, p: _StatementParser) -> WorkflowDataFrame:
        p.expect_kw("RENAME")
        p.expect_kw("COLUMNS")
        mapping: Dict[str, str] = {}
        while True:
            old = p.next().value
            t = p.next()
            if t.value != ":":
                raise FugueSQLSyntaxError("RENAME COLUMNS uses old:new pairs")
            mapping[old] = p.next().value
            if p.peek().value == ",":
                p.next()
                continue
            break
        df = self._opt_from_df(p)
        return df.rename(mapping)

    def _stmt_alter(self, p: _StatementParser) -> WorkflowDataFrame:
        p.expect_kw("ALTER")
        p.expect_kw("COLUMNS")
        schema = p.text_until("FROM", "YIELD", "PERSIST", "BROADCAST", "CHECKPOINT")
        df = self._opt_from_df(p)
        return df.alter_columns(schema)


def _is_df_like(obj: Any) -> bool:
    import pandas as pd
    import pyarrow as pa

    from ..collections.yielded import Yielded

    return isinstance(obj, (DataFrame, pd.DataFrame, pa.Table, Yielded, WorkflowDataFrame))


def _inject_from(text: str) -> str:
    """Append ``FROM _0`` to a SELECT with no FROM clause."""
    upper = text.upper()
    for kw in (" WHERE ", " GROUP ", " HAVING ", " ORDER ", " LIMIT "):
        idx = upper.find(kw)
        if idx >= 0:
            return text[:idx] + " FROM _0 " + text[idx:]
    return text + " FROM _0"


def _interleave(sql: str, mapping: Dict[str, WorkflowDataFrame]) -> List[Any]:
    """Split SQL text into [str, WorkflowDataFrame, str, ...] pieces for
    ``FugueWorkflow.select``. Token-aware: only IDENT tokens are replaced,
    never content inside string literals or quoted identifiers."""
    if len(mapping) == 0:
        return [sql]
    parts: List[Any] = []
    pos = 0
    for t in tokenize(sql):
        if t.kind in ("IDENT", "QIDENT") and t.value in mapping:
            if t.pos > pos:
                parts.append(sql[pos : t.pos])
            parts.append(mapping[t.value])
            # QIDENT spans include the backticks in the source
            pos = t.pos + len(t.value) + (2 if t.kind == "QIDENT" else 0)
    if pos < len(sql):
        parts.append(sql[pos:])
    return parts


# ---------------------------------------------------------------------------
# public api
# ---------------------------------------------------------------------------


class FugueSQLWorkflow(FugueWorkflow):
    """FugueWorkflow with ``__call__(sql)`` compiling FugueSQL
    (reference ``fugue/sql/workflow.py:17``)."""

    def __init__(self, compile_conf: Any = None):
        super().__init__(compile_conf)
        self._sql_vars: Dict[str, Any] = {}

    def __call__(self, code: str, *args: Any, **kwargs: Any) -> None:
        global_vars, local_vars = get_caller_global_local_vars()
        variables = dict(self._sql_vars)
        for a in args:
            if isinstance(a, dict):
                variables.update(a)
        variables.update(kwargs)
        code = fill_sql_template(code, {**local_vars, **variables})
        compiler = FugueSQLCompiler(
            self,
            {k: v for k, v in variables.items() if _is_df_like(v)},
            global_vars,
            local_vars,
        )
        compiler.compile(code)
        self._sql_vars.update(
            {k: v for k, v in compiler._scope.items()}
        )


def fill_sql_template(template: str, variables: Dict[str, Any]) -> str:
    """Jinja-fill the template (reference uses the same mechanism)."""
    if "{{" not in template and "{%" not in template:
        return template
    import jinja2

    safe = {
        k: v
        for k, v in variables.items()
        if isinstance(k, str) and k.isidentifier() and not k.startswith("__")
        and not _is_df_like(v)
    }
    return jinja2.Template(template).render(safe)


def fugue_sql(
    query: str,
    *args: Any,
    engine: Any = None,
    engine_conf: Any = None,
    as_fugue: bool = False,
    as_local: bool = False,
    **kwargs: Any,
) -> Any:
    """Run FugueSQL and return the LAST statement's dataframe
    (reference ``fugue/sql/api.py:18``)."""
    from ..dataframe.api import get_native_as_df

    dag = fugue_sql_flow(query, *args, **kwargs)
    last = dag._last_compiled
    if last is None:
        raise FugueSQLSyntaxError("fugue_sql requires the last statement to output a dataframe")
    last.yield_dataframe_as("__fugue_sql_result__", as_local=as_local)
    dag.run(engine, engine_conf)
    result = dag.yields["__fugue_sql_result__"].result
    dag.release_task_results()  # free intermediates now, not at cyclic GC
    return result if as_fugue else get_native_as_df(result)


def fugue_sql_flow(query: str, *args: Any, **kwargs: Any) -> "FugueSQLWorkflow":
    """Compile FugueSQL into a workflow you can run (reference ``:111``)."""
    global_vars, local_vars = get_caller_global_local_vars()
    dag = FugueSQLWorkflow()
    variables: Dict[str, Any] = {}
    for a in args:
        if isinstance(a, dict):
            variables.update(a)
    variables.update(kwargs)
    code = fill_sql_template(query, {**local_vars, **variables})
    compiler = FugueSQLCompiler(
        dag,
        {k: v for k, v in variables.items() if _is_df_like(v)},
        global_vars,
        local_vars,
    )
    compiler.compile(code)
    dag._last_compiled = compiler.last  # type: ignore
    return dag
