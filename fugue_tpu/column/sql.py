"""SELECT-column validation and SQL text generation from the expression IR.

Parity with the reference (`fugue/column/sql.py:38,233`): ``SelectColumns``
validates a projection (agg/group-key rules, wildcard rules, unique names);
``SQLExpressionGenerator`` renders the IR to SQL text for SQL-backed engines
and computes the schema-correction diff after SQL type inference.
"""

from typing import Any, Callable, Dict, Iterable, List, Optional

import pyarrow as pa

from .._utils.assertion import assert_or_throw
from .._utils.hash import to_uuid
from ..exceptions import FugueSQLError
from ..schema import Schema, type_to_expression
from .expressions import (
    ColumnExpr,
    _BinaryOpExpr,
    _FuncExpr,
    _LitColumnExpr,
    _NamedColumnExpr,
    _UnaryOpExpr,
)
from .functions import is_agg


class SelectColumns:
    """A validated set of select expressions."""

    def __init__(self, *cols: ColumnExpr, arg_distinct: bool = False):
        self._distinct = arg_distinct
        self._cols = [c.infer_alias() for c in cols]
        assert_or_throw(len(self._cols) > 0, FugueSQLError("select can't be empty"))
        self._wildcards = [
            c for c in self._cols
            if isinstance(c, _NamedColumnExpr) and c.wildcard
        ]
        assert_or_throw(
            len(self._wildcards) <= 1,
            FugueSQLError("at most one wildcard is allowed"),
        )
        names = [c.output_name for c in self._cols if c.output_name != "" and c.output_name != "*"]
        assert_or_throw(
            len(names) == len(set(names)),
            lambda: FugueSQLError(f"duplicated output names in {names}"),
        )
        self._agg_funcs = [c for c in self._cols if is_agg(c)]
        self._non_agg = [
            c for c in self._cols if not is_agg(c) and not (
                isinstance(c, _NamedColumnExpr) and c.wildcard
            )
        ]
        self._literals = [c for c in self._cols if isinstance(c, _LitColumnExpr)]
        if self.has_agg:
            assert_or_throw(
                len(self._wildcards) == 0,
                FugueSQLError("wildcard can't be used together with aggregation"),
            )

    @property
    def is_distinct(self) -> bool:
        return self._distinct

    @property
    def all_cols(self) -> List[ColumnExpr]:
        return self._cols

    @property
    def has_agg(self) -> bool:
        return len(self._agg_funcs) > 0

    @property
    def has_literals(self) -> bool:
        return len(self._literals) > 0

    @property
    def has_wildcard(self) -> bool:
        return len(self._wildcards) > 0

    @property
    def simple(self) -> bool:
        return all(
            isinstance(c, _NamedColumnExpr) and c.as_type is None for c in self._cols
        )

    @property
    def simple_cols(self) -> List[ColumnExpr]:
        return [c for c in self._cols if isinstance(c, _NamedColumnExpr)]

    @property
    def agg_funcs(self) -> List[ColumnExpr]:
        return self._agg_funcs

    @property
    def non_agg_funcs(self) -> List[ColumnExpr]:
        return [
            c for c in self._non_agg
            if not isinstance(c, (_NamedColumnExpr, _LitColumnExpr))
        ]

    @property
    def group_keys(self) -> List[ColumnExpr]:
        """Non-agg, non-literal columns — the implicit GROUP BY keys."""
        return [c for c in self._non_agg if not isinstance(c, _LitColumnExpr)]

    def assert_all_with_names(self) -> "SelectColumns":
        for c in self._cols:
            if isinstance(c, _NamedColumnExpr) and c.wildcard:
                continue
            assert_or_throw(
                c.output_name != "",
                lambda: FugueSQLError(f"{c!r} has no output name"),
            )
        return self

    def assert_no_wildcard(self) -> "SelectColumns":
        assert_or_throw(not self.has_wildcard, FugueSQLError("wildcard not allowed"))
        return self

    def assert_no_agg(self) -> "SelectColumns":
        assert_or_throw(not self.has_agg, FugueSQLError("aggregation not allowed"))
        return self

    def replace_wildcard(self, schema: Schema) -> "SelectColumns":
        """Expand ``*`` into explicit column references."""
        if not self.has_wildcard:
            return self
        explicit = {
            c.output_name for c in self._cols if c.output_name not in ("", "*")
        }
        cols: List[ColumnExpr] = []
        for c in self._cols:
            if isinstance(c, _NamedColumnExpr) and c.wildcard:
                from .expressions import col as _col

                cols.extend(_col(n) for n in schema.names if n not in explicit)
            else:
                cols.append(c)
        return SelectColumns(*cols, arg_distinct=self._distinct)

    def infer_schema(self, schema: Schema) -> Optional[Schema]:
        """Best-effort output schema; None when any type can't be inferred."""
        sc = self.replace_wildcard(schema)
        fields = []
        for c in sc.all_cols:
            tp = c.infer_type(schema)
            if tp is None or c.output_name == "":
                return None
            fields.append(pa.field(c.output_name, tp))
        return Schema(fields)

    def __uuid__(self) -> str:
        return to_uuid(self._distinct, [c.__uuid__() for c in self._cols])


class SQLExpressionGenerator:
    """Render the expression IR to SQL text.

    Reference: ``fugue/column/sql.py:233``. ``enable_cast`` controls whether
    ``cast`` nodes render as SQL CAST (engines that post-cast set it False).
    """

    def __init__(self, enable_cast: bool = True):
        self._enable_cast = enable_cast
        self._func_handlers: Dict[str, Callable[[_FuncExpr], str]] = {}

    def add_func_handler(
        self, name: str, handler: Callable[["_FuncExpr"], str]
    ) -> "SQLExpressionGenerator":
        self._func_handlers[name.upper()] = handler
        return self

    def type_to_sql_type(self, tp: pa.DataType) -> str:
        if pa.types.is_int8(tp):
            return "TINYINT"
        if pa.types.is_int16(tp):
            return "SMALLINT"
        if pa.types.is_int32(tp):
            return "INT"
        if pa.types.is_integer(tp):
            return "BIGINT"
        if pa.types.is_float32(tp):
            return "FLOAT"
        if pa.types.is_floating(tp):
            return "DOUBLE"
        if pa.types.is_boolean(tp):
            return "BOOLEAN"
        if pa.types.is_string(tp):
            return "VARCHAR"
        if pa.types.is_binary(tp):
            return "BINARY"
        if pa.types.is_date(tp):
            return "DATE"
        if pa.types.is_timestamp(tp):
            return "TIMESTAMP"
        raise NotImplementedError(f"can't convert {tp} to SQL type")

    def generate(self, expr: ColumnExpr) -> str:
        body = self._gen(expr)
        if self._enable_cast and expr.as_type is not None:
            body = f"CAST({body} AS {self.type_to_sql_type(expr.as_type)})"
        if expr.as_name != "":
            return f"{body} AS {expr.as_name}"
        return body

    def generate_no_alias(self, expr: ColumnExpr) -> str:
        body = self._gen(expr)
        if self._enable_cast and expr.as_type is not None:
            body = f"CAST({body} AS {self.type_to_sql_type(expr.as_type)})"
        return body

    def _gen(self, expr: ColumnExpr) -> str:
        if isinstance(expr, _NamedColumnExpr):
            return expr.name
        if isinstance(expr, _LitColumnExpr):
            v = expr.value
            if v is None:
                return "NULL"
            if isinstance(v, bool):
                return "TRUE" if v else "FALSE"
            if isinstance(v, str):
                escaped = v.replace("'", "''")
                return f"'{escaped}'"
            return repr(v)
        if isinstance(expr, _UnaryOpExpr):
            inner = self._wrap(expr.col)
            if expr.op == "IS_NULL":
                return f"{inner} IS NULL"
            if expr.op == "NOT_NULL":
                return f"{inner} IS NOT NULL"
            if expr.op == "~":
                return f"NOT {inner}"
            if expr.op == "-":
                return f"-{inner}"
            raise NotImplementedError(f"unary op {expr.op}")
        if isinstance(expr, _BinaryOpExpr):
            op_map = {"&": "AND", "|": "OR", "==": "=", "!=": "<>"}
            op = op_map.get(expr.op, expr.op)
            return f"{self._wrap(expr.left)} {op} {self._wrap(expr.right)}"
        if isinstance(expr, _FuncExpr):
            h = self._func_handlers.get(expr.func.upper())
            if h is not None:
                return h(expr)
            d = "DISTINCT " if expr.is_distinct else ""
            args = ",".join(self._gen_with_cast(a) for a in expr.args)
            return f"{expr.func}({d}{args})"
        raise NotImplementedError(f"can't generate SQL for {type(expr)}")

    def _gen_with_cast(self, expr: ColumnExpr) -> str:
        body = self._gen(expr)
        if self._enable_cast and expr.as_type is not None:
            body = f"CAST({body} AS {self.type_to_sql_type(expr.as_type)})"
        return body

    def _wrap(self, expr: ColumnExpr) -> str:
        s = self._gen_with_cast(expr)
        if isinstance(expr, (_BinaryOpExpr,)):
            return f"({s})"
        return s

    def where(self, condition: ColumnExpr, table: str) -> str:
        assert_or_throw(
            not is_agg(condition),
            FugueSQLError("where condition can't contain aggregation"),
        )
        return f"SELECT * FROM {table} WHERE {self.generate_no_alias(condition)}"

    def select(
        self,
        columns: SelectColumns,
        table: str,
        where: Optional[ColumnExpr] = None,
        having: Optional[ColumnExpr] = None,
    ) -> str:
        columns.assert_all_with_names()
        distinct = "DISTINCT " if columns.is_distinct else ""
        proj = ", ".join(self.generate(c) for c in columns.all_cols)
        sql = f"SELECT {distinct}{proj} FROM {table}"
        if where is not None:
            sql += f" WHERE {self.generate_no_alias(where)}"
        if columns.has_agg and len(columns.group_keys) > 0:
            keys = ", ".join(self.generate_no_alias(k) for k in columns.group_keys)
            sql += f" GROUP BY {keys}"
        if having is not None:
            assert_or_throw(
                columns.has_agg, FugueSQLError("having requires aggregation")
            )
            sql += f" HAVING {self.generate_no_alias(having)}"
        return sql

    def correct_select_schema(
        self, input_schema: Schema, select: SelectColumns, output_schema: Schema
    ) -> Optional[Schema]:
        """Compute the cast-diff between what SQL produced and what the
        expressions declare; None when nothing to correct."""
        expected = select.replace_wildcard(input_schema).infer_schema(input_schema)
        if expected is None:
            return None
        diff = [
            f for f in expected.fields
            if f.name in output_schema and output_schema[f.name].type != f.type
        ]
        return Schema(diff) if len(diff) > 0 else None
