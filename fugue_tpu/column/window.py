"""Window-function evaluation on pandas.

Supports ``ROW_NUMBER/RANK/DENSE_RANK/LAG/LEAD`` and windowed aggregates
(``SUM/AVG/MIN/MAX/COUNT/FIRST/LAST``) over ``PARTITION BY`` groups with
``ORDER BY`` — output order preserves the input row order (SQL semantics).

Aggregates WITH an ORDER BY are running aggregates over a ROWS frame
(``ROWS UNBOUNDED PRECEDING .. CURRENT ROW``); without ORDER BY they cover
the whole partition. NULL order keys rank last.
"""

from typing import Any, List

import numpy as np
import pandas as pd

from ..exceptions import FugueSQLSyntaxError
from .expressions import _WindowExpr

_WINDOW_AGGS = {"SUM": "sum", "AVG": "mean", "MIN": "min", "MAX": "max",
                "COUNT": "count", "FIRST": "first", "LAST": "last"}


def eval_window(pdf: pd.DataFrame, expr: _WindowExpr) -> pd.Series:
    from .eval import evaluate

    work = pdf.reset_index(drop=True)
    order_names = [n for n, _ in expr.order_by]
    asc = [a for _, a in expr.order_by]
    if len(order_names) > 0:
        ordered = work.sort_values(order_names, ascending=asc, kind="stable")
    else:
        ordered = work
    if len(expr.partition_by) > 0:
        grouped = ordered.groupby(expr.partition_by, dropna=False, sort=False)
    else:
        grouped = None
    func = expr.func

    def _arg_series(frame: pd.DataFrame) -> pd.Series:
        v = evaluate(frame, expr.args[0])
        if not isinstance(v, pd.Series):
            v = pd.Series([v] * len(frame), index=frame.index)
        return v

    if func == "ROW_NUMBER":
        res = (
            grouped.cumcount() + 1
            if grouped is not None
            else pd.Series(np.arange(1, len(ordered) + 1), index=ordered.index)
        )
    elif func in ("RANK", "DENSE_RANK"):
        if len(order_names) == 0:
            raise FugueSQLSyntaxError(f"{func} requires an ORDER BY")
        # composite ranks from the stable-sorted frame: a rank group starts
        # wherever any order column differs from the previous row WITHIN the
        # partition; NULL order keys compare equal to each other
        if len(ordered) == 0:
            return pd.Series([], dtype="int64")
        okeys = ordered[order_names]
        if grouped is not None:
            pkeys = [ordered[c] for c in expr.partition_by]
            prev = okeys.groupby(pkeys, dropna=False).shift()
            pos = grouped.cumcount()
        else:
            prev = okeys.shift()
            pos = pd.Series(np.arange(len(ordered)), index=ordered.index)
        # fillna(False): eq() over nullable extension dtypes yields pd.NA
        # for value-vs-NULL comparisons, and NA would pass .all() as True
        equal_prev = (
            okeys.eq(prev).fillna(False).astype(bool)
            | (okeys.isna() & prev.isna())
        ).all(axis=1)
        changed = ~equal_prev | (pos == 0)
        if func == "DENSE_RANK":
            res = (
                changed.groupby(pkeys, dropna=False).cumsum()
                if grouped is not None
                else changed.cumsum()
            )
        else:
            start_pos = pos.where(changed)
            res = (
                start_pos.groupby(pkeys, dropna=False).ffill()
                if grouped is not None
                else start_pos.ffill()
            ) + 1
        res = res.astype("int64")
    elif func in ("LAG", "LEAD"):
        def _scalar_arg(i: int) -> Any:
            # offset/default may be literals or constant expressions (-1.0)
            v = evaluate(ordered.head(1), expr.args[i])
            return v.iloc[0] if isinstance(v, pd.Series) else v

        offset = int(_scalar_arg(1)) if len(expr.args) > 1 else 1
        default = _scalar_arg(2) if len(expr.args) > 2 else None
        shift = offset if func == "LAG" else -offset
        v = _arg_series(ordered)
        # mark in-partition positions so the default only fills positions
        # whose offset falls OUTSIDE the partition (genuine NULLs pass through)
        marker = pd.Series(True, index=ordered.index)
        if grouped is not None:
            keys = [ordered[c] for c in expr.partition_by]
            res = v.groupby(keys, dropna=False).shift(shift)
            inpart = marker.groupby(keys, dropna=False).shift(shift)
        else:
            res = v.shift(shift)
            inpart = marker.shift(shift)
        if default is not None:
            res = res.where(inpart.notna(), default)
    elif func in _WINDOW_AGGS:
        v = _arg_series(ordered)
        keys = (
            [ordered[c] for c in expr.partition_by] if grouped is not None else None
        )
        frame = getattr(expr, "frame", None)
        if len(order_names) > 0:
            # SQL default frame with ORDER BY: RANGE UNBOUNDED PRECEDING ..
            # CURRENT ROW (peer rows share the running value)
            if frame is None:
                frame = ("range", "unb_prec", "current")
            kind, start, end = frame
            if start == "unb_prec" and end == "unb_foll":
                res = _whole_partition_agg(v, keys, func, ordered)
            elif kind == "rows" and start == "unb_prec" and end == "current":
                res = _running_agg(v, keys, func)
            elif kind == "range" and start == "unb_prec" and end == "current":
                run = _running_agg(v, keys, func)
                # broadcast each peer group's LAST running value (positional)
                pk = (keys or []) + [ordered[c] for c in order_names]
                res = run.groupby(pk, dropna=False).transform(
                    lambda x: x.iloc[-1]
                )
            else:
                res = _bounded_frame_agg(
                    ordered, v, keys, order_names, asc, func, frame
                )
        elif keys is not None:
            if func == "FIRST":
                res = v.groupby(keys, dropna=False).transform(lambda x: x.iloc[0])
            elif func == "LAST":
                res = v.groupby(keys, dropna=False).transform(lambda x: x.iloc[-1])
            else:
                res = v.groupby(keys, dropna=False).transform(_WINDOW_AGGS[func])
        else:
            if func == "FIRST":
                agg = v.iloc[0] if len(v) > 0 else None
            elif func == "LAST":
                agg = v.iloc[-1] if len(v) > 0 else None
            elif func == "COUNT":
                agg = v.notna().sum()
            else:
                agg = getattr(v, _WINDOW_AGGS[func])()
            res = pd.Series([agg] * len(ordered), index=ordered.index)
    else:
        raise FugueSQLSyntaxError(f"unsupported window function {func}")
    # restore the original row order
    return res.reindex(work.index)


def _whole_partition_agg(
    v: pd.Series, keys: Any, func: str, ordered: pd.DataFrame
) -> pd.Series:
    """UNBOUNDED PRECEDING .. UNBOUNDED FOLLOWING — the whole partition."""
    if keys is not None:
        g = v.groupby(keys, dropna=False)
        if func == "FIRST":
            return g.transform(lambda x: x.iloc[0])
        if func == "LAST":
            return g.transform(lambda x: x.iloc[-1])
        return g.transform(_WINDOW_AGGS[func])
    if func == "FIRST":
        agg = v.iloc[0] if len(v) > 0 else None
    elif func == "LAST":
        agg = v.iloc[-1] if len(v) > 0 else None
    elif func == "COUNT":
        agg = v.notna().sum()
    else:
        agg = getattr(v, _WINDOW_AGGS[func])()
    return pd.Series([agg] * len(v), index=v.index)


def _bound_offsets(start: Any, end: Any) -> Any:
    """Normalize bounds to (lo_off, hi_off) where None = unbounded; offsets
    are signed relative positions/values (preceding negative). The parser
    rejects UNBOUNDED FOLLOWING starts / UNBOUNDED PRECEDING ends."""

    def off(b: Any) -> Any:
        if b in ("unb_prec", "unb_foll"):
            return None
        if b == "current":
            return 0
        tag, n = b
        return -n if tag == "prec" else n

    return off(start), off(end)


def _bounded_frame_agg(
    ordered: pd.DataFrame,
    v: pd.Series,
    keys: Any,
    order_names: List[str],
    asc: List[bool],
    func: str,
    frame: Any,
) -> pd.Series:
    """Explicit ROWS/RANGE frames with numeric bounds.

    ROWS offsets are row positions; RANGE offsets are order-key value
    distances (single numeric ORDER BY key required). Per partition the
    window [lo, hi) per row comes from positions / ``searchsorted`` over
    the ordered keys; aggregates skip NULLs (SQL semantics).
    """
    if func in ("FIRST", "LAST"):
        raise FugueSQLSyntaxError(
            f"{func} does not support explicit window frames"
        )
    kind, start, end = frame
    lo_off, hi_off = _bound_offsets(start, end)
    if start == "unb_prec":
        lo_off = None
    range_offsets = kind == "range" and (
        lo_off not in (None, 0) or hi_off not in (None, 0)
    )
    if range_offsets and len(order_names) != 1:
        raise FugueSQLSyntaxError(
            "RANGE with offsets requires exactly one ORDER BY key"
        )

    out = np.full(len(v), np.nan, dtype=np.float64)
    vals = v.to_numpy(dtype=np.float64, na_value=np.nan)
    okeys = ordered[order_names] if kind == "range" else None
    if keys is not None:
        # positional locations per partition, in sorted (frame) order
        group_iter = [
            np.sort(np.asarray(g))
            for g in ordered.groupby(
                [k for k in keys], dropna=False, sort=False
            ).indices.values()
        ]
    else:
        group_iter = [np.arange(len(ordered))]
    for gpos in group_iter:
        n = len(gpos)
        if n == 0:  # empty frame (keys=None path): nothing to window
            continue
        gv = vals[gpos]
        if kind == "rows":
            lo = (
                np.zeros(n, dtype=np.int64)
                if lo_off is None
                else np.clip(np.arange(n) + lo_off, 0, n)
            )
            hi = (
                np.full(n, n, dtype=np.int64)
                if hi_off is None
                else np.clip(np.arange(n) + hi_off + 1, 0, n)
            )
        elif range_offsets:
            okey = ordered[order_names[0]].to_numpy(dtype=np.float64)[gpos]
            sign = 1.0 if asc[0] else -1.0
            k = sign * okey  # ascending view
            lo = (
                np.zeros(n, dtype=np.int64)
                if lo_off is None
                else np.searchsorted(k, k + lo_off, side="left")
            )
            hi = (
                np.full(n, n, dtype=np.int64)
                if hi_off is None
                else np.searchsorted(k, k + hi_off, side="right")
            )
        else:
            # RANGE with CURRENT ROW bounds: peer-group (tied order keys)
            # boundaries computed WITHIN the partition — the global sort
            # interleaves partitions, so row-to-previous-row comparison
            # there would merge peers whose global neighbors happen to tie.
            # fillna(False): eq() over nullable extension dtypes yields
            # pd.NA for value-vs-NULL comparisons, and NA would pass
            # .all() as True
            gk = okeys.iloc[gpos]
            eq_prev = (
                gk.eq(gk.shift()).fillna(False).astype(bool)
                | (gk.isna() & gk.shift().isna())
            ).all(axis=1)
            changed = (~eq_prev).to_numpy().copy()
            changed[0] = True
            gid = np.cumsum(changed) - 1
            starts = np.flatnonzero(changed)
            ends = np.append(starts[1:], n)
            lo = (
                np.zeros(n, dtype=np.int64)
                if lo_off is None
                else starts[gid]  # CURRENT ROW → first peer
            )
            hi = (
                np.full(n, n, dtype=np.int64)
                if hi_off is None
                else ends[gid]  # CURRENT ROW → last peer
            )
        for i in range(n):
            w = gv[lo[i] : hi[i]]
            w = w[~np.isnan(w)]
            if func == "COUNT":
                out[gpos[i]] = len(w)
            elif len(w) == 0:
                out[gpos[i]] = np.nan
            elif func == "SUM":
                out[gpos[i]] = w.sum()
            elif func == "AVG":
                out[gpos[i]] = w.mean()
            elif func == "MIN":
                out[gpos[i]] = w.min()
            elif func == "MAX":
                out[gpos[i]] = w.max()
            else:  # pragma: no cover
                raise FugueSQLSyntaxError(f"unsupported frame aggregate {func}")
    res = pd.Series(out, index=ordered.index)  # positional over `ordered`
    if func == "COUNT":
        res = res.fillna(0).astype("int64")
    return res


def _running_agg(v: pd.Series, keys: Any, func: str) -> pd.Series:
    """SQL aggregates skip NULLs: cumulative ops run over null-filled values
    and positions with zero preceding non-null rows stay NULL."""

    def _grp(s: pd.Series) -> Any:
        return s.groupby(keys, dropna=False) if keys is not None else s

    nn = v.notna()
    n = _grp(nn).cumsum() if keys is not None else nn.cumsum()
    if func == "COUNT":
        return n.astype("int64")
    if func in ("SUM", "MIN", "MAX", "AVG"):
        attr = {"SUM": "cumsum", "AVG": "cumsum", "MIN": "cummin", "MAX": "cummax"}[func]
        cs = getattr(_grp(v), attr)() if keys is not None else getattr(v, attr)()
        # pandas cum* skip NaN but leave NaN AT null positions; SQL carries
        # the previous running value — ffill (dtype-preserving, works for
        # datetimes too) and mask positions with zero preceding non-nulls
        cs = cs.groupby(keys, dropna=False).ffill() if keys is not None else cs.ffill()
        res = cs / n if func == "AVG" else cs
        return res.where(n > 0)
    if func == "FIRST":
        # FIRST_VALUE = the first ROW's value, nulls included
        if keys is not None:
            return v.groupby(keys, dropna=False).transform(lambda x: x.iloc[0])
        return pd.Series([v.iloc[0]] * len(v), index=v.index)
    if func == "LAST":  # running last = the current row's value
        return v
    raise FugueSQLSyntaxError(f"unsupported running window aggregate {func}")
