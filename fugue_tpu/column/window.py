"""Window-function evaluation on pandas.

Supports ``ROW_NUMBER/RANK/DENSE_RANK/LAG/LEAD`` and windowed aggregates
(``SUM/AVG/MIN/MAX/COUNT/FIRST/LAST``) over ``PARTITION BY`` groups with
``ORDER BY`` — output order preserves the input row order (SQL semantics).

Aggregates WITH an ORDER BY are running aggregates over a ROWS frame
(``ROWS UNBOUNDED PRECEDING .. CURRENT ROW``); without ORDER BY they cover
the whole partition. NULL order keys rank last.
"""

from typing import Any, List

import numpy as np
import pandas as pd

from ..exceptions import FugueSQLSyntaxError
from .expressions import _NamedColumnExpr, _WindowExpr

_WINDOW_AGGS = {"SUM": "sum", "AVG": "mean", "MIN": "min", "MAX": "max",
                "COUNT": "count", "FIRST": "first", "LAST": "last"}


def eval_window(pdf: pd.DataFrame, expr: _WindowExpr) -> pd.Series:
    from .eval import evaluate

    work = pdf.reset_index(drop=True)
    order_names = [n for n, _ in expr.order_by]
    asc = [a for _, a in expr.order_by]
    if len(order_names) > 0:
        ordered = work.sort_values(order_names, ascending=asc, kind="stable")
    else:
        ordered = work
    if len(expr.partition_by) > 0:
        grouped = ordered.groupby(expr.partition_by, dropna=False, sort=False)
    else:
        grouped = None
    func = expr.func

    def _arg_series(frame: pd.DataFrame) -> pd.Series:
        v = evaluate(frame, expr.args[0])
        if not isinstance(v, pd.Series):
            v = pd.Series([v] * len(frame), index=frame.index)
        return v

    if func == "ROW_NUMBER":
        res = (
            grouped.cumcount() + 1
            if grouped is not None
            else pd.Series(np.arange(1, len(ordered) + 1), index=ordered.index)
        )
    elif func in ("RANK", "DENSE_RANK"):
        if len(order_names) != 1:
            raise FugueSQLSyntaxError(
                f"{func} requires exactly one ORDER BY column"
            )
        method = "min" if func == "RANK" else "dense"
        col = ordered[order_names[0]]
        if grouped is not None:
            res = col.groupby(
                [ordered[c] for c in expr.partition_by], dropna=False
            ).rank(method=method, ascending=asc[0], na_option="bottom")
        else:
            res = col.rank(method=method, ascending=asc[0], na_option="bottom")
        res = res.astype("int64")
    elif func in ("LAG", "LEAD"):
        def _scalar_arg(i: int) -> Any:
            # offset/default may be literals or constant expressions (-1.0)
            v = evaluate(ordered.head(1), expr.args[i])
            return v.iloc[0] if isinstance(v, pd.Series) else v

        offset = int(_scalar_arg(1)) if len(expr.args) > 1 else 1
        default = _scalar_arg(2) if len(expr.args) > 2 else None
        shift = offset if func == "LAG" else -offset
        v = _arg_series(ordered)
        # mark in-partition positions so the default only fills positions
        # whose offset falls OUTSIDE the partition (genuine NULLs pass through)
        marker = pd.Series(True, index=ordered.index)
        if grouped is not None:
            keys = [ordered[c] for c in expr.partition_by]
            res = v.groupby(keys, dropna=False).shift(shift)
            inpart = marker.groupby(keys, dropna=False).shift(shift)
        else:
            res = v.shift(shift)
            inpart = marker.shift(shift)
        if default is not None:
            res = res.where(inpart.notna(), default)
    elif func in _WINDOW_AGGS:
        v = _arg_series(ordered)
        keys = (
            [ordered[c] for c in expr.partition_by] if grouped is not None else None
        )
        if len(order_names) > 0:
            # running aggregate over a ROWS frame up to the current row
            res = _running_agg(v, keys, func)
        elif keys is not None:
            res = v.groupby(keys, dropna=False).transform(_WINDOW_AGGS[func])
        else:
            agg = getattr(v, _WINDOW_AGGS[func])() if func != "COUNT" else v.notna().sum()
            res = pd.Series([agg] * len(ordered), index=ordered.index)
    else:
        raise FugueSQLSyntaxError(f"unsupported window function {func}")
    # restore the original row order
    return res.reindex(work.index)


def _running_agg(v: pd.Series, keys: Any, func: str) -> pd.Series:
    g = v.groupby(keys, dropna=False) if keys is not None else None

    def _cum(attr: str) -> pd.Series:
        return getattr(g, attr)() if g is not None else getattr(v, attr)()

    if func == "SUM":
        return _cum("cumsum")
    if func == "MIN":
        return _cum("cummin")
    if func == "MAX":
        return _cum("cummax")
    if func == "COUNT":
        nn = v.notna()
        return (
            nn.groupby(keys, dropna=False).cumsum() if keys is not None else nn.cumsum()
        ).astype("int64")
    if func == "AVG":
        s = _cum("cumsum")
        nn = v.notna()
        n = (
            nn.groupby(keys, dropna=False).cumsum() if keys is not None else nn.cumsum()
        )
        return s / n
    if func == "FIRST":
        return g.transform("first") if g is not None else pd.Series(
            [v.iloc[0]] * len(v), index=v.index
        )
    if func == "LAST":  # running last = the current row's value
        return v
    raise FugueSQLSyntaxError(f"unsupported running window aggregate {func}")
