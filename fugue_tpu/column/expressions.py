"""Column expression IR.

Parity with the reference (`fugue/column/expressions.py:8`): ``col``/``lit``/
``null``/``all_cols``/``function`` build an expression tree with operators,
``alias`` and ``cast``. Redesigned as a backend-neutral IR: the same tree is
evaluated by pandas (native engine), compiled to jax.numpy (TPU engine), or
rendered to SQL text (`fugue_tpu/column/sql.py`) — the reference only renders
SQL.
"""

from typing import Any, Iterable, List, Optional, Union

import pyarrow as pa

from .._utils.assertion import assert_or_throw
from .._utils.hash import to_uuid
from ..schema import Schema, to_pa_datatype


class ColumnExpr:
    """Base of the expression tree."""

    def __init__(self):
        self._as_name = ""
        self._as_type: Optional[pa.DataType] = None

    @property
    def name(self) -> str:
        return ""

    @property
    def as_name(self) -> str:
        return self._as_name

    @property
    def as_type(self) -> Optional[pa.DataType]:
        return self._as_type

    @property
    def output_name(self) -> str:
        return self._as_name if self._as_name != "" else self.name

    def alias(self, as_name: str) -> "ColumnExpr":
        res = self._copy()
        res._as_name = as_name
        res._as_type = self._as_type
        return res

    def cast(self, data_type: Any) -> "ColumnExpr":
        res = self._copy()
        res._as_name = self._as_name
        res._as_type = None if data_type is None else to_pa_datatype(data_type)
        return res

    def infer_type(self, schema: Schema) -> Optional[pa.DataType]:
        return self._as_type

    def infer_alias(self) -> "ColumnExpr":
        return self

    @property
    def children(self) -> List["ColumnExpr"]:
        return []

    def _copy(self) -> "ColumnExpr":
        import copy

        return copy.copy(self)

    # -- operators ---------------------------------------------------------
    def __add__(self, other: Any) -> "ColumnExpr":
        return _BinaryOpExpr("+", self, other)

    def __radd__(self, other: Any) -> "ColumnExpr":
        return _BinaryOpExpr("+", other, self)

    def __sub__(self, other: Any) -> "ColumnExpr":
        return _BinaryOpExpr("-", self, other)

    def __rsub__(self, other: Any) -> "ColumnExpr":
        return _BinaryOpExpr("-", other, self)

    def __mul__(self, other: Any) -> "ColumnExpr":
        return _BinaryOpExpr("*", self, other)

    def __rmul__(self, other: Any) -> "ColumnExpr":
        return _BinaryOpExpr("*", other, self)

    def __truediv__(self, other: Any) -> "ColumnExpr":
        return _BinaryOpExpr("/", self, other)

    def __rtruediv__(self, other: Any) -> "ColumnExpr":
        return _BinaryOpExpr("/", other, self)

    def __lt__(self, other: Any) -> "ColumnExpr":
        return _BinaryOpExpr("<", self, other)

    def __le__(self, other: Any) -> "ColumnExpr":
        return _BinaryOpExpr("<=", self, other)

    def __gt__(self, other: Any) -> "ColumnExpr":
        return _BinaryOpExpr(">", self, other)

    def __ge__(self, other: Any) -> "ColumnExpr":
        return _BinaryOpExpr(">=", self, other)

    def __eq__(self, other: Any) -> "ColumnExpr":  # type: ignore
        return _BinaryOpExpr("==", self, other)

    def __ne__(self, other: Any) -> "ColumnExpr":  # type: ignore
        return _BinaryOpExpr("!=", self, other)

    def __and__(self, other: Any) -> "ColumnExpr":
        return _BinaryOpExpr("&", self, other)

    def __rand__(self, other: Any) -> "ColumnExpr":
        return _BinaryOpExpr("&", other, self)

    def __or__(self, other: Any) -> "ColumnExpr":
        return _BinaryOpExpr("|", self, other)

    def __ror__(self, other: Any) -> "ColumnExpr":
        return _BinaryOpExpr("|", other, self)

    def __invert__(self) -> "ColumnExpr":
        return _UnaryOpExpr("~", self)

    def __neg__(self) -> "ColumnExpr":
        return _UnaryOpExpr("-", self)

    def is_null(self) -> "ColumnExpr":
        return _UnaryOpExpr("IS_NULL", self)

    def not_null(self) -> "ColumnExpr":
        return _UnaryOpExpr("NOT_NULL", self)

    def __uuid__(self) -> str:
        return to_uuid(
            type(self).__name__,
            self._as_name,
            str(self._as_type),
            self._uuid_keys(),
            [c.__uuid__() for c in self.children],
        )

    def _uuid_keys(self) -> List[Any]:
        return []

    def __hash__(self) -> int:
        return hash(self.__uuid__())

    def __bool__(self) -> bool:
        raise TypeError("ColumnExpr has no truth value; use & | ~ for logic")


def _to_col(obj: Any) -> ColumnExpr:
    if isinstance(obj, ColumnExpr):
        return obj
    return lit(obj)


class _NamedColumnExpr(ColumnExpr):
    def __init__(self, name: str):
        super().__init__()
        self._name = name

    @property
    def name(self) -> str:
        return self._name

    @property
    def wildcard(self) -> bool:
        return self._name == "*"

    def infer_type(self, schema: Schema) -> Optional[pa.DataType]:
        if self.as_type is not None:
            return self.as_type
        if self.wildcard:
            return None
        return schema[self._name].type if self._name in schema else None

    def __repr__(self) -> str:
        return self._name if self.as_name == "" else f"{self._name} AS {self.as_name}"

    def _uuid_keys(self) -> List[Any]:
        return [self._name]


class _LitColumnExpr(ColumnExpr):
    def __init__(self, value: Any):
        import datetime as _dt

        super().__init__()
        assert_or_throw(
            value is None
            or isinstance(
                value, (int, float, bool, str, bytes, _dt.date, _dt.datetime)
            ),
            lambda: NotImplementedError(f"unsupported literal {value!r}"),
        )
        self._value = value

    @property
    def value(self) -> Any:
        return self._value

    def infer_type(self, schema: Schema) -> Optional[pa.DataType]:
        import datetime as _dt

        if self.as_type is not None:
            return self.as_type
        if self._value is None:
            return None
        if isinstance(self._value, bool):
            return pa.bool_()
        if isinstance(self._value, int):
            return pa.int64()
        if isinstance(self._value, float):
            return pa.float64()
        if isinstance(self._value, str):
            return pa.string()
        if isinstance(self._value, _dt.datetime):
            return pa.timestamp("us")
        if isinstance(self._value, _dt.date):
            return pa.date32()
        return pa.binary()

    def __repr__(self) -> str:
        v = f"'{self._value}'" if isinstance(self._value, str) else repr(self._value)
        return v if self.as_name == "" else f"{v} AS {self.as_name}"

    def _uuid_keys(self) -> List[Any]:
        return [repr(self._value)]


class _UnaryOpExpr(ColumnExpr):
    def __init__(self, op: str, expr: ColumnExpr):
        super().__init__()
        self._op = op
        self._expr = _to_col(expr)

    @property
    def op(self) -> str:
        return self._op

    @property
    def col(self) -> ColumnExpr:
        return self._expr

    @property
    def name(self) -> str:
        return self._expr.name

    @property
    def children(self) -> List[ColumnExpr]:
        return [self._expr]

    def infer_type(self, schema: Schema) -> Optional[pa.DataType]:
        if self.as_type is not None:
            return self.as_type
        if self._op in ("IS_NULL", "NOT_NULL", "~"):
            return pa.bool_()
        if self._op == "-":
            return self._expr.infer_type(schema)
        return None

    def __repr__(self) -> str:
        s = f"{self._op}({self._expr!r})"
        return s if self.as_name == "" else f"{s} AS {self.as_name}"

    def _uuid_keys(self) -> List[Any]:
        return [self._op]


class _BinaryOpExpr(ColumnExpr):
    def __init__(self, op: str, left: Any, right: Any):
        super().__init__()
        self._op = op
        self._left = _to_col(left)
        self._right = _to_col(right)

    @property
    def op(self) -> str:
        return self._op

    @property
    def left(self) -> ColumnExpr:
        return self._left

    @property
    def right(self) -> ColumnExpr:
        return self._right

    @property
    def children(self) -> List[ColumnExpr]:
        return [self._left, self._right]

    def infer_type(self, schema: Schema) -> Optional[pa.DataType]:
        if self.as_type is not None:
            return self.as_type
        if self._op in ("<", "<=", ">", ">=", "==", "!=", "&", "|"):
            return pa.bool_()
        lt = self._left.infer_type(schema)
        rt = self._right.infer_type(schema)
        if lt is None or rt is None:
            return None
        if self._op == "/":
            return pa.float64()
        if lt == rt:
            return lt
        if pa.types.is_floating(lt) or pa.types.is_floating(rt):
            return pa.float64()
        if pa.types.is_integer(lt) and pa.types.is_integer(rt):
            return pa.int64()
        return None

    def __repr__(self) -> str:
        s = f"({self._left!r} {self._op} {self._right!r})"
        return s if self.as_name == "" else f"{s} AS {self.as_name}"

    def _uuid_keys(self) -> List[Any]:
        return [self._op]


class _FuncExpr(ColumnExpr):
    def __init__(
        self,
        func: str,
        *args: Any,
        arg_distinct: bool = False,
        is_agg: bool = False,
    ):
        super().__init__()
        self._func = func
        self._args = [_to_col(a) for a in args]
        self._is_distinct = arg_distinct
        self._is_agg = is_agg

    @property
    def func(self) -> str:
        return self._func

    @property
    def is_distinct(self) -> bool:
        return self._is_distinct

    @property
    def is_agg(self) -> bool:
        return self._is_agg

    @property
    def args(self) -> List[ColumnExpr]:
        return self._args

    @property
    def children(self) -> List[ColumnExpr]:
        return self._args

    def infer_alias(self) -> ColumnExpr:
        # agg functions over a single named column default to that name
        if self.as_name == "" and len(self._args) == 1 and self._args[0].name != "":
            return self.alias(self._args[0].name)
        return self

    def __repr__(self) -> str:
        inner = ",".join(repr(a) for a in self._args)
        d = "DISTINCT " if self._is_distinct else ""
        s = f"{self._func}({d}{inner})"
        return s if self.as_name == "" else f"{s} AS {self.as_name}"

    def _uuid_keys(self) -> List[Any]:
        return [self._func, self._is_distinct, self._is_agg]


def col(obj: Union[str, ColumnExpr], alias: str = "") -> ColumnExpr:
    """Reference a column by name (``"*"`` is the wildcard)."""
    if isinstance(obj, ColumnExpr):
        return obj.alias(alias) if alias != "" else obj
    res: ColumnExpr = _NamedColumnExpr(obj)
    return res.alias(alias) if alias != "" else res


def lit(obj: Any, alias: str = "") -> ColumnExpr:
    res: ColumnExpr = _LitColumnExpr(obj)
    return res.alias(alias) if alias != "" else res


def null() -> ColumnExpr:
    return lit(None)


def all_cols() -> ColumnExpr:
    return col("*")


def function(name: str, *args: Any, arg_distinct: bool = False, **kwargs: Any) -> ColumnExpr:
    return _FuncExpr(name, *args, arg_distinct=arg_distinct)


class _CaseWhenExpr(ColumnExpr):
    """CASE WHEN c1 THEN v1 [WHEN ...] ELSE d END."""

    def __init__(self, cases: List[Any], default: Any = None):
        super().__init__()
        self._cases = [(_to_col(c), _to_col(v)) for c, v in cases]
        self._default = _to_col(default) if default is not None else lit(None)

    @property
    def cases(self) -> List[Any]:
        return self._cases

    @property
    def default(self) -> ColumnExpr:
        return self._default

    @property
    def children(self) -> List[ColumnExpr]:
        res: List[ColumnExpr] = []
        for c, v in self._cases:
            res.extend([c, v])
        res.append(self._default)
        return res

    def infer_type(self, schema: Schema) -> Optional[pa.DataType]:
        if self.as_type is not None:
            return self.as_type
        return self._cases[0][1].infer_type(schema)

    def __repr__(self) -> str:
        inner = " ".join(f"WHEN {c!r} THEN {v!r}" for c, v in self._cases)
        return f"CASE {inner} ELSE {self._default!r} END"

    def _uuid_keys(self) -> List[Any]:
        return ["case_when"]


class _InExpr(ColumnExpr):
    """expr IN (literals...) (optionally negated)."""

    def __init__(self, expr: Any, values: List[Any], positive: bool = True):
        super().__init__()
        self._expr = _to_col(expr)
        self._values = list(values)
        self._positive = positive

    @property
    def col(self) -> ColumnExpr:
        return self._expr

    @property
    def values(self) -> List[Any]:
        return self._values

    @property
    def positive(self) -> bool:
        return self._positive

    @property
    def children(self) -> List[ColumnExpr]:
        return [self._expr]

    def infer_type(self, schema: Schema) -> Optional[pa.DataType]:
        return self.as_type if self.as_type is not None else pa.bool_()

    def __repr__(self) -> str:
        op = "IN" if self._positive else "NOT IN"
        return f"({self._expr!r} {op} {tuple(self._values)})"

    def _uuid_keys(self) -> List[Any]:
        return ["in", self._positive, repr(self._values)]


class _LikeExpr(ColumnExpr):
    """expr LIKE pattern (SQL % and _ wildcards), optionally negated."""

    def __init__(self, expr: Any, pattern: str, positive: bool = True):
        super().__init__()
        self._expr = _to_col(expr)
        self._pattern = pattern
        self._positive = positive

    @property
    def col(self) -> ColumnExpr:
        return self._expr

    @property
    def pattern(self) -> str:
        return self._pattern

    @property
    def positive(self) -> bool:
        return self._positive

    @property
    def children(self) -> List[ColumnExpr]:
        return [self._expr]

    def infer_type(self, schema: Schema) -> Optional[pa.DataType]:
        return self.as_type if self.as_type is not None else pa.bool_()

    def __repr__(self) -> str:
        op = "LIKE" if self._positive else "NOT LIKE"
        return f"({self._expr!r} {op} {self._pattern!r})"

    def _uuid_keys(self) -> List[Any]:
        return ["like", self._positive, self._pattern]


def case_when(*cases: Any, default: Any = None) -> ColumnExpr:
    """Build CASE WHEN from (condition, value) pairs."""
    return _CaseWhenExpr(list(cases), default=default)


class _WindowExpr(ColumnExpr):
    """``func(args) OVER (PARTITION BY keys ORDER BY sorts)``.

    Not an aggregate: it returns one value per input row.
    """

    def __init__(
        self,
        func: str,
        args: List[Any],
        partition_by: List[str],
        order_by: List[Any],  # (name, ascending) pairs
        frame: Any = None,  # (kind, start, end); None = dialect default
    ):
        super().__init__()
        self._func = func.upper()
        self._args = [_to_col(a) for a in args]
        self._partition_by = list(partition_by)
        self._order_by = list(order_by)
        # frame: kind ∈ {"rows","range"}; bounds are "unb_prec"/"unb_foll"/
        # "current"/("prec", n)/("foll", n)
        self._frame = frame

    @property
    def func(self) -> str:
        return self._func

    @property
    def args(self) -> List[ColumnExpr]:
        return self._args

    @property
    def partition_by(self) -> List[str]:
        return self._partition_by

    @property
    def order_by(self) -> List[Any]:
        return self._order_by

    @property
    def frame(self) -> Any:
        return self._frame

    @property
    def children(self) -> List[ColumnExpr]:
        return list(self._args)

    def infer_type(self, schema: Schema) -> Optional[pa.DataType]:
        if self.as_type is not None:
            return self.as_type
        if self._func in ("ROW_NUMBER", "RANK", "DENSE_RANK", "COUNT"):
            return pa.int64()
        if self._func == "AVG":
            return pa.float64()
        if len(self._args) > 0:
            return self._args[0].infer_type(schema)
        return None

    def __repr__(self) -> str:
        inner = ",".join(repr(a) for a in self._args)
        pb = f" PARTITION BY {self._partition_by}" if self._partition_by else ""
        ob = f" ORDER BY {self._order_by}" if self._order_by else ""
        s = f"{self._func}({inner}) OVER ({pb}{ob} )"
        return s if self.as_name == "" else f"{s} AS {self.as_name}"

    def _uuid_keys(self) -> List[Any]:
        return [
            "window",
            self._func,
            self._partition_by,
            repr(self._order_by),
            repr(self._frame),
        ]


def structural_key(e: "ColumnExpr") -> str:
    """Identity of an expression ignoring its output alias (cast KEPT —
    ``CAST(x AS int)`` must not match plain ``x``). The shared matching
    key for GROUP BY / ORDER BY expression materialization."""
    return e.alias("").__uuid__()


def derived_name(e: "ColumnExpr") -> str:
    """The readable derived column name of an unaliased expression (what
    SQL backends display), used to name materialized helper columns.
    Casts render explicitly — ``repr`` omits them, and ``CAST(x AS int)``
    must not collide with plain ``x``."""
    bare = e.alias("")
    if bare.as_type is not None:
        return f"CAST({repr(bare.cast(None))} AS {bare.as_type})"
    return repr(bare)
