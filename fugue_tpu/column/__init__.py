from .expressions import ColumnExpr, all_cols, col, function, lit, null
from .sql import SelectColumns, SQLExpressionGenerator
from . import functions

__all__ = [
    "ColumnExpr",
    "all_cols",
    "col",
    "function",
    "lit",
    "null",
    "SelectColumns",
    "SQLExpressionGenerator",
    "functions",
]
