"""Built-in column functions (aggregations and scalars).

Parity with the reference (`fugue/column/functions.py`).
"""

from typing import Any, Optional

import pyarrow as pa

from ..schema import Schema
from .expressions import ColumnExpr, _FuncExpr, _to_col, function


def coalesce(*args: Any) -> ColumnExpr:
    return function("COALESCE", *[_to_col(a) for a in args])


def min(col: ColumnExpr) -> ColumnExpr:  # noqa: A001
    return _SameTypeUnaryAggFuncExpr("MIN", col)


def max(col: ColumnExpr) -> ColumnExpr:  # noqa: A001
    return _SameTypeUnaryAggFuncExpr("MAX", col)


def count(col: ColumnExpr) -> ColumnExpr:
    return _UnaryAggFuncExpr("COUNT", col)


def count_distinct(col: ColumnExpr) -> ColumnExpr:
    return _UnaryAggFuncExpr("COUNT", col, arg_distinct=True)


def avg(col: ColumnExpr) -> ColumnExpr:
    return _UnaryAggFuncExpr("AVG", col)


def mean(col: ColumnExpr) -> ColumnExpr:
    return avg(col)


def sum(col: ColumnExpr) -> ColumnExpr:  # noqa: A001
    return _UnaryAggFuncExpr("SUM", col)


def first(col: ColumnExpr) -> ColumnExpr:
    return _SameTypeUnaryAggFuncExpr("FIRST", col)


def last(col: ColumnExpr) -> ColumnExpr:
    return _SameTypeUnaryAggFuncExpr("LAST", col)


def is_agg(column: Any) -> bool:
    """Whether the expression tree contains an aggregation
    (reference ``fugue/column/functions.py:314``)."""
    if isinstance(column, _FuncExpr):
        if column.is_agg:
            return True
    if isinstance(column, ColumnExpr):
        return any(is_agg(c) for c in column.children)
    return False


class _UnaryAggFuncExpr(_FuncExpr):
    def __init__(self, func: str, col: Any, arg_distinct: bool = False):
        super().__init__(func, _to_col(col), arg_distinct=arg_distinct, is_agg=True)

    def infer_type(self, schema: Schema) -> Optional[pa.DataType]:
        if self.as_type is not None:
            return self.as_type
        f = self.func.upper()
        if f == "COUNT":
            return pa.int64()
        if f == "AVG":
            return pa.float64()
        if f == "SUM":
            t = self.args[0].infer_type(schema)
            if t is None:
                return None
            if pa.types.is_integer(t):
                return pa.int64()
            if pa.types.is_floating(t):
                return pa.float64()
            return t
        return None


class _SameTypeUnaryAggFuncExpr(_UnaryAggFuncExpr):
    def infer_type(self, schema: Schema) -> Optional[pa.DataType]:
        if self.as_type is not None:
            return self.as_type
        return self.args[0].infer_type(schema)
