"""Pandas evaluator for the column-expression IR.

This replaces the reference's SQL-generation path for the native engine
(reference derives select/filter/assign/aggregate by generating SQL and
running qpd — ``fugue/execution/execution_engine.py:736-939``). Here the IR
is evaluated directly on pandas; the TPU engine has a parallel jnp evaluator.
"""

from typing import Any, Dict, List, Optional

import numpy as np
import pandas as pd
import pyarrow as pa

from .._utils.assertion import assert_or_throw
from ..exceptions import FugueSQLError
from ..schema import Schema
from .expressions import (
    ColumnExpr,
    _BinaryOpExpr,
    _CaseWhenExpr,
    _FuncExpr,
    _InExpr,
    _LikeExpr,
    _LitColumnExpr,
    _NamedColumnExpr,
    _UnaryOpExpr,
)

# scalar SQL functions on pandas series
_SCALAR_FUNCS = {
    "ABS": lambda s: s.abs(),
    "UPPER": lambda s: s.str.upper(),
    "LOWER": lambda s: s.str.lower(),
    "LENGTH": lambda s: s.str.len().astype("int64"),
    "TRIM": lambda s: s.str.strip(),
    "FLOOR": lambda s: np.floor(s),
    "CEIL": lambda s: np.ceil(s),
    "CEILING": lambda s: np.ceil(s),
    "ROUND": lambda s, *a: s.round(int(a[0]) if a else 0),
    "SQRT": lambda s: np.sqrt(s),
    "EXP": lambda s: np.exp(s),
    "LN": lambda s: np.log(s),
    "LOG": lambda s: np.log(s),
    # SQL MOD: the result sign follows the DIVIDEND (unlike python %)
    "MOD": lambda s, d: np.sign(s) * (abs(s) % abs(d)),
    # SQL POWER returns double (negative int exponents are legal)
    "POWER": lambda s, e: np.power(s.astype("float64"), e),
    "POW": lambda s, e: np.power(s.astype("float64"), e),
    "SIGN": lambda s: np.sign(s),
    "REPLACE": lambda s, old, new: s.str.replace(old, new, regex=False),
    # SQL LPAD/RPAD: multi-char pads allowed; result truncated to width
    "LPAD": lambda s, w, c=" ": s.map(
        lambda x: None if x is None else (str(c) * int(w) + x)[-int(w):]
        if len(x) < int(w) else x[: int(w)]
    ),
    "RPAD": lambda s, w, c=" ": s.map(
        lambda x: None if x is None else (x + str(c) * int(w))[: int(w)]
    ),
    "SUBSTRING": lambda s, start, length=None: s.str.slice(
        int(start) - 1, int(start) - 1 + int(length) if length is not None else None
    ),
    "SUBSTR": lambda s, start, length=None: s.str.slice(
        int(start) - 1, int(start) - 1 + int(length) if length is not None else None
    ),
    "CONCAT": None,  # special-cased (multi-arg)
}
from .sql import SelectColumns


def _cast_series(s: pd.Series, tp: pa.DataType) -> pd.Series:
    arr = pa.Array.from_pandas(s)
    return arr.cast(tp, safe=False).to_pandas()


def evaluate(pdf: pd.DataFrame, expr: ColumnExpr) -> Any:
    """Evaluate a non-aggregate expression to a Series (or scalar literal)."""
    res = _eval(pdf, expr)
    if expr.as_type is not None and isinstance(res, pd.Series):
        res = _cast_series(res, expr.as_type)
    elif expr.as_type is not None:
        res = _cast_series(pd.Series([res]), expr.as_type).iloc[0]
    return res


def _eval(pdf: pd.DataFrame, expr: ColumnExpr) -> Any:
    if isinstance(expr, _NamedColumnExpr):
        return pdf[expr.name]
    if isinstance(expr, _LitColumnExpr):
        return expr.value
    if isinstance(expr, _UnaryOpExpr):
        v = evaluate(pdf, expr.col)
        if expr.op == "IS_NULL":
            return v.isna()
        if expr.op == "NOT_NULL":
            return v.notna()
        if expr.op == "~":
            if isinstance(v, pd.Series) and v.dtype == object:
                return v.map(lambda x: None if x is None else not x)
            return ~v
        if expr.op == "-":
            return -v
        raise NotImplementedError(f"unary op {expr.op}")
    if isinstance(expr, _BinaryOpExpr):
        l = evaluate(pdf, expr.left)
        r = evaluate(pdf, expr.right)
        op = expr.op
        if op == "+":
            return l + r
        if op == "-":
            return l - r
        if op == "*":
            return l * r
        if op == "/":
            return l / r
        if op == "<":
            return l < r
        if op == "<=":
            return l <= r
        if op == ">":
            return l > r
        if op == ">=":
            return l >= r
        if op == "==":
            return l == r
        if op == "!=":
            return l != r
        if op == "&":
            return _as_bool(l) & _as_bool(r)
        if op == "|":
            return _as_bool(l) | _as_bool(r)
        raise NotImplementedError(f"binary op {op}")
    if isinstance(expr, _CaseWhenExpr):
        # positional (numpy) evaluation: input frames from groupby carry
        # non-default indexes, so label alignment would silently misalign
        n = len(pdf)
        result = np.empty(n, dtype=object)
        decided = np.zeros(n, dtype=bool)
        for c, v in expr.cases:
            cond = _as_bool(evaluate(pdf, c))
            cond_np = (
                cond.to_numpy() if isinstance(cond, pd.Series) else np.full(n, bool(cond))
            )
            val = evaluate(pdf, v)
            val_np = val.to_numpy() if isinstance(val, pd.Series) else None
            pick = cond_np & ~decided
            result[pick] = val_np[pick] if val_np is not None else val
            decided |= cond_np
        dval = evaluate(pdf, expr.default)
        dval_np = dval.to_numpy() if isinstance(dval, pd.Series) else None
        result[~decided] = dval_np[~decided] if dval_np is not None else dval
        return pd.Series(result, index=pdf.index).infer_objects()
    if isinstance(expr, _InExpr):
        v = evaluate(pdf, expr.col)
        if not isinstance(v, pd.Series):
            v = pd.Series([v] * len(pdf))
        res = v.isin(expr.values)
        # SQL three-valued logic: NULL never satisfies IN or NOT IN
        return res if expr.positive else (~res & v.notna())
    if isinstance(expr, _LikeExpr):
        import re as _re

        v = evaluate(pdf, expr.col)
        if not isinstance(v, pd.Series):
            v = pd.Series([v] * len(pdf))
        pat = _re.escape(expr.pattern).replace("%", ".*").replace("_", ".")
        res = v.str.match(f"^{pat}$", na=False)
        return res if expr.positive else (~res & v.notna())
    if isinstance(expr, _FuncExpr) and not expr.is_agg:
        fname = expr.func.upper()
        if fname == "CONCAT":
            args = [evaluate(pdf, a) for a in expr.args]
            res = None
            for a in args:
                part = a.astype(str) if isinstance(a, pd.Series) else str(a)
                res = part if res is None else res + part
            return res
        if fname in _SCALAR_FUNCS and _SCALAR_FUNCS[fname] is not None:
            args = [evaluate(pdf, a) for a in expr.args]
            first = args[0]
            if not isinstance(first, pd.Series):
                first = pd.Series([first] * len(pdf))
            return _SCALAR_FUNCS[fname](first, *args[1:])
        if expr.func.upper() == "COALESCE":
            args = [evaluate(pdf, a) for a in expr.args]
            res = None
            for a in args:
                if res is None:
                    res = a if isinstance(a, pd.Series) else pd.Series([a] * len(pdf))
                else:
                    fill = a if not isinstance(a, pd.Series) else a
                    res = res.where(res.notna(), fill)
            return res
        raise NotImplementedError(f"function {expr.func} not supported on pandas")
    raise NotImplementedError(f"can't evaluate {type(expr)}")


def _as_bool(v: Any) -> Any:
    if isinstance(v, pd.Series):
        if v.dtype == bool:
            return v
        return v.astype("boolean").fillna(False).astype(bool)
    return bool(v)


def eval_agg(pdf: pd.DataFrame, expr: _FuncExpr) -> Any:
    """Evaluate an aggregate function over a whole frame → scalar."""
    func = expr.func.upper()
    arg = expr.args[0] if len(expr.args) > 0 else None
    v = evaluate(pdf, arg) if arg is not None else None
    if not isinstance(v, pd.Series):
        v = pd.Series([v] * len(pdf))
    if expr.is_distinct:
        v = v.drop_duplicates()
    if func == "COUNT":
        return int(v.notna().sum())
    if func == "MIN":
        nn = v.dropna()
        return None if len(nn) == 0 else nn.min()
    if func == "MAX":
        nn = v.dropna()
        return None if len(nn) == 0 else nn.max()
    if func == "SUM":
        nn = v.dropna()
        return None if len(nn) == 0 else nn.sum()
    if func == "AVG":
        nn = v.dropna()
        return None if len(nn) == 0 else nn.mean()
    if func == "FIRST":
        nn = v.dropna()
        return nn.iloc[0] if len(nn) > 0 else None
    if func == "LAST":
        nn = v.dropna()
        return nn.iloc[-1] if len(nn) > 0 else None
    raise NotImplementedError(f"aggregation {func} not supported")


def eval_filter(pdf: pd.DataFrame, condition: ColumnExpr) -> pd.DataFrame:
    mask = evaluate(pdf, condition)
    mask = _as_bool(mask)
    if not isinstance(mask, pd.Series):
        return pdf if mask else pdf.head(0)
    return pdf[mask].reset_index(drop=True)


def eval_select(
    pdf: pd.DataFrame,
    input_schema: Schema,
    columns: SelectColumns,
    where: Optional[ColumnExpr] = None,
    having: Optional[ColumnExpr] = None,
) -> pd.DataFrame:
    """Full SELECT semantics on pandas: where → project/aggregate → having
    → distinct."""
    sc = columns.replace_wildcard(input_schema).assert_all_with_names()
    if where is not None:
        pdf = eval_filter(pdf, where)
    if not sc.has_agg:
        data = {}
        for c in sc.all_cols:
            v = evaluate(pdf, c)
            if not isinstance(v, pd.Series):
                v = pd.Series([v] * len(pdf), dtype=object if v is None else None)
            data[c.output_name] = v.reset_index(drop=True)
        res = pd.DataFrame(data) if len(pdf) > 0 else pd.DataFrame(
            {k: pd.Series(dtype=v.dtype) for k, v in data.items()}
        )
        assert_or_throw(having is None, FugueSQLError("having requires aggregation"))
        if sc.is_distinct:
            res = res.drop_duplicates().reset_index(drop=True)
        return res

    group_keys = list(sc.group_keys)
    group_key_ids = {id(c) for c in group_keys}
    if len(group_keys) == 0:
        row = {}
        for c in sc.all_cols:
            if isinstance(c, _LitColumnExpr):
                row[c.output_name] = evaluate(pdf, c)
            else:
                row[c.output_name] = _agg_one(pdf, c)
        res = pd.DataFrame([row], columns=[c.output_name for c in sc.all_cols])
    else:
        key_names = []
        kdf = pd.DataFrame(index=pdf.index)
        for k in group_keys:
            kv = evaluate(pdf, k)
            if not isinstance(kv, pd.Series):
                kv = pd.Series([kv] * len(pdf))
            kdf[k.output_name] = kv
            key_names.append(k.output_name)
        work = pd.concat([pdf.reset_index(drop=True), kdf.reset_index(drop=True).add_prefix("__key_")], axis=1)
        out_rows: List[dict] = []
        grouped = work.groupby(
            [f"__key_{k}" for k in key_names], dropna=False, sort=False
        )
        fast = _fast_grouped_agg(
            grouped, list(pdf.columns), sc, key_names, group_key_ids
        )
        if fast is not None:
            if having is not None:
                fast = _eval_having_filter(fast, sc, having)
            if sc.is_distinct:
                fast = fast.drop_duplicates().reset_index(drop=True)
            return fast
        for kv, sub in grouped:
            if not isinstance(kv, tuple):
                kv = (kv,)
            row = {}
            for name, val in zip(key_names, kv):
                row[name] = None if _is_na(val) else val
            sub_orig = sub[[c for c in pdf.columns]]
            for c in sc.all_cols:
                if id(c) in group_key_ids:
                    continue
                row[c.output_name] = _agg_one(sub_orig, c)
            out_rows.append(row)
        cols_order = [c.output_name for c in sc.all_cols]
        res = pd.DataFrame(out_rows, columns=cols_order) if len(out_rows) > 0 else pd.DataFrame(columns=cols_order)
    if having is not None:
        res = _eval_having_filter(res, sc, having)
    if sc.is_distinct:
        res = res.drop_duplicates().reset_index(drop=True)
    return res


def substitute_exprs(expr: ColumnExpr, mapping: Dict[str, str]) -> ColumnExpr:
    """Replace every subtree whose structural uuid (alias ignored; cast
    kept, with a cast-stripped second probe so ``CAST(expr AS t)`` matches
    ``expr`` and keeps the cast) appears in ``mapping`` with a reference
    to the mapped column name —
    used by GROUP BY-expression materialization to point projections and
    HAVING at the computed helper columns. Unknown node types pass
    through unchanged (no substitution inside them)."""
    from .expressions import col as _named_col, structural_key

    def _finish(out: ColumnExpr, e: ColumnExpr) -> ColumnExpr:
        """Restore the original node's cast/alias onto a rebuilt node."""
        if e.as_type is not None and out.as_type is None:
            out = out.cast(e.as_type)
        if e.output_name != "" and out.output_name != e.output_name:
            out = out.alias(e.output_name)
        return out

    def rw(e: ColumnExpr) -> ColumnExpr:
        key = structural_key(e)
        if key in mapping:
            return _finish(_named_col(mapping[key]), e)
        if e.as_type is not None:
            # CAST(<mapped expr> AS t) matches the bare expr and keeps the
            # cast — the cast-KEPT first probe only prevents CAST(x) from
            # silently COLLIDING with plain x when naming helpers
            bare_key = structural_key(e.cast(None))
            if bare_key in mapping:
                return _finish(_named_col(mapping[bare_key]).cast(e.as_type), e)
        if isinstance(e, _FuncExpr) and e.is_agg:
            # aggregate subtrees stay UNTOUCHED: their args evaluate over
            # pre-group rows, and rebuilding would downgrade the agg
            # subclass to a plain _FuncExpr (losing is_agg)
            return e
        if isinstance(e, _BinaryOpExpr):
            return _finish(_BinaryOpExpr(e.op, rw(e.left), rw(e.right)), e)
        if isinstance(e, _UnaryOpExpr):
            return _finish(_UnaryOpExpr(e.op, rw(e.col)), e)
        if isinstance(e, _FuncExpr):
            return _finish(
                _FuncExpr(
                    e.func,
                    *[rw(a) for a in e.args],
                    arg_distinct=e.is_distinct,
                ),
                e,
            )
        if isinstance(e, _InExpr):
            return _finish(_InExpr(rw(e.col), e.values, e.positive), e)
        if isinstance(e, _LikeExpr):
            return _finish(_LikeExpr(rw(e.col), e.pattern, e.positive), e)
        if isinstance(e, _CaseWhenExpr):
            return _finish(
                _CaseWhenExpr(
                    [(rw(c), rw(v)) for c, v in e.cases], rw(e.default)
                ),
                e,
            )
        return e

    return rw(expr)


def rewrite_having_aggs(
    having: ColumnExpr, agg_cols: List[ColumnExpr]
) -> ColumnExpr:
    """Replace aggregate subtrees in HAVING that structurally match a SELECT
    aggregate (ignoring alias/cast) with references to its output column —
    the rewritten predicate evaluates over the aggregated frame with the
    plain evaluator. Shared by the oracle and the device engine."""
    from .expressions import col as _named_col
    from .functions import is_agg

    agg_map: Dict[str, str] = {}
    for c in agg_cols:
        if is_agg(c):
            agg_map[c.alias("").cast(None).__uuid__()] = c.output_name

    def rw(e: ColumnExpr) -> ColumnExpr:
        if isinstance(e, _FuncExpr) and e.is_agg:
            key = e.alias("").cast(None).__uuid__()
            if key not in agg_map:
                raise FugueSQLError(
                    f"HAVING aggregate {e!r} does not appear in the SELECT list"
                )
            out: ColumnExpr = _named_col(agg_map[key])
            if e.as_type is not None:
                out = out.cast(e.as_type)
            return out
        if not is_agg(e):
            return e
        if isinstance(e, _BinaryOpExpr):
            return _BinaryOpExpr(e.op, rw(e.left), rw(e.right))
        if isinstance(e, _UnaryOpExpr):
            return _UnaryOpExpr(e.op, rw(e.col))
        if isinstance(e, _FuncExpr):
            return _FuncExpr(
                e.func, *[rw(a) for a in e.args], arg_distinct=e.is_distinct
            )
        if isinstance(e, _InExpr):
            return _InExpr(rw(e.col), e.values, e.positive)
        if isinstance(e, _LikeExpr):
            return _LikeExpr(rw(e.col), e.pattern, e.positive)
        if isinstance(e, _CaseWhenExpr):
            return _CaseWhenExpr(
                [(rw(c), rw(v)) for c, v in e.cases], rw(e.default)
            )
        raise NotImplementedError(f"unsupported HAVING expression {e!r}")

    return rw(having)


def _eval_having_filter(
    res: pd.DataFrame, sc: SelectColumns, having: ColumnExpr
) -> pd.DataFrame:
    """HAVING over the aggregated frame: rewrite aggregate subtrees to read
    their computed output columns, then filter normally."""
    from .functions import is_agg

    aggs = [c for c in sc.all_cols if is_agg(c)]
    return eval_filter(res, rewrite_having_aggs(having, aggs))


def _fast_grouped_agg(
    grouped: Any,
    input_cols: List[str],
    sc: SelectColumns,
    key_names: List[str],
    group_key_ids: Any,
) -> Optional[pd.DataFrame]:
    """Vectorized (cython) grouped aggregation for the common SELECT shape
    where every non-key output is a plain ``FUNC(column)`` (or COUNT(*)) —
    the per-group Python loop below costs ~1s/M rows; this path is ~50x
    faster and preserves the same NULL semantics (SUM/MIN/MAX/AVG of an
    all-NULL group is NULL via skipna + ``min_count``; FIRST/LAST skip
    NULLs like the scalar evaluator). Returns None when any output needs
    the general per-group evaluator."""
    plans: List[Any] = []
    for c in sc.all_cols:
        if id(c) in group_key_ids:
            continue
        if not isinstance(c, _FuncExpr) or not c.is_agg or c.is_distinct:
            return None
        func = c.func.upper()
        if len(c.args) != 1:
            return None
        a = c.args[0]
        if func == "COUNT" and (
            (isinstance(a, _LitColumnExpr) and a.value is not None)
            or (isinstance(a, _NamedColumnExpr) and a.name == "*")
        ):
            plans.append((c.output_name, "size", None, c.as_type))
            continue
        if (
            func not in ("SUM", "COUNT", "MIN", "MAX", "AVG", "FIRST", "LAST")
            or not isinstance(a, _NamedColumnExpr)
            or a.name not in input_cols
        ):
            return None
        plans.append((c.output_name, func, a.name, c.as_type))
    pieces: Dict[str, pd.Series] = {}
    for name, kind, src, as_type in plans:
        if (
            kind in ("MIN", "MAX")
            and src is not None
            and grouped.obj[src].dtype == object
        ):
            # cython groupby min/max raises on object columns holding None
            # (str-vs-None comparison); the general per-group path below
            # drops NULLs first — same semantics, just slower
            return None
        if kind == "size":
            s = grouped.size()
        elif kind == "SUM":
            s = grouped[src].sum(min_count=1)
        elif kind == "COUNT":
            s = grouped[src].count()
        elif kind == "MIN":
            s = grouped[src].min()
        elif kind == "MAX":
            s = grouped[src].max()
        elif kind == "AVG":
            s = grouped[src].mean()
        elif kind == "FIRST":
            s = grouped[src].first()
        else:
            s = grouped[src].last()
        if as_type is not None:
            cast = _cast_series(s, as_type)  # returns a fresh RangeIndex
            cast.index = s.index  # re-align to the group keys
            s = cast
        pieces[name] = s
    if len(pieces) > 0:
        res = pd.DataFrame(pieces).reset_index()
    else:  # SELECT of group keys only
        res = grouped.size().reset_index().drop(columns=[0])
    res.columns = [
        (c[len("__key_"):] if isinstance(c, str) and c.startswith("__key_") else c)
        for c in res.columns
    ]
    return res.reindex(columns=[c.output_name for c in sc.all_cols])


def _is_na(v: Any) -> bool:
    try:
        return v is None or (isinstance(v, float) and np.isnan(v)) or v is pd.NA or v is pd.NaT
    except Exception:
        return False


def _agg_one(pdf: pd.DataFrame, c: ColumnExpr) -> Any:
    """Evaluate one select column that contains aggregation(s)."""
    if isinstance(c, _FuncExpr) and c.is_agg:
        v = eval_agg(pdf, c)
        if c.as_type is not None:
            v = _cast_series(pd.Series([v]), c.as_type).iloc[0]
        return v
    # expression over aggregates, e.g. sum(a) + 1: substitute agg nodes
    return _eval_scalar_expr(pdf, c)


def _eval_scalar_expr(pdf: pd.DataFrame, c: ColumnExpr) -> Any:
    if isinstance(c, _FuncExpr) and c.is_agg:
        return eval_agg(pdf, c)
    if isinstance(c, _LitColumnExpr):
        return c.value
    if isinstance(c, _BinaryOpExpr):
        l = _eval_scalar_expr(pdf, c.left)
        r = _eval_scalar_expr(pdf, c.right)
        return {
            "+": lambda: l + r,
            "-": lambda: l - r,
            "*": lambda: l * r,
            "/": lambda: l / r,
        }[c.op]()
    if isinstance(c, _UnaryOpExpr) and c.op == "-":
        return -_eval_scalar_expr(pdf, c.col)
    raise NotImplementedError(f"can't evaluate scalar expression {c!r}")
