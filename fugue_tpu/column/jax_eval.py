"""jax.numpy evaluator for the column-expression IR.

The device twin of ``eval.py``: the same ``ColumnExpr`` tree compiles to XLA
over a dict of (sharded) jax arrays — projections/assignments on the TPU
engine run fully on device, with XLA propagating shardings through the
elementwise graph (no collectives needed for row-wise exprs).
"""

from typing import Any, Dict, Optional

import numpy as np
import pyarrow as pa

from ..exceptions import FugueInvalidOperation
from .expressions import (
    ColumnExpr,
    _BinaryOpExpr,
    _CaseWhenExpr,
    _FuncExpr,
    _LitColumnExpr,
    _NamedColumnExpr,
    _UnaryOpExpr,
)


def pa_type_to_np_dtype(tp: pa.DataType) -> Any:
    if pa.types.is_boolean(tp):
        return np.bool_
    return tp.to_pandas_dtype()


def evaluate_jnp(cols: Dict[str, Any], expr: ColumnExpr) -> Any:
    """Evaluate a non-aggregate expression over jnp arrays (traceable)."""
    import jax.numpy as jnp

    res = _eval(cols, expr)
    if expr.as_type is not None:
        res = jnp.asarray(res).astype(pa_type_to_np_dtype(expr.as_type))
    return res


def _eval(cols: Dict[str, Any], expr: ColumnExpr) -> Any:
    import jax.numpy as jnp

    if isinstance(expr, _NamedColumnExpr):
        if expr.name not in cols:
            raise FugueInvalidOperation(f"column {expr.name} is not on device")
        return cols[expr.name]
    if isinstance(expr, _LitColumnExpr):
        return expr.value
    if isinstance(expr, _UnaryOpExpr):
        v = evaluate_jnp(cols, expr.col)
        if expr.op == "IS_NULL":
            return jnp.isnan(v) if jnp.issubdtype(jnp.asarray(v).dtype, jnp.floating) else jnp.zeros_like(v, dtype=bool)
        if expr.op == "NOT_NULL":
            return ~jnp.isnan(v) if jnp.issubdtype(jnp.asarray(v).dtype, jnp.floating) else jnp.ones_like(v, dtype=bool)
        if expr.op == "~":
            return jnp.logical_not(v)
        if expr.op == "-":
            return -v
        raise NotImplementedError(expr.op)
    if isinstance(expr, _BinaryOpExpr):
        l = evaluate_jnp(cols, expr.left)
        r = evaluate_jnp(cols, expr.right)
        op = expr.op
        if op == "+":
            return l + r
        if op == "-":
            return l - r
        if op == "*":
            return l * r
        if op == "/":
            return l / r
        if op == "<":
            return l < r
        if op == "<=":
            return l <= r
        if op == ">":
            return l > r
        if op == ">=":
            return l >= r
        if op == "==":
            return l == r
        if op == "!=":
            return l != r
        if op == "&":
            return jnp.logical_and(l, r)
        if op == "|":
            return jnp.logical_or(l, r)
        raise NotImplementedError(op)
    if isinstance(expr, _CaseWhenExpr):
        # CASE WHEN as a reversed jnp.where chain: the FIRST matching case
        # wins, NaN/false conditions fall through to the default — the
        # same semantics the positional pandas evaluator implements
        res = evaluate_jnp(cols, expr.default)
        for c, v in reversed(expr.cases):
            cond = evaluate_jnp(cols, c)
            res = jnp.where(cond, evaluate_jnp(cols, v), res)
        return res
    if isinstance(expr, _FuncExpr) and not expr.is_agg:
        if expr.func.upper() == "COALESCE":
            args = [evaluate_jnp(cols, a) for a in expr.args]
            res = args[0]
            for a in args[1:]:
                res = jnp.where(jnp.isnan(res), a, res)
            return res
        raise NotImplementedError(f"function {expr.func} not supported on device")
    raise NotImplementedError(f"can't evaluate {type(expr)} on device")


# ---------------------------------------------------------------------------
# three-valued (SQL NULL) evaluation over encoded device frames
# ---------------------------------------------------------------------------
#
# Each node evaluates to (value, isnull). NULL semantics follow SQL/Kleene:
# comparisons/arithmetic propagate NULL; AND/OR use three-valued logic;
# IS_NULL/COALESCE consume the null flag. String predicates on
# dictionary-encoded columns evaluate HOST-SIDE over the dictionary (via the
# pandas evaluator) into a lookup table the device gathers by code — the
# TPU-native way to run string filters without device strings.


def _contains_null_ops(expr: ColumnExpr) -> bool:
    """Whether the subtree consumes NULL flags (IS_NULL/NOT_NULL/COALESCE) —
    such subtrees must NOT evaluate over the dictionary (which has no
    nulls); the three-valued evaluator handles them with code<0."""
    if isinstance(expr, _UnaryOpExpr) and expr.op in ("IS_NULL", "NOT_NULL"):
        return True
    if isinstance(expr, _FuncExpr) and expr.func.upper() == "COALESCE":
        return True
    return any(_contains_null_ops(c) for c in expr.children)


def _dict_subtree_col(expr: ColumnExpr, encodings: Dict[str, dict]) -> Optional[str]:
    """If the subtree references exactly ONE dict-encoded column (and any
    literals) and consumes no NULL flags, return its name — the whole
    subtree can evaluate over the dictionary on host. None otherwise."""
    names: set = set()

    def walk(e: ColumnExpr) -> bool:
        if isinstance(e, _NamedColumnExpr):
            if e.wildcard:
                return False
            names.add(e.name)
            return True
        if isinstance(e, _LitColumnExpr):
            return True
        return all(walk(c) for c in e.children)

    if not walk(expr) or _contains_null_ops(expr):
        return None
    if len(names) == 1:
        n = next(iter(names))
        if n in encodings and encodings[n]["kind"] == "dict":
            return n
    return None


def _eval_over_dictionary(expr: ColumnExpr, name: str, dictionary: Any) -> Any:
    """Evaluate the subtree on the host over the dictionary values → a
    numpy table of len(dictionary) results."""
    import pandas as pd

    from .eval import evaluate as eval_pd

    pdf = pd.DataFrame({name: dictionary.to_pandas()})
    res = eval_pd(pdf, expr)
    if not isinstance(res, pd.Series):
        res = pd.Series([res] * len(pdf))
    return np.asarray(res.to_numpy())


def evaluate_jnp_3v(
    cols: Dict[str, Any],
    masks: Dict[str, Any],
    dict_tables: Dict[str, Any],
    expr: ColumnExpr,
    code_cols: Any = frozenset(),
) -> Any:
    """Evaluate with SQL NULL semantics → (value, isnull) jnp arrays.

    ``dict_tables`` maps dict-encoded column names to HOST-precomputed
    lookup tables for the dict subtrees found by
    :func:`plan_dict_lookups` — keyed by the subtree expression uuid.
    ``code_cols`` are dictionary-encoded column names whose raw value is
    the int32 code (NULL = −1) — the planner only lets them appear where
    just the null flag is consumed (IS_NULL/NOT_NULL).
    """
    import jax.numpy as jnp

    def ev(e: ColumnExpr) -> Any:
        # casts apply at EVERY node, not just the root: `CAST(x AS int) > 0`
        # must compare the cast value (the fused-chain composer also relies
        # on this when it inlines cast-carrying projections into predicates)
        v, nl = _ev3(e)
        if e.as_type is not None:
            v = jnp.asarray(v).astype(pa_type_to_np_dtype(e.as_type))
        return v, nl

    def _ev3(e: ColumnExpr) -> Any:
        key = e.__uuid__()
        if key in dict_tables:
            name, table = dict_tables[key]
            code = cols[name]
            idx = jnp.clip(code, 0, max(table.shape[0] - 1, 0))
            val = jnp.asarray(table)[idx] if table.shape[0] > 0 else jnp.zeros_like(code, dtype=table.dtype)
            return val, code < 0
        if isinstance(e, _NamedColumnExpr):
            v = cols[e.name]
            if e.name in code_cols:
                return v, v < 0  # only the null flag is meaningful
            if e.name in masks:
                return v, masks[e.name]
            if jnp.issubdtype(v.dtype, jnp.floating):
                return v, jnp.isnan(v)
            return v, jnp.zeros(v.shape, dtype=bool)
        if isinstance(e, _LitColumnExpr):
            # np scalar (not python bool): `~` must mean logical not
            return e.value, np.False_
        if isinstance(e, _UnaryOpExpr):
            v, nl = ev(e.col)
            if e.op == "IS_NULL":
                return nl, np.False_
            if e.op == "NOT_NULL":
                return jnp.logical_not(nl), np.False_
            if e.op == "~":
                return jnp.logical_not(v), nl
            if e.op == "-":
                return -v, nl
            raise NotImplementedError(e.op)
        if isinstance(e, _BinaryOpExpr):
            lv, ln = ev(e.left)
            rv, rn = ev(e.right)
            op = e.op
            if op in ("&", "|"):
                lb = jnp.asarray(lv, dtype=bool)
                rb = jnp.asarray(rv, dtype=bool)
                if op == "&":
                    # Kleene AND: FALSE dominates NULL
                    val = lb & rb
                    nul = (ln | rn) & ~((~ln & ~lb) | (~rn & ~rb))
                else:
                    # Kleene OR: TRUE dominates NULL
                    val = lb | rb
                    nul = (ln | rn) & ~((~ln & lb) | (~rn & rb))
                return val, nul
            nul = ln | rn
            if op == "+":
                return lv + rv, nul
            if op == "-":
                return lv - rv, nul
            if op == "*":
                return lv * rv, nul
            if op == "/":
                return lv / rv, nul
            if op == "<":
                return lv < rv, nul
            if op == "<=":
                return lv <= rv, nul
            if op == ">":
                return lv > rv, nul
            if op == ">=":
                return lv >= rv, nul
            if op == "==":
                return lv == rv, nul
            if op == "!=":
                return lv != rv, nul
            raise NotImplementedError(op)
        if isinstance(e, _FuncExpr) and not e.is_agg:
            if e.func.upper() == "COALESCE":
                parts = [ev(a) for a in e.args]
                val, nul = parts[-1]
                for pv, pn in reversed(parts[:-1]):
                    val = jnp.where(pn, val, pv)
                    nul = pn & nul
                return val, nul
            raise NotImplementedError(f"function {e.func} not supported on device")
        if isinstance(e, _CaseWhenExpr):
            # first matching case wins; a NULL condition falls through
            # (SQL: NULL is not TRUE) — same outcome as the pandas path
            val, nul = ev(e.default)
            for c, v in reversed(e.cases):
                cv, cn = ev(c)
                vv, vn = ev(v)
                take = jnp.asarray(cv, dtype=bool) & jnp.logical_not(cn)
                val = jnp.where(take, vv, val)
                nul = jnp.where(take, vn, nul)
            return val, nul
        raise NotImplementedError(f"can't evaluate {type(e)} on device")

    return ev(expr)


def plan_dict_lookups(
    expr: ColumnExpr, encodings: Dict[str, dict]
) -> Optional[Dict[str, Any]]:
    """Find maximal dict-column subtrees and precompute their host lookup
    tables. Returns {subtree_uuid: (col_name, np table)} or None when the
    expression cannot run on device (a dict column used outside a
    host-evaluable subtree)."""
    tables: Dict[str, Any] = {}

    def plan(e: ColumnExpr, under_null: bool = False) -> bool:
        name = _dict_subtree_col(e, encodings)
        if name is not None and not isinstance(e, _NamedColumnExpr):
            try:
                table = _eval_over_dictionary(e, name, encodings[name]["dictionary"])
            except Exception:
                return False
            if table.dtype == object:
                return False  # string-valued result has no device type
            tables[e.__uuid__()] = (name, table)
            return True
        if isinstance(e, _NamedColumnExpr):
            # a bare dict column produces no device VALUE — it is only
            # allowed where just its null flag is consumed
            if e.name in encodings and encodings[e.name]["kind"] == "dict":
                return under_null
            return True
        if isinstance(e, _LitColumnExpr):
            return True
        if isinstance(e, _UnaryOpExpr) and e.op in ("IS_NULL", "NOT_NULL"):
            return plan(e.col, under_null=True)
        return all(plan(c) for c in e.children)

    return tables if plan(expr) else None


def _epoch_of(value: Any, tp: Any) -> Optional[int]:
    """Convert a datetime-like literal to the epoch int of the column's
    arrow storage (timestamp unit / date32 days). None = not convertible."""
    import datetime as _dt

    import pandas as pd

    try:
        ts = pd.Timestamp(value)
    except Exception:
        return None
    if pa.types.is_date32(tp):
        return (ts - pd.Timestamp("1970-01-01")).days
    if pa.types.is_timestamp(tp):
        ns = ts.value  # nanoseconds since epoch
        div = {"s": 10**9, "ms": 10**6, "us": 10**3, "ns": 1}[tp.unit]
        return ns // div
    return None


def _rewrite_datetime_literals(
    expr: ColumnExpr, encodings: Dict[str, dict]
) -> Any:
    """Rewrite comparisons between epoch-encoded datetime columns and
    datetime-like literals into integer comparisons. Returns
    (rewritten_expr, names of datetime columns now usable as plain ints),
    or (expr, empty set) when nothing applies."""
    import datetime as _dt

    allowed: set = set()

    def is_dt_col(e: ColumnExpr) -> bool:
        return (
            isinstance(e, _NamedColumnExpr)
            and encodings.get(e.name, {}).get("kind") == "datetime"
        )

    def rw(e: ColumnExpr) -> ColumnExpr:
        if isinstance(e, _BinaryOpExpr):
            if e.op in ("<", "<=", ">", ">=", "==", "!="):
                l, r = e.left, e.right
                for a, b, flip in ((l, r, False), (r, l, True)):
                    if is_dt_col(a) and isinstance(b, _LitColumnExpr):
                        if not isinstance(
                            b.value, (str, _dt.date, _dt.datetime)
                        ):
                            continue
                        epoch = _epoch_of(b.value, encodings[a.name]["type"])
                        if epoch is None:
                            continue
                        allowed.add(a.name)
                        lit_e = _LitColumnExpr(epoch)
                        return (
                            _BinaryOpExpr(e.op, lit_e, a)
                            if flip
                            else _BinaryOpExpr(e.op, a, lit_e)
                        )
            return _BinaryOpExpr(e.op, rw(e.left), rw(e.right))
        if isinstance(e, _UnaryOpExpr):
            if e.op in ("IS_NULL", "NOT_NULL") and is_dt_col(e.col):
                allowed.add(e.col.name)
                return e
            return _UnaryOpExpr(e.op, rw(e.col))
        return e

    return rw(expr), allowed


def device_predicate_plan(
    expr: ColumnExpr, device_cols: Any, encodings: Dict[str, dict]
) -> Optional[Dict[str, Any]]:
    """Gate + plan for three-valued device evaluation of a predicate.

    Returns ``(dict_lookup_tables, rewritten_expr)`` when the expression
    can run on device with :func:`evaluate_jnp_3v`, else None. Dict-encoded
    columns are allowed only inside host-reducible subtrees; datetime
    columns are allowed where a literal comparison rewrote to epoch ints
    or under IS_NULL/NOT_NULL.
    """
    from .functions import is_agg

    if is_agg(expr):
        return None
    expr, dt_allowed = _rewrite_datetime_literals(expr, encodings)
    tables = plan_dict_lookups(expr, encodings)
    if tables is None:
        return None

    def ok(e: ColumnExpr, under_null: bool = False) -> bool:
        if e.__uuid__() in tables:
            return True
        if e.as_type is not None and not (
            pa.types.is_integer(e.as_type)
            or pa.types.is_floating(e.as_type)
            or pa.types.is_boolean(e.as_type)
        ):
            return False
        if isinstance(e, _NamedColumnExpr):
            if e.wildcard or e.name not in device_cols:
                return False
            if e.name in encodings:
                kind = encodings[e.name]["kind"]
                if kind == "dict":
                    return under_null  # only the null flag is usable
                if kind == "datetime":
                    # usable where a literal comparison rewrote to epoch
                    # ints, or under IS_NULL/NOT_NULL
                    return under_null or e.name in dt_allowed
                return False
            return True
        if isinstance(e, _LitColumnExpr):
            return e.value is not None and isinstance(e.value, (int, float, bool))
        if isinstance(e, _UnaryOpExpr):
            if e.op in ("IS_NULL", "NOT_NULL"):
                return ok(e.col, under_null=True)
            return e.op in ("~", "-") and ok(e.col)
        if isinstance(e, _BinaryOpExpr):
            return e.op in (
                "+", "-", "*", "/", "<", "<=", ">", ">=", "==", "!=", "&", "|"
            ) and ok(e.left) and ok(e.right)
        if isinstance(e, _FuncExpr):
            return (
                not e.is_agg
                and e.func.upper() == "COALESCE"
                and all(ok(a) for a in e.args)
            )
        if isinstance(e, _CaseWhenExpr):
            # lowered as a jnp.where chain in evaluate_jnp_3v; every
            # condition/value/default must itself be device-evaluable
            # (a None default fails the literal rule above)
            return all(ok(c) for c in e.children)
        return False

    return (tables, expr) if ok(expr) else None


def can_evaluate_on_device(
    expr: ColumnExpr, device_cols: Any, check_agg: bool = True
) -> bool:
    """Whether the expression only references device columns and device ops."""
    from .functions import is_agg

    if check_agg and is_agg(expr):
        return False
    if expr.as_type is not None and not (
        pa.types.is_integer(expr.as_type)
        or pa.types.is_floating(expr.as_type)
        or pa.types.is_boolean(expr.as_type)
    ):
        # device arrays can't hold strings/binary/nested → host fallback
        return False
    if isinstance(expr, _NamedColumnExpr):
        return expr.name in device_cols and not expr.wildcard
    if isinstance(expr, _LitColumnExpr):
        # None (null) has no device representation yet -> host fallback
        return expr.value is not None and isinstance(expr.value, (int, float, bool))
    if isinstance(expr, _FuncExpr):
        if expr.is_agg or expr.func.upper() != "COALESCE":
            return False
    elif isinstance(expr, _CaseWhenExpr):
        # lowered as a jnp.where chain; a None default/value has no device
        # representation (same rule as bare literals below)
        pass
    elif not isinstance(expr, (_NamedColumnExpr, _LitColumnExpr, _BinaryOpExpr, _UnaryOpExpr)):
        # unknown node types (IN/LIKE/...) have no jnp lowering yet
        return False
    return all(
        can_evaluate_on_device(c, device_cols, check_agg=False) for c in expr.children
    )
