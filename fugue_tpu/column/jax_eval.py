"""jax.numpy evaluator for the column-expression IR.

The device twin of ``eval.py``: the same ``ColumnExpr`` tree compiles to XLA
over a dict of (sharded) jax arrays — projections/assignments on the TPU
engine run fully on device, with XLA propagating shardings through the
elementwise graph (no collectives needed for row-wise exprs).
"""

from typing import Any, Dict, Optional

import numpy as np
import pyarrow as pa

from ..exceptions import FugueInvalidOperation
from .expressions import (
    ColumnExpr,
    _BinaryOpExpr,
    _FuncExpr,
    _LitColumnExpr,
    _NamedColumnExpr,
    _UnaryOpExpr,
)


def pa_type_to_np_dtype(tp: pa.DataType) -> Any:
    if pa.types.is_boolean(tp):
        return np.bool_
    return tp.to_pandas_dtype()


def evaluate_jnp(cols: Dict[str, Any], expr: ColumnExpr) -> Any:
    """Evaluate a non-aggregate expression over jnp arrays (traceable)."""
    import jax.numpy as jnp

    res = _eval(cols, expr)
    if expr.as_type is not None:
        res = jnp.asarray(res).astype(pa_type_to_np_dtype(expr.as_type))
    return res


def _eval(cols: Dict[str, Any], expr: ColumnExpr) -> Any:
    import jax.numpy as jnp

    if isinstance(expr, _NamedColumnExpr):
        if expr.name not in cols:
            raise FugueInvalidOperation(f"column {expr.name} is not on device")
        return cols[expr.name]
    if isinstance(expr, _LitColumnExpr):
        return expr.value
    if isinstance(expr, _UnaryOpExpr):
        v = evaluate_jnp(cols, expr.col)
        if expr.op == "IS_NULL":
            return jnp.isnan(v) if jnp.issubdtype(jnp.asarray(v).dtype, jnp.floating) else jnp.zeros_like(v, dtype=bool)
        if expr.op == "NOT_NULL":
            return ~jnp.isnan(v) if jnp.issubdtype(jnp.asarray(v).dtype, jnp.floating) else jnp.ones_like(v, dtype=bool)
        if expr.op == "~":
            return jnp.logical_not(v)
        if expr.op == "-":
            return -v
        raise NotImplementedError(expr.op)
    if isinstance(expr, _BinaryOpExpr):
        l = evaluate_jnp(cols, expr.left)
        r = evaluate_jnp(cols, expr.right)
        op = expr.op
        if op == "+":
            return l + r
        if op == "-":
            return l - r
        if op == "*":
            return l * r
        if op == "/":
            return l / r
        if op == "<":
            return l < r
        if op == "<=":
            return l <= r
        if op == ">":
            return l > r
        if op == ">=":
            return l >= r
        if op == "==":
            return l == r
        if op == "!=":
            return l != r
        if op == "&":
            return jnp.logical_and(l, r)
        if op == "|":
            return jnp.logical_or(l, r)
        raise NotImplementedError(op)
    if isinstance(expr, _FuncExpr) and not expr.is_agg:
        if expr.func.upper() == "COALESCE":
            args = [evaluate_jnp(cols, a) for a in expr.args]
            res = args[0]
            for a in args[1:]:
                res = jnp.where(jnp.isnan(res), a, res)
            return res
        raise NotImplementedError(f"function {expr.func} not supported on device")
    raise NotImplementedError(f"can't evaluate {type(expr)} on device")


def can_evaluate_on_device(
    expr: ColumnExpr, device_cols: Any, check_agg: bool = True
) -> bool:
    """Whether the expression only references device columns and device ops."""
    from .functions import is_agg

    if check_agg and is_agg(expr):
        return False
    if expr.as_type is not None and not (
        pa.types.is_integer(expr.as_type)
        or pa.types.is_floating(expr.as_type)
        or pa.types.is_boolean(expr.as_type)
    ):
        # device arrays can't hold strings/binary/nested → host fallback
        return False
    if isinstance(expr, _NamedColumnExpr):
        return expr.name in device_cols and not expr.wildcard
    if isinstance(expr, _LitColumnExpr):
        # None (null) has no device representation yet -> host fallback
        return expr.value is not None and isinstance(expr.value, (int, float, bool))
    if isinstance(expr, _FuncExpr):
        if expr.is_agg or expr.func.upper() != "COALESCE":
            return False
    elif not isinstance(expr, (_NamedColumnExpr, _LitColumnExpr, _BinaryOpExpr, _UnaryOpExpr)):
        # unknown node types (CASE/IN/LIKE/...) have no jnp lowering yet
        return False
    return all(
        can_evaluate_on_device(c, device_cols, check_agg=False) for c in expr.children
    )
