"""The ``fa``-style functional API facade.

Parity with the reference (`fugue/api.py:1-72`): one flat namespace with
dataset/dataframe utilities, engine verbs, workflow entrypoints and SQL.

Usage::

    import fugue_tpu.api as fa

    with fa.engine_context("tpu"):
        res = fa.transform(df, fn, schema="*", partition={"by": ["k"]})
"""

from .dataset.api import (  # noqa: F401
    as_fugue_dataset,
    count,
    get_num_partitions,
    is_bounded,
    is_empty,
    is_local,
    show,
)
from .dataframe.api import (  # noqa: F401
    alter_columns,
    as_array,
    as_array_iterable,
    as_arrow,
    as_dict_iterable,
    as_dicts,
    as_fugue_df,
    as_local,
    as_local_bounded,
    as_pandas,
    drop_columns,
    get_column_names,
    get_native_as_df,
    get_schema,
    head,
    is_df,
    normalize_column_names,
    peek_array,
    peek_dict,
    rename,
    select_columns,
)
from .execution.api import (  # noqa: F401
    aggregate,
    anti_join,
    as_fugue_engine_df,
    assign,
    broadcast,
    clear_global_engine,
    cross_join,
    distinct,
    dropna,
    engine_context,
    fillna,
    filter,  # noqa: A004
    full_outer_join,
    get_context_engine,
    get_current_conf,
    get_current_parallelism,
    inner_join,
    intersect,
    join,
    left_outer_join,
    load,
    persist,
    repartition,
    right_outer_join,
    run_engine_function,
    sample,
    save,
    select,
    semi_join,
    set_global_engine,
    subtract,
    take,
    union,
)
from .workflow.api import out_transform, raw_sql, transform  # noqa: F401
from .sql import fugue_sql, fugue_sql_flow, fsql  # noqa: F401
