"""Per-verb roofline recording (ROADMAP item 5 groundwork) — RECORD ONLY.

While tracing is enabled, every traced engine verb's close folds one
observation — achieved bytes/s and rows/s — into an in-memory table
keyed ``<verb>|<dtype-class>|w<width-bucket>``, and the folds are
published into the :class:`~fugue_tpu.tuning.store.TunedStore` under its
``"rooflines"`` top-level key at run-scope flush (same atomic
temp-write+rename publish, same LRU entry bound as the ``"tuning"``
key). No placement decision reads these yet; ``engine.report()`` renders
them so the measured per-verb ceilings are visible before anything acts
on them.

Cost contract (``fugue.tpu.tuning.rooflines``, default ON): one
in-memory dict fold per traced verb close while tracing is enabled;
nothing at all while tracing is off (the hook lives behind the tracer's
enabled check). The result-frame probe reads only already-materialized
metadata — it must NEVER force a device fetch or an ingest (a lazy
frame with unknown row count simply isn't folded).
"""

import threading
import time
import weakref
from typing import Any, Callable, Dict, Optional, Tuple

__all__ = [
    "RooflineRecorder",
    "rooflines_enabled",
    "frame_profile",
    "install_verb_observer",
]

# a close faster than this carries no usable throughput signal (the
# MIN_WALL_S discipline from tuner.py, scaled to single-verb granularity)
MIN_VERB_WALL_S = 1e-4


def rooflines_enabled(conf: Any) -> bool:
    from ..constants import FUGUE_TPU_CONF_TUNING_ROOFLINES

    if conf is None:
        return True
    try:
        return bool(conf.get(FUGUE_TPU_CONF_TUNING_ROOFLINES, True))
    except Exception:
        return True


def _dtype_class(pa_type: Any) -> str:
    import pyarrow.types as pt

    if pt.is_floating(pa_type):
        return "float"
    if pt.is_integer(pa_type):
        return "int"
    if pt.is_boolean(pa_type):
        return "bool"
    if pt.is_temporal(pa_type):
        return "temporal"
    return "object"


def _width_bucket(width: int) -> int:
    """Power-of-two ceiling: w1/w2/w4/w8... — bounded key cardinality."""
    return 1 << max(0, width - 1).bit_length() if width > 1 else 1


def frame_profile(out: Any) -> Optional[Tuple[int, int, str, int]]:
    """Cheap ``(rows, bytes, dtype_class, width_bucket)`` of a verb's
    result frame, or None when it can't be read without forcing work.

    - rows: a device frame's cached ``_row_count`` (NEVER the masked
      ``count()`` — that forces a device fetch), or ``count()`` on a
      local bounded frame (metadata there);
    - bytes: summed device-column ``nbytes`` when the frame is
      device-resident, the arrow table's ``nbytes`` when the native
      object exposes one, else the 64-bit-cell estimate ``rows*width*8``
      (exact for ingested device frames — the engine ingests to 8-byte
      columns);
    - dtype class: ``float``/``int``/``bool``/``temporal`` when every
      column agrees, ``mixed`` otherwise.
    """
    try:
        schema = getattr(out, "schema", None)
        pa_schema = getattr(schema, "pa_schema", None)
        if pa_schema is None:
            return None
        fields = list(pa_schema)
        width = len(fields)
        if width == 0:
            return None
        classes = {_dtype_class(f.type) for f in fields}
        cls = classes.pop() if len(classes) == 1 else "mixed"

        rows: Optional[int] = None
        rc = getattr(out, "_row_count", None)
        if isinstance(rc, int):
            rows = rc if rc >= 0 else None
        elif getattr(out, "is_local", False) and getattr(out, "is_bounded", False):
            rows = int(out.count())
        if rows is None or rows < 0:
            return None

        nbytes = 0
        dc = getattr(out, "_device_cols", None)
        if isinstance(dc, dict) and dc:
            nbytes = sum(int(getattr(a, "nbytes", 0) or 0) for a in dc.values())
        if nbytes <= 0:
            nb = getattr(getattr(out, "native", None), "nbytes", None)
            nbytes = int(nb) if isinstance(nb, int) and nb > 0 else rows * width * 8
        return rows, nbytes, cls, _width_bucket(width)
    except Exception:
        return None


def _fold(entry: Dict[str, Any], obs: Dict[str, Any]) -> Dict[str, Any]:
    """Merge one observation batch into a roofline entry. Associative and
    commutative over batches (sums add, bests max, lasts last-write-win)
    — the same discipline as the span-histogram mergeable encoding, so a
    delta published by a flush composes with what another process already
    wrote under the same key."""
    out = dict(entry)
    out["obs"] = int(out.get("obs", 0) or 0) + int(obs.get("obs", 1))
    for k in ("rows", "bytes", "wall_s"):
        out[k] = (out.get(k, 0) or 0) + obs.get(k, 0)
    for k in ("best_bytes_s", "best_rows_s"):
        out[k] = max(float(out.get(k, 0.0) or 0.0), float(obs.get(k, 0.0)))
    for k in ("last_bytes_s", "last_rows_s"):
        if obs.get(k) is not None:
            out[k] = obs[k]
    return out


class RooflineRecorder:
    """In-memory fold table + flush-to-store for one engine's tuner.

    ``record`` is the traced-verb close hook: probe the result frame,
    fold under the lock, done — no I/O. ``flush`` drains the pending
    folds into the store's ``"rooflines"`` key as a DELTA (the store
    merge sums/maxes against what's already persisted, so concurrent
    processes sharing one store file compose instead of clobbering)."""

    def __init__(self, store: Any, stats: Any = None):
        self._store = store
        self._stats = stats
        self._lock = threading.Lock()
        self._pending: Dict[str, Dict[str, Any]] = {}

    def record(self, verb: str, wall_s: float, result: Any) -> None:
        if wall_s < MIN_VERB_WALL_S:
            return
        prof = frame_profile(result)
        if prof is None:
            return
        rows, nbytes, cls, wbucket = prof
        if rows <= 0 and nbytes <= 0:
            return
        self.observe(verb, cls, wbucket, wall_s, rows, nbytes)

    def observe(
        self, verb: str, dtype_class: str, width: int, wall_s: float,
        rows: int, nbytes: int,
    ) -> None:
        """Fold one explicit observation (the testable core of
        :meth:`record`; ``width`` is the already-bucketed column count)."""
        if wall_s <= 0:
            return
        key = f"{verb}|{dtype_class}|w{width}"
        obs = {
            "obs": 1,
            "rows": int(rows),
            "bytes": int(nbytes),
            "wall_s": float(wall_s),
            "best_bytes_s": nbytes / wall_s,
            "best_rows_s": rows / wall_s,
            "last_bytes_s": nbytes / wall_s,
            "last_rows_s": rows / wall_s,
        }
        with self._lock:
            self._pending[key] = _fold(self._pending.get(key, {}), obs)
        if self._stats is not None:
            self._stats.inc("roofline_folds")

    def pending_count(self) -> int:
        with self._lock:
            return len(self._pending)

    def flush(self) -> bool:
        """Publish the pending folds into the store's ``rooflines`` key
        (atomic read-merge-write; LRU-bounded there). True when a publish
        happened. Never raises — recording must not fail a run."""
        with self._lock:
            pend, self._pending = self._pending, {}
        if not pend:
            return False
        try:

            def mutate(entries: Dict[str, Any]) -> Dict[str, Any]:
                now = time.time()
                for key, obs in pend.items():
                    cur = entries.get(key)
                    merged = _fold(cur if isinstance(cur, dict) else {}, obs)
                    merged["ts"] = now
                    entries[key] = merged
                return entries

            return bool(self._store.publish_rooflines(mutate))
        except Exception:
            # put the folds back so the next flush retries them
            with self._lock:
                for key, obs in pend.items():
                    self._pending[key] = _fold(self._pending.get(key, {}), obs)
            return False

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        """Persisted entries overlaid with the not-yet-flushed folds —
        what ``engine.report()`` renders."""
        try:
            out = {k: dict(v) for k, v in self._store.rooflines().items()}
        except Exception:
            out = {}
        with self._lock:
            for key, obs in self._pending.items():
                out[key] = _fold(out.get(key, {}), obs)
        return out


def install_verb_observer(engine: Any) -> None:
    """Install the process-wide traced-verb close hook bound (by weakref)
    to ``engine``'s tuner. Called at jax-engine construction when
    ``fugue.tpu.tuning.rooflines`` is enabled; a newer engine's install
    replaces an older one's (the resource-probe registration rule). The
    hook only ever runs while tracing is enabled — ``traced_verb``'s
    disabled path stays a single attribute check."""
    from ..obs.tracer import set_verb_observer

    if not rooflines_enabled(getattr(engine, "conf", None)):
        return
    ref = weakref.ref(engine)

    def _observe(verb: str, wall_s: float, result: Any) -> None:
        e = ref()
        if e is None:
            set_verb_observer(None)  # engine collected: self-uninstall
            return
        e.tuner.roofline.record(verb, wall_s, result)

    set_verb_observer(_observe)
