"""Cost-based adaptive execution — the feedback loop over the engine's
own telemetry (docs/tuning.md).

Every performance knob this module touches has a static conf default
that PRs 2/6/7/8 already measure the consequences of: stream chunk size
and prefetch depth show up as per-stream ``overlap_fraction`` /
``fetch_wait`` / producer-vs-consumer wait in ``PipelineStats``; shuffle
bucket count shows up as ``peak_device_bytes`` vs the device budget and
per-bucket overhead; join-side size estimates show up as the actual
bytes/rows the spill partitioner measured. This module closes the loop:

- :func:`run_scope` (entered by ``workflow.run``) keys one run's
  observations by the plan fingerprint;
- :meth:`Tuner.stream_params` / :meth:`Tuner.join_params` /
  :meth:`Tuner.repartition_params` resolve knobs — from the learned
  entry when one exists, from the static rule otherwise (every
  resolution is recorded as a decision with its evidence);
- at scope exit, :meth:`Tuner.flush` turns the run's observations into
  the NEXT generation's settings via bounded multiplicative adjustment
  (at most ``MAX_CHUNK_FACTOR``x / ``MAX_BUCKET_FACTOR``x per
  generation, so a wild first estimate converges within a few runs
  instead of oscillating) and publishes them to the
  :class:`~fugue_tpu.tuning.store.TunedStore`.

Degradation ladder (every rung bit-identical in results):

1. ``fugue.tpu.tuning.enabled=false`` → this module is inert; every
   knob resolves exactly as before the layer existed.
2. No run scope (direct engine verb calls outside ``workflow.run``) →
   static conf.
3. Scope but no learned entry (cold plan) → static conf, decision
   recorded as ``static: no observations``.
4. Learned entry → adaptive values; the RUNTIME decision function
   (``choose_join_strategy``, the streaming eligibility checks) stays
   authoritative — tuning only feeds it better inputs.
5. Streams too small to measure (``wall < MIN_WALL_S``) are never
   adjusted — tiny test workloads can't perturb the store.
"""

import contextvars
import hashlib
import threading
from contextlib import contextmanager
from typing import Any, Dict, Iterator, List, Optional, Tuple

from .stats import TuningStats
from .store import TunedStore, resolve_tuned_path

__all__ = [
    "Tuner",
    "StreamHandle",
    "ExchangeHandle",
    "plan_fingerprint",
    "tuning_enabled",
    "run_scope",
    "current_scope",
    "describe_tuning",
    "adjust_stream",
    "adjust_buckets",
    "adjust_pipeline",
]

# -- adjustment policy constants (docs/tuning.md "Adjustment policy") -------
MIN_WALL_S = 0.15  # streams faster than this carry no usable signal
MIN_SHUFFLE_WALL_S = 0.3
CHUNK_BAND_HI = 16  # chunk-count band: above it, grow chunk_rows ...
CHUNK_TARGET = 8  # ... toward this many chunks per stream
MAX_CHUNK_FACTOR = 4.0  # bounded multiplicative step per generation
CHUNK_MIN_ROWS = 1 << 12
CHUNK_MAX_ROWS = 1 << 22
CHUNK_BYTES_FRACTION = 8  # chunk bytes stay under budget/8
DEPTH_MAX = 8
MAX_BUCKET_FACTOR = 8.0
MIN_BUCKETS_TO_SHRINK = 16  # below this, per-bucket overhead is noise
PEAK_TARGET_FRACTION = 2  # aim bucket-pair peak at budget/2
CARDINALITY_MARGIN = 0.2  # republish observed sizes on >20% drift
PAIR_DEPTH_MAX = 4  # bucket-pair prefetch never queues deeper than this
MEM_BYTES_MIN = 1 << 26  # learned mem-tier budget floor (64 MiB)
MEM_BYTES_MAX = 1 << 30  # ... and ceiling (1 GiB)


_ADDR_RE = None


def _sig_of(v: Any) -> str:
    """Address-free signature of one task parameter. Task ``__uuid__``s
    hash raw data objects by IDENTITY (correct for checkpoints, where a
    false hit serves wrong data) — but tuning keys on plan SHAPE: the
    same pipeline over a re-created stream source must land on the same
    entry, and the worst a collision can cost is a mis-tuned knob that
    the next observation corrects, never a wrong result."""
    global _ADDR_RE
    if _ADDR_RE is None:
        import re

        _ADDR_RE = re.compile(r"0x[0-9a-fA-F]+")
    if callable(v):
        return "fn:%s.%s" % (
            getattr(v, "__module__", ""),
            getattr(v, "__qualname__", type(v).__name__),
        )
    try:
        r = repr(v)
    except Exception:
        r = type(v).__name__
    return _ADDR_RE.sub("0x", r[:200])


def plan_fingerprint(tasks: Any) -> Optional[str]:
    """Structural fingerprint of the POST-optimization task DAG — the
    store key. Same plan shape => same fingerprint across processes and
    restarts (task uuids won't do: they embed data-object identity)."""
    try:
        tasks = list(tasks)
        idx = {id(t): i for i, t in enumerate(tasks)}
        md = hashlib.sha1()
        for i, t in enumerate(tasks):
            parts = [
                str(i),
                type(t).__name__,
                type(getattr(t, "extension", None)).__name__,
            ]
            try:
                for k in sorted(str(k) for k in t.params.keys()):
                    parts.append(f"{k}={_sig_of(t.params[k])}")
            except Exception:
                pass
            try:
                parts.append(str(t.partition_spec))
            except Exception:
                pass
            try:
                parts.append(
                    ",".join(str(idx.get(id(x), -1)) for x in t.inputs)
                )
            except Exception:
                pass
            md.update(("|".join(parts) + "\n").encode())
        return md.hexdigest()[:16]
    except Exception:
        return None


def tuning_enabled(conf: Any) -> bool:
    from ..constants import FUGUE_TPU_CONF_TUNING_ENABLED

    if conf is None:
        return True
    try:
        return bool(conf.get(FUGUE_TPU_CONF_TUNING_ENABLED, True))
    except Exception:
        return True


def _confidence(obs: int) -> float:
    return round(min(1.0, obs / 3.0), 2)


# -- pure adjustment functions (unit-tested directly) ------------------------
def adjust_stream(
    chunk_rows: int, depth: int, obs: Dict[str, Any], budget_bytes: int
) -> Optional[Dict[str, Any]]:
    """Next-generation (chunk_rows, prefetch_depth) for one stream from
    one observed run, or None when the run carries no usable signal.

    - too many chunks (> ``CHUNK_BAND_HI``) → grow ``chunk_rows`` toward
      ``CHUNK_TARGET`` chunks, at most ``MAX_CHUNK_FACTOR``x per
      generation, capped so one chunk stays under
      ``budget/CHUNK_BYTES_FRACTION`` bytes;
    - consumer starved (waited on an empty queue far longer than the
      producer waited on a full one) → deepen the prefetch queue, up to
      ``DEPTH_MAX``;
    - producer starved → shallower queue (floor 2: double buffering),
      releasing host memory the pipeline can't use.
    """
    chunks = int(obs.get("chunks_prefetched", 0) or 0)
    wall = float(obs.get("wall_s", 0.0) or 0.0)
    if chunks <= 0 or wall < MIN_WALL_S:
        return None
    notes: List[str] = []
    new_chunk, new_depth = int(chunk_rows), int(depth)
    if chunks > CHUNK_BAND_HI:
        factor = min(MAX_CHUNK_FACTOR, chunks / float(CHUNK_TARGET))
        new_chunk = int(chunk_rows * factor)
        rows = int(obs.get("rows", 0) or 0)
        nbytes = int(obs.get("bytes", 0) or 0)
        if rows > 0 and nbytes > 0 and budget_bytes > 0:
            bpr = max(nbytes / rows, 1e-9)
            new_chunk = min(
                new_chunk, int(budget_bytes / CHUNK_BYTES_FRACTION / bpr)
            )
        new_chunk = max(CHUNK_MIN_ROWS, min(CHUNK_MAX_ROWS, new_chunk))
        new_chunk = -(-new_chunk // 1024) * 1024  # stable jit-key rounding
        if new_chunk != chunk_rows:
            notes.append(
                f"{chunks} chunks > band {CHUNK_BAND_HI}: chunk_rows "
                f"{chunk_rows} -> {new_chunk} (x{factor:.1f}, bounded)"
            )
    pw = float(obs.get("producer_wait_s", 0.0) or 0.0)
    cw = float(obs.get("consumer_wait_s", 0.0) or 0.0)
    if depth >= 1:
        if cw > max(2.0 * pw, 0.05) and depth < DEPTH_MAX and chunks > 2 * depth:
            new_depth = min(DEPTH_MAX, max(2, depth * 2))
            notes.append(
                f"producer-bound (consumer waited {cw:.2f}s vs {pw:.2f}s): "
                f"prefetch_depth {depth} -> {new_depth}"
            )
        elif pw > max(2.0 * cw, 0.05) and depth > 2:
            new_depth = max(2, depth // 2)
            notes.append(
                f"consumer-bound (producer waited {pw:.2f}s vs {cw:.2f}s): "
                f"prefetch_depth {depth} -> {new_depth}"
            )
    converged = new_chunk == chunk_rows and new_depth == depth
    overlap = obs.get("overlap_fraction", 0.0)
    return {
        "chunk_rows": new_chunk,
        "prefetch_depth": new_depth,
        "converged": converged,
        "evidence": "; ".join(notes)
        or (
            f"in band: {chunks} chunks, waits balanced "
            f"(overlap {overlap}, wall {wall:.2f}s)"
        ),
    }


def adjust_buckets(
    buckets: int, obs: Dict[str, Any], budget_bytes: int
) -> Optional[Dict[str, Any]]:
    """Next-generation shuffle bucket count from one observed exchange.

    The static sizer (``bucket_count``: size / (budget/32)) guesses the
    bucket-pair expansion; the measured ``peak_device_bytes`` replaces
    the guess: scale P so the peak lands near
    ``budget/PEAK_TARGET_FRACTION`` — fewer, larger buckets when the
    observed peak was far under budget (less per-bucket overhead), more
    when it crowded the budget. Bounded to ``MAX_BUCKET_FACTOR``x per
    generation; never shrinks below-noise bucket counts."""
    peak = int(obs.get("peak_device_bytes", 0) or 0)
    wall = float(obs.get("wall_s", 0.0) or 0.0)
    if buckets <= 0 or peak <= 0 or budget_bytes <= 0:
        return None
    over_budget = peak > budget_bytes
    if not over_budget and (
        wall < MIN_SHUFFLE_WALL_S or buckets <= MIN_BUCKETS_TO_SHRINK
    ):
        return None
    target_peak = budget_bytes / float(PEAK_TARGET_FRACTION)
    ideal = max(1, -(-int(buckets * (peak / target_peak)) // 1))
    lo = max(1, int(buckets / MAX_BUCKET_FACTOR))
    hi = min(4096, int(buckets * MAX_BUCKET_FACTOR))
    new = max(lo, min(hi, ideal))
    if not over_budget and 0.5 <= peak / target_peak <= 2.0:
        new = buckets  # close enough: stability beats the last few %
    return {
        "buckets": new,
        "converged": new == buckets,
        "evidence": (
            f"peak {peak}B at {buckets} buckets vs budget {budget_bytes}B "
            f"(target ~{int(target_peak)}B): buckets {buckets} -> {new}"
        ),
    }


def adjust_pipeline(
    depth: int, mem_bytes: int, obs: Dict[str, Any]
) -> Optional[Dict[str, Any]]:
    """Next-generation (pair_depth, mem_bytes) for one pipelined
    exchange from its observed producer/consumer waits and mem-tier
    pressure, or None when the run carries no usable signal.

    - consumer starved (the kernel waited on the pair producer far
      longer than the producer waited on a full queue) → deepen the
      pair prefetch, up to ``PAIR_DEPTH_MAX``;
    - producer starved → shallower (floor 0: serial consumption — on a
      single-core mesh a producer thread only steals consumer time);
    - demotions under ledger pressure → grow the mem-tier budget
      (bounded 2x per generation, capped at ``MEM_BYTES_MAX``);
    - a tier running far under its cap shrinks toward what the exchange
      actually used, releasing host memory the pipeline can't use.
    """
    groups = int(obs.get("pipe_chunks", 0) or 0)
    wall = float(obs.get("wall_s", 0.0) or 0.0)
    if groups <= 0 or wall < MIN_SHUFFLE_WALL_S:
        return None
    notes: List[str] = []
    new_depth, new_mem = int(depth), int(mem_bytes)
    pw = float(obs.get("pipe_producer_wait_s", 0.0) or 0.0)
    cw = float(obs.get("pipe_consumer_wait_s", 0.0) or 0.0)
    if cw > max(2.0 * pw, 0.05) and depth < PAIR_DEPTH_MAX and groups > 2 * max(depth, 1):
        new_depth = min(PAIR_DEPTH_MAX, max(1, depth * 2))
        notes.append(
            f"producer-bound (consumer waited {cw:.2f}s vs {pw:.2f}s): "
            f"pair_depth {depth} -> {new_depth}"
        )
    elif pw > max(2.0 * cw, 0.05) and depth > 0:
        new_depth = depth // 2
        notes.append(
            f"consumer-bound (producer waited {pw:.2f}s vs {cw:.2f}s): "
            f"pair_depth {depth} -> {new_depth}"
        )
    demotions = int(obs.get("mem_demotions", 0) or 0)
    used = int(obs.get("mem_bytes_used", 0) or 0)
    if demotions > 0 and mem_bytes < MEM_BYTES_MAX:
        new_mem = min(MEM_BYTES_MAX, max(MEM_BYTES_MIN, mem_bytes * 2))
        notes.append(
            f"{demotions} demotions under a {mem_bytes}B ledger: "
            f"mem_bytes -> {new_mem}"
        )
    elif demotions == 0 and 0 < used < mem_bytes // 4 and mem_bytes > MEM_BYTES_MIN:
        new_mem = max(MEM_BYTES_MIN, used * 2)
        notes.append(
            f"tier used {used}B of {mem_bytes}B with no pressure: "
            f"mem_bytes -> {new_mem}"
        )
    converged = new_depth == depth and new_mem == mem_bytes
    return {
        "pair_depth": new_depth,
        "mem_bytes": new_mem,
        "converged": converged,
        "evidence": "; ".join(notes)
        or (
            f"pipeline balanced: {groups} groups, waits {pw:.2f}s/{cw:.2f}s, "
            f"tier {used}B/{mem_bytes}B"
        ),
    }


# -- run scope ---------------------------------------------------------------
class _Scope:
    """One workflow.run's tuning context: the plan fingerprint, per-kind
    ordinal counters (deterministic stream/join ids for a deterministic
    plan), the handles awaiting their prefetcher, and the observations
    collected for flush."""

    def __init__(self, tuner: "Tuner", plan_fp: str, enabled: bool):
        self.tuner = tuner
        self.plan_fp = plan_fp
        self.enabled = enabled
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {}
        self.pending: Dict[str, "StreamHandle"] = {}
        self.stream_obs: List[Tuple["StreamHandle", Dict[str, Any]]] = []
        self.exchanges: List["ExchangeHandle"] = []

    def next_sid(self, kind: str) -> str:
        with self._lock:
            n = self._counters.get(kind, 0)
            self._counters[kind] = n + 1
        return kind if n == 0 else f"{kind}#{n}"

    def add_stream_obs(self, handle: "StreamHandle", run: Dict[str, Any]) -> None:
        with self._lock:
            self.stream_obs.append((handle, dict(run)))

    def add_exchange(self, handle: "ExchangeHandle") -> None:
        with self._lock:
            self.exchanges.append(handle)


_RUN: "contextvars.ContextVar[Optional[_Scope]]" = contextvars.ContextVar(
    "fugue_tpu_tuning_scope", default=None
)


def current_scope() -> Optional[_Scope]:
    return _RUN.get()


@contextmanager
def run_scope(engine: Any, plan_fp: Optional[str], conf: Any = None) -> Iterator[Any]:
    """Entered by ``workflow.run`` around task execution. ``conf`` is the
    run's merged view (engine conf overlaid with workflow compile conf) —
    the same precedence ``explain()`` uses — so a per-workflow or
    per-tenant ``fugue.tpu.tuning.enabled=false`` disables tuning for
    THIS run without touching the shared engine."""
    tuner = getattr(engine, "tuner", None)
    if tuner is None or plan_fp is None:
        yield None
        return
    enabled = tuning_enabled(conf if conf is not None else getattr(engine, "conf", None))
    scope = _Scope(tuner, plan_fp, enabled)
    token = _RUN.set(scope if enabled else None)
    try:
        yield scope
    finally:
        _RUN.reset(token)
        if enabled:
            try:
                tuner.flush(scope)
            except Exception:  # learning must never fail a run
                import logging

                logging.getLogger("fugue_tpu.tuning").debug(
                    "tuning flush failed", exc_info=True
                )


# -- handles -----------------------------------------------------------------
class StreamHandle:
    """One stream's resolved knobs plus the observation funnel back."""

    __slots__ = (
        "scope",
        "sid",
        "chunk_rows",
        "prefetch_depth",
        "adaptive",
        "used_depth",
    )

    def __init__(
        self,
        scope: _Scope,
        sid: str,
        chunk_rows: int,
        prefetch_depth: Optional[int],
        adaptive: bool,
    ):
        self.scope = scope
        self.sid = sid
        self.chunk_rows = chunk_rows
        self.prefetch_depth = prefetch_depth  # None = use the static default
        self.adaptive = adaptive
        self.used_depth = 0

    @property
    def coalesce(self) -> bool:
        """Merge undersized source chunks up to ``chunk_rows`` before the
        device step. Only an ADAPTIVE setting coalesces: the static path
        must stay bit-identical in shape to the pre-tuning engine."""
        return self.adaptive

    def observe(self, run: Dict[str, Any]) -> None:
        self.scope.tuner.stats.inc("observations")
        self.scope.add_stream_obs(self, run)


class ExchangeHandle:
    """One spill join/repartition's calibration + observation funnel."""

    __slots__ = (
        "scope",
        "sid",
        "entry",
        "used_buckets",
        "used_pair_depth",
        "used_mem_bytes",
        "obs",
    )

    def __init__(self, scope: _Scope, sid: str, entry: Optional[Dict[str, Any]]):
        self.scope = scope
        self.sid = sid
        self.entry = dict(entry or {})
        self.used_buckets = 0
        self.used_pair_depth = 0
        self.used_mem_bytes = 0
        self.obs: Dict[str, Any] = {}
        scope.add_exchange(self)

    def bucket_count(self, conf: Any, est_bytes: Optional[int]) -> int:
        """Calibrated bucket count for this exchange: the learned value
        when one exists, the static ``bucket_count`` rule otherwise."""
        from ..shuffle.strategy import bucket_count as _static

        cal = self.entry.get("buckets")
        if cal:
            n = max(1, min(4096, int(cal)))
            source, evidence = "adaptive", str(self.entry.get("evidence", ""))
        else:
            n = _static(conf, est_bytes)
            source, evidence = "static", "no observations"
        self.used_buckets = n
        self.scope.tuner.stats.decision(
            {
                "target": "shuffle",
                "key": self.sid,
                "plan": self.scope.plan_fp,
                "value": {"buckets": n},
                "source": source,
                "evidence": evidence,
                "confidence": _confidence(int(self.entry.get("obs", 0) or 0)),
            }
        )
        return n

    def pipeline_params(
        self, conf: Any, static_depth: int, static_mem_bytes: int
    ) -> Tuple[int, int]:
        """Resolve the pipelined exchange's pair-prefetch depth and
        mem-tier budget: the learned values when prior runs of this plan
        observed the pipeline, the static conf resolution otherwise.
        Every resolution is recorded as a decision with its evidence."""
        depth, mem = self.entry.get("pair_depth"), self.entry.get("mem_bytes")
        if depth is not None or mem is not None:
            d = int(depth) if depth is not None else int(static_depth)
            m = int(mem) if mem is not None else int(static_mem_bytes)
            source = "adaptive"
            evidence = str(self.entry.get("pipe_evidence", ""))
        else:
            d, m = int(static_depth), int(static_mem_bytes)
            source, evidence = "static", "no observations"
        self.used_pair_depth, self.used_mem_bytes = d, m
        self.scope.tuner.stats.decision(
            {
                "target": "shuffle_pipeline",
                "key": self.sid,
                "plan": self.scope.plan_fp,
                "value": {"pair_depth": d, "mem_bytes": m},
                "source": source,
                "evidence": evidence,
                "confidence": _confidence(int(self.entry.get("obs", 0) or 0)),
            }
        )
        return d, m

    def observe_pair_stream(self, run: Dict[str, Any]) -> None:
        """The pair prefetcher's finished-run telemetry (the PR 2
        ``PipelineStats`` run dict): producer/consumer waits name the
        pipeline's bottleneck for the next generation."""
        self.obs.update(
            pipe_chunks=int(run.get("chunks_prefetched", 0) or 0),
            pipe_producer_wait_s=float(run.get("producer_wait_s", 0.0) or 0.0),
            pipe_consumer_wait_s=float(run.get("consumer_wait_s", 0.0) or 0.0),
        )
        self.scope.tuner.stats.inc("observations")

    def observe_pipeline(self, info: Dict[str, Any]) -> None:
        """Mem-tier pressure + grouping evidence from the finished
        exchange (ledger peak/demotions, pairs per group)."""
        self.obs.update(
            pairs_per_group=int(info.get("pairs_per_group", 0) or 0),
            mem_bytes_used=int(info.get("mem_bytes_used", 0) or 0),
            mem_demotions=int(info.get("mem_demotions", 0) or 0),
        )

    def observe_sides(
        self, left_bytes: int, right_bytes: int, left_rows: int, right_rows: int
    ) -> None:
        self.obs.update(
            left_bytes=int(left_bytes),
            right_bytes=int(right_bytes),
            left_rows=int(left_rows),
            right_rows=int(right_rows),
        )
        self.scope.tuner.stats.inc("observations")

    def observe_run(self, peak_device_bytes: int, wall_s: float) -> None:
        self.obs.update(
            peak_device_bytes=int(peak_device_bytes), wall_s=float(wall_s)
        )

    def observe_exchange(self, stages: int, peak_stage_bytes: int) -> None:
        """Staged device-exchange telemetry (ISSUE 17): how many
        collective stages this join's schedule ran and the high-water
        per-stage payload. Persisted as exchange-size calibration
        evidence — next runs of this plan see the measured schedule in
        ``workflow.explain()``, and the recorded side cardinalities (the
        ``observe_sides`` funnel) are what steer ``choose_join_strategy``
        onto the device_exchange rung without re-estimating."""
        self.obs.update(
            exch_stages=int(stages),
            exch_peak_stage_bytes=int(peak_stage_bytes),
        )
        self.scope.tuner.stats.inc("observations")


# -- the tuner ---------------------------------------------------------------
class Tuner:
    """Per-engine adaptive-execution coordinator. Owns the stats group
    (``engine.stats()["tuning"]``) and the persistent store; all knob
    resolutions and all learning go through here."""

    def __init__(self, conf: Any = None):
        from ..constants import FUGUE_TPU_CONF_TUNING_MAX_ENTRIES
        from .store import DEFAULT_MAX_ENTRIES

        self._conf = conf
        self.stats = TuningStats()
        try:
            max_entries = int(
                conf.get(FUGUE_TPU_CONF_TUNING_MAX_ENTRIES, DEFAULT_MAX_ENTRIES)
            )
        except Exception:
            max_entries = DEFAULT_MAX_ENTRIES
        self.store = TunedStore(
            resolve_tuned_path(conf), max_entries=max_entries, stats=self.stats
        )
        # per-verb roofline folds (ISSUE 18, record-only) — published into
        # the same store under its "rooflines" key at flush
        from .roofline import RooflineRecorder

        self.roofline = RooflineRecorder(self.store, stats=self.stats)

    # MetricsRegistry source contract (fugue_tpu/obs/registry.py)
    def as_dict(self) -> Dict[str, Any]:
        out = self.stats.as_dict()
        out["entries"] = self.store.count()
        out["roofline_pending"] = self.roofline.pending_count()
        return out

    def reset(self) -> None:
        """Counters zero; LEARNED entries are kept (the JitCache
        keep-entries contract — forgetting them would re-pay cold runs)."""
        self.stats.reset()

    # -- resolution ----------------------------------------------------------
    def _plan_section(self, scope: _Scope, section: str, sid: str) -> Optional[dict]:
        entry = self.store.plan_entry(scope.plan_fp)
        if not entry:
            return None
        sec = entry.get(section)
        if not isinstance(sec, dict):
            return None
        v = sec.get(sid)
        return v if isinstance(v, dict) else None

    def stream_params(self, verb: str, static_chunk_rows: int) -> Optional[StreamHandle]:
        """Resolve one stream's chunk size (and learned prefetch depth).
        Returns None outside an enabled run scope — the caller uses its
        static values untouched, exactly the pre-tuning code path."""
        scope = _RUN.get()
        if scope is None or not scope.enabled:
            return None
        sid = scope.next_sid(verb)
        learned = self._plan_section(scope, "streams", sid)
        if learned and int(learned.get("chunk_rows", 0) or 0) > 0:
            handle = StreamHandle(
                scope,
                sid,
                int(learned["chunk_rows"]),
                int(learned["prefetch_depth"])
                if learned.get("prefetch_depth")
                else None,
                adaptive=True,
            )
            source, evidence = "adaptive", str(learned.get("evidence", ""))
            conf_n = int(learned.get("obs", 0) or 0)
        else:
            handle = StreamHandle(scope, sid, int(static_chunk_rows), None, False)
            source, evidence = "static", "no observations"
            conf_n = 0
        self.stats.decision(
            {
                "target": "stream",
                "key": sid,
                "plan": scope.plan_fp,
                "value": {
                    "chunk_rows": handle.chunk_rows,
                    "prefetch_depth": handle.prefetch_depth,
                },
                "source": source,
                "evidence": evidence,
                "confidence": _confidence(conf_n),
            }
        )
        scope.pending[verb] = handle
        return handle

    def take_stream_handle(self, verb: str) -> Optional[StreamHandle]:
        """Claim the handle :meth:`stream_params` left for this verb's
        prefetcher (same function invocation, same thread)."""
        scope = _RUN.get()
        if scope is None or not scope.enabled:
            return None
        return scope.pending.pop(verb, None)

    def join_params(
        self,
        est_left_bytes: Optional[int],
        est_right_bytes: Optional[int],
        est_right_rows: Optional[int],
    ) -> Tuple[Optional[int], Optional[int], Optional[int], Optional[ExchangeHandle]]:
        """Feed observed join-side cardinalities back into the strategy
        ladder: where the static estimate is UNKNOWN (None — one-pass
        streams, host frames), substitute what a previous run of this
        plan measured. Known estimates are never overridden — the live
        size is fresher than history."""
        scope = _RUN.get()
        if scope is None or not scope.enabled:
            return est_left_bytes, est_right_bytes, est_right_rows, None
        sid = scope.next_sid("join")
        learned = self._plan_section(scope, "joins", sid)
        handle = ExchangeHandle(scope, sid, learned)
        l, r, rr = est_left_bytes, est_right_bytes, est_right_rows
        used: List[str] = []
        if learned:
            if l is None and learned.get("left_bytes"):
                l = int(learned["left_bytes"])
                used.append(f"left_bytes~{l}")
            if r is None and learned.get("right_bytes"):
                r = int(learned["right_bytes"])
                used.append(f"right_bytes~{r}")
            if rr is None and learned.get("right_rows"):
                rr = int(learned["right_rows"])
                used.append(f"right_rows~{rr}")
        self.stats.decision(
            {
                "target": "join",
                "key": sid,
                "plan": scope.plan_fp,
                "value": {
                    "left_bytes": l,
                    "right_bytes": r,
                    "right_rows": rr,
                },
                "source": "adaptive" if used else "static",
                "evidence": (
                    "observed cardinalities: " + ", ".join(used)
                    if used
                    else "no observations"
                ),
                "confidence": _confidence(int((learned or {}).get("obs", 0) or 0)),
            }
        )
        return l, r, rr, handle

    # -- learning ------------------------------------------------------------
    def _budget(self) -> int:
        from ..shuffle.strategy import device_budget_bytes

        try:
            return device_budget_bytes(self._conf)
        except Exception:
            return 0

    def flush(self, scope: _Scope) -> None:
        """Turn the scope's observations into next-generation settings and
        persist. Publishes to disk only on MATERIAL change (a new or
        changed setting, a convergence flip, a >20% cardinality drift);
        bookkeeping-only updates stay in memory — a converged warm server
        does not rewrite the file on every submission."""
        # drain the run's roofline folds first — they publish (or no-op)
        # independently of whether any knob observation landed below
        self.roofline.flush()
        with scope._lock:
            stream_obs = list(scope.stream_obs)
            exchanges = list(scope.exchanges)
        if not stream_obs and not any(h.obs for h in exchanges):
            return
        budget = self._budget()
        material = False
        converged_flips = 0
        cur_entry = self.store.plan_entry(scope.plan_fp) or {}

        def mutate(e: Dict[str, Any]) -> Optional[Dict[str, Any]]:
            nonlocal material, converged_flips
            streams = dict(e.get("streams") or {})
            joins = dict(e.get("joins") or {})
            for handle, run in stream_obs:
                used_chunk = handle.chunk_rows
                used_depth = handle.used_depth
                adj = adjust_stream(used_chunk, used_depth, run, budget)
                cur = streams.get(handle.sid)
                if adj is None:
                    if cur:
                        cur = dict(cur)
                        cur["obs"] = int(cur.get("obs", 0) or 0) + 1
                        streams[handle.sid] = cur
                    continue
                if cur is None and adj["converged"]:
                    continue  # static values already in band: nothing learned
                new = {
                    "chunk_rows": adj["chunk_rows"],
                    "prefetch_depth": adj["prefetch_depth"],
                    "obs": int((cur or {}).get("obs", 0) or 0) + 1,
                    "converged": adj["converged"],
                    "evidence": adj["evidence"],
                }
                if adj["converged"] and not (cur or {}).get("converged"):
                    converged_flips += 1
                if (
                    cur is None
                    or cur.get("chunk_rows") != new["chunk_rows"]
                    or cur.get("prefetch_depth") != new["prefetch_depth"]
                    or bool(cur.get("converged")) != new["converged"]
                ):
                    material = True
                streams[handle.sid] = new
            for handle in exchanges:
                if not handle.obs:
                    continue
                cur = dict(joins.get(handle.sid) or {})
                new = dict(cur)
                new["obs"] = int(cur.get("obs", 0) or 0) + 1
                for k in ("left_bytes", "right_bytes", "left_rows", "right_rows"):
                    v = handle.obs.get(k)
                    if v is None:
                        continue
                    old = cur.get(k)
                    if old is None or abs(v - old) > CARDINALITY_MARGIN * max(
                        old, 1
                    ):
                        new[k] = int(v)
                        material = True
                # staged device-exchange calibration (ISSUE 17): persist
                # the measured schedule (stage count + peak per-stage
                # payload) under the same drift margin as cardinalities
                for k in ("exch_stages", "exch_peak_stage_bytes"):
                    v = handle.obs.get(k)
                    if v is None:
                        continue
                    old = cur.get(k)
                    if old is None or abs(v - old) > CARDINALITY_MARGIN * max(
                        old, 1
                    ):
                        new[k] = int(v)
                        material = True
                if handle.used_buckets and handle.obs.get("peak_device_bytes"):
                    adj = adjust_buckets(handle.used_buckets, handle.obs, budget)
                    if adj is not None:
                        if cur.get("buckets") != adj["buckets"] or bool(
                            cur.get("converged")
                        ) != adj["converged"]:
                            material = True
                        if adj["converged"] and not cur.get("converged"):
                            converged_flips += 1
                        new["buckets"] = adj["buckets"]
                        new["converged"] = adj["converged"]
                        new["evidence"] = adj["evidence"]
                if handle.obs.get("pipe_chunks"):
                    padj = adjust_pipeline(
                        handle.used_pair_depth, handle.used_mem_bytes, handle.obs
                    )
                    if padj is not None:
                        if (
                            cur.get("pair_depth") != padj["pair_depth"]
                            or cur.get("mem_bytes") != padj["mem_bytes"]
                            or bool(cur.get("pipe_converged"))
                            != padj["converged"]
                        ):
                            material = True
                        if padj["converged"] and not cur.get("pipe_converged"):
                            converged_flips += 1
                        new["pair_depth"] = padj["pair_depth"]
                        new["mem_bytes"] = padj["mem_bytes"]
                        new["pipe_converged"] = padj["converged"]
                        new["pipe_evidence"] = padj["evidence"]
                if new != cur:
                    joins[handle.sid] = new
            if not streams and not joins:
                return None
            e["streams"] = streams
            e["joins"] = joins
            return e

        # compute ONCE against the current snapshot; publish overlays the
        # computed sections onto a fresh read (cross-process merge at the
        # entry level; a racing publisher of the SAME plan last-wins)
        merged = mutate(dict(cur_entry))
        if merged is None:
            return
        if converged_flips:
            self.stats.inc("converged", converged_flips)
        if material:

            def install(e: Dict[str, Any]) -> Dict[str, Any]:
                out_streams = dict(e.get("streams") or {})
                out_streams.update(merged.get("streams") or {})
                out_joins = dict(e.get("joins") or {})
                out_joins.update(merged.get("joins") or {})
                e["streams"] = out_streams
                e["joins"] = out_joins
                return e

            self.store.publish(scope.plan_fp, install)
        else:
            import time as _time

            merged["ts"] = _time.time()
            merged.setdefault("gen", int(cur_entry.get("gen", 0) or 0))
            self.store.remember(scope.plan_fp, merged)


# -- explain rendering -------------------------------------------------------
def describe_tuning(
    conf: Any, plan_fp: Optional[str], engine: Any = None
) -> List[str]:
    """The ``workflow.explain()`` tuning section: what the tuner WOULD
    use for this plan right now — per-knob value, source, evidence and
    confidence — or why it stays static."""
    lines = ["", "Adaptive tuning (docs/tuning.md):"]
    if not tuning_enabled(conf):
        lines.append(
            "  DISABLED (fugue.tpu.tuning.enabled=false) -- all knobs static"
        )
        return lines
    if plan_fp is None:
        lines.append("  static: plan not fingerprintable")
        return lines
    tuner = getattr(engine, "tuner", None) if engine is not None else None
    store = tuner.store if tuner is not None else TunedStore(resolve_tuned_path(conf))
    entry = store.plan_entry(plan_fp)
    if not entry:
        lines.append(
            f"  static: no observations for plan {plan_fp} "
            f"(store: {store.path})"
        )
        return lines
    gen = int(entry.get("gen", 0) or 0)
    lines.append(f"  plan {plan_fp}: generation {gen} (store: {store.path})")
    for sid, s in sorted((entry.get("streams") or {}).items()):
        if not isinstance(s, dict):
            continue
        lines.append(
            "  stream %s: chunk_rows=%s prefetch_depth=%s [%s, obs=%s, "
            "confidence=%s] -- %s"
            % (
                sid,
                s.get("chunk_rows"),
                s.get("prefetch_depth"),
                "converged" if s.get("converged") else "adjusting",
                s.get("obs", 0),
                _confidence(int(s.get("obs", 0) or 0)),
                s.get("evidence", ""),
            )
        )
    for sid, j in sorted((entry.get("joins") or {}).items()):
        if not isinstance(j, dict):
            continue
        parts = []
        if j.get("buckets"):
            parts.append(f"buckets={j['buckets']}")
        if j.get("pair_depth") is not None:
            parts.append(f"pair_depth={j['pair_depth']}")
        if j.get("mem_bytes") is not None:
            parts.append(f"mem_bytes={j['mem_bytes']}")
        for k in ("left_bytes", "right_bytes", "right_rows"):
            if j.get(k) is not None:
                parts.append(f"{k}~{j[k]}")
        if j.get("exch_stages") is not None:
            parts.append(
                f"exchange: {j['exch_stages']} stages @ "
                f"<={j.get('exch_peak_stage_bytes', 0)}B/stage"
            )
        lines.append(
            "  %s: %s [%s, obs=%s, confidence=%s] -- %s"
            % (
                sid,
                " ".join(parts) or "(cardinalities only)",
                "converged" if j.get("converged") else "adjusting",
                j.get("obs", 0),
                _confidence(int(j.get("obs", 0) or 0)),
                j.get("evidence", ""),
            )
        )
    return lines
