"""Tuning counters + decision log — an ``engine.metrics`` source
(``engine.stats()["tuning"]``, flattened onto ``/metrics``).

Follows the system-wide reset contract (``JitCache.reset``): counters
zero on ``reset``, the LEARNED settings (which live in the
:class:`~fugue_tpu.tuning.store.TunedStore`, not here) are kept — a
stats reset must never turn into a perf event by forgetting what the
engine already converged to.

Every shared-attribute write happens under ``self._lock`` — this class
is required to pass ``tools/lint_locks.py --strict`` from day one.
"""

import threading
from collections import deque
from typing import Any, Dict, List

__all__ = ["TuningStats", "MAX_DECISIONS"]

# decisions kept for rendering (stats/report); enough for one large plan
MAX_DECISIONS = 64

_COUNTERS = (
    "decisions",  # every knob resolution (adaptive + static)
    "adaptive",  # resolutions served from learned observations
    "static",  # resolutions that fell back to the static rule
    "observations",  # telemetry records absorbed (streams/joins/shuffles)
    "publishes",  # store writes (temp-write+rename publishes)
    "loads",  # store file (re)loads
    "load_failures",  # corrupt/unreadable store files degraded to defaults
    "evictions",  # stale plan fingerprints dropped at publish time
    "converged",  # settings marked converged this process
)


class TuningStats:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._c: Dict[str, int] = {}
        self._decisions: "deque" = deque(maxlen=MAX_DECISIONS)

    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._c[name] = self._c.get(name, 0) + int(n)

    def decision(self, d: Dict[str, Any]) -> None:
        """Record one knob resolution: ``{"target", "key", "value",
        "source", "evidence", "confidence"}`` — the same record
        ``workflow.explain()`` renders."""
        with self._lock:
            self._c["decisions"] = self._c.get("decisions", 0) + 1
            src = "adaptive" if d.get("source") == "adaptive" else "static"
            self._c[src] = self._c.get(src, 0) + 1
            self._decisions.append(dict(d))

    def last_decisions(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(d) for d in self._decisions]

    def as_dict(self) -> Dict[str, Any]:
        with self._lock:
            out: Dict[str, Any] = {k: self._c.get(k, 0) for k in _COUNTERS}
            out["last_decisions"] = [dict(d) for d in self._decisions]
            return out

    def reset(self) -> None:
        with self._lock:
            self._c = {}
            self._decisions.clear()
