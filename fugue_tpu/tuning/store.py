"""Persistence for learned settings — the ``_tuned.json`` plan ledger.

The file is the SAME ``ops/_tuned.json`` the dense-sum kernel A/B has
always used; this module owns its ``"tuning"`` and ``"rooflines"``
top-level keys and preserves every other key verbatim on publish, so the
tenants of the file never clobber each other. Layout::

    {
      "dense_sum": {...},            # ops/segment.py's A/B winner
      "tuning": {
        "version": 1,
        "plans": {
          "<plan_fp>": {
            "ts": <last-used epoch seconds>,
            "gen": <publish generation>,
            "streams": {"<sid>": {"chunk_rows", "prefetch_depth",
                                   "obs", "converged", "evidence"}},
            "joins":   {"<sid>": {"left_bytes", "right_bytes",
                                   "right_rows", "buckets", "obs",
                                   "converged", "evidence"}}
          }
        }
      },
      "rooflines": {                 # ISSUE 18 record-only throughput folds
        "version": 1,
        "entries": {
          "<verb>|<dtype-class>|w<width>": {
            "ts", "obs", "rows", "bytes", "wall_s",
            "best_bytes_s", "best_rows_s", "last_bytes_s", "last_rows_s"
          }
        }
      }
    }

Contracts (docs/tuning.md):

- **Atomic publish**: temp-write in the same directory + ``os.replace``,
  the PR 1 checkpoint discipline — a reader (or a racing second process)
  sees the old complete file or the new complete file, never a torn one.
  Concurrent publishers re-read the file under their own process lock
  before merging, so a race loses at most the OTHER process's newest
  entry to last-writer-wins — never the file's integrity.
- **Corrupt/truncated/unreadable → defaults with ONE warning** per path
  per process; the store keeps working memory-only so a warm engine still
  converges within its own lifetime.
- **Stale-fingerprint eviction**: at most ``max_entries`` plan entries,
  least-recently-used (``ts``) dropped at publish time.
"""

import json
import logging
import os
import tempfile
import threading
import time
from typing import Any, Callable, Dict, Optional, Set

__all__ = ["TunedStore", "default_tuned_path", "resolve_tuned_path"]

DEFAULT_MAX_ENTRIES = 64

_log = logging.getLogger("fugue_tpu.tuning")

# one warning per degraded path per process — corrupt files and unwritable
# directories must not spam every run
_WARNED: Set[str] = set()
_WARNED_LOCK = threading.Lock()


def _warn_once(path: str, kind: str, detail: str) -> None:
    key = f"{kind}:{path}"
    with _WARNED_LOCK:
        if key in _WARNED:
            return
        _WARNED.add(key)
    _log.warning(
        "tuning store %s (%s): %s -- degrading to defaults "
        "(static conf; in-memory learning only)",
        kind,
        path,
        detail,
    )


def default_tuned_path() -> str:
    from ..ops import segment as _seg

    return _seg._TUNED_PATH


def resolve_tuned_path(conf: Any) -> str:
    """Conf > env > package default (same precedence as the cache dir)."""
    from ..constants import FUGUE_TPU_CONF_TUNING_PATH

    try:
        p = str(conf.get(FUGUE_TPU_CONF_TUNING_PATH, "") or "")
    except Exception:
        p = ""
    if p:
        return p
    return os.environ.get("FUGUE_TPU_TUNING_PATH", "") or default_tuned_path()


class TunedStore:
    """mtime-cached reader + read-merge-write publisher over one path."""

    def __init__(
        self, path: str, max_entries: int = DEFAULT_MAX_ENTRIES, stats: Any = None
    ):
        self.path = path
        self.max_entries = max(1, int(max_entries))
        self._lock = threading.Lock()
        self._stats = stats
        # memory overlay: what THIS process learned; authoritative when the
        # file can't be read or written (degraded mode keeps converging)
        self._mem: Dict[str, Dict[str, Any]] = {}
        self._cache: Dict[str, Dict[str, Any]] = {}
        self._cache_sig: Any = ("", -1)
        # ditto for the "rooflines" top-level key (ISSUE 18 record-only
        # per-verb throughput ceilings — docs/tuning.md)
        self._mem_roof: Dict[str, Dict[str, Any]] = {}

    def _inc(self, name: str, n: int = 1) -> None:
        if self._stats is not None:
            self._stats.inc(name, n)

    # -- reading -------------------------------------------------------------
    def _read_file(self) -> Dict[str, Any]:
        """The whole JSON document (all top-level keys), {} when absent or
        corrupt (corrupt warns once and counts a load_failure)."""
        try:
            with open(self.path, encoding="utf-8") as f:
                raw = f.read()
        except FileNotFoundError:
            return {}
        except OSError as ex:
            self._inc("load_failures")
            _warn_once(self.path, "unreadable", str(ex))
            return {}
        try:
            doc = json.loads(raw)
            if not isinstance(doc, dict):
                raise ValueError(f"top-level {type(doc).__name__}, expected object")
            return doc
        except Exception as ex:
            self._inc("load_failures")
            _warn_once(self.path, "corrupt", str(ex))
            return {}

    def _plans_of(self, doc: Dict[str, Any]) -> Dict[str, Dict[str, Any]]:
        tuning = doc.get("tuning")
        if not isinstance(tuning, dict):
            return {}
        plans = tuning.get("plans")
        if not isinstance(plans, dict):
            return {}
        # tolerate foreign/garbage entries: only dict-valued plans survive
        return {str(k): v for k, v in plans.items() if isinstance(v, dict)}

    def _roof_of(self, doc: Dict[str, Any]) -> Dict[str, Dict[str, Any]]:
        roof = doc.get("rooflines")
        if not isinstance(roof, dict):
            return {}
        entries = roof.get("entries")
        if not isinstance(entries, dict):
            return {}
        return {str(k): v for k, v in entries.items() if isinstance(v, dict)}

    @staticmethod
    def _merge_roof_entry(
        a: Dict[str, Any], b: Dict[str, Any]
    ) -> Dict[str, Any]:
        """Reconcile two VIEWS of one cumulative fold entry (the file's
        and this process's memory). Each view's totals (obs/rows/bytes/
        wall_s) and best_* rates only ever grow, so element-wise max never
        double-counts — and when one view is a superset of the other (the
        common case: our publish landed, then another process folded on
        top), max recovers exactly the fresher superset. ``last_*``/``ts``
        travel as a block from whichever view folded more recently."""
        out = dict(a)
        for k, v in b.items():
            if k == "ts" or k.startswith("last_"):
                continue
            cur = out.get(k)
            if isinstance(v, (int, float)) and isinstance(cur, (int, float)):
                out[k] = max(cur, v)
            elif cur is None:
                out[k] = v
        src = b if float(b.get("ts", 0) or 0) >= float(a.get("ts", 0) or 0) else a
        for k, v in src.items():
            if k == "ts" or k.startswith("last_"):
                out[k] = v
        return out

    def _overlay_roof_locked(
        self, entries: Dict[str, Dict[str, Any]]
    ) -> Dict[str, Dict[str, Any]]:
        for k, v in self._mem_roof.items():
            cur = entries.get(k)
            entries[k] = (
                dict(v) if cur is None else self._merge_roof_entry(cur, v)
            )
        return entries

    def rooflines(self) -> Dict[str, Dict[str, Any]]:
        """All roofline entries (``<verb>|<dtype-class>|w<width>`` →
        throughput fold), the file's view reconciled with this process's
        memory (:meth:`_merge_roof_entry`)."""
        with self._lock:
            return self._overlay_roof_locked(self._roof_of(self._read_file()))

    def plans(self) -> Dict[str, Dict[str, Any]]:
        """All plan entries, file overlaid with this process's memory
        (memory wins — it is at least as new as what we last published)."""
        with self._lock:
            try:
                st = os.stat(self.path)
                sig = (self.path, st.st_mtime_ns, st.st_size)
            except OSError:
                sig = (self.path, -1, -1)
            if sig != self._cache_sig:
                self._cache = self._plans_of(self._read_file())
                self._cache_sig = sig
                self._inc("loads")
            merged = dict(self._cache)
            merged.update(self._mem)
            return merged

    def plan_entry(self, fp: str) -> Optional[Dict[str, Any]]:
        return self.plans().get(fp)

    def count(self) -> int:
        return len(self.plans())

    def remember(self, fp: str, entry: Dict[str, Any]) -> None:
        """In-memory-only update (observation bookkeeping on an already
        converged entry) — no file write, no eviction."""
        with self._lock:
            self._mem[fp] = dict(entry)

    # -- publishing ----------------------------------------------------------
    def publish(
        self, fp: str, mutate: Callable[[Dict[str, Any]], Optional[Dict[str, Any]]]
    ) -> bool:
        """Apply ``mutate(entry_or_empty) -> entry | None`` to plan ``fp``
        and persist. ``None`` means "nothing learned" — no write happens.
        Returns True when a publish (file or memory) occurred."""
        with self._lock:
            doc = self._read_file()
            plans = self._plans_of(doc)
            plans.update(self._mem)
            cur = plans.get(fp)
            entry = mutate(dict(cur) if isinstance(cur, dict) else {})
            if entry is None:
                return False
            entry["ts"] = time.time()
            entry["gen"] = int(entry.get("gen", 0)) + 1
            plans[fp] = entry
            self._mem[fp] = entry
            # stale-fingerprint eviction: LRU by last-used timestamp
            while len(plans) > self.max_entries:
                victim = min(
                    plans, key=lambda k: float(plans[k].get("ts", 0) or 0)
                )
                plans.pop(victim)
                self._mem.pop(victim, None)
                self._inc("evictions")
            doc.setdefault("tuning", {})
            doc["tuning"] = {"version": 1, "plans": plans}
            if self._write_doc_locked(doc):
                self._cache = plans
                self._inc("publishes")
            return True

    def _write_doc_locked(self, doc: Dict[str, Any]) -> bool:
        """Atomic whole-document write (temp in the same dir +
        ``os.replace``), refreshing the mtime cache signature. Caller
        holds ``self._lock``. False (after the one-shot unwritable
        warning) when the path can't be written — memory-only from
        there."""
        try:
            d = os.path.dirname(self.path) or "."
            os.makedirs(d, exist_ok=True)
            fd, tmp = tempfile.mkstemp(dir=d, prefix="._tuned_", suffix=".json")
            try:
                with os.fdopen(fd, "w", encoding="utf-8") as f:
                    json.dump(doc, f, indent=1, sort_keys=True)
                os.replace(tmp, self.path)
            finally:
                if os.path.exists(tmp):  # replace failed
                    try:
                        os.remove(tmp)
                    except OSError:
                        pass
            try:
                st = os.stat(self.path)
                self._cache_sig = (self.path, st.st_mtime_ns, st.st_size)
            except OSError:
                self._cache_sig = (self.path, -1, -1)
            return True
        except OSError as ex:
            # unwritable store: memory-only from here on, one warning
            _warn_once(self.path, "unwritable", str(ex))
            return False

    def publish_rooflines(
        self, mutate: Callable[[Dict[str, Any]], Optional[Dict[str, Any]]]
    ) -> bool:
        """Apply ``mutate(entries) -> entries | None`` to the
        ``"rooflines"`` top-level key and persist — the same
        read-merge-write + atomic-replace + LRU discipline as
        :meth:`publish`, preserving every other key (``tuning``,
        ``dense_sum``) verbatim. ``None`` = nothing to record."""
        with self._lock:
            doc = self._read_file()
            entries = self._overlay_roof_locked(self._roof_of(doc))
            out = mutate(dict(entries))
            if out is None:
                return False
            # stale-entry eviction: LRU by last-fold timestamp, the same
            # bound as plan entries (the two tables share max_entries)
            while len(out) > self.max_entries:
                victim = min(out, key=lambda k: float(out[k].get("ts", 0) or 0))
                out.pop(victim)
                self._inc("evictions")
            self._mem_roof = {k: dict(v) for k, v in out.items()}
            doc["rooflines"] = {"version": 1, "entries": out}
            if self._write_doc_locked(doc):
                self._inc("roofline_publishes")
            return True
