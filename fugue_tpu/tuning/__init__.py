"""Cost-based adaptive execution (docs/tuning.md): a feedback layer that
re-derives the engine's performance knobs — stream chunk size, prefetch
depth, shuffle bucket count, join-side size estimates — from its own
telemetry, keyed by plan fingerprint and persisted to ``ops/_tuned.json``
so a warm server converges across submissions and survives restart.
``fugue.tpu.tuning.enabled=false`` restores the static-conf engine
bit-identically."""

from .roofline import RooflineRecorder, install_verb_observer, rooflines_enabled
from .stats import TuningStats
from .store import TunedStore, default_tuned_path, resolve_tuned_path
from .tuner import (
    ExchangeHandle,
    StreamHandle,
    Tuner,
    adjust_buckets,
    adjust_stream,
    current_scope,
    describe_tuning,
    plan_fingerprint,
    run_scope,
    tuning_enabled,
)

__all__ = [
    "ExchangeHandle",
    "RooflineRecorder",
    "StreamHandle",
    "TunedStore",
    "Tuner",
    "TuningStats",
    "adjust_buckets",
    "adjust_stream",
    "current_scope",
    "default_tuned_path",
    "describe_tuning",
    "install_verb_observer",
    "plan_fingerprint",
    "resolve_tuned_path",
    "rooflines_enabled",
    "run_scope",
    "tuning_enabled",
]
