from .mesh import (
    ROW_AXIS,
    build_mesh,
    num_row_shards,
    pad_rows,
    replicated_sharding,
    row_sharding,
)
from .distributed import initialize_distributed, is_multihost, process_info

__all__ = [
    "ROW_AXIS",
    "build_mesh",
    "num_row_shards",
    "pad_rows",
    "replicated_sharding",
    "row_sharding",
    "initialize_distributed",
    "is_multihost",
    "process_info",
]
