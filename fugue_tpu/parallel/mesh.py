"""Device-mesh management for the TPU engine.

The engine's distribution model (SURVEY §5.8): one ``jax.sharding.Mesh``
whose first axis ("rows") shards dataframe rows (data parallel over
partitions — the reference's only parallelism, §2.14); additional axes are
available to compiled UDFs for model-style sharding. Multi-host: the mesh is
built over ALL processes' devices (``jax.devices()``), so collectives ride
ICI within a slice and DCN across slices.
"""

from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

ROW_AXIS = "rows"


def build_mesh(
    mesh_shape: Optional[Sequence[int]] = None,
    axis_names: Optional[Sequence[str]] = None,
    devices: Optional[List[Any]] = None,
):
    """Build a Mesh; default is 1-D over all devices with axis "rows"."""
    import jax
    from jax.sharding import Mesh

    devs = devices if devices is not None else jax.devices()
    if mesh_shape is None:
        mesh_shape = (len(devs),)
    if axis_names is None:
        axis_names = (ROW_AXIS,) + tuple(f"ax{i}" for i in range(1, len(mesh_shape)))
    n = int(np.prod(mesh_shape))
    if n != len(devs):
        devs = devs[:n]
    arr = np.array(devs).reshape(tuple(mesh_shape))
    return Mesh(arr, tuple(axis_names))


def row_sharding(mesh: Any):
    """NamedSharding placing axis 0 on the mesh row axis."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P(ROW_AXIS))


def replicated_sharding(mesh: Any):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P())


def num_row_shards(mesh: Any) -> int:
    return mesh.shape[ROW_AXIS]


def pad_rows(n: int, shards: int) -> int:
    """Rows after padding to a multiple of the shard count."""
    if shards <= 1:
        return n
    return ((n + shards - 1) // shards) * shards
