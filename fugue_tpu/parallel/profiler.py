"""Tracing/profiling hooks (SURVEY §5.1).

The reference has no built-in tracing (closest: the engine ``log``); the
TPU build adds native JAX profiler integration: traces capture XLA
compilation, device compute, and transfers, viewable in TensorBoard or
Perfetto.

Usage::

    from fugue_tpu.parallel.profiler import profile

    with profile("/tmp/fugue_trace"):
        fa.transform(df, fn, engine="tpu")

Conf-driven: setting ``fugue.tpu.profile.dir`` on an engine makes
``profiled_engine_context`` trace everything inside the context.

Pairs with the host-side span tracer (``fugue_tpu/obs``, see
``docs/observability.md``): with ``fugue.tpu.trace.enabled`` on, every
engine-verb and streaming-chunk span also enters a
``jax.profiler.TraceAnnotation`` of the same name, so a capture taken
inside :func:`profile` shows the host span names on the XLA device
timeline — the two trace sources line up in Perfetto.
"""

from contextlib import contextmanager
from typing import Any, Iterator, Optional

FUGUE_TPU_CONF_PROFILE_DIR = "fugue.tpu.profile.dir"


@contextmanager
def profile(log_dir: str) -> Iterator[None]:
    """Capture a JAX profiler trace into ``log_dir``."""
    import jax

    with jax.profiler.trace(log_dir, create_perfetto_trace=False):
        yield


@contextmanager
def annotate(name: str) -> Iterator[None]:
    """Name a region in the trace (shows up in the profiler timeline)."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield


@contextmanager
def profiled_engine_context(engine: Any = None, conf: Any = None) -> Iterator[Any]:
    """``fa.engine_context`` that traces when the conf sets a profile dir."""
    from ..execution.api import engine_context

    with engine_context(engine, conf) as e:
        log_dir = e.conf.get(FUGUE_TPU_CONF_PROFILE_DIR, "")
        if log_dir == "":
            yield e
        else:
            with profile(log_dir):
                yield e
