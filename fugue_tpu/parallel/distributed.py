"""Multi-host (pod-scale) initialization.

The reference delegates multi-node transport to Spark/Dask/Ray clusters
(SURVEY §5.8); here the equivalent is JAX's multi-controller runtime: every
host runs the same program, ``jax.distributed.initialize`` wires the hosts
into one runtime, and ``jax.devices()`` then spans the whole pod slice — so
the engine's mesh (built over all devices) automatically scales collectives
over ICI within a slice and DCN across slices with no framework changes.

Typical pod usage::

    from fugue_tpu.parallel import initialize_distributed
    import fugue_tpu.api as fa

    initialize_distributed()          # on every host (env-driven on TPU)
    with fa.engine_context("tpu"):
        fa.transform(...)             # rows sharded across ALL hosts' chips
"""

from typing import Any, Optional


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    **kwargs: Any,
) -> None:
    """Initialize the multi-host JAX runtime (idempotent).

    On TPU pods all arguments are discovered from the environment; on other
    platforms pass coordinator/num_processes/process_id explicitly.
    """
    import jax

    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            **kwargs,
        )
    except RuntimeError as e:  # already initialized → idempotent
        msg = str(e).lower()
        if "already initialized" not in msg and "called once" not in msg:
            raise


def is_multihost() -> bool:
    import jax

    return jax.process_count() > 1


def process_info() -> dict:
    """Host-level topology facts for logging/diagnostics."""
    import jax

    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_device_count": jax.local_device_count(),
        "global_device_count": jax.device_count(),
    }
