"""Test-harness plugins: parameterize tests over backends.

Parity with the reference (`fugue/test/plugins.py:39-96,99,226` +
``fugue_test/fixtures.py``): backends register a session factory; suites
bind to one with ``@fugue_test_suite("name")``; single tests parameterize
with ``@with_backend("native", "jax")``.
"""

from contextlib import contextmanager
from typing import TYPE_CHECKING, Any, Callable, Dict, Iterator, List, Optional, Tuple

import pytest

if TYPE_CHECKING:  # pragma: no cover - typing only; keep plugin import cheap
    from ..execution.execution_engine import ExecutionEngine

_TEST_BACKENDS: Dict[str, "FugueTestBackend"] = {}
_PYTEST_CONFIG: List[Any] = [None]


def _register_marker(name: str) -> None:
    config = _PYTEST_CONFIG[0]
    if config is not None:
        config.addinivalue_line(
            "markers", f"{name}: tests bound to the {name!r} fugue-tpu backend"
        )


def pytest_configure(config: Any) -> None:
    """pytest11 hook: register one marker per backend; the config is kept so
    backends registered later (nested conftests) also get markers."""
    _PYTEST_CONFIG[0] = config
    for name in _TEST_BACKENDS:
        _register_marker(name)


class FugueTestBackend:
    """Subclass + register to expose a backend to the test harness."""

    name = ""
    session_conf: Dict[str, Any] = {}

    @classmethod
    @contextmanager
    def session_context(cls) -> Iterator[Any]:
        """Yield a live session object (engine spec) for the backend."""
        yield cls.name

    @classmethod
    @contextmanager
    def engine_context(cls) -> Iterator["ExecutionEngine"]:
        from ..execution.factory import make_execution_engine

        with cls.session_context() as session:
            engine = make_execution_engine(session, dict(cls.session_conf))
            try:
                yield engine
            finally:
                engine.stop()


def fugue_test_backend(cls: type) -> type:
    """Class decorator registering a FugueTestBackend."""
    assert issubclass(cls, FugueTestBackend) and cls.name != ""
    _TEST_BACKENDS[cls.name] = cls  # type: ignore
    _register_marker(cls.name)  # covers backends registered after configure
    return cls


def get_test_backend(name: str) -> "FugueTestBackend":
    if name not in _TEST_BACKENDS:
        raise KeyError(
            f"test backend {name!r} is not registered; have {sorted(_TEST_BACKENDS)}"
        )
    return _TEST_BACKENDS[name]  # type: ignore


def fugue_test_suite(backend: str, mark_test: bool = False) -> Callable[[type], type]:
    """Bind a test-suite class to a backend: injects ``make_engine`` and a
    class-scoped engine fixture (reference ``@fugue_test_suite``)."""

    def deco(cls: type) -> type:
        backend_cls = get_test_backend(backend)

        def make_engine(self: Any) -> "ExecutionEngine":
            from ..execution.factory import make_execution_engine

            return make_execution_engine(backend, dict(backend_cls.session_conf))

        cls.make_engine = make_engine  # type: ignore
        cls.backend = backend  # type: ignore
        if mark_test:
            cls = pytest.mark.__getattr__(backend)(cls)
        return cls

    return deco


def with_backend(*backends: str) -> Callable:
    """Parameterize one test over engines: the test receives ``backend_engine``."""

    def deco(func: Callable) -> Callable:
        @pytest.mark.parametrize("fugue_backend_name", list(backends))
        def wrapper(*args: Any, fugue_backend_name: str, **kwargs: Any) -> Any:
            backend_cls = get_test_backend(fugue_backend_name)
            with backend_cls.engine_context() as engine:
                return func(*args, backend_engine=engine, **kwargs)

        wrapper.__name__ = func.__name__
        return wrapper

    return deco


# ---------------------------------------------------------------------------
# built-in backends
# ---------------------------------------------------------------------------


@fugue_test_backend
class NativeTestBackend(FugueTestBackend):
    name = "native"


@fugue_test_backend
class PandasTestBackend(FugueTestBackend):
    name = "pandas"


@fugue_test_backend
class JaxTestBackend(FugueTestBackend):
    """The jax engine on whatever devices are visible (tests pin CPU mesh)."""

    name = "jax"
