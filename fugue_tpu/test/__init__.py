from .plugins import (
    FugueTestBackend,
    fugue_test_backend,
    fugue_test_suite,
    get_test_backend,
    with_backend,
)
