"""Fleet coordination: cross-replica single-flight and a failover client.

PR 10 proved one :class:`~fugue_tpu.serve.EngineServer`; "millions of
users" (ROADMAP 3) needs a replicated tier where any single process can
die mid-run without losing or double-executing a submission. Two pieces
live here (docs/serving.md "Fleet"):

:class:`FleetCoordinator` — replicas sharing a disk store directory
(``fugue.tpu.cache.dir``) collapse identical submissions ACROSS servers.
Before executing a fingerprintable plan, a replica claims its key in the
shared store (``ArtifactStore.try_claim`` — atomic ``O_CREAT|O_EXCL``
create, lease expiry + same-host dead-pid detection make a dead owner's
claim stealable). The claim owner executes and publishes the yielded
frames (host pandas + schema, atomic temp-write+rename like every other
store publish); every other replica's waiter polls and serves the
published artifact instead of re-executing. Published results double as
a cluster-wide serve-result cache: a later identical submission on ANY
replica is answered from the store without queueing.

:class:`FleetClient` — the balancer side: reads ``/readyz`` (queue
depth / budget / store health) from every replica, places each
submission on the least-loaded accepting one, sheds on fleet-wide 503,
and — holding the submission payload and an idempotency key — fails a
dead replica's in-flight submissions over to a survivor under the SAME
key. Combined with the claim protocol and each replica's submission
journal (:mod:`~fugue_tpu.serve.journal`), the observable effect is
exactly-once even though execution is at-least-once.

Exactly-once caveats, stated plainly: a LIVE owner that outruns its
lease can be raced by a stealer — both executions are the same
deterministic plan over the same bytes, so the published artifact is
bit-identical whichever wins the atomic rename (set
``fugue.tpu.serve.fleet.lease_s`` above your slowest plan to avoid the
wasted work). Output sinks (``show``/``save``) run once per *executing*
replica, not once per fleet — unfingerprintable plans never enter the
protocol at all and always execute locally.
"""

import os
import threading
import time
import uuid as _uuid
from typing import Any, Dict, List, Optional, Tuple

from ..workflow._checkpoint import _atomic_publish, _best_effort_remove

__all__ = [
    "FleetCoordinator",
    "FleetClient",
    "FleetSubmission",
    "FleetResult",
    "view_result_key",
    "parse_view_result_name",
]

# continuous views (docs/views.md) publish each generation under this
# grammar; "--" is banned in view ids so the name parses unambiguously
_VIEW_RESULT_PREFIX = "view--"
_RESULT_SUFFIX = ".result.pkl"


def view_result_key(view_id: str, generation: int) -> str:
    """Fleet result-store key of one view generation."""
    return f"{_VIEW_RESULT_PREFIX}{view_id}--g{int(generation):08d}"


def parse_view_result_name(name: str) -> Optional[Tuple[str, int]]:
    """``(view_id, generation)`` from a results-dir filename, or None
    for an ordinary request-scoped result."""
    if not name.startswith(_VIEW_RESULT_PREFIX) or not name.endswith(
        _RESULT_SUFFIX
    ):
        return None
    stem = name[len(_VIEW_RESULT_PREFIX): -len(_RESULT_SUFFIX)]
    vid, sep, g = stem.rpartition("--g")
    if not sep or not vid or not g.isdigit():
        return None
    return vid, int(g)


class FleetResult:
    """A rehydrated cross-replica result: duck-types the slice of
    ``FugueWorkflowResult`` the serving layer reads (``.yields`` of
    objects carrying ``.result`` frames)."""

    class _Yield:
        __slots__ = ("result",)

        def __init__(self, df: Any):
            self.result = df

    def __init__(self, yields: Dict[str, Any]):
        self.yields = {k: FleetResult._Yield(df) for k, df in yields.items()}


class FleetCoordinator:
    """Cross-replica single-flight + result cache over a shared store."""

    def __init__(
        self,
        store: Any,
        replica_id: str,
        lease_s: float = 30.0,
        poll_s: float = 0.05,
        max_results: int = 256,
        stats: Any = None,
        injector: Any = None,
        log: Any = None,
    ):
        self.store = store
        self.replica_id = replica_id
        self.lease_s = float(lease_s)
        self.poll_s = max(0.005, float(poll_s))
        self.max_results = int(max_results)
        self.results_dir = os.path.join(store.root, "serve")
        self._stats = stats
        self._injector = injector
        self._log = log
        os.makedirs(self.results_dir, exist_ok=True)

    def _inc(self, name: str, n: int = 1) -> None:
        if self._stats is not None:
            self._stats.inc(name, n)

    def _result_path(self, key: str) -> str:
        return os.path.join(self.results_dir, key + ".result.pkl")

    # -- the result artifact -------------------------------------------------
    def load_result(self, key: str) -> Optional[Dict[str, Any]]:
        """The published ``{yield_name: (pandas, schema_str)}`` payload,
        or None. Torn/corrupt payloads are deleted and read as absent —
        a miss re-executes; it can never serve wrong bytes."""
        path = self._result_path(key)
        try:
            with open(path, "rb") as f:
                blob = f.read()
        except FileNotFoundError:
            return None
        except OSError:
            return None
        try:
            import cloudpickle

            payload = cloudpickle.loads(blob)
            os.utime(path, None)  # LRU touch
            return payload
        except Exception:
            _best_effort_remove(path)
            return None

    def publish_result(self, key: str, frames: Dict[str, Any]) -> bool:
        """Atomically publish the claim owner's yielded frames and
        release the claim. Racing publishers of the same key write
        identical content by construction; the last rename wins whole."""
        import cloudpickle

        final = self._result_path(key)
        tmp = f"{final}.__tmp_{_uuid.uuid4().hex}"
        try:
            with open(tmp, "wb") as f:
                f.write(cloudpickle.dumps(frames))
            _atomic_publish(tmp, final)
        except Exception as ex:
            _best_effort_remove(tmp)
            if self._log is not None:
                self._log.warning(
                    "fleet: result publish of %s failed: %s", key[:12], ex
                )
            self.release(key)
            return False
        self._inc("fleet_publishes")
        self._evict_results()
        self.release(key)
        return True

    def release(self, key: str) -> None:
        self.store.release_claim(key, self.replica_id)

    def remove_result(self, key: str) -> bool:
        """Delete one published payload (the views maintainer retires
        superseded generations through this). True if a file went away."""
        path = self._result_path(key)
        existed = os.path.exists(path)
        _best_effort_remove(path)
        return existed and not os.path.exists(path)

    def _evict_results(self) -> None:
        """mtime-LRU count cap, the ArtifactStore eviction discipline.

        Standing views are NOT request-scoped (ISSUE 20 small fix): the
        latest generation per view is pinned — it must stay servable for
        ``GET /serve/view/<id>`` until a newer generation supersedes it,
        however much interactive traffic churns the LRU. Pinned files
        are excluded from both the count and the eviction; superseded
        generations age out like any request result (and the maintainer
        retires them proactively)."""
        if self.max_results <= 0:
            return
        try:
            names = [
                n for n in os.listdir(self.results_dir)
                if n.endswith(_RESULT_SUFFIX)
            ]
        except OSError:
            return
        latest_gen: Dict[str, int] = {}
        for n in names:
            parsed = parse_view_result_name(n)
            if parsed is not None:
                vid, gen = parsed
                latest_gen[vid] = max(gen, latest_gen.get(vid, 0))
        evictable = [
            n for n in names
            if (
                (p := parse_view_result_name(n)) is None
                or p[1] < latest_gen.get(p[0], 0)
            )
        ]
        if len(evictable) <= self.max_results:
            return
        entries = []
        for n in evictable:
            p = os.path.join(self.results_dir, n)
            try:
                entries.append((os.stat(p).st_mtime, p))
            except OSError:
                continue
        entries.sort()
        for _mt, p in entries[: max(0, len(entries) - self.max_results)]:
            _best_effort_remove(p)

    # -- the single-flight protocol ------------------------------------------
    def acquire(self, key: str) -> Tuple[str, Optional[Dict[str, Any]]]:
        """Block until this replica either owns the claim for ``key``
        (``("owner", None)`` — caller executes and must publish or
        release) or another replica's published result is servable
        (``("result", payload)``). Bounded by the holder's lease: a dead
        owner's claim is stolen at latest ``lease_s`` after its last
        write, so the wait can't wedge."""
        stole = False
        while True:
            payload = self.load_result(key)
            if payload is not None:
                self._inc("fleet_result_hits")
                return "result", payload
            holder = self.store.read_claim(key)
            owned, _cur = self.store.try_claim(key, self.replica_id, self.lease_s)
            if owned:
                # the owner may have published between our result check
                # and the claim write — serve it rather than re-run
                payload = self.load_result(key)
                if payload is not None:
                    self.release(key)
                    self._inc("fleet_result_hits")
                    return "result", payload
                stole = holder is not None and holder.get("owner") not in (
                    None,
                    self.replica_id,
                )
                self._inc("fleet_claims")
                if stole:
                    self._inc("fleet_claim_steals")
                    # flight recorder (ISSUE 18): a stolen claim is a
                    # recovery-ladder event — the prior owner died (or
                    # outran its lease) mid-execution
                    from ..obs.events import get_event_log

                    get_event_log().emit(
                        "fleet.claim_steal",
                        key=key[:12],
                        owner=self.replica_id,
                        prev_owner=(holder or {}).get("owner"),
                    )
                # the serve.claim fault site fires in the CALLER, after it
                # has recorded ownership — a fault between claim write and
                # execution start must still release the claim on unwind
                return "owner", None
            self._inc("fleet_waits")
            time.sleep(self.poll_s)

    def lookup(self, key: str) -> Optional[Dict[str, Any]]:
        """Non-blocking: the published result if present (the submit-time
        fast path — a warm fleet answers without queueing)."""
        payload = self.load_result(key)
        if payload is not None:
            self._inc("fleet_result_hits")
        return payload


class FleetSubmission:
    """A client-side handle: which replica holds the submission, plus
    everything needed to replay it elsewhere under the same key."""

    def __init__(
        self, replica: int, sid: str, payload: Dict[str, Any], deduped: bool
    ):
        self.replica = replica
        self.sid = sid
        self.payload = payload
        self.deduped = deduped
        self.failovers = 0

    @property
    def idempotency_key(self) -> str:
        return self.payload["idempotency_key"]


class FleetClient:
    """Least-loaded placement + idempotent failover over N replicas.

    ``replicas`` is a list of ``(host, port)`` pairs (or prebuilt
    :class:`~fugue_tpu.serve.ServeHttpClient` objects). Every submission
    carries an idempotency key (one is minted when the caller brings
    none) so a replay onto ANY replica — after a crash, a timeout, or a
    retry — maps onto one observable submission.
    """

    # the worker_lost taxonomy (ISSUE 14): ServeWorkerLost (a replica
    # dead or stateless post-admit — WorkerLostError, retryable) and raw
    # transport failures trigger failover; a workflow's own error
    # (rehydrated from the result payload) never does — re-running a
    # deterministically failing plan elsewhere just fails again, and
    # would re-run its side effects. KeyError stays for pre-taxonomy
    # callers' unknown-id shape.
    from ..resilience import WorkerLostError as _WL

    _FAILOVER_ERRORS = (ConnectionError, OSError, KeyError, _WL)

    def __init__(
        self,
        replicas: List[Any],
        connect_timeout: float = 5.0,
        read_timeout: float = 60.0,
    ):
        from .client import ServeHttpClient

        self._clients: List[Any] = [
            r
            if isinstance(r, ServeHttpClient)
            else ServeHttpClient(
                r[0], r[1], connect_timeout=connect_timeout, read_timeout=read_timeout
            )
            for r in replicas
        ]
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {}

    def _inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counters)

    @property
    def replicas(self) -> int:
        return len(self._clients)

    # -- federated metrics (ISSUE 18 tentpole, piece 3) -----------------------
    def federated_span_metrics(self) -> Tuple[Any, List[Optional[str]]]:
        """Merge every reachable replica's ``/metrics/snapshot`` into one
        fresh :class:`~fugue_tpu.obs.metrics.SpanMetrics`. The encoding is
        associative and commutative, so the merged histogram's per-series
        count equals the SUM of the per-replica counts exactly — nothing
        is estimated. Returns ``(merged, replica_ids)`` (a None replica id
        means the process served metrics without a serve front end)."""
        from ..obs.metrics import SpanMetrics

        merged = SpanMetrics()
        replicas: List[Optional[str]] = []
        for cl in self._clients:
            try:
                snap = cl.metrics_snapshot()
            except Exception:
                self._inc("metrics_unreachable")
                continue
            merged.merge(snap.get("spans") or {})
            replicas.append(snap.get("replica"))
        self._inc("metrics_federations")
        return merged, replicas

    def federated_metrics(self) -> str:
        """ONE fleet-level Prometheus text exposition: per-replica span
        histograms merged via :meth:`federated_span_metrics` and rendered
        through the same ``to_prometheus_text`` the per-replica
        ``/metrics`` route uses — scrape one page for the whole fleet."""
        from ..obs.prom import to_prometheus_text

        merged, _replicas = self.federated_span_metrics()
        return to_prometheus_text(span_metrics=merged)

    # -- placement -----------------------------------------------------------
    def readyz_all(self) -> List[Optional[Dict[str, Any]]]:
        """One ``/readyz`` snapshot per replica; None = unreachable."""
        out: List[Optional[Dict[str, Any]]] = []
        for cl in self._clients:
            try:
                out.append(cl.readyz())
            except Exception:
                out.append(None)
        return out

    @staticmethod
    def _placeable(rz: Optional[Dict[str, Any]]) -> bool:
        if rz is None or not rz.get("accepting", False):
            return False
        if rz.get("status") == "store_unwritable":
            # drain: a replica whose shared disk died must not take new
            # work it can neither journal nor publish
            return False
        return rz.get("queue_free", 0) > 0 or rz.get("status") == "ready"

    def _candidates(self) -> List[int]:
        """Replica indexes ordered least-loaded first (queue depth +
        active runs, ties by index for determinism)."""
        snaps = self.readyz_all()
        scored = [
            (rz.get("queue_depth", 0) + rz.get("active_runs", 0), i)
            for i, rz in enumerate(snaps)
            if self._placeable(rz)
        ]
        scored.sort()
        return [i for _s, i in scored]

    # -- the session API -----------------------------------------------------
    def submit(
        self,
        dag: Any,
        tenant: str = "default",
        priority: Optional[int] = None,
        idempotency_key: Optional[str] = None,
        reserve_bytes: Optional[int] = None,
    ) -> FleetSubmission:
        """Place one submission on the least-loaded accepting replica.
        Raises :class:`~fugue_tpu.serve.ServeRejected` with reason
        ``fleet_unavailable`` when no replica can take it (the
        fleet-wide shed)."""
        from .server import ServeRejected

        payload = {
            "dag": dag,
            "tenant": tenant,
            "priority": priority,
            "idempotency_key": idempotency_key or "fleet-" + _uuid.uuid4().hex,
            "reserve_bytes": reserve_bytes,
        }
        candidates = self._candidates()
        last: Optional[BaseException] = None
        for idx in candidates:
            try:
                sub = self._clients[idx].submit(**payload)
                self._inc("submitted")
                return FleetSubmission(
                    idx, sub["id"], payload, bool(sub.get("deduped"))
                )
            except ServeRejected as ex:
                last = ex  # overloaded between snapshot and submit: next
            except self._FAILOVER_ERRORS as ex:
                last = ex
                self._inc("submit_failovers")
        self._inc("shed")
        raise ServeRejected(
            "fleet_unavailable",
            f"no replica of {len(self._clients)} accepted"
            + (f" (last: {type(last).__name__}: {last})" if last else ""),
        )

    def result(
        self, sub: FleetSubmission, timeout: Optional[float] = None
    ) -> Dict[str, Any]:
        """Block for the submission's frames, failing over to a survivor
        (same idempotency key, same payload) when its replica dies. The
        replica-side journal + claim protocol make the replay a dedup
        hit whenever the original execution published."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            remaining = (
                None if deadline is None else max(0.05, deadline - time.monotonic())
            )
            try:
                return self._clients[sub.replica].result(sub.sid, timeout=remaining)
            except TimeoutError:
                raise
            except self._FAILOVER_ERRORS:
                self._failover(sub, deadline)

    def _failover(self, sub: FleetSubmission, deadline: Optional[float]) -> None:
        """Re-place ``sub`` on a surviving replica under the SAME
        idempotency key; mutates the handle in place."""
        from .server import ServeRejected

        failed = sub.replica
        while True:
            # prefer survivors; the replica that just failed us is a last
            # resort (it may have restarted and replayed its journal)
            cand = self._candidates()
            cand = [i for i in cand if i != failed] + [i for i in cand if i == failed]
            for idx in cand:
                try:
                    re = self._clients[idx].submit(**sub.payload)
                    sub.replica = idx
                    sub.sid = re["id"]
                    sub.failovers += 1
                    self._inc("failovers")
                    from ..obs.events import get_event_log

                    get_event_log().emit(
                        "fleet.failover",
                        key=sub.idempotency_key[:24],
                        from_replica=failed,
                        to_replica=idx,
                    )
                    return
                except (ServeRejected, *self._FAILOVER_ERRORS):
                    continue
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(
                    f"failover of {sub.idempotency_key} found no live replica"
                )
            time.sleep(0.1)
