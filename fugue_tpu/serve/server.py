"""The long-lived engine server: N concurrent sessions, one engine.

Everything below PR 9 optimizes ONE workflow at a time; the north star —
heavy traffic from many users — is an *execution environment serving
many jobs* (arXiv:2301.07896), with per-job scheduling over a shared
runtime (arXiv:2209.06146). :class:`EngineServer` is that environment,
in-process: it owns one live :class:`~fugue_tpu.execution.ExecutionEngine`
(its mesh, jit cache, result/delta cache, stats) and admits
``workflow.run`` submissions from any number of concurrent sessions
through an admission/scheduling queue.

The moving parts (docs/serving.md):

- **Admission**: a bounded queue (``fugue.tpu.serve.queue_depth``) —
  past it submissions are REJECTED, and ``/readyz`` reports overloaded
  *before* that so a load balancer can shed first. Tenant byte budgets
  (``fugue.tpu.serve.tenant.<id>.budget_bytes``) gate admission against
  the live charged-byte ledger (:class:`~fugue_tpu.serve.tenant.TenantAccounts`).
- **Scheduling**: ``fugue.tpu.serve.max_concurrent`` worker threads;
  lowest priority number first, FIFO within a priority, and a queued
  execution's effective priority improves one level per
  ``fugue.tpu.serve.aging_s`` waited — starvation-free by construction.
- **Single-flight dedup**: submissions whose post-optimization plan
  fingerprint (:mod:`fugue_tpu.serve.dedup`) matches an in-flight
  execution JOIN it — one execution, every waiter gets the result.
  A canceled waiter detaches without canceling the shared execution.
- **Attribution**: every execution runs inside
  ``run_labels(tenant=...)``, so the PR 6 span histograms
  (``engine.stats()["latency"]``, ``/metrics``) carry a ``tenant``
  label — bounded-cardinality via the same rotation as ``run``.
"""

import os
import socket
import threading
import time
import uuid as _uuid
from collections import OrderedDict
from contextlib import nullcontext
from typing import Any, Dict, List, Optional

from ..constants import (
    FUGUE_TPU_CONF_DIST_HB_DIR,
    FUGUE_TPU_CONF_DIST_HB_INTERVAL_S,
    FUGUE_TPU_CONF_SERVE_AGING_S,
    FUGUE_TPU_CONF_SERVE_DEFAULT_PRIORITY,
    FUGUE_TPU_CONF_SERVE_FLEET_ENABLED,
    FUGUE_TPU_CONF_SERVE_FLEET_LEASE_S,
    FUGUE_TPU_CONF_SERVE_FLEET_MAX_RESULTS,
    FUGUE_TPU_CONF_SERVE_FLEET_POLL_S,
    FUGUE_TPU_CONF_SERVE_JOURNAL_DIR,
    FUGUE_TPU_CONF_SERVE_JOURNAL_MAX_BYTES,
    FUGUE_TPU_CONF_SERVE_MAX_CONCURRENT,
    FUGUE_TPU_CONF_SERVE_MAX_TENANTS,
    FUGUE_TPU_CONF_SERVE_QUEUE_DEPTH,
    FUGUE_TPU_CONF_SERVE_REPLICA_ID,
    FUGUE_TPU_CONF_SERVE_RESERVE_BYTES,
    FUGUE_TPU_CONF_SERVE_RETAIN,
    FUGUE_TPU_CONF_TRACE_SPOOL_DIR,
    FUGUE_TPU_CONF_VIEWS_ENABLED,
)
from ..resilience import SITE_SERVE_CLAIM, SITE_SERVE_JOURNAL, FaultInjector
from ..workflow.factory import build_workflow, is_workflow_factory
from .dedup import submission_key
from .fleet import FleetCoordinator, FleetResult
from .journal import SubmissionJournal
from .stats import ServeStats
from .tenant import TenantAccounts, TenantPolicy, tenant_policy

__all__ = [
    "EngineServer",
    "ServeRejected",
    "Submission",
    "SubmissionCanceled",
]


class ServeRejected(Exception):
    """Admission refused (queue full / tenant budget / server stopped)."""

    def __init__(self, reason: str, detail: str = ""):
        super().__init__(f"submission rejected: {reason}" + (f" ({detail})" if detail else ""))
        self.reason = reason


class SubmissionCanceled(Exception):
    """``result()`` called on a canceled submission."""


class _Execution:
    """One unit of engine work, shared by every deduped waiter."""

    __slots__ = (
        "key", "dag", "tenant", "priority", "seq", "submitted_at",
        "started_at", "finished_at", "started", "state", "result",
        "error", "waiters", "done", "trace",
    )

    def __init__(self, key: Optional[str], dag: Any, tenant: str,
                 priority: int, seq: int):
        self.key = key
        self.trace: Dict[str, str] = {}
        self.dag = dag
        self.tenant = tenant
        self.priority = int(priority)
        self.seq = seq
        self.submitted_at = time.monotonic()
        self.started_at: Optional[float] = None
        self.finished_at: Optional[float] = None
        self.started = False
        self.state = "queued"  # queued | running | done | failed | canceled
        self.result: Any = None
        self.error: Optional[BaseException] = None
        self.waiters: List["Submission"] = []
        self.done = threading.Event()


class Submission:
    """One session's handle on a (possibly shared) execution."""

    def __init__(self, server: "EngineServer", execution: _Execution,
                 tenant: str, priority: int, deduped: bool):
        self.id = _uuid.uuid4().hex[:16]
        self.tenant = tenant
        self.priority = int(priority)
        self.deduped = deduped
        self._server = server
        self._execution = execution
        self._canceled = False
        self._event = threading.Event()

    # -- introspection -------------------------------------------------------
    @property
    def status(self) -> str:
        if self._canceled:
            return "canceled"
        return self._execution.state

    @property
    def done(self) -> bool:
        return self.status in ("done", "failed", "canceled")

    @property
    def queue_wait_s(self) -> Optional[float]:
        ex = self._execution
        if ex.started_at is None:
            return None
        return ex.started_at - ex.submitted_at

    @property
    def run_s(self) -> Optional[float]:
        ex = self._execution
        if ex.started_at is None or ex.finished_at is None:
            return None
        return ex.finished_at - ex.started_at

    # -- blocking API --------------------------------------------------------
    def wait(self, timeout: Optional[float] = None) -> bool:
        """True once the submission reached a terminal state."""
        return self._event.wait(timeout)

    def result(self, timeout: Optional[float] = None) -> Any:
        """Block for the :class:`~fugue_tpu.workflow.FugueWorkflowResult`.

        For a deduped submission this is the EXECUTED workflow's result —
        the yielded frames are shared live objects, exactly like a
        result-cache memory hit. Raises the execution's error, or
        :class:`SubmissionCanceled`; ``TimeoutError`` past ``timeout``.
        Claiming the result releases this submission's tenant byte
        charge (the caller holds the frames now, not the server)."""
        from ..obs import get_tracer

        with get_tracer().span(
            "serve.wait", cat="serve", tenant=self.tenant, id=self.id
        ):
            if not self._event.wait(timeout):
                raise TimeoutError(
                    f"submission {self.id} not done after {timeout}s "
                    f"(status={self.status})"
                )
        if self._canceled:
            raise SubmissionCanceled(f"submission {self.id} was canceled")
        ex = self._execution
        if ex.state == "failed":
            assert ex.error is not None
            raise ex.error
        self._server._accounts.release(self.tenant, self.id)
        return ex.result

    def cancel(self) -> bool:
        """Detach from the execution. Never cancels a SHARED execution:
        other waiters keep theirs; only a queued execution whose last
        waiter leaves is removed from the queue. True when this call
        changed state (idempotent thereafter)."""
        return self._server._cancel(self)


class EngineServer:
    """A long-lived serving front end over one shared engine."""

    def __init__(self, engine: Any = None, conf: Any = None):
        if engine is None:
            from ..execution.factory import make_execution_engine

            engine = make_execution_engine(None, conf)
        self._engine = engine
        c = engine.conf
        self.max_concurrent = max(1, int(c.get(FUGUE_TPU_CONF_SERVE_MAX_CONCURRENT, 2)))
        self.queue_capacity = max(1, int(c.get(FUGUE_TPU_CONF_SERVE_QUEUE_DEPTH, 64)))
        self.default_priority = int(c.get(FUGUE_TPU_CONF_SERVE_DEFAULT_PRIORITY, 5))
        self.aging_s = float(c.get(FUGUE_TPU_CONF_SERVE_AGING_S, 30.0))
        self.default_reserve = int(c.get(FUGUE_TPU_CONF_SERVE_RESERVE_BYTES, 0))
        self.retain = max(1, int(c.get(FUGUE_TPU_CONF_SERVE_RETAIN, 256)))
        self.max_tenants = max(1, int(c.get(FUGUE_TPU_CONF_SERVE_MAX_TENANTS, 256)))
        self.replica_id = str(
            c.get(FUGUE_TPU_CONF_SERVE_REPLICA_ID, "")
        ) or f"{socket.gethostname()}-{os.getpid()}"
        self._stats = ServeStats(max_tenants=self.max_tenants)
        self._accounts = TenantAccounts()
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._queue: List[_Execution] = []
        self._inflight: Dict[str, _Execution] = {}  # dedup key -> execution
        self._subs: Dict[str, Submission] = {}
        self._idem: Dict[str, str] = {}  # idempotency key -> submission id
        self._done_order: List[str] = []  # retention ring of finished subs
        # per-tenant state is LRU-bounded like the retention ring: tenant
        # ids are client-supplied, and a hostile client minting ids must
        # rotate state, never grow it (ISSUE 13 satellite)
        self._policies: "OrderedDict[str, TenantPolicy]" = OrderedDict()
        self._overlay_warned: "OrderedDict[str, bool]" = OrderedDict()
        self._store_health: Dict[str, Any] = {}
        self._store_health_ts = 0.0
        self._seq = 0
        self._active = 0
        self._peak_queue = 0
        self._workers: List[threading.Thread] = []
        self._running = False
        self._injector = FaultInjector.from_conf(c)
        # fleet coordination (docs/serving.md "Fleet"): active only when
        # the engine mounts a shared disk store — replicas sharing that
        # directory collapse identical submissions across processes.
        # fleet.enabled=false restores single-server behavior exactly.
        self._fleet: Optional[FleetCoordinator] = None
        if bool(c.get(FUGUE_TPU_CONF_SERVE_FLEET_ENABLED, True)):
            disk = getattr(engine.result_cache, "disk", None)
            if disk is not None:
                self._fleet = FleetCoordinator(
                    disk,
                    self.replica_id,
                    lease_s=float(c.get(FUGUE_TPU_CONF_SERVE_FLEET_LEASE_S, 30.0)),
                    poll_s=float(c.get(FUGUE_TPU_CONF_SERVE_FLEET_POLL_S, 0.05)),
                    max_results=int(
                        c.get(FUGUE_TPU_CONF_SERVE_FLEET_MAX_RESULTS, 256)
                    ),
                    stats=self._stats,
                    injector=self._injector,
                    log=engine.log,
                )
        # crash-safe submission journal (serve/journal.py): per-replica
        # fsync'd WAL; admissions append BEFORE queueing, restarts replay
        self._journal: Optional[SubmissionJournal] = None
        jdir = str(c.get(FUGUE_TPU_CONF_SERVE_JOURNAL_DIR, ""))
        if jdir:
            self._journal = SubmissionJournal(
                os.path.join(jdir, f"{self.replica_id}.jsonl"),
                self.replica_id,
                log=engine.log,
                max_bytes=int(
                    c.get(FUGUE_TPU_CONF_SERVE_JOURNAL_MAX_BYTES, 64 * 1024 * 1024)
                ),
            )
        # cluster tracing (ISSUE 18): with a spool dir configured this
        # replica exports its span buffer after every execution so a
        # driver-side assembler merges it into ONE fleet trace
        self._spool_dir = str(c.get(FUGUE_TPU_CONF_TRACE_SPOOL_DIR, ""))
        # cross-host liveness (ISSUE 14): with a heartbeat dir configured
        # this replica beats under its replica_id, and the shared store's
        # claim stealing (cache/store.py) judges it by that beat instead
        # of a same-host pid probe — fleet claim steal works across hosts
        self._heartbeat: Optional[Any] = None
        hb_dir = str(c.get(FUGUE_TPU_CONF_DIST_HB_DIR, ""))
        if hb_dir:
            from ..dist.heartbeat import DEFAULT_INTERVAL_S, HeartbeatWriter

            self._heartbeat = HeartbeatWriter(
                hb_dir,
                self.replica_id,
                interval_s=float(
                    c.get(FUGUE_TPU_CONF_DIST_HB_INTERVAL_S, DEFAULT_INTERVAL_S)
                ),
                injector=self._injector,
                log=engine.log,
            )
        # continuous views (ISSUE 20, docs/views.md): default OFF, and
        # even when on, inert without the shared store every piece of the
        # subsystem (registry, leases, generation payloads) lives on
        self._views: Optional[Any] = None
        if bool(c.get(FUGUE_TPU_CONF_VIEWS_ENABLED, False)):
            if self._fleet is None:
                engine.log.warning(
                    "views: fugue.tpu.views.enabled is on but no shared "
                    "store is mounted (fugue.tpu.cache.dir, with the fleet "
                    "enabled); continuous views stay off"
                )
            else:
                from ..views import ViewService

                self._views = ViewService(self)
        # serving counters ride the engine's unified registry (ISSUE 3
        # contract: engine.stats()["serve"], reset under keep-entries)
        engine.metrics.register("serve", self._stats)
        if self._views is not None:
            engine.metrics.register("views", self._views)
        if self._fleet is not None:
            # fleet rollup (ISSUE 18, metrics federation): the cross-
            # replica coordination counters as their own stats group —
            # engine.stats()["fleet"] answers "is the fleet dedup/failover
            # machinery actually firing" without digging through serve.*
            engine.metrics.register("fleet", _FleetRollup(self))
        self._register_probes()

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "EngineServer":
        with self._lock:
            if self._running:
                return self
            self._running = True
            self._workers = [
                threading.Thread(
                    target=self._worker, name=f"fugue-serve-{i}", daemon=True
                )
                for i in range(self.max_concurrent)
            ]
        for t in self._workers:
            t.start()
        if self._heartbeat is not None:
            self._heartbeat.start()
        self._replay_journal()
        if self._views is not None:
            # after the submission replay: view registrations replay from
            # the same WAL, then the watch loop starts ticking
            self._views.start()
        return self

    def _replay_journal(self) -> None:
        """Resubmit this replica's admitted-but-unfinished journal
        entries under their original idempotency keys (crash recovery).
        The claim protocol turns a replay whose original execution
        published into a fleet result hit, not a re-run."""
        if self._journal is None:
            return
        replayed = 0
        for rec in self._journal.unfinished():
            dag = self._journal.decode_dag(rec)
            if dag is None:
                # audited but not replayable (unpicklable in-process dag)
                self._journal.done(rec.get("sid", ""), "unreplayable")
                continue
            try:
                self.submit(
                    dag,
                    tenant=rec.get("tenant", "default"),
                    priority=rec.get("priority"),
                    idempotency_key=rec.get("idem"),
                    reserve_bytes=rec.get("reserve"),
                )
                self._stats.inc("journal_replays")
                replayed += 1
            except ServeRejected:
                pass  # shed on replay too: rejection is never silent
            finally:
                # the replayed submission journals its own fresh admit
                # record; retire the pre-crash one either way
                self._journal.done(rec.get("sid", ""), "replayed")
        if replayed:
            from ..obs.events import get_event_log

            get_event_log().emit(
                "serve.journal_replay", replica=self.replica_id, entries=replayed
            )

    def stop(self, timeout: Optional[float] = 30.0) -> None:
        """Stop admitting and drain: in-flight executions finish, still-
        queued ones fail their waiters with ``ServeRejected``."""
        if self._views is not None:
            # stop the watch loop first (it submits into the queue being
            # drained below) and release its leases so a peer takes over
            self._views.stop()
        with self._cv:
            if not self._running:
                return
            self._running = False
            dropped, self._queue = self._queue, []
            for ex in dropped:
                ex.state = "failed"
                ex.error = ServeRejected("server_stopped")
                if ex.key is not None:
                    self._inflight.pop(ex.key, None)
            self._cv.notify_all()
        for ex in dropped:
            self._finish_waiters(ex)
            if self._journal is not None:
                for sub in ex.waiters:
                    # an ORDERLY stop retires its drained admissions so a
                    # restart doesn't replay work the client saw rejected
                    # (a crash, by definition, writes nothing here)
                    self._journal.done(sub.id, "dropped")
        with self._lock:
            workers, self._workers = self._workers, []
        for t in workers:
            t.join(timeout=timeout)
        if self._heartbeat is not None:
            # an orderly stop removes the beat file — departure reads as
            # UNKNOWN (pid fallback), not as a death to steal from
            self._heartbeat.stop(remove=True)
        if self._journal is not None:
            self._journal.close()

    def __enter__(self) -> "EngineServer":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.stop()

    @property
    def engine(self) -> Any:
        return self._engine

    @property
    def views(self) -> Optional[Any]:
        """The continuous-view service, or None when
        ``fugue.tpu.views.enabled`` is off (the kill-switch contract:
        registration endpoints 404, no watcher threads)."""
        return self._views

    @property
    def running(self) -> bool:
        return self._running

    @property
    def queue_depth(self) -> int:
        with self._lock:
            return len(self._queue)

    @property
    def active_runs(self) -> int:
        with self._lock:
            return self._active

    def store_health(self) -> Dict[str, Any]:
        """Writability of the shared dirs this replica depends on (the
        fleet result store and the journal dir) — what ``/readyz`` turns
        into a 503 ``store_unwritable`` so the balancer DRAINS a replica
        whose disk died instead of queueing onto it. Probed by actually
        creating+removing a file, cached for 5s (readyz is polled)."""
        now = time.monotonic()
        with self._lock:
            if self._store_health and now - self._store_health_ts < 5.0:
                return dict(self._store_health)
        probes: List[str] = []
        if self._fleet is not None:
            probes.append(self._fleet.results_dir)
        if self._journal is not None:
            d = os.path.dirname(self._journal.path)
            if d:
                probes.append(d)
        health: Dict[str, Any] = {"writable": True, "probed": bool(probes)}
        for d in probes:
            probe = os.path.join(d, f".probe_{_uuid.uuid4().hex}")
            try:
                with open(probe, "w") as f:
                    f.write("ok")
                os.remove(probe)
            except OSError as ex:
                health = {
                    "writable": False,
                    "probed": True,
                    "path": d,
                    "error": f"{type(ex).__name__}: {ex}",
                }
                break
        with self._lock:
            self._store_health = dict(health)
            self._store_health_ts = now
        return health

    # -- admission -----------------------------------------------------------
    def submit(
        self,
        dag: Any,
        tenant: str = "default",
        priority: Optional[int] = None,
        idempotency_key: Optional[str] = None,
        reserve_bytes: Optional[int] = None,
    ) -> Submission:
        """Admit one workflow. ``dag`` is a built ``FugueWorkflow`` or a
        zero-arg factory returning one (factories keep one-pass stream
        sources fresh per submission). Raises :class:`ServeRejected` on
        queue-full / budget / stopped — rejection is an ERROR to the
        session and a counter to the operator, never silent."""
        from ..obs import get_tracer

        tracer = get_tracer()
        tenant = str(tenant)
        tctx: Any = nullcontext()
        if tracer.enabled:
            from ..obs import current_trace_id, trace_scope

            if current_trace_id() is None:
                # cluster tracing (ISSUE 18): an in-process submission
                # mints its own trace root; an HTTP submission arrives
                # with the client's trace already bound by the handler
                # (rpc/http.py reads X-Fugue-Trace) and keeps it
                tctx = trace_scope()
        with tctx, tracer.span("serve.submit", cat="serve", tenant=tenant) as sp:
            if not self._running:
                raise ServeRejected("server_stopped")
            # the journal records what was SUBMITTED: a factory pickles
            # (and replays fresh); a built dag is journaled best-effort
            raw_dag = dag
            if is_workflow_factory(dag):
                dag = build_workflow(dag)
            self._stats.inc("submitted")
            self._stats.inc_tenant(tenant, "submitted")
            if idempotency_key is not None:
                with self._lock:
                    sid = self._idem.get(idempotency_key)
                    prior = self._subs.get(sid) if sid is not None else None
                if prior is not None:
                    # the retry-safe replay: the client's resend (riding
                    # the HTTP retry policy) maps onto the SAME submission
                    self._stats.inc("idempotent_replays")
                    sp.set(outcome="idempotent_replay", id=prior.id)
                    return prior
            pol = self._policy(tenant)
            prio = (
                int(priority)
                if priority is not None
                else (pol.priority if pol.priority is not None else self.default_priority)
            )
            if pol.conf_overlay:
                dag._conf.update(pol.conf_overlay)
            key = submission_key(dag, self._engine)
            reserve = (
                int(reserve_bytes) if reserve_bytes is not None else self.default_reserve
            )
            # cluster-wide result cache (docs/serving.md "Fleet"): a plan
            # some replica already executed and published answers here
            # without queueing — the cross-replica analogue of a result-
            # cache memory hit. Probed OUTSIDE the admission lock (disk).
            if key is not None and self._fleet is not None:
                sub = self._admit_fleet_hit(
                    key, tenant, prio, reserve, idempotency_key, pol, sp
                )
                if sub is not None:
                    return sub
            with self._cv:
                if not self._running:
                    raise ServeRejected("server_stopped")
                # single-flight: an identical in-flight plan is joined,
                # not re-run — no queue slot, no budget charge (the work
                # and the live result already exist once)
                if key is not None:
                    ex = self._inflight.get(key)
                    if ex is not None and ex.state in ("queued", "running"):
                        sub = Submission(self, ex, tenant, prio, deduped=True)
                        ex.waiters.append(sub)
                        ex.priority = min(ex.priority, prio)
                        self._subs[sub.id] = sub
                        if idempotency_key is not None:
                            self._idem[idempotency_key] = sub.id
                        self._stats.inc("dedup_hits")
                        self._stats.inc_tenant(tenant, "dedup_hits")
                        self._journal_admit(
                            sub, idempotency_key, tenant, prio, reserve, raw_dag
                        )
                        sp.set(outcome="dedup", id=sub.id, key=key[:12])
                        return sub
                if len(self._queue) >= self.queue_capacity:
                    self._stats.inc("rejected_queue_full")
                    self._stats.inc_tenant(tenant, "rejected")
                    sp.set(outcome="rejected_queue_full")
                    raise ServeRejected(
                        "queue_full",
                        f"{len(self._queue)}/{self.queue_capacity} queued",
                    )
                sub = Submission(self, None, tenant, prio, deduped=False)  # type: ignore[arg-type]
                if not self._accounts.try_charge(
                    tenant, sub.id, reserve, pol.budget_bytes
                ):
                    self._stats.inc("rejected_budget")
                    self._stats.inc_tenant(tenant, "rejected")
                    sp.set(outcome="rejected_budget")
                    raise ServeRejected(
                        "tenant_budget",
                        f"tenant {tenant} live {self._accounts.charged(tenant)}B"
                        f" + reserve {reserve}B > budget {pol.budget_bytes}B",
                    )
                self._seq += 1
                ex = _Execution(key, dag, tenant, prio, self._seq)
                if tracer.enabled:
                    # the worker thread re-enters this scope so serve.run
                    # (and the dag's spans) land under the submit's trace
                    from ..obs import trace_carrier

                    ex.trace = trace_carrier()
                ex.waiters.append(sub)
                sub._execution = ex
                # WAL before the queue: an admission the client can see
                # must survive this process dying (the serve.journal
                # fault site sits exactly in that window)
                self._journal_admit(
                    sub, idempotency_key, tenant, prio, reserve, raw_dag
                )
                self._queue.append(ex)
                self._peak_queue = max(self._peak_queue, len(self._queue))
                if key is not None:
                    self._inflight[key] = ex
                self._subs[sub.id] = sub
                if idempotency_key is not None:
                    self._idem[idempotency_key] = sub.id
                self._stats.inc("admitted")
                self._cv.notify()
            sp.set(
                outcome="admitted",
                id=sub.id,
                priority=prio,
                key=(key or "")[:12],
                queue_depth=len(self._queue),
            )
            return sub

    def get(self, submission_id: str) -> Optional[Submission]:
        with self._lock:
            return self._subs.get(submission_id)

    # -- internals -----------------------------------------------------------
    def _journal_admit(
        self,
        sub: Submission,
        idem: Optional[str],
        tenant: str,
        prio: int,
        reserve: int,
        dag: Any,
    ) -> None:
        """WAL append + the ``serve.journal`` fault site (between the
        fsync'd append and the submission becoming admitted)."""
        if self._journal is not None:
            self._journal.admit(sub.id, idem, tenant, prio, reserve, dag)
            self._stats.inc("journal_appends")
        self._injector.fire(SITE_SERVE_JOURNAL)

    def _admit_fleet_hit(
        self,
        key: str,
        tenant: str,
        prio: int,
        reserve: int,
        idem: Optional[str],
        pol: TenantPolicy,
        sp: Any,
    ) -> Optional[Submission]:
        """Serve a submission from another replica's published result
        (or this one's, from a previous life). None = no artifact, take
        the normal admission path."""
        payload = self._fleet.lookup(key)
        if payload is None:
            return None
        try:
            result = self._rehydrate(payload)
        except Exception:
            # an unloadable payload is a miss, never a wedge
            return None
        sub = Submission(self, None, tenant, prio, deduped=True)  # type: ignore[arg-type]
        with self._cv:
            if not self._running:
                raise ServeRejected("server_stopped")
            if not self._accounts.try_charge(tenant, sub.id, reserve, pol.budget_bytes):
                self._stats.inc("rejected_budget")
                self._stats.inc_tenant(tenant, "rejected")
                sp.set(outcome="rejected_budget")
                raise ServeRejected(
                    "tenant_budget",
                    f"tenant {tenant} live {self._accounts.charged(tenant)}B"
                    f" + reserve {reserve}B > budget {pol.budget_bytes}B",
                )
            self._seq += 1
            ex = _Execution(key, None, tenant, prio, self._seq)
            now = time.monotonic()
            ex.started_at = now
            ex.finished_at = now
            ex.state = "done"
            ex.result = result
            ex.waiters.append(sub)
            sub._execution = ex
            self._subs[sub.id] = sub
            if idem is not None:
                self._idem[idem] = sub.id
        measured = _result_bytes(result)
        self._accounts.restate(tenant, sub.id, measured)
        self._stats.inc_tenant(tenant, "completed")
        self._stats.inc_tenant(tenant, "dedup_hits")
        ex.done.set()
        sub._event.set()
        self._retire([sub])
        sp.set(outcome="fleet_hit", id=sub.id, key=key[:12])
        return sub

    def _rehydrate(self, payload: Dict[str, Any]) -> FleetResult:
        """``{name: (pandas, schema_str)}`` → engine frames wrapped in a
        result the waiters (and /serve/result) can read like any other."""
        yields: Dict[str, Any] = {}
        for name, item in payload.items():
            pdf, schema = item
            df = self._engine.to_df(pdf, schema=schema) if schema else (
                self._engine.to_df(pdf)
            )
            yields[name] = df
        return FleetResult(yields)

    @staticmethod
    def _extract_frames(result: Any) -> Optional[Dict[str, Any]]:
        """A publishable ``{name: (pandas, schema_str)}`` of the run's
        yields, or None when any frame can't cross a process boundary
        (unbounded/stream/device-laid-out) — then nothing publishes."""
        frames: Dict[str, Any] = {}
        try:
            for name, y in (result.yields if result is not None else {}).items():
                df = getattr(y, "result", None)
                if df is None or not getattr(df, "is_bounded", False):
                    return None
                frames[name] = (df.as_pandas(), str(df.schema))
        except Exception:
            return None
        return frames
    def _policy(self, tenant: str) -> TenantPolicy:
        with self._lock:
            pol = self._policies.get(tenant)
            if pol is not None:
                self._policies.move_to_end(tenant)
        if pol is None:
            pol = tenant_policy(self._engine.conf, tenant)
            warn = False
            with self._lock:
                if pol.dropped_keys and tenant not in self._overlay_warned:
                    warn = True
                    self._overlay_warned[tenant] = True
                    self._overlay_warned.move_to_end(tenant)
                    while len(self._overlay_warned) > self.max_tenants:
                        self._overlay_warned.popitem(last=False)
                self._policies[tenant] = pol
                self._policies.move_to_end(tenant)
                # LRU-bounded like the retention ring: client-supplied
                # tenant ids must rotate state, never grow it
                while len(self._policies) > self.max_tenants:
                    self._policies.popitem(last=False)
            if warn:
                self._engine.log.warning(
                    "tenant %s conf overlay keys %s dropped: overlays are "
                    "run-scoped fugue.tpu.* keys only; keys outside "
                    "fugue.tpu.* change workflow/compile semantics and "
                    "are refused",
                    tenant,
                    list(pol.dropped_keys),
                )
        return pol

    def _pick_locked(self) -> Optional[_Execution]:
        """Lowest effective (priority − levels aged), FIFO within — an
        O(n) scan over a bounded queue; deterministic by seq."""
        if not self._queue:
            return None
        now = time.monotonic()

        def eff(ex: _Execution) -> Any:
            aged = (
                int((now - ex.submitted_at) / self.aging_s)
                if self.aging_s > 0
                else 0
            )
            return (ex.priority - aged, ex.seq)

        best = min(self._queue, key=eff)
        self._queue.remove(best)
        return best

    def _worker(self) -> None:
        while True:
            with self._cv:
                while self._running and not self._queue:
                    self._cv.wait(timeout=0.5)
                if not self._running:
                    return
                ex = self._pick_locked()
                if ex is None:
                    continue
                ex.started = True
                ex.started_at = time.monotonic()
                ex.state = "running"
                self._active += 1
            try:
                self._run_execution(ex)
            finally:
                with self._cv:
                    self._active -= 1

    def _run_execution(self, ex: _Execution) -> None:
        from ..obs import get_tracer

        tracer = get_tracer()
        wait_s = (ex.started_at or ex.submitted_at) - ex.submitted_at
        self._stats.inc("executions")
        # tenant attribution: the run's span-histogram samples (and every
        # thread the run forks — contexts propagate) carry tenant=<id>;
        # workflow.run's own run_labels nests inside and overlays its
        # workflow/run ids, keeping this tenant label
        labels: Any = nullcontext()
        tctx: Any = nullcontext()
        if tracer.enabled:
            from ..obs import run_labels

            labels = run_labels(tenant=ex.tenant)
            if ex.trace:
                # re-enter the submission's trace on this worker thread:
                # serve.run (and everything the dag forks) attaches under
                # the submitting client's trace id, not a fresh root
                from ..obs import trace_scope

                tctx = trace_scope(ex.trace.get("trace"), ex.trace.get("parent"))
        fleet_owner = False
        with tctx:  # fleet claims/events below carry the submit's trace too
            try:
                # cross-replica single-flight (docs/serving.md "Fleet"): claim
                # the key in the shared store, or serve the owner's published
                # result instead of re-executing. acquire() is bounded by the
                # holder's lease — a dead owner's claim is stolen, never waited
                # on forever.
                if self._fleet is not None and ex.key is not None:
                    role, payload = self._fleet.acquire(ex.key)
                    if role == "result":
                        ex.result = self._rehydrate(payload)
                        ex.finished_at = time.monotonic()
                        ex.state = "done"
                    else:
                        fleet_owner = True
                        # between claim write and execution start — the chaos
                        # tests' deterministic crash point; an injected error
                        # here unwinds through the release below
                        self._injector.fire(SITE_SERVE_CLAIM)
                if ex.state != "done":
                    if self._journal is not None:
                        # the no-double-execution audit reads these: one exec
                        # record per dag actually run on this replica
                        self._journal.exec_start(
                            ex.waiters[0].id if ex.waiters else "", ex.key
                        )
                        self._stats.inc("journal_appends")
                    with labels, tracer.span(
                        "serve.run",
                        cat="serve",
                        tenant=ex.tenant,
                        priority=ex.priority,
                        waiters=len(ex.waiters),
                        queue_wait_s=round(wait_s, 6),
                    ):
                        result = ex.dag.run(self._engine)
                    ex.result = result
                    ex.finished_at = time.monotonic()
                    ex.state = "done"
                    if fleet_owner:
                        frames = self._extract_frames(result)
                        if frames is not None:
                            # publish releases the claim; waiters fleet-wide
                            # load this artifact instead of executing
                            self._fleet.publish_result(ex.key, frames)
                        else:
                            self._fleet.release(ex.key)
            except BaseException as e:  # the waiter gets the error, not the worker
                ex.error = e
                ex.finished_at = time.monotonic()
                ex.state = "failed"
                if fleet_owner:
                    # no error tombstones: a failed owner releases the claim
                    # so a cross-replica waiter re-decides (executes) rather
                    # than caching a failure fleet-wide
                    self._fleet.release(ex.key)
        if ex.state == "done":
            self._stats.inc("completed")
        else:
            self._stats.inc("failed")
        measured = _result_bytes(ex.result) if ex.state == "done" else 0
        rows = _result_rows(ex.result) if ex.state == "done" else 0
        run_s = (ex.finished_at or 0.0) - (ex.started_at or 0.0)
        with self._lock:
            if ex.key is not None and self._inflight.get(ex.key) is ex:
                del self._inflight[ex.key]
            waiters = list(ex.waiters)
        for sub in waiters:
            t = sub.tenant
            self._stats.inc_tenant(t, "completed" if ex.state == "done" else "failed")
            self._stats.inc_tenant(t, "queue_wait_s", wait_s)
            self._stats.inc_tenant(t, "run_s", run_s)
            if rows:
                self._stats.inc_tenant(t, "rows_out", rows)
            # live accounting: the reserve becomes the measured bytes the
            # tenant now holds on the server (released when claimed)
            self._accounts.restate(t, sub.id, measured)
            if self._journal is not None:
                self._journal.done(sub.id, ex.state)
                self._stats.inc("journal_appends")
        self._finish_waiters(ex)
        self._retire(waiters)
        self._maybe_publish_spool()

    def _maybe_publish_spool(self) -> None:
        """Cumulative, idempotent span export (obs/spool.py): last write
        wins, so publishing after every execution is safe and cheap."""
        if not self._spool_dir:
            return
        from ..obs import get_tracer

        if not get_tracer().enabled:
            return
        from ..obs.spool import publish_spool

        try:
            publish_spool(self._spool_dir, label=f"replica {self.replica_id}")
        except Exception as ex:
            self._engine.log.warning("trace spool publish failed: %s", ex)

    def _finish_waiters(self, ex: _Execution) -> None:
        ex.done.set()
        with self._lock:
            waiters = list(ex.waiters)
        for sub in waiters:
            sub._event.set()

    def _retire(self, finished: List[Submission]) -> None:
        """Retention ring: keep the last ``serve.retain`` finished
        submissions addressable (RPC result pickup); evicted ones release
        their tenant charge."""
        with self._lock:
            self._done_order.extend(s.id for s in finished)
            evicted: List[Submission] = []
            while len(self._done_order) > self.retain:
                sid = self._done_order.pop(0)
                sub = self._subs.pop(sid, None)
                if sub is not None:
                    evicted.append(sub)
            if evicted:
                gone = {s.id for s in evicted}
                self._idem = {
                    k: v for k, v in self._idem.items() if v not in gone
                }
        for sub in evicted:
            self._accounts.release(sub.tenant, sub.id)
            self._stats.inc("retained_evictions")

    def _cancel(self, sub: Submission) -> bool:
        with self._cv:
            if sub._canceled or sub._execution.state in ("done", "failed"):
                return False
            sub._canceled = True
            ex = sub._execution
            if sub in ex.waiters:
                ex.waiters.remove(sub)
            self._stats.inc("canceled")
            if not ex.waiters and not ex.started and ex in self._queue:
                # the last waiter left a not-yet-started execution: the
                # work is no longer wanted by anyone — drop it
                self._queue.remove(ex)
                ex.state = "canceled"
                if ex.key is not None and self._inflight.get(ex.key) is ex:
                    del self._inflight[ex.key]
                self._stats.inc("canceled_executions")
        self._accounts.release(sub.tenant, sub.id)
        if self._journal is not None:
            self._journal.done(sub.id, "canceled")
        sub._event.set()
        return True

    # -- observability -------------------------------------------------------
    def _register_probes(self) -> None:
        """Queue-depth / active-run gauges on the global resource sampler
        (weakly bound — a collected server's probes remove themselves)."""
        import weakref

        from ..obs import get_sampler
        from ..obs.sampler import ProbeGone

        ref = weakref.ref(self)

        def _probe(attr: str):
            def fn() -> float:
                s = ref()
                if s is None:
                    raise ProbeGone()
                return float(getattr(s, attr))

            return fn

        sampler = get_sampler()
        sampler.register_probe("serve_queue_depth", _probe("queue_depth"))
        sampler.register_probe("serve_active_runs", _probe("active_runs"))

    def stats(self) -> Dict[str, Any]:
        """Counters plus live gauges — what ``/readyz`` and the bench
        load driver read."""
        out = self._stats.as_dict()
        with self._lock:
            out.update(
                queue_depth=len(self._queue),
                queue_capacity=self.queue_capacity,
                peak_queue_depth=self._peak_queue,
                active_runs=self._active,
                max_concurrent=self.max_concurrent,
                inflight_keys=len(self._inflight),
                retained=len(self._done_order),
                replica_id=self.replica_id,
                fleet_enabled=self._fleet is not None,
                journal_enabled=self._journal is not None,
                journal_compactions=(
                    self._journal.compactions if self._journal is not None else 0
                ),
                heartbeat_enabled=self._heartbeat is not None,
            )
        out["charged_bytes"] = self._accounts.as_dict()
        # adaptive-execution convergence at a glance (docs/tuning.md): the
        # long-lived server is exactly where cross-submission learning
        # pays off, so surface the tuner's counters next to the serving
        # gauges (full decisions stay in engine.stats()["tuning"])
        try:
            t = self._engine.tuner.as_dict()
            out["tuning"] = {
                k: t.get(k, 0)
                for k in ("decisions", "adaptive", "static", "converged", "entries")
            }
        except Exception:
            pass
        return out


class _FleetRollup:
    """``engine.stats()["fleet"]`` — the cross-replica view: the
    ``fleet_*`` counters sliced out of :class:`~fugue_tpu.serve.stats.ServeStats`
    (renamed without the prefix) plus live store gauges. Weakly bound so
    a collected server unregisters itself in effect; ``reset()`` is a
    no-op because the underlying counters already reset with the
    ``serve`` source (one reset, not two)."""

    def __init__(self, server: "EngineServer"):
        import weakref

        self._ref = weakref.ref(server)

    def as_dict(self) -> Dict[str, Any]:
        srv = self._ref()
        if srv is None or srv._fleet is None:
            return {}
        st = srv._stats.as_dict()
        out: Dict[str, Any] = {
            k[len("fleet_"):]: v
            for k, v in st.items()
            if k.startswith("fleet_") and isinstance(v, (int, float))
        }
        out["replica_id"] = srv.replica_id
        try:
            out["results_cached"] = sum(
                1
                for n in os.listdir(srv._fleet.results_dir)
                if n.endswith(".result.pkl")
            )
        except OSError:
            out["results_cached"] = 0
        return out

    def reset(self) -> None:
        pass


def _result_bytes(result: Any) -> int:
    """Measured live bytes of a run's yielded frames (best effort)."""
    from ..cache.store import estimate_df_bytes

    total = 0
    try:
        for y in (result.yields if result is not None else {}).values():
            df = getattr(y, "result", None)
            if df is not None:
                total += estimate_df_bytes(df)
    except Exception:
        pass
    return total


def _result_rows(result: Any) -> int:
    total = 0
    try:
        for y in (result.yields if result is not None else {}).values():
            df = getattr(y, "result", None)
            if df is not None and getattr(df, "is_bounded", False):
                total += int(df.count())
    except Exception:
        pass
    return total
