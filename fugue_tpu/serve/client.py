"""Remote session client for the /serve/* HTTP surface.

A thin, dependency-free counterpart of :class:`~fugue_tpu.rpc.http.HttpRPCClient`:
submissions ride POST with cloudpickled payloads, polls/results ride GET.
Retry semantics follow the rpc/http.py idempotency rule — a submit is
only blindly re-sent when it carries an ``idempotency_key`` (the server
then maps the resend onto the SAME submission), otherwise only
failures-before-send retry.

Error taxonomy (ISSUE 14 satellite): a replica that dies POST-ADMIT —
unreachable when the result is fetched, or restarted without this
submission's state — surfaces as :class:`ServeWorkerLost` (``code ==
"worker_lost"``, classified ``WORKER_LOST`` = retryable by the PR 1
taxonomy) instead of a generic transport error, so callers (and
:class:`~fugue_tpu.serve.FleetClient`) can mechanically distinguish
"replay me elsewhere" from a workflow's own deterministic failure,
which re-raises as itself and is NEVER retried.
"""

import base64
import http.client
import json
import time
from typing import Any, Dict, Optional

import cloudpickle

from ..resilience import RetryPolicy, WorkerLostError, classify_failure
from .server import ServeRejected

__all__ = ["ServeHttpClient", "ServeWorkerLost"]


class ServeWorkerLost(WorkerLostError, KeyError):
    """A serve replica died (or lost its state) after admitting a
    submission. ``code`` is the stable taxonomy string callers switch
    on; the original transport failure is chained as ``__cause__``.
    Also a ``KeyError`` (the unknown-id contract predates the taxonomy),
    but ``classify_failure`` sees ``WorkerLostError`` first: retryable."""

    code = "worker_lost"

    def __init__(self, message: str, submission_id: Optional[str] = None):
        super().__init__(message)
        self.submission_id = submission_id

    def __str__(self) -> str:  # KeyError would repr() the message
        return self.args[0] if self.args else ""


class ServeHttpClient:
    def __init__(
        self,
        host: str,
        port: int,
        connect_timeout: float = 5.0,
        read_timeout: float = 60.0,
        policy: Optional[RetryPolicy] = None,
    ):
        self._host = host
        self._port = port
        self._connect_timeout = connect_timeout
        self._read_timeout = read_timeout
        self._policy = policy or RetryPolicy(max_attempts=3)

    # -- transport -----------------------------------------------------------
    def _request_once(self, method: str, path: str, body: Optional[bytes]) -> Any:
        sent = False
        conn = http.client.HTTPConnection(
            self._host, self._port, timeout=self._connect_timeout
        )
        try:
            conn.connect()
            if conn.sock is not None:
                conn.sock.settimeout(self._read_timeout)
            sent = True
            headers = {"Content-Length": str(len(body))} if body is not None else {}
            from ..rpc.http import trace_headers

            headers.update(trace_headers())
            conn.request(method, path, body=body, headers=headers)
            resp = conn.getresponse()
            data = resp.read()
            return resp.status, resp.getheader("Content-Type", ""), data
        except Exception as ex:
            ex._fugue_request_sent = sent  # type: ignore[attr-defined]
            raise
        finally:
            conn.close()

    def _request(
        self, method: str, path: str, body: Optional[bytes] = None,
        idempotent: bool = False,
    ) -> Any:
        attempts = 0
        while True:
            try:
                return self._request_once(method, path, body)
            except Exception as ex:
                attempts += 1
                sent = getattr(ex, "_fugue_request_sent", False)
                retryable = (idempotent or not sent) and self._policy.should_retry(
                    classify_failure(ex), attempts
                )
                if not retryable:
                    raise
                time.sleep(self._policy.delay(attempts, seed=path))

    @staticmethod
    def _json(status: int, ctype: str, data: bytes) -> Dict[str, Any]:
        payload = json.loads(data.decode() or "{}")
        payload["_http_status"] = status
        return payload

    # -- the session API -----------------------------------------------------
    def submit(
        self,
        dag: Any,
        tenant: str = "default",
        priority: Optional[int] = None,
        idempotency_key: Optional[str] = None,
        reserve_bytes: Optional[int] = None,
    ) -> Dict[str, Any]:
        """Submit a workflow (a built dag or a zero-arg factory — the
        factory form is what actually crosses the wire cleanly, since a
        built dag may close over local frames). Returns the submission
        payload (``id``, ``status``, ``deduped``…); raises
        :class:`ServeRejected` on a 429 shed."""
        body = base64.b64encode(
            cloudpickle.dumps(
                {
                    "dag": dag,
                    "tenant": tenant,
                    "priority": priority,
                    "idempotency_key": idempotency_key,
                    "reserve_bytes": reserve_bytes,
                }
            )
        )
        status, ctype, data = self._request(
            "POST", "/serve/submit", body,
            idempotent=idempotency_key is not None,
        )
        payload = self._json(status, ctype, data)
        if status == 429:
            raise ServeRejected(payload.get("rejected", "rejected"),
                                payload.get("error", ""))
        if status != 200:
            raise ConnectionError(f"/serve/submit returned HTTP {status}: {payload}")
        return payload

    def _lost(self, sid: str, what: str, cause: Optional[BaseException]) -> Any:
        raise ServeWorkerLost(
            f"serve replica {self._host}:{self._port} lost submission "
            f"{sid} during {what}"
            + (f" ({type(cause).__name__}: {cause})" if cause is not None else ""),
            submission_id=sid,
        ) from cause

    def poll(self, submission_id: str) -> Dict[str, Any]:
        try:
            status, ctype, data = self._request(
                "GET", f"/serve/poll?id={submission_id}", idempotent=True
            )
        except (ConnectionError, OSError) as ex:
            # the replica is gone with our submission: structured
            # worker_lost, not a generic transport error
            return self._lost(submission_id, "poll", ex)
        return self._json(status, ctype, data)

    def result(
        self,
        submission_id: str,
        timeout: Optional[float] = None,
        poll_interval: float = 0.05,
    ) -> Dict[str, Any]:
        """Poll until done, then fetch the yielded frames as pandas
        (``{yield_name: pandas.DataFrame}``). Raises the execution's
        error, re-hydrated — or :class:`ServeWorkerLost` when the
        REPLICA (not the workflow) died post-admit: unreachable, or
        restarted without this submission (404 on a known-admitted id)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            try:
                status, ctype, data = self._request(
                    "GET", f"/serve/result?id={submission_id}", idempotent=True
                )
            except (ConnectionError, OSError) as ex:
                return self._lost(submission_id, "result", ex)
            if status == 200 and ctype.startswith("application/octet-stream"):
                ok, payload = cloudpickle.loads(base64.b64decode(data))
                if not ok:
                    raise payload
                return payload
            if status == 404:
                # admitted here, unknown now: the replica restarted and
                # lost (or retention-evicted) this submission's state
                return self._lost(submission_id, "result (unknown id)", None)
            if status != 202:
                raise ConnectionError(f"/serve/result returned HTTP {status}")
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(
                    f"submission {submission_id} not done after {timeout}s"
                )
            time.sleep(poll_interval)

    def cancel(self, submission_id: str) -> Dict[str, Any]:
        status, ctype, data = self._request(
            "POST", "/serve/cancel", json.dumps({"id": submission_id}).encode(),
            idempotent=True,  # cancel is naturally idempotent
        )
        return self._json(status, ctype, data)

    # -- continuous views (ISSUE 20; see docs/views.md) ----------------------
    def register_view(
        self,
        view_id: str,
        factory: Any,
        source: str,
        fmt: str = "",
        tenant: str = "default",
    ) -> Dict[str, Any]:
        """Register a continuous view: ``factory`` is a zero-arg workflow
        factory (same wire rule as :meth:`submit` — a BUILT dag may close
        over local frames and is rejected server-side), ``source`` the
        watched path. Raises ``ValueError`` on a 400 (bad id/factory),
        ``KeyError`` on a 404 (views disabled on the replica).
        Registration is idempotent server-side, so retries are safe."""
        body = base64.b64encode(
            cloudpickle.dumps(
                {
                    "id": view_id,
                    "factory": factory,
                    "source": source,
                    "format": fmt,
                    "tenant": tenant,
                }
            )
        )
        status, ctype, data = self._request(
            "POST", "/serve/register", body, idempotent=True
        )
        if status == 404:
            raise KeyError(
                f"/serve/register answered 404 — views disabled on "
                f"{self._host}:{self._port} (fugue.tpu.views.enabled)"
            )
        payload = self._json(status, ctype, data)
        if status == 400:
            raise ValueError(payload.get("error", "invalid view registration"))
        if status != 200:
            raise ConnectionError(f"/serve/register returned HTTP {status}")
        return payload

    def unregister_view(self, view_id: str) -> Dict[str, Any]:
        status, ctype, data = self._request(
            "POST", "/serve/unregister",
            json.dumps({"id": view_id}).encode(),
            idempotent=True,  # unregister is naturally idempotent
        )
        if status == 404 and not data:
            raise KeyError(
                f"/serve/unregister answered 404 — views disabled on "
                f"{self._host}:{self._port}"
            )
        return self._json(status, ctype, data)

    def views(self) -> Dict[str, Any]:
        """``GET /serve/views`` — every registered view's describe dict."""
        status, ctype, data = self._request("GET", "/serve/views", idempotent=True)
        if status != 200:
            raise ConnectionError(f"/serve/views returned HTTP {status}")
        return self._json(status, ctype, data)

    def view(
        self,
        view_id: str,
        timeout: Optional[float] = None,
        poll_interval: float = 0.05,
    ) -> Dict[str, Any]:
        """The view's latest published generation: ``{view, generation,
        as_of, staleness_s, mode, frames, schemas}`` with ``frames`` as
        ``{yield_name: pandas}``. 202 (registered, nothing published yet)
        polls like :meth:`result` when ``timeout`` is set, else raises
        ``TimeoutError`` immediately; 404 raises ``KeyError``."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            status, ctype, data = self._request(
                "GET", f"/serve/view?id={view_id}", idempotent=True
            )
            if status == 200 and ctype.startswith("application/octet-stream"):
                return cloudpickle.loads(base64.b64decode(data))
            if status == 404:
                raise KeyError(f"unknown view {view_id!r} (or views disabled)")
            if status != 202:
                raise ConnectionError(f"/serve/view returned HTTP {status}")
            if deadline is None or time.monotonic() > deadline:
                raise TimeoutError(
                    f"view {view_id!r} has no published generation"
                    + (f" after {timeout}s" if timeout is not None else "")
                )
            time.sleep(poll_interval)

    def readyz(self) -> Dict[str, Any]:
        status, ctype, data = self._request("GET", "/readyz", idempotent=True)
        return self._json(status, ctype, data)

    def metrics_snapshot(self) -> Dict[str, Any]:
        """This replica's span-histogram families in the mergeable
        encoding (``GET /metrics/snapshot``) — what
        :meth:`FleetClient.federated_metrics` merges fleet-wide."""
        status, ctype, data = self._request(
            "GET", "/metrics/snapshot", idempotent=True
        )
        if status != 200:
            raise ConnectionError(f"/metrics/snapshot returned HTTP {status}")
        return self._json(status, ctype, data)
