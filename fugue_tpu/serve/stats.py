"""Serving-layer counters: one thread-safe registry source with a global
section and a per-tenant breakdown.

Follows the repo-wide stats contracts: ``inc``/``as_dict`` under one
narrow lock (``CacheStats`` style), and ``reset()`` zeroes counters
without tearing down structure (the ``JitCache.reset`` keep-entries
rule — gauges like queue depth are re-read live, never stored).
"""

import threading
from typing import Dict

__all__ = ["ServeStats"]

_COUNTERS = (
    "submitted",            # every submit() call that reached admission
    "admitted",             # enqueued as a new execution
    "dedup_hits",           # joined an identical in-flight execution
    "idempotent_replays",   # same idempotency key re-submitted
    "rejected_queue_full",
    "rejected_budget",
    "executions",           # executions actually started on a worker
    "completed",
    "failed",
    "canceled",             # submissions canceled by their owner
    "canceled_executions",  # queued executions whose last waiter canceled
    "retained_evictions",   # completed submissions dropped past serve.retain
)

_TENANT_COUNTERS = (
    "submitted",
    "completed",
    "failed",
    "rejected",
    "dedup_hits",
    "rows_out",
    "queue_wait_s",
    "run_s",
)


class ServeStats:
    """Thread-safe serving counters (a ``MetricsRegistry`` source)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.reset()

    def inc(self, name: str, n: float = 1) -> None:
        with self._lock:
            self._c[name] = self._c.get(name, 0) + n

    def inc_tenant(self, tenant: str, name: str, n: float = 1) -> None:
        with self._lock:
            t = self._t.setdefault(str(tenant), {})
            t[name] = t.get(name, 0) + n

    def get(self, name: str) -> float:
        with self._lock:
            return self._c.get(name, 0)

    def as_dict(self) -> Dict[str, object]:
        with self._lock:
            out: Dict[str, object] = {k: self._c.get(k, 0) for k in _COUNTERS}
            out["tenants"] = {
                tid: {
                    k: (round(v, 6) if isinstance(v, float) else v)
                    for k, v in sorted(t.items())
                }
                for tid, t in sorted(self._t.items())
            }
        return out

    def reset(self) -> None:
        with self._lock:
            self._c: Dict[str, float] = {}
            self._t: Dict[str, Dict[str, float]] = {}
