"""Serving-layer counters: one thread-safe registry source with a global
section and a per-tenant breakdown.

Follows the repo-wide stats contracts: ``inc``/``as_dict`` under one
narrow lock (``CacheStats`` style), and ``reset()`` zeroes counters
without tearing down structure (the ``JitCache.reset`` keep-entries
rule — gauges like queue depth are re-read live, never stored).

The per-tenant breakdown is BOUNDED: tenant ids are client-supplied, so
the map keeps at most ``max_tenants`` (``fugue.tpu.serve.max_tenants``)
entries with least-recently-incremented eviction — the same LRU
discipline as the retention ring. A hostile client minting tenant ids
rotates the breakdown; it cannot leak memory in a long-lived server.
"""

import threading
from collections import OrderedDict
from typing import Dict

__all__ = ["ServeStats"]

_COUNTERS = (
    "submitted",            # every submit() call that reached admission
    "admitted",             # enqueued as a new execution
    "dedup_hits",           # joined an identical in-flight execution
    "idempotent_replays",   # same idempotency key re-submitted
    "rejected_queue_full",
    "rejected_budget",
    "executions",           # executions actually started on a worker
    "completed",
    "failed",
    "canceled",             # submissions canceled by their owner
    "canceled_executions",  # queued executions whose last waiter canceled
    "retained_evictions",   # completed submissions dropped past serve.retain
    "tenant_evictions",     # per-tenant state rotated past serve.max_tenants
    # crash-safe journal (serve/journal.py)
    "journal_appends",      # WAL records fsync'd (admit + exec + done)
    "journal_replays",      # unfinished admissions resubmitted on restart
    # fleet coordination (serve/fleet.py, docs/serving.md "Fleet")
    "fleet_claims",         # cross-replica claims this replica won
    "fleet_claim_steals",   # claims taken from a dead/expired owner
    "fleet_result_hits",    # submissions served from another replica's artifact
    "fleet_publishes",      # results this replica published to the store
    "fleet_waits",          # poll iterations spent waiting on another owner
)

_TENANT_COUNTERS = (
    "submitted",
    "completed",
    "failed",
    "rejected",
    "dedup_hits",
    "rows_out",
    "queue_wait_s",
    "run_s",
)


class ServeStats:
    """Thread-safe serving counters (a ``MetricsRegistry`` source)."""

    def __init__(self, max_tenants: int = 256) -> None:
        self._lock = threading.Lock()
        self._max_tenants = max(1, int(max_tenants))
        self.reset()

    def inc(self, name: str, n: float = 1) -> None:
        with self._lock:
            self._c[name] = self._c.get(name, 0) + n

    def inc_tenant(self, tenant: str, name: str, n: float = 1) -> None:
        with self._lock:
            t = self._t.setdefault(str(tenant), {})
            t[name] = t.get(name, 0) + n
            self._t.move_to_end(str(tenant))
            while len(self._t) > self._max_tenants:
                self._t.popitem(last=False)
                self._c["tenant_evictions"] = self._c.get("tenant_evictions", 0) + 1

    def get(self, name: str) -> float:
        with self._lock:
            return self._c.get(name, 0)

    def as_dict(self) -> Dict[str, object]:
        with self._lock:
            out: Dict[str, object] = {k: self._c.get(k, 0) for k in _COUNTERS}
            out["tenants"] = {
                tid: {
                    k: (round(v, 6) if isinstance(v, float) else v)
                    for k, v in sorted(t.items())
                }
                for tid, t in sorted(self._t.items())
            }
        return out

    def reset(self) -> None:
        with self._lock:
            self._c: Dict[str, float] = {}
            self._t: "OrderedDict[str, Dict[str, float]]" = OrderedDict()
