"""Per-tenant serving policy and live byte accounting.

Policy comes from the engine conf under ``fugue.tpu.serve.tenant.<id>.*``
(see ``docs/serving.md``):

- ``priority`` — scheduling default for submissions that don't name one;
- ``budget_bytes`` — the admission gate: the tenant's *charged* bytes
  (reserves of in-flight submissions plus the measured result bytes of
  completed-but-unclaimed ones) plus the new submission's reserve must
  stay under it. 0 = unlimited.
- ``freshness_s`` — the tenant's view-staleness SLO in seconds
  (continuous views, ``docs/views.md``): a standing view whose pending
  refresh has waited past the at-risk fraction of this budget gets a
  priority boost in the admission queue; past the full budget a
  ``view.slo_breach`` event is recorded. Unset / <= 0 = no SLO.
- ``conf.<key>`` — a per-run conf overlay merged into every submitted
  workflow's compile conf. Any ``fugue.tpu.*`` key is accepted:
  ``workflow.run`` scopes workflow conf per run (the engine's
  ``run_conf_scope`` context overlay), so an overlay can never be
  written into the SHARED engine conf or leak into another tenant's run.
  Keys outside ``fugue.tpu.*`` (workflow/compile semantics like
  ``fugue.workflow.*``) are still dropped with one warning per tenant —
  they change what a dag MEANS, not how this engine runs it.

Accounting is *live*, not declarative: a submission is admitted against
its declared ``reserve_bytes`` (or the ``fugue.tpu.serve.reserve_bytes``
default), and the charge is re-stated to the measured
:func:`~fugue_tpu.cache.store.estimate_df_bytes` of its yielded frames
the moment the run finishes — exactly what the tenant is actually
holding live on the server until the result is claimed or evicted.
"""

import threading
from typing import Any, Dict, Optional, Tuple

from ..constants import (
    FUGUE_TPU_CONF_SERVE_TENANT_OVERLAY_PREFIX,
    FUGUE_TPU_CONF_SERVE_TENANT_PREFIX,
)

__all__ = ["TenantPolicy", "TenantAccounts", "tenant_policy"]


class TenantPolicy:
    """One tenant's parsed conf overlay."""

    def __init__(
        self,
        tenant: str,
        priority: Optional[int] = None,
        budget_bytes: int = 0,
        conf_overlay: Optional[Dict[str, Any]] = None,
        dropped_keys: Tuple[str, ...] = (),
        freshness_s: Optional[float] = None,
    ):
        self.tenant = tenant
        self.priority = priority
        self.budget_bytes = int(budget_bytes)
        self.conf_overlay = dict(conf_overlay or {})
        self.dropped_keys = tuple(dropped_keys)
        self.freshness_s = None if freshness_s is None else float(freshness_s)


def tenant_policy(conf: Any, tenant: str) -> TenantPolicy:
    """Parse ``fugue.tpu.serve.tenant.<id>.*`` out of an engine conf."""
    prefix = f"{FUGUE_TPU_CONF_SERVE_TENANT_PREFIX}{tenant}."
    priority: Optional[int] = None
    budget = 0
    freshness: Optional[float] = None
    overlay: Dict[str, Any] = {}
    dropped = []
    try:
        items = list(conf.items())
    except Exception:
        items = []
    for k, v in items:
        ks = str(k)
        if not ks.startswith(prefix):
            continue
        sub = ks[len(prefix):]
        if sub == "priority":
            priority = int(v)
        elif sub == "budget_bytes":
            budget = int(v)
        elif sub == "freshness_s":
            freshness = float(v)
        elif sub.startswith("conf."):
            key = sub[len("conf."):]
            # any fugue.tpu.* key is safely per-run now that workflow.run
            # scopes workflow conf (engine.run_conf_scope) instead of
            # writing it into the shared engine conf; keys outside it are
            # compile-semantics knobs a serving operator shouldn't set
            if key.startswith(FUGUE_TPU_CONF_SERVE_TENANT_OVERLAY_PREFIX):
                overlay[key] = v
            else:
                dropped.append(key)
    return TenantPolicy(
        tenant,
        priority=priority,
        budget_bytes=budget,
        conf_overlay=overlay,
        dropped_keys=tuple(dropped),
        freshness_s=freshness,
    )


class TenantAccounts:
    """Live charged-byte ledger, keyed (tenant, submission id)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._charges: Dict[Tuple[str, str], int] = {}

    def charged(self, tenant: str) -> int:
        with self._lock:
            return sum(
                v for (t, _sid), v in self._charges.items() if t == tenant
            )

    def try_charge(self, tenant: str, sid: str, nbytes: int, budget: int) -> bool:
        """Admission gate: charge ``nbytes`` unless it would push the
        tenant past ``budget`` (0 = unlimited). Atomic check-and-charge."""
        nbytes = max(0, int(nbytes))
        with self._lock:
            if budget > 0:
                live = sum(
                    v for (t, _sid), v in self._charges.items() if t == tenant
                )
                if live + nbytes > budget:
                    return False
            self._charges[(tenant, sid)] = nbytes
            return True

    def restate(self, tenant: str, sid: str, nbytes: int) -> None:
        """Replace a reserve with the measured live bytes (run finished).
        Never *rejects* — the work is already done; the next admission
        simply sees the true charge."""
        with self._lock:
            if (tenant, sid) in self._charges:
                self._charges[(tenant, sid)] = max(0, int(nbytes))

    def release(self, tenant: str, sid: str) -> None:
        with self._lock:
            self._charges.pop((tenant, sid), None)

    def as_dict(self) -> Dict[str, int]:
        with self._lock:
            out: Dict[str, int] = {}
            for (t, _sid), v in self._charges.items():
                out[t] = out.get(t, 0) + v
            return out
