"""Multi-tenant serving layer: a long-lived engine server for concurrent
workflows (docs/serving.md).

Quick start::

    from fugue_tpu.jax import JaxExecutionEngine
    from fugue_tpu.serve import EngineServer

    eng = JaxExecutionEngine({"fugue.tpu.serve.max_concurrent": 4})
    with EngineServer(eng) as server:
        sub = server.submit(build_dag, tenant="acme", priority=3)
        frames = sub.result().yields

Over HTTP (the ``rpc/http.py`` surface)::

    server.engine.rpc_server.bind_serve(server)   # + start the http server
    client = ServeHttpClient(host, port)
    sid = client.submit(build_dag, tenant="acme", idempotency_key="req-1")
    frames = client.result(sid, timeout=60)
"""

from .client import ServeHttpClient, ServeWorkerLost
from .dedup import submission_key
from .fleet import (
    FleetClient,
    FleetCoordinator,
    FleetResult,
    FleetSubmission,
    parse_view_result_name,
    view_result_key,
)
from .journal import SubmissionJournal
from .server import EngineServer, ServeRejected, Submission, SubmissionCanceled
from .stats import ServeStats
from .tenant import TenantAccounts, TenantPolicy, tenant_policy

__all__ = [
    "EngineServer",
    "FleetClient",
    "FleetCoordinator",
    "FleetResult",
    "FleetSubmission",
    "ServeHttpClient",
    "ServeRejected",
    "ServeStats",
    "ServeWorkerLost",
    "Submission",
    "SubmissionCanceled",
    "SubmissionJournal",
    "TenantAccounts",
    "TenantPolicy",
    "parse_view_result_name",
    "submission_key",
    "tenant_policy",
    "view_result_key",
]
