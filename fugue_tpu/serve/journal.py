"""Crash-safe submission journal: a per-replica write-ahead log.

A replica that dies mid-run must not LOSE admitted submissions — the
fleet contract (docs/serving.md "Fleet") is exactly-once *observable*
effect over at-least-once execution. The journal is the at-least-once
half: every admission appends one fsync'd jsonl record (idempotency key,
tenant, priority, cloudpickled dag payload) BEFORE the submission enters
the queue, and completion appends a ``done`` record. On restart the
replica replays its own unfinished entries under their original
idempotency keys; a balancer (:class:`~fugue_tpu.serve.fleet.FleetClient`)
fails a dead replica's submissions over to a survivor the same way. The
cross-replica claim protocol (``cache/store.py``) turns either replay
into a dedup hit instead of a duplicate execution whenever the original
run got far enough to publish.

File format — append-only jsonl, one file per replica
(``<dir>/<replica_id>.jsonl``), records:

- ``{"op": "admit", "sid", "idem", "tenant", "priority", "reserve",
  "dag" (base64 cloudpickle | null), "ts"}``
- ``{"op": "exec", "sid", "key"}`` — this replica became the claim owner
  and is about to execute (the no-double-execution audit reads these)
- ``{"op": "done", "sid", "state"}`` — terminal; replay skips the sid

Appends are atomic at the record level (single ``write`` of one line,
fsync'd); a torn final line — the crash window — is skipped by the
reader, which costs at most the one record whose admission never
completed anyway (the ``serve.journal`` fault site sits exactly there).

Compaction (ISSUE 14 satellite, the PR 13 follow-up): a WAL only ever
grows, and a long-lived replica's is dominated by records of submissions
that already reached a terminal ``done`` — dead weight for the only
thing the file is FOR (replay). When the file passes
``fugue.tpu.serve.journal.max_bytes`` (checked every few appends, or on
an explicit :meth:`compact`), it is rewritten keeping exactly the
records of sids with NO ``done`` record, fsync'd to a temp file and
atomically published over the original — a crash mid-compaction leaves
the complete old file. ``unfinished()`` is provably identical before and
after (the replay-parity test), and the no-double-exec audit only ever
loses exec/done PAIRS of completed work, which it counts as zero anyway.
"""

import base64
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

__all__ = ["SubmissionJournal"]


class SubmissionJournal:
    """Append-only fsync'd WAL of one replica's admitted submissions."""

    # how often the size check runs; a stat per append would be waste
    _COMPACT_CHECK_EVERY = 32

    def __init__(
        self, path: str, replica_id: str, log: Any = None, max_bytes: int = 0
    ):
        self.path = path
        self.replica_id = replica_id
        self.max_bytes = int(max_bytes)
        self._log = log
        self._lock = threading.Lock()
        self._fd: Optional[int] = None
        self._appends = 0
        self._compactions = 0
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)

    # -- write side ----------------------------------------------------------
    def _append(self, rec: Dict[str, Any]) -> None:
        line = (json.dumps(rec, separators=(",", ":")) + "\n").encode()
        with self._lock:
            if self._fd is None:
                self._fd = os.open(
                    self.path, os.O_CREAT | os.O_WRONLY | os.O_APPEND, 0o644
                )
            os.write(self._fd, line)
            os.fsync(self._fd)
            self._appends += 1
            if (
                self.max_bytes > 0
                and self._appends % self._COMPACT_CHECK_EVERY == 0
            ):
                try:
                    over = os.fstat(self._fd).st_size > self.max_bytes
                except OSError:
                    over = False
                if over:
                    self._compact_locked()

    def admit(
        self,
        sid: str,
        idem: Optional[str],
        tenant: str,
        priority: int,
        reserve: int,
        dag: Any,
    ) -> None:
        """Journal an admission. The dag (or factory) is cloudpickled
        best-effort: an unpicklable in-process dag (closing over live
        frames) journals with ``dag=null`` — the admission is still
        audited, it just can't be replayed from this file."""
        payload: Optional[str] = None
        try:
            import cloudpickle

            payload = base64.b64encode(cloudpickle.dumps(dag)).decode()
        except Exception:
            if self._log is not None:
                self._log.warning(
                    "journal: submission %s dag not picklable; journaled "
                    "without a replayable payload",
                    sid,
                )
        self._append(
            {
                "op": "admit",
                "sid": sid,
                "idem": idem,
                "tenant": tenant,
                "priority": int(priority),
                "reserve": int(reserve),
                "dag": payload,
                "ts": time.time(),
            }
        )

    def exec_start(self, sid: str, key: Optional[str]) -> None:
        self._append({"op": "exec", "sid": sid, "key": key})

    def done(self, sid: str, state: str) -> None:
        self._append({"op": "done", "sid": sid, "state": state})

    # -- standing views (ISSUE 20) -------------------------------------------
    # A view registration is durable state, not a one-shot submission: it
    # journals BEFORE the spec becomes visible on the shared store, and
    # unregistration writes the terminal ``done``. The sid carries the
    # registration epoch (``view:<id>@<created_ts>``) so a
    # register→unregister→re-register cycle never aliases: compaction is
    # sid-based, and an aliased sid would let the old registration's
    # ``done`` swallow the new registration's record. The submission
    # replay path never sees these (``unfinished()`` filters on op ==
    # "admit"); :meth:`view_unfinished` is the views-side replay reader.

    @staticmethod
    def view_sid(view_id: str, created_ts: float) -> str:
        return f"view:{view_id}@{created_ts!r}"

    def view_register(self, sid: str, payload: Dict[str, Any]) -> None:
        """WAL a view registration (``payload`` is the wire-safe spec
        dict, factory already base64 cloudpickle)."""
        self._append(
            {"op": "view_register", "sid": sid, "view": payload,
             "ts": time.time()}
        )

    def view_unregister(self, sid: str) -> None:
        self.done(sid, "unregistered")

    def view_unfinished(self) -> List[Dict[str, Any]]:
        """Registration records with no terminal ``done`` — what a
        restarted replica re-publishes to the shared registry. Last
        record per view id wins (a re-register after unregister)."""
        done = set()
        regs: Dict[str, Dict[str, Any]] = {}
        for rec in self.read_records(self.path):
            op = rec.get("op")
            if op == "done":
                done.add(rec.get("sid"))
            elif op == "view_register" and rec.get("sid"):
                vid = (rec.get("view") or {}).get("id")
                if vid:
                    regs[vid] = rec
        return [r for r in regs.values() if r.get("sid") not in done]

    @property
    def appends(self) -> int:
        with self._lock:
            return self._appends

    @property
    def compactions(self) -> int:
        with self._lock:
            return self._compactions

    def close(self) -> None:
        with self._lock:
            if self._fd is not None:
                os.close(self._fd)
                self._fd = None

    # -- compaction ----------------------------------------------------------
    def compact(self) -> int:
        """Rewrite the WAL keeping only records of sids with no terminal
        ``done`` record. Returns how many records were dropped. Replay
        parity: ``unfinished()`` before == after, by construction."""
        with self._lock:
            return self._compact_locked()

    def _compact_locked(self) -> int:
        recs = self.read_records(self.path)
        done = {r.get("sid") for r in recs if r.get("op") == "done"}
        keep = [r for r in recs if r.get("sid") not in done]
        dropped = len(recs) - len(keep)
        if dropped <= 0:
            return 0
        tmp = f"{self.path}.__compact_{os.getpid()}"
        try:
            fd = os.open(tmp, os.O_CREAT | os.O_WRONLY | os.O_TRUNC, 0o644)
            try:
                for r in keep:
                    os.write(
                        fd, (json.dumps(r, separators=(",", ":")) + "\n").encode()
                    )
                os.fsync(fd)
            finally:
                os.close(fd)
            os.replace(tmp, self.path)
        except OSError as ex:
            # a failed compaction must never lose the WAL: the original
            # file is untouched until the atomic rename
            try:
                os.remove(tmp)
            except OSError:
                pass
            if self._log is not None:
                self._log.warning("journal compaction of %s failed: %s", self.path, ex)
            return 0
        # the old fd points at the unlinked pre-compaction inode: reopen
        # so later appends land in the compacted file
        if self._fd is not None:
            os.close(self._fd)
            self._fd = os.open(
                self.path, os.O_CREAT | os.O_WRONLY | os.O_APPEND, 0o644
            )
        self._compactions += 1
        if self._log is not None:
            self._log.info(
                "journal %s compacted: %d record(s) of finished submissions "
                "dropped, %d kept",
                os.path.basename(self.path),
                dropped,
                len(keep),
            )
        return dropped

    # -- read side -----------------------------------------------------------
    @staticmethod
    def read_records(path: str) -> List[Dict[str, Any]]:
        """Every parseable record in ``path`` (a torn trailing line —
        the crash window — is skipped)."""
        out: List[Dict[str, Any]] = []
        try:
            with open(path, "rb") as f:
                for raw in f:
                    try:
                        out.append(json.loads(raw.decode()))
                    except Exception:
                        continue
        except FileNotFoundError:
            pass
        return out

    def unfinished(self) -> List[Dict[str, Any]]:
        """This replica's admitted-but-not-done records, in admission
        order — what a restart replays."""
        done = set()
        admits: List[Dict[str, Any]] = []
        for rec in self.read_records(self.path):
            op = rec.get("op")
            if op == "done":
                done.add(rec.get("sid"))
            elif op == "admit":
                admits.append(rec)
        return [r for r in admits if r.get("sid") not in done]

    def decode_dag(self, rec: Dict[str, Any]) -> Optional[Any]:
        payload = rec.get("dag")
        if not payload:
            return None
        try:
            import cloudpickle

            return cloudpickle.loads(base64.b64decode(payload))
        except Exception:
            if self._log is not None:
                self._log.warning(
                    "journal: replay of %s skipped (payload undecodable)",
                    rec.get("sid"),
                )
            return None
