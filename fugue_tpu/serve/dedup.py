"""Plan-fingerprint identity for in-flight dedup (single-flight).

Two tenants submitting the same workflow should share ONE execution. The
identity that makes that safe is the same one the result cache already
trusts: the canonical post-optimization plan fingerprint of
``fugue_tpu/cache/fingerprint.py`` — verb kinds, normalized params, UDF
source, input file (path, size, mtime) lists, engine class, conf salt.
Two submissions with equal keys are the same computation over the same
bytes under the same engine, so handing both the one result is exactly
what the cross-run cache would do anyway, just collapsed in flight.

Refusal is a value here too: if ANY non-output task refuses to
fingerprint (streams, non-deterministic UDFs, device frames, RPC
callbacks — everything docs/cache.md lists), the submission gets **no**
dedup key and always runs on its own. A refusal can never cause a wrong
share.

Output sinks (show/save/assert) never fingerprint — their side effects
are the point — but they don't poison dedup: an output task contributes
its deterministic task uuid plus its inputs' fingerprints, so two
identical dags (same sinks over the same fingerprinted frames) still
share, and the sink's side effect runs once per shared execution (the
semantics a served result share implies; see docs/serving.md).
"""

import hashlib
from typing import Any, Optional

from .._utils.params import ParamDict
from ..workflow._tasks import OutputTask

__all__ = ["submission_key"]


def submission_key(dag: Any, engine: Any, conf: Any = None) -> Optional[str]:
    """The in-flight dedup key for submitting ``dag`` to ``engine``, or
    ``None`` when the plan can't be fully fingerprinted (no dedup).

    Runs the same optimize→fingerprint pipeline the run path will run
    (dry: ``optimize_tasks`` clones, it never mutates the compiled
    tasks), under the same conf precedence — engine conf overlaid with
    the workflow's compile conf — so the key identifies the plan that
    would actually execute, not the one the user happened to type.
    """
    from ..cache.fingerprint import fingerprint_tasks
    from ..plan import optimize_tasks

    plan_conf = ParamDict(engine.conf)
    for k, v in dag._conf.items():
        plan_conf[k] = v
    if conf is not None:
        for k, v in ParamDict(conf).items():
            plan_conf[k] = v
    try:
        run_tasks, _aliases, _removed, _report = optimize_tasks(
            dag._tasks, plan_conf
        )
        fpr = fingerprint_tasks(run_tasks, plan_conf, type(engine).__name__)
    except Exception:
        return None  # an unplannable dag fails at run time, not here
    parts = []
    for t in run_tasks:
        fp = fpr.fp(t)
        if fp is not None:
            parts.append(fp)
            continue
        if not isinstance(t, OutputTask):
            return None  # refusal anywhere = no dedup, never a wrong share
        in_fps = [fpr.fp(d) for d in t.inputs]
        if any(f is None for f in in_fps):
            return None
        parts.append("out:" + t.__uuid__() + ":" + ",".join(in_fps))
    # both waiters read results by yield name — the mapping is part of
    # the identity (same plan, different names = different submissions)
    parts.append("yields:" + ",".join(sorted(dag.yields.keys())))
    return hashlib.md5("|".join(parts).encode()).hexdigest()
