"""Native (C++) acceleration layer, loaded via ctypes.

The reference's performance-critical parsing ships as an optional C++ ANTLR
parser ("50+ times faster", reference setup.py:50 / README.md:162); this is
the in-tree equivalent for the SQL stack: a C++ tokenizer compiled with g++
and bound with ctypes (pybind11 is not in the build image). Falls back to
the pure-Python tokenizer when the shared library is absent; ``build()``
compiles it on demand.
"""

import ctypes
import os
import subprocess
from typing import Any, List, Optional

_LIB_NAME = "_libftnative.so"
_LIB_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(os.path.dirname(os.path.dirname(_LIB_DIR)), "native", "tokenizer.cpp")

_lib: Optional[ctypes.CDLL] = None
_load_failed = False


class _FtToken(ctypes.Structure):
    _fields_ = [("kind", ctypes.c_int), ("pos", ctypes.c_int), ("len", ctypes.c_int)]


def build(force: bool = False) -> bool:
    """Compile the native library with g++. Returns True on success."""
    out = os.path.join(_LIB_DIR, _LIB_NAME)
    if os.path.exists(out) and not force:
        try:  # rebuild when the source is newer than the compiled lib
            if not (
                os.path.exists(_SRC) and os.path.getmtime(_SRC) > os.path.getmtime(out)
            ):
                return True
        except OSError:
            return True
    if not os.path.exists(_SRC):
        return False
    try:
        subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", out],
            check=True,
            capture_output=True,
        )
        global _lib, _load_failed
        _lib = None
        _load_failed = False
        return True
    except (subprocess.CalledProcessError, FileNotFoundError):
        return False


def _get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _load_failed
    if _lib is not None:
        return _lib
    if _load_failed:
        return None
    path = os.path.join(_LIB_DIR, _LIB_NAME)
    # build() is a no-op when the lib exists and is newer than the source —
    # routing every load through it keeps a stale .so from shadowing edits
    if not build() and not os.path.exists(path):
        _load_failed = True
        return None
    try:
        lib = ctypes.CDLL(path)
        lib.ft_tokenize.argtypes = [
            ctypes.c_char_p,
            ctypes.c_int,
            ctypes.POINTER(ctypes.POINTER(_FtToken)),
            ctypes.POINTER(ctypes.c_int),
            ctypes.c_char_p,
            ctypes.c_int,
        ]
        lib.ft_tokenize.restype = ctypes.c_int
        lib.ft_free.argtypes = [ctypes.POINTER(_FtToken)]
        lib.ft_free.restype = None
        _lib = lib
        return lib
    except OSError:
        _load_failed = True
        return None


def native_available() -> bool:
    return _get_lib() is not None


def tokenize_native(sql: str) -> Optional[List[Any]]:
    """Tokenize with the C++ tokenizer; None if the native lib is missing.

    Returns the same Token objects as the Python tokenizer.
    """
    lib = _get_lib()
    if lib is None:
        return None
    from ..exceptions import FugueSQLSyntaxError
    from ..sql.parser import Token

    raw = sql.encode("utf-8")
    if len(raw) != len(sql):
        # non-ASCII input: the C tokenizer is ASCII-only while the python
        # tokenizer accepts unicode identifiers/whitespace — fall back
        return None
    out_tokens = ctypes.POINTER(_FtToken)()
    out_count = ctypes.c_int(0)
    err = ctypes.create_string_buffer(256)
    rc = lib.ft_tokenize(
        raw, len(raw), ctypes.byref(out_tokens), ctypes.byref(out_count), err, 256
    )
    if rc == -2:
        raise FugueSQLSyntaxError(err.value.decode())
    if rc != 0:
        return None  # allocation failure → python fallback
    try:
        result: List[Token] = []
        # input is guaranteed ASCII here (early return above), so byte
        # offsets are str indexes
        for i in range(out_count.value):
            t = out_tokens[i]
            s, e = t.pos, t.pos + t.len
            kind = ("IDENT", "QIDENT", "STRING", "NUMBER", "OP", "PUNCT")[t.kind]
            text = sql[s:e]
            if kind == "STRING":
                quote = text[0]
                text = text[1:-1].replace(quote * 2, quote)
            elif kind == "QIDENT":
                text = text[1:-1]
            result.append(Token(kind, text, s))
        result.append(Token("EOF", "", len(sql)))
        return result
    finally:
        lib.ft_free(out_tokens)
