"""SQL text structured into table-reference and literal segments.

Parity with the reference (`fugue/collections/sql.py:14,48`): SQL statements
are stored as ``(is_table_ref, text)`` segments so engines can substitute
their own temp-table naming before execution. Dialect transpilation is a
plugin (``transpile_sql``); the in-tree implementation lives in
``fugue_tpu.sql.dialect`` (registered at import — the sqlglot role:
quoting/type/function/LIMIT conversions between registered dialect
profiles) and the decorated default below is the no-dialect passthrough.
"""

import uuid
from typing import Any, Callable, Iterable, List, Optional, Tuple

from .._utils.hash import to_uuid
from .._utils.registry import fugue_plugin


class TempTableName:
    """A unique, safely-named temp table reference embeddable in raw SQL."""

    def __init__(self):
        self.key = "_" + str(uuid.uuid4())[:5]

    @property
    def ref(self) -> str:
        return f"<tmpdf:{self.key}>"

    def __repr__(self) -> str:
        return self.ref


@fugue_plugin
def transpile_sql(raw: str, from_dialect: Optional[str], to_dialect: Optional[str]) -> str:
    """Transpile SQL between dialects (default: passthrough)."""
    return raw


class StructuredRawSQL:
    """An immutable sequence of ``(is_table_ref, text)`` SQL segments."""

    def __init__(self, statements: Iterable[Tuple[bool, str]], dialect: Optional[str] = None):
        self._statements = list(statements)
        self._dialect = dialect

    @property
    def dialect(self) -> Optional[str]:
        return self._dialect

    def __iter__(self):
        return iter(self._statements)

    def construct(
        self,
        name_map: Any = None,
        dialect: Optional[str] = None,
        log: Any = None,
    ) -> str:
        """Render the SQL, mapping table refs through ``name_map`` (a dict or
        a callable), then transpile to ``dialect`` if it differs."""

        def _map(name: str) -> str:
            if name_map is None:
                return name
            if callable(name_map):
                return name_map(name)
            return name_map.get(name, name)

        raw = " ".join(_map(t) if is_ref else t for is_ref, t in self._statements)
        if dialect is not None and self._dialect is not None and dialect != self._dialect:
            transpiled = transpile_sql(raw, self._dialect, dialect)
            if log is not None:
                log.debug(
                    "transpiled %s from %s to %s: %s",
                    raw, self._dialect, dialect, transpiled,
                )
            raw = transpiled
        return raw

    @staticmethod
    def from_expr(
        sql: str, prefix: str = "<tmpdf:", suffix: str = ">", dialect: Optional[str] = None
    ) -> "StructuredRawSQL":
        """Parse raw text containing ``<tmpdf:key>`` markers into segments."""
        statements: List[Tuple[bool, str]] = []
        pos = 0
        while True:
            start = sql.find(prefix, pos)
            if start < 0:
                if pos < len(sql):
                    statements.append((False, sql[pos:]))
                break
            end = sql.find(suffix, start)
            if end < 0:
                statements.append((False, sql[pos:]))
                break
            if start > pos:
                statements.append((False, sql[pos:start]))
            statements.append((True, sql[start + len(prefix) : end]))
            pos = end + len(suffix)
        return StructuredRawSQL(statements, dialect=dialect)

    def __uuid__(self) -> str:
        return to_uuid(self._dialect, self._statements)
