"""Partitioning vocabulary: ``PartitionSpec`` and per-partition cursors.

Capability parity with the reference (`fugue/collections/partition.py:79`):
``algo`` ∈ {default, hash, rand, even, coarse}, ``num`` supports expressions
with ``ROWCOUNT``/``CONCURRENCY`` keywords, ``by`` keys, ``presort``
("a asc, b desc"), the ``"per_row"`` shorthand, and a deterministic uuid.

On the TPU engine each ``algo`` lowers to a sharding strategy over the
device mesh (SURVEY.md §2.14): hash → bucket exchange via collectives,
even → balanced redistribution, rand → permuted exchange.
"""

import json
from typing import Any, Dict, List, Optional

from .._utils.assertion import assert_or_throw
from .._utils.hash import to_uuid
from .._utils.params import IndexedOrderedDict, ParamDict, to_list_of_str
from ..constants import KEYWORD_CONCURRENCY, KEYWORD_ROWCOUNT
from ..exceptions import FugueTPUError


class PartitionSpecError(FugueTPUError):
    """Invalid partition specification."""


def parse_presort_exp(presort: Any) -> IndexedOrderedDict:
    """Parse ``"a asc, b desc"`` into an ordered ``{name: ascending}`` map.

    Accepts a ready-made dict (validated+copied) or a string expression.
    Column names may be backtick-quoted.
    """
    if presort is None:
        return IndexedOrderedDict()
    if isinstance(presort, dict):
        res = IndexedOrderedDict()
        for k, v in presort.items():
            assert_or_throw(
                isinstance(v, bool),
                lambda: PartitionSpecError(f"presort direction for {k} must be bool"),
            )
            res[str(k)] = v
        return res
    res = IndexedOrderedDict()
    s = str(presort).strip()
    if s == "":
        return res
    for part in s.split(","):
        part = part.strip()
        if part == "":
            raise PartitionSpecError(f"invalid presort expression {presort!r}")
        if part.startswith("`"):
            end = part.index("`", 1)
            name = part[1:end]
            rest = part[end + 1 :].strip()
        else:
            tokens = part.split()
            name = tokens[0]
            rest = " ".join(tokens[1:])
        direction = rest.strip().lower()
        if direction in ("", "asc"):
            asc = True
        elif direction == "desc":
            asc = False
        else:
            raise PartitionSpecError(f"invalid presort direction {rest!r} in {presort!r}")
        assert_or_throw(
            name not in res,
            lambda: PartitionSpecError(f"duplicated presort key {name!r}"),
        )
        res[name] = asc
    return res


def _safe_eval_num(expr: str, variables: Dict[str, int]) -> int:
    """Evaluate a numeric partition expression like ``ROWCOUNT/4 + 1``."""
    import ast
    import operator as op

    ops = {
        ast.Add: op.add,
        ast.Sub: op.sub,
        ast.Mult: op.mul,
        ast.Div: op.truediv,
        ast.FloorDiv: op.floordiv,
        ast.Mod: op.mod,
        ast.Pow: op.pow,
        ast.USub: op.neg,
    }

    def ev(node: ast.AST) -> float:
        if isinstance(node, ast.Expression):
            return ev(node.body)
        if isinstance(node, ast.Constant) and isinstance(node.value, (int, float)):
            return node.value
        if isinstance(node, ast.Name):
            if node.id in variables:
                return variables[node.id]
            raise PartitionSpecError(f"unknown keyword {node.id} in {expr!r}")
        if isinstance(node, ast.BinOp) and type(node.op) in ops:
            return ops[type(node.op)](ev(node.left), ev(node.right))
        if isinstance(node, ast.UnaryOp) and type(node.op) in ops:
            return ops[type(node.op)](ev(node.operand))
        raise PartitionSpecError(f"invalid partition number expression {expr!r}")

    return int(ev(ast.parse(expr, mode="eval")))


class PartitionSpec:
    """Description of how to partition a dataset.

    Examples::

        PartitionSpec()                       # default (engine decides)
        PartitionSpec(num=4)
        PartitionSpec(algo="hash", by=["a"], presort="b desc")
        PartitionSpec("per_row")              # every row its own partition
        PartitionSpec(spec1, num=8)           # override on top of another spec
    """

    def __init__(self, *args: Any, **kwargs: Any):
        p = ParamDict()
        for a in args:
            if a is None:
                continue
            elif isinstance(a, PartitionSpec):
                self._update_dict(p, a.jsondict)
            elif isinstance(a, Dict):
                self._update_dict(p, a)
            elif isinstance(a, str):
                if a == "per_row":
                    self._update_dict(p, dict(algo="even", num=KEYWORD_ROWCOUNT))
                elif a.lower() in ("hash", "rand", "even", "coarse", "default"):
                    self._update_dict(p, dict(algo=a.lower()))
                else:
                    self._update_dict(p, json.loads(a))
            elif isinstance(a, int):
                self._update_dict(p, dict(num=a))
            else:
                raise PartitionSpecError(f"can't initialize PartitionSpec with {a!r}")
        self._update_dict(p, kwargs)
        self._num_partitions = str(p.get("num", p.get("num_partitions", "0")))
        self._algo = str(p.get("algo", "")).lower()
        assert_or_throw(
            self._algo in ("", "default", "hash", "rand", "even", "coarse"),
            lambda: PartitionSpecError(f"invalid algo {self._algo!r}"),
        )
        if self._algo == "default":
            self._algo = ""
        self._partition_by = to_list_of_str(p.get_or_none("by", object) or p.get_or_none("partition_by", object))
        assert_or_throw(
            len(self._partition_by) == len(set(self._partition_by)),
            lambda: PartitionSpecError(f"duplicated partition keys {self._partition_by}"),
        )
        self._presort = parse_presort_exp(p.get_or_none("presort", object))
        overlap = set(self._partition_by) & set(self._presort.keys())
        assert_or_throw(
            len(overlap) == 0,
            lambda: PartitionSpecError(f"presort keys {overlap} overlap partition keys"),
        )
        extra = set(p.keys()) - {"num", "num_partitions", "algo", "by", "partition_by", "presort"}
        assert_or_throw(
            len(extra) == 0,
            lambda: PartitionSpecError(f"invalid PartitionSpec keys {extra}"),
        )

    @staticmethod
    def _update_dict(d: ParamDict, u: Dict[str, Any]) -> None:
        for k, v in u.items():
            if k == "partition_by":
                k = "by"
            if k == "num_partitions":
                k = "num"
            d[k] = v

    @property
    def empty(self) -> bool:
        return (
            self._num_partitions in ("0", "")
            and self._algo == ""
            and len(self._partition_by) == 0
            and len(self._presort) == 0
        )

    @property
    def num_partitions(self) -> str:
        return self._num_partitions

    def get_num_partitions(self, **expr_map_funcs: Any) -> int:
        """Evaluate the partition-number expression.

        ``expr_map_funcs`` maps keywords (``ROWCOUNT``, ``CONCURRENCY``) to
        zero-arg callables, evaluated lazily only if the keyword appears.
        """
        expr = self._num_partitions
        variables: Dict[str, int] = {}
        for k, f in expr_map_funcs.items():
            if k in expr:
                variables[k] = int(f())
        if expr.strip() == "":
            return 0
        try:
            return int(expr)
        except ValueError:
            return _safe_eval_num(expr, variables)

    @property
    def algo(self) -> str:
        return self._algo

    @property
    def partition_by(self) -> List[str]:
        return self._partition_by

    @property
    def presort(self) -> IndexedOrderedDict:
        return self._presort

    @property
    def presort_expr(self) -> str:
        return ",".join(f"{k} {'ASC' if v else 'DESC'}" for k, v in self._presort.items())

    @property
    def jsondict(self) -> ParamDict:
        return ParamDict(
            dict(
                num_partitions=self._num_partitions,
                algo=self._algo,
                partition_by=self._partition_by,
                presort=self.presort_expr,
            )
        )

    def __repr__(self) -> str:
        return f"PartitionSpec({json.dumps(dict(self.jsondict))})"

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, PartitionSpec) and self.jsondict == other.jsondict

    def __uuid__(self) -> str:
        return to_uuid(self.jsondict)

    def get_sorts(
        self, schema: Any, with_partition_keys: bool = True
    ) -> IndexedOrderedDict:
        """Full sort map for a physical partition: partition keys + presort."""
        res = IndexedOrderedDict()
        if with_partition_keys:
            for k in self._partition_by:
                assert_or_throw(
                    k in schema,
                    lambda: PartitionSpecError(f"partition key {k} not in {schema}"),
                )
                res[k] = True
        for k, v in self._presort.items():
            assert_or_throw(
                k in schema,
                lambda: PartitionSpecError(f"presort key {k} not in {schema}"),
            )
            res[k] = v
        return res

    def get_key_schema(self, schema: Any) -> Any:
        """Sub-schema of the partition keys."""
        return schema.extract(self._partition_by)

    def get_cursor(self, schema: Any, physical_partition_no: int) -> "PartitionCursor":
        return PartitionCursor(schema, self, physical_partition_no)


EMPTY_PARTITION_SPEC = PartitionSpec()


class DatasetPartitionCursor:
    """Minimal cursor: tracks the physical partition number and current item.

    Reference: ``fugue/collections/partition.py:336``.
    """

    def __init__(self, physical_no: int):
        self._physical_no = physical_no
        self._item: Any = None
        self._item_factory: Any = None
        self._partition_no = 0
        self._slice_no = 0

    def set(self, item: Any, partition_no: int, slice_no: int) -> None:
        # a callable item resolves lazily on first access: most transformers
        # never read the cursor row, and eager peeking costs a per-partition
        # row materialization in the map hot loop
        if callable(item):
            self._item_factory = item
            self._item = None
        else:
            self._item_factory = None
            self._item = item
        self._partition_no = partition_no
        self._slice_no = slice_no

    @property
    def item(self) -> Any:
        if self._item is None and self._item_factory is not None:
            self._item = self._item_factory()
            self._item_factory = None
        return self._item

    @property
    def partition_no(self) -> int:
        return self._partition_no

    @property
    def physical_partition_no(self) -> int:
        return self._physical_no

    @property
    def slice_no(self) -> int:
        return self._slice_no


class PartitionCursor(DatasetPartitionCursor):
    """Cursor over logical partitions inside one physical partition.

    Exposes the key values of the current logical partition, given the frame
    schema and the spec (reference ``fugue/collections/partition.py:404``).
    """

    def __init__(self, schema: Any, spec: PartitionSpec, physical_partition_no: int):
        super().__init__(physical_partition_no)
        self._schema = schema
        self._spec = spec
        self._key_index = [schema.index_of_key(k) for k in spec.partition_by]

    @property
    def row(self) -> List[Any]:
        return self.item

    @property
    def row_schema(self) -> Any:
        return self._schema

    @property
    def key_schema(self) -> Any:
        return self._schema.extract(self._spec.partition_by)

    @property
    def key_value_array(self) -> List[Any]:
        return [self.row[i] for i in self._key_index]

    @property
    def key_value_dict(self) -> Dict[str, Any]:
        return {self._schema.names[i]: self.row[i] for i in self._key_index}
