"""Workflow outputs that outlive ``run()``.

Parity with the reference (`fugue/collections/yielded.py:7,37`): a
``Yielded`` is identified by a deterministic uuid; ``PhysicalYielded``
additionally carries a storage location (file path or table name).
"""

from typing import Any

from .._utils.assertion import assert_or_throw
from .._utils.hash import to_uuid
from ..exceptions import FugueInvalidOperation


class Yielded:
    """Base class for values yielded out of a workflow run."""

    def __init__(self, yid: str):
        self._yid = to_uuid(yid)

    def __uuid__(self) -> str:
        return self._yid

    @property
    def is_set(self) -> bool:  # pragma: no cover - abstract
        raise NotImplementedError

    def __copy__(self) -> "Yielded":
        return self

    def __deepcopy__(self, memo: Any) -> "Yielded":
        return self


class PhysicalYielded(Yielded):
    """Yielded result backed by storage: ``storage_type`` ∈ {file, table}."""

    def __init__(self, yid: str, storage_type: str):
        super().__init__(yid)
        assert_or_throw(
            storage_type in ("file", "table"),
            lambda: FugueInvalidOperation(f"invalid storage type {storage_type}"),
        )
        self._name = ""
        self._storage_type = storage_type

    @property
    def is_set(self) -> bool:
        return self._name != ""

    def set_value(self, name: str) -> None:
        self._name = name

    @property
    def name(self) -> str:
        assert_or_throw(self.is_set, lambda: FugueInvalidOperation("value is not set"))
        return self._name

    @property
    def storage_type(self) -> str:
        return self._storage_type
