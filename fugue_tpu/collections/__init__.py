from .partition import (
    PartitionCursor,
    PartitionSpec,
    DatasetPartitionCursor,
    parse_presort_exp,
)
from .sql import StructuredRawSQL, TempTableName
from .yielded import PhysicalYielded, Yielded

__all__ = [
    "PartitionCursor",
    "PartitionSpec",
    "DatasetPartitionCursor",
    "parse_presort_exp",
    "StructuredRawSQL",
    "TempTableName",
    "PhysicalYielded",
    "Yielded",
]
