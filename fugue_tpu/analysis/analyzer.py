"""AST trace of plain-Python pandas UDFs (docs/analysis.md).

``analyze_transform_task`` inspects the function behind a ``transform``
task and produces a :class:`UdfAnalysis`: exact column read/write sets, a
purity/determinism/row-locality verdict, and — when every statement falls
inside the recognized shape subset — a translation into the step tuples
(``("assign", ...)`` / ``("filter", ...)`` / ...) that the fusion and
segment-lowering passes already compile.

The walk degrades in tiers, never upward:

- **translatable** — every statement is a recognized row-local shape
  (column arithmetic/comparisons/masks, ``fillna``/``clip``/``where``/
  ``mask``/``isin``/``astype``/``np.where``, statically-decidable ``if``
  over bound scalar params). ``steps`` holds the translation.
- **pure** — recognized constructs only, but something crosses rows
  (a ``.sum()``-style reduction, a data-dependent ``if``): no steps, but
  reads/writes stay EXACT, so pruning still reaches the producer.
- **opaque** — an unrecognized construct (global reads, ``.apply``,
  loops, unknown methods, aliasing): reads/writes collapse to ALL and the
  UDF keeps today's fully conservative treatment.

Function traces are cached by the PR 5 UDF fingerprint
(:func:`fugue_tpu.cache.fingerprint._callable_fp` — source + defaults +
closure cells) plus the bound parameter values, so an EDITED udf or a
different closure re-analyzes while repeated runs hit the cache.
"""

import ast
import inspect
import textwrap
import threading
from typing import Any, Dict, List, Optional, Set, Tuple

from ..column.expressions import (
    ColumnExpr,
    _InExpr,
    case_when,
    col as _col,
    function as _function,
    lit as _lit,
)
from ..schema import Schema

__all__ = [
    "AnalysisStats",
    "REASON_CODES",
    "UdfAnalysis",
    "analyze_transform_task",
    "transform_row_local",
]

# canonical refusal codes — a BOUNDED vocabulary (flattened onto /metrics
# as fugue_tpu_analysis_refused_<code>; free-form detail stays in the
# human-readable reason rendered by workflow.explain()/lint())
REASON_CODES = (
    "signature",  # not a plain pandas-in/pandas-out interfaceless function
    "source",  # no retrievable source
    "globals",  # reads a module-level name (not a whitelisted module)
    "mutable-closure",  # closes over a non-scalar value
    "param",  # non-scalar / unbound extra parameter
    "reduction",  # crosses rows (.sum()/.mean()/... ) — pure, not row-local
    "conditional",  # data-dependent control flow
    "loop",  # for/while
    "apply",  # .apply/.map/lambda escape hatch
    "unknown-call",  # unrecognized function or method
    "unknown-construct",  # any other unrecognized statement/expression
    "aliasing",  # references a superseded frame variable
    "non-deterministic",  # @non_deterministic or np.random/time usage
    "callback",  # RPC callback wired in
    "ignore-errors",  # partition-dropping error swallowing
    "validation-rules",  # schema/partition validation rules attached
    "partitioned",  # non-empty partition spec (row order depends on exchange)
    "schema",  # unsupported output schema form
    "pinned",  # checkpointed task (storage identity is uuid-keyed)
    "input-schema",  # producer schema unknown at plan time
    "disabled",  # fugue.tpu.plan.translate_udfs=false
    "error",  # analyzer crashed — treated as opaque
)

_UNKNOWN = object()  # a scalar whose value is only known at run time


class _Hard(Exception):
    """Unrecognized construct: facts collapse to ALL."""

    def __init__(self, code: str, detail: str):
        super().__init__(detail)
        self.code = code
        self.detail = detail


class _Soft(Exception):
    """Recognized but untranslatable (reduction, data-dependent if):
    translation dies, exact facts survive."""

    def __init__(self, code: str, detail: str):
        super().__init__(detail)
        self.code = code
        self.detail = detail


class UdfAnalysis:
    """Per-task analysis result. ``reads``/``writes`` are ``None`` when
    unknowable (the conservative ALL); ``steps`` is the micro-step
    translation (final schema-shaping select is built by the expansion
    pass, which knows the producer's column names)."""

    __slots__ = (
        "name",
        "fp",
        "reads",
        "writes",
        "pure",
        "deterministic",
        "row_local",
        "steps",
        "star",
        "declared",
        "schema_ok",
        "reason",
        "code",
        "required_extra",
    )

    def __init__(self) -> None:
        self.name = "<udf>"
        self.fp = ""
        self.reads: Optional[Set[str]] = None
        self.writes: Optional[Set[str]] = None
        self.pure = False
        self.deterministic = False
        self.row_local = False
        self.steps: Optional[List[Tuple]] = None
        self.star = False
        self.declared: List[Tuple[str, Any]] = []
        self.schema_ok = False
        self.reason: Optional[str] = None
        self.code: Optional[str] = None
        self.required_extra: Set[str] = set()

    @property
    def facts_ok(self) -> bool:
        return self.reads is not None and self.writes is not None

    @property
    def verdict(self) -> str:
        if self.steps is not None:
            return "translatable"
        if self.pure:
            return "pure"
        return "opaque"

    @property
    def new_names(self) -> Set[str]:
        return {n for n, _ in self.declared}

    def describe(self) -> str:
        tag = f"udf {self.name}[{self.fp}]"
        if self.steps is not None:
            return f"{tag}: translatable ({len(self.steps)} step(s))"
        why = self.reason or self.code or "?"
        return f"{tag}: {self.verdict}, interpreted -- {why}"


class AnalysisStats:
    """Engine-level analyzer counters (an ``engine.metrics`` source) —
    ``engine.stats()["analysis"]``, flattened onto ``/metrics``. The same
    narrow-lock pattern as ``PlanStats`` (concurrent serving runs absorb
    from many sessions onto one engine)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self.udfs_analyzed = 0
            self.udfs_translated = 0
            self.udfs_refused = 0
            self.refused: Dict[str, int] = {}

    def absorb(self, diags: List[Dict[str, Any]]) -> None:
        with self._lock:
            for d in diags:
                self.udfs_analyzed += 1
                if d.get("translated"):
                    self.udfs_translated += 1
                else:
                    self.udfs_refused += 1
                    code = str(d.get("code") or "unknown-construct")
                    if code not in REASON_CODES:
                        code = "unknown-construct"
                    self.refused[code] = self.refused.get(code, 0) + 1

    def as_dict(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "udfs_analyzed": self.udfs_analyzed,
                "udfs_translated": self.udfs_translated,
                "udfs_refused": self.udfs_refused,
                "refused": dict(self.refused),
            }


# ---------------------------------------------------------------------------
# function-level trace
# ---------------------------------------------------------------------------


class _FuncTrace:
    __slots__ = ("steps", "reads", "writes", "pure", "reason", "code")

    def __init__(self) -> None:
        self.steps: Optional[List[Tuple]] = []
        self.reads: Optional[Set[str]] = set()
        self.writes: Optional[Set[str]] = set()
        self.pure = True
        self.reason: Optional[str] = None
        self.code: Optional[str] = None


# recognized series reductions (pure, NOT row-local)
_REDUCTIONS = {"sum", "mean", "min", "max", "count", "median", "std", "var"}

# every method/function name the tracer recognizes as side-effect-free —
# the facts-only scanner keeps the purity verdict only for these
_PURE_METHODS = _REDUCTIONS | {
    "fillna", "clip", "where", "mask", "isna", "isnull", "notna",
    "notnull", "abs", "round", "isin", "astype", "copy", "reset_index",
    "rename", "drop", "assign", "sqrt", "exp", "log", "floor", "ceil",
    "isnan",
}

# pandas dtype spellings → fugue schema type expressions
_DTYPES = {
    "int": "long",
    "int64": "long",
    "int32": "int",
    "int16": "short",
    "float": "double",
    "float64": "double",
    "float32": "float",
    "bool": "bool",
    "str": "str",
}

_NP_FUNCS = {
    "abs": "ABS",
    "sqrt": "SQRT",
    "exp": "EXP",
    "log": "LN",
    "floor": "FLOOR",
    "ceil": "CEIL",
}

_BINOPS = {
    ast.Add: lambda a, b: a + b,
    ast.Sub: lambda a, b: a - b,
    ast.Mult: lambda a, b: a * b,
    ast.Div: lambda a, b: a / b,
    ast.BitAnd: lambda a, b: a & b,
    ast.BitOr: lambda a, b: a | b,
}

_CMPOPS = {
    ast.Lt: lambda a, b: a < b,
    ast.LtE: lambda a, b: a <= b,
    ast.Gt: lambda a, b: a > b,
    ast.GtE: lambda a, b: a >= b,
    ast.Eq: lambda a, b: a == b,
    ast.NotEq: lambda a, b: a != b,
}

_SCALARS = (bool, int, float, str, bytes, type(None))


class _Tracer:
    def __init__(self, func: Any, bound: Dict[str, Any]):
        self.func = func
        self.bound = bound
        self.t = _FuncTrace()
        self.env: Dict[str, Any] = {}
        # bound Series expressions (``m = df["x"] > 0``): valid only until
        # the next frame-mutating step — pandas captured the VALUES, a
        # name-based re-evaluation later would see different ones
        self.series_env: Dict[str, Tuple[int, ColumnExpr]] = {}
        self.series_gen = 0
        self.modules: Dict[str, str] = {}  # name -> "numpy" | "pandas"
        self.frame = ""  # current frame variable
        self.retired: Set[str] = set()
        self.returned = False

    # -- lifecycle ---------------------------------------------------------
    def run(self) -> _FuncTrace:
        try:
            body = self._parse()
            self._bind()
            self._block(body)
            if not self.returned:
                raise _Hard("unknown-construct", "no plain frame return")
        except _Hard as h:
            self.t.steps = None
            self.t.reads = None
            self.t.writes = None
            self.t.pure = False
            self.t.code, self.t.reason = h.code, h.detail
        except _Soft:  # pragma: no cover - softs are absorbed per-statement
            pass
        return self.t

    def _parse(self) -> List[ast.stmt]:
        try:
            src = textwrap.dedent(inspect.getsource(self.func))
            tree = ast.parse(src)
        except Exception:
            raise _Hard("source", "source not retrievable")
        fn = tree.body[0] if tree.body else None
        if not isinstance(fn, ast.FunctionDef):
            raise _Hard("source", "not a plain function definition")
        a = fn.args
        if a.vararg is not None or a.kwarg is not None:
            raise _Hard("signature", "*args/**kwargs signature")
        names = [x.arg for x in a.posonlyargs + a.args + a.kwonlyargs]
        if len(names) == 0:
            raise _Hard("signature", "no dataframe argument")
        self.frame = names[0]
        # bind remaining parameters to their task-provided / default values
        defaults: Dict[str, Any] = {}
        try:
            for p in inspect.signature(self.func).parameters.values():
                if p.default is not inspect.Parameter.empty:
                    defaults[p.name] = p.default
        except Exception:
            pass
        for n in names[1:]:
            v = self.bound[n] if n in self.bound else defaults.get(n, _UNKNOWN)
            if v is _UNKNOWN or not isinstance(v, _SCALARS):
                raise _Hard("param", f"parameter {n!r} is not a bound scalar")
            self.env[n] = v
        return fn.body

    def _bind(self) -> None:
        code = getattr(self.func, "__code__", None)
        closure = getattr(self.func, "__closure__", None)
        if code is not None and closure:
            for name, cell in zip(code.co_freevars, closure):
                try:
                    v = cell.cell_contents
                except ValueError:
                    raise _Hard("mutable-closure", f"unbound cell {name!r}")
                if isinstance(v, _SCALARS):
                    self.env[name] = v
                elif inspect.ismodule(v) and v.__name__.split(".")[0] in (
                    "numpy",
                    "pandas",
                ):
                    self.modules[name] = v.__name__.split(".")[0]
                else:
                    raise _Hard(
                        "mutable-closure",
                        f"closes over {type(v).__name__} {name!r}",
                    )

    # -- statements --------------------------------------------------------
    def _block(self, body: List[ast.stmt]) -> None:
        for i, s in enumerate(body):
            if self.returned:
                return  # unreachable code can't affect behavior
            if (
                i == 0
                and isinstance(s, ast.Expr)
                and isinstance(s.value, ast.Constant)
                and isinstance(s.value.value, str)
            ):
                continue  # docstring
            try:
                self._stmt(s)
            except _Soft as sf:
                self._die(sf.code, sf.detail)
                self._facts_stmt(s)

    def _die(self, code: str, detail: str) -> None:
        """Translation (and row-locality) die; exact facts survive."""
        self.t.steps = None
        if self.t.code is None:
            self.t.code, self.t.reason = code, detail

    def _emit(self, step: Tuple) -> None:
        self.series_gen += 1  # any frame mutation staleness-marks bound series
        if self.t.steps is not None:
            self.t.steps.append(step)

    def _stmt(self, s: ast.stmt) -> None:
        if isinstance(s, ast.Return):
            if s.value is None:
                raise _Hard("unknown-construct", "returns nothing")
            steps = self._frame_expr(s.value)
            if steps is None:
                raise _Hard("unknown-construct", "returns a non-frame value")
            for st in steps:
                self._emit(st)
            self.returned = True
            return
        if isinstance(s, ast.Assign):
            if len(s.targets) != 1:
                raise _Hard("unknown-construct", "chained assignment")
            self._assign(s.targets[0], s.value)
            return
        if isinstance(s, ast.AnnAssign):
            if s.value is None:
                return
            self._assign(s.target, s.value)
            return
        if isinstance(s, ast.AugAssign):
            if type(s.op) not in _BINOPS:
                raise _Hard("unknown-construct", "augmented op")
            tgt_load = ast.parse(ast.unparse(s.target), mode="eval").body
            bin_ = ast.BinOp(left=tgt_load, op=s.op, right=s.value)
            ast.copy_location(bin_, s)
            ast.fix_missing_locations(bin_)
            self._assign(s.target, bin_)
            return
        if isinstance(s, ast.If):
            known, v = self._static(s.test)
            if known:
                self._block(s.body if v else s.orelse)
                return
            # data-dependent branch: translation dies; reads/writes of BOTH
            # arms (and the test) are still exact facts
            self._die("conditional", "data-dependent if")
            self._facts_node(s.test)
            for st in s.body + s.orelse:
                self._facts_stmt(st)
            return
        if isinstance(s, ast.Expr):
            raise _Hard("unknown-construct", "expression statement (no effect)")
        if isinstance(s, ast.Pass):
            return
        if isinstance(s, (ast.For, ast.While)):
            raise _Hard("loop", "loop over data")
        if isinstance(s, (ast.Global, ast.Nonlocal)):
            raise _Hard("globals", "global/nonlocal declaration")
        raise _Hard("unknown-construct", type(s).__name__.lower())

    def _assign(self, target: ast.expr, value: ast.expr) -> None:
        # df["z"] = <expr>
        if isinstance(target, ast.Subscript) and self._is_frame(target.value):
            name = self._const_str(target.slice)
            if name is None:
                raise _Hard("unknown-construct", "non-literal column target")
            e = self._expr(value)
            if self.t.writes is not None:
                self.t.writes.add(name)
            self._emit(("assign", (e.alias(name),)))
            return
        if isinstance(target, ast.Name):
            # rebinding the frame to a transformed frame
            steps = self._frame_expr(value)
            if steps is not None:
                for st in steps:
                    self._emit(st)
                if target.id != self.frame:
                    self.retired.add(self.frame)
                    self.retired.discard(target.id)
                    self.frame = target.id
                self.series_env.pop(target.id, None)
                self.env.pop(target.id, None)
                return
            if target.id == self.frame:
                raise _Hard("aliasing", "frame variable rebound to a non-frame")
            # scalar binding (constants / arithmetic over known scalars)
            known, v = self._static(value)
            if known:
                self.env[target.id] = v
                self.series_env.pop(target.id, None)
                return
            # a bound Series expression, or a recognized reduction →
            # runtime scalar (pure, not row-local)
            try:
                e = self._expr(value)
            except _Soft as sf:
                self.env[target.id] = _UNKNOWN
                self.series_env.pop(target.id, None)
                raise sf
            self.series_env[target.id] = (self.series_gen, e)
            self.env.pop(target.id, None)
            return
        raise _Hard("unknown-construct", "assignment target")

    # -- facts-only scanning (after translation died) ----------------------
    def _facts_stmt(self, s: ast.stmt) -> None:
        try:
            if isinstance(s, (ast.Assign, ast.AugAssign)):
                if isinstance(s, ast.Assign) and len(s.targets) != 1:
                    raise _Hard("unknown-construct", "chained assignment")
                t0 = s.targets[0] if isinstance(s, ast.Assign) else s.target
                if isinstance(t0, ast.Subscript) and self._is_frame(t0.value):
                    name = self._const_str(t0.slice)
                    if name is None:
                        # unknown written column set: facts must collapse
                        raise _Hard(
                            "unknown-construct", "non-literal column target"
                        )
                    if self.t.writes is not None:
                        self.t.writes.add(name)
                    if isinstance(s, ast.AugAssign):
                        # the augmented op also READS the target
                        if self.t.reads is not None:
                            self.t.reads.add(name)
                    self._facts_node(s.value)
                    return
                if isinstance(t0, ast.Name):
                    self.env.setdefault(t0.id, _UNKNOWN)
                    self._facts_node(s.value)
                    return
                raise _Hard("unknown-construct", "assignment target form")
            if isinstance(s, ast.Return) and s.value is not None:
                self._facts_node(s.value)
                self.returned = True
                return
            if isinstance(s, ast.If):
                self._facts_node(s.test)
                for st in s.body + s.orelse:
                    self._facts_stmt(st)
                return
            self._facts_node(s)
        except _Hard as h:
            # facts themselves are unknowable
            self.t.reads = None
            self.t.writes = None
            self.t.pure = False
            if self.t.code is None:
                self.t.code, self.t.reason = h.code, h.detail

    def _facts_node(self, node: ast.AST) -> None:
        """Collect column reads; any opaque frame use collapses to ALL.
        A method call outside the recognized-pure set clears the purity
        verdict (reads stay exact — pruning is sound for impure UDFs)."""
        if isinstance(node, ast.Subscript) and self._is_frame(node.value):
            name = self._const_str(node.slice)
            if name is not None:
                if self.t.reads is not None:
                    self.t.reads.add(name)
                self._facts_node(node.slice)
                return
            self._facts_node(node.slice)
            return
        if isinstance(node, ast.Name) and self._is_frame(node):
            raise _Hard("unknown-construct", "opaque frame use")
        if isinstance(node, ast.Call):
            fn = node.func
            known = isinstance(fn, ast.Attribute) and fn.attr in _PURE_METHODS
            if not known:
                self.t.pure = False
        for child in ast.iter_child_nodes(node):
            self._facts_node(child)

    # -- helpers -----------------------------------------------------------
    def _is_frame(self, node: ast.AST) -> bool:
        if not isinstance(node, ast.Name):
            return False
        if node.id in self.retired:
            raise _Hard("aliasing", f"superseded frame variable {node.id!r}")
        return node.id == self.frame

    def _const_str(self, node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        return None

    def _module_of(self, node: ast.AST) -> Optional[str]:
        if not isinstance(node, ast.Name):
            return None
        if (
            node.id in self.env
            or node.id in self.series_env
            or node.id == self.frame
            or node.id in self.retired
        ):
            return None  # locally bound names shadow any module alias
        if node.id in self.modules:
            return self.modules[node.id]
        g = getattr(self.func, "__globals__", {})
        if node.id in g and inspect.ismodule(g[node.id]):
            root = g[node.id].__name__.split(".")[0]
            if root in ("numpy", "pandas"):
                self.modules[node.id] = root
                return root
        return None

    def _static(self, node: ast.expr) -> Tuple[bool, Any]:
        """Evaluate a scalar expression over literals and bound params."""
        try:
            return True, self._static_eval(node)
        except (_Soft, _Hard, _NotStatic):
            return False, None

    def _static_eval(self, node: ast.expr) -> Any:
        if isinstance(node, ast.Constant):
            if isinstance(node.value, _SCALARS):
                return node.value
            raise _NotStatic()
        if isinstance(node, ast.Name):
            if node.id in self.env and self.env[node.id] is not _UNKNOWN:
                return self.env[node.id]
            raise _NotStatic()
        if isinstance(node, ast.BinOp) and type(node.op) in _BINOPS:
            return _BINOPS[type(node.op)](
                self._static_eval(node.left), self._static_eval(node.right)
            )
        if isinstance(node, ast.Compare) and len(node.ops) == 1:
            if type(node.ops[0]) not in _CMPOPS:
                raise _NotStatic()
            return _CMPOPS[type(node.ops[0])](
                self._static_eval(node.left),
                self._static_eval(node.comparators[0]),
            )
        if isinstance(node, ast.UnaryOp):
            if isinstance(node.op, ast.Not):
                return not self._static_eval(node.operand)
            if isinstance(node.op, ast.USub):
                return -self._static_eval(node.operand)
            raise _NotStatic()
        if isinstance(node, ast.BoolOp):
            vals = [self._static_eval(v) for v in node.values]
            if isinstance(node.op, ast.And):
                r = vals[0]
                for v in vals[1:]:
                    r = r and v
                return r
            r = vals[0]
            for v in vals[1:]:
                r = r or v
            return r
        raise _NotStatic()

    # -- frame-producing expressions (statement/return position) -----------
    def _frame_expr(self, node: ast.expr) -> Optional[List[Tuple]]:
        if self._is_frame(node):
            return []
        if isinstance(node, ast.Subscript):
            sl = node.slice
            if self._const_str(sl) is not None:
                return None  # df["c"] is a Series, not a frame
            inner = self._frame_expr(node.value)
            if inner is None:
                return None
            if isinstance(sl, ast.List):
                names = [self._const_str(e) for e in sl.elts]
                if any(n is None for n in names):
                    raise _Hard("unknown-construct", "non-literal projection")
                if self.t.reads is not None:
                    self.t.reads.update(names)  # type: ignore[arg-type]
                return inner + [("project", tuple(names))]
            cond = self._expr(sl)
            return inner + [("filter", cond)]
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            recv = node.func.value
            m = node.func.attr
            try:
                inner = self._frame_expr(recv)
            except _Hard:
                inner = None
            if inner is None:
                return None
            args, kw = node.args, {k.arg: k.value for k in node.keywords}
            if None in kw:
                raise _Hard("unknown-construct", "**kwargs call")
            if m == "copy" and not args and not kw:
                return inner
            if m == "reset_index":
                known, v = self._static(kw.get("drop", ast.Constant(False)))
                if known and v is True and not args:
                    return inner
                raise _Hard("unknown-call", "reset_index without drop=True")
            if m == "rename":
                mapping = kw.get("columns")
                if not isinstance(mapping, ast.Dict):
                    raise _Hard("unknown-call", "rename without columns=dict")
                ren: Dict[str, str] = {}
                for k, v in zip(mapping.keys, mapping.values):
                    ks = self._const_str(k) if k is not None else None
                    vs = self._const_str(v)
                    if ks is None or vs is None:
                        raise _Hard("unknown-call", "non-literal rename")
                    ren[ks] = vs
                if self.t.writes is not None:
                    self.t.writes.update(ren.keys())
                    self.t.writes.update(ren.values())
                if self.t.reads is not None:
                    self.t.reads.update(ren.keys())
                return inner + [("rename", ren)]
            if m == "drop":
                cols = kw.get("columns")
                if cols is None and len(args) == 1:
                    cols = args[0]
                if not isinstance(cols, ast.List):
                    raise _Hard("unknown-call", "drop without a column list")
                names = [self._const_str(e) for e in cols.elts]
                if any(n is None for n in names):
                    raise _Hard("unknown-call", "non-literal drop")
                if self.t.reads is not None:
                    self.t.reads.update(names)  # type: ignore[arg-type]
                return inner + [("drop", tuple(names), False)]
            if m == "assign":
                if args:
                    raise _Hard("unknown-call", "positional assign")
                exprs: List[ColumnExpr] = []
                for name, vexpr in kw.items():
                    e = self._expr(vexpr)
                    exprs.append(e.alias(str(name)))
                    if self.t.writes is not None:
                        self.t.writes.add(str(name))
                return inner + [("assign", tuple(exprs))]
            if m in ("fillna", "dropna", "astype", "apply", "pipe"):
                code = "apply" if m in ("apply", "pipe") else "unknown-call"
                raise _Hard(code, f"frame-level .{m}()")
            return None
        return None

    # -- column expressions -------------------------------------------------
    def _expr(self, node: ast.expr) -> ColumnExpr:
        if isinstance(node, ast.Constant):
            if isinstance(node.value, _SCALARS):
                return _lit(node.value)
            raise _Hard("unknown-construct", f"literal {node.value!r}")
        if isinstance(node, ast.Name):
            if node.id in self.series_env:
                gen, e = self.series_env[node.id]
                if gen != self.series_gen:
                    raise _Hard(
                        "aliasing", f"series variable {node.id!r} is stale"
                    )
                return e
            if node.id in self.env:
                v = self.env[node.id]
                if v is _UNKNOWN:
                    raise _Soft("reduction", f"runtime scalar {node.id!r}")
                return _lit(v)
            if self._is_frame(node):
                raise _Hard("unknown-construct", "whole-frame use in expression")
            if self._module_of(node) is not None:
                raise _Hard("unknown-construct", "module used as a value")
            raise _Hard("globals", f"reads global {node.id!r}")
        if isinstance(node, ast.Subscript) and self._is_frame(node.value):
            name = self._const_str(node.slice)
            if name is None:
                raise _Hard("unknown-construct", "non-literal column reference")
            if self.t.reads is not None:
                self.t.reads.add(name)
            return _col(name)
        if isinstance(node, ast.BinOp):
            if type(node.op) not in _BINOPS:
                raise _Hard(
                    "unknown-construct", f"operator {type(node.op).__name__}"
                )
            return _BINOPS[type(node.op)](
                self._expr(node.left), self._expr(node.right)
            )
        if isinstance(node, ast.Compare):
            if len(node.ops) != 1 or type(node.ops[0]) not in _CMPOPS:
                raise _Hard("unknown-construct", "chained/unknown comparison")
            return _CMPOPS[type(node.ops[0])](
                self._expr(node.left), self._expr(node.comparators[0])
            )
        if isinstance(node, ast.UnaryOp):
            if isinstance(node.op, ast.Invert):
                return ~self._expr(node.operand)
            if isinstance(node.op, ast.USub):
                return -self._expr(node.operand)
            raise _Hard("unknown-construct", "not/+ on a column")
        if isinstance(node, ast.IfExp):
            known, v = self._static(node.test)
            if known:
                return self._expr(node.body if v else node.orelse)
            raise _Soft("conditional", "data-dependent ternary")
        if isinstance(node, ast.Lambda):
            raise _Hard("apply", "lambda")
        if isinstance(node, ast.Call):
            return self._call(node)
        raise _Hard("unknown-construct", type(node).__name__.lower())

    def _scalar_arg(self, node: ast.expr) -> Any:
        known, v = self._static(node)
        if not known:
            raise _Hard("unknown-call", "non-scalar argument")
        return v

    def _call(self, node: ast.Call) -> ColumnExpr:
        if not isinstance(node.func, ast.Attribute):
            if isinstance(node.func, ast.Name) and node.func.id == "abs":
                if len(node.args) == 1 and not node.keywords:
                    return _function("ABS", self._expr(node.args[0]))
            raise _Hard("unknown-call", ast.unparse(node.func))
        recv, m = node.func.value, node.func.attr
        kw = {k.arg: k.value for k in node.keywords}
        if None in kw:
            raise _Hard("unknown-construct", "**kwargs call")
        mod = self._module_of(recv)
        if mod is not None:
            return self._module_call(mod, m, node.args, kw)
        # nested module attr: np.random.xyz(...)
        if (
            isinstance(recv, ast.Attribute)
            and self._module_of(recv.value) == "numpy"
            and recv.attr == "random"
        ):
            raise _Hard("non-deterministic", f"np.random.{m}")
        r = self._expr(recv)  # the receiver series
        return self._method_call(r, m, node.args, kw)

    def _module_call(
        self, mod: str, m: str, args: List[ast.expr], kw: Dict[str, ast.expr]
    ) -> ColumnExpr:
        if mod == "numpy":
            if m == "where" and len(args) == 3 and not kw:
                c = self._expr(args[0])
                a = self._expr(args[1])
                b = self._expr(args[2])
                return case_when((c, a), default=b)
            if m in _NP_FUNCS and len(args) == 1 and not kw:
                return _function(_NP_FUNCS[m], self._expr(args[0]))
            if m == "isnan" and len(args) == 1 and not kw:
                return self._expr(args[0]).is_null()
            raise _Hard("unknown-call", f"np.{m}")
        if mod == "pandas":
            if m in ("isna", "isnull") and len(args) == 1 and not kw:
                return self._expr(args[0]).is_null()
            if m in ("notna", "notnull") and len(args) == 1 and not kw:
                return self._expr(args[0]).not_null()
            raise _Hard("unknown-call", f"pd.{m}")
        raise _Hard("unknown-call", f"{mod}.{m}")  # pragma: no cover

    def _method_call(
        self,
        r: ColumnExpr,
        m: str,
        args: List[ast.expr],
        kw: Dict[str, ast.expr],
    ) -> ColumnExpr:
        if m == "fillna" and len(args) + len(kw) == 1:
            node = args[0] if args else kw.get("value")
            if node is None:
                raise _Hard("unknown-call", "fillna(...) argument form")
            v = self._scalar_arg(node)
            if v is None:
                raise _Hard("unknown-call", "fillna(None)")
            return _function("COALESCE", r, _lit(v))
        if m == "clip":
            lo = self._scalar_arg(args[0]) if len(args) > 0 else None
            hi = self._scalar_arg(args[1]) if len(args) > 1 else None
            if "lower" in kw:
                lo = self._scalar_arg(kw["lower"])
            if "upper" in kw:
                hi = self._scalar_arg(kw["upper"])
            cases = []
            if lo is not None:
                cases.append((r < _lit(lo), _lit(lo)))
            if hi is not None:
                cases.append((r > _lit(hi), _lit(hi)))
            if not cases:
                return r
            return case_when(*cases, default=r)
        if m in ("where", "mask"):
            cnode = args[0] if args else kw.get("cond")
            if cnode is None:
                raise _Hard("unknown-call", f".{m}() without a condition")
            cond = self._expr(cnode)
            if len(args) > 1:
                other = self._expr(args[1])
            elif "other" in kw:
                other = self._expr(kw["other"])
            else:
                raise _Hard("unknown-call", f".{m}() without other=")
            if m == "where":
                return case_when((cond, r), default=other)
            return case_when((cond, other), default=r)
        if m in ("isna", "isnull") and not args and not kw:
            return r.is_null()
        if m in ("notna", "notnull") and not args and not kw:
            return r.not_null()
        if m == "abs" and not args and not kw:
            return _function("ABS", r)
        if m == "round":
            n = self._scalar_arg(args[0]) if args else 0
            return _function("ROUND", r, _lit(int(n)))
        if m == "isin" and len(args) == 1 and not kw:
            if not isinstance(args[0], ast.List):
                raise _Hard("unknown-call", "isin over a non-literal list")
            vals = [self._scalar_arg(e) for e in args[0].elts]
            return _InExpr(r, vals, True)
        if m == "astype" and len(args) == 1 and not kw:
            t = self._scalar_arg(args[0])
            if not isinstance(t, str) or t not in _DTYPES:
                raise _Hard("unknown-call", f"astype({t!r})")
            return r.cast(_DTYPES[t])
        if m in _REDUCTIONS and not args and not kw:
            raise _Soft("reduction", f".{m}() crosses rows")
        if m in ("apply", "map", "transform", "agg", "aggregate", "pipe"):
            raise _Hard("apply", f".{m}() escapes analysis")
        raise _Hard("unknown-call", f".{m}()")


class _NotStatic(Exception):
    pass


# ---------------------------------------------------------------------------
# caching + task-level analysis
# ---------------------------------------------------------------------------

_TRACE_CACHE: Dict[str, _FuncTrace] = {}
_TRACE_CACHE_MAX = 256
_TRACE_LOCK = threading.Lock()


def _trace_function(func: Any, bound: Dict[str, Any]) -> Tuple[_FuncTrace, str]:
    """Trace with a cache keyed by the PR 5 callable fingerprint (source +
    defaults + closure cells) plus the bound parameter values."""
    from .._utils.hash import to_uuid
    from ..cache.fingerprint import _Refused, _callable_fp

    try:
        fp = _callable_fp(func)
    except _Refused as r:
        t = _FuncTrace()
        t.steps, t.reads, t.writes, t.pure = None, None, None, False
        t.code, t.reason = "non-deterministic", r.reason
        return t, ""
    try:
        key = to_uuid(fp, sorted((k, repr(v)) for k, v in bound.items()))
    except Exception:
        key = ""
    if key:
        with _TRACE_LOCK:
            hit = _TRACE_CACHE.get(key)
        if hit is not None:
            return hit, fp[:8]
    t = _Tracer(func, bound).run()
    if key:
        with _TRACE_LOCK:
            if len(_TRACE_CACHE) >= _TRACE_CACHE_MAX:
                _TRACE_CACHE.clear()
            _TRACE_CACHE[key] = t
    return t, fp[:8]


def _parse_schema_arg(arg: Any) -> Tuple[bool, List[Tuple[str, Any]]]:
    """(star, declared-with-dtypes) for the supported schema-arg forms:
    an explicit schema, or ``*``-prefixed append (``"*,z:double"``).
    Raises _Hard("schema") on anything else (callables, exclusions)."""
    if isinstance(arg, Schema):
        return False, [(f.name, f.type) for f in arg.fields]
    if not isinstance(arg, str):
        raise _Hard("schema", f"schema arg {type(arg).__name__}")
    s = arg.strip()
    if "*" in s:
        if not s.startswith("*") or "*" in s[1:]:
            raise _Hard("schema", f"schema form {s!r}")
        rest = s[1:].lstrip()
        if rest == "":
            return True, []
        if not rest.startswith(",") or any(ch in rest for ch in "~+-"):
            raise _Hard("schema", f"schema form {s!r}")
        try:
            return True, [(f.name, f.type) for f in Schema(rest[1:]).fields]
        except Exception:
            raise _Hard("schema", f"schema form {s!r}")
    try:
        return False, [(f.name, f.type) for f in Schema(s).fields]
    except Exception:
        raise _Hard("schema", f"schema form {s!r}")


def analyze_transform_task(task: Any) -> Optional[UdfAnalysis]:
    """Task-level analysis of a ``RunTransformer`` task (None when the
    task is not a transformer task). Never raises — every failure is a
    conservative verdict with a reason."""
    try:
        return _analyze_transform_task(task)
    except Exception as ex:  # analysis must never fail planning
        a = UdfAnalysis()
        a.code, a.reason = "error", f"analyzer error: {type(ex).__name__}"
        return a


def _refused(a: UdfAnalysis, code: str, reason: str) -> UdfAnalysis:
    a.code, a.reason = code, reason
    a.steps = None
    return a


def _analyze_transform_task(task: Any) -> Optional[UdfAnalysis]:
    from ..cache.fingerprint import _NON_DETERMINISTIC_ATTR
    from ..extensions.transformer.convert import (
        _FuncAsOutputTransformer,
        _FuncAsTransformer,
    )

    tf = task.params.get_or_none("transformer", object)
    if tf is None:
        return None
    a = UdfAnalysis()
    a.name = "<udf>"
    if isinstance(tf, _FuncAsOutputTransformer) or not isinstance(
        tf, _FuncAsTransformer
    ):
        return _refused(
            a, "signature", f"{type(tf).__name__} is not a plain function UDF"
        )
    func = getattr(getattr(tf, "_wrapper", None), "_func", None)
    if func is None:
        return _refused(a, "signature", "no wrapped function")
    a.name = getattr(func, "__name__", "<udf>")
    wrapper = tf._wrapper
    from ..dataframe.function_wrapper import _PandasParam

    params = list(wrapper._params.values())
    if (
        len(params) == 0
        or type(params[0]) is not _PandasParam
        or type(wrapper._rt) is not _PandasParam
        or any(c in wrapper.input_code for c in "fF")
    ):
        return _refused(
            a, "signature", "not a pandas-DataFrame-in/DataFrame-out function"
        )
    if task.params.get_or_none("callback", object) is not None:
        return _refused(a, "callback", "RPC callback wired in")
    if len(task.params.get("ignore_errors", []) or []) > 0:
        return _refused(a, "ignore-errors", "ignore_errors drops partitions")
    if tf.validation_rules:
        return _refused(a, "validation-rules", "validation rules attached")
    if getattr(func, _NON_DETERMINISTIC_ATTR, False) or getattr(
        tf, _NON_DETERMINISTIC_ATTR, False
    ):
        return _refused(a, "non-deterministic", "marked @non_deterministic")
    try:
        a.star, a.declared = _parse_schema_arg(tf._output_schema_arg)
        a.schema_ok = True
    except _Hard as h:
        a.code, a.reason = h.code, h.detail
    bound = dict(task.params.get("params", {}) or {})
    trace, fp = _trace_function(func, bound)
    a.fp = fp
    a.reads = None if trace.reads is None else set(trace.reads)
    a.writes = None if trace.writes is None else set(trace.writes)
    a.pure = trace.pure
    a.deterministic = trace.pure
    spec = task.partition_spec
    a.required_extra = set(spec.partition_by) | set(spec.presort.keys())
    if trace.steps is None:
        a.code = a.code or trace.code
        a.reason = a.reason or trace.reason
        a.steps = None
        return a
    # function is row-local; task-level conditions for using that fact
    if not spec.empty:
        return _refused(
            a,
            "partitioned",
            "partitioned transform (row order depends on exchange)",
        )
    a.row_local = True
    if not a.schema_ok:
        a.steps = None
        return a
    a.steps = list(trace.steps)
    return a


def transform_row_local(task: Any) -> bool:
    """Whether this transform task provably computes each output row from
    one input row — the delta-cache splitting precondition. Conservative:
    any analysis failure is False."""
    try:
        a = analyze_transform_task(task)
        return a is not None and a.row_local and a.deterministic
    except Exception:
        return False
