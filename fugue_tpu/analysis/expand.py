"""Expansion pass: translated UDF transforms → ordinary plan nodes.

Runs FIRST in the optimizer (before pushdown/prune/fuse/lowering), so a
translated UDF's steps are plain ``filter``/``assign``/``select`` logical
nodes that every later pass composes with natively: filters commute
around them, pruning sees their exact demand, fusion collapses them with
surrounding verbs, and segment lowering compiles the whole chain into one
``shard_map`` program. Analyzed-but-untranslated transforms keep their
node, with the :class:`~fugue_tpu.analysis.analyzer.UdfAnalysis` attached
to ``info["analysis"]`` so demand analysis and filter pushdown can still
use the exact column facts.

A translated chain ends in a schema-shaping step that reproduces the
declared output schema (column order and dtype casts) EXACTLY as the
interpreted path's schema enforcement would — any mismatch the builder
can't prove refuses back to the interpreted path.
"""

from typing import Any, Dict, List, Optional, Tuple

from ..column.expressions import col as _col
from ..column.sql import SelectColumns
from ..plan.ir import (
    K_ASSIGN,
    K_DROP,
    K_FILTER,
    K_PROJECT,
    K_RENAME,
    K_SELECT,
    K_TRANSFORM,
    LNode,
    infer_schemas,
)
from .analyzer import UdfAnalysis, analyze_transform_task

__all__ = ["expand_udf_transforms"]


def _node_for_step(st: Tuple) -> LNode:
    kind = st[0]
    if kind == "project":
        return LNode(None, K_PROJECT, {"columns": list(st[1])})
    if kind == "drop":
        return LNode(
            None, K_DROP, {"columns": list(st[1]), "if_exists": bool(st[2])}
        )
    if kind == "rename":
        return LNode(None, K_RENAME, {"columns": dict(st[1])})
    if kind == "filter":
        return LNode(None, K_FILTER, {"condition": st[1]})
    if kind == "assign":
        return LNode(None, K_ASSIGN, {"columns": list(st[1])})
    if kind == "select":
        return LNode(
            None, K_SELECT, {"columns": st[1], "where": None, "having": None}
        )
    raise AssertionError(f"untranslatable step {kind}")  # pragma: no cover


def _build_final_steps(
    a: UdfAnalysis, in_names: List[str]
) -> Tuple[Optional[List[Tuple]], Optional[str]]:
    """Append the schema-shaping step for the declared output schema, or
    (None, reason) when the translation can't be proven to reproduce the
    interpreted path's enforced schema."""
    names = list(in_names)
    for st in a.steps or []:
        kind = st[0]
        if kind == "project":
            if any(c not in names for c in st[1]):
                return None, "projects a column missing from the input"
            names = list(st[1])
        elif kind == "drop":
            if any(c not in names for c in st[1]):
                return None, "drops a column missing from the input"
            dropped = set(st[1])
            names = [c for c in names if c not in dropped]
            if not names:
                return None, "drops every column"
        elif kind == "rename":
            m = dict(st[1])
            if any(k not in names for k in m):
                return None, "renames a column missing from the input"
            names = [m.get(c, c) for c in names]
            if len(set(names)) != len(names):
                return None, "rename collides with an existing column"
        elif kind == "filter":
            pass
        elif kind == "assign":
            for e in st[1]:
                n = e.output_name
                if n not in names:
                    names.append(n)
        else:  # pragma: no cover - the tracer only emits the above
            return None, f"unexpected step {kind}"
    if a.star:
        if a.writes is None:
            return None, "write set unknown"
        for n, _ in a.declared:
            if n in in_names:
                return None, f"declares existing column {n!r} under '*'"
        overlap = sorted(a.writes & set(in_names))
        if overlap:
            # the enforced output dtype of a written passthrough column is
            # its ORIGINAL input dtype — unknowable at plan time
            return (
                None,
                f"writes passthrough column {overlap[0]!r} "
                "(dtype unknown at plan time)",
            )
        out: List[Tuple[str, Any]] = [(c, None) for c in in_names]
        out.extend(a.declared)
    else:
        out = list(a.declared)
    missing = [n for n, _ in out if n not in names]
    if missing:
        return None, f"declared column {missing[0]!r} is never produced"
    steps = list(a.steps or [])
    # schema shaping as (cast-assign, project) rather than one big select:
    # an assign only demands the columns it casts and a project demands
    # exactly its list, so downstream demand keeps narrowing through the
    # translated chain (one select would read EVERY output), and un-cast
    # passthrough columns (e.g. group keys) stay plain for lowering
    casts = [_col(n).cast(t).alias(n) for n, t in out if t is not None]
    if casts:
        steps.append(("assign", tuple(casts)))
    if names != [n for n, _ in out]:
        steps.append(("project", tuple(n for n, _ in out)))
    if not steps:
        steps = [("project", tuple(names))]
    return steps, None


def _splice(nodes: List[LNode], n: LNode, steps: List[Tuple]) -> LNode:
    from ..plan.fused import describe_step

    new_nodes = [_node_for_step(st) for st in steps]
    prev = n.inputs[0]
    for nn in new_nodes:
        nn.inputs = [prev]
        prev = nn
    tail = new_nodes[-1]
    tail.result_of = list(n.result_of)
    tail.tail_origin = n.task
    tail.pinned = n.pinned
    a: UdfAnalysis = n.info["analysis"]
    tail.annotations.append(
        "udf %s[%s] translated: %s"
        % (a.name, a.fp, " | ".join(describe_step(s) for s in steps))
    )
    for c in nodes:
        if n in c.inputs:
            c.inputs = [tail if i is n else i for i in c.inputs]
    pos = nodes.index(n)
    nodes[pos : pos + 1] = new_nodes
    return tail


def expand_udf_transforms(
    nodes: List[LNode], report: Any, translate: bool = True
) -> List[Dict[str, Any]]:
    """Analyze every transform node; attach facts; replace translatable
    ones with plain plan nodes. Returns the per-UDF diagnostics (also
    stored on ``report.udf_diags``)."""
    diags: List[Dict[str, Any]] = []
    for n in list(nodes):
        if n.kind != K_TRANSFORM or n.task is None:
            continue
        a = analyze_transform_task(n.task)
        if a is None:
            continue
        n.info["analysis"] = a
        diag: Dict[str, Any] = {
            "udf": a.name,
            "fp": a.fp,
            "verdict": a.verdict,
            "code": a.code,
            "reason": a.reason,
            "translated": False,
        }
        refusal: Optional[Tuple[str, str]] = None
        final: Optional[List[Tuple]] = None
        if a.steps is None:
            refusal = (
                a.code or "unknown-construct",
                a.reason or "unrecognized construct",
            )
        elif not translate:
            refusal = (
                "disabled",
                "translation disabled (fugue.tpu.plan.translate_udfs=false)",
            )
        elif not n.task.checkpoint.is_null:
            refusal = (
                "pinned",
                "checkpointed transform (storage identity is uuid-keyed)",
            )
        elif len(n.inputs) != 1:
            refusal = ("signature", "multi-input transform")
        else:
            # prior expansions may have changed the graph — infer fresh
            in_names = infer_schemas(nodes).get(id(n.inputs[0]))
            if in_names is None:
                refusal = (
                    "input-schema", "producer schema unknown at plan time"
                )
            else:
                final, err = _build_final_steps(a, list(in_names))
                if final is None:
                    refusal = ("schema", err or "schema mismatch")
        if refusal is not None:
            code, detail = refusal
            diag["code"], diag["reason"] = code, detail
            msg = f"udf {a.name}[{a.fp}]: interpreted -- {detail}"
            n.annotations.append(msg)
            report.note(msg)
            report.udfs_refused += 1
        else:
            assert final is not None
            _splice(nodes, n, final)
            diag["translated"] = True
            diag["verdict"] = "translated"
            diag["code"], diag["reason"] = None, None
            report.udfs_translated += 1
            report.note(
                f"udf {a.name}[{a.fp}]: translated into "
                f"{len(final)} compiled step(s)"
            )
        report.udfs_analyzed += 1
        diags.append(diag)
    report.udf_diags.extend(diags)
    return diags
