"""UDF static analysis (docs/analysis.md).

HiFrames-style (arXiv:1704.02341) AST analysis over plain-Python pandas
per-partition UDFs. For every ``transform`` task the analyzer produces

- exact column **read/write sets** — so the optimizer's backward demand
  analysis no longer treats a UDF as "reads everything" and column
  pruning / filter pushdown commute through it;
- a **purity / determinism / row-locality verdict** — so the delta cache
  (``fugue_tpu/cache/delta.py``) may serve analyzed row-local UDF chains
  incrementally;
- for the recognized shape subset (column arithmetic, comparisons,
  boolean masks, ``fillna``/``clip``/``where``/``mask``, ``np.where``
  conditionals, ``isin``, casts) a **translation** into the SAME step
  tuples the fusion (``fugue_tpu/plan/fused.py``) and segment-lowering
  (``fugue_tpu/plan/lowering.py``) passes already compile — a translated
  UDF fuses into surrounding chains and lowers into single ``shard_map``
  programs.

Soundness over coverage: EVERY unrecognized construct refuses
conservatively to the interpreted path (bit-identical by construction)
with its reason rendered per-UDF in ``workflow.explain()`` and counted in
``engine.stats()["analysis"]``.
"""

from .analyzer import (
    REASON_CODES,
    AnalysisStats,
    UdfAnalysis,
    analyze_transform_task,
    transform_row_local,
)
from .expand import expand_udf_transforms
from .lint import LintDiagnostic, LintReport, lint_tasks

__all__ = [
    "AnalysisStats",
    "LintDiagnostic",
    "LintReport",
    "REASON_CODES",
    "UdfAnalysis",
    "analyze_transform_task",
    "expand_udf_transforms",
    "lint_tasks",
    "transform_row_local",
]
