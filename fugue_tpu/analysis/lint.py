"""``workflow.lint()`` — a no-execution static check pass.

Runs the UDF analyzer plus the existing plan machinery (optimizer dry
run: join-strategy annotation, segment lowering, delta-eligibility
marking, cache description hooks) over a built workflow and returns
STRUCTURED diagnostics: per-UDF verdict + refusal reason, predicted join
strategies, predicted lowered segments, and every optimizer note. Also
rendered by ``workflow.explain(lint=True)``.
"""

from typing import Any, Dict, List, Optional

__all__ = ["LintDiagnostic", "LintReport", "lint_tasks"]


class LintDiagnostic:
    """One structured finding. ``kind`` ∈ {"udf", "join", "segment",
    "note"}; ``status`` is the verdict/strategy/refusal code."""

    __slots__ = ("kind", "name", "status", "message")

    def __init__(self, kind: str, name: str, status: str, message: str):
        self.kind = kind
        self.name = name
        self.status = status
        self.message = message

    def as_dict(self) -> Dict[str, str]:
        return {
            "kind": self.kind,
            "name": self.name,
            "status": self.status,
            "message": self.message,
        }

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"LintDiagnostic({self.kind}:{self.name}:{self.status})"


class LintReport:
    """The result of :meth:`FugueWorkflow.lint`. ``diagnostics`` is the
    structured list; ``plan_report`` the underlying optimizer report."""

    def __init__(self, diagnostics: List[LintDiagnostic], plan_report: Any):
        self.diagnostics = diagnostics
        self.plan_report = plan_report

    def by_kind(self, kind: str) -> List[LintDiagnostic]:
        return [d for d in self.diagnostics if d.kind == kind]

    @property
    def udfs(self) -> List[LintDiagnostic]:
        return self.by_kind("udf")

    def as_dict(self) -> List[Dict[str, str]]:
        return [d.as_dict() for d in self.diagnostics]

    def render(self) -> str:
        lines = ["== lint =="]
        if not self.diagnostics:
            lines.append("  (no findings)")
        for d in self.diagnostics:
            name = f" {d.name}" if d.name else ""
            lines.append(f"  [{d.kind}]{name}: {d.status} -- {d.message}")
        return "\n".join(lines)


def lint_tasks(tasks: List[Any], conf: Any) -> LintReport:
    """Dry-run the optimizer (nothing executes, original tasks are never
    mutated) and fold its structured facts into a LintReport."""
    from ..plan import optimize_tasks

    _run_tasks, _a, _r, report = optimize_tasks(tasks, conf)
    diags: List[LintDiagnostic] = []
    for d in getattr(report, "udf_diags", []):
        status = "translated" if d.get("translated") else str(
            d.get("code") or "refused"
        )
        msg = (
            "translated into compiled steps"
            if d.get("translated")
            else str(d.get("reason") or "refused to the interpreted path")
        )
        diags.append(
            LintDiagnostic("udf", f'{d["udf"]}[{d["fp"]}]', status, msg)
        )
    for j in getattr(report, "join_strategies", []):
        diags.append(
            LintDiagnostic(
                "join", str(j["node"]), str(j["strategy"]), str(j["reason"])
            )
        )
    for s in getattr(report, "segments", []):
        diags.append(LintDiagnostic("segment", s.split(":")[0], "lowered", s))
    seen = {(d.kind, d.name, d.message) for d in diags}
    for nt in report.notes:
        if nt.startswith("udf ") or "strategy=" in nt:
            continue  # already structured above
        if ("note", "", nt) not in seen:
            diags.append(LintDiagnostic("note", "", "info", nt))
    return LintReport(diags, report)
