"""Configuration keys and global defaults.

Parity with the reference's conf surface (``/root/reference/fugue/constants.py``)
plus TPU-engine keys. All keys use the ``fugue.`` prefix so user code written
against the reference conf names keeps working.
"""

from typing import Any, Dict

from ._utils.params import ParamDict

KEYWORD_ROWCOUNT = "ROWCOUNT"
KEYWORD_CONCURRENCY = "CONCURRENCY"
KEYWORD_PARALLELISM = "PARALLELISM"

FUGUE_ENTRYPOINT = "fugue.plugins"

FUGUE_CONF_WORKFLOW_CONCURRENCY = "fugue.workflow.concurrency"
FUGUE_CONF_WORKFLOW_CHECKPOINT_PATH = "fugue.workflow.checkpoint.path"
FUGUE_CONF_WORKFLOW_AUTO_PERSIST = "fugue.workflow.auto_persist"
FUGUE_CONF_WORKFLOW_AUTO_PERSIST_VALUE = "fugue.workflow.auto_persist_value"
FUGUE_CONF_WORKFLOW_EXCEPTION_HIDE = "fugue.workflow.exception.hide"
FUGUE_CONF_WORKFLOW_EXCEPTION_INJECT = "fugue.workflow.exception.inject"
FUGUE_CONF_WORKFLOW_EXCEPTION_OPTIMIZE = "fugue.workflow.exception.optimize"
FUGUE_CONF_SQL_IGNORE_CASE = "fugue.sql.compile.ignore_case"
FUGUE_CONF_SQL_DIALECT = "fugue.sql.compile.dialect"
FUGUE_CONF_DEFAULT_PARTITIONS = "fugue.default.partitions"
FUGUE_CONF_CACHE_PATH = "fugue.workflow.cache.path"

# TPU-engine specific
FUGUE_TPU_CONF_MESH_SHAPE = "fugue.tpu.mesh_shape"
FUGUE_TPU_CONF_ROW_AXIS = "fugue.tpu.row_axis"
FUGUE_TPU_CONF_DEFAULT_BATCH_ROWS = "fugue.tpu.default_batch_rows"
# cap on O(shards x groups) partial-row transfers (distinct cardinality guard)
FUGUE_TPU_CONF_MAX_PARTIAL_ROWS = "fugue.tpu.max_partial_rows"
# debug: cross-check compiled shard_map transformers against the masked
# reference on shard 0 (catches UDFs ignoring the __valid__ contract)
FUGUE_TPU_CONF_VALIDATE_COMPILED = "fugue.tpu.validate_compiled"
# fork-pool size for the general (host pandas) UDF map path; -1 = auto
# (the engine's get_current_parallelism), 0/1 = serial
FUGUE_TPU_CONF_MAP_PARALLELISM = "fugue.tpu.map.parallelism"
# frames below this row count always map serially (pool setup ~100ms)
FUGUE_TPU_CONF_MAP_PARALLEL_MIN_ROWS = "fugue.tpu.map.parallel_min_rows"
# max dense segment-id space for the sort-free keyed compiled map plan
FUGUE_TPU_CONF_DENSE_MAP_RANGE = "fugue.tpu.map.dense_range"
# keep the ingestion arrow table alive on JaxDataFrames for zero-cost host
# reads (global conf; ~2x host memory on ingest-heavy pipelines when True)
FUGUE_TPU_CONF_INGEST_CACHE = "fugue.tpu.ingest_cache"
# --- resilience layer (see fugue_tpu/resilience and docs/resilience.md) ---
# retry policy for fork-pool map chunks: attempts (1 disables retry),
# exponential backoff base/multiplier/cap (seconds) and jitter fraction
FUGUE_TPU_CONF_RETRY_ATTEMPTS = "fugue.tpu.retry.attempts"
FUGUE_TPU_CONF_RETRY_BASE = "fugue.tpu.retry.base"
FUGUE_TPU_CONF_RETRY_MULTIPLIER = "fugue.tpu.retry.multiplier"
FUGUE_TPU_CONF_RETRY_MAX_BACKOFF = "fugue.tpu.retry.max_backoff"
FUGUE_TPU_CONF_RETRY_JITTER = "fugue.tpu.retry.jitter"
# per-workflow-task retry attempts (default 1 = fail fast, matching the
# reference); retried tasks re-consult StrongCheckpoint.exists so finished
# upstream work replays from disk instead of recomputing
FUGUE_TPU_CONF_RETRY_TASK_ATTEMPTS = "fugue.tpu.retry.task.attempts"
# HTTP RPC client retry attempts (connect-phase failures and idempotent
# calls only — a request that may have reached the server is never blindly
# re-sent)
FUGUE_TPU_CONF_RETRY_RPC_ATTEMPTS = "fugue.tpu.retry.rpc.attempts"
# per-chunk wall-clock deadline (seconds) on the fork-pool map path;
# 0/unset = unbounded
FUGUE_TPU_CONF_MAP_CHUNK_TIMEOUT = "fugue.tpu.map.chunk_timeout"
# fault-injection plan (see fugue_tpu/resilience/fault.py for the grammar);
# also settable via the FUGUE_TPU_FAULT_PLAN env var
FUGUE_TPU_CONF_FAULT_PLAN = "fugue.tpu.fault.plan"
# HTTP RPC client socket timeouts (seconds)
FUGUE_RPC_CONF_HTTP_CONNECT_TIMEOUT = "fugue.rpc.http_client.connect_timeout"
FUGUE_RPC_CONF_HTTP_READ_TIMEOUT = "fugue.rpc.http_client.read_timeout"

# --- observability (see fugue_tpu/obs and docs/observability.md) ---
# master switch for the hierarchical span tracer (workflow task → engine
# verb → streaming chunk / map worker attempt); the FUGUE_TPU_TRACE env
# var overrides this in both directions. Disabled costs ~an attribute
# check per instrumented site.
FUGUE_TPU_CONF_TRACE_ENABLED = "fugue.tpu.trace.enabled"
# mirror host spans into the XLA timeline via jax.profiler.TraceAnnotation
# so device and host spans line up in a Perfetto capture (default True;
# only active while tracing is enabled)
FUGUE_TPU_CONF_TRACE_XLA = "fugue.tpu.trace.xla"
# directory to auto-export a Chrome trace-event JSON into after every
# workflow run (one file per run); empty/unset = no auto-export
FUGUE_TPU_CONF_TRACE_DIR = "fugue.tpu.trace.dir"
# span buffer cap; past it new spans are dropped (and counted as dropped)
FUGUE_TPU_CONF_TRACE_MAX_SPANS = "fugue.tpu.trace.max_spans"
# shared spool directory for cluster tracing (ISSUE 18): remote processes
# (dist workers, serve replicas) atomically publish their span buffer +
# resource-sampler ring there as <host>-<pid>.spool.json, and
# obs.assemble_trace merges the spools into ONE Perfetto trace with one
# named track per process. Empty/unset = no spooling (the default; spool
# writes only happen while tracing is also enabled)
FUGUE_TPU_CONF_TRACE_SPOOL_DIR = "fugue.tpu.trace.spool_dir"

# --- cluster flight recorder (fugue_tpu/obs/events.py; ISSUE 18) ---
# master switch for the append-only recovery-event log: lease
# acquire/renew/steal, heartbeat expiry, re-dispatch, orphan
# invalidation, speculative twins, fleet failovers, journal replays —
# typed JSON records carrying the cluster trace id, rendered by
# workflow.timeline() / tools/fugue_timeline.py. Default OFF; the
# FUGUE_TPU_EVENTS env var overrides in both directions. Disabled cost
# is one attribute check per recovery event (which are rare by nature).
FUGUE_TPU_CONF_EVENTS_ENABLED = "fugue.tpu.events.enabled"
# shared directory the per-process event files append into
# (<host>-<pid>.events.jsonl); FUGUE_TPU_EVENTS_DIR env overrides
FUGUE_TPU_CONF_EVENTS_DIR = "fugue.tpu.events.dir"

# --- live telemetry (fugue_tpu/obs/sampler.py + /metrics; ISSUE 6) ---
# master switch for the continuous resource sampler: a daemon thread
# recording device bytes, host RSS, jit/result-cache occupancy and
# pipeline overlap_fraction into a bounded ring buffer — exported as
# Perfetto counter tracks and /metrics gauges. Default OFF; the
# FUGUE_TPU_TELEMETRY env var overrides in both directions. Enabled
# costs <2% (a handful of cheap probes per interval); disabled there is
# no thread at all.
FUGUE_TPU_CONF_TELEMETRY_ENABLED = "fugue.tpu.telemetry.enabled"
# seconds between resource samples (default 0.25)
FUGUE_TPU_CONF_TELEMETRY_INTERVAL = "fugue.tpu.telemetry.interval"
# ring buffer capacity in samples (default 4096; oldest samples drop)
FUGUE_TPU_CONF_TELEMETRY_RING = "fugue.tpu.telemetry.ring_size"
# value of the `workflow` label attached to every span-histogram sample
# during a run (default: a stable 8-hex hash of the workflow's task
# uuids) — the per-tenant attribution key of the future serving layer
FUGUE_TPU_CONF_TELEMETRY_WORKFLOW = "fugue.tpu.telemetry.workflow"

# streaming (out-of-core) execution: rows per host->device chunk; the
# device working set is O(chunk_rows x columns), NOT O(dataset)
FUGUE_TPU_CONF_STREAM_CHUNK_ROWS = "fugue.tpu.stream.chunk_rows"
# depth of the background ingest pipeline's chunk queue (see
# fugue_tpu/jax/pipeline.py and docs/streaming.md): host decode + H2D of
# the NEXT chunks overlap device compute on the CURRENT one; device working
# set grows to O((depth+1) x chunk). 0 disables (strictly serial chunks)
FUGUE_TPU_CONF_STREAM_PREFETCH_DEPTH = "fugue.tpu.stream.prefetch_depth"
# "lo,hi" inclusive int key range for streaming dense aggregates; without
# it the range is probed from the FIRST chunk only, and any later
# out-of-range key raises (one-pass streams can't be re-scanned)
FUGUE_TPU_CONF_STREAM_KEY_RANGE = "fugue.tpu.stream.key_range"

# logical plan optimizer (fugue_tpu/plan, docs/plan.md): rewrites the task
# DAG at workflow.run() time. The master switch gates all passes; each pass
# can also be disabled individually. All default ON; every rewrite is
# result-identical to the unoptimized path (tests/plan/test_optimizer.py).
# plan.* keys are per-run compile switches: workflow.run() honors them from
# engine conf, run conf AND workflow compile_conf, without writing the
# compile_conf values back into a (possibly shared) engine's conf.
FUGUE_TPU_CONF_PLAN_PREFIX = "fugue.tpu.plan."
FUGUE_TPU_CONF_PLAN_OPTIMIZE = "fugue.tpu.plan.optimize"
# column pruning: push projections into create/load/stream producers so
# columns no downstream task reads are never decoded or H2D-transferred
FUGUE_TPU_CONF_PLAN_PRUNE = "fugue.tpu.plan.prune"
# filter pushdown: hoist filters through projections/renames/joins toward
# the producer so invalid rows are masked before device work
FUGUE_TPU_CONF_PLAN_PUSHDOWN = "fugue.tpu.plan.pushdown"
# verb fusion: collapse adjacent select/filter/assign chains into one
# FusedVerbs task (single jitted step on the jax engine; per-chunk on
# streams)
FUGUE_TPU_CONF_PLAN_FUSE = "fugue.tpu.plan.fuse"
# whole-plan SPMD segment lowering (docs/plan.md): after fusion, collapse
# maximal device-resident segments — a row-local verb chain flowing into a
# dense aggregate / take / distinct / broadcast-join probe — into ONE
# LoweredSegment task the jax engine compiles to a single shard_map-
# partitioned XLA program (per-segment fallback to the per-verb path on
# any lowering refusal keeps results bit-identical)
FUGUE_TPU_CONF_PLAN_LOWER_SEGMENTS = "fugue.tpu.plan.lower_segments"

# UDF static analysis (fugue_tpu/analysis, docs/analysis.md): AST-trace
# plain-Python pandas UDFs into exact column read/write sets, purity/
# row-locality verdicts, and (for the recognized shape subset) a
# translation into the compiled step pipeline. analyze_udfs=false
# restores the fully conservative pre-analysis treatment inside the
# optimizer (UDFs demand ALL columns, never translate);
# translate_udfs=false keeps the facts (pruning/pushdown still commute
# through analyzed UDFs) but always runs UDFs on the interpreted path.
# Both default ON; every refusal is bit-identical by construction. The
# plan.* prefix keeps them per-run compile switches (never written into a
# shared engine's conf).
FUGUE_TPU_CONF_PLAN_ANALYZE_UDFS = "fugue.tpu.plan.analyze_udfs"
FUGUE_TPU_CONF_PLAN_TRANSLATE_UDFS = "fugue.tpu.plan.translate_udfs"

# content-addressed result cache (fugue_tpu/cache, docs/cache.md): memoize
# task outputs ACROSS runs, keyed on canonical post-optimization plan
# fingerprints. Master switch (default ON — with no cache.dir the cache is
# memory-only and scoped to one engine); =false is byte-for-byte the
# pre-cache execution path.
FUGUE_TPU_CONF_CACHE_ENABLED = "fugue.tpu.cache.enabled"
# artifact-store directory (shared across processes; atomic publishes).
# Empty/unset = no disk tier. The FUGUE_TPU_CACHE_DIR env var is the
# fallback when the conf key is unset. An unwritable dir degrades the
# cache to memory-only with a single warning.
FUGUE_TPU_CONF_CACHE_DIR = "fugue.tpu.cache.dir"
# byte budget of the in-process LRU over live result frames
FUGUE_TPU_CONF_CACHE_MEM_BYTES = "fugue.tpu.cache.mem_bytes"
# size cap of the on-disk artifact store; LRU-evicted past it
FUGUE_TPU_CONF_CACHE_DISK_BYTES = "fugue.tpu.cache.disk_bytes"
# frames larger than this are never written to the disk tier (still
# memory-cached when they fit the mem budget)
FUGUE_TPU_CONF_CACHE_MAX_ARTIFACT_BYTES = "fugue.tpu.cache.max_artifact_bytes"
# CreateData tables above this are REFUSED (poisoned), not content-hashed
FUGUE_TPU_CONF_CACHE_FINGERPRINT_MAX_BYTES = "fugue.tpu.cache.fingerprint_max_bytes"
# free-form namespace mixed into every fingerprint: bump it to invalidate
# all entries without deleting files
FUGUE_TPU_CONF_CACHE_SALT = "fugue.tpu.cache.salt"
# partition-level incremental recompute (docs/cache.md "Incremental
# recompute"): a warm run over a GROWN Load source recomputes only the new
# partitions and merges with the cached result/partial accumulator.
# Default ON; =false restores the all-or-nothing whole-task cache.
FUGUE_TPU_CONF_CACHE_DELTA_ENABLED = "fugue.tpu.cache.delta.enabled"
# artifact-COUNT cap of the on-disk store (per-partition delta artifacts
# multiply small files; bytes alone don't bound inode pressure). mtime-LRU
# evicted past it, alongside the disk_bytes cap. 0 = unlimited.
FUGUE_TPU_CONF_CACHE_DISK_MAX_ENTRIES = "fugue.tpu.cache.disk_max_entries"

# out-of-core hash shuffle (fugue_tpu/shuffle, docs/shuffle.md): spill
# key-hash buckets to disk, then join/repartition bucket-at-a-time so
# inputs FAR past device memory complete under a bounded device working
# set. Master switch (default ON; =false restores the pre-shuffle ladder:
# broadcast / in-device copartition / host fallback).
FUGUE_TPU_CONF_SHUFFLE_ENABLED = "fugue.tpu.shuffle.enabled"
# spill-file directory; unset = <tempdir>/fugue_tpu_shuffle. Each shuffle
# creates a unique subdirectory, removed on success AND on failure.
FUGUE_TPU_CONF_SHUFFLE_DIR = "fugue.tpu.shuffle.dir"
# explicit bucket count P (0 = auto from size estimate / bucket_bytes)
FUGUE_TPU_CONF_SHUFFLE_BUCKETS = "fugue.tpu.shuffle.buckets"
# target on-disk bytes per bucket when auto-sizing P; each bucket pair
# must fit the device budget TOGETHER with the join's intermediates, so
# keep this a small fraction (default 1/32) of device_budget_bytes
FUGUE_TPU_CONF_SHUFFLE_BUCKET_BYTES = "fugue.tpu.shuffle.bucket_bytes"
# the device byte budget joins must stay under: size estimates past it
# pick the spill-shuffle strategy. 0/unset = auto (device memory stats
# when the backend reports them, else half of host MemTotal).
FUGUE_TPU_CONF_SHUFFLE_DEVICE_BUDGET = "fugue.tpu.shuffle.device_budget_bytes"
# right sides at or under this row count use the broadcast join strategy
# (default: ops/join.py MAX_BROADCAST_ROWS). Conf-driven so deployments
# can trade replication memory against exchange latency per mesh.
FUGUE_TPU_CONF_JOIN_BROADCAST_MAX_ROWS = "fugue.tpu.join.broadcast_max_rows"
# --- pipelined out-of-core exchange (docs/shuffle.md "Pipelined
# exchange") --- kill-switch for the overlapped spill pipeline:
# write-behind bucket writes, the memory-resident bucket tier, and
# bucket-pair prefetch + budget-bounded pair grouping in the spill join.
# =false restores the strict phase-barrier PR 8 path bit-identically
# (identical span multisets, identical per-bucket chunking).
FUGUE_TPU_CONF_SHUFFLE_PIPELINE_ENABLED = "fugue.tpu.shuffle.pipeline.enabled"
# host-byte ledger for the memory-resident bucket tier: buckets whose
# accumulated arrow bytes fit this budget never touch disk (demoted
# largest-first under pressure; demoted buckets keep the full
# write+publish+recovery discipline). 0/unset = auto (1/16 of host
# MemTotal, capped at 256MiB); negative disables the tier.
FUGUE_TPU_CONF_SHUFFLE_MEM_BUCKET_BYTES = "fugue.tpu.shuffle.mem_bucket_bytes"
# bucket-pair prefetch depth for the spill join's consumer: the producer
# reads+decodes+pads+device-ingests pair group i+1 while the kernel runs
# group i. unset = the stream prefetcher's auto default (0 on single-core
# cpu-mesh hosts, where a producer thread only steals consumer time);
# <=0 = consume serially (still grouped + mem-tiered when the pipeline
# is enabled).
FUGUE_TPU_CONF_SHUFFLE_PREFETCH_DEPTH = "fugue.tpu.shuffle.prefetch_depth"
# bounded write-behind queue depth (bucket batches in flight to the
# background spill writer thread before the partitioner blocks)
FUGUE_TPU_CONF_SHUFFLE_WRITEBEHIND_DEPTH = "fugue.tpu.shuffle.writebehind_depth"
# --- device-resident exchange (docs/shuffle.md "Device exchange") ---
# kill-switch for the device_exchange strategy rung: joins whose sides
# exceed the per-device budget but fit aggregate mesh memory exchange
# rows on-device with a staged one-hop-at-a-time collective schedule
# instead of spilling. =false restores the three-rung ladder (such
# joins spill, bit-identically to the pre-exchange behavior).
FUGUE_TPU_CONF_SHUFFLE_DEVICE_EXCHANGE_ENABLED = (
    "fugue.tpu.shuffle.device_exchange.enabled"
)
# per-stage collective payload cap for the staged exchange schedule, in
# bytes per device. 0/unset = auto (1/8 of device_budget_bytes — small
# enough that a stage buffer never threatens the budget, large enough
# that per-stage fixed costs amortize across the schedule).
FUGUE_TPU_CONF_SHUFFLE_EXCHANGE_STAGE_BYTES = (
    "fugue.tpu.shuffle.device_exchange.stage_bytes"
)

# --- multi-tenant serving layer (fugue_tpu/serve, docs/serving.md) ---
# concurrent workflow executions one EngineServer runs at a time (its
# worker-thread pool size); everything past it waits in the admission queue
FUGUE_TPU_CONF_SERVE_MAX_CONCURRENT = "fugue.tpu.serve.max_concurrent"
# admission queue capacity: submissions past it are REJECTED (the /readyz
# readiness endpoint reports "overloaded" with a 503 before that happens,
# so a load balancer can shed first)
FUGUE_TPU_CONF_SERVE_QUEUE_DEPTH = "fugue.tpu.serve.queue_depth"
# priority for submissions that don't name one (lower = sooner; ties FIFO)
FUGUE_TPU_CONF_SERVE_DEFAULT_PRIORITY = "fugue.tpu.serve.default_priority"
# starvation guard: a queued execution's effective priority improves by
# one level per aging_s seconds waited, so FIFO-within-priority can never
# starve the lowest level under a steady high-priority stream. 0 disables.
FUGUE_TPU_CONF_SERVE_AGING_S = "fugue.tpu.serve.aging_s"
# bytes charged against a tenant's budget per admitted submission when
# the submission doesn't declare its own reserve_bytes (replaced by the
# measured result bytes once the run finishes — live accounting)
FUGUE_TPU_CONF_SERVE_RESERVE_BYTES = "fugue.tpu.serve.reserve_bytes"
# how many completed submissions the server retains for result pickup
# (oldest evicted past it; their tenant byte charge releases on eviction)
FUGUE_TPU_CONF_SERVE_RETAIN = "fugue.tpu.serve.retain"
# per-tenant overlays: fugue.tpu.serve.tenant.<id>.priority (scheduling
# default), fugue.tpu.serve.tenant.<id>.budget_bytes (admission gate:
# live charged bytes + the new reserve must stay under it; 0 = unlimited),
# and fugue.tpu.serve.tenant.<id>.conf.<key> (per-run conf overlay — any
# fugue.tpu.* key: workflow.run scopes conf per run, so an overlay can
# never leak into another tenant's run; non-fugue.tpu keys are dropped
# with a warning)
FUGUE_TPU_CONF_SERVE_TENANT_PREFIX = "fugue.tpu.serve.tenant."
# keys every tenant conf overlay must start with (run-scoped by the
# workflow.run conf overlay; see docs/serving.md)
FUGUE_TPU_CONF_SERVE_TENANT_OVERLAY_PREFIX = "fugue.tpu."
# distinct tenant ids the serving layer keeps state for (per-tenant stats
# breakdown, parsed tenant policies, the one-warning-per-tenant set) —
# least-recently-seen tenants past it are evicted, the same LRU
# discipline as the serve.retain retention ring: a hostile client minting
# tenant ids must not leak memory in a long-lived server
FUGUE_TPU_CONF_SERVE_MAX_TENANTS = "fugue.tpu.serve.max_tenants"

# --- serving fleet (fugue_tpu/serve/fleet.py, docs/serving.md "Fleet") ---
# master switch for cross-replica coordination. ON by default but only
# ACTIVE when the engine mounts a shared disk store (fugue.tpu.cache.dir)
# — replicas sharing that directory collapse identical submissions across
# processes via claim files and serve each other's published results.
# =false (or a single replica with no shared store) preserves the
# single-server behavior bit-identically, including the /serve/* wire
# contract.
FUGUE_TPU_CONF_SERVE_FLEET_ENABLED = "fugue.tpu.serve.fleet.enabled"
# claim lease in seconds: a claim older than this whose owner can't be
# proven alive is STEALABLE — a dead replica's in-flight plan is taken
# over by whichever waiter gets the atomic claim rewrite in first. A
# same-host owner with a dead pid is stealable immediately.
FUGUE_TPU_CONF_SERVE_FLEET_LEASE_S = "fugue.tpu.serve.fleet.lease_s"
# how often a cross-replica waiter re-checks the shared store for the
# owner's published result (and the owner's claim for expiry)
FUGUE_TPU_CONF_SERVE_FLEET_POLL_S = "fugue.tpu.serve.fleet.poll_s"
# published serve-result payloads kept in the shared store (mtime-LRU
# eviction past it, the ArtifactStore discipline)
FUGUE_TPU_CONF_SERVE_FLEET_MAX_RESULTS = "fugue.tpu.serve.fleet.max_results"
# this replica's stable identity in claim files / journal names /
# /readyz; default "<hostname>-<pid>" (unique per process)
FUGUE_TPU_CONF_SERVE_REPLICA_ID = "fugue.tpu.serve.replica_id"
# crash-safe submission journal: the directory holding each replica's
# append-only fsync'd WAL (<replica_id>.jsonl). Unset (default) disables
# journaling; on restart a replica REPLAYS its own unfinished entries
# under their original idempotency keys (docs/serving.md "Fleet").
FUGUE_TPU_CONF_SERVE_JOURNAL_DIR = "fugue.tpu.serve.journal.dir"
# journal compaction threshold (bytes): past it the WAL is rewritten
# atomically with every terminal submission's records dropped — replay
# semantics are provably unchanged (unfinished() parity). 0 disables.
FUGUE_TPU_CONF_SERVE_JOURNAL_MAX_BYTES = "fugue.tpu.serve.journal.max_bytes"

# --- continuous views (fugue_tpu/views, docs/views.md) ---
# master kill-switch, default OFF: =false means no registration
# endpoints (they 404), no watcher threads, and a serve wire contract /
# span multiset bit-identical to the pre-views tiers. Turning it on
# requires a shared store (fugue.tpu.cache.dir) — the registry, heads,
# leases and generation payloads all live there so any replica can serve
# while exactly one maintains.
FUGUE_TPU_CONF_VIEWS_ENABLED = "fugue.tpu.views.enabled"
# watcher loop interval in seconds: how often the maintainer re-observes
# every watched source (and renews its watch leases)
FUGUE_TPU_CONF_VIEWS_POLL_S = "fugue.tpu.views.poll_s"
# per-view watch lease duration: a lease this old whose holder cannot be
# proven alive (dist heartbeat / same-host pid probe) is stealable — the
# exactly-one-maintainer guarantee under replica death
FUGUE_TPU_CONF_VIEWS_LEASE_S = "fugue.tpu.views.lease_s"
# published generations retained per view beyond the pinned latest one
# (older generation payloads are deleted by the maintainer on publish)
FUGUE_TPU_CONF_VIEWS_KEEP_GENERATIONS = "fugue.tpu.views.keep_generations"
# how many priority points an SLO-at-risk refresh gains (priority is
# min-wins, so the boost SUBTRACTS; floor 0)
FUGUE_TPU_CONF_VIEWS_SLO_BOOST = "fugue.tpu.views.slo_boost"
# fraction of a tenant's freshness_s after which a pending refresh counts
# as at-risk and takes the boost (breach itself is at 1.0)
FUGUE_TPU_CONF_VIEWS_SLO_RISK_FRACTION = "fugue.tpu.views.slo_risk_fraction"
# registered views cap (bounds /metrics cardinality and registry scans)
FUGUE_TPU_CONF_VIEWS_MAX = "fugue.tpu.views.max"
# how long a maintainer waits for one refresh submission to finish before
# counting it failed and retrying next tick
FUGUE_TPU_CONF_VIEWS_REFRESH_TIMEOUT_S = "fugue.tpu.views.refresh_timeout_s"

# --- multi-host worker tier (fugue_tpu/dist, docs/distributed.md) ---
# master kill-switch: =false makes DistSupervisor.run_* execute the whole
# job serially in THIS process (same functions, same bucket order) —
# bit-identical to the distributed result by construction.
FUGUE_TPU_CONF_DIST_ENABLED = "fugue.tpu.dist.enabled"
# task lease duration: a lease this old whose owner cannot be proven
# alive (heartbeat) is stealable by any live worker; owners renew at
# lease_s/3 while executing, so only a dead or wedged owner expires.
FUGUE_TPU_CONF_DIST_LEASE_S = "fugue.tpu.dist.lease_s"
# heartbeat protocol: every worker/replica writes
# <heartbeat.dir>/<id>.hb.json every interval_s (atomic rename); a
# heartbeat older than stale_after_s is PROOF of death for lease/claim
# stealing — the cross-host replacement for same-host pid probes. The
# dir is shared by the dist worker tier AND the serve fleet (an
# EngineServer with this key set writes heartbeats under its replica_id,
# and claim stealing in cache/store.py consults them).
FUGUE_TPU_CONF_DIST_HB_DIR = "fugue.tpu.dist.heartbeat.dir"
FUGUE_TPU_CONF_DIST_HB_INTERVAL_S = "fugue.tpu.dist.heartbeat.interval_s"
FUGUE_TPU_CONF_DIST_HB_STALE_S = "fugue.tpu.dist.heartbeat.stale_after_s"
# shuffle-fragment fetch mode: "auto" reads the producer's file directly
# when its path is visible on this host's filesystem and falls back to
# the producer's HTTP /dist/fetch route; "remote" always fetches over
# HTTP except from this worker's own dir (the true multi-host shape —
# what the chaos gate runs); "local" never fetches (single-host tier).
FUGUE_TPU_CONF_DIST_FETCH = "fugue.tpu.dist.fetch"
# reduce-side bucket count for the network-partitioned exchange
FUGUE_TPU_CONF_DIST_BUCKETS = "fugue.tpu.dist.buckets"
# straggler mitigation: a task leased (and renewed) by a LIVE owner for
# longer than this is marked speculative — a second worker re-executes
# it and the first published done-record wins (artifacts are content-
# addressed, so the loser's publish dedups). 0 (default) disables.
FUGUE_TPU_CONF_DIST_SPECULATIVE_AFTER_S = "fugue.tpu.dist.speculative_after_s"
# supervisor/worker poll cadence over the shared board
FUGUE_TPU_CONF_DIST_POLL_S = "fugue.tpu.dist.poll_s"
# reduce-side fragment prefetch depth: fragments for a bucket are fetched
# (local read or remote /dist/fetch) through a depth-bounded background
# producer so network/disk fetch of fragment i+1 overlaps the decode and
# reduce compute of fragment i. <=0 = fetch serially (the pre-pipeline
# shape); default 2 (network fetch releases the GIL, so the overlap is
# real even on single-core hosts).
FUGUE_TPU_CONF_DIST_FETCH_PREFETCH_DEPTH = "fugue.tpu.dist.fetch_prefetch_depth"
# shared task-board root for DISTRIBUTED WORKFLOW execution: when set (and
# dist.enabled is true), workflow.run hands distributable fragments of the
# post-optimization DAG — Load roots + row-local chains into an equi-join,
# keyed aggregate, or bucket-local SQL SELECT — to
# DistSupervisor.run_workflow_job as leased board tasks
# (fugue_tpu/plan/distribute.py, docs/distributed.md "Distributed
# workflows"). Unset (default) = planner inert, fully local execution.
FUGUE_TPU_CONF_DIST_BOARD = "fugue.tpu.dist.board"
# wall-clock timeout (seconds) for one distributed workflow fragment's
# board job; 0/unset = unbounded (recovery is driven by leases, not this)
FUGUE_TPU_CONF_DIST_WORKFLOW_TIMEOUT_S = "fugue.tpu.dist.workflow_timeout_s"
# wall-clock deadline (seconds) across ALL RetryPolicy-driven attempts of
# one /dist/fetch fragment fetch (conf prefix fugue.tpu.retry.dist.*);
# past it the fetch stops retrying and the orphaned-fragment ladder runs
FUGUE_TPU_CONF_RETRY_DIST_DEADLINE_S = "fugue.tpu.retry.dist.deadline_s"

# --- cost-based adaptive execution (fugue_tpu/tuning, docs/tuning.md) ---
# Feedback layer that re-derives stream chunk size / prefetch depth and
# shuffle bucket sizing from the engine's OWN telemetry (pipeline stats,
# spill-join observations), keyed by plan fingerprint. Master kill-switch:
# =false restores the static-conf behavior bit-identically (no store
# reads, no writes, every knob resolves exactly as before this layer).
# Per-workflow/compile-conf scoped like fugue.tpu.plan.* — workflow.run
# never writes fugue.tpu.tuning.* into a shared engine's conf.
FUGUE_TPU_CONF_TUNING_ENABLED = "fugue.tpu.tuning.enabled"
FUGUE_TPU_CONF_TUNING_PREFIX = "fugue.tpu.tuning."
# where learned settings persist (atomic temp-write+rename; corrupt or
# unwritable files degrade to defaults with ONE warning). Default: the
# package's ops/_tuned.json, next to the dense-sum A/B winner; the
# FUGUE_TPU_TUNING_PATH env var overrides (test isolation).
FUGUE_TPU_CONF_TUNING_PATH = "fugue.tpu.tuning.path"
# plan-fingerprint entries kept in the store; least-recently-used past it
# are evicted at publish time (stale-plan hygiene for long-lived servers)
FUGUE_TPU_CONF_TUNING_MAX_ENTRIES = "fugue.tpu.tuning.max_entries"
# per-verb roofline recording (ISSUE 18, ROADMAP item 5 groundwork):
# while tracing is enabled the jax engine folds each verb's achieved
# bytes/s and rows/s into the TunedStore's "rooflines" key (same atomic
# publish + LRU bounds), rendered by engine.report(). Record-only — no
# placement decision reads it yet. Default ON (cost: one in-memory fold
# per traced verb close; nothing at all while tracing is off).
FUGUE_TPU_CONF_TUNING_ROOFLINES = "fugue.tpu.tuning.rooflines"

FUGUE_COMPILE_TIME_CONFIGS = {
    FUGUE_CONF_WORKFLOW_AUTO_PERSIST,
    FUGUE_CONF_WORKFLOW_AUTO_PERSIST_VALUE,
    FUGUE_CONF_SQL_IGNORE_CASE,
    FUGUE_CONF_SQL_DIALECT,
}

_FUGUE_GLOBAL_CONF = ParamDict(
    {
        FUGUE_CONF_WORKFLOW_CONCURRENCY: 1,
        FUGUE_CONF_WORKFLOW_AUTO_PERSIST: False,
        FUGUE_CONF_WORKFLOW_EXCEPTION_HIDE: "fugue.,fugue_tpu.,concurrent.,"
        "pandas.,pyarrow.,jax.,numpy.",
        FUGUE_CONF_WORKFLOW_EXCEPTION_INJECT: 3,
        FUGUE_CONF_WORKFLOW_EXCEPTION_OPTIMIZE: True,
        FUGUE_CONF_SQL_IGNORE_CASE: False,
        FUGUE_CONF_SQL_DIALECT: "spark",
        FUGUE_CONF_DEFAULT_PARTITIONS: -1,
    }
)


def register_global_conf(conf: Dict[str, Any], on_dup: int = ParamDict.OVERWRITE) -> None:
    """Merge keys into the process-level global conf (lowest priority layer)."""
    _FUGUE_GLOBAL_CONF.update(conf, on_dup=on_dup)
