"""Arrow-backed Schema with a compact string expression syntax.

In-tree replacement for ``triad.Schema`` which the reference depends on for
its entire data model (SURVEY.md §0). The expression grammar matches the
reference's user-facing syntax so transformer schema hints (``# schema:``)
and ``transform(..., schema="*,c:int")`` behave identically
(reference behavior: ``fugue/extensions/transformer/convert.py:357-363``):

    schema  := pair ("," pair)*
    pair    := name ":" type
    type    := primitive | "[" type "]"           (list)
             | "{" schema "}"                     (struct)
             | "<" type "," type ">"              (map)
             | "decimal(p[,s])" | "timestamp(unit[,tz])"
    name    := identifier | `backquoted name`

Primitives: bool, byte/int8, short/int16, int/int32, long/int64,
uint8..uint64, float16, float/float32, double/float64, str/string,
date, datetime (timestamp us), binary/bytes, null.
"""

from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple, Union

import pandas as pd
import pyarrow as pa

from ._utils.params import IndexedOrderedDict
from .exceptions import FugueDataFrameOperationError

_PRIMITIVES: Dict[str, pa.DataType] = {
    "null": pa.null(),
    "bool": pa.bool_(),
    "boolean": pa.bool_(),
    "byte": pa.int8(),
    "int8": pa.int8(),
    "short": pa.int16(),
    "int16": pa.int16(),
    "int": pa.int32(),
    "int32": pa.int32(),
    "long": pa.int64(),
    "int64": pa.int64(),
    "ubyte": pa.uint8(),
    "uint8": pa.uint8(),
    "ushort": pa.uint16(),
    "uint16": pa.uint16(),
    "uint": pa.uint32(),
    "uint32": pa.uint32(),
    "ulong": pa.uint64(),
    "uint64": pa.uint64(),
    "float16": pa.float16(),
    "float": pa.float32(),
    "float32": pa.float32(),
    "double": pa.float64(),
    "float64": pa.float64(),
    "str": pa.string(),
    "string": pa.string(),
    "date": pa.date32(),
    "datetime": pa.timestamp("us"),
    "binary": pa.binary(),
    "bytes": pa.binary(),
}

_TYPE_TO_EXPR: Dict[pa.DataType, str] = {
    pa.null(): "null",
    pa.bool_(): "bool",
    pa.int8(): "byte",
    pa.int16(): "short",
    pa.int32(): "int",
    pa.int64(): "long",
    pa.uint8(): "uint8",
    pa.uint16(): "uint16",
    pa.uint32(): "uint32",
    pa.uint64(): "uint64",
    pa.float16(): "float16",
    pa.float32(): "float",
    pa.float64(): "double",
    pa.string(): "str",
    pa.large_string(): "str",
    pa.date32(): "date",
    pa.timestamp("us"): "datetime",
    pa.binary(): "binary",
    pa.large_binary(): "binary",
}


def _split_top(s: str, sep: str) -> List[str]:
    """Split on ``sep`` at bracket depth 0, honoring backtick quoting."""
    parts: List[str] = []
    depth = 0
    quoted = False
    cur: List[str] = []
    for ch in s:
        if ch == "`":
            quoted = not quoted
            cur.append(ch)
        elif quoted:
            cur.append(ch)
        elif ch in "[{<(":
            depth += 1
            cur.append(ch)
        elif ch in "]}>)":
            depth -= 1
            cur.append(ch)
        elif ch == sep and depth == 0:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    parts.append("".join(cur))
    return parts


def _parse_type(expr: str) -> pa.DataType:
    s = expr.strip()
    if s == "":
        raise SyntaxError("empty type expression")
    if s.startswith("[") and s.endswith("]"):
        return pa.list_(_parse_type(s[1:-1]))
    if s.startswith("{") and s.endswith("}"):
        inner = s[1:-1].strip()
        fields = [] if inner == "" else _parse_fields(inner)
        return pa.struct(fields)
    if s.startswith("<") and s.endswith(">"):
        kv = _split_top(s[1:-1], ",")
        if len(kv) != 2:
            raise SyntaxError(f"invalid map type {expr}")
        return pa.map_(_parse_type(kv[0]), _parse_type(kv[1]))
    if s.startswith("decimal(") and s.endswith(")"):
        args = [int(x) for x in s[len("decimal(") : -1].split(",")]
        return pa.decimal128(*args)
    if s.startswith("timestamp(") and s.endswith(")"):
        args = [x.strip() for x in s[len("timestamp(") : -1].split(",")]
        return pa.timestamp(args[0], tz=args[1] if len(args) > 1 else None)
    if s in _PRIMITIVES:
        return _PRIMITIVES[s]
    raise SyntaxError(f"unknown type expression {expr!r}")


def _parse_fields(expr: str) -> List[pa.Field]:
    fields: List[pa.Field] = []
    for part in _split_top(expr, ","):
        part = part.strip()
        if part == "":
            raise SyntaxError(f"invalid schema expression {expr!r}")
        nt = _split_top(part, ":")
        if len(nt) != 2:
            raise SyntaxError(f"invalid field expression {part!r}")
        name = nt[0].strip()
        if name.startswith("`") and name.endswith("`") and len(name) >= 2:
            name = name[1:-1]
        if name == "":
            raise SyntaxError(f"empty field name in {part!r}")
        fields.append(pa.field(name, _parse_type(nt[1])))
    return fields


def expression_to_schema(expr: str) -> pa.Schema:
    return pa.schema(_parse_fields(expr))


def to_pa_datatype(obj: Any) -> pa.DataType:
    """Convert a string expression / python type / numpy dtype to arrow."""
    import numpy as np

    if isinstance(obj, pa.DataType):
        return obj
    if isinstance(obj, str):
        return _parse_type(obj)
    if obj is int:
        return pa.int64()
    if obj is float:
        return pa.float64()
    if obj is str:
        return pa.string()
    if obj is bool:
        return pa.bool_()
    if obj is bytes:
        return pa.binary()
    import datetime

    if obj is datetime.datetime:
        return pa.timestamp("us")
    if obj is datetime.date:
        return pa.date32()
    if isinstance(obj, (np.dtype, type)):
        return pa.from_numpy_dtype(obj)
    raise TypeError(f"can't convert {obj!r} to pyarrow DataType")


def type_to_expression(tp: pa.DataType) -> str:
    if tp in _TYPE_TO_EXPR:
        return _TYPE_TO_EXPR[tp]
    if pa.types.is_timestamp(tp):
        if tp.tz is None:
            return "datetime" if tp.unit == "us" else f"timestamp({tp.unit})"
        return f"timestamp({tp.unit},{tp.tz})"
    if pa.types.is_decimal(tp):
        return f"decimal({tp.precision},{tp.scale})"
    if pa.types.is_large_list(tp) or pa.types.is_list(tp):
        return f"[{type_to_expression(tp.value_type)}]"
    if pa.types.is_struct(tp):
        inner = ",".join(f"{f.name}:{type_to_expression(f.type)}" for f in tp)
        return "{" + inner + "}"
    if pa.types.is_map(tp):
        return f"<{type_to_expression(tp.key_type)},{type_to_expression(tp.item_type)}>"
    if pa.types.is_date(tp):
        return "date"
    raise NotImplementedError(f"can't convert {tp} to expression")


def _quote_name(name: str) -> str:
    if name.isidentifier():
        return name
    return f"`{name}`"


def _normalize_type(tp: pa.DataType) -> pa.DataType:
    """Canonicalize types coming from external data (large_* → plain)."""
    if pa.types.is_dictionary(tp):
        return _normalize_type(tp.value_type)
    if pa.types.is_large_string(tp):
        return pa.string()
    if pa.types.is_large_binary(tp):
        return pa.binary()
    if pa.types.is_large_list(tp):
        return pa.list_(_normalize_type(tp.value_type))
    if pa.types.is_list(tp):
        return pa.list_(_normalize_type(tp.value_type))
    if pa.types.is_struct(tp):
        return pa.struct([pa.field(f.name, _normalize_type(f.type)) for f in tp])
    if pa.types.is_date(tp):
        return pa.date32()
    return tp


class Schema(IndexedOrderedDict):
    """Ordered ``name → pa.Field`` mapping with set-like operations.

    Accepts: expression strings, ``pa.Schema``/``pa.Field``, pandas frames,
    other Schemas, dicts, lists/tuples of any of these, and kwargs.
    """

    def __init__(self, *args: Any, **kwargs: Any):
        super().__init__()
        if len(args) > 0 and len(kwargs) > 0:
            raise SyntaxError("can't set both args and kwargs")
        for a in args:
            self.append(a)
        for k, v in kwargs.items():
            self._append_field(pa.field(k, to_pa_datatype(v)))

    # -- construction ------------------------------------------------------
    def _append_field(self, field: pa.Field) -> None:
        if field.name in self:
            raise SchemaError(f"duplicated field name {field.name!r}")
        if field.name == "":
            raise SchemaError("field name can't be empty")
        field = pa.field(field.name, _normalize_type(field.type))
        self[field.name] = field

    def append(self, obj: Any) -> "Schema":
        if obj is None:
            return self
        if isinstance(obj, pa.Field):
            self._append_field(obj)
        elif isinstance(obj, str):
            for f in _parse_fields(obj):
                self._append_field(f)
        elif isinstance(obj, Schema):
            for f in obj.fields:
                self._append_field(f)
        elif isinstance(obj, pa.Schema):
            for f in obj:
                self._append_field(f)
        elif isinstance(obj, pd.DataFrame):
            self.append(_pandas_to_pa_schema(obj))
        elif isinstance(obj, Dict):
            for k, v in obj.items():
                self._append_field(pa.field(k, to_pa_datatype(v)))
        elif isinstance(obj, tuple) and len(obj) == 2 and isinstance(obj[0], str):
            self._append_field(pa.field(obj[0], to_pa_datatype(obj[1])))
        elif isinstance(obj, Iterable):
            for x in obj:
                self.append(x)
        else:
            raise SchemaError(f"can't append {obj!r} to schema")
        return self

    def copy(self) -> "Schema":
        return Schema(self.fields)

    # -- accessors ---------------------------------------------------------
    @property
    def names(self) -> List[str]:
        return list(self.keys())

    @property
    def fields(self) -> List[pa.Field]:
        return list(self.values())

    @property
    def types(self) -> List[pa.DataType]:
        return [f.type for f in self.values()]

    def _derived_cache(self, key: str, build: Any) -> Any:
        """Version-stamped memo for derived views — schema objects are read
        per logical partition in map loops, so rebuilding these each access
        is a real hot-loop cost."""
        v = (getattr(self, "_version", 0), len(self))
        hit = self.__dict__.get(key)
        if hit is not None and hit[0] == v:
            return hit[1]
        res = build()
        self.__dict__[key] = (v, res)
        return res

    @property
    def pa_schema(self) -> pa.Schema:
        return self._derived_cache(
            "_pa_schema_memo", lambda: pa.schema(self.fields)
        )

    @property
    def pandas_dtype(self) -> Dict[str, Any]:
        # copy: callers may legitimately mutate the returned mapping
        return dict(
            self._derived_cache("_pandas_dtype_memo", self._build_pandas_dtype)
        )

    def _build_pandas_dtype(self) -> Dict[str, Any]:
        return {
            f.name: pd.api.types.pandas_dtype(f.type.to_pandas_dtype())
            if not pa.types.is_nested(f.type)
            and not pa.types.is_string(f.type)
            and not pa.types.is_binary(f.type)
            and not pa.types.is_null(f.type)
            else pd.api.types.pandas_dtype(object)
            for f in self.fields
        }

    def get_field(self, name: str) -> pa.Field:
        return self[name]

    def index_of_key(self, key: str) -> int:
        return super().index_of_key(key)

    def __getitem__(self, key: Any) -> Any:
        if isinstance(key, int):
            return self.get_value_by_index(key)
        if isinstance(key, slice):
            return Schema(self.fields[key])
        if isinstance(key, (list, set)):
            return self.extract(list(key))
        return super().__getitem__(key)

    # -- comparisons -------------------------------------------------------
    def __eq__(self, other: Any) -> bool:
        return self.is_like(other)

    def __ne__(self, other: Any) -> bool:
        return not self.is_like(other)

    def __hash__(self) -> int:  # needed because __eq__ is overridden
        return hash(str(self))

    def is_like(self, other: Any, equal_groups: Optional[List[List[Callable]]] = None) -> bool:
        """Equality, optionally treating type groups as interchangeable.

        ``equal_groups=[[pa.types.is_integer]]`` treats all integer widths as
        equal — used by the test comparator (reference
        ``fugue/dataframe/utils.py:67``).
        """
        if other is None:
            return False
        if isinstance(other, Schema):
            o = other
        else:
            try:
                o = Schema(other)
            except Exception:
                return False
        if self.names != o.names:
            return False
        for a, b in zip(self.types, o.types):
            if a == b:
                continue
            if equal_groups is not None and any(
                all(chk(t) for t in (a, b)) for grp in equal_groups for chk in [lambda t, g=grp: any(c(t) for c in g)]
            ):
                continue
            return False
        return True

    def __contains__(self, key: Any) -> bool:
        if key is None:
            return False
        if isinstance(key, str):
            if ":" not in key:
                return super().__contains__(key)
            try:
                fields = _parse_fields(key)
            except Exception:
                return False
            return all(self.__contains__(f) for f in fields)
        if isinstance(key, pa.Field):
            return super().__contains__(key.name) and self[key.name].type == key.type
        if isinstance(key, Schema):
            return all(self.__contains__(f) for f in key.fields)
        if isinstance(key, Iterable):
            return all(self.__contains__(k) for k in key)
        return False

    # -- set-like ops ------------------------------------------------------
    def __add__(self, other: Any) -> "Schema":
        return self.copy().append(other)

    def __sub__(self, other: Any) -> "Schema":
        return self.remove(other, ignore_key_mismatch=False)

    def exclude(self, other: Any) -> "Schema":
        """Drop the given names/fields, ignoring ones not present."""
        return self.remove(other, ignore_key_mismatch=True)

    def remove(self, obj: Any, ignore_key_mismatch: bool = False) -> "Schema":
        names: List[str] = []

        def collect(o: Any) -> None:
            if o is None:
                return
            if isinstance(o, str):
                if ":" in o:
                    for f in _parse_fields(o):
                        collect(f)
                else:
                    names.append(o)
            elif isinstance(o, pa.Field):
                if o.name in self and self[o.name].type != o.type:
                    raise SchemaError(f"can't remove {o}: type mismatch")
                names.append(o.name)
            elif isinstance(o, (Schema, pa.Schema)):
                for f in (o.fields if isinstance(o, Schema) else list(o)):
                    collect(f)
            elif isinstance(o, Iterable):
                for x in o:
                    collect(x)
            else:
                raise SchemaError(f"can't remove {o!r} from schema")

        collect(obj)
        missing = [n for n in names if n not in self]
        if len(missing) > 0 and not ignore_key_mismatch:
            raise SchemaError(f"fields {missing} not in schema {self}")
        keep = set(self.names) - set(names)
        return Schema([f for f in self.fields if f.name in keep])

    def extract(
        self,
        obj: Any,
        ignore_key_mismatch: bool = False,
        require_type_match: bool = True,
    ) -> "Schema":
        """Select a sub-schema by names (order follows ``obj``)."""
        names: List[str] = []

        def collect(o: Any) -> None:
            if o is None:
                return
            if isinstance(o, str):
                if ":" in o:
                    for f in _parse_fields(o):
                        collect(f)
                else:
                    names.append(o)
            elif isinstance(o, pa.Field):
                if o.name in self and require_type_match and self[o.name].type != o.type:
                    raise SchemaError(f"can't extract {o}: type mismatch with {self[o.name]}")
                names.append(o.name)
            elif isinstance(o, (Schema, pa.Schema)):
                for f in o if isinstance(o, pa.Schema) else o.fields:
                    collect(f)
            elif isinstance(o, Iterable):
                for x in o:
                    collect(x)
            else:
                raise SchemaError(f"can't extract {o!r}")

        collect(obj)
        fields: List[pa.Field] = []
        for n in names:
            if n in self:
                fields.append(self[n])
            elif not ignore_key_mismatch:
                raise SchemaError(f"field {n!r} not in schema {self}")
        return Schema(fields)

    def intersect(
        self,
        other: Any,
        ignore_type_mismatch: bool = True,
        use_other_order: bool = False,
    ) -> "Schema":
        o = other if isinstance(other, Schema) else Schema(other) if not isinstance(other, (list, set)) or any(":" in str(x) for x in other) else None
        if o is None:  # plain name list
            names = [str(x) for x in other]
            order = names if use_other_order else [n for n in self.names if n in set(names)]
            return Schema([self[n] for n in order if n in self])
        res: List[pa.Field] = []
        mine, theirs = (o, self) if use_other_order else (self, o)
        for f in mine.fields:
            if f.name in theirs:
                if theirs[f.name].type == f.type:
                    res.append(self[f.name])
                elif not ignore_type_mismatch:
                    raise SchemaError(f"type mismatch on {f.name}")
        return Schema(res)

    def union(self, other: Any, require_type_match: bool = False) -> "Schema":
        o = other if isinstance(other, Schema) else Schema(other)
        res = self.copy()
        for f in o.fields:
            if f.name not in res:
                res._append_field(f)
            elif require_type_match and res[f.name].type != f.type:
                raise SchemaError(f"type mismatch on {f.name}: {res[f.name].type} vs {f.type}")
        return res

    def rename(self, columns: Dict[str, str], ignore_missing: bool = False) -> "Schema":
        if not ignore_missing:
            missing = [k for k in columns if k not in self]
            if len(missing) > 0:
                raise SchemaError(f"can't rename: {missing} not in schema")
        new_names = [columns.get(n, n) for n in self.names]
        if len(set(new_names)) != len(new_names):
            raise SchemaError(f"rename causes duplicated names: {new_names}")
        return Schema([pa.field(n, f.type) for n, f in zip(new_names, self.fields)])

    def alter(self, subschema: Any) -> "Schema":
        """Change types of a subset of columns (names must exist)."""
        if subschema is None:
            return self
        sub = subschema if isinstance(subschema, Schema) else Schema(subschema)
        missing = [n for n in sub.names if n not in self]
        if len(missing) > 0:
            raise SchemaError(f"can't alter: {missing} not in schema {self}")
        return Schema(
            [sub[f.name] if f.name in sub else f for f in self.fields]
        )

    def transform(self, *args: Any, **kwargs: Any) -> "Schema":
        """Build a derived schema from expressions.

        Expression pieces (reference behavior:
        ``fugue/extensions/transformer/convert.py:357-363`` +
        triad semantics):

        - ``*`` — all current columns
        - ``name:type`` — add a column
        - ``-a,b`` / ``-a,-b`` — drop columns (error if missing)
        - ``~a,b`` — drop columns (ignore missing)
        - a callable — applied to self, result appended
        - a Schema/pa.Schema/dict — appended
        """
        result = Schema()
        subtract: List[str] = []
        soft_subtract: List[str] = []

        def handle_expr(expr: str) -> None:
            # "-a,b" / "~a,b": after a -/~ prefix, following bare names stay
            # in drop mode until a typed field or "*" resets to add mode
            mode = "add"
            for part in _split_top(expr, ","):
                part = part.strip()
                if part == "":
                    continue
                if part == "*":
                    mode = "add"
                    result.append(self)
                elif part.startswith("-"):
                    mode = "sub"
                    subtract.append(part[1:].strip())
                elif part.startswith("~"):
                    mode = "soft"
                    soft_subtract.append(part[1:].strip())
                elif ":" in part:
                    mode = "add"
                    result.append(part)
                elif mode == "sub":
                    subtract.append(part)
                elif mode == "soft":
                    soft_subtract.append(part)
                else:
                    result.append(part)

        for a in args:
            if a is None:
                continue
            if callable(a) and not isinstance(a, (str, Schema)):
                result.append(a(self))
            elif isinstance(a, str):
                handle_expr(a)
            else:
                result.append(a)
        for k, v in kwargs.items():
            result.append((k, to_pa_datatype(v)))
        res = result
        if len(subtract) > 0:
            res = res.remove(subtract, ignore_key_mismatch=False)
        if len(soft_subtract) > 0:
            res = res.exclude(soft_subtract)
        return res

    # -- misc --------------------------------------------------------------
    def assert_not_empty(self) -> "Schema":
        if len(self) == 0:
            raise SchemaError("schema is empty")
        return self

    def create_empty_arrow_table(self) -> pa.Table:
        return pa.Table.from_arrays(
            [pa.array([], type=f.type) for f in self.fields], schema=self.pa_schema
        )

    def create_empty_pandas_df(self, use_extension_types: bool = True) -> pd.DataFrame:
        return self.create_empty_arrow_table().to_pandas()

    def __repr__(self) -> str:
        return str(self)

    def __str__(self) -> str:
        return ",".join(
            f"{_quote_name(f.name)}:{type_to_expression(f.type)}" for f in self.fields
        )

    def __uuid__(self) -> str:
        from ._utils.hash import to_uuid

        return to_uuid(str(self))


class SchemaError(FugueDataFrameOperationError):
    """Invalid schema expression or operation."""


def _pandas_to_pa_schema(df: pd.DataFrame) -> pa.Schema:
    """Infer an arrow schema from a pandas frame, mapping object→str."""
    schema = pa.Schema.from_pandas(df, preserve_index=False)
    fields = []
    for f in schema:
        if pa.types.is_null(f.type):
            fields.append(pa.field(f.name, pa.string()))
        else:
            fields.append(pa.field(f.name, _normalize_type(f.type)))
    return pa.schema(fields)
