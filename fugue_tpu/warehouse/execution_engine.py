"""Warehouse execution engine: Fugue ops pushed down to an external SQL
database over DB-API — the reference's Ibis role rebuilt in-tree.

Parity target: ``/root/reference/fugue_ibis/execution_engine.py`` —
``IbisSQLEngine`` (select/join/set-ops/take/sample as backend SQL,
``:30-300``), ``IbisMapEngine`` (map roundtrips through a local engine,
``:302-350``), ``IbisExecutionEngine`` (``:352``). Instead of the ibis
expression tree + per-backend compilers, this engine generates standard
SQL directly (the in-tree ``SQLExpressionGenerator`` provides the
column-IR lowering) and speaks plain DB-API, with sqlite3 (stdlib) as the
in-env warehouse. TPU note: this role is the escape hatch for data that
lives in an external system — the device engine ingests from it via
``as_arrow()``; compute-heavy paths belong on the JaxExecutionEngine.
"""

import datetime
import itertools
import logging
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

import pyarrow as pa

from .._utils.assertion import assert_or_throw
from .._utils.io import load_df as _io_load_df
from .._utils.io import save_df as _io_save_df
from ..collections.partition import (
    PartitionCursor,
    PartitionSpec,
    parse_presort_exp,
)
from ..collections.sql import StructuredRawSQL
from ..column import ColumnExpr, SelectColumns
from ..column.sql import SQLExpressionGenerator
from ..dataframe import ArrowDataFrame, DataFrame, DataFrames, LocalDataFrame
from ..dataframe.utils import get_join_schemas
from ..exceptions import FugueInvalidOperation
from ..execution.execution_engine import ExecutionEngine, MapEngine, SQLEngine
from ..execution.native_execution_engine import NativeExecutionEngine
from ..schema import Schema
from .dataframe import WarehouseDataFrame

_TEMP_TABLE_NAMES = (f"_fugue_temp_table_{i:d}" for i in itertools.count())
_ROWNUM_COL = "__fugue_wh_rn__"

from .profile import _SCHEMA_META_TABLE  # single source of truth


class _StorageCastGenerator(SQLExpressionGenerator):
    """Column-IR → SQL with casts lowered to the warehouse's STORAGE
    classes (sqlite cast targets are TEXT/INTEGER/REAL/BLOB, not logical
    type names) — the declared arrow type still rides the recorded frame
    schema, so fetch reconstructs the exact logical type."""

    def __init__(self, profile: Any = None) -> None:
        super().__init__(enable_cast=True)
        from .profile import get_profile

        self._profile = get_profile(profile)

    def type_to_sql_type(self, tp: pa.DataType) -> str:
        return self._profile.storage_type(tp)


class WarehouseSQLEngine(SQLEngine):
    """SQL facet: raw SELECT statements run in the warehouse (reference
    ``IbisSQLEngine.select``, ``fugue_ibis/execution_engine.py:41-58``).

    Also usable as a secondary SQL engine on a NON-warehouse execution
    engine (FugueSQL ``CONNECT sqlite``): frames then move into a private
    sqlite session for the statement, mirroring how the reference's
    DuckDB SQL engine serves other engines."""

    def __init__(self, execution_engine: ExecutionEngine):
        super().__init__(execution_engine)
        self._wh: "WarehouseExecutionEngine" = (
            execution_engine
            if isinstance(execution_engine, WarehouseExecutionEngine)
            else SQLiteExecutionEngine(execution_engine.conf)
        )

    @property
    def is_distributed(self) -> bool:
        return False

    @property
    def dialect(self) -> Optional[str]:
        # raw SELECT text (usually FugueSQL's spark-flavored dialect)
        # transpiles to the warehouse driver's dialect before execution
        return self._wh._profile.name

    def encode_name(self, name: str) -> str:
        return self._wh.encode_name(name)

    def select(self, dfs: DataFrames, statement: StructuredRawSQL) -> DataFrame:
        eng = self._wh
        name_map: Dict[str, str] = {}
        for k, v in dfs.items():
            wdf = eng.to_df(v)
            # temp table names are identifier-safe by construction; they
            # pass through the dialect transpile as bare identifiers
            name_map[k] = wdf.table
        sql = statement.construct(
            name_map=name_map, dialect=self.dialect, log=self.log
        )
        tbl = eng.materialize(sql)
        schema: Optional[Schema] = None
        probe = eng.connection.execute(
            f"SELECT 1 FROM {eng.encode_name(tbl)} LIMIT 1"
        ).fetchone()
        if probe is None:
            # EMPTY result: nothing to sample, so decltype-less computed
            # columns would degrade to string — infer the schema statically
            # from the projected expression IR over the input schemas
            # instead (parsed in the statement's own dialect text)
            from ..sql.infer import infer_output_schema

            pre = statement.construct(log=None)
            inferred = infer_output_schema(
                pre, {k: v.schema for k, v in dfs.items()}
            )
            if inferred is not None:
                actual_cols = [
                    n for n, _ in eng._profile.table_info(eng.connection, tbl)
                ]
                if list(inferred.names) == actual_cols:
                    schema = inferred
                    eng.record_schema(tbl, schema)
        if schema is None:
            schema = eng.infer_table_schema(tbl)
        return eng.track_temp_table(WarehouseDataFrame(eng, tbl, schema))

    def table_exists(self, table: str) -> bool:
        eng = self._wh
        cur = eng.connection.execute(
            eng._profile.table_exists_sql(views=True), (table,)
        )
        return cur.fetchone() is not None

    def save_table(
        self,
        df: DataFrame,
        table: str,
        mode: str = "overwrite",
        partition_spec: Optional[PartitionSpec] = None,
        **kwargs: Any,
    ) -> None:
        eng = self._wh
        if self.table_exists(table):
            assert_or_throw(
                mode == "overwrite",
                FugueInvalidOperation(f"table {table} exists, mode must be overwrite"),
            )
            eng.connection.execute(f"DROP TABLE {eng.encode_name(table)}")
        wdf = eng.to_df(df)
        eng.connection.execute(
            f"CREATE TABLE {eng.encode_name(table)} AS "
            f"SELECT * FROM {eng.encode_name(wdf.table)}"
        )
        eng.record_schema(table, wdf.schema, persistent=True)
        eng.connection.commit()

    def load_table(self, table: str, **kwargs: Any) -> DataFrame:
        eng = self._wh
        assert_or_throw(
            self.table_exists(table),
            FugueInvalidOperation(f"table {table} doesn't exist"),
        )
        return WarehouseDataFrame(
            eng, table, eng.infer_table_schema(table), snapshot=False
        )


class WarehouseMapEngine(MapEngine):
    """Map facet: per-partition UDFs roundtrip through the local engine
    (reference ``IbisMapEngine.map_dataframe``,
    ``fugue_ibis/execution_engine.py:330-350``)."""

    @property
    def is_distributed(self) -> bool:
        return False

    def map_dataframe(
        self,
        df: DataFrame,
        map_func: Callable[[PartitionCursor, LocalDataFrame], LocalDataFrame],
        output_schema: Any,
        partition_spec: PartitionSpec,
        on_init: Optional[Callable[[int, DataFrame], Any]] = None,
        map_func_format_hint: Optional[str] = None,
    ) -> DataFrame:
        eng: "WarehouseExecutionEngine" = self.execution_engine  # type: ignore
        local = eng.to_df(df).as_local_bounded()
        res = eng.local_engine.map_engine.map_dataframe(
            local,
            map_func=map_func,
            output_schema=output_schema,
            partition_spec=partition_spec,
            on_init=on_init,
            map_func_format_hint=map_func_format_hint,
        )
        return eng.ingest(res.as_local_bounded())


class WarehouseExecutionEngine(ExecutionEngine):
    """Engine verbs lowered to warehouse SQL (reference
    ``IbisExecutionEngine``, ``fugue_ibis/execution_engine.py:352``).

    ``connection`` is a DB-API connection; sqlite3 is the stdlib-provided
    warehouse this repo ships with (:class:`SQLiteExecutionEngine`).
    Frames are temp tables in that connection; every relational verb is a
    single SQL statement over them, so the data never leaves the
    warehouse except for ``map_dataframe`` (local roundtrip) and
    ``as_*`` fetches.
    """

    def __init__(
        self,
        conf: Any = None,
        connection: Any = None,
        path: str = ":memory:",
        profile: Any = None,
    ):
        super().__init__(conf)
        import sqlite3

        from .profile import get_profile

        self._profile = get_profile(profile)
        self._own_connection = connection is None
        self._connection = (
            connection
            if connection is not None
            else sqlite3.connect(path, check_same_thread=False)
        )
        if self._own_connection:
            # engines created as private sessions (e.g. CONNECT sqlite's
            # WarehouseSQLEngine) have no stop() caller — close the owned
            # connection when the engine is released. Frames keep the
            # engine alive, so a finalized engine has no live frames.
            import weakref

            weakref.finalize(self, _close_quietly, self._connection)
        self._schemas: Dict[str, Schema] = {}
        self._local_engine = NativeExecutionEngine(conf)
        # delegated map/fallback work reports recovery counters on THIS
        # engine (see fugue_tpu/resilience/counters.py)
        self._local_engine._resilience_stats = self.resilience_stats
        self._log = logging.getLogger("fugue_tpu.warehouse")
        self._gen = _StorageCastGenerator(self._profile)

    # ---- base wiring ------------------------------------------------------
    @property
    def log(self) -> logging.Logger:
        return self._log

    @property
    def is_distributed(self) -> bool:
        return False

    @property
    def connection(self) -> Any:
        return self._connection

    @property
    def local_engine(self) -> ExecutionEngine:
        """The non-warehouse engine handling ops beyond SQL (reference
        ``non_ibis_engine``, ``fugue_ibis/execution_engine.py:372``)."""
        return self._local_engine

    def create_default_map_engine(self) -> MapEngine:
        return WarehouseMapEngine(self)

    def create_default_sql_engine(self) -> SQLEngine:
        return WarehouseSQLEngine(self)

    def get_current_parallelism(self) -> int:
        return 1

    def stop_engine(self) -> None:
        if self._own_connection:
            self._connection.close()

    def encode_name(self, name: str) -> str:
        return self._profile.quote(name)

    def convert_yield_dataframe(self, df: DataFrame, as_local: bool) -> DataFrame:
        # warehouse frames die with the connection (reference DuckDB does
        # the same for owned connections, fugue_duckdb/execution_engine.py:505):
        # results yielded past the engine's lifetime must be local copies.
        # ctx_count <= 1 = the top-level (per-run) context — the engine
        # stops when it exits, so the yield must not reference it
        if as_local or (self._own_connection and self._ctx_count <= 1):
            return df.as_local() if isinstance(df, WarehouseDataFrame) else df
        return df

    # ---- data movement ----------------------------------------------------
    def to_df(self, df: Any, schema: Any = None) -> WarehouseDataFrame:
        if isinstance(df, WarehouseDataFrame):
            assert_or_throw(
                schema is None or Schema(schema) == df.schema,
                FugueInvalidOperation("schema must match the warehouse frame"),
            )
            return df
        local = self._local_engine.to_df(df, schema)
        return self.ingest(local)

    def temp_frame(self, tbl: str, schema: Schema) -> WarehouseDataFrame:
        """Wrap a materialized temp table, recording its schema and its
        drop-on-release lifecycle."""
        self.record_schema(tbl, schema)
        return self.track_temp_table(WarehouseDataFrame(self, tbl, schema))

    def track_temp_table(self, frame: WarehouseDataFrame) -> WarehouseDataFrame:
        """Register ``frame``'s temp table for DROP when the frame is
        garbage-collected — chained pipelines would otherwise hold a full
        copy of every intermediate result for the connection's lifetime."""
        import weakref

        weakref.finalize(frame, _drop_table_quietly, self._connection, frame.table)
        return frame

    def ingest(self, df: DataFrame) -> WarehouseDataFrame:
        """Write a local frame into a warehouse temp table."""
        tbl = next(_TEMP_TABLE_NAMES)
        schema = df.schema
        self._connection.execute(
            self._profile.create_temp_table_sql(tbl, schema)
        )
        arrow = df.as_arrow() if not isinstance(df, ArrowDataFrame) else df.native
        rows = _arrow_to_storage_rows(arrow, schema)
        self._connection.executemany(
            self._profile.insert_sql(tbl, len(schema.fields)), rows
        )
        self.record_schema(tbl, schema)
        return self.track_temp_table(WarehouseDataFrame(self, tbl, schema))

    def materialize(self, sql: str) -> str:
        """Run ``sql`` into a fresh temp table; return the table name."""
        tbl = next(_TEMP_TABLE_NAMES)
        self._connection.execute(
            self._profile.create_temp_table_as_sql(tbl, sql)
        )
        return tbl

    def record_schema(
        self, table: str, schema: Schema, persistent: bool = False
    ) -> None:
        self._schemas[table] = schema
        if persistent:
            # schema fidelity across engine instances over the same DB file:
            # sqlite's storage classes can't round-trip bool/datetime/int
            # widths, so the exact Fugue schema rides in a meta table
            self._connection.execute(self._profile.meta_create_sql())
            self._connection.execute(
                self._profile.meta_upsert_sql(), (table, str(schema))
            )

    def infer_table_schema(self, table: str) -> Schema:
        """Schema of a warehouse table: recorded if known, else inferred
        from sqlite column decltypes + value sampling (the price of a
        dynamically-typed warehouse; recorded schemas are authoritative).

        Known degradation: a raw-SQL SELECT whose computed columns carry
        no decltype AND whose result set is empty has nothing to sample,
        so those columns fall back to string (the reference avoids this
        by compiling ibis expressions, which carry types end-to-end —
        `/root/reference/fugue_ibis/execution_engine.py:41-58`; a plain
        DB-API cursor has no equivalent). Recorded schemas — every table
        produced by ingest/temp_frame/save_table — never hit this path.
        """
        if table in self._schemas:
            return self._schemas[table]
        cur = self._connection.execute(
            self._profile.meta_select_sql(), (table,)
        ) if self._meta_exists() else None
        row = cur.fetchone() if cur is not None else None
        if row is not None:
            schema = Schema(row[1])
            self._schemas[table] = schema
            return schema
        fields: List[pa.Field] = []
        for name, decltype in self._profile.table_info(self._connection, table):
            tp = self._profile.decl_to_arrow(decltype)
            if tp is None:
                tp = self._sample_type(table, name)
            fields.append(pa.field(name, tp))
        schema = Schema(fields)
        self._schemas[table] = schema
        return schema

    def _meta_exists(self) -> bool:
        cur = self._connection.execute(
            self._profile.table_exists_sql(views=False), (_SCHEMA_META_TABLE,)
        )
        return cur.fetchone() is not None

    def _sample_type(self, table: str, col: str) -> pa.DataType:
        cur = self._connection.execute(
            f"SELECT typeof({self.encode_name(col)}) FROM "
            f"{self.encode_name(table)} WHERE {self.encode_name(col)} "
            "IS NOT NULL LIMIT 1"
        )
        row = cur.fetchone()
        kind = row[0] if row is not None else None
        return {
            "integer": pa.int64(),
            "real": pa.float64(),
            "text": pa.string(),
            "blob": pa.binary(),
        }.get(kind, pa.string())

    def fetch_arrow(self, table: str, schema: Schema) -> pa.Table:
        return self.fetch_arrow_query(
            "SELECT "
            + ", ".join(self.encode_name(n) for n in schema.names)
            + f" FROM {self.encode_name(table)}",
            schema,
        )

    def fetch_arrow_query(self, sql: str, schema: Schema) -> pa.Table:
        cur = self._connection.execute(sql)
        rows = cur.fetchall()
        cols = list(zip(*rows)) if len(rows) > 0 else [[] for _ in schema.fields]
        arrays = [
            _storage_to_arrow(list(vals), f.type)
            for vals, f in zip(cols, schema.fields)
        ]
        return pa.Table.from_arrays(arrays, schema=schema.pa_schema)

    # ---- literals for generated SQL ---------------------------------------
    def lit_sql(self, value: Any) -> str:
        if value is None:
            return "NULL"
        if isinstance(value, bool):
            return "1" if value else "0"
        if isinstance(value, float):
            import math

            if math.isnan(value):
                return "NULL"  # SQL has no NaN literal; NULL is its storage
            if math.isinf(value):
                # sqlite parses out-of-range literals to ±Infinity
                return "9e999" if value > 0 else "-9e999"
            return repr(value)
        if isinstance(value, int):
            return repr(value)
        if isinstance(value, bytes):
            return "X'" + value.hex() + "'"
        if isinstance(value, datetime.datetime):
            return "'" + value.isoformat(sep=" ") + "'"
        if isinstance(value, datetime.date):
            return "'" + value.isoformat() + "'"
        return "'" + str(value).replace("'", "''") + "'"

    # ---- distribution primitives (single warehouse: metadata no-ops) ------
    def repartition(self, df: DataFrame, partition_spec: PartitionSpec) -> DataFrame:
        self.log.warning("%s doesn't respect repartition", self)
        return df

    def broadcast(self, df: DataFrame) -> DataFrame:
        return df

    def persist(self, df: DataFrame, lazy: bool = False, **kwargs: Any) -> DataFrame:
        return self.to_df(df)  # frames are materialized tables already

    # ---- relational verbs as warehouse SQL --------------------------------
    def join(
        self,
        df1: DataFrame,
        df2: DataFrame,
        how: str,
        on: Optional[List[str]] = None,
    ) -> DataFrame:
        d1, d2 = self.to_df(df1), self.to_df(df2)
        key_schema, end_schema = get_join_schemas(d1, d2, how=how, on=on)
        keys = key_schema.names
        a, b = self.encode_name(d1.table), self.encode_name(d2.table)
        how_l = how.lower().replace("_", "").replace(" ", "")
        # plain = (not null-safe IS): NULL join keys never match, matching
        # the suites' join semantics on every engine
        on_clause = " AND ".join(
            f"a.{self.encode_name(k)} = b.{self.encode_name(k)}" for k in keys
        )

        def _sel(key_side: str, coalesce_keys: bool = False) -> str:
            """Projection in end-schema order: key columns read from
            ``key_side`` (COALESCEd across sides for full outer), non-key
            columns from the side that owns them."""
            cols = []
            for n in end_schema.names:
                en = self.encode_name(n)
                if n in keys:
                    other = "b" if key_side == "a" else "a"
                    cols.append(
                        f"COALESCE({key_side}.{en}, {other}.{en}) AS {en}"
                        if coalesce_keys
                        else f"{key_side}.{en} AS {en}"
                    )
                else:
                    side = "a" if n in d1.schema else "b"
                    cols.append(f"{side}.{en} AS {en}")
            return ", ".join(cols)

        if how_l == "cross":
            sql = f"SELECT {_sel('a')} FROM {a} AS a CROSS JOIN {b} AS b"
        elif how_l == "inner":
            sql = f"SELECT {_sel('a')} FROM {a} AS a JOIN {b} AS b ON {on_clause}"
        elif how_l == "leftouter":
            sql = f"SELECT {_sel('a')} FROM {a} AS a LEFT JOIN {b} AS b ON {on_clause}"
        elif how_l == "rightouter":
            # mirrored left join; the right side owns the key values
            sql = (
                f"SELECT {_sel('b')} FROM {b} AS b "
                f"LEFT JOIN {a} AS a ON {on_clause}"
            )
        elif how_l == "fullouter":
            if self._profile.supports_full_outer_join:
                sql = (
                    f"SELECT {_sel('a', coalesce_keys=True)} FROM {a} AS a "
                    f"FULL OUTER JOIN {b} AS b ON {on_clause}"
                )
            else:
                # emulation for drivers without FULL OUTER JOIN (sqlite
                # < 3.39): left join ∪ right rows with NO left match.
                # ``a.rowid IS NULL`` (not a payload column) detects the
                # no-match case even when every a-column is legitimately
                # NULL; NULL-keyed b rows never match so they land in the
                # anti part with their own key values
                sql = (
                    f"SELECT {_sel('a', coalesce_keys=True)} FROM {a} AS a "
                    f"LEFT JOIN {b} AS b ON {on_clause} "
                    f"UNION ALL "
                    f"SELECT {_sel('b')} FROM {b} AS b "
                    f"LEFT JOIN {a} AS a ON {on_clause} WHERE a.rowid IS NULL"
                )
        elif how_l in ("semi", "leftsemi"):
            cond = " AND ".join(
                f"b.{self.encode_name(k)} = a.{self.encode_name(k)}" for k in keys
            )
            sql = (
                f"SELECT * FROM {a} AS a WHERE EXISTS "
                f"(SELECT 1 FROM {b} AS b WHERE {cond})"
            )
        elif how_l in ("anti", "leftanti"):
            cond = " AND ".join(
                f"b.{self.encode_name(k)} = a.{self.encode_name(k)}" for k in keys
            )
            sql = (
                f"SELECT * FROM {a} AS a WHERE NOT EXISTS "
                f"(SELECT 1 FROM {b} AS b WHERE {cond})"
            )
        else:
            raise FugueInvalidOperation(f"{how} is not a valid join type")
        return self.temp_frame(self.materialize(sql), end_schema)

    def union(self, df1: DataFrame, df2: DataFrame, distinct: bool = True) -> DataFrame:
        return self._set_op("UNION" if distinct else "UNION ALL", df1, df2)

    def subtract(
        self, df1: DataFrame, df2: DataFrame, distinct: bool = True
    ) -> DataFrame:
        if distinct:
            return self._set_op("EXCEPT", df1, df2)
        return self._bag_set_op("EXCEPT", df1, df2)

    def intersect(
        self, df1: DataFrame, df2: DataFrame, distinct: bool = True
    ) -> DataFrame:
        if distinct:
            return self._set_op("INTERSECT", df1, df2)
        return self._bag_set_op("INTERSECT", df1, df2)

    def _set_op(self, op: str, df1: DataFrame, df2: DataFrame) -> DataFrame:
        d1, d2 = self.to_df(df1), self.to_df(df2)
        assert_or_throw(
            d1.schema == d2.schema,
            FugueInvalidOperation(f"schema mismatch {d1.schema} vs {d2.schema}"),
        )
        cols = ", ".join(self.encode_name(n) for n in d1.schema.names)
        sql = (
            f"SELECT {cols} FROM {self.encode_name(d1.table)} {op} "
            f"SELECT {cols} FROM {self.encode_name(d2.table)}"
        )
        return self.temp_frame(self.materialize(sql), d1.schema)

    def _bag_set_op(self, op: str, df1: DataFrame, df2: DataFrame) -> DataFrame:
        """Bag (``ALL``) semantics for EXCEPT/INTERSECT, which sqlite only
        offers as distinct: number duplicate rows on both sides, apply the
        distinct op over (row, duplicate-index), then drop the index."""
        d1, d2 = self.to_df(df1), self.to_df(df2)
        assert_or_throw(
            d1.schema == d2.schema,
            FugueInvalidOperation(f"schema mismatch {d1.schema} vs {d2.schema}"),
        )
        names = d1.schema.names
        cols = ", ".join(self.encode_name(n) for n in names)
        part = ", ".join(self.encode_name(n) for n in names)
        rn = self.encode_name(_ROWNUM_COL)

        def _numbered(tbl: str) -> str:
            return (
                f"SELECT {cols}, ROW_NUMBER() OVER (PARTITION BY {part}) AS {rn} "
                f"FROM {self.encode_name(tbl)}"
            )

        sql = (
            f"SELECT {cols} FROM ({_numbered(d1.table)} {op} "
            f"{_numbered(d2.table)})"
        )
        return self.temp_frame(self.materialize(sql), d1.schema)

    def distinct(self, df: DataFrame) -> DataFrame:
        d = self.to_df(df)
        cols = ", ".join(self.encode_name(n) for n in d.schema.names)
        return self.temp_frame(
            self.materialize(
                f"SELECT DISTINCT {cols} FROM {self.encode_name(d.table)}"
            ),
            d.schema,
        )

    def dropna(
        self,
        df: DataFrame,
        how: str = "any",
        thresh: Optional[int] = None,
        subset: Optional[List[str]] = None,
    ) -> DataFrame:
        d = self.to_df(df)
        names = subset if subset is not None else d.schema.names
        assert_or_throw(
            all(n in d.schema for n in names),
            FugueInvalidOperation(f"{names} not a subset of {d.schema}"),
        )
        assert_or_throw(
            how in ("any", "all"), ValueError(f"how must be 'any' or 'all', got {how!r}")
        )
        nn = [f"({self.encode_name(n)} IS NOT NULL)" for n in names]
        if thresh is not None:
            assert_or_throw(
                how == "any", ValueError("when thresh is set, how must be 'any'")
            )
            cond = " + ".join(nn) + f" >= {int(thresh)}"
        elif how == "any":
            cond = " AND ".join(nn)
        else:  # "all": keep rows with at least one non-null
            cond = " OR ".join(nn)
        return self.temp_frame(
            self.materialize(
                f"SELECT * FROM {self.encode_name(d.table)} WHERE {cond}"
            ),
            d.schema,
        )

    def fillna(
        self, df: DataFrame, value: Any, subset: Optional[List[str]] = None
    ) -> DataFrame:
        d = self.to_df(df)
        if isinstance(value, dict):
            assert_or_throw(
                all(v is not None for v in value.values()),
                ValueError("fillna value can not be None or contain None"),
            )
            vd = value
        else:
            assert_or_throw(value is not None, ValueError("fillna value can not be None"))
            names = subset if subset is not None else d.schema.names
            vd = {n: value for n in names}
        cols = []
        for n in d.schema.names:
            if n in vd:
                cols.append(
                    f"COALESCE({self.encode_name(n)}, {self.lit_sql(vd[n])}) "
                    f"AS {self.encode_name(n)}"
                )
            else:
                cols.append(self.encode_name(n))
        return self.temp_frame(
            self.materialize(
                f"SELECT {', '.join(cols)} FROM {self.encode_name(d.table)}"
            ),
            d.schema,
        )

    def sample(
        self,
        df: DataFrame,
        n: Optional[int] = None,
        frac: Optional[float] = None,
        replace: bool = False,
        seed: Optional[int] = None,
    ) -> DataFrame:
        assert_or_throw(
            (n is None) != (frac is None),
            ValueError("one and only one of n and frac should be non-negative"),
        )
        assert_or_throw(
            not replace,
            NotImplementedError("warehouse sample doesn't support replacement"),
        )
        d = self.to_df(df)
        cols = ", ".join(self.encode_name(c) for c in d.schema.names)
        if seed is not None:
            # deterministic seeded sample: a golden-ratio multiplicative
            # hash of a generated row number mixed with the seed stands in
            # for random() — same seed + same table contents = same
            # sample, matching the other engines' reproducibility contract
            # (consecutive row numbers step by ~0.618 * 2^32 mod 2^32, the
            # Weyl equidistribution). ROW_NUMBER() rather than rowid: a
            # user column named "rowid" shadows sqlite's, and views have
            # none. The pre-multiply % 2^31 keeps the product inside
            # sqlite's signed 64-bit INTEGER (2^31 * 2654435761 < 2^63)
            # even for billion-row tables / huge seeds; the hash pattern
            # repeats past 2^31 rows, which sampling tolerates.
            rn = "__ft_rn"
            while rn in d.schema.names:
                rn = "_" + rn
            h = (
                f"((({rn} + {int(seed) & 0x7FFFFFFF}) % 2147483648) "
                "* 2654435761 % 4294967296)"
            )
            src = (
                f"(SELECT {cols}, ROW_NUMBER() OVER () AS {rn} "
                f"FROM {self.encode_name(d.table)})"
            )
            if frac is not None:
                sql = (
                    f"SELECT {cols} FROM {src} "
                    f"WHERE ({h} / 4294967296.0) < {float(frac)}"
                )
            else:
                sql = f"SELECT {cols} FROM {src} ORDER BY {h} LIMIT {int(n)}"
        elif frac is not None:
            # random() is a signed 64-bit int; map onto [0, 1)
            sql = (
                f"SELECT {cols} FROM {self.encode_name(d.table)} "
                f"WHERE (random() / 18446744073709551616.0 + 0.5) < {float(frac)}"
            )
        else:
            sql = (
                f"SELECT {cols} FROM {self.encode_name(d.table)} "
                f"ORDER BY random() LIMIT {int(n)}"
            )
        return self.temp_frame(self.materialize(sql), d.schema)

    def take(
        self,
        df: DataFrame,
        n: int,
        presort: str,
        na_position: str = "last",
        partition_spec: Optional[PartitionSpec] = None,
    ) -> DataFrame:
        assert_or_throw(isinstance(n, int), ValueError("n needs to be an integer"))
        partition_spec = partition_spec or PartitionSpec()
        d = self.to_df(df)
        _presort = (
            parse_presort_exp(presort)
            if presort is not None and presort != ""
            else partition_spec.presort
        )
        sorts: List[str] = []
        for k, asc in _presort.items():
            s = self.encode_name(k) + (" ASC" if asc else " DESC")
            s += " NULLS FIRST" if na_position == "first" else " NULLS LAST"
            sorts.append(s)
        order_by = ("ORDER BY " + ", ".join(sorts)) if len(sorts) > 0 else ""
        cols = ", ".join(self.encode_name(c) for c in d.schema.names)
        if len(partition_spec.partition_by) == 0:
            sql = f"SELECT * FROM {self.encode_name(d.table)} {order_by} LIMIT {n}"
        else:
            pcols = ", ".join(
                self.encode_name(c) for c in partition_spec.partition_by
            )
            rn = self.encode_name(_ROWNUM_COL)
            sql = (
                f"SELECT {cols} FROM ("
                f"SELECT {cols}, ROW_NUMBER() OVER (PARTITION BY {pcols} "
                f"{order_by}) AS {rn} FROM {self.encode_name(d.table)}"
                f") WHERE {rn} <= {n}"
            )
        return self.temp_frame(self.materialize(sql), d.schema)

    # ---- column-IR pushdown ------------------------------------------------
    def select(
        self,
        df: DataFrame,
        cols: SelectColumns,
        where: Optional[ColumnExpr] = None,
        having: Optional[ColumnExpr] = None,
    ) -> DataFrame:
        """Column-IR SELECT generated as SQL and run in the warehouse —
        the pushdown the reference gets from ibis expression compilation
        (``IbisSQLEngine.select``); the base class would materialize to
        pandas instead."""
        d = self.to_df(df)
        schema = cols.replace_wildcard(d.schema).infer_schema(d.schema)
        if schema is None:
            # some expression type can't be statically inferred — fall back
            # to the base (host-side) evaluation for exactness
            return super().select(df, cols, where=where, having=having)
        sql = self._gen.select(
            cols, self.encode_name(d.table), where=where, having=having
        )
        return self.temp_frame(self.materialize(sql), schema)

    # ---- IO ----------------------------------------------------------------
    def load_df(
        self,
        path: Any,
        format_hint: Any = None,
        columns: Any = None,
        **kwargs: Any,
    ) -> DataFrame:
        tbl, _ = _io_load_df(path, format_hint=format_hint, columns=columns, **kwargs)
        return self.ingest(ArrowDataFrame(tbl))

    def save_df(
        self,
        df: DataFrame,
        path: str,
        format_hint: Any = None,
        mode: str = "overwrite",
        partition_spec: Optional[PartitionSpec] = None,
        force_single: bool = False,
        **kwargs: Any,
    ) -> None:
        partition_cols = (
            list(partition_spec.partition_by)
            if partition_spec is not None and len(partition_spec.partition_by) > 0
            else None
        )
        _io_save_df(
            self.to_df(df).as_arrow(),
            path,
            format_hint=format_hint,
            mode=mode,
            partition_cols=partition_cols,
            **kwargs,
        )


class SQLiteExecutionEngine(WarehouseExecutionEngine):
    """The stdlib-backed concrete warehouse (sqlite3) — registered as
    engine name ``"sqlite"``. ``conf["fugue.sqlite.path"]`` selects a DB
    file; default is in-memory."""

    def __init__(self, conf: Any = None, connection: Any = None, **kwargs: Any):
        from .._utils.params import ParamDict

        # a malformed path must fail loudly — silently opening :memory:
        # would let save_table writes vanish with the process
        path = ParamDict(conf).get_or_none("fugue.sqlite.path", str) or ":memory:"
        super().__init__(conf, connection=connection, path=path)


# ---- storage conversion helpers ------------------------------------------


def _arrow_to_storage_rows(tbl: pa.Table, schema: Schema) -> List[Tuple]:
    """Arrow table → python rows in sqlite storage form (bool→int,
    datetime→ISO text); exact for int64 (python ints are unbounded)."""
    converters: List[Optional[Callable[[Any], Any]]] = []
    for f in schema.fields:
        if pa.types.is_boolean(f.type):
            converters.append(lambda v: None if v is None else int(v))
        elif pa.types.is_timestamp(f.type):
            converters.append(
                lambda v: None if v is None else v.isoformat(sep=" ")
            )
        elif pa.types.is_date(f.type):
            converters.append(lambda v: None if v is None else v.isoformat())
        else:
            converters.append(None)
    cols = [tbl.column(f.name).to_pylist() for f in schema.fields]
    out: List[Tuple] = []
    for row in zip(*cols) if len(cols) > 0 else []:
        out.append(
            tuple(
                v if c is None else c(v) for v, c in zip(row, converters)
            )
        )
    return out


def _storage_to_arrow(values: List[Any], tp: pa.DataType) -> pa.Array:
    """Sqlite storage values → arrow array of the declared type."""
    if pa.types.is_boolean(tp):
        values = [None if v is None else bool(v) for v in values]
        return pa.array(values, type=tp)
    if pa.types.is_timestamp(tp):
        values = [
            None if v is None else datetime.datetime.fromisoformat(str(v))
            for v in values
        ]
        return pa.array(values, type=tp)
    if pa.types.is_date(tp):
        values = [
            None if v is None else datetime.date.fromisoformat(str(v))
            for v in values
        ]
        return pa.array(values, type=tp)
    if pa.types.is_floating(tp):
        # sqlite may hand back ints for REAL columns holding whole numbers
        values = [None if v is None else float(v) for v in values]
        return pa.array(values, type=tp)
    return pa.array(values, type=tp)


def _close_quietly(connection: Any) -> None:
    """weakref-finalizer body: best-effort close of an owned connection."""
    try:
        connection.close()
    except Exception:
        pass


def _drop_table_quietly(connection: Any, table: str) -> None:
    """weakref-finalizer body: best-effort DROP of a released temp table
    (the connection may already be closed at interpreter shutdown)."""
    try:
        connection.execute('DROP TABLE IF EXISTS "' + table.replace('"', '""') + '"')
    except Exception:
        pass
