"""Warehouse driver profiles — the per-database half of the DB-API layer.

The reference's Ibis engine is the base class for BigQuery/Trino/Postgres
backends (`/root/reference/fugue_ibis/execution_engine.py:30,352`): one
engine, many drivers. This module plays that role for the in-tree
warehouse engine: everything driver-specific — identifier quoting, storage
type names, DDL shapes, introspection queries, bind-parameter style, the
upsert spelling for the schema meta table — lives in a
:class:`WarehouseProfile`; `WarehouseExecutionEngine` is written purely
against this interface plus portable SQL (transpiled to the profile's
dialect by ``fugue_tpu.sql.dialect``).

Two profiles ship: :class:`SQLiteProfile` (live — sqlite3 is in the
stdlib) and :class:`PostgresProfile` (emission-verified by golden tests;
this environment has no server, but every SQL string the engine would send
is asserted against known-good postgres syntax).
"""

from typing import Any, List, Optional, Tuple

import pyarrow as pa

from ..exceptions import FugueInvalidOperation
from ..schema import Schema

_SCHEMA_META_TABLE = "__fugue_schemas__"


class WarehouseProfile:
    """Driver-specific SQL construction + introspection for one database."""

    #: profile name AND the transpile-target dialect (fugue_tpu.sql.dialect)
    name: str = ""
    #: DB-API paramstyle: "qmark" (?) or "format" (%s)
    paramstyle: str = "qmark"

    @property
    def supports_full_outer_join(self) -> bool:
        """Whether the driver executes FULL OUTER JOIN natively; when
        False the engine emulates it (left join ∪ right-anti rows)."""
        return True

    # -- identifiers / parameters ------------------------------------------
    def quote(self, name: str) -> str:
        return '"' + name.replace('"', '""') + '"'

    def placeholder(self, index: int) -> str:
        return "?" if self.paramstyle == "qmark" else "%s"

    def placeholders(self, n: int) -> str:
        return ", ".join(self.placeholder(i) for i in range(n))

    # -- types --------------------------------------------------------------
    def storage_type(self, tp: pa.DataType) -> str:
        """Column type name for CREATE TABLE; raise for unstorable types."""
        raise NotImplementedError

    # -- DDL / DML ----------------------------------------------------------
    def create_temp_table_sql(self, table: str, schema: Schema) -> str:
        cols = ", ".join(
            f"{self.quote(f.name)} {self.storage_type(f.type)}"
            for f in schema.fields
        )
        return f"CREATE TEMP TABLE {self.quote(table)} ({cols})"

    def insert_sql(self, table: str, n_cols: int) -> str:
        return (
            f"INSERT INTO {self.quote(table)} "
            f"VALUES ({self.placeholders(n_cols)})"
        )

    def create_temp_table_as_sql(self, table: str, select_sql: str) -> str:
        return f"CREATE TEMP TABLE {self.quote(table)} AS {select_sql}"

    def drop_table_sql(self, table: str) -> str:
        return f"DROP TABLE IF EXISTS {self.quote(table)}"

    # -- schema meta table (exact fugue schemas across engine instances) ----
    def meta_create_sql(self) -> str:
        return (
            f"CREATE TABLE IF NOT EXISTS {_SCHEMA_META_TABLE} "
            "(tbl TEXT PRIMARY KEY, schema TEXT)"
        )

    def meta_upsert_sql(self) -> str:
        raise NotImplementedError

    def meta_select_sql(self) -> str:
        return (
            f"SELECT tbl, schema FROM {_SCHEMA_META_TABLE} "
            f"WHERE tbl = {self.placeholder(0)}"
        )

    # -- introspection -------------------------------------------------------
    def table_exists_sql(self, views: bool = True) -> str:
        """One bind param: the table name. Returns ≥1 row iff it exists."""
        raise NotImplementedError

    def table_info(self, connection: Any, table: str) -> List[Tuple[str, str]]:
        """[(column_name, declared_type)] for an existing table."""
        raise NotImplementedError

    def decl_to_arrow(self, decl: str) -> Optional[pa.DataType]:
        """Declared column type → arrow type; None = needs value sampling."""
        raise NotImplementedError


class SQLiteProfile(WarehouseProfile):
    name = "sqlite"
    paramstyle = "qmark"

    @property
    def supports_full_outer_join(self) -> bool:
        # FULL/RIGHT OUTER JOIN arrived in sqlite 3.39 (2022-06); older
        # baked-in libs (e.g. 3.34) need the emulated form
        import sqlite3

        ver = tuple(int(x) for x in sqlite3.sqlite_version.split(".")[:2])
        return ver >= (3, 39)

    _STORAGE: List[Tuple[Any, str]] = [
        (pa.types.is_boolean, "INTEGER"),
        (pa.types.is_integer, "INTEGER"),
        (pa.types.is_floating, "REAL"),
        (pa.types.is_string, "TEXT"),
        (pa.types.is_large_string, "TEXT"),
        (pa.types.is_binary, "BLOB"),
        (pa.types.is_large_binary, "BLOB"),
        (pa.types.is_timestamp, "TEXT"),
        (pa.types.is_date, "TEXT"),
    ]

    def storage_type(self, tp: pa.DataType) -> str:
        for pred, st in self._STORAGE:
            if pred(tp):
                return st
        raise FugueInvalidOperation(
            f"type {tp} has no {self.name} storage mapping (nested/decimal "
            "columns are not supported by the warehouse engine)"
        )

    def meta_upsert_sql(self) -> str:
        return f"INSERT OR REPLACE INTO {_SCHEMA_META_TABLE} VALUES (?, ?)"

    def table_exists_sql(self, views: bool = True) -> str:
        kinds = "('table','view')" if views else "('table')"
        return (
            "SELECT name FROM sqlite_master "
            f"WHERE type IN {kinds} AND name = ?"
        )

    def table_info(self, connection: Any, table: str) -> List[Tuple[str, str]]:
        rows = connection.execute(
            f"PRAGMA table_info({self.quote(table)})"
        ).fetchall()
        return [(name, decl or "") for _, name, decl, *_rest in rows]

    def decl_to_arrow(self, decl: str) -> Optional[pa.DataType]:
        decl = (decl or "").upper()
        if "INT" in decl:
            return pa.int64()
        if decl in ("REAL", "FLOAT", "DOUBLE"):
            return pa.float64()
        if "CHAR" in decl or "TEXT" in decl:
            return pa.string()
        if "BLOB" in decl:
            return pa.binary()
        return None  # dynamic typing: sample values


class PostgresProfile(WarehouseProfile):
    """Emission profile for PostgreSQL (psycopg-style DB-API).

    No live server exists in this environment; golden tests
    (``tests/warehouse/test_profiles.py``) pin every SQL string the engine
    would send. The mappings follow postgres documentation syntax:
    ``information_schema`` introspection, ``%s`` placeholders,
    ``ON CONFLICT`` upsert, real column types (no storage-class collapse,
    so ``decl_to_arrow`` never needs value sampling)."""

    name = "postgres"
    paramstyle = "format"

    _STORAGE: List[Tuple[Any, str]] = [
        (pa.types.is_boolean, "BOOLEAN"),
        (lambda t: pa.types.is_integer(t) and t.bit_width <= 16, "SMALLINT"),
        (lambda t: pa.types.is_integer(t) and t.bit_width <= 32, "INTEGER"),
        (pa.types.is_integer, "BIGINT"),
        (lambda t: pa.types.is_floating(t) and t.bit_width <= 32, "REAL"),
        (pa.types.is_floating, "DOUBLE PRECISION"),
        (pa.types.is_string, "TEXT"),
        (pa.types.is_large_string, "TEXT"),
        (pa.types.is_binary, "BYTEA"),
        (pa.types.is_large_binary, "BYTEA"),
        (pa.types.is_timestamp, "TIMESTAMP"),
        (pa.types.is_date, "DATE"),
    ]

    def storage_type(self, tp: pa.DataType) -> str:
        for pred, st in self._STORAGE:
            if pred(tp):
                return st
        raise FugueInvalidOperation(
            f"type {tp} has no {self.name} storage mapping (nested/decimal "
            "columns are not supported by the warehouse engine)"
        )

    def create_temp_table_sql(self, table: str, schema: Schema) -> str:
        cols = ", ".join(
            f"{self.quote(f.name)} {self.storage_type(f.type)}"
            for f in schema.fields
        )
        return f"CREATE TEMPORARY TABLE {self.quote(table)} ({cols})"

    def create_temp_table_as_sql(self, table: str, select_sql: str) -> str:
        return f"CREATE TEMPORARY TABLE {self.quote(table)} AS {select_sql}"

    def meta_upsert_sql(self) -> str:
        return (
            f"INSERT INTO {_SCHEMA_META_TABLE} VALUES (%s, %s) "
            "ON CONFLICT (tbl) DO UPDATE SET schema = EXCLUDED.schema"
        )

    def table_exists_sql(self, views: bool = True) -> str:
        if views:
            return (
                "SELECT table_name FROM information_schema.tables "
                "WHERE table_name = %s"
            )
        return (
            "SELECT table_name FROM information_schema.tables "
            "WHERE table_type = 'BASE TABLE' AND table_name = %s"
        )

    def table_info(self, connection: Any, table: str) -> List[Tuple[str, str]]:
        cur = connection.execute(
            "SELECT column_name, data_type FROM information_schema.columns "
            "WHERE table_name = %s ORDER BY ordinal_position",
            (table,),
        )
        return [(name, decl or "") for name, decl in cur.fetchall()]

    def decl_to_arrow(self, decl: str) -> Optional[pa.DataType]:
        decl = (decl or "").upper()
        mapping = {
            "BOOLEAN": pa.bool_(),
            "SMALLINT": pa.int16(),
            "INTEGER": pa.int32(),
            "BIGINT": pa.int64(),
            "REAL": pa.float32(),
            "DOUBLE PRECISION": pa.float64(),
            "TEXT": pa.string(),
            "CHARACTER VARYING": pa.string(),
            "BYTEA": pa.binary(),
            "TIMESTAMP": pa.timestamp("us"),
            "TIMESTAMP WITHOUT TIME ZONE": pa.timestamp("us"),
            "DATE": pa.date32(),
        }
        return mapping.get(decl)


PROFILES = {
    "sqlite": SQLiteProfile,
    "postgres": PostgresProfile,
}


def get_profile(name: Any) -> WarehouseProfile:
    if isinstance(name, WarehouseProfile):
        return name
    key = str(name or "sqlite").lower()
    if key not in PROFILES:
        raise FugueInvalidOperation(
            f"unknown warehouse profile {name!r}; known: {sorted(PROFILES)}"
        )
    return PROFILES[key]()
