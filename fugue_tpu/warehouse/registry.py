"""Register the warehouse (sqlite) engine with the plugin system.

Parity with the reference's backend registries (e.g.
``fugue_duckdb/registry.py:38-74``): engine available by name
(``"sqlite"``), inferred from WarehouseDataFrame/sqlite3.Connection
inputs, and usable as a SQL engine for CONNECT/engine-switch statements.
"""

import sqlite3
from typing import Any, List

from ..execution.factory import (
    infer_execution_engine,
    parse_execution_engine,
)
from .dataframe import WarehouseDataFrame
from .execution_engine import SQLiteExecutionEngine


@infer_execution_engine.candidate(
    lambda objs: any(
        isinstance(o, (WarehouseDataFrame, sqlite3.Connection)) for o in objs
    )
)
def _infer_warehouse_engine(objs: List[Any]) -> Any:
    for o in objs:
        if isinstance(o, WarehouseDataFrame):
            return o._wh_engine
        if isinstance(o, sqlite3.Connection):
            return o
    return "sqlite"  # pragma: no cover


@parse_execution_engine.candidate(
    lambda engine, conf, **kwargs: isinstance(engine, sqlite3.Connection),
    priority=1.5,
)
def _parse_sqlite_connection(engine: Any, conf: Any, **kwargs: Any) -> Any:
    return SQLiteExecutionEngine(conf, connection=engine)


# NOTE the "sqlite" engine/SQL-engine NAMES register lazily in
# fugue_tpu/execution/__init__.py (the single registration site, same
# pattern as "jax"/"tpu") — this module adds only inference/parsing
