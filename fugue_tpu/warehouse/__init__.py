"""External-SQL-warehouse engine role (the reference's fugue_ibis analog):
Fugue ops pushed down to a DB-API database; sqlite3 is the in-env backend."""

from .dataframe import WarehouseDataFrame
from .execution_engine import (
    SQLiteExecutionEngine,
    WarehouseExecutionEngine,
    WarehouseMapEngine,
    WarehouseSQLEngine,
)
from .hybrid import WarehouseJaxExecutionEngine, WarehouseJaxMapEngine
from . import registry  # noqa: F401  (self-registration at import)

__all__ = [
    "WarehouseDataFrame",
    "WarehouseExecutionEngine",
    "WarehouseJaxExecutionEngine",
    "WarehouseJaxMapEngine",
    "WarehouseMapEngine",
    "WarehouseSQLEngine",
    "SQLiteExecutionEngine",
]
