"""Warehouse+device hybrid engine — the reference's DuckDask analog.

The reference composes DuckDB SQL with Dask maps in ONE engine
(`/root/reference/fugue_duckdb/dask.py:17-40`): relational verbs stay in
the vectorized SQL backend, per-partition UDFs run on the distributed
side. `WarehouseJaxExecutionEngine` is the same composition TPU-first:

- SQL/relational verbs (select/filter/join/set-ops/aggregate pushdown…)
  run in the warehouse over DB-API (inherited from
  :class:`WarehouseExecutionEngine`);
- `map_dataframe` hands the frame to the **jax mesh** via ONE arrow
  fetch — jax-annotated UDFs compile onto the device mesh
  (`fugue_tpu/jax/execution_engine.py`), pandas UDFs run the engine's
  partitioned host path — and the result lands back in the warehouse as
  arrow. No local-oracle map roundtrip anywhere.

A mixed FugueSQL pipeline (SELECT … then TRANSFORM … then SELECT …)
therefore runs start-to-finish on one engine: storage-side SQL,
device-side compute.
"""

from typing import Any, Callable, Optional

from ..collections.partition import PartitionCursor, PartitionSpec
from ..dataframe import ArrowDataFrame, DataFrame, LocalDataFrame
from ..execution.execution_engine import ExecutionEngine, MapEngine
from .execution_engine import SQLiteExecutionEngine


class WarehouseJaxMapEngine(MapEngine):
    """Map facet bridging warehouse tables onto the device mesh."""

    @property
    def is_distributed(self) -> bool:
        return True

    @property
    def map_handles_repartition(self) -> bool:
        # the jax map engine owns its partitioning decisions (logical
        # grouping / device exchange) — no warehouse-side pre-shuffle
        return True

    def map_dataframe(
        self,
        df: DataFrame,
        map_func: Callable[[PartitionCursor, LocalDataFrame], LocalDataFrame],
        output_schema: Any,
        partition_spec: PartitionSpec,
        on_init: Optional[Callable[[int, DataFrame], Any]] = None,
        map_func_format_hint: Optional[str] = None,
    ) -> DataFrame:
        eng: "WarehouseJaxExecutionEngine" = self.execution_engine  # type: ignore
        wdf = eng.to_df(df)
        # ONE warehouse -> arrow fetch; the jax engine ingests to device
        arrow = ArrowDataFrame(eng.fetch_arrow(wdf.table, wdf.schema))
        res = eng.jax_engine.map_engine.map_dataframe(
            arrow,
            map_func=map_func,
            output_schema=output_schema,
            partition_spec=partition_spec,
            on_init=on_init,
            map_func_format_hint=map_func_format_hint,
        )
        # ONE device -> arrow handoff back into warehouse storage
        return eng.ingest(res)


class WarehouseJaxExecutionEngine(SQLiteExecutionEngine):
    """SQL in the warehouse, maps on the jax device mesh (reference
    ``DuckDaskExecutionEngine``, ``fugue_duckdb/dask.py:17``). Registered
    as engine name ``"sqlite_jax"``; ``conf["fugue.sqlite.path"]``
    selects the DB file like the plain sqlite engine."""

    def __init__(self, conf: Any = None, connection: Any = None, **kwargs: Any):
        super().__init__(conf, connection=connection, **kwargs)
        from ..jax import JaxExecutionEngine

        self._jax_engine = JaxExecutionEngine(conf)

    @property
    def jax_engine(self) -> ExecutionEngine:
        """The device-mesh side handling compute-heavy maps."""
        return self._jax_engine

    @property
    def is_distributed(self) -> bool:
        return True

    def create_default_map_engine(self) -> MapEngine:
        return WarehouseJaxMapEngine(self)

    def get_current_parallelism(self) -> int:
        return self._jax_engine.get_current_parallelism()

    def stop_engine(self) -> None:
        self._jax_engine.stop_engine()
        super().stop_engine()
