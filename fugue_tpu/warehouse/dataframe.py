"""WarehouseDataFrame — a frame whose data LIVES in an external SQL
warehouse (DB-API connection), fetched only on demand.

This fills the reference's Ibis role (`fugue_ibis/execution_engine.py:352`,
`fugue_ibis/dataframe.py`): Fugue ops push down to the warehouse as SQL;
the frame itself is a (connection, table, schema) triple. The in-env
warehouse is sqlite3 (stdlib); the engine is written against plain DB-API
so other warehouses can slot in.
"""

from typing import Any, Dict, Iterable, List, Optional

import pyarrow as pa

from .._utils.assertion import assert_or_throw
from ..dataframe import (
    ArrowDataFrame,
    DataFrame,
    LocalBoundedDataFrame,
)
from ..exceptions import (
    FugueDataFrameEmptyError,
    FugueDataFrameOperationError,
)
from ..schema import Schema


class WarehouseDataFrame(DataFrame):
    """Lazy frame over a warehouse table (reference
    ``fugue_ibis/dataframe.py:23`` — an IbisTable wrapper with the same
    fetch-on-demand contract)."""

    def __init__(
        self, engine: Any, table: str, schema: Any, snapshot: bool = True
    ):
        self._wh_engine = engine
        self._table = table
        # snapshot=False for frames bound to persistent NAMED tables
        # (load_table): those can be overwritten underneath the frame, so
        # count() must not be memoized for them
        self._snapshot = snapshot
        self._count: Optional[int] = None
        super().__init__(schema if isinstance(schema, Schema) else Schema(schema))

    @property
    def table(self) -> str:
        """The warehouse-side table name holding this frame's rows."""
        return self._table

    @property
    def native(self) -> "WarehouseDataFrame":
        """The warehouse frame IS the native handle (like the reference's
        IbisTable, a lazy pointer into the backend); raw DB access is via
        ``.table`` + the engine's connection."""
        return self

    @property
    def is_local(self) -> bool:
        return False

    @property
    def is_bounded(self) -> bool:
        return True

    @property
    def num_partitions(self) -> int:
        return 1

    @property
    def empty(self) -> bool:
        return self.count() == 0

    def count(self) -> int:
        # temp frames are immutable snapshots of materialized tables, so
        # the count is computed once — validators hammer count()/empty and
        # a remote DB-API warehouse would otherwise pay a round-trip each
        # time; named-table frames (snapshot=False) always re-query
        if self._count is None or not self._snapshot:
            cur = self._wh_engine.connection.execute(
                f"SELECT COUNT(*) FROM {self._wh_engine.encode_name(self._table)}"
            )
            self._count = int(cur.fetchone()[0])
        return self._count

    def peek_array(self) -> List[Any]:
        head = self.head(1)
        arr = head.as_array()
        assert_or_throw(len(arr) > 0, FugueDataFrameEmptyError("empty dataframe"))
        return arr[0]

    def as_local_bounded(self) -> LocalBoundedDataFrame:
        return ArrowDataFrame(self._wh_engine.fetch_arrow(self._table, self.schema))

    def as_arrow(self, type_safe: bool = False) -> pa.Table:
        return self._wh_engine.fetch_arrow(self._table, self.schema)

    def as_pandas(self) -> Any:
        return self.as_local_bounded().as_pandas()

    def as_array(
        self, columns: Optional[List[str]] = None, type_safe: bool = False
    ) -> List[Any]:
        return self.as_local_bounded().as_array(columns, type_safe=type_safe)

    def as_array_iterable(
        self, columns: Optional[List[str]] = None, type_safe: bool = False
    ) -> Iterable[Any]:
        return self.as_local_bounded().as_array_iterable(columns, type_safe=type_safe)

    def _drop_cols(self, cols: List[str]) -> DataFrame:
        keep = [n for n in self.schema.names if n not in cols]
        return self._project(keep)

    def _select_cols(self, cols: List[str]) -> DataFrame:
        return self._project(cols)

    def _project(self, cols: List[str]) -> DataFrame:
        e = self._wh_engine
        sel = ", ".join(e.encode_name(c) for c in cols)
        tbl = e.materialize(
            f"SELECT {sel} FROM {e.encode_name(self._table)}"
        )
        return e.temp_frame(tbl, self.schema.extract(cols))

    def rename(self, columns: Dict[str, str]) -> DataFrame:
        try:
            new_schema = self.schema.rename(columns)
        except Exception as e:
            raise FugueDataFrameOperationError(str(e)) from e
        eng = self._wh_engine
        sel = ", ".join(
            f"{eng.encode_name(n)} AS {eng.encode_name(columns.get(n, n))}"
            for n in self.schema.names
        )
        tbl = eng.materialize(f"SELECT {sel} FROM {eng.encode_name(self._table)}")
        return eng.temp_frame(tbl, new_schema)

    def alter_columns(self, columns: Any) -> DataFrame:
        new_schema = Schema(self.schema).alter(columns)
        if new_schema == self.schema:
            return self
        # casts run host-side through arrow — exact, and the result goes
        # back into the warehouse so the frame stays warehouse-resident
        local = ArrowDataFrame(self.as_arrow().cast(new_schema.pa_schema))
        return self._wh_engine.ingest(local)

    def head(
        self, n: int, columns: Optional[List[str]] = None
    ) -> LocalBoundedDataFrame:
        # straight off a cursor — a temp table just to read n rows would
        # live (and hold a copy) until the connection closes
        e = self._wh_engine
        cols = columns if columns is not None else self.schema.names
        sel = ", ".join(e.encode_name(c) for c in cols)
        return ArrowDataFrame(
            e.fetch_arrow_query(
                f"SELECT {sel} FROM {e.encode_name(self._table)} LIMIT {int(n)}",
                self.schema.extract(cols),
            )
        )
