"""All extensible plugin hooks in one namespace.

Parity with the reference (`fugue/plugins.py`): backends and user libraries
register candidates on these hooks to extend the framework.
"""

from .collections.sql import transpile_sql  # noqa: F401
from .dataset.api import as_fugue_dataset  # noqa: F401
from .dataset.dataset import get_dataset_display  # noqa: F401
from .dataframe.api import as_fugue_df, get_native_as_df  # noqa: F401
from .dataframe.function_wrapper import fugue_annotated_param  # noqa: F401
from .execution.factory import (  # noqa: F401
    infer_execution_engine,
    parse_execution_engine,
    register_default_execution_engine,
    register_default_sql_engine,
    register_execution_engine,
    register_sql_engine,
)
from .extensions.creator.convert import parse_creator, register_creator  # noqa: F401
from .extensions.outputter.convert import (  # noqa: F401
    parse_outputter,
    register_outputter,
)
from .extensions.processor.convert import (  # noqa: F401
    parse_processor,
    register_processor,
)
from .extensions.transformer.convert import (  # noqa: F401
    parse_output_transformer,
    parse_transformer,
    register_output_transformer,
    register_transformer,
)


def namespace_candidate(namespace: str, matcher: "callable") -> "callable":
    """Build a matcher for namespaced string extensions like ``"viz:bar"``
    (reference ``triad namespace_candidate`` usage in ``fugue_contrib``)."""

    def _m(obj: "object", *args: "object", **kwargs: "object") -> bool:
        if not isinstance(obj, str) or ":" not in obj:
            return False
        ns, expr = obj.split(":", 1)
        return ns == namespace and matcher(expr)

    return _m
