"""Outputter conversion (reference ``fugue/extensions/outputter/convert.py``)."""

import copy
from typing import Any, Callable, Dict, List, Optional

from ..._utils.assertion import assert_or_throw
from ..._utils.convert import get_caller_global_local_vars, to_instance
from ..._utils.hash import to_uuid
from ..._utils.registry import fugue_plugin
from ...dataframe import DataFrames
from ...dataframe.function_wrapper import DataFrameFunctionWrapper
from ...exceptions import FugueInterfacelessError
from .._shared import ExtensionRegistry, resolve_extension_object
from .._utils import parse_validation_rules_from_comment, to_validation_rules
from .outputter import Outputter

_OUTPUTTER_REGISTRY = ExtensionRegistry("outputter")


def register_outputter(alias: str, obj: Any, on_dup: str = "overwrite") -> None:
    _OUTPUTTER_REGISTRY.register(alias, obj, on_dup)


@fugue_plugin
def parse_outputter(obj: Any) -> Any:
    return obj


def outputter(**validation_rules: Any) -> Callable[[Callable], "_FuncAsOutputter"]:
    def deco(func: Callable) -> _FuncAsOutputter:
        return _FuncAsOutputter.from_func(
            func, validation_rules=to_validation_rules(validation_rules)
        )

    return deco


def _to_outputter(
    obj: Any,
    global_vars: Optional[Dict[str, Any]] = None,
    local_vars: Optional[Dict[str, Any]] = None,
) -> Outputter:
    global_vars, local_vars = get_caller_global_local_vars(global_vars, local_vars)
    parsed = parse_outputter(obj)
    resolved = resolve_extension_object(
        parsed, _OUTPUTTER_REGISTRY, Outputter, global_vars, local_vars
    )
    if isinstance(resolved, Outputter):
        return copy.copy(resolved)
    if isinstance(resolved, type) and issubclass(resolved, Outputter):
        return to_instance(resolved, Outputter)
    if callable(resolved):
        return _FuncAsOutputter.from_func(resolved, validation_rules={})
    raise FugueInterfacelessError(f"can't convert {obj!r} to an outputter")


class _FuncAsOutputter(Outputter):
    @property
    def validation_rules(self) -> Dict[str, Any]:
        return self._validation_rules  # type: ignore

    def process(self, dfs: DataFrames) -> None:
        args: List[Any] = []
        if self._engine_param:  # type: ignore
            args.append(self.execution_engine)
        if self._dfs_input:  # type: ignore
            args.append(dfs)
        else:
            args.extend(dfs.values())
        self._wrapper.run(args, self.params, ignore_unknown=False, output=False)  # type: ignore

    def __uuid__(self) -> str:
        return to_uuid(self._wrapper.__uuid__(), self._validation_rules)  # type: ignore

    @staticmethod
    def from_func(func: Callable, validation_rules: Dict[str, Any]) -> "_FuncAsOutputter":
        validation_rules = dict(validation_rules)
        validation_rules.update(parse_validation_rules_from_comment(func))
        tr = _FuncAsOutputter()
        tr._wrapper = DataFrameFunctionWrapper(  # type: ignore
            func, "^e?(c|[dlspq]+)x*z?$", "^n$"
        )
        tr._engine_param = tr._wrapper.input_code.startswith("e")  # type: ignore
        tr._dfs_input = "c" in tr._wrapper.input_code  # type: ignore
        tr._validation_rules = validation_rules  # type: ignore
        return tr
