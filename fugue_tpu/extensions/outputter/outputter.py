"""Outputter — driver-side n-input→0-output extension (reference
``fugue/extensions/outputter/outputter.py``)."""

from ...dataframe import DataFrames
from ..context import ExtensionContext


class Outputter(ExtensionContext):
    def process(self, dfs: DataFrames) -> None:
        raise NotImplementedError

    @property
    def validation_rules(self) -> dict:
        return {}
