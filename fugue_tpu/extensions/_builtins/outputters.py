"""Built-in outputters (reference ``fugue/extensions/_builtins/outputters.py``)."""

from typing import Any, List, Optional

from ..._utils.assertion import assert_or_throw
from ...collections.yielded import Yielded
from ...dataframe import DataFrame, DataFrames
from ...dataframe.utils import _df_eq
from ...exceptions import FugueWorkflowError
from ..outputter.outputter import Outputter


class Show(Outputter):
    def process(self, dfs: DataFrames) -> None:
        n = self.params.get("n", 10)
        with_count = self.params.get("with_count", False)
        title = self.params.get_or_none("title", str)
        for i, df in enumerate(dfs.values()):
            df.show(n=n, with_count=with_count, title=title if i == 0 else None)


class AssertEqual(Outputter):
    def process(self, dfs: DataFrames) -> None:
        assert_or_throw(len(dfs) >= 2, FugueWorkflowError("assert_eq requires 2+ inputs"))
        expected = dfs[0]
        for i in range(1, len(dfs)):
            _df_eq(expected, dfs[i], throw=True, **self.params)


class AssertNotEqual(Outputter):
    def process(self, dfs: DataFrames) -> None:
        assert_or_throw(len(dfs) >= 2, FugueWorkflowError("assert_ne requires 2+ inputs"))
        expected = dfs[0]
        for i in range(1, len(dfs)):
            assert_or_throw(
                not _df_eq(expected, dfs[i], **self.params),
                AssertionError("dataframes are equal"),
            )


class Save(Outputter):
    def process(self, dfs: DataFrames) -> None:
        assert_or_throw(len(dfs) == 1, FugueWorkflowError("save takes one input"))
        kwargs = self.params.get("params", dict())
        path = self.params.get_or_throw("path", str)
        format_hint = self.params.get("fmt", "")
        mode = self.params.get("mode", "overwrite")
        partition_spec = self.partition_spec
        force_single = self.params.get("single", False)
        self.execution_engine.save_df(
            df=dfs[0],
            path=path,
            format_hint=format_hint or None,
            mode=mode,
            partition_spec=partition_spec,
            force_single=force_single,
            **kwargs,
        )
