"""Built-in creators (reference ``fugue/extensions/_builtins/creators.py``)."""

from typing import Any

from ...collections.yielded import Yielded
from ...dataframe import DataFrame
from ..creator.creator import Creator


class Load(Creator):
    def create(self) -> DataFrame:
        kwargs = self.params.get("params", dict())
        path = self.params.get_or_throw("path", str)
        format_hint = self.params.get("fmt", "")
        columns = self.params.get_or_none("columns", object)
        return self.execution_engine.load_df(
            path=path, format_hint=format_hint or None, columns=columns, **kwargs
        )


class CreateData(Creator):
    def create(self) -> DataFrame:
        data = self.params.get_or_throw("data", object)
        schema = self.params.get_or_none("schema", object)
        if isinstance(data, Yielded):
            return self.execution_engine.load_yielded(data)
        if (
            isinstance(data, DataFrame)
            and data.is_local
            and not data.is_bounded
            and schema is None
        ):
            # one-pass stream frames enter the DAG lazily — eager to_df
            # would materialize them; downstream verbs with a streaming
            # plan (aggregate, keyless compiled map) consume them
            # out-of-core, everything else converts at its own to_df
            return data
        return self.execution_engine.to_df(data, schema=schema)
