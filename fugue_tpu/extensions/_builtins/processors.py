"""Built-in processors — the implementations behind workflow verbs.

Parity with the reference (`fugue/extensions/_builtins/processors.py`):
RunTransformer, RunJoin, RunSetOperation, Distinct, Dropna, Fillna,
RunSQLSelect, Zip, Select, Filter, Assign, Aggregate, Rename, AlterColumns,
Sample, Take, DropColumns, SelectColumns.
"""

from typing import Any, List, Optional, Type

from ..._utils.assertion import assert_or_throw
from ...collections.partition import PartitionCursor, PartitionSpec
from ...collections.sql import StructuredRawSQL
from ...column import SelectColumns as ColSelectColumns
from ...dataframe import ArrayDataFrame, DataFrame, DataFrames, LocalDataFrame
from ...exceptions import FugueWorkflowError
from ...rpc.base import to_rpc_handler
from ...schema import Schema
from .._utils import validate_input_schema
from ..processor.processor import Processor
from ..transformer.transformer import CoTransformer, Transformer


class RunTransformer(Processor):
    """Wrap a Transformer/CoTransformer into a map/comap call
    (reference ``processors.py:23``)."""

    @property
    def validation_rules(self) -> dict:
        return self._transformer.validation_rules  # type: ignore

    def process(self, dfs: DataFrames) -> DataFrame:
        df = dfs[0]
        tf = self.params.get_or_throw("transformer", object)
        ignore_errors = self.params.get("ignore_errors", [])
        callback = self.params.get_or_none("callback", object)
        save_partition = self.partition_spec
        engine = self.execution_engine
        tf._workflow_conf = engine.conf
        tf._params = self.params.get("params", dict())
        tf._partition_spec = save_partition
        tf._execution_engine = engine
        rpc_handler = to_rpc_handler(callback)
        from ...rpc.base import EmptyRPCHandler

        if not isinstance(rpc_handler, EmptyRPCHandler):
            tf._callback = engine.rpc_server.make_client(rpc_handler)
        else:
            tf._callback = None
        if isinstance(tf, CoTransformer):
            return self._run_cotransform(df, tf, ignore_errors)
        return self._run_transform(df, tf, ignore_errors)

    def _run_transform(
        self, df: DataFrame, tf: Transformer, ignore_errors: List[Any]
    ) -> DataFrame:
        engine = self.execution_engine
        spec = self.partition_spec
        # a map engine that groups logically inside map_dataframe (both the
        # host pandas path and the device segment path) doesn't need a
        # physical exchange first — mirroring Spark's map engine, which owns
        # its repartition decisions inside map_dataframe
        if not spec.empty and not getattr(
            engine.map_engine, "map_handles_repartition", False
        ):
            df = engine.repartition(df, spec)
        validate_input_schema(df.schema, tf.validation_rules)
        schema = Schema(tf.get_output_schema(df))
        tf._output_schema = schema
        tf._key_schema = spec.get_key_schema(df.schema)
        runner = _TransformerRunner(df, tf, _parse_exceptions(ignore_errors))
        fmt = tf.get_format_hint() if hasattr(tf, "get_format_hint") else None
        return engine.map_engine.map_dataframe(
            df,
            runner.run,
            output_schema=schema,
            partition_spec=spec,
            on_init=runner.on_init,
            map_func_format_hint=fmt,
        )

    def _run_cotransform(
        self, df: DataFrame, tf: CoTransformer, ignore_errors: List[Any]
    ) -> DataFrame:
        engine = self.execution_engine
        assert_or_throw(
            df.metadata.get("serialized", False),
            FugueWorkflowError("the input of cotransform must be a zipped dataframe"),
        )
        spec = self.partition_spec
        if spec.empty:
            keys = df.metadata.get("keys", [])
            spec = PartitionSpec(by=keys) if len(keys) > 0 else spec
        empty_dfs = DataFrames(
            {
                (df.metadata["names"][i] if df.metadata.get("serialized_has_name", False) else f"_{i}"):
                ArrayDataFrame([], s)
                for i, s in enumerate(df.metadata["schemas"])
            }
        )
        schema = Schema(tf.get_output_schema(empty_dfs))
        tf._output_schema = schema
        tf._key_schema = df.schema.extract(df.metadata.get("keys", []))
        runner = _CoTransformerRunner(tf, _parse_exceptions(ignore_errors), schema)
        return engine.comap(
            df,
            runner.run,
            output_schema=schema,
            partition_spec=spec,
            on_init=runner.on_init,
        )


def _parse_exceptions(ignore_errors: List[Any]) -> List[Type[Exception]]:
    from ..._utils.convert import to_type

    return [to_type(x, Exception) for x in ignore_errors]  # type: ignore


class _TransformerRunner:
    def __init__(self, df: DataFrame, transformer: Transformer, ignore_errors: List[type]):
        self.schema = df.schema
        self.metadata = df.metadata if df.has_metadata else None
        self.transformer = transformer
        self.ignore_errors = tuple(ignore_errors)

    def run(self, cursor: PartitionCursor, df: LocalDataFrame) -> LocalDataFrame:
        self.transformer._cursor = cursor  # type: ignore
        df._metadata = self.metadata
        if len(self.ignore_errors) == 0:
            return self.transformer.transform(df)
        try:
            return self.transformer.transform(df).as_local_bounded()
        except self.ignore_errors:  # type: ignore
            return ArrayDataFrame([], self.transformer.output_schema)

    def on_init(self, partition_no: int, df: DataFrame) -> None:
        s = self.transformer.partition_spec
        self.transformer._cursor = s.get_cursor(self.schema, partition_no)  # type: ignore
        self.transformer.on_init(df)


class _CoTransformerRunner:
    def __init__(self, transformer: CoTransformer, ignore_errors: List[type], schema: Schema):
        self.transformer = transformer
        self.ignore_errors = tuple(ignore_errors)
        self.schema = schema

    def run(self, cursor: PartitionCursor, dfs: DataFrames) -> LocalDataFrame:
        self.transformer._cursor = cursor  # type: ignore
        if len(self.ignore_errors) == 0:
            return self.transformer.transform(dfs)
        try:
            return self.transformer.transform(dfs).as_local_bounded()
        except self.ignore_errors:  # type: ignore
            return ArrayDataFrame([], self.schema)

    def on_init(self, partition_no: int, dfs: DataFrames) -> None:
        self.transformer._cursor = PartitionCursor(  # type: ignore
            Schema(), self.transformer.partition_spec, partition_no
        )
        self.transformer.on_init(dfs)


class RunJoin(Processor):
    def process(self, dfs: DataFrames) -> DataFrame:
        if len(dfs) == 1:
            return dfs[0]
        how = self.params.get_or_throw("how", str)
        on = self.params.get("on", [])
        df = dfs[0]
        for i in range(1, len(dfs)):
            df = self.execution_engine.join(df, dfs[i], how=how, on=on)
        return df


class RunSetOperation(Processor):
    def process(self, dfs: DataFrames) -> DataFrame:
        if len(dfs) == 1:
            return dfs[0]
        how = self.params.get_or_throw("how", str)
        unique = self.params.get("distinct", True)
        ops = {
            "union": self.execution_engine.union,
            "subtract": self.execution_engine.subtract,
            "intersect": self.execution_engine.intersect,
        }
        assert_or_throw(how in ops, FugueWorkflowError(f"invalid set op {how}"))
        op = ops[how]
        df = dfs[0]
        for i in range(1, len(dfs)):
            df = op(df, dfs[i], distinct=unique)
        return df


class Distinct(Processor):
    def process(self, dfs: DataFrames) -> DataFrame:
        assert_or_throw(len(dfs) == 1, FugueWorkflowError("distinct takes one input"))
        return self.execution_engine.distinct(dfs[0])


class Dropna(Processor):
    def process(self, dfs: DataFrames) -> DataFrame:
        assert_or_throw(len(dfs) == 1, FugueWorkflowError("dropna takes one input"))
        how = self.params.get("how", "any")
        assert_or_throw(
            how in ("any", "all"),
            FugueWorkflowError("how' needs to be either 'any' or 'all'"),
        )
        thresh = self.params.get_or_none("thresh", int)
        subset = self.params.get_or_none("subset", list)
        return self.execution_engine.dropna(dfs[0], how=how, thresh=thresh, subset=subset)


class Fillna(Processor):
    def process(self, dfs: DataFrames) -> DataFrame:
        assert_or_throw(len(dfs) == 1, FugueWorkflowError("fillna takes one input"))
        value = self.params.get_or_none("value", object)
        subset = self.params.get_or_none("subset", list)
        return self.execution_engine.fillna(dfs[0], value=value, subset=subset)


class RunSQLSelect(Processor):
    def process(self, dfs: DataFrames) -> DataFrame:
        statement = self.params.get_or_throw("statement", StructuredRawSQL)
        engine = self.execution_engine
        spec = self.params.get_or_none("sql_engine", object)
        if spec is None:
            sql_engine = engine.sql_engine
        else:
            # engine-specific select (FugueSQL CONNECT): a registered SQL
            # engine, a SQLEngine class, or an execution-engine name whose
            # SQL facet runs this statement
            from ...execution.factory import (
                make_execution_engine,
                make_sql_engine,
            )

            from ...exceptions import FuguePluginsRegistrationError

            kw = dict(self.params.get("sql_engine_params", dict()))
            try:
                sql_engine = make_sql_engine(spec, engine, **kw)
            except FuguePluginsRegistrationError:
                # not a registered SQL engine — treat the spec as an
                # execution-engine name and run on its SQL facet; the
                # temporary engine stops once the result is detached
                other = make_execution_engine(spec, conf=engine.conf, **kw)
                try:
                    res = other.sql_engine.select(dfs, statement)
                    return engine.to_df(res.as_local_bounded())
                finally:
                    if other is not engine and not other.in_context:
                        other.stop()
        return sql_engine.select(dfs, statement)


class Zip(Processor):
    def process(self, dfs: DataFrames) -> DataFrame:
        how = self.params.get("how", "inner")
        partition_spec = self.partition_spec
        temp_path = self.params.get_or_none("temp_path", str)
        to_file_threshold = self.params.get("to_file_threshold", -1)
        return self.execution_engine.zip(
            dfs,
            how=how,
            partition_spec=partition_spec,
            temp_path=temp_path,
            to_file_threshold=to_file_threshold,
        )


class Select(Processor):
    def process(self, dfs: DataFrames) -> DataFrame:
        assert_or_throw(len(dfs) == 1, FugueWorkflowError("select takes one input"))
        columns = self.params.get_or_throw("columns", ColSelectColumns)
        where = self.params.get_or_none("where", object)
        having = self.params.get_or_none("having", object)
        return self.execution_engine.select(dfs[0], columns, where=where, having=having)


class Filter(Processor):
    def process(self, dfs: DataFrames) -> DataFrame:
        assert_or_throw(len(dfs) == 1, FugueWorkflowError("filter takes one input"))
        condition = self.params.get_or_throw("condition", object)
        return self.execution_engine.filter(dfs[0], condition)


class Assign(Processor):
    def process(self, dfs: DataFrames) -> DataFrame:
        assert_or_throw(len(dfs) == 1, FugueWorkflowError("assign takes one input"))
        columns = self.params.get_or_throw("columns", list)
        return self.execution_engine.assign(dfs[0], columns)


class Aggregate(Processor):
    def process(self, dfs: DataFrames) -> DataFrame:
        assert_or_throw(len(dfs) == 1, FugueWorkflowError("aggregate takes one input"))
        columns = self.params.get_or_throw("columns", list)
        return self.execution_engine.aggregate(dfs[0], self.partition_spec, columns)


class Rename(Processor):
    def process(self, dfs: DataFrames) -> DataFrame:
        assert_or_throw(len(dfs) == 1, FugueWorkflowError("rename takes one input"))
        columns = self.params.get_or_throw("columns", dict)
        return dfs[0].rename(columns)


class AlterColumns(Processor):
    def process(self, dfs: DataFrames) -> DataFrame:
        assert_or_throw(len(dfs) == 1, FugueWorkflowError("alter_columns takes one input"))
        columns = self.params.get_or_throw("columns", object)
        return dfs[0].alter_columns(columns)


class DropColumns(Processor):
    def process(self, dfs: DataFrames) -> DataFrame:
        assert_or_throw(len(dfs) == 1, FugueWorkflowError("drop takes one input"))
        if_exists = self.params.get("if_exists", False)
        columns = self.params.get_or_throw("columns", list)
        if if_exists:
            columns = [c for c in columns if c in dfs[0].schema]
        return dfs[0].drop(columns)


class SelectColumns(Processor):
    def process(self, dfs: DataFrames) -> DataFrame:
        assert_or_throw(len(dfs) == 1, FugueWorkflowError("select takes one input"))
        columns = self.params.get_or_throw("columns", list)
        return dfs[0][columns]


class Sample(Processor):
    def process(self, dfs: DataFrames) -> DataFrame:
        assert_or_throw(len(dfs) == 1, FugueWorkflowError("sample takes one input"))
        n = self.params.get_or_none("n", int)
        frac = self.params.get_or_none("frac", float)
        replace = self.params.get("replace", False)
        seed = self.params.get_or_none("seed", int)
        return self.execution_engine.sample(dfs[0], n=n, frac=frac, replace=replace, seed=seed)


class Take(Processor):
    def process(self, dfs: DataFrames) -> DataFrame:
        assert_or_throw(len(dfs) == 1, FugueWorkflowError("take takes one input"))
        n = self.params.get_or_none("n", int)
        presort = self.params.get("presort", "")
        na_position = self.params.get("na_position", "last")
        return self.execution_engine.take(
            dfs[0],
            n=n,  # type: ignore
            presort=presort,
            na_position=na_position,
            partition_spec=self.partition_spec,
        )


class SaveAndUse(Processor):
    def process(self, dfs: DataFrames) -> DataFrame:
        assert_or_throw(len(dfs) == 1, FugueWorkflowError("save takes one input"))
        kwargs = self.params.get("params", dict())
        path = self.params.get_or_throw("path", str)
        format_hint = self.params.get("fmt", "")
        mode = self.params.get("mode", "overwrite")
        partition_spec = self.partition_spec
        force_single = self.params.get("single", False)
        engine = self.execution_engine
        engine.save_df(
            df=dfs[0],
            path=path,
            format_hint=format_hint or None,
            mode=mode,
            partition_spec=partition_spec,
            force_single=force_single,
            **kwargs,
        )
        return engine.load_df(path, format_hint=format_hint or None)
