"""Creator conversion (reference ``fugue/extensions/creator/convert.py``)."""

import copy
from typing import Any, Callable, Dict, List, Optional

from ..._utils.assertion import assert_or_throw
from ..._utils.convert import get_caller_global_local_vars, to_instance
from ..._utils.hash import to_uuid
from ..._utils.registry import fugue_plugin
from ...dataframe import DataFrame
from ...dataframe.function_wrapper import DataFrameFunctionWrapper
from ...exceptions import FugueInterfacelessError
from ...schema import Schema
from .._shared import ExtensionRegistry, parse_comment_annotation, resolve_extension_object
from .creator import Creator

_CREATOR_REGISTRY = ExtensionRegistry("creator")


def register_creator(alias: str, obj: Any, on_dup: str = "overwrite") -> None:
    _CREATOR_REGISTRY.register(alias, obj, on_dup)


@fugue_plugin
def parse_creator(obj: Any) -> Any:
    return obj


def creator(schema: Any = None) -> Callable[[Callable], "_FuncAsCreator"]:
    def deco(func: Callable) -> _FuncAsCreator:
        return _FuncAsCreator.from_func(func, schema)

    return deco


def _to_creator(
    obj: Any,
    schema: Any = None,
    global_vars: Optional[Dict[str, Any]] = None,
    local_vars: Optional[Dict[str, Any]] = None,
) -> Creator:
    global_vars, local_vars = get_caller_global_local_vars(global_vars, local_vars)
    parsed = parse_creator(obj)
    resolved = resolve_extension_object(
        parsed, _CREATOR_REGISTRY, Creator, global_vars, local_vars
    )
    if isinstance(resolved, Creator):
        assert_or_throw(
            schema is None,
            FugueInterfacelessError("schema must be None for Creator instances"),
        )
        return copy.copy(resolved)
    if isinstance(resolved, type) and issubclass(resolved, Creator):
        return to_instance(resolved, Creator)
    if callable(resolved):
        return _FuncAsCreator.from_func(resolved, schema)
    raise FugueInterfacelessError(f"can't convert {obj!r} to a creator")


class _FuncAsCreator(Creator):
    def create(self) -> DataFrame:
        args: List[Any] = []
        if self._engine_param:  # type: ignore
            args.append(self.execution_engine)
        return self._wrapper.run(  # type: ignore
            args,
            self.params,
            ignore_unknown=False,
            output_schema=self._output_schema_arg,  # type: ignore
        )

    def __uuid__(self) -> str:
        return to_uuid(self._wrapper.__uuid__(), str(self._output_schema_arg))  # type: ignore

    @staticmethod
    def from_func(func: Callable, schema: Any) -> "_FuncAsCreator":
        if schema is None:
            schema = parse_comment_annotation(func, "schema")
        tr = _FuncAsCreator()
        tr._wrapper = DataFrameFunctionWrapper(func, "^e?x*z?$", "^[dlspq]$")  # type: ignore
        tr._engine_param = tr._wrapper.input_code.startswith("e")  # type: ignore
        tr._output_schema_arg = None if schema is None else Schema(schema)  # type: ignore
        if tr._wrapper.need_output_schema:
            assert_or_throw(
                tr._output_schema_arg is not None,
                FugueInterfacelessError("schema is required for this output annotation"),
            )
        return tr
