"""Creator — driver-side 0-input extension (reference
``fugue/extensions/creator/creator.py``)."""

from ...dataframe import DataFrame
from ..context import ExtensionContext


class Creator(ExtensionContext):
    def create(self) -> DataFrame:
        raise NotImplementedError
