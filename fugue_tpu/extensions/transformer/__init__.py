from .transformer import CoTransformer, OutputCoTransformer, OutputTransformer, Transformer
from . import convert
