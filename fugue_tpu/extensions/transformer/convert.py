"""Transformer conversion: classes, functions, strings → Transformer.

Parity with the reference (`fugue/extensions/transformer/convert.py:28,101,242,328,423`):
``parse_transformer`` plugin, ``register_transformer``, ``@transformer`` /
``@cotransformer`` / ``@output_transformer`` / ``@output_cotransformer``
decorators, and the interfaceless ``_FuncAsTransformer`` (schema from
argument or ``# schema:`` comment).
"""

import copy
from typing import Any, Callable, Dict, List, Optional, Union

from ..._utils.assertion import assert_or_throw
from ..._utils.convert import get_caller_global_local_vars, to_instance
from ..._utils.hash import to_uuid
from ..._utils.registry import fugue_plugin
from ...dataframe import DataFrame, DataFrames, LocalDataFrame
from ...dataframe.function_wrapper import DataFrameFunctionWrapper
from ...exceptions import FugueInterfacelessError
from ...schema import Schema
from .._shared import ExtensionRegistry, parse_comment_annotation, resolve_extension_object
from .._utils import parse_validation_rules_from_comment, to_validation_rules
from .transformer import CoTransformer, OutputCoTransformer, OutputTransformer, Transformer

OUTPUT_TRANSFORMER_DUMMY_SCHEMA = Schema("_0:int")

_TRANSFORMER_REGISTRY = ExtensionRegistry("transformer")
_OUT_TRANSFORMER_REGISTRY = ExtensionRegistry("output_transformer")


def register_transformer(alias: str, obj: Any, on_dup: str = "overwrite") -> None:
    _TRANSFORMER_REGISTRY.register(alias, obj, on_dup)


def register_output_transformer(alias: str, obj: Any, on_dup: str = "overwrite") -> None:
    _OUT_TRANSFORMER_REGISTRY.register(alias, obj, on_dup)


@fugue_plugin
def parse_transformer(obj: Any) -> Any:
    """Plugin hook: custom transformer spec parsing (e.g. namespaced names)."""
    return obj


@fugue_plugin
def parse_output_transformer(obj: Any) -> Any:
    return obj


def transformer(schema: Any, **validation_rules: Any) -> Callable[[Callable], "_FuncAsTransformer"]:
    """Decorator version of transform functions (reference ``:242``)."""

    def deco(func: Callable) -> _FuncAsTransformer:
        assert_or_throw(
            not _is_cotransform_func(func),
            FugueInterfacelessError("multi-dataframe functions must use @cotransformer"),
        )
        return _FuncAsTransformer.from_func(
            func, schema, validation_rules=to_validation_rules(validation_rules)
        )

    return deco


def output_transformer(**validation_rules: Any) -> Callable[[Callable], "_FuncAsOutputTransformer"]:
    def deco(func: Callable) -> _FuncAsOutputTransformer:
        return _FuncAsOutputTransformer.from_func(
            func, None, validation_rules=to_validation_rules(validation_rules)
        )

    return deco


def cotransformer(schema: Any, **validation_rules: Any) -> Callable[[Callable], "_FuncAsCoTransformer"]:
    def deco(func: Callable) -> _FuncAsCoTransformer:
        return _FuncAsCoTransformer.from_func(
            func, schema, validation_rules=to_validation_rules(validation_rules)
        )

    return deco


def output_cotransformer(**validation_rules: Any) -> Callable[[Callable], "_FuncAsOutputCoTransformer"]:
    def deco(func: Callable) -> _FuncAsOutputCoTransformer:
        return _FuncAsOutputCoTransformer.from_func(
            func, None, validation_rules=to_validation_rules(validation_rules)
        )

    return deco


def _to_transformer(
    obj: Any,
    schema: Any = None,
    global_vars: Optional[Dict[str, Any]] = None,
    local_vars: Optional[Dict[str, Any]] = None,
) -> Union[Transformer, CoTransformer]:
    global_vars, local_vars = get_caller_global_local_vars(global_vars, local_vars)
    return _to_general_transformer(
        obj, schema, global_vars, local_vars,
        registry=_TRANSFORMER_REGISTRY,
        parse=parse_transformer,
        func_single=_FuncAsTransformer,
        func_multi=_FuncAsCoTransformer,
        bases=(Transformer, CoTransformer),
    )


def _to_output_transformer(
    obj: Any,
    global_vars: Optional[Dict[str, Any]] = None,
    local_vars: Optional[Dict[str, Any]] = None,
) -> Union[Transformer, CoTransformer]:
    global_vars, local_vars = get_caller_global_local_vars(global_vars, local_vars)
    return _to_general_transformer(
        obj, None, global_vars, local_vars,
        registry=_OUT_TRANSFORMER_REGISTRY,
        parse=parse_output_transformer,
        func_single=_FuncAsOutputTransformer,
        func_multi=_FuncAsOutputCoTransformer,
        bases=(Transformer, CoTransformer),
    )


def _to_general_transformer(
    obj: Any,
    schema: Any,
    global_vars: Any,
    local_vars: Any,
    registry: ExtensionRegistry,
    parse: Callable,
    func_single: type,
    func_multi: type,
    bases: tuple,
) -> Union[Transformer, CoTransformer]:
    parsed = parse(obj)
    resolved = resolve_extension_object(parsed, registry, bases[0], global_vars, local_vars)
    if isinstance(resolved, bases):
        copied = copy.copy(resolved)
        assert_or_throw(
            schema is None,
            FugueInterfacelessError("schema must be None when using an interface class"),
        )
        return copied
    if isinstance(resolved, type) and issubclass(resolved, bases):
        return to_instance(resolved, object)
    if callable(resolved):
        if _is_cotransform_func(resolved):
            return func_multi.from_func(resolved, schema, validation_rules={})
        return func_single.from_func(resolved, schema, validation_rules={})
    raise FugueInterfacelessError(f"can't convert {obj!r} to a transformer")


def _is_cotransform_func(func: Callable) -> bool:
    try:
        w = DataFrameFunctionWrapper(func)
    except FugueInterfacelessError:
        return False
    code = w.input_code
    dfs = [c for c in code if c in "clspqd"]
    return code.startswith("c") or len([c for c in code.split("x")[0] if c in "lspq"]) > 1


class _FuncAsTransformer(Transformer):
    """A plain function adapted into a Transformer (reference ``:328``)."""

    @property
    def validation_rules(self) -> Dict[str, Any]:
        return self._validation_rules  # type: ignore

    def get_output_schema(self, df: DataFrame) -> Any:
        return _apply_schema_arg(df.schema, self._output_schema_arg)

    def get_format_hint(self) -> Optional[str]:
        return self._wrapper.get_format_hint()

    @property
    def using_callback(self) -> bool:
        return any(c in self._wrapper.input_code for c in "fF")

    @property
    def callback_required(self) -> bool:
        return "f" in self._wrapper.input_code

    def transform(self, df: LocalDataFrame) -> LocalDataFrame:
        args: List[Any] = [df]
        if self.using_callback:
            args.append(
                self.callback if self.has_callback or self.callback_required else None
            )
        return self._wrapper.run(  # type: ignore
            args, self.params, ignore_unknown=False, output_schema=self.output_schema
        )

    def __uuid__(self) -> str:
        return to_uuid(
            self._wrapper.__uuid__(),
            str(self._output_schema_arg),
            self._validation_rules,
        )

    @staticmethod
    def from_func(
        func: Callable, schema: Any, validation_rules: Dict[str, Any]
    ) -> "_FuncAsTransformer":
        if schema is None:
            schema = parse_comment_annotation(func, "schema")
        validation_rules = dict(validation_rules)
        validation_rules.update(parse_validation_rules_from_comment(func))
        tr = _FuncAsTransformer()
        tr._wrapper = DataFrameFunctionWrapper(  # type: ignore
            func, "^[lspqj][fF]?x*z?$", "^[lspqjr]$"
        )
        tr._output_schema_arg = schema  # type: ignore
        tr._validation_rules = validation_rules  # type: ignore
        # interfaceless transformers ALWAYS need a declared output schema —
        # engines must know it before execution (reference behavior)
        assert_or_throw(
            schema is not None,
            FugueInterfacelessError(
                "schema is required for interfaceless transformers "
                "(pass schema=... or add a '# schema:' comment)"
            ),
        )
        return tr


class _FuncAsOutputTransformer(_FuncAsTransformer, OutputTransformer):
    """Function → OutputTransformer (reference ``:412``)."""

    def get_output_schema(self, df: DataFrame) -> Any:
        return OUTPUT_TRANSFORMER_DUMMY_SCHEMA

    def transform(self, df: LocalDataFrame) -> LocalDataFrame:
        args: List[Any] = [df]
        if self.using_callback:
            args.append(
                self.callback if self.has_callback or self.callback_required else None
            )
        self._wrapper.run(args, self.params, ignore_unknown=False, output=False)  # type: ignore
        from ...dataframe import ArrayDataFrame

        return ArrayDataFrame([], OUTPUT_TRANSFORMER_DUMMY_SCHEMA)

    @staticmethod
    def from_func(
        func: Callable, schema: Any, validation_rules: Dict[str, Any]
    ) -> "_FuncAsOutputTransformer":
        assert_or_throw(
            schema is None, FugueInterfacelessError("schema must be None for output transformers")
        )
        validation_rules = dict(validation_rules)
        validation_rules.update(parse_validation_rules_from_comment(func))
        tr = _FuncAsOutputTransformer()
        tr._wrapper = DataFrameFunctionWrapper(  # type: ignore
            func, "^[lspqj][fF]?x*z?$", "^[lspnqjr]$"
        )
        tr._output_schema_arg = None  # type: ignore
        tr._validation_rules = validation_rules  # type: ignore
        return tr


class _FuncAsCoTransformer(CoTransformer):
    """Function → CoTransformer (reference ``:423``)."""

    @property
    def validation_rules(self) -> Dict[str, Any]:
        return self._validation_rules  # type: ignore

    def get_output_schema(self, dfs: DataFrames) -> Any:
        # cotransform schema arg can't reference "*" (multiple inputs)
        return Schema(self._output_schema_arg)  # type: ignore

    def get_format_hint(self) -> Optional[str]:
        return self._wrapper.get_format_hint()

    @property
    def using_callback(self) -> bool:
        return any(c in self._wrapper.input_code for c in "fF")

    @property
    def callback_required(self) -> bool:
        return "f" in self._wrapper.input_code

    def transform(self, dfs: DataFrames) -> LocalDataFrame:
        if self._dfs_input:  # type: ignore
            args: List[Any] = [dfs]
        else:
            args = list(dfs.values())
        if self.using_callback:
            args.append(
                self.callback if self.has_callback or self.callback_required else None
            )
        return self._wrapper.run(  # type: ignore
            args, self.params, ignore_unknown=False, output_schema=self.output_schema
        )

    def __uuid__(self) -> str:
        return to_uuid(
            self._wrapper.__uuid__(),
            str(self._output_schema_arg),
            self._validation_rules,
        )

    @staticmethod
    def from_func(
        func: Callable, schema: Any, validation_rules: Dict[str, Any]
    ) -> "_FuncAsCoTransformer":
        assert_or_throw(
            len(validation_rules) == 0 and len(parse_validation_rules_from_comment(func)) == 0,
            FugueInterfacelessError("cotransformers take no validation rules"),
        )
        if schema is None:
            schema = parse_comment_annotation(func, "schema")
        if isinstance(schema, Schema):
            schema = str(schema)
        tr = _FuncAsCoTransformer()
        tr._wrapper = DataFrameFunctionWrapper(  # type: ignore
            func, "^(c|[lspqj]+)[fF]?x*z?$", "^[lspqjr]$"
        )
        tr._dfs_input = tr._wrapper.input_code.startswith("c")  # type: ignore
        tr._output_schema_arg = schema  # type: ignore
        tr._validation_rules = {}  # type: ignore
        assert_or_throw(
            schema is not None,
            FugueInterfacelessError("schema is required for interfaceless cotransformers"),
        )
        return tr


class _FuncAsOutputCoTransformer(_FuncAsCoTransformer, OutputCoTransformer):
    def get_output_schema(self, dfs: DataFrames) -> Any:
        return OUTPUT_TRANSFORMER_DUMMY_SCHEMA

    def transform(self, dfs: DataFrames) -> LocalDataFrame:
        if self._dfs_input:  # type: ignore
            args: List[Any] = [dfs]
        else:
            args = list(dfs.values())
        if self.using_callback:
            args.append(
                self.callback if self.has_callback or self.callback_required else None
            )
        self._wrapper.run(args, self.params, ignore_unknown=False, output=False)  # type: ignore
        from ...dataframe import ArrayDataFrame

        return ArrayDataFrame([], OUTPUT_TRANSFORMER_DUMMY_SCHEMA)

    @staticmethod
    def from_func(
        func: Callable, schema: Any, validation_rules: Dict[str, Any]
    ) -> "_FuncAsOutputCoTransformer":
        assert_or_throw(
            schema is None, FugueInterfacelessError("schema must be None for output cotransformers")
        )
        tr = _FuncAsOutputCoTransformer()
        tr._wrapper = DataFrameFunctionWrapper(  # type: ignore
            func, "^(c|[lspqj]+)[fF]?x*z?$", "^[lspnqjr]$"
        )
        tr._dfs_input = tr._wrapper.input_code.startswith("c")  # type: ignore
        tr._output_schema_arg = None  # type: ignore
        tr._validation_rules = {}  # type: ignore
        return tr


def _apply_schema_arg(input_schema: Schema, schema_arg: Any) -> Schema:
    assert_or_throw(
        schema_arg is not None,
        FugueInterfacelessError("output schema is required but not provided"),
    )
    if isinstance(schema_arg, Schema):
        return schema_arg
    if callable(schema_arg):
        return Schema(schema_arg(input_schema))
    if isinstance(schema_arg, (list, tuple)):
        return input_schema.transform(*schema_arg)
    return input_schema.transform(schema_arg)

