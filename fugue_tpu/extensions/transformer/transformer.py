"""Transformer / CoTransformer interfaces — worker-side per-partition logic.

Parity with the reference (`fugue/extensions/transformer/transformer.py:8,101,113,201`).
"""

from typing import Any

from ...dataframe import DataFrame, DataFrames, LocalDataFrame
from ..context import ExtensionContext


class Transformer(ExtensionContext):
    """Per-logical-partition transformation, instantiated on the driver,
    executed on workers."""

    def get_output_schema(self, df: DataFrame) -> Any:
        raise NotImplementedError

    def on_init(self, df: DataFrame) -> None:  # pragma: no cover - optional hook
        pass

    def transform(self, df: LocalDataFrame) -> LocalDataFrame:
        raise NotImplementedError

    @property
    def validation_rules(self) -> dict:
        return {}


class OutputTransformer(Transformer):
    """Transformer with no output (side effects only); reference ``:101``."""

    def get_output_schema(self, df: DataFrame) -> Any:
        from . import convert

        return convert.OUTPUT_TRANSFORMER_DUMMY_SCHEMA

    def process(self, df: LocalDataFrame) -> None:
        raise NotImplementedError

    def transform(self, df: LocalDataFrame) -> LocalDataFrame:
        from ...dataframe import ArrayDataFrame

        self.process(df)
        return ArrayDataFrame([], self.get_output_schema(df))


class CoTransformer(ExtensionContext):
    """Per-co-partition transformation over zipped frames; reference ``:113``."""

    def get_output_schema(self, dfs: DataFrames) -> Any:
        raise NotImplementedError

    def on_init(self, dfs: DataFrames) -> None:  # pragma: no cover - optional hook
        pass

    def transform(self, dfs: DataFrames) -> LocalDataFrame:
        raise NotImplementedError

    @property
    def validation_rules(self) -> dict:
        return {}


class OutputCoTransformer(CoTransformer):
    def get_output_schema(self, dfs: DataFrames) -> Any:
        from . import convert

        return convert.OUTPUT_TRANSFORMER_DUMMY_SCHEMA

    def process(self, dfs: DataFrames) -> None:
        raise NotImplementedError

    def transform(self, dfs: DataFrames) -> LocalDataFrame:
        from ...dataframe import ArrayDataFrame

        self.process(dfs)
        return ArrayDataFrame([], self.get_output_schema(dfs))
